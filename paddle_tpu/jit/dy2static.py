"""Dygraph-to-static AST conversion for data-dependent Python control flow.

Reference parity: the dygraph_to_static transformer pipeline —
`ProgramTranslator` (fluid/dygraph/dygraph_to_static/program_translator.py:667)
with its per-construct transformers (ifelse_transformer.py,
loop_transformer.py) and the `convert_ifelse`/`convert_while_loop` runtime
dispatchers (convert_operators.py), which let `@to_static` code keep Python
`if`/`while` over tensors.

TPU-native design: most dygraph code traces directly under jax.jit, so the
AST pass only needs to rewrite the two constructs tracing cannot express —
`if` and `while` whose predicate is a *traced* value — into runtime
dispatchers that pick `lax.cond` / `lax.while_loop` when the predicate is a
tensor and plain Python control flow otherwise (exactly the reference's
convert_* contract).  Supported subset (documented, checked):

  * `if`/`elif`/`else` where every name live after the branch is assigned
    in BOTH branches (lax.cond needs matching output structures),
  * `while` whose carried names exist before the loop and keep
    shape/dtype (lax.while_loop shape-invariant carry),
  * `break`/`continue` inside `while`/`for` bodies (ref
    break_continue_transformer.py): rewritten into carried boolean flags
    — the loop condition gains AND NOT(break_flag), and statements after
    a potential break/continue are wrapped in guard `if`s, so a traced
    break predicate lowers to lax control flow,
  * `for i in range(...)` (ref loop_transformer.py for-range): lowered to
    the `while` form — static bounds keep the plain Python loop (list
    appends etc. still work), traced bounds or a traced break become
    lax.while_loop,
  * tail transformers (ref assert_transformer.py, cast_transformer.py,
    print_transformer.py, tensor_shape_transformer.py, convert_len):
    `assert` dispatches to a host check when the predicate is traced;
    `int(x)`/`float(x)`/`bool(x)` on traced tensors become astype;
    `print(tensor)` becomes jax.debug.print under trace; `len(tensor)`
    and `x.shape[i]` are STATIC under XLA, so the reference's
    dynamic-shape plumbing collapses to python ints (python lists with
    static-bound loops keep working through the plain-loop path for the
    same reason — the reference's LoDTensorArray conversion is only
    needed when shapes are dynamic),
  * no `return`/`yield` inside converted bodies; no list append inside a
    loop that actually lowers to lax.while_loop (a lax carry cannot grow
    — use a preallocated buffer + indexed writes, the dense analogue of
    the reference's LoDTensorArray); no closures over mutated free
    variables.

Functions using constructs outside the subset fall back to plain tracing
(data-INdependent control flow still works there); a data-dependent
predicate will then raise jax's TracerBoolConversionError as before.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["ast_transform", "convert_ifelse", "convert_while",
           "convert_assert", "convert_cast", "convert_print", "convert_len",
           "Unsupported"]


class Unsupported(Exception):
    """Raised when a function is outside the convertible subset."""


_UNDEF = object()  # placeholder for names not yet bound before an `if`


def _is_traced(x) -> bool:
    return isinstance(x, (jax.core.Tracer, jax.Array))


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   args: Tuple) -> Tuple:
    """ref convert_operators.py convert_ifelse: tensor pred -> lax.cond,
    python pred -> plain call."""
    if _is_traced(pred):
        p = jnp.reshape(pred, ()).astype(bool)
        out_t = true_fn(*args)
        out_f = false_fn(*args)
        _check_match(out_t, out_f)
        # names unbound before the `if` (fresh in both branches) carry a
        # placeholder; lax.cond operands must be arrays, so substitute a
        # dummy — the branches provably assign before use (checked above)
        safe = tuple(jnp.zeros(()) if a is _UNDEF else a for a in args)
        return jax.lax.cond(p, lambda a: true_fn(*a), lambda a: false_fn(*a),
                            safe)
    return true_fn(*args) if pred else false_fn(*args)


def _check_match(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    for x, y in zip(fa, fb):
        xs = getattr(x, "shape", ()) if x is not _UNDEF else None
        ys = getattr(y, "shape", ()) if y is not _UNDEF else None
        if x is _UNDEF or y is _UNDEF or xs != ys:
            raise Unsupported(
                "converted `if`: both branches must assign every output "
                f"with matching shapes (got {xs} vs {ys}); a name assigned "
                "in only one branch cannot cross a lax.cond boundary")


def convert_while(cond_fn: Callable, body_fn: Callable, carry: Tuple) -> Tuple:
    """ref convert_operators.py convert_while_loop."""
    probe = cond_fn(*carry)
    if _is_traced(probe):
        if any(c is _UNDEF for c in carry):
            raise Unsupported(
                "converted `while`: every carried variable must be bound "
                "before the loop (lax.while_loop carry)")
        # flags introduced by the break/continue rewrite start as python
        # bools; canonicalize the carry so the while_loop typechecks
        carry = tuple(jnp.asarray(c) if isinstance(c, (bool, int, float))
                      else c for c in carry)
        return jax.lax.while_loop(
            lambda c: jnp.reshape(cond_fn(*c), ()).astype(bool),
            lambda c: tuple(body_fn(*c)), tuple(carry))
    while True:
        if _is_traced(probe):
            # the condition became traced mid-flight (e.g. a traced break
            # flag joined it): continue as lax.while_loop from here
            return convert_while(cond_fn, body_fn, carry)
        if not probe:
            return carry
        carry = tuple(body_fn(*carry))
        probe = cond_fn(*carry)


def convert_assert(pred, msg_fn=None):
    """ref dygraph_to_static/assert_transformer.py -> layers.Assert: a
    traced predicate checks host-side via ordered io_callback (needs PJRT
    host callbacks — CPU/real-TPU runtimes, not the axon dev tunnel);
    concrete values keep plain `assert` semantics.  ``msg_fn`` is a THUNK:
    python evaluates an assert's message only on failure, so the AST
    rewrite wraps it in a lambda and it is called here only when the
    check actually fails."""
    def _msg():
        return msg_fn() if msg_fn is not None else "converted assert failed"

    if isinstance(pred, jax.core.Tracer):
        import numpy as np
        from jax.experimental import io_callback

        def host_check(p):
            # ALL elements must hold (the reference Assert op contract;
            # eager python would refuse a multi-element truth test)
            if not bool(np.asarray(p).all()):
                raise AssertionError(_msg())
            return np.zeros((), np.int32)

        io_callback(host_check, jax.ShapeDtypeStruct((), jnp.int32),
                    pred, ordered=True)
        return
    if not pred:
        raise AssertionError(_msg())


_CAST_DTYPES = {"int": jnp.int32, "float": jnp.float32, "bool": jnp.bool_}


def convert_cast(value, ty: str):
    """ref cast_transformer.py: int(x)/float(x)/bool(x) on a TRACED tensor
    become astype (int32/float32/bool — x64 is off on TPU); concrete
    values keep the builtin conversion."""
    if isinstance(value, jax.core.Tracer):
        return value.astype(_CAST_DTYPES[ty])
    return {"int": int, "float": float, "bool": bool}[ty](value)


def convert_print(*args, **kwargs):
    """ref print_transformer.py -> Print op: traced args print host-side
    via ordered io_callback with FULL builtin-print semantics (sep/end/
    file honored — jax.debug.print would drop them); same runtime caveat
    as convert_assert.  Concrete values use builtin print directly."""
    if any(isinstance(a, jax.core.Tracer) for a in args):
        import numpy as np
        from jax.experimental import io_callback

        arr_idx = [i for i, a in enumerate(args)
                   if isinstance(a, (jax.core.Tracer, jax.Array))]
        static_args = list(args)

        def host_print(*arrs):
            merged = list(static_args)
            for i, a in zip(arr_idx, arrs):
                merged[i] = np.asarray(a)
            print(*merged, **kwargs)
            return np.zeros((), np.int32)

        io_callback(host_print, jax.ShapeDtypeStruct((), jnp.int32),
                    *[args[i] for i in arr_idx], ordered=True)
        return
    print(*args, **kwargs)


def convert_len(x):
    """ref convert_operators.py convert_len + tensor_shape_transformer:
    len(tensor) is the leading dim — STATIC under XLA, so the reference's
    dynamic-shape plumbing collapses to a python int."""
    if isinstance(x, (jax.core.Tracer, jax.Array)):
        return x.shape[0]
    return len(x)


def _and_not(test, brk):
    """cond AND NOT break_flag, python/tensor aware (break rewrite)."""
    if _is_traced(test) or _is_traced(brk):
        t = jnp.reshape(jnp.asarray(test), ()).astype(bool)
        b = jnp.reshape(jnp.asarray(brk), ()).astype(bool)
        return jnp.logical_and(t, jnp.logical_not(b))
    return bool(test) and not bool(brk)


def _not_skipping(brk, cnt):
    """NOT (break_flag OR continue_flag) — the guard predicate wrapping
    statements after a potential break/continue."""
    if _is_traced(brk) or _is_traced(cnt):
        b = jnp.reshape(jnp.asarray(brk), ()).astype(bool)
        c = jnp.reshape(jnp.asarray(cnt), ()).astype(bool)
        return jnp.logical_not(jnp.logical_or(b, c))
    return not (bool(brk) or bool(cnt))


def _range_cond(i, stop, step):
    """for-range continuation predicate, sign-of-step aware."""
    if _is_traced(i) or _is_traced(stop) or _is_traced(step):
        return jnp.where(jnp.asarray(step) > 0,
                         jnp.asarray(i) < jnp.asarray(stop),
                         jnp.asarray(i) > jnp.asarray(stop))
    return i < stop if step > 0 else i > stop


# ------------------------------------------------------------------ AST ----

def _assigned_names(nodes: Sequence[ast.stmt]) -> list:
    names = []

    class V(ast.NodeVisitor):
        def visit_Name(self, n):
            if isinstance(n.ctx, ast.Store) and n.id not in names:
                names.append(n.id)

        def visit_FunctionDef(self, n):  # don't descend into nested defs
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_AugAssign(self, n):
            if isinstance(n.target, ast.Name) and n.target.id not in names:
                names.append(n.target.id)
            self.generic_visit(n)

    for s in nodes:
        V().visit(s)
    return names


class _Checker(ast.NodeVisitor):
    """Reject constructs the subset cannot express inside converted bodies:
    return/yield ANYWHERE (a generated body_fn must return the carry
    tuple — even inside a nested python-iterated `for` the return would
    escape the carry), break/continue only OUTSIDE nested loops (a nested
    loop owns its own, handled by its own conversion)."""

    def __init__(self):
        self.banned = None
        self.saw_bc = False  # break/continue at the CURRENT loop level
        self._loop_depth = 0

    def visit_Break(self, n):
        if self._loop_depth == 0:
            self.banned = "break"
            self.saw_bc = True

    def visit_Continue(self, n):
        if self._loop_depth == 0:
            self.banned = "continue"
            self.saw_bc = True

    def visit_Return(self, n):
        self.banned = "return"

    def visit_Yield(self, n):
        self.banned = "yield"

    def visit_FunctionDef(self, n):
        # nested defs (incl. ones this transformer generated for an inner
        # converted construct) own their returns — don't descend
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_While(self, n):
        self._loop_depth += 1
        self.generic_visit(n)
        self._loop_depth -= 1

    visit_For = visit_While


def _contains_bc(node: ast.stmt) -> bool:
    """Does this statement contain a break/continue belonging to the
    CURRENT loop (not to a nested loop)?"""
    c = _Checker()
    c.visit(node)
    return c.saw_bc


def _name(n, ctx=ast.Load):
    return ast.Name(id=n, ctx=ctx())


def _rewrite_break_continue(body, brk, cnt):
    """ref break_continue_transformer.py: replace break/continue with flag
    assignments and wrap the statements after a potential break/continue in
    a guard `if not (brk or cnt)` — which the If conversion then lowers to
    lax.cond when the flags are traced."""

    def rewrite_stmt(s):
        if isinstance(s, ast.Break):
            return [ast.Assign(targets=[_name(brk, ast.Store)],
                               value=ast.Constant(value=True))]
        if isinstance(s, ast.Continue):
            return [ast.Assign(targets=[_name(cnt, ast.Store)],
                               value=ast.Constant(value=True))]
        if isinstance(s, ast.If):
            s = ast.If(test=s.test, body=rewrite_block(s.body),
                       orelse=rewrite_block(s.orelse))
        return [s]

    def rewrite_block(stmts):
        out = []
        for i, s in enumerate(stmts):
            had_bc = _contains_bc(s)
            out.extend(rewrite_stmt(s))
            if had_bc and i + 1 < len(stmts):
                guard = ast.If(
                    test=ast.Call(
                        func=_name("__pdtpu_not_skipping"),
                        args=[_name(brk), _name(cnt)], keywords=[]),
                    body=rewrite_block(stmts[i + 1:]), orelse=[])
                out.append(guard)
                break
        return out

    return rewrite_block(body)


def _check_body(nodes):
    c = _Checker()
    for s in nodes:
        c.visit(s)
    if c.banned:
        raise Unsupported(
            f"`{c.banned}` inside a converted control-flow body is outside "
            "the dy2static subset")


_REWRITABLE_BUILTINS = ("print", "int", "float", "bool", "len")


def _shadowed_builtins(fdef) -> frozenset:
    """Rewritable builtin names the function rebinds — via params,
    assignments, for/with targets, imports, or nested definitions.  A call
    through a rebound name is the user's object, not the builtin, so the
    cast/print/len rewrite must not fire on it.  Collection is
    whole-function conservative: python scoping makes a name assigned
    anywhere in a scope local everywhere in it, and nested defs are folded
    in too (the transformer rewrites inside them as well)."""
    bound = set()
    for n in ast.walk(fdef):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            bound.add(n.id)
        elif isinstance(n, ast.arg):
            bound.add(n.arg)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)) and n is not fdef:
            bound.add(n.name)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for alias in n.names:
                bound.add((alias.asname or alias.name).split(".")[0])
    return frozenset(bound & set(_REWRITABLE_BUILTINS))


class _Transformer(ast.NodeTransformer):
    def __init__(self, shadowed=()):
        self.counter = 0
        self.shadowed = frozenset(shadowed)

    def _fresh(self, kind):
        self.counter += 1
        return f"__pdtpu_{kind}_{self.counter}"

    # -- tail transformers: assert / cast / print / len ----------------------
    def visit_Assert(self, node: ast.Assert):
        self.generic_visit(node)
        # the message becomes a thunk: python evaluates it only on failure
        msg = (ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=node.msg) if node.msg is not None
            else ast.Constant(value=None))
        return ast.Expr(value=ast.Call(
            func=_name("__pdtpu_convert_assert"),
            args=[node.test, msg], keywords=[]))

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        if not isinstance(node.func, ast.Name):
            return node
        fid = node.func.id
        if fid in self.shadowed:  # user rebound the name; not the builtin
            return node
        if fid in ("int", "float", "bool") and len(node.args) == 1 \
                and not node.keywords:
            return ast.Call(func=_name("__pdtpu_convert_cast"),
                            args=[node.args[0], ast.Constant(value=fid)],
                            keywords=[])
        if fid == "len" and len(node.args) == 1 and not node.keywords:
            return ast.Call(func=_name("__pdtpu_convert_len"),
                            args=list(node.args), keywords=[])
        if fid == "print":
            return ast.Call(func=_name("__pdtpu_convert_print"),
                            args=list(node.args),
                            keywords=list(node.keywords))
        return node

    # -- if ------------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        outs = sorted(set(_assigned_names(node.body))
                      | set(_assigned_names(node.orelse)))
        if not outs:
            # pure side-effect-free branch on possibly-traced pred is
            # meaningless; leave python semantics (will raise if traced)
            return node
        _check_body(node.body)
        _check_body(node.orelse)
        tname, fname = self._fresh("true"), self._fresh("false")
        args = [ast.arg(arg=n) for n in outs]

        def mk(nm, body):
            stmts = list(body) or [ast.Pass()]
            stmts.append(ast.Return(value=ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Load()) for n in outs],
                ctx=ast.Load())))
            return ast.FunctionDef(
                name=nm,
                args=ast.arguments(posonlyargs=[], args=args, vararg=None,
                                   kwonlyargs=[], kw_defaults=[], kwarg=None,
                                   defaults=[]),
                body=stmts, decorator_list=[], returns=None)

        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in outs],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__pdtpu_convert_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=tname, ctx=ast.Load()),
                      ast.Name(id=fname, ctx=ast.Load()),
                      ast.Tuple(elts=[
                          ast.Call(func=ast.Name(id="__pdtpu_maybe",
                                                 ctx=ast.Load()),
                                   args=[ast.Call(func=ast.Name(
                                       id="locals", ctx=ast.Load()),
                                       args=[], keywords=[]),
                                       ast.Constant(value=n)],
                                   keywords=[])
                          for n in outs], ctx=ast.Load())],
                keywords=[]))
        # restore python semantics for names the taken branch did not bind:
        # `if __pdtpu_is_undef(x): del x` so a later read raises
        # UnboundLocalError exactly like the untransformed code (only
        # reachable on the python-predicate path; the traced path proves
        # both branches assign)
        cleanup = [ast.If(
            test=ast.Call(func=ast.Name(id="__pdtpu_is_undef",
                                        ctx=ast.Load()),
                          args=[ast.Name(id=n, ctx=ast.Load())],
                          keywords=[]),
            body=[ast.Delete(targets=[ast.Name(id=n, ctx=ast.Del())])],
            orelse=[]) for n in outs]
        return [mk(tname, node.body), mk(fname, node.orelse), call] + cleanup

    # -- while ---------------------------------------------------------------
    def _prepare_loop_flags(self, node):
        """Rewrite break/continue in the RAW loop body into carried flags
        (ref break_continue_transformer.py).  Returns prologue statements
        binding the flags before the loop."""
        if not any(_contains_bc(s) for s in node.body):
            return []
        brk, cnt = self._fresh("brk"), self._fresh("cnt")
        node.body = (
            [ast.Assign(targets=[_name(cnt, ast.Store)],
                        value=ast.Constant(value=False))]
            + _rewrite_break_continue(node.body, brk, cnt))
        node.test = ast.Call(func=_name("__pdtpu_and_not"),
                             args=[node.test, _name(brk)], keywords=[])
        return [ast.Assign(targets=[_name(n, ast.Store)],
                           value=ast.Constant(value=False))
                for n in (brk, cnt)]

    def visit_While(self, node: ast.While):
        if node.orelse:
            raise Unsupported("while/else is outside the dy2static subset")
        prologue = self._prepare_loop_flags(node)
        self.generic_visit(node)
        _check_body(node.body)
        return prologue + self._convert_while_node(node)

    def _convert_while_node(self, node: ast.While):
        carries = sorted(set(_assigned_names(node.body)))
        if not carries:
            raise Unsupported(
                "converted `while` body assigns nothing: infinite or "
                "side-effect loop cannot become lax.while_loop")
        cname, bname = self._fresh("cond"), self._fresh("body")
        args = [ast.arg(arg=n) for n in carries]
        cond_fn = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[], args=args, vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=[ast.Return(value=node.test)], decorator_list=[],
            returns=None)
        body_stmts = list(node.body)
        body_stmts.append(ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in carries],
            ctx=ast.Load())))
        body_fn = ast.FunctionDef(
            name=bname,
            args=ast.arguments(posonlyargs=[], args=args, vararg=None,
                               kwonlyargs=[], kw_defaults=[], kwarg=None,
                               defaults=[]),
            body=body_stmts, decorator_list=[], returns=None)
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in carries],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__pdtpu_convert_while", ctx=ast.Load()),
                args=[ast.Name(id=cname, ctx=ast.Load()),
                      ast.Name(id=bname, ctx=ast.Load()),
                      ast.Tuple(elts=[
                          ast.Call(func=ast.Name(id="__pdtpu_maybe",
                                                 ctx=ast.Load()),
                                   args=[ast.Call(func=ast.Name(
                                       id="locals", ctx=ast.Load()),
                                       args=[], keywords=[]),
                                       ast.Constant(value=n)],
                                   keywords=[])
                          for n in carries], ctx=ast.Load())],
                keywords=[]))
        return [cond_fn, body_fn, call]

    # -- for-range (ref loop_transformer.py for-range lowering) -------------
    def visit_For(self, node: ast.For):
        if node.orelse:
            raise Unsupported("for/else is outside the dy2static subset")
        it = node.iter
        if not (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and not it.keywords):
            # non-range iterables iterate in python (fine for concrete
            # sequences under trace); just convert nested constructs
            self.generic_visit(node)
            return node
        if not isinstance(node.target, ast.Name):
            raise Unsupported(
                "for-range target must be a plain name in the dy2static "
                "subset")
        i = node.target.id
        a = it.args
        if len(a) == 1:
            start, stop, step = ast.Constant(value=0), a[0], \
                ast.Constant(value=1)
        elif len(a) == 2:
            start, stop, step = a[0], a[1], ast.Constant(value=1)
        elif len(a) == 3:
            start, stop, step = a
        else:
            raise Unsupported("range() takes 1-3 arguments")
        idx_n = self._fresh("idx")
        stop_n, step_n = self._fresh("stop"), self._fresh("step")
        setup = [
            ast.Assign(targets=[_name(idx_n, ast.Store)], value=start),
            ast.Assign(targets=[_name(stop_n, ast.Store)], value=stop),
            ast.Assign(targets=[_name(step_n, ast.Store)], value=step),
            # bind the loop var before the loop so it is a lax carry (its
            # value after the loop — incl. python's "keeps the last/break
            # value" semantics — comes from the body's `i = idx` assign)
            ast.Assign(targets=[_name(i, ast.Store)], value=_name(idx_n)),
        ]
        test = ast.Call(func=_name("__pdtpu_range_cond"),
                        args=[_name(idx_n), _name(stop_n), _name(step_n)],
                        keywords=[])
        # body: i = idx; <original body>; idx = idx + step — the hidden
        # counter always advances (continue included) while `i` freezes at
        # its last assigned iteration (python for semantics, break too)
        body = [ast.Assign(targets=[_name(i, ast.Store)],
                           value=_name(idx_n))] + list(node.body)
        loop = ast.While(test=test, body=body, orelse=[])
        prologue = self._prepare_loop_flags(loop)
        loop.body.append(ast.Assign(
            targets=[_name(idx_n, ast.Store)],
            value=ast.BinOp(left=_name(idx_n), op=ast.Add(),
                            right=_name(step_n))))
        self.generic_visit(loop)
        _check_body(loop.body)
        converted = self._convert_while_node(loop)
        return setup + prologue + converted


def _maybe(frame_locals, name):
    return frame_locals.get(name, _UNDEF)


def _is_undef(x) -> bool:
    return x is _UNDEF


def ast_transform(fn: Callable) -> Callable:
    """Return fn with data-dependent if/while rewritten, or raise
    Unsupported when conversion cannot apply (caller falls back to plain
    tracing — the reference logs and falls back the same way)."""
    if inspect.ismethod(fn):
        return ast_transform(fn.__func__).__get__(fn.__self__)
    if fn.__closure__:
        raise Unsupported(
            "functions with closures are outside the dy2static subset "
            "(recompiling would sever the closure cells)")
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise Unsupported(f"source unavailable: {e}") from e
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise Unsupported("not a plain function definition")
    shadowed = _shadowed_builtins(fdef)
    if not any(isinstance(n, (ast.If, ast.While, ast.For, ast.Assert))
               or (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                   and n.func.id in _REWRITABLE_BUILTINS
                   and n.func.id not in shadowed)
               for n in ast.walk(fdef)):
        raise Unsupported("nothing to convert")
    fdef.decorator_list = []  # strip @to_static etc. to avoid recursion
    new_tree = _Transformer(shadowed=shadowed).visit(tree)
    ast.fix_missing_locations(new_tree)
    code = compile(new_tree, f"<dy2static {fn.__qualname__}>", "exec")
    glb = dict(fn.__globals__)
    glb["__pdtpu_convert_ifelse"] = convert_ifelse
    glb["__pdtpu_convert_while"] = convert_while
    glb["__pdtpu_maybe"] = _maybe
    glb["__pdtpu_is_undef"] = _is_undef
    glb["__pdtpu_and_not"] = _and_not
    glb["__pdtpu_not_skipping"] = _not_skipping
    glb["__pdtpu_range_cond"] = _range_cond
    glb["__pdtpu_convert_assert"] = convert_assert
    glb["__pdtpu_convert_cast"] = convert_cast
    glb["__pdtpu_convert_print"] = convert_print
    glb["__pdtpu_convert_len"] = convert_len
    loc: dict = {}
    exec(code, glb, loc)
    out = loc[fdef.name]
    functools.update_wrapper(out, fn)
    return out
