"""paddle_tpu.jit — dygraph-to-static, traced layers, and model export.

Reference parity: the dygraph_to_static subsystem — `@declarative`/
`paddle.jit.to_static` (fluid/dygraph/jit.py:155, program_translator.py:667),
`TracedLayer` (dygraph/jit.py), and `paddle.jit.save`/`load` which emit the
inference-model format consumed by AnalysisPredictor (SURVEY.md §1 L4, L5).

TPU-native design: the reference needs a 400-file AST-transformer pipeline
because its imperative mode executes op-by-op; here dygraph code *is already
traceable* — `to_static` is jax.jit over a functional capture of the Layer
(params lifted to arguments), with per-signature executable caching.  Export
is `jax.export`: the traced forward is lowered to StableHLO and serialized;
`load` deserializes to an executable artifact that runs without the original
Python class — the same role ProgramDesc+save_inference_model plays in the
reference, but carried by XLA's stable IR instead of a custom proto.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import _swapped, buffers_dict, parameters_dict
from ..nn.layer.base import Layer

__all__ = ["InputSpec", "to_static", "not_to_static", "TracedLayer",
           "TranslatedLayer", "save", "load"]

_FORMAT_VERSION = 1
_MODEL_SUFFIX = ".pdmodel"     # serialized jax.export artifact (StableHLO)
_PARAMS_SUFFIX = ".pdiparams"  # npz state dict (reference suffix parity)
_META_SUFFIX = ".pdmeta.json"


class InputSpec:
    """Shape/dtype signature of one input (ref paddle.static.InputSpec).

    `None` dims mean "any" for to_static's cache key; export requires all
    dims concrete (XLA static shapes — SURVEY.md §7 hard parts)."""

    def __init__(self, shape: Sequence[Optional[int]], dtype: Any = "float32",
                 name: Optional[str] = None):
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t, name: Optional[str] = None) -> "InputSpec":
        return cls(t.shape, t.dtype, name)

    def to_sds(self) -> jax.ShapeDtypeStruct:
        if any(d is None for d in self.shape):
            raise ValueError(
                f"InputSpec {self.name or ''} has unknown dims {self.shape}; "
                "export needs concrete shapes")
        return jax.ShapeDtypeStruct(self.shape, self.dtype)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _canon(x):
    return x if isinstance(x, (jax.Array, np.ndarray)) else np.asarray(x)


class StaticFunction:
    """The object `to_static` returns (ref program_translator.py
    StaticFunction): callable with per-signature compiled-program caching.

    Data-dependent Python `if`/`while` over tensors are AST-rewritten to
    lax.cond/lax.while_loop dispatchers when the function is inside the
    dy2static subset (see jit/dy2static.py); otherwise the original
    trace-based path applies (matching the reference's convert-or-fallback
    behavior, program_translator.py:667)."""

    def __init__(self, fn: Callable, layer: Optional[Layer] = None,
                 input_spec: Optional[Sequence[InputSpec]] = None):
        from . import dy2static

        self._orig_fn = fn
        try:
            fn = dy2static.ast_transform(fn)
            self._converted = True
        except dy2static.Unsupported:
            self._converted = False
        self._fn = fn
        self._layer = layer
        self.input_spec = list(input_spec) if input_spec else None
        self._cache: Dict[tuple, Callable] = {}
        self._last_args: Optional[Tuple] = None

    @property
    def layer(self):
        return self._layer

    def _functional(self):
        if self._layer is None:
            return jax.jit(self._fn)
        # Call the ORIGINAL forward (self._fn), not layer(*args): to_static
        # on a Layer rebinds layer.forward to this StaticFunction, so going
        # back through Layer.__call__ would recurse.
        layer, fn = self._layer, self._fn

        def pure(params, buffers, *args):
            with _swapped(layer, params, dict(buffers)):
                return fn(*args)

        return jax.jit(pure)

    def __call__(self, *args):
        args = tuple(_canon(a) for a in args)
        self._last_args = args
        # _canon guarantees jax.Array or np.ndarray — read .dtype directly,
        # never jnp.asarray (that would device-transfer just to build a key)
        key = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = self._functional()
            self._cache[key] = compiled
        if self._layer is None:
            return compiled(*args)
        return compiled(parameters_dict(self._layer, trainable_only=False),
                        buffers_dict(self._layer), *args)

    # -- export support -----------------------------------------------------
    def _example_sds(self) -> List[jax.ShapeDtypeStruct]:
        if self.input_spec:
            return [s.to_sds() for s in self.input_spec]
        if self._last_args is not None:
            return [jax.ShapeDtypeStruct(a.shape, jnp.dtype(a.dtype))
                    for a in self._last_args]
        raise ValueError(
            "cannot export: pass input_spec or call the function once first")


def to_static(function=None, input_spec: Optional[Sequence[InputSpec]] = None,
              **kwargs):
    """Decorator/wrapper converting dygraph code to a compiled static function
    (ref @to_static jit.py:155). Accepts a function, a bound Layer method, or
    a Layer (wraps its forward)."""

    def wrap(obj):
        if isinstance(obj, Layer):
            sf = StaticFunction(obj.forward, layer=obj, input_spec=input_spec)
            obj.forward = sf
            return obj
        layer = getattr(obj, "__self__", None)
        if isinstance(layer, Layer):
            return StaticFunction(obj.__func__.__get__(layer), layer=layer,
                                  input_spec=input_spec)
        return StaticFunction(obj, input_spec=input_spec)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn):
    """ref paddle.jit.not_to_static — marker excluding a function from
    conversion; conversion here is whole-trace jit, so it is an identity
    marker kept for API parity."""
    fn.__pdtpu_not_to_static__ = True
    return fn


# --------------------------------------------------------------- save/load --
def _export_artifact(fn: Callable, sds_list: List[jax.ShapeDtypeStruct]):
    exp = jax.export.export(jax.jit(fn))(*sds_list)
    return exp


def save(obj, path: str, input_spec: Optional[Sequence[InputSpec]] = None):
    """Serialize a Layer / to_static function to `path{.pdmodel,.pdiparams,
    .pdmeta.json}` (ref paddle.jit.save → __model__ + params files).

    The .pdmodel artifact has parameters **baked in as constants** and runs
    standalone (inference); .pdiparams keeps the state_dict for reload into
    Python (fine-tuning path).
    """
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    if isinstance(obj, Layer):
        layer = obj
        sf = obj.forward if isinstance(obj.forward, StaticFunction) else None
        raw_forward = sf._fn if sf is not None else obj.forward
    elif isinstance(obj, StaticFunction):
        sf, layer = obj, obj.layer
        raw_forward = obj._fn
    else:
        raise TypeError(f"jit.save expects a Layer or to_static function, got {type(obj)}")
    # Always export through the original forward — a to_static-rebound
    # layer.forward would re-enter the compiled path mid-trace.  Parameters
    # are read as concrete arrays and baked into the artifact as constants.
    fn = (lambda *a: raw_forward(*a))

    if input_spec is not None:
        specs = [s if isinstance(s, InputSpec) else
                 InputSpec(tuple(s.shape), s.dtype, getattr(s, "name", None))
                 for s in input_spec]
        sds = [s.to_sds() for s in specs]
    elif sf is not None:
        sds = sf._example_sds()
        specs = [InputSpec(s.shape, s.dtype) for s in sds]
    else:
        raise ValueError("pass input_spec (layer has no recorded example call)")

    was_training = getattr(layer, "training", False)
    if layer is not None and was_training:
        layer.eval()  # export inference behavior (no dropout etc.)
    try:
        exp = _export_artifact(fn, sds)
    finally:
        if layer is not None and was_training:
            layer.train()

    with open(path + _MODEL_SUFFIX, "wb") as f:
        f.write(exp.serialize())

    state: Dict[str, np.ndarray] = {}
    if layer is not None:
        for k, v in layer.state_dict().items():
            state[k] = np.asarray(getattr(v, "value", v))
    np.savez(path + _PARAMS_SUFFIX, **state)

    meta = {
        "format_version": _FORMAT_VERSION,
        "inputs": [{"shape": list(s.shape), "dtype": str(np.dtype(s.dtype)),
                    "name": s.name} for s in specs],
        "param_names": sorted(state),
        "platforms": list(exp.platforms),
    }
    with open(path + _META_SUFFIX, "w") as f:
        json.dump(meta, f, indent=1)


class TranslatedLayer:
    """Loaded model (ref TranslatedLayer of paddle.jit.load): an executable
    artifact + the saved state_dict.  Callable for inference; the compiled
    path is the deserialized StableHLO module under jit."""

    def __init__(self, exported, meta: Dict, state: Dict[str, np.ndarray]):
        self._exported = exported
        self._meta = meta
        self._state = state
        self._compiled = jax.jit(exported.call)

    def __call__(self, *args):
        return self._compiled(*[_canon(a) for a in args])

    forward = __call__

    def state_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._state)

    @property
    def input_specs(self) -> List[InputSpec]:
        return [InputSpec(i["shape"], i["dtype"], i.get("name"))
                for i in self._meta["inputs"]]

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("a loaded inference artifact is not trainable; "
                           "rebuild the Layer and set_state_dict(state_dict())")


def load(path: str) -> TranslatedLayer:
    """Load a `jit.save`d model (ref paddle.jit.load)."""
    with open(path + _MODEL_SUFFIX, "rb") as f:
        exported = jax.export.deserialize(bytearray(f.read()))
    with open(path + _META_SUFFIX) as f:
        meta = json.load(f)
    state = {}
    params_file = path + _PARAMS_SUFFIX + ".npz"
    if os.path.exists(params_file):
        with np.load(params_file, allow_pickle=False) as z:
            state = {k: z[k] for k in z.files}
    return TranslatedLayer(exported, meta, state)


# ------------------------------------------------------------- TracedLayer --
class TracedLayer:
    """ref dygraph/jit.py TracedLayer: trace a dygraph Layer with example
    inputs; the result replays the traced computation and can be saved as an
    inference model."""

    def __init__(self, layer: Layer, sds: List[jax.ShapeDtypeStruct]):
        self._layer = layer
        self._sds = sds
        self._sf = StaticFunction(layer.forward, layer=layer)

    @staticmethod
    def trace(layer: Layer, inputs: Sequence[Any]) -> Tuple[Any, "TracedLayer"]:
        inputs = [_canon(i) for i in inputs]
        sds = [jax.ShapeDtypeStruct(i.shape, jnp.asarray(i).dtype) for i in inputs]
        tl = TracedLayer(layer, sds)
        out = tl(*inputs)
        return out, tl

    def __call__(self, *args):
        return self._sf(*args)

    def save_inference_model(self, path: str, feed=None, fetch=None) -> None:
        specs = [InputSpec(s.shape, s.dtype) for s in self._sds]
        save(self._layer, path, input_spec=specs)
