"""Live telemetry plane: zero-dependency HTTP exposition of the runtime
instruments.

Reference parity: platform/monitor.h keeps an always-on ``StatValue``
registry meant to be *watched* while the job runs, and the reference's
device tracer streams while training; our PR 2/3/9 instruments (metrics,
trace/flight-recorder, xprof) were pull-only-at-exit.  This module turns
them into an operable system: a threaded stdlib HTTP server any rank can
run (``telemetry_port`` flag; ``launch --telemetry_port BASE`` assigns
``BASE + rank`` per worker) serving

* ``/metrics``  — Prometheus text exposition of the process-wide
  ``utils/monitor.py`` registry (``parse_prometheus_text``-round-trippable)
* ``/healthz``  — JSON liveness: rank/pid/uptime, elastic membership view
  and per-rank last-heartbeat ages when the process joined one (or when
  ``PDTPU_ELASTIC_DIR`` names a membership dir), watchdog goodput summary
  when a watchdog is live.  HTTP 200 while healthy, 503 once membership
  sees dead ranks or the watchdog has flagged anomalies.
* ``/flight``   — the live flight-recorder ring as JSON (same schema as a
  post-mortem dump, but scrapeable from a *running* job)
* ``/xprof``    — the last published ``Executor.xprof_report()`` snapshot
  (the Executor publishes automatically via :func:`publish_snapshot`)
* ``/spans``    — recent span begin/end events from the flight ring
  (``?n=200`` bounds the reply; ``?since=SEQ`` reads incrementally, with
  an explicit ``truncated: true`` when the cursor fell behind the ring)
* ``/ledger``   — calibration-ledger records (utils/ledger.py): the
  measured-vs-predicted drift stream per compiled program, same
  ``?since=``/``truncated`` cursor contract as ``/spans``, plus the
  per-model calibration bands
* ``/history``  — the SLO engine's retained metric samples
  (utils/monitor.py ``MetricsHistory``): ``?series=a,b`` selects series,
  ``?since=SEQ`` reads incrementally with the same ``truncated`` verdict,
  ``?max_points=N`` thins the reply by even-stride downsampling
* ``/alerts``   — the SLO engine's alert plane (utils/slo.py): every
  (slo, severity) state machine, firing names, the recent transition
  chain, and the registered objectives.  Firing page-severity alerts
  also flip ``/healthz`` to 503 via the health-provider hook.

Server threads are daemons (``ThreadingHTTPServer.daemon_threads``) and the
accept loop runs on a daemon thread, so a scraped process — including a
pytest worker — exits without joins.  Everything served is a snapshot copy;
scrapes never block writers.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from ..core import flags as _flags
from . import monitor as _monitor
from . import trace as _trace

__all__ = ["TelemetryServer", "start_telemetry", "stop_telemetry",
           "get_server", "start_from_env", "publish_snapshot",
           "get_snapshot", "register_health_provider", "TELEMETRY_PORT_ENV"]

TELEMETRY_PORT_ENV = "PDTPU_TELEMETRY_PORT"

_m_requests = _monitor.counter(
    "telemetry.requests", "HTTP requests served by the telemetry plane, "
    "by endpoint path.", labelnames=("path",))
_m_scrape_ms = _monitor.histogram(
    "telemetry.scrape_ms", "Wall time to render one telemetry HTTP "
    "response (snapshot + serialization).")
_m_port = _monitor.gauge(
    "telemetry.port", "Port the process's telemetry server is bound to "
    "(0 = not serving).")

# ---------------------------------------------------------------------------
# Published snapshots: modules push their latest report; endpoints serve it.
# ---------------------------------------------------------------------------
_snapshots: Dict[str, Any] = {}
_snapshots_lock = threading.Lock()


def publish_snapshot(kind: str, doc: Any) -> None:
    """Store a JSON-safe document under ``kind`` for the telemetry plane to
    serve (``/xprof`` serves kind ``"xprof"``).  The Executor publishes its
    roofline report here on every ``xprof_report()`` call; any module can
    publish its own kind — last write wins, stamped with a publish time."""
    with _snapshots_lock:
        _snapshots[str(kind)] = {"published_at": time.time(), "doc": doc}


def get_snapshot(kind: str) -> Optional[Dict[str, Any]]:
    with _snapshots_lock:
        return _snapshots.get(str(kind))


# ---------------------------------------------------------------------------
# Health providers: named callables contributing to /healthz.
# ---------------------------------------------------------------------------
_health_providers: Dict[str, Callable[[], Any]] = {}
_health_lock = threading.Lock()


def register_health_provider(name: str, provider: Callable[[], Any]) -> None:
    """Contribute a JSON-safe section to ``/healthz`` under ``name``.  A
    provider returning a dict with ``"healthy": False`` flips the endpoint
    to HTTP 503; raising providers are reported as their repr, never a
    failed scrape.  The watchdog registers itself here."""
    with _health_lock:
        _health_providers[str(name)] = provider


def _elastic_health() -> Optional[Dict[str, Any]]:
    """Membership + heartbeat ages: through the process's live
    ElasticMember when one is started, else read-only off the
    PDTPU_ELASTIC_DIR heartbeat files (an observer process — a dashboard
    sidecar — gets the same view without joining)."""
    from ..elastic import membership as _membership

    member = _membership.current_member()
    directory = member.dir if member is not None else \
        os.environ.get(_membership.ELASTIC_DIR_ENV)
    if not directory:
        return None
    ages = _membership.heartbeat_ages(directory)
    out: Dict[str, Any] = {
        "dir": directory,
        "heartbeat_age_s": {str(r): round(a, 3)
                            for r, a in sorted(ages.items())},
        "last_heartbeat_age_s": round(max(ages.values()), 3) if ages
                                else None,
    }
    if member is not None:
        v = member.view()
        out.update(rank=member.rank, live=list(v.live), dead=list(v.dead),
                   evicted=list(v.evicted), world_size=member.world_size(),
                   steps={str(r): s for r, s in sorted(v.steps.items())},
                   healthy=not v.dead)
    return out


class TelemetryServer:
    """One process's telemetry HTTP server.

    ::

        srv = TelemetryServer(port=0).start()     # 0 = ephemeral port
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/metrics")
        srv.stop()

    ``port=0`` binds an ephemeral port (tests); the launcher assigns
    deterministic per-rank ports so operators can point Prometheus at
    ``BASE + rank`` for every rank of a job.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[_monitor.MetricRegistry] = None):
        self.host = host
        self._requested_port = int(port)
        self.registry = registry or _monitor.default_registry()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t_start = 0.0

    @property
    def port(self) -> int:
        """The bound port (resolves ephemeral port 0), 0 when stopped."""
        return self._httpd.server_address[1] if self._httpd else 0

    @property
    def running(self) -> bool:
        return self._httpd is not None

    # -- request handling ----------------------------------------------------
    def _routes(self):
        return {
            "/": self._index,
            "/metrics": self._metrics,
            "/healthz": self._healthz,
            "/flight": self._flight,
            "/xprof": self._xprof,
            "/spans": self._spans,
            "/ledger": self._ledger,
            "/history": self._history,
            "/alerts": self._alerts,
        }

    def _index(self, query) -> tuple:
        lines = ["paddle_tpu telemetry plane", ""]
        lines += sorted(self._routes())[1:]
        return 200, "text/plain; charset=utf-8", "\n".join(lines) + "\n"

    def _metrics(self, query) -> tuple:
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                self.registry.to_prometheus_text())

    def _healthz(self, query) -> tuple:
        doc: Dict[str, Any] = {
            "status": "ok",
            "rank": _trace._rank(),
            "pid": os.getpid(),
            "trace_id": _trace.job_trace_id(),
            "uptime_s": round(time.time() - self._t_start, 3),
        }
        healthy = True
        try:
            elastic = _elastic_health()
        except Exception as e:  # a broken share must not 500 the probe
            elastic = {"error": repr(e)}
        if elastic is not None:
            doc["elastic"] = elastic
            if elastic.get("healthy") is False:
                healthy = False
        with _health_lock:
            providers = list(_health_providers.items())
        for name, provider in providers:
            try:
                section = provider()
            except Exception as e:
                section = {"error": repr(e)}
            if section is None:
                continue
            doc[name] = section
            if isinstance(section, dict) and section.get("healthy") is False:
                healthy = False
        doc["status"] = "ok" if healthy else "degraded"
        return (200 if healthy else 503, "application/json",
                json.dumps(doc, default=repr))

    def _flight(self, query) -> tuple:
        return (200, "application/json",
                json.dumps(_trace.flight_recorder().to_json(), default=repr))

    def _xprof(self, query) -> tuple:
        snap = get_snapshot("xprof")
        if snap is None:
            return (404, "application/json", json.dumps(
                {"error": "no xprof report published yet — run "
                          "Executor.xprof_report() (metrics flag on)"}))
        return 200, "application/json", json.dumps(snap, default=repr)

    def _spans(self, query) -> tuple:
        try:
            n = int(query.get("n", ["200"])[0])
            since = int(query.get("since", ["0"])[0])
        except ValueError:
            return (400, "application/json",
                    json.dumps({"error": "n/since must be integers"}))
        fr = _trace.flight_recorder()
        events, truncated = fr.read_since(since)
        events = [e for e in events
                  if e.get("kind") in ("span_begin", "span_end")]
        return 200, "application/json", json.dumps({
            "last_seq": fr.last_seq,
            # the ring already evicted events past the cursor: the poller
            # fell behind the bounded window (distinct from the ?n= trim,
            # which only bounds this reply)
            "truncated": truncated,
            "spans": events[-max(0, n):],
        }, default=repr)

    def _ledger(self, query) -> tuple:
        try:
            n = int(query.get("n", ["200"])[0])
            since = int(query.get("since", ["0"])[0])
        except ValueError:
            return (400, "application/json",
                    json.dumps({"error": "n/since must be integers"}))
        from . import ledger as _ledger_mod

        led = _ledger_mod.ledger()
        records, truncated = led.read_since(since)
        return 200, "application/json", json.dumps({
            "last_seq": led.last_seq,
            "truncated": truncated,
            "bands": _ledger_mod.BANDS,
            "records": records[-max(0, n):],
        }, default=repr)

    def _history(self, query) -> tuple:
        try:
            since = int(query.get("since", ["0"])[0])
            max_points = int(query.get("max_points", ["512"])[0])
        except ValueError:
            return (400, "application/json", json.dumps(
                {"error": "since/max_points must be integers"}))
        from . import slo as _slo

        hist = _slo.history()
        names = hist.names()
        wanted = names
        if "series" in query:
            requested = [s for part in query["series"]
                         for s in part.split(",") if s]
            wanted = [s for s in requested if s in names]
        series = {name: hist.read_since(name, since, max_points=max_points)
                  for name in wanted}
        return 200, "application/json", json.dumps({
            "last_seq": hist.last_seq(),
            "sample_secs": float(_flags.get_flag("slo_sample_secs")),
            "names": names,
            "series": series,
        }, default=repr)

    def _alerts(self, query) -> tuple:
        from . import slo as _slo

        eng = _slo.get_engine()
        if eng is None:
            return 200, "application/json", json.dumps(
                {"running": False, "alerts": [], "firing": [],
                 "transitions": [], "objectives": []})
        return 200, "application/json", json.dumps(eng.alerts_doc(),
                                                   default=repr)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "TelemetryServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            # every request on its own daemon thread; never log to stderr
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                t0 = time.perf_counter()
                parsed = urlparse(self.path)
                route = server._routes().get(parsed.path)
                if route is None:
                    status, ctype, body = 404, "application/json", \
                        json.dumps({"error": f"no endpoint {parsed.path!r}",
                                    "endpoints": sorted(server._routes())})
                else:
                    try:
                        status, ctype, body = route(parse_qs(parsed.query))
                    except Exception as e:  # endpoint bug ≠ dead plane
                        status, ctype, body = 500, "application/json", \
                            json.dumps({"error": repr(e)})
                payload = body.encode("utf-8")
                try:
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except (BrokenPipeError, ConnectionResetError):
                    return  # scraper went away mid-reply
                _m_requests.inc(path=parsed.path)
                _m_scrape_ms.observe((time.perf_counter() - t0) * 1000.0)

        httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                    Handler)
        httpd.daemon_threads = True
        httpd.allow_reuse_address = True
        self._httpd = httpd
        self._t_start = time.time()
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="pdtpu-telemetry", daemon=True)
        self._thread.start()
        _m_port.set(self.port)
        _trace.flight_recorder().record(
            "telemetry_start", name=f"{self.host}:{self.port}",
            port=self.port)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        _m_port.set(0)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# ---------------------------------------------------------------------------
# Process-wide singleton + launch-worker bootstrap.
# ---------------------------------------------------------------------------
_server: Optional[TelemetryServer] = None
_server_lock = threading.Lock()


def get_server() -> Optional[TelemetryServer]:
    return _server


def start_telemetry(port: Optional[int] = None,
                    host: str = "127.0.0.1") -> TelemetryServer:
    """Start (or return) the process-wide telemetry server.  ``port=None``
    resolves from the ``telemetry_port`` flag; an explicit 0 binds an
    ephemeral port."""
    global _server
    with _server_lock:
        if _server is not None and _server.running:
            return _server
        if port is None:
            port = int(_flags.get_flag("telemetry_port"))
        _server = TelemetryServer(port=port, host=host).start()
        return _server


def stop_telemetry() -> None:
    """Stop the process-wide server AND reset the plane's shared state:
    registered health providers and published snapshots are dropped, so a
    stop/start cycle serves only sections re-registered by live modules —
    a provider closing over a dead watchdog or stale executor must not
    haunt the next server's /healthz (idempotence regression-pinned in
    tests/test_telemetry.py).  Per-instance ``TelemetryServer.stop()``
    deliberately does NOT clear them: tests run private servers against
    the same process-wide provider dict."""
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
    with _health_lock:
        _health_providers.clear()
    with _snapshots_lock:
        _snapshots.clear()


def start_from_env() -> Optional[TelemetryServer]:
    """Worker bootstrap, called at ``paddle_tpu`` import: start the plane
    when ``PDTPU_TELEMETRY_PORT`` (exported per-rank by ``launch
    --telemetry_port``) or the ``telemetry_port`` flag names a port.  A
    bind failure (port taken — e.g. a not-yet-reaped predecessor after an
    elastic restart) is flight-recorded and swallowed: telemetry must
    never kill a training job."""
    env = os.environ.get(TELEMETRY_PORT_ENV, "")
    try:
        port = int(env) if env else int(_flags.get_flag("telemetry_port"))
    except ValueError:
        port = 0
    if port <= 0:
        return None
    try:
        srv = start_telemetry(port=port)
    except OSError as e:
        _trace.flight_recorder().record(
            "telemetry_bind_failed", name=f"port{port}", port=port,
            error=repr(e))
        return None
    # the plane is up: bring the SLO engine with it (slo flag gated; a
    # broken engine start is swallowed — observability must never kill
    # the job it observes)
    try:
        from . import slo as _slo
        _slo.start_from_env()
    except Exception:
        pass
    return srv
