"""Model encryption: AES-CTR cipher over the native runtime.

Reference parity: paddle/fluid/framework/io/crypto/ — ``Cipher`` /
``CipherFactory`` (cipher.h) and ``AESCipher`` (aes_cipher.cc, cryptopp),
used to encrypt inference-model files.  TPU-native design: a self-contained
FIPS-197 AES core in native/src/crypto.cc (C++, validated against the
FIPS-197 and SP 800-38A known-answer vectors in tests/test_native.py) in
CTR mode, driven over ctypes; files carry a 16-byte random IV header.
"""
from __future__ import annotations

import ctypes
import hashlib
import hmac as _hmac
import os
from typing import Optional

from ..core import native as _native

_MAGIC = b"PDTPU\x01"   # legacy v1 header: magic + 16-byte IV (no auth tag)
_MAGIC2 = b"PDTPU\x02"  # v2 header: magic + IV + ct + HMAC-SHA256(iv||ct)
_TAG_LEN = 32


class Cipher:
    """AES-CTR cipher (ref cipher.h Cipher).  ``key`` is 16/24/32 raw
    bytes.

    Blobs are authenticated: encrypt() appends an HMAC-SHA256 tag (keyed by
    a digest-separated derivation of ``key``) over ``iv || ciphertext``, and
    decrypt() rejects tampered or truncated blobs with ``ValueError``.
    There is no unauthenticated fallback — pre-tag v1 blobs (``PDTPU\\x01``,
    never shipped) are rejected, so the tag cannot be stripped by rewriting
    the magic (downgrade attack).  The reference's AESCipher
    (aes_cipher.cc) is unauthenticated; this is a deliberate strengthening.
    """

    def __init__(self, key: bytes):
        if len(key) not in (16, 24, 32):
            raise ValueError(
                f"AES key must be 16/24/32 bytes, got {len(key)}")
        self._key = bytes(key)
        lib = _native.get_lib()
        if lib is None:
            raise RuntimeError(
                "native runtime unavailable; build native/ first "
                "(make -C native)")
        self._lib = lib

    def _crypt(self, data: bytes, iv: bytes) -> bytes:
        buf = bytearray(data)
        if buf:
            c_buf = (ctypes.c_uint8 * len(buf)).from_buffer(buf)
            rc = self._lib.pd_aes_ctr_crypt(self._key, len(self._key), iv,
                                            c_buf, len(buf))
            if rc != 0:
                raise RuntimeError("pd_aes_ctr_crypt failed")
        return bytes(buf)

    def _mac_key(self) -> bytes:
        return hashlib.sha256(b"pdtpu-mac:" + self._key).digest()

    def encrypt(self, plaintext: bytes, iv: Optional[bytes] = None) -> bytes:
        """Returns header || iv || ciphertext || tag (ref AESCipher::Encrypt,
        plus integrity the reference lacks)."""
        iv = os.urandom(16) if iv is None else bytes(iv)
        if len(iv) != 16:
            raise ValueError("IV must be 16 bytes")
        ct = self._crypt(plaintext, iv)
        tag = _hmac.new(self._mac_key(), iv + ct, hashlib.sha256).digest()
        return _MAGIC2 + iv + ct + tag

    def decrypt(self, blob: bytes) -> bytes:
        if blob[:len(_MAGIC2)] == _MAGIC2:
            body = blob[len(_MAGIC2):]
            if len(body) < 16 + _TAG_LEN:
                raise ValueError("encrypted blob truncated")
            iv, ct, tag = body[:16], body[16:-_TAG_LEN], body[-_TAG_LEN:]
            want = _hmac.new(self._mac_key(), iv + ct,
                             hashlib.sha256).digest()
            if not _hmac.compare_digest(tag, want):
                raise ValueError(
                    "encrypted blob failed authentication (wrong key or "
                    "tampered data)")
            return self._crypt(ct, iv)
        if blob[:len(_MAGIC)] == _MAGIC:
            raise ValueError(
                "unauthenticated v1 blob rejected (re-encrypt with the "
                "current format; v1 acceptance would enable a tag-stripping "
                "downgrade)")
        raise ValueError("not a paddle_tpu encrypted blob (bad magic)")

    def encrypt_to_file(self, plaintext: bytes, path: str) -> None:
        """ref AESCipher::EncryptToFile."""
        with open(path, "wb") as f:
            f.write(self.encrypt(plaintext))

    def decrypt_from_file(self, path: str) -> bytes:
        """ref AESCipher::DecryptFromFile."""
        with open(path, "rb") as f:
            return self.decrypt(f.read())


class CipherFactory:
    """ref cipher.h CipherFactory::CreateCipher — the reference reads a
    cipher-config file naming the algorithm; only AES-CTR exists here."""

    @staticmethod
    def create_cipher(key: bytes) -> Cipher:
        return Cipher(key)


def generate_key(n_bytes: int = 32) -> bytes:
    """Random AES key (ref CipherUtils::GenKey)."""
    if n_bytes not in (16, 24, 32):
        raise ValueError("AES key length must be 16/24/32 bytes")
    return os.urandom(n_bytes)
