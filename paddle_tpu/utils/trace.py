"""Distributed tracing + flight recorder.

Reference parity: the reference correlates host `RecordEvent` trees with
device activity per process and merges them offline (tools/timeline.py over
CUPTI/profiler protos, SURVEY §5.1) — but it never correlates *across*
processes: each trainer/pserver timeline is an island and a dead worker
leaves only an exit code.

TPU-native design: a W3C-traceparent-style context layer on top of the
existing native event store.

* ``SpanContext`` — (trace_id, span_id, parent_id) with thread-local
  current-span tracking.  One job-level trace_id is minted by
  ``distributed.launch`` and exported to every rank (``PDTPU_TRACE_ID``), so
  spans from all ranks, PS clients and PS servers share one trace.
* ``span(name, **attrs)`` — context manager that nests under
  ``profiler.RecordEvent`` (spans land in the native event store and come
  out in chrome traces / summaries) and logs begin/end into the flight
  recorder with the span's ids and attributes.
* ``inject(carrier)`` / ``extract(carrier)`` — propagate the current
  context across process boundaries (the PS wire protocol carries the
  traceparent; the server parents its handler span under the caller's).
* ``FlightRecorder`` — bounded ring of the last N structured events (span
  begin/end, RPCs, executor runs, heartbeats, NaN hits, exceptions;
  ``flight_recorder_size`` flag).  ``arm_postmortem`` hooks
  ``sys.excepthook`` and SIGTERM so a dying rank dumps the ring to JSON —
  the post-mortem a crashed worker leaves behind.
* ``arm_from_env`` — called at ``paddle_tpu`` import inside launch workers
  (``PDTPU_TRACE_DIR`` set): enables the profiler, arms the post-mortem,
  and atexit-dumps the per-rank chrome trace that ``python -m
  tools.tracecat`` merges into one multi-rank timeline.
"""
from __future__ import annotations

import atexit
import json
import os
import re
import signal
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import flags as _flags
from . import profiler as _profiler

__all__ = [
    "SpanContext", "Span", "span", "current_span", "current_context",
    "inject", "extract", "job_trace_id", "FlightRecorder", "flight_recorder",
    "arm_postmortem", "arm_from_env", "register_postmortem_info",
    "TRACE_ID_ENV", "TRACE_DIR_ENV",
]

TRACE_ID_ENV = "PDTPU_TRACE_ID"
TRACE_DIR_ENV = "PDTPU_TRACE_DIR"

# version 00, 16-byte trace id, 8-byte span id, flags (sampled)
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def _rand_hex(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


_job_trace_id_cached: Optional[str] = None
_job_lock = threading.Lock()


def job_trace_id() -> str:
    """The process's job-level trace id: ``PDTPU_TRACE_ID`` when launched
    under ``distributed.launch`` (every rank shares it), else minted once
    per process."""
    global _job_trace_id_cached
    if _job_trace_id_cached is None:
        with _job_lock:
            if _job_trace_id_cached is None:
                env = os.environ.get(TRACE_ID_ENV, "")
                _job_trace_id_cached = (
                    env if re.fullmatch(r"[0-9a-f]{32}", env)
                    else _rand_hex(16))
    return _job_trace_id_cached


def _rank() -> int:
    try:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:
        return 0


class SpanContext:
    """Immutable (trace_id, span_id, parent_id) triple, W3C-trace-context
    shaped: 32-hex trace id shared by the whole job, 16-hex span id."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id or job_trace_id()
        self.span_id = span_id or _rand_hex(8)
        self.parent_id = parent_id

    def child(self) -> "SpanContext":
        return SpanContext(self.trace_id, _rand_hex(8), self.span_id)

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, value: str) -> "Optional[SpanContext]":
        m = _TRACEPARENT_RE.match(str(value).strip().lower())
        if m is None:
            return None
        return cls(trace_id=m.group(1), span_id=m.group(2))

    def __repr__(self):
        return (f"SpanContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, parent_id={self.parent_id!r})")

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_id == other.parent_id)


_tls = threading.local()


def _span_stack() -> List["Span"]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_span() -> "Optional[Span]":
    st = _span_stack()
    return st[-1] if st else None


def current_context() -> Optional[SpanContext]:
    sp = current_span()
    return sp.context if sp is not None else None


def inject(carrier: Dict[str, str]) -> Dict[str, str]:
    """Write the current context into `carrier` (W3C ``traceparent`` key).
    No current span → carrier untouched.  Returns the carrier."""
    ctx = current_context()
    if ctx is not None:
        carrier["traceparent"] = ctx.to_traceparent()
    return carrier


def extract(carrier: Optional[Dict[str, str]]) -> Optional[SpanContext]:
    """Read a context out of `carrier`; None on absent/malformed."""
    if not carrier:
        return None
    tp = carrier.get("traceparent")
    if not tp:
        return None
    return SpanContext.from_traceparent(tp)


class Span:
    """Scoped span: nests under the thread's current span (or under
    ``parent`` when given — how a PS server parents its handler span under
    the calling trainer's context), pushes a ``profiler.RecordEvent`` so
    the span lands in the native event store, and records begin/end into
    the flight recorder.

    ::

        with trace.span("executor::run", program=7) as sp:
            ...                       # sp.context carries the ids
            sp.set_attr("ops", 42)
    """

    def __init__(self, name: str, parent: Optional[SpanContext] = None,
                 **attrs: Any):
        self.name = str(name)
        self._parent = parent
        self.attrs = dict(attrs)
        self.context: Optional[SpanContext] = None
        self._event: Optional[_profiler.RecordEvent] = None
        self._t0 = 0.0

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        base = self._parent if self._parent is not None else current_context()
        self.context = base.child() if base is not None else SpanContext()
        self._event = _profiler.RecordEvent(self.name)
        self._event.__enter__()
        _span_stack().append(self)
        self._t0 = time.perf_counter()
        flight_recorder().record("span_begin", name=self.name,
                                 ctx=self.context, **self.attrs)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        fields = dict(self.attrs)
        fields["dur_ms"] = round(dur_ms, 3)
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        flight_recorder().record("span_end", name=self.name,
                                 ctx=self.context, **fields)
        st = _span_stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:          # mispaired exit: drop without corrupting
            st.remove(self)
        self._event.__exit__(exc_type, exc, tb)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with Span(self.name, **self.attrs):
                return fn(*args, **kwargs)
        return wrapper


span = Span


# ---------------------------------------------------------------------------
# Flight recorder: bounded ring of structured events, dumped post-mortem.
# ---------------------------------------------------------------------------
def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _json_safe(v) for k, v in value.items()}
    return repr(value)


class FlightRecorder:
    """Ring buffer of the last N structured events (``flight_recorder_size``
    flag).  Appends are lock-free (deque with maxlen); every event is stamped
    with wall time, rank, thread, and the ids of the event's span context
    (explicit ``ctx=`` or the thread's current span)."""

    def __init__(self, size: Optional[int] = None):
        if size is None:
            size = int(_flags.get_flag("flight_recorder_size"))
        self._events: "deque" = deque(maxlen=max(1, int(size)))
        self._seq = 0
        self._seq_lock = threading.Lock()

    @property
    def size(self) -> int:
        return self._events.maxlen

    @property
    def last_seq(self) -> int:
        """Monotonic count of events ever recorded (ring evictions
        included) — cursor anchor for :meth:`events_since`."""
        return self._seq

    def events_since(self, seq: int) -> List[Dict[str, Any]]:
        """Events with a ``seq`` stamp strictly greater than ``seq`` still
        present in the ring — the incremental read the watchdog and the
        telemetry ``/spans`` endpoint poll with (events evicted between
        polls are simply gone; the ring is a window, not a log)."""
        return [e for e in self._events if e.get("seq", 0) > seq]

    def read_since(self, seq: int) -> Tuple[List[Dict[str, Any]], bool]:
        """:meth:`events_since` plus an explicit truncation verdict: True
        when the ring has already evicted (or :meth:`clear`-ed) events the
        ``seq`` cursor was entitled to, so pollers of ``/spans`` and
        ``/ledger`` can tell "nothing happened" apart from "you fell
        behind the window" instead of silently losing events."""
        events = list(self._events)
        if self._seq <= seq:
            truncated = False          # cursor is current (or from the
            #                            future after a restart) — nothing
            #                            was missed
        elif not events:
            truncated = True           # events were recorded past the
            #                            cursor but none survive (cleared
            #                            ring, or size-0 window)
        else:
            oldest = min(e.get("seq", 0) for e in events)
            truncated = oldest > seq + 1
        return [e for e in events if e.get("seq", 0) > seq], truncated

    def record(self, kind: str, name: str = "",
               ctx: Optional[SpanContext] = None, **fields: Any) -> None:
        if ctx is None:
            ctx = current_context()
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        ev: Dict[str, Any] = {
            "seq": seq,
            "ts": time.time(),
            "kind": str(kind),
            "name": str(name),
            "rank": _rank(),
            "thread": threading.current_thread().name,
        }
        if ctx is not None:
            ev["trace_id"] = ctx.trace_id
            ev["span_id"] = ctx.span_id
            if ctx.parent_id:
                ev["parent_id"] = ctx.parent_id
        for k, v in fields.items():
            ev[k] = _json_safe(v)
        self._events.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def to_json(self) -> Dict[str, Any]:
        return {
            "meta": {
                "rank": _rank(),
                "pid": os.getpid(),
                "trace_id": job_trace_id(),
                "size": self.size,
                "dumped_at": time.time(),
            },
            "events": self.events(),
        }

    def dump(self, path: str) -> int:
        """Write the ring as JSON; returns the event count.  Written via a
        temp file + rename so a dump racing a second signal never leaves a
        truncated file."""
        with _pm_info_lock:
            providers = list(_pm_info.items())
        for kind, provider in providers:
            try:
                info = provider()
            except Exception:
                continue
            if info is not None:
                self.record(kind, **{"info": info})
        doc = self.to_json()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return len(doc["events"])


# -- post-mortem info providers ---------------------------------------------
# Modules register a zero-arg callable keyed by event kind; dump() calls each
# one and records its (JSON-safe) snapshot into the ring just before writing,
# so a crash dump carries live state the ring itself never saw — e.g. xprof
# registers "xprof.summary" (top regions + MFU of the last profile report).
_pm_info: Dict[str, Callable[[], Any]] = {}
_pm_info_lock = threading.Lock()


def register_postmortem_info(kind: str, provider: Callable[[], Any]) -> None:
    """Attach `provider`'s snapshot to every flight-recorder dump as one
    event of `kind`.  The provider returns a JSON-safe dict (or None to
    skip); it must not raise — but a dump is a last-gasp path, so failures
    are swallowed there regardless."""
    with _pm_info_lock:
        _pm_info[str(kind)] = provider


_flight: Optional[FlightRecorder] = None
_flight_lock = threading.Lock()


def flight_recorder() -> FlightRecorder:
    """The process-wide flight recorder (created on first use so the
    ``flight_recorder_size`` flag/env is honored)."""
    global _flight
    if _flight is None:
        with _flight_lock:
            if _flight is None:
                _flight = FlightRecorder()
    return _flight


# ---------------------------------------------------------------------------
# Post-mortem arming: excepthook + SIGTERM dump, launch-worker bootstrap.
# ---------------------------------------------------------------------------
_armed_paths: List[str] = []


def arm_postmortem(path: str, signals=(signal.SIGTERM,)) -> None:
    """Dump the flight recorder to `path` when the process dies abnormally:
    an uncaught exception (``sys.excepthook`` — the exception itself is
    recorded first) or a termination signal (the launcher's abort path).
    Prior hooks/handlers are chained, not replaced."""
    _armed_paths.append(path)
    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            flight_recorder().record("exception", name=exc_type.__name__,
                                     message=str(exc)[:500])
            flight_recorder().dump(path)
        except OSError:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook

    for sig in signals:
        try:
            prev = signal.getsignal(sig)

            def handler(signum, frame, _prev=prev):
                try:
                    flight_recorder().record(
                        "signal", name=signal.Signals(signum).name)
                    flight_recorder().dump(path)
                except OSError:
                    pass
                if callable(_prev):
                    _prev(signum, frame)
                else:
                    # default disposition: exit like the signal killed us
                    # (SystemExit runs atexit, so the chrome trace dumps too)
                    sys.exit(128 + signum)

            signal.signal(sig, handler)
        except (ValueError, OSError):
            pass  # not the main thread / unsupported signal: excepthook only


_armed_from_env = False


def arm_from_env() -> Optional[str]:
    """Launch-worker bootstrap (idempotent), called at ``paddle_tpu`` import
    when ``PDTPU_TRACE_DIR`` is set: start the host profiler, arm the
    post-mortem dump to ``flight.rank<r>.json``, and register an atexit
    export of the per-rank chrome trace ``trace.rank<r>.json`` — the files
    ``python -m tools.tracecat`` merges.  Returns the trace dir (or None
    when the env var is unset)."""
    global _armed_from_env
    trace_dir = os.environ.get(TRACE_DIR_ENV)
    if not trace_dir or _armed_from_env:
        return trace_dir or None
    _armed_from_env = True
    rank = _rank()
    os.makedirs(trace_dir, exist_ok=True)
    trace_path = os.path.join(trace_dir, f"trace.rank{rank}.json")
    flight_path = os.path.join(trace_dir, f"flight.rank{rank}.json")
    _profiler.start_profiler()
    arm_postmortem(flight_path)

    def _dump_at_exit():
        try:
            _profiler.export_chrome_tracing(trace_path)
        except Exception:
            pass
        try:
            flight_recorder().dump(flight_path)
        except OSError:
            pass

    atexit.register(_dump_at_exit)
    flight_recorder().record("worker_start", name=f"rank{rank}",
                             trace_dir=trace_dir)
    return trace_dir
