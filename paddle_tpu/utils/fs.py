"""Filesystem abstraction: LocalFS + HDFSClient.

Reference parity: python/paddle/distributed/fleet/utils/fs.py — ``FS`` ABC
with ``LocalFS`` and ``HDFSClient`` (the reference shells out to the
``hadoop fs`` CLI with retries; framework/io/fs.cc does the same from C++).
The auto-checkpoint and fleet checkpoint paths take an ``fs`` object so
cloud jobs can point at HDFS; local runs use LocalFS.

The HDFS data plane is unchanged from the reference design (a subprocess
CLI wrapper — there is nothing TPU-specific about remote file IO); the
binary is configurable so tests can exercise the full command plumbing with
a stub executable.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import List, Optional


class ExecuteError(RuntimeError):
    pass


class FS:
    """ref fs.py FS abstract interface."""

    def ls_dir(self, path):  # -> (dirs, files)
        raise NotImplementedError

    def is_dir(self, path) -> bool:
        raise NotImplementedError

    def is_file(self, path) -> bool:
        raise NotImplementedError

    def is_exist(self, path) -> bool:
        raise NotImplementedError

    def mkdirs(self, path) -> None:
        raise NotImplementedError

    def delete(self, path) -> None:
        raise NotImplementedError

    def rename(self, src, dst) -> None:
        raise NotImplementedError

    def upload(self, local_path, fs_path) -> None:
        raise NotImplementedError

    def download(self, fs_path, local_path) -> None:
        raise NotImplementedError

    def touch(self, path, exist_ok=True) -> None:
        raise NotImplementedError


class LocalFS(FS):
    """ref fs.py LocalFS — thin os/shutil wrapper."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        entries = sorted(os.listdir(path))
        dirs = [e for e in entries if os.path.isdir(os.path.join(path, e))]
        files = [e for e in entries if not os.path.isdir(os.path.join(path, e))]
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def upload(self, local_path, fs_path):
        self.mkdirs(os.path.dirname(fs_path) or ".")
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path):
            if not exist_ok:
                raise ExecuteError(f"{path} exists")
            return
        open(path, "a").close()


class HDFSClient(FS):
    """``hadoop fs`` CLI wrapper (ref fs.py HDFSClient: builds
    ``hadoop --config <dir> fs -<cmd>`` lines, retries transient failures).

    ``hadoop_bin`` defaults to ``hadoop`` on PATH; configs may carry
    ``fs.default.name`` / ``hadoop.job.ugi`` like the reference.
    """

    def __init__(self, hadoop_home: Optional[str] = None, configs=None,
                 hadoop_bin: Optional[str] = None, time_out=5 * 60 * 1000,
                 sleep_inter=1000, retries: int = 3):
        if hadoop_bin is None:
            if hadoop_home:
                hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
            else:
                hadoop_bin = shutil.which("hadoop")
        if hadoop_bin is None:
            raise RuntimeError(
                "HDFSClient needs a hadoop CLI: pass hadoop_home=/path or "
                "put `hadoop` on PATH (ref fleet/utils/fs.py HDFSClient)")
        # generic -D options are FsShell options: they go AFTER the `fs`
        # subcommand (`hadoop fs -D k=v -ls ...`), like the reference builds
        # its command lines
        self._bin = hadoop_bin
        self._dopts: List[str] = []
        for k, v in (configs or {}).items():
            self._dopts += ["-D", f"{k}={v}"]
        self._retries = int(retries)
        self._timeout = time_out / 1000.0
        self._sleep_inter = sleep_inter / 1000.0

    def _cmd(self, args) -> List[str]:
        return [self._bin, "fs", *self._dopts, *args]

    def _run(self, *args: str) -> str:
        cmd = self._cmd(args)
        last = None
        for attempt in range(self._retries):
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=self._timeout)
            except subprocess.TimeoutExpired:
                last = f"timed out after {self._timeout}s"
                continue
            if proc.returncode == 0:
                return proc.stdout
            last = proc.stderr.strip()
            if attempt + 1 < self._retries:
                time.sleep(self._sleep_inter)
        raise ExecuteError(f"{' '.join(cmd)} failed after "
                           f"{self._retries} tries: {last}")

    def _test(self, flag: str, path: str) -> bool:
        try:
            proc = subprocess.run(self._cmd(["-test", flag, path]),
                                  capture_output=True, text=True,
                                  timeout=self._timeout)
        except subprocess.TimeoutExpired:
            raise ExecuteError(f"hadoop fs -test {flag} {path} timed out")
        return proc.returncode == 0

    def ls_dir(self, path):
        out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return sorted(dirs), sorted(files)

    def is_dir(self, path):
        return self._test("-d", path)

    def is_file(self, path):
        return self._test("-f", path)

    def is_exist(self, path):
        return self._test("-e", path)

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def rename(self, src, dst):
        self._run("-mv", src, dst)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path):
            if not exist_ok:
                raise ExecuteError(f"{path} exists")
            return
        self._run("-touchz", path)
