"""Numerical debugging: NaN/Inf detection.

Reference parity: FLAGS_check_nan_inf (platform/flags.cc:44) and the per-op
post-check `CheckOpHasNanOrInf` that executors run over op outputs
(framework/details/nan_inf_utils_detail.cc), reporting the op and variable
name.  TPU-native design (SURVEY.md §5.2 mapping): under jit there are no
per-op boundaries — the check runs on whole pytrees at user-chosen points
(losses, grads, params) via `check_numerics`, with `jax.debug.callback`
making it jit-safe; `enable_nan_check()` flips jax's global debug_nans for
eager paths and arms the flag consulted by the train-step helpers.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..core import flags as _flags
from . import monitor as _monitor

__all__ = ["check_numerics", "enable_nan_check", "disable_nan_check",
           "nan_check_enabled"]

_m_nan_events = _monitor.counter(
    "debug.nan_events", "NaN/Inf detections raised by check_numerics, per "
    "check-point tag (ref FLAGS_check_nan_inf post-checks).",
    labelnames=("tag",))


def enable_nan_check(eager_also: bool = True) -> None:
    """Arm NaN/Inf checking (ref FLAGS_check_nan_inf)."""
    _flags.set_flags({"check_nan_inf": True})
    if eager_also:
        jax.config.update("jax_debug_nans", True)


def disable_nan_check() -> None:
    _flags.set_flags({"check_nan_inf": False})
    jax.config.update("jax_debug_nans", False)


def nan_check_enabled() -> bool:
    return bool(_flags.get_flag("check_nan_inf"))


def _report(bad_names, tag):
    names = [n for n in bad_names if n]
    # count + flight-record the hit BEFORE raising: the post-mortem dump of
    # a run that died on NaN shows which tensor tripped first
    _m_nan_events.inc(tag=str(tag))
    from . import trace as _trace

    _trace.flight_recorder().record("nan", name=str(tag), leaves=names)
    raise FloatingPointError(
        f"NaN/Inf detected in {tag!r}: {names}"
        if names else f"NaN/Inf detected in {tag!r}")


def check_numerics(tree: Any, tag: str = "tensors", force: bool = False):
    """Raise FloatingPointError if any leaf of `tree` has NaN/Inf.

    jit-safe (uses jax.debug.callback); a no-op unless the check_nan_inf
    flag is set or `force=True`.  Returns `tree` so it can be inlined:
        grads = check_numerics(grads, "grads")
    """
    if not (force or nan_check_enabled()):
        return tree
    leaves_with_paths = jax.tree_util.tree_flatten_with_path(tree)[0]

    names = []
    flags = []
    for path, leaf in leaves_with_paths:
        arr = jnp.asarray(leaf)
        if not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        names.append(jax.tree_util.keystr(path))
        flags.append(~jnp.all(jnp.isfinite(arr)))
    if not flags:
        return tree

    def _cb(bad):
        bad_names = [n for n, b in zip(names, bad) if b]
        if bad_names:
            _report(bad_names, tag)

    jax.debug.callback(_cb, jnp.stack(flags))
    return tree
