"""Op-level cost attribution, roofline/MFU analysis, and device-memory
profiling over XLA's own cost model (``xprof``).

Reference parity: the reference pairs its host profiler with a CUPTI device
tracer (platform/device_tracer.h) so kernel time is attributable to the
framework op that launched it, and tools/timeline.py renders the join.  A
TPU has no CUPTI — and XLA fuses ops so aggressively that "which kernel"
is the wrong question anyway.  TPU-native design: attribution happens at
the *HLO metadata* layer instead of the driver layer.

* **Attribution** — the Executor's traced step wraps every lowered op in
  ``jax.named_scope("<op_type>.b<block>.i<idx>")`` (``@``/``:`` are eaten
  by XLA's scope sanitizer, so the encoding is dotted); the scope survives
  into each HLO instruction's ``metadata.op_name`` — through fusion, and
  through AD as ``jvp(<scope>)`` / ``transpose(jvp(<scope>))``, which means
  backward-pass FLOPs attribute to the *source* forward op.  A post-compile
  pass parses the optimized module text (``aot.as_text()``), models per-
  instruction flops and bytes from shapes (dot/conv get exact formulas,
  elementwise get element counts), and aggregates per source-op region and
  per op type.  ``cost_analysis()`` totals anchor the model (the
  ``flops_xla``/``bytes_xla`` fields).
* **Roofline / MFU** — a device peak table (TPU generations + a documented
  CPU fallback) classifies each region compute- vs memory-bound by
  arithmetic intensity vs the ridge point, models per-region time as
  ``max(flops/peak_flops, bytes/peak_bw)``, and computes per-region and
  whole-program MFU.  A measured step time (``executor.step_time_ms``)
  anchors the model; modeled-vs-measured drift is itself a report field —
  a drift ≫ 1 means the program is bound by something the roofline does
  not see (host overhead, collectives, serialization).
* **Memory** — ``memory_analysis()`` (args / outputs / temps / generated
  code) becomes the ``executor.device_mem_*`` gauges plus a per-program
  breakdown, and a ``jax.live_arrays()`` census tracks what is actually
  resident right now (the serving ``TenantManager`` layers peak-temp
  tracking across its live-executable LRU on top).

``python -m tools.xprof`` renders table / JSON / chrome-trace views; the
last built report is flight-recorded (top regions + MFU) on post-mortem
dumps so a crash dump carries a perf snapshot.

Model limitations (documented, reported, never silently wrong): loop
bodies are counted once (trip counts are dynamic), custom-calls model 0
flops unless the owning kernel registered a cost model
(``register_custom_call_cost`` — every ops/pallas kernel does, keyed by
its ``pallas.<kernel>`` scope tag, so fused-kernel programs keep ≥90%
attribution coverage; bytes always count), and bytes are modeled at
fusion granularity — fused intermediates are register traffic, not HBM.
"""
from __future__ import annotations

import json
import math
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import monitor as _monitor
from . import trace as _trace

__all__ = [
    "resolve_peaks", "parse_hlo", "attribute_hlo", "build_report",
    "profile_aot", "profile_jit", "memory_stats", "live_array_census",
    "render_table", "to_chrome_trace", "summarize", "last_summary",
    "OP_SCOPE_RE", "op_scope_name",
]

# -- telemetry (registered at import so metricsdump lists them) --------------
_m_reports = _monitor.counter(
    "xprof.reports", "xprof roofline/attribution reports built.")
_m_coverage = _monitor.gauge(
    "xprof.attribution_coverage", "Fraction of the last report's modeled "
    "flops attributed to named source ops (named_scope regions).")
_m_mfu = _monitor.gauge(
    "xprof.mfu", "Whole-program MFU of the last report (measured when a "
    "step time anchored it, else modeled).")

# ---------------------------------------------------------------------------
# Device peak table.
# ---------------------------------------------------------------------------
# (device_kind substring, peak dense flops/sec (bf16), peak HBM bytes/sec,
# HBM capacity bytes) per *jax device* — chips for v4+, cores for v2/v3.
# Public spec numbers; the table is deliberately coarse: the roofline
# classifies and ranks, it does not promise cycle accuracy.  The capacity
# column is what static/memcheck.py prices peak residency against (MC001).
_GB = 1 << 30
_TPU_PEAKS: Tuple[Tuple[str, float, float, int], ...] = (
    ("v6e", 918e12, 1640e9, 32 * _GB), ("trillium", 918e12, 1640e9, 32 * _GB),
    ("v5p", 459e12, 2765e9, 95 * _GB),
    ("v5 lite", 197e12, 819e9, 16 * _GB), ("v5e", 197e12, 819e9, 16 * _GB),
    ("v4", 275e12, 1228e9, 32 * _GB),
    ("v3", 61.5e12, 450e9, 16 * _GB),   # per core (2 cores/chip)
    ("v2", 22.5e12, 150e9, 8 * _GB),    # per core
)
# Order-of-magnitude CPU fallback (one host core running XLA:CPU): the
# absolute MFU is meaningless there, but the ridge point (5 flops/byte)
# still separates compute-bound matmuls from memory-bound elementwise, so
# classification and ranking work on CPU CI.  No HBM capacity: host RAM is
# not a budget memcheck can meaningfully enforce, so hbm_bytes stays None
# and MC001 only fires under an explicit capacity override.
_CPU_PEAK = (200e9, 40e9, None)


class PeakSpec:
    __slots__ = ("kind", "flops_per_sec", "bytes_per_sec", "source",
                 "hbm_bytes")

    def __init__(self, kind: str, flops_per_sec: float,
                 bytes_per_sec: float, source: str,
                 hbm_bytes: Optional[int] = None):
        self.kind = kind
        self.flops_per_sec = float(flops_per_sec)
        self.bytes_per_sec = float(bytes_per_sec)
        self.source = source
        # per-device HBM capacity in bytes; None when unknown (CPU fallback)
        self.hbm_bytes = None if hbm_bytes is None else int(hbm_bytes)

    @property
    def ridge(self) -> float:
        """Arithmetic intensity (flops/byte) where compute and memory time
        balance — AI above it is compute-bound."""
        return self.flops_per_sec / self.bytes_per_sec

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "peak_flops_per_sec": self.flops_per_sec,
                "peak_bytes_per_sec": self.bytes_per_sec,
                "ridge_flops_per_byte": round(self.ridge, 3),
                "hbm_bytes": self.hbm_bytes,
                "source": self.source}


def resolve_peaks(device_kind: Optional[str] = None,
                  peak_flops: Optional[float] = None,
                  peak_bytes_per_sec: Optional[float] = None) -> PeakSpec:
    """The peak spec for ``device_kind`` (default: the first jax device).
    Explicit ``peak_flops``/``peak_bytes_per_sec`` override the table —
    the escape hatch for new hardware."""
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = "unknown"
    if peak_flops is not None and peak_bytes_per_sec is not None:
        return PeakSpec(device_kind, peak_flops, peak_bytes_per_sec,
                        "override")
    low = device_kind.lower()
    for sub, fl, bw, hbm in _TPU_PEAKS:
        if sub in low:
            return PeakSpec(device_kind, fl, bw, "table", hbm_bytes=hbm)
    fl, bw, hbm = _CPU_PEAK
    return PeakSpec(device_kind, fl, bw, "fallback", hbm_bytes=hbm)


# ---------------------------------------------------------------------------
# Optimized-HLO text parsing.
# ---------------------------------------------------------------------------
_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "u2": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,\s]*)\](?:\{[^}]*\})?")
_COMP_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s+\([^)]*\)\s+->\s+.+\{\s*$")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
    r"(\([^)]*\)|[a-z0-9]+\[[0-9,\s]*\](?:\{[^}]*\})?)\s+"
    r"([\w\-]+)\((.*)$")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_BODY_RE = re.compile(r"body=%?([\w.\-]+)")
_COND_RE = re.compile(r"condition=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_LHS_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{([0-9,\s]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=(\w+)_(\w+)->(\w+)")

# Regions: the executor encodes each lowered op as <op_type>.b<block>.i<idx>
# (see op_scope_name); AD wraps the component in jvp()/transpose().
OP_SCOPE_RE = re.compile(r"^([A-Za-z0-9_]+)\.b(\d+)\.i(\d+)$")
_WRAP_RE = re.compile(r"^([A-Za-z_][\w.\-]*)\((.+)\)$")

# flops = output element count for these opcodes (coarse: one op per lane)
_ELEMENTWISE = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "power",
    "exponential", "exponential-minus-one", "log", "log-plus-one", "tanh",
    "logistic", "rsqrt", "sqrt", "cbrt", "negate", "abs", "sign", "floor",
    "ceil", "round-nearest-afz", "round-nearest-even", "compare", "select",
    "and", "or", "xor", "not", "shift-left", "shift-right-logical",
    "shift-right-arithmetic", "remainder", "atan2", "sine", "cosine", "tan",
    "expm1", "log1p", "is-finite", "clamp", "erf",
))
# pure data movement / bookkeeping: zero flops, and for the starred set the
# instruction itself also carries no HBM traffic (operands are counted by
# their consumers)
_ZERO_BYTES = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "iota",
))


def op_scope_name(op_type: str, block_idx: int, op_idx: int) -> str:
    """The named-scope encoding the Executor plants per lowered op.  Dotted
    — XLA's scope sanitizer truncates ``@`` and ``:`` out of
    ``metadata.op_name`` (measured), so ``mul@0:3`` would arrive as just
    ``mul``; ``mul.b0.i3`` survives intact."""
    return f"{op_type}.b{block_idx}.i{op_idx}"


class HloInstr:
    __slots__ = ("name", "opcode", "out_shapes", "operand_shapes", "op_name",
                 "rest")

    def __init__(self, name, opcode, out_shapes, operand_shapes, op_name,
                 rest):
        self.name = name
        self.opcode = opcode
        self.out_shapes = out_shapes          # [(dtype, (dims...)), ...]
        self.operand_shapes = operand_shapes
        self.op_name = op_name
        self.rest = rest                      # attr tail for dot/conv/calls


def _parse_shapes(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    out = []
    for dtype, dims in _SHAPE_RE.findall(text):
        try:
            shape = tuple(int(d) for d in dims.replace(" ", "").split(",")
                          if d != "")
        except ValueError:
            shape = ()
        out.append((dtype, shape))
    return out


def _elems(shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


def _shape_bytes(dtype: str, shape: Tuple[int, ...]) -> int:
    return _elems(shape) * _DTYPE_BYTES.get(dtype, 4)


def parse_hlo(text: str) -> Tuple[Dict[str, List[HloInstr]], List[str]]:
    """Parse HLO module text into {computation name: [instructions]} plus
    the list of ENTRY computation names (one per module in the text)."""
    comps: Dict[str, List[HloInstr]] = {}
    entries: List[str] = []
    current: Optional[List[HloInstr]] = None
    for line in text.splitlines():
        m = _COMP_RE.match(line)
        if m is not None:
            current = comps.setdefault(m.group(2), [])
            if m.group(1):
                entries.append(m.group(2))
            continue
        if current is None:
            continue
        if line.strip() == "}":
            current = None
            continue
        mi = _INSTR_RE.match(line)
        if mi is None:
            continue
        name, out_type, opcode, rest = mi.groups()
        op_name_m = _OPNAME_RE.search(rest)
        # operand refs are always "<shape> %<name>"; attr shapes (layouts,
        # literals) never precede a %-ref, so this scan is unambiguous
        operands = _parse_shapes(
            " ".join(re.findall(r"([a-z0-9]+\[[0-9,\s]*\](?:\{[^}]*\})?)\s+%",
                                rest.split(", metadata=")[0])))
        current.append(HloInstr(
            name, opcode, _parse_shapes(out_type), operands,
            op_name_m.group(1) if op_name_m else "", rest))
    return comps, entries


# custom-call cost registry: Pallas kernels lower to custom-call
# instructions XLA's shape-based model cannot price, so each kernel
# wrapper emits a ``jax.named_scope("pallas.<kernel>")`` tag (it survives
# into metadata.op_name) and registers fn(HloInstr) -> flops here via
# ops/pallas/config.register_cost.  Bytes need no registry: custom-call
# operand/output bytes are already counted by _instr_bytes.
_CUSTOM_CALL_COSTS: Dict[str, Any] = {}


def register_custom_call_cost(tag: str, instr_flops_fn) -> None:
    """Price custom-call instructions whose metadata op_name contains
    ``tag`` with ``instr_flops_fn(instr) -> flops``."""
    _CUSTOM_CALL_COSTS[tag] = instr_flops_fn


def _custom_call_flops(instr: HloInstr) -> float:
    for tag, fn in _CUSTOM_CALL_COSTS.items():
        if tag in instr.op_name:
            try:
                return float(fn(instr))
            except Exception:
                return 0.0
    return 0.0


def _instr_flops(instr: HloInstr) -> float:
    op = instr.opcode
    if not instr.out_shapes:
        return 0.0
    out_elems = sum(_elems(s) for _, s in instr.out_shapes)
    if op == "custom-call":
        return _custom_call_flops(instr)
    if op == "dot":
        m = _LHS_CDIMS_RE.search(instr.rest)
        if m is None or not instr.operand_shapes:
            return 2.0 * out_elems
        lhs = instr.operand_shapes[0][1]
        contracted = 1
        for d in (int(x) for x in m.group(1).replace(" ", "").split(",")
                  if x != ""):
            if d < len(lhs):
                contracted *= lhs[d]
        return 2.0 * out_elems * contracted
    if op == "convolution":
        # flops = 2 * out_elems * (kernel taps per output element); the rhs
        # dims minus its 'o' (output-feature) dim are exactly those taps —
        # grouped convs included, since rhs 'i' is already per-group
        m = _DIM_LABELS_RE.search(instr.rest)
        if m is None or len(instr.operand_shapes) < 2:
            return 2.0 * out_elems
        rhs_labels = m.group(2)
        rhs = instr.operand_shapes[1][1]
        taps = 1
        for i, lab in enumerate(rhs_labels):
            if lab != "o" and i < len(rhs):
                taps *= rhs[i]
        return 2.0 * out_elems * taps
    if op in ("reduce", "reduce-window"):
        return float(sum(_elems(s) for _, s in instr.operand_shapes[:1])
                     or out_elems)
    # sparse-lookup pricing (parallel/embedding.py exchange): one
    # address-compute+load per gathered element, one accumulate per
    # scattered update element — so an embedding backward's cost scales
    # with batch ids, never with vocab size
    if op == "gather":
        return float(out_elems)
    if op == "scatter":
        # operands = (target, indices, updates): pay for the update rows
        if len(instr.operand_shapes) >= 3:
            return float(_elems(instr.operand_shapes[2][1]))
        return float(out_elems)
    if op == "dynamic-update-slice":
        if len(instr.operand_shapes) >= 2:
            return float(_elems(instr.operand_shapes[1][1]))
        return float(out_elems)
    if op in _ELEMENTWISE:
        return float(out_elems)
    return 0.0


def _instr_bytes(instr: HloInstr) -> float:
    if instr.opcode in _ZERO_BYTES:
        return 0.0
    total = sum(_shape_bytes(d, s) for d, s in instr.out_shapes)
    total += sum(_shape_bytes(d, s) for d, s in instr.operand_shapes)
    return float(total)


def _unwrap(component: str) -> str:
    """Strip transform wrappers — ``transpose(jvp(X))`` → ``X`` — so
    backward-pass instructions attribute to their forward source scope."""
    while True:
        m = _WRAP_RE.match(component)
        if m is None:
            return component
        component = m.group(2)


def _region_of(op_name: str) -> Tuple[str, str, bool]:
    """(region key, op_type, attributed) for one instruction's op_name.

    Attributed regions come from user named scopes: either the Executor's
    ``<op_type>.b<N>.i<M>`` encoding (innermost match wins — sub-block ops
    nest inside their control-flow op's scope) or any named_scope path the
    user planted (dygraph Layers push their layer names).  ``jit(...)``
    components are jax function boundaries, not user scopes, and the final
    component is the lowered primitive — both are stripped."""
    if not op_name or "/" not in op_name:
        return ("<unattributed>", op_name or "<none>", False)
    comps = op_name.split("/")
    for comp in reversed(comps):
        core = _unwrap(comp)
        m = OP_SCOPE_RE.match(core)
        if m is not None:
            return (core, m.group(1), True)
    kept = []
    for comp in comps[:-1]:
        if comp.startswith(("jit(", "pjit(")):
            continue
        core = _unwrap(comp)
        if core.startswith(("jit(", "pjit(")) or not core:
            continue
        kept.append(core)
    if kept:
        return ("/".join(kept), kept[-1], True)
    return ("<unattributed>", _unwrap(comps[-1]), False)


class _Region:
    __slots__ = ("key", "op_type", "attributed", "flops", "bytes", "instrs")

    def __init__(self, key: str, op_type: str, attributed: bool):
        self.key = key
        self.op_type = op_type
        self.attributed = attributed
        self.flops = 0.0
        self.bytes = 0.0
        self.instrs = 0


def attribute_hlo(text: str) -> Dict[str, _Region]:
    """Walk every module's entry computation (recursing into fusion bodies,
    while bodies/conditions and conditional branches), model per-instruction
    flops and bytes, and aggregate per source region.

    Bytes are modeled at fusion granularity: instructions inside a fused
    computation contribute flops to their own region but no bytes (fused
    intermediates never touch HBM); the fusion instruction's operand +
    output traffic lands on the fusion root's region.  Loop bodies count
    once — HLO does not carry trip counts."""
    comps, entries = parse_hlo(text)
    regions: Dict[str, _Region] = {}
    visited = set()

    def reg(op_name: str) -> _Region:
        key, op_type, attributed = _region_of(op_name)
        r = regions.get(key)
        if r is None:
            r = regions[key] = _Region(key, op_type, attributed)
        return r

    def walk(comp_name: str, fused: bool) -> None:
        if comp_name in visited or comp_name not in comps:
            return
        visited.add(comp_name)
        for instr in comps[comp_name]:
            r = reg(instr.op_name)
            fl = _instr_flops(instr)
            if fl:
                r.flops += fl
            if instr.opcode == "fusion":
                if not fused:
                    r.bytes += _instr_bytes(instr)
                r.instrs += 1
                m = _CALLS_RE.search(instr.rest)
                if m is not None:
                    walk(m.group(1), True)
                continue
            if instr.opcode == "while":
                r.instrs += 1
                for pat in (_BODY_RE, _COND_RE):
                    m = pat.search(instr.rest)
                    if m is not None:
                        walk(m.group(1), fused)
                continue
            if instr.opcode == "conditional":
                r.instrs += 1
                m = _BRANCHES_RE.search(instr.rest)
                if m is not None:
                    for b in re.findall(r"%?([\w.\-]+)", m.group(1)):
                        walk(b, fused)
                continue
            if not fused:
                r.bytes += _instr_bytes(instr)
            r.instrs += 1

    for entry in entries:
        walk(entry, False)
    return regions


# ---------------------------------------------------------------------------
# Report assembly.
# ---------------------------------------------------------------------------
def _cost_dict(cost) -> Dict[str, float]:
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    return cost if isinstance(cost, dict) else {}


def build_report(hlo_text: str, cost=None, memory: Optional[dict] = None,
                 measured_ms: Optional[float] = None,
                 peaks: Optional[PeakSpec] = None,
                 top: Optional[int] = None) -> Dict[str, Any]:
    """The xprof report: per-region roofline over the attribution of
    ``hlo_text``, anchored by XLA's ``cost_analysis`` totals (``cost``) and
    a measured step time when available."""
    peaks = peaks or resolve_peaks()
    regions = attribute_hlo(hlo_text)
    total_flops = sum(r.flops for r in regions.values())
    total_bytes = sum(r.bytes for r in regions.values())
    attributed = sum(r.flops for r in regions.values() if r.attributed)
    coverage = (attributed / total_flops) if total_flops > 0 else 1.0

    rows = []
    for r in regions.values():
        t_c = r.flops / peaks.flops_per_sec
        t_m = r.bytes / peaks.bytes_per_sec
        t = max(t_c, t_m)
        ai = (r.flops / r.bytes) if r.bytes > 0 else math.inf
        rows.append({
            "region": r.key,
            "op_type": r.op_type,
            "attributed": r.attributed,
            "instructions": r.instrs,
            "flops": r.flops,
            "bytes": r.bytes,
            "arithmetic_intensity": (round(ai, 3) if math.isfinite(ai)
                                     else None),
            "bound": "compute" if t_c >= t_m else "memory",
            "modeled_ms": t * 1000.0,
            "mfu": (r.flops / (t * peaks.flops_per_sec)) if t > 0 else 0.0,
        })
    rows.sort(key=lambda row: row["modeled_ms"], reverse=True)
    modeled_ms = sum(row["modeled_ms"] for row in rows)
    for row in rows:
        row["share"] = (row["modeled_ms"] / modeled_ms) if modeled_ms > 0 \
            else 0.0
        row["modeled_ms"] = round(row["modeled_ms"], 6)
        row["share"] = round(row["share"], 4)
        row["mfu"] = round(row["mfu"], 4)
    if top is not None:
        dropped = len(rows) - int(top)
        rows = rows[:int(top)]
    else:
        dropped = 0

    by_type: Dict[str, Dict[str, float]] = {}
    for r in regions.values():
        agg = by_type.setdefault(
            r.op_type, {"flops": 0.0, "bytes": 0.0, "regions": 0})
        agg["flops"] += r.flops
        agg["bytes"] += r.bytes
        agg["regions"] += 1

    cd = _cost_dict(cost)
    flops_xla = cd.get("flops")
    bytes_xla = cd.get("bytes accessed")
    mfu_model = (total_flops / (modeled_ms / 1000.0 * peaks.flops_per_sec)
                 if modeled_ms > 0 else 0.0)
    mfu_meas = drift = None
    if measured_ms and measured_ms > 0:
        mfu_meas = total_flops / (measured_ms / 1000.0 * peaks.flops_per_sec)
        drift = measured_ms / modeled_ms if modeled_ms > 0 else None

    report = {
        "schema": "xprof.report.v1",
        "device": peaks.to_json(),
        "totals": {
            "flops_modeled": total_flops,
            "bytes_modeled": total_bytes,
            "flops_xla": flops_xla,
            "bytes_xla": bytes_xla,
            "attributed_flops": attributed,
            "attribution_coverage": round(coverage, 4),
            "modeled_ms": round(modeled_ms, 6),
            "measured_ms": (round(measured_ms, 4) if measured_ms else None),
            "measured_vs_modeled": (round(drift, 3) if drift else None),
            "mfu_modeled": round(mfu_model, 6),
            "mfu_measured": (round(mfu_meas, 6) if mfu_meas is not None
                             else None),
        },
        "regions": rows,
        "regions_dropped": max(0, dropped),
        "by_op_type": {k: {"flops": v["flops"], "bytes": v["bytes"],
                           "regions": int(v["regions"])}
                       for k, v in sorted(by_type.items())},
    }
    if memory:
        report["memory"] = memory
    _m_reports.inc()
    if _monitor.enabled():
        _m_coverage.set(report["totals"]["attribution_coverage"])
        _m_mfu.set(mfu_meas if mfu_meas is not None else mfu_model)
    _remember(report)
    return report


def memory_stats(aot) -> Optional[Dict[str, int]]:
    """Device-memory breakdown of a compiled executable via
    ``memory_analysis()``: argument / output / temp / generated-code bytes
    (None when the backend exposes no memory model)."""
    try:
        ma = aot.memory_analysis()
    except Exception:
        return None
    if isinstance(ma, (list, tuple)):
        ma = ma[0] if ma else None
    if ma is None:
        return None
    try:
        stats = {
            "args_bytes": int(ma.argument_size_in_bytes),
            "out_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "code_bytes": int(ma.generated_code_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
    except AttributeError:
        return None
    stats["total_bytes"] = (stats["args_bytes"] + stats["out_bytes"]
                            + stats["temp_bytes"] + stats["code_bytes"])
    return stats


def live_array_census() -> Dict[str, Any]:
    """What is actually resident: count and bytes of every live
    ``jax.Array`` in the process (committed or not)."""
    import jax

    count = 0
    nbytes = 0
    for a in jax.live_arrays():
        count += 1
        nbytes += getattr(a, "nbytes", 0) or 0
    return {"count": count, "bytes": nbytes}


def profile_aot(aot, measured_ms: Optional[float] = None,
                peaks: Optional[PeakSpec] = None,
                top: Optional[int] = None) -> Dict[str, Any]:
    """Build the report straight from a jax AOT-compiled executable
    (``jit(f).lower(...).compile()``): optimized HLO text + cost_analysis +
    memory_analysis, all from the artifact that actually runs."""
    text = aot.as_text()
    cost = None
    try:
        cost = aot.cost_analysis()
    except Exception:
        pass
    return build_report(text, cost=cost, memory=memory_stats(aot),
                        measured_ms=measured_ms, peaks=peaks, top=top)


def roofline_totals(aot) -> Optional[Dict[str, Any]]:
    """The roofline ``totals`` block straight off an AOT executable — the
    modeled-ms leg the calibration ledger (utils/ledger.py) joins against
    measured step time.  None when the backend yields no profile (e.g. a
    deserialized persistent-cache artifact without cost analysis)."""
    try:
        return profile_aot(aot)["totals"]
    except Exception:
        return None


def profile_jit(fn, *example, measured_ms: Optional[float] = None,
                peaks: Optional[PeakSpec] = None,
                top: Optional[int] = None) -> Dict[str, Any]:
    """Lower + compile ``fn`` against ``example`` args and profile the
    result.  ``fn`` may already be jitted; a plain callable is jitted."""
    import jax

    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    aot = jitted.lower(*example).compile()
    return profile_aot(aot, measured_ms=measured_ms, peaks=peaks, top=top)


# ---------------------------------------------------------------------------
# Rendering.
# ---------------------------------------------------------------------------
def _human(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit, div in (("T", 1e12), ("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(n) >= div:
            return f"{n / div:.2f}{unit}"
    return f"{n:.0f}"


def render_table(report: Dict[str, Any], top: int = 20) -> str:
    """Human-readable report: totals header + ranked region table."""
    t = report["totals"]
    dev = report["device"]
    lines = [
        f"xprof report — device {dev['kind']} "
        f"(peak {_human(dev['peak_flops_per_sec'])}F/s, "
        f"{_human(dev['peak_bytes_per_sec'])}B/s, "
        f"ridge {dev['ridge_flops_per_byte']} F/B, {dev['source']})",
        f"  flops modeled {_human(t['flops_modeled'])} "
        f"(xla: {_human(t['flops_xla'])})   "
        f"bytes modeled {_human(t['bytes_modeled'])} "
        f"(xla: {_human(t['bytes_xla'])})",
        f"  attribution coverage {t['attribution_coverage']:.1%}   "
        f"modeled {t['modeled_ms']:.4f} ms   "
        f"measured {t['measured_ms'] if t['measured_ms'] is not None else '-'} ms"
        f"   drift x{t['measured_vs_modeled'] if t['measured_vs_modeled'] is not None else '-'}",
        f"  MFU modeled {t['mfu_modeled']:.4f}"
        + (f"   MFU measured {t['mfu_measured']:.4f}"
           if t["mfu_measured"] is not None else ""),
        "",
        f"{'region':<44} {'bound':<7} {'flops':>9} {'bytes':>9} "
        f"{'AI':>8} {'ms(model)':>10} {'share':>7} {'MFU':>7}",
    ]
    for row in report["regions"][:top]:
        ai = row["arithmetic_intensity"]
        lines.append(
            f"{row['region'][:44]:<44} {row['bound']:<7} "
            f"{_human(row['flops']):>9} {_human(row['bytes']):>9} "
            f"{(f'{ai:.1f}' if ai is not None else 'inf'):>8} "
            f"{row['modeled_ms']:>10.4f} {row['share']:>6.1%} "
            f"{row['mfu']:>7.3f}")
    hidden = len(report["regions"]) - top + report.get("regions_dropped", 0)
    if hidden > 0:
        lines.append(f"  ... {hidden} more regions (use --top/--format json)")
    if "memory" in report:
        m = report["memory"]
        lines.append(
            f"memory: args {_human(m['args_bytes'])}B  "
            f"out {_human(m['out_bytes'])}B  temp {_human(m['temp_bytes'])}B  "
            f"code {_human(m['code_bytes'])}B  "
            f"total {_human(m['total_bytes'])}B")
    return "\n".join(lines)


def to_chrome_trace(report: Dict[str, Any]) -> Dict[str, Any]:
    """Synthetic chrome://tracing timeline of the *modeled* step: regions
    laid end to end by modeled time (the roofline's serial-execution view),
    ranked track order, bound class in args."""
    events: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": f"xprof model ({report['device']['kind']})"}},
    ]
    ts = 0.0
    for row in report["regions"]:
        dur = row["modeled_ms"] * 1000.0
        events.append({
            "name": row["region"], "ph": "X", "pid": 0, "tid": 0,
            "ts": round(ts, 3), "dur": round(dur, 3),
            "args": {"bound": row["bound"], "flops": row["flops"],
                     "bytes": row["bytes"], "mfu": row["mfu"],
                     "share": row["share"]},
        })
        ts += dur
    return {"traceEvents": events,
            "metadata": {"totals": report["totals"]}}


def summarize(report: Dict[str, Any], top: int = 3) -> Dict[str, Any]:
    """Condensed block for bench JSON lines and flight-recorder events:
    coverage, MFU, drift, and the top regions (plus the top memory-bound
    ones by name — the answer to "which regions are eating the step")."""
    t = report["totals"]
    return {
        "device": report["device"]["kind"],
        "attribution_coverage": t["attribution_coverage"],
        "mfu_modeled": t["mfu_modeled"],
        "mfu_measured": t["mfu_measured"],
        "measured_vs_modeled": t["measured_vs_modeled"],
        "top_regions": [
            {"region": r["region"], "bound": r["bound"],
             "modeled_ms": r["modeled_ms"], "share": r["share"]}
            for r in report["regions"][:top]],
        "top_memory_bound": [
            r["region"] for r in report["regions"]
            if r["bound"] == "memory"][:top],
        "memory": report.get("memory"),
    }


# ---------------------------------------------------------------------------
# Flight-recorder integration: the last summary rides post-mortem dumps.
# ---------------------------------------------------------------------------
_last_lock = threading.Lock()
_last_summary: Optional[Dict[str, Any]] = None
_hook_registered = False


def last_summary() -> Optional[Dict[str, Any]]:
    with _last_lock:
        return dict(_last_summary) if _last_summary is not None else None


def _remember(report: Dict[str, Any]) -> None:
    global _last_summary, _hook_registered
    s = summarize(report)
    s.pop("memory", None)  # keep the flight event compact
    with _last_lock:
        _last_summary = s
        if not _hook_registered:
            _hook_registered = True
            _trace.register_postmortem_info("xprof.summary", last_summary)


if __name__ == "__main__":  # pragma: no cover - convenience passthrough
    import sys

    from tools import xprof as _cli

    sys.exit(_cli.main())
