from . import checkpoint, debug, monitor, profiler
from .debug import check_numerics, disable_nan_check, enable_nan_check
