from . import (auto_checkpoint, checkpoint, debug, monitor, profiler,
               telemetry, trace, watchdog)
from .auto_checkpoint import AutoCheckpoint
from .debug import check_numerics, disable_nan_check, enable_nan_check
