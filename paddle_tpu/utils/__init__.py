from . import checkpoint
