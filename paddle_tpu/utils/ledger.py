"""Calibration ledger: measured-vs-predicted drift tracking.

The platform carries three static cost models — shardcheck's
``CommEstimate`` (allreduce wire bytes), memcheck's ``MemEstimate``
(peak HBM), and the xprof roofline (modeled step ms) — whose accuracy
is pinned once by tests (2x comm, 1.5x HBM) and then trusted blindly.
This module closes that loop at run time: every ``Executor.run``
compile event and every closed steady-state step window appends a
record keyed by (program fingerprint x plan fingerprint x mesh
fingerprint) that joins what the models *predicted* with what the run
actually *measured* (``executor.step_time_ms``,
``comm.allreduce_bytes``, ``Executor.memory_stats()``), computes a
symmetric drift ratio per model, and raises a ``ledger_drift`` flight
anomaly (counted by the watchdog) when a ratio leaves its calibration
band.  The records are the data source the autoplan scorer
(ROADMAP item 2) gates against and the ``/ledger`` telemetry endpoint
plus ``tools/fleetview`` aggregate across ranks — the reference's
platform/monitor.h StatValue ancestry, turned into a self-auditing
measure-to-verify loop over our own estimators (TACCL, arxiv
2111.04867).

Design rules, in order:

* **Never into the run path.**  Every public hook is wrapped — a
  broken estimator degrades to an unpriced record, never a failed
  ``Executor.run``.
* **Observation only.**  Predictions reuse the memoized compile-path
  analyses (``estimate_peak_cached``; ``estimate_comm`` is pure
  Program arithmetic); nothing here traces, so zero steady-state
  retraces and warm persistent-cache starts hold under the ``ledger``
  flag (pinned in tests/test_ledger.py).
* **Drift is symmetric**: ``max(pred/meas, meas/pred) >= 1.0``, so one
  band bounds both over- and under-prediction — the same two-sided
  contract the shardcheck/memcheck calibration tests pin.
* **Appends are atomic.**  The optional JSONL sink issues one
  ``O_APPEND`` ``os.write`` per record, so concurrent ranks on a
  shared filesystem never interleave mid-line (same idiom as the
  elastic heartbeat files).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags as _flags
from . import monitor as _monitor
from . import trace as _trace

__all__ = [
    "BANDS", "LEDGER_DIR_ENV", "Ledger", "ledger", "drift_ratio",
    "enabled", "pre_compile", "observe_compile", "observe_step",
]

LEDGER_DIR_ENV = "PDTPU_LEDGER_DIR"

# Calibration bands: a drift ratio above the band flight-records a
# ledger_drift anomaly.  comm/mem mirror the test-pinned 2x / 1.5x
# envelopes of estimate_comm / estimate_peak.  The roofline leg is
# tracked but unbanded (None): its peak tables model TPU hardware, so
# measured-vs-modeled ms on CPU CI hosts drifts by design — a band
# lands once TPU-measured calibration data exists (ROADMAP item 2).
BANDS: Dict[str, Optional[float]] = {
    "comm": 2.0,
    "mem": 1.5,
    "roofline": None,
}

_m_records = _monitor.counter(
    "ledger.records", "Calibration-ledger records appended, by kind "
    "(compile event vs steady-state window).", labelnames=("kind",))
_m_drift = _monitor.gauge(
    "ledger.drift_ratio", "Latest symmetric measured-vs-predicted drift "
    "ratio per cost model (>= 1.0; 1.0 = perfectly calibrated).",
    labelnames=("model",))
_m_alarms = _monitor.counter(
    "ledger.drift_alarms", "Drift ratios observed outside a model's "
    "calibration band (each one is also a ledger_drift flight anomaly).",
    labelnames=("model",))


def drift_ratio(predicted: Optional[float],
                measured: Optional[float]) -> Optional[float]:
    """Symmetric calibration ratio: ``max(p/m, m/p)``, or None when either
    leg is missing/non-positive (no prediction, no measurement — e.g. a
    warm persistent-cache start records no traced comm bytes)."""
    try:
        p, m = float(predicted), float(measured)
    except (TypeError, ValueError):
        return None
    if p <= 0.0 or m <= 0.0:
        return None
    r = p / m
    return max(r, 1.0 / r)


class Ledger:
    """Bounded in-memory ring of calibration records + optional JSONL sink.

    The ring mirrors the flight recorder's cursor contract: records carry a
    monotonic ``seq``, ``read_since(seq)`` returns the still-retained tail
    plus an explicit truncation verdict, and ``last_seq`` anchors the next
    incremental ``/ledger?since=`` poll."""

    def __init__(self, capacity: int = 256, path: Optional[str] = None):
        self._records: "deque" = deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._lock = threading.Lock()
        self._path = path
        # per-program join state: the latest compile event's predictions and
        # compile-time measurements, re-joined by later window records
        self._join: Dict[str, Dict[str, Any]] = {}
        # per-program open step window (measured step_time_ms samples)
        self._win: Dict[str, List[float]] = {}

    # -- cursor reads (telemetry /ledger) ---------------------------------

    @property
    def last_seq(self) -> int:
        return self._seq

    def read_since(self, seq: int) -> Tuple[List[Dict[str, Any]], bool]:
        """Records with seq strictly greater than ``seq`` still in the
        ring, plus True when the cursor fell behind the bounded window
        (same verdict rule as FlightRecorder.read_since)."""
        with self._lock:
            records = list(self._records)
            last = self._seq
        if last <= seq:
            truncated = False
        elif not records:
            truncated = True
        else:
            truncated = min(r["seq"] for r in records) > seq + 1
        return [r for r in records if r["seq"] > seq], truncated

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    # -- appends ----------------------------------------------------------

    def append(self, kind: str, key: Dict[str, Optional[str]],
               predicted: Dict[str, Optional[float]],
               measured: Dict[str, Optional[float]],
               **extra: Any) -> Dict[str, Any]:
        """Join one prediction/measurement pair into a record: compute the
        per-model drifts, update the gauges, flag band exits, append to the
        ring (and the JSONL sink), and return the record."""
        drift = {
            "comm": drift_ratio(predicted.get("comm_bytes"),
                                measured.get("allreduce_bytes")),
            "mem": drift_ratio(predicted.get("peak_hbm_bytes"),
                               measured.get("mem_total_bytes")),
            "roofline": drift_ratio(predicted.get("roofline_ms"),
                                    measured.get("step_time_ms")),
        }
        violations = []
        for model, ratio in drift.items():
            if ratio is None:
                continue
            _m_drift.set(ratio, model=model)
            band = BANDS.get(model)
            if band is not None and ratio > band:
                violations.append(model)
        with self._lock:
            self._seq += 1
            record = {
                "seq": self._seq,
                "ts": time.time(),
                "kind": kind,
                "rank": _trace._rank(),
                "key": dict(key),
                "predicted": dict(predicted),
                "measured": dict(measured),
                "drift": drift,
                "band_violations": violations,
            }
            record.update(extra)
            self._records.append(record)
        _m_records.inc(kind=kind)
        for model in violations:
            _m_alarms.inc(model=model)
            # the watchdog's flight drain counts these into its anomaly
            # report; band exits are advisory (they never flip /healthz)
            _trace.flight_recorder().record(
                "ledger_drift", name=model, model=model,
                drift=round(drift[model], 4), band=BANDS[model],
                program=key.get("program") or "")
        if self._path:
            self._append_line(record)
        return record

    def _append_line(self, record: Dict[str, Any]) -> None:
        """One O_APPEND write per line: atomic on POSIX local filesystems,
        so N ranks sharing a ledger_dir never interleave mid-record."""
        try:
            data = (json.dumps(record, sort_keys=True,
                               default=repr) + "\n").encode("utf-8")
            fd = os.open(self._path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
        except OSError:
            pass  # a full/readonly disk must not take down training

    # -- Executor hooks (see module functions for the guarded entry) ------

    def compile_event(self, *, entry, program, plan, feed_arrays,
                      fetch_names, mem_report, pre) -> None:
        program_fp = entry.fingerprint
        plan_fp = None
        mesh_fp = None
        if plan is not None:
            try:
                plan_fp = plan.fingerprint()
            except Exception:
                plan_fp = None
            try:
                from ..parallel.mesh import mesh_fingerprint
                mesh_fp = mesh_fingerprint(plan.resolve_mesh())
            except Exception:
                mesh_fp = None
        key = {"program": program_fp, "plan": plan_fp, "mesh": mesh_fp}

        predicted: Dict[str, Optional[float]] = {
            "comm_bytes": None, "peak_hbm_bytes": None, "roofline_ms": None}
        if plan is not None:
            try:
                from ..static.shardcheck import estimate_comm
                est = estimate_comm(program, plan)
                # the measured leg is the traced comm.allreduce_bytes
                # histogram, which records allreduce wire bytes only —
                # compare like with like (gather_bytes stays out)
                predicted["comm_bytes"] = float(est.allreduce_bytes)
            except Exception:
                pass
        mem_est = mem_report.mem if mem_report is not None else None
        if mem_est is None:
            try:
                from ..static.memcheck import estimate_peak_cached
                mem_est = estimate_peak_cached(program, plan, feed_arrays,
                                               fetch_names)
            except Exception:
                mem_est = None
        if mem_est is not None:
            predicted["peak_hbm_bytes"] = float(mem_est.peak_bytes)
        if entry.aot is not None:
            try:
                from . import xprof as _xprof
                totals = _xprof.roofline_totals(entry.aot)
                if totals and totals.get("modeled_ms"):
                    predicted["roofline_ms"] = float(totals["modeled_ms"])
            except Exception:
                pass

        measured: Dict[str, Optional[float]] = {
            "step_time_ms": None, "allreduce_bytes": None,
            "mem_total_bytes": None}
        # comm bytes are recorded at TRACE time (compress._record_comm):
        # the histogram delta across this compile is what the trace moved
        # per step.  A warm persistent-cache start deserializes without
        # tracing — delta 0 — and the comm leg stays honestly unmeasured.
        if pre is not None and pre.get("comm_bytes") is not None:
            try:
                from ..static.shardcheck import measured_comm_bytes
                delta = measured_comm_bytes() - pre["comm_bytes"]
                if delta > 0:
                    measured["allreduce_bytes"] = float(delta)
            except Exception:
                pass
        if entry.mem:
            # args+out+temp — the exact quantity estimate_peak models and
            # the memcheck calibration tests measure (code bytes excluded
            # on both sides)
            measured["mem_total_bytes"] = float(
                entry.mem.get("args_bytes", 0)
                + entry.mem.get("out_bytes", 0)
                + entry.mem.get("temp_bytes", 0))

        self.append("compile", key, predicted, measured,
                    disk_cache=getattr(entry, "disk_cache", None))
        self._join[program_fp] = {
            "key": key, "predicted": predicted,
            "measured": dict(measured),
        }

    def step_observed(self, program_fp: str, step_ms: float) -> None:
        window = int(_flags.get_flag("ledger_window"))
        if window <= 0:
            return
        samples = self._win.setdefault(program_fp, [])
        samples.append(float(step_ms))
        if len(samples) < window:
            return
        self._win[program_fp] = []
        samples.sort()
        median = samples[len(samples) // 2]
        join = self._join.get(program_fp, {})
        predicted = dict(join.get("predicted") or {
            "comm_bytes": None, "peak_hbm_bytes": None, "roofline_ms": None})
        measured = dict(join.get("measured") or {
            "allreduce_bytes": None, "mem_total_bytes": None})
        measured["step_time_ms"] = median
        key = join.get("key") or {"program": program_fp, "plan": None,
                                  "mesh": None}
        self.append("window", key, predicted, measured,
                    window_steps=len(samples),
                    window_min_ms=round(samples[0], 4),
                    window_max_ms=round(samples[-1], 4))


# ---------------------------------------------------------------------------
# Process-wide singleton + guarded Executor-facing hooks.
# ---------------------------------------------------------------------------
_singleton: Optional[Ledger] = None
_singleton_lock = threading.Lock()


def _sink_path() -> Optional[str]:
    d = str(_flags.get_flag("ledger_dir") or "").strip() \
        or os.environ.get(LEDGER_DIR_ENV, "").strip()
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        return None
    return os.path.join(d, f"ledger.rank{_trace._rank()}.jsonl")


def ledger() -> Ledger:
    """The process-wide ledger (created on first use; the JSONL sink path
    is resolved then, after launch has exported PDTPU_LEDGER_DIR)."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = Ledger(path=_sink_path())
        return _singleton


def reset() -> None:
    """Drop the singleton (tests): the next ledger() call re-resolves the
    sink path and starts a fresh ring/cursor space."""
    global _singleton
    with _singleton_lock:
        _singleton = None


def enabled() -> bool:
    """Ledger hooks run only when both the ledger flag and the metrics
    plane are on — without metrics there is no measured leg to join."""
    return bool(_flags.get_flag("ledger")) and _monitor.enabled()


def pre_compile() -> Optional[Dict[str, float]]:
    """Snapshot taken at the top of the Executor's miss branch: the
    cumulative traced comm bytes *before* this compile, so the compile
    event can attribute the histogram delta to its own trace."""
    if not enabled():
        return None
    try:
        from ..static.shardcheck import measured_comm_bytes
        return {"comm_bytes": measured_comm_bytes()}
    except Exception:
        return None


def observe_compile(*, entry, program, plan, feed_arrays, fetch_names,
                    mem_report=None, pre=None) -> None:
    """Append the compile-event record (guarded: never raises into
    Executor.run; a failing estimator means an unpriced leg, not a failed
    compile)."""
    if not enabled():
        return
    try:
        ledger().compile_event(entry=entry, program=program, plan=plan,
                               feed_arrays=feed_arrays,
                               fetch_names=fetch_names,
                               mem_report=mem_report, pre=pre)
    except Exception:
        pass


def observe_step(program_fp: str, step_ms: float) -> None:
    """Feed one measured steady-state step time into the program's open
    window (guarded; the caller already paid the device sync for
    executor.step_time_ms — this adds a list append)."""
    if not bool(_flags.get_flag("ledger")):
        return
    try:
        ledger().step_observed(program_fp, step_ms)
    except Exception:
        pass
