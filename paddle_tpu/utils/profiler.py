"""Profiler API: scoped host events, summaries, chrome-trace timelines, and
an XLA/jax.profiler bridge.

Reference parity: python/paddle/fluid/profiler.py (`start_profiler`,
`stop_profiler`, the `profiler(...)` context manager, `reset_profiler`) over
platform/profiler.h `RecordEvent` (:126) / `EnableProfiler` (:208), plus
tools/timeline.py's chrome://tracing export.  The host side records into the
native C++ event store (native/src/profiler.cc) through the ctypes bridge;
the device side is delegated to `jax.profiler` (XLA's own tracer replaces
the reference's CUPTI DeviceTracer, SURVEY.md §5.1 TPU mapping).
"""
from __future__ import annotations

import contextlib
import functools
import json
import time
from typing import Optional

from ..core import native as _native
from . import monitor as _monitor

_SORTED_KEYS = (None, "total", "calls", "max", "min", "ave")

__all__ = [
    "RecordEvent", "record_event", "start_profiler", "stop_profiler",
    "reset_profiler", "profiler", "export_chrome_tracing", "summary",
    "start_device_trace", "stop_device_trace",
]


class RecordEvent:
    """Scoped host-side event (ref platform/profiler.h:126).

    Usable as a context manager or a decorator::

        with profiler.RecordEvent("data_load"):
            batch = next(loader)
    """

    def __init__(self, name: str):
        self.name = str(name)

    def __enter__(self):
        _native.prof_push(self.name)
        return self

    def __exit__(self, *exc):
        _native.prof_pop()
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)
        return wrapper


record_event = RecordEvent


def start_profiler(state: str = "All") -> None:
    """ref fluid/profiler.py start_profiler; `state` kept for API parity —
    host events are always recorded, "GPU"/"All" additionally arms the
    device-trace bridge on the next `start_device_trace` call."""
    _native.prof_enable()


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None,
                  stream=None) -> None:
    """Stop recording; emit the summary table (sorted per `sorted_key`:
    total|calls|max|min|ave, ref fluid stop_profiler) and optionally dump a
    chrome-trace timeline to `profile_path` (ref stop_profiler's
    profile_path dumps a proto; here it is directly chrome-trace JSON).

    `stream` routes the summary: None → stdout (the fluid behavior), a
    file-like object → `.write()`, a logger → `.info()` — so library users
    can capture or silence the table instead of eating a bare print."""
    _native.prof_disable()
    if profile_path:
        export_chrome_tracing(profile_path)
    s = summary(sorted_key)
    if not s:
        return
    if stream is None:
        print(s)
    elif hasattr(stream, "write"):
        stream.write(s if s.endswith("\n") else s + "\n")
    elif hasattr(stream, "info"):
        stream.info(s)
    else:
        raise TypeError(f"stream must be None, file-like, or a logger; "
                        f"got {type(stream).__name__}")


def reset_profiler() -> None:
    _native.prof_clear()


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None):
    """ref fluid/profiler.py:profiler context manager."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def export_chrome_tracing(path: str, registry=None) -> int:
    """Dump all recorded host events as chrome://tracing JSON
    (ref tools/timeline.py), merging the metric registry's counter samples
    as chrome counter-track (`ph:"C"`) events so the trace viewer shows
    cache-hit/RPC/step counts alongside the spans.

    Multi-rank aware: every event's pid is this worker's rank (from
    `PADDLE_TRAINER_ID`; the native store writes pid 0) and `ph:"M"`
    `process_name`/`process_sort_index` metadata events label the process —
    so traces from a `distributed.launch` job merge into one readable
    timeline (`python -m tools.tracecat`).  Returns the number of events
    written."""
    import os

    try:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0") or 0)
    except ValueError:
        rank = 0
    n = _native.prof_export_chrome(path)
    if n >= 0:
        with open(path) as f:
            data = json.load(f)
    else:  # native runtime unavailable: counters-only trace
        data = {"traceEvents": []}
    events = data.setdefault("traceEvents", [])
    for e in events:
        e["pid"] = rank
    ts_us = time.time() * 1e6
    reg = registry if registry is not None else _monitor.default_registry()
    for m in reg.metrics():
        if m.kind != "counter":
            continue
        for labels, value in m.samples():
            name = m.name
            if labels:
                name += "{" + ",".join(f"{k}={labels[k]}"
                                       for k in sorted(labels)) + "}"
            events.append({"name": name, "ph": "C", "pid": rank, "ts": ts_us,
                           "args": {"value": float(value)}})
    data["traceEvents"] = [
        {"name": "process_name", "ph": "M", "pid": rank,
         "args": {"name": f"paddle_tpu rank {rank}"}},
        {"name": "process_sort_index", "ph": "M", "pid": rank,
         "args": {"sort_index": rank}},
    ] + events
    with open(path, "w") as f:
        json.dump(data, f)
    return len(data["traceEvents"])


def summary(sorted_key: Optional[str] = None) -> str:
    """Aggregated per-event table, sorted descending by `sorted_key`
    (total|calls|max|min|ave; default total — ref profiler_helper.h)."""
    if sorted_key not in _SORTED_KEYS:
        raise ValueError(
            f"sorted_key must be one of {_SORTED_KEYS}, got {sorted_key!r}")
    return _native.prof_summary(sorted_key)


# ---------------------------------------------------------------- devices --
def start_device_trace(logdir: str) -> None:
    """Start an XLA device trace (TensorBoard format) — the TPU replacement
    for the reference's CUPTI DeviceTracer (platform/device_tracer.h:19)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax
    jax.profiler.stop_trace()
