"""Profiler API: scoped host events, summaries, chrome-trace timelines, and
an XLA/jax.profiler bridge.

Reference parity: python/paddle/fluid/profiler.py (`start_profiler`,
`stop_profiler`, the `profiler(...)` context manager, `reset_profiler`) over
platform/profiler.h `RecordEvent` (:126) / `EnableProfiler` (:208), plus
tools/timeline.py's chrome://tracing export.  The host side records into the
native C++ event store (native/src/profiler.cc) through the ctypes bridge;
the device side is delegated to `jax.profiler` (XLA's own tracer replaces
the reference's CUPTI DeviceTracer, SURVEY.md §5.1 TPU mapping).
"""
from __future__ import annotations

import contextlib
import functools
import time
from typing import Optional

from ..core import native as _native

__all__ = [
    "RecordEvent", "record_event", "start_profiler", "stop_profiler",
    "reset_profiler", "profiler", "export_chrome_tracing", "summary",
    "start_device_trace", "stop_device_trace",
]


class RecordEvent:
    """Scoped host-side event (ref platform/profiler.h:126).

    Usable as a context manager or a decorator::

        with profiler.RecordEvent("data_load"):
            batch = next(loader)
    """

    def __init__(self, name: str):
        self.name = str(name)

    def __enter__(self):
        _native.prof_push(self.name)
        return self

    def __exit__(self, *exc):
        _native.prof_pop()
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)
        return wrapper


record_event = RecordEvent


def start_profiler(state: str = "All") -> None:
    """ref fluid/profiler.py start_profiler; `state` kept for API parity —
    host events are always recorded, "GPU"/"All" additionally arms the
    device-trace bridge on the next `start_device_trace` call."""
    _native.prof_enable()


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None) -> None:
    """Stop recording; print the summary table and optionally dump a
    chrome-trace timeline to `profile_path` (ref stop_profiler's
    profile_path dumps a proto; here it is directly chrome-trace JSON)."""
    _native.prof_disable()
    if profile_path:
        _native.prof_export_chrome(profile_path)
    s = _native.prof_summary()
    if s:
        print(s)


def reset_profiler() -> None:
    _native.prof_clear()


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: Optional[str] = None,
             profile_path: Optional[str] = None):
    """ref fluid/profiler.py:profiler context manager."""
    start_profiler(state)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def export_chrome_tracing(path: str) -> int:
    """Dump all recorded host events as chrome://tracing JSON
    (ref tools/timeline.py). Returns number of events written."""
    return _native.prof_export_chrome(path)


def summary() -> str:
    """Aggregated per-event table sorted by total time
    (ref profiler_helper.h table)."""
    return _native.prof_summary()


# ---------------------------------------------------------------- devices --
def start_device_trace(logdir: str) -> None:
    """Start an XLA device trace (TensorBoard format) — the TPU replacement
    for the reference's CUPTI DeviceTracer (platform/device_tracer.h:19)."""
    import jax
    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax
    jax.profiler.stop_trace()
