"""Runtime stats monitor (Python face of the native StatRegistry).

Reference parity: platform/monitor.h — `StatValue` (:43), `StatRegistry`
(:84) and the STAT_ADD/STAT_RESET macros; values flow into the same
process-wide native registry the C++ subsystems (datafeed) publish to, so
`stats()` shows framework and native counters together.
"""
from __future__ import annotations

from typing import Dict

from ..core import native as _native

__all__ = ["stat_add", "stat_set", "stat_get", "stat_reset", "stats"]

stat_add = _native.stat_add
stat_set = _native.stat_set
stat_get = _native.stat_get
stat_reset = _native.stat_reset


def stats() -> Dict[str, int]:
    """All registered gauges, name -> value."""
    return _native.stat_list()
