"""Runtime telemetry: typed metrics registry + the native StatRegistry shim.

Reference parity: platform/monitor.h — `StatValue` (:43), `StatRegistry`
(:84) and the STAT_ADD/STAT_RESET macros.  The reference keeps a flat
process-wide int registry that C++ subsystems (datafeed) publish to; that
face survives here as the `stat_add`/`stat_set`/`stat_get`/`stat_reset`/
`stats` compat shim over the ctypes bridge.

TPU-native design (SURVEY §5.1): on top of the flat int store this module
grows a real telemetry subsystem — thread-safe `Counter`/`Gauge`/`Histogram`
metric types with optional labels, collected in a `MetricRegistry` with
Prometheus-text and JSON exporters.  The Executor, the op-lowering registry,
the PS server, and the hapi train loop publish into the process-wide
`default_registry()`; `python -m tools.metricsdump` runs a small workload
and dumps it.  Collection is gated behind the `metrics` flag
(`PDTPU_FLAGS_metrics`, default on): with the flag off every instrumented
path still runs but records nothing (one dict lookup of overhead per
would-be sample).

Metric names must match ``^[a-z0-9_.]+$`` (dots become underscores in the
Prometheus rendering) so exporter output stays Prometheus-legal.
"""
from __future__ import annotations

import math
import re
import threading
import time
from collections import deque as _deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import flags as _flags
from ..core import native as _native

__all__ = [
    # metric types + registry
    "Counter", "Gauge", "Histogram", "MetricRegistry",
    "default_registry", "counter", "gauge", "histogram", "enabled",
    "parse_prometheus_text", "TIME_MS_BUCKETS",
    # metrics history (the SLO engine's data plane)
    "SeriesRing", "MetricsHistory", "series_key",
    # native StatRegistry compat shim
    "stat_add", "stat_set", "stat_get", "stat_reset", "stats",
]

_NAME_RE = re.compile(r"^[a-z0-9_.]+$")

# Bucket ladder for wall-time histograms in milliseconds: sub-ms host work
# up through multi-second XLA compiles.
TIME_MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


def enabled() -> bool:
    """True when metric collection is on (the `metrics` flag)."""
    return bool(_flags.get_flag("metrics"))


def _fmt_le(bound: float) -> str:
    return "+Inf" if math.isinf(bound) else repr(float(bound))


class Metric:
    """Base: a named family of samples keyed by label values.

    Mutators are no-ops while the `metrics` flag is off; reads and
    registration always work, so exporters list every declared metric even
    when collection never ran."""

    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} must match {_NAME_RE.pattern} "
                "(lowercase, digits, '_', '.') to stay Prometheus-legal")
        self.name = name
        self.description = description
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._cells: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if tuple(sorted(labels)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"metric {self.name!r} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[k]) for k in self.labelnames)

    def _labels_dict(self, key: Tuple[str, ...]) -> Dict[str, str]:
        return dict(zip(self.labelnames, key))

    def samples(self) -> List[Tuple[Dict[str, str], Any]]:
        """Snapshot [(labels, value)] — safe to iterate while writers run."""
        with self._lock:
            items = list(self._cells.items())
        return [(self._labels_dict(k), v) for k, v in items]


class Counter(Metric):
    """Monotonically increasing count (ref StatValue::increase)."""

    kind = "counter"

    def inc(self, value: float = 1, **labels) -> None:
        if value < 0:
            raise ValueError(f"counter {self.name!r}: cannot inc by {value}")
        key = self._key(labels)
        if not enabled():
            return
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + value

    def value(self, **labels):
        with self._lock:
            return self._cells.get(self._key(labels), 0)


class Gauge(Metric):
    """Last-written value; optionally computed at collect time via
    `set_function` (the Prometheus callback-gauge pattern — used for
    ages/sizes that are cheaper to compute on demand)."""

    kind = "gauge"

    def __init__(self, name: str, description: str = "",
                 labelnames: Sequence[str] = ()):
        super().__init__(name, description, labelnames)
        self._functions: Dict[Tuple[str, ...], Callable[[], float]] = {}

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        if not enabled():
            return
        with self._lock:
            self._cells[key] = value

    def inc(self, value: float = 1, **labels) -> None:
        key = self._key(labels)
        if not enabled():
            return
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + value

    def dec(self, value: float = 1, **labels) -> None:
        self.inc(-value, **labels)

    def set_function(self, fn: Callable[[], float], **labels) -> None:
        """Register `fn` to produce this sample's value at collect time.
        Registration is independent of the `metrics` flag; the flag gates
        whether collect evaluates it."""
        key = self._key(labels)
        with self._lock:
            self._functions[key] = fn

    def remove(self, **labels) -> None:
        """Drop the sample (and any collect-time function) for `labels`."""
        key = self._key(labels)
        with self._lock:
            self._cells.pop(key, None)
            self._functions.pop(key, None)

    def value(self, **labels):
        key = self._key(labels)
        with self._lock:
            fn = self._functions.get(key)
            if fn is None:
                return self._cells.get(key, 0)
        try:
            return fn()
        except Exception:
            return math.nan

    def samples(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            items = dict(self._cells)
            fns = list(self._functions.items())
        if fns and enabled():
            # evaluate callbacks outside the lock: a function touching other
            # metrics (or this one) must not deadlock collection — and a
            # raising callback degrades to a nan sample instead of failing
            # the whole scrape (percentile-over-empty-histogram gauges are
            # the canonical case: Histogram.percentile itself returns nan on
            # an empty cell, but a user callback gets the same safety net)
            for key, fn in fns:
                try:
                    items[key] = fn()
                except Exception:
                    items[key] = math.nan
        return [(self._labels_dict(k), v) for k, v in items.items()]


class _HistCell:
    __slots__ = ("count", "total", "mn", "mx", "bucket_counts")

    def __init__(self, nbuckets: int):
        self.count = 0
        self.total = 0.0
        self.mn = math.inf
        self.mx = -math.inf
        self.bucket_counts = [0] * nbuckets


class Histogram(Metric):
    """Bucketed distribution with count/sum/min/max (the per-event Agg of
    profiler_helper.h, generalized to arbitrary observations)."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, description, labelnames)
        bounds = tuple(sorted(float(b) for b in (buckets or TIME_MS_BUCKETS)))
        if not bounds or not math.isinf(bounds[-1]):
            bounds = bounds + (math.inf,)
        self.buckets = bounds

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        if not enabled():
            return
        v = float(value)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _HistCell(len(self.buckets))
            cell.count += 1
            cell.total += v
            cell.mn = min(cell.mn, v)
            cell.mx = max(cell.mx, v)
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    cell.bucket_counts[i] += 1
                    break

    class _Timer:
        def __init__(self, hist: "Histogram", labels):
            self._hist, self._labels = hist, labels

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._hist.observe((time.perf_counter() - self._t0) * 1000.0,
                               **self._labels)
            return False

    def time(self, **labels) -> "Histogram._Timer":
        """Context manager observing the block's wall time in ms."""
        return Histogram._Timer(self, labels)

    def _cell_percentile(self, cell: _HistCell, q: float) -> float:
        """Estimate the q-th percentile (0 <= q <= 100) from one cell's
        bucket counts: rank the target observation, find its bucket, and
        interpolate linearly inside it (the Prometheus histogram_quantile
        estimator), clamped to the observed [min, max] so single-bucket
        cells report honest bounds instead of bucket edges."""
        if cell.count == 0:
            return math.nan
        rank = (q / 100.0) * cell.count
        cum, lo = 0, 0.0
        for bound, n in zip(self.buckets, cell.bucket_counts):
            prev = cum
            cum += n
            if cum >= rank and n:
                hi = cell.mx if math.isinf(bound) else bound
                est = lo + (hi - lo) * ((rank - prev) / n)
                return min(max(est, cell.mn), cell.mx)
            if not math.isinf(bound):
                lo = bound
        return cell.mx

    def percentile(self, q: float, **labels) -> float:
        """The q-th percentile estimate for one labeled cell.

        An empty histogram — the cell was never observed, or collection ran
        with the ``metrics`` flag off — returns ``nan``, never raises: the
        serving TTFT percentile gauges and the SLO projection scrape this
        at collect time, and a scrape must not fail because traffic hasn't
        arrived yet (regression-pinned in tests/test_metrics.py).  One
        shared implementation for every latency consumer (serving SLO
        admission, servebench reports) — the estimate's error is bounded by
        the containing bucket's width, so size the ``buckets`` ladder to
        the precision the decision needs."""
        if not 0 <= q <= 100:
            raise ValueError(f"percentile q must be in [0, 100], got {q}")
        with self._lock:
            cell = self._cells.get(self._key(labels))
            if cell is None:
                return math.nan
            snap = _HistCell(len(self.buckets))
            snap.count, snap.total = cell.count, cell.total
            snap.mn, snap.mx = cell.mn, cell.mx
            snap.bucket_counts = list(cell.bucket_counts)
        return self._cell_percentile(snap, q)

    # quantile points the JSON exporter publishes for every histogram cell
    JSON_QUANTILES = (50.0, 90.0, 95.0, 99.0)

    def _stat(self, cell: _HistCell) -> Dict[str, Any]:
        cum, out = 0, {}
        for bound, n in zip(self.buckets, cell.bucket_counts):
            cum += n
            out[_fmt_le(bound)] = cum
        quantiles = {f"p{q:g}": self._cell_percentile(cell, q)
                     for q in self.JSON_QUANTILES} if cell.count else {}
        return {"count": cell.count, "sum": cell.total,
                "min": cell.mn if cell.count else 0.0,
                "max": cell.mx if cell.count else 0.0,
                "quantiles": quantiles,
                "buckets": out}

    def samples(self) -> List[Tuple[Dict[str, str], Dict[str, Any]]]:
        with self._lock:
            items = [(k, self._stat(c)) for k, c in self._cells.items()]
        return [(self._labels_dict(k), stat) for k, stat in items]

    def count(self, **labels) -> int:
        with self._lock:
            cell = self._cells.get(self._key(labels))
            return cell.count if cell else 0

    def sum(self, **labels) -> float:
        with self._lock:
            cell = self._cells.get(self._key(labels))
            return cell.total if cell else 0.0


_KIND_TO_CLS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricRegistry:
    """Process-wide set of named metrics with get-or-create registration
    (registering the same (name, type, labelnames) twice returns the same
    object — modules instrument at import without ownership fights)."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.RLock()

    # -- registration --------------------------------------------------------
    def _get_or_create(self, cls, name: str, description: str,
                       labelnames: Sequence[str], **kwargs) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}; cannot "
                        f"re-register as {cls.kind} with labels "
                        f"{tuple(labelnames)}")
                return m
            m = cls(name, description, labelnames, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, description: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, description, labelnames)

    def gauge(self, name: str, description: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, description, labelnames)

    def histogram(self, name: str, description: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, description, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def metrics(self) -> List[Metric]:
        """Snapshot list — stable under concurrent registration."""
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Zero every metric's samples; registrations stay."""
        for m in self.metrics():
            with m._lock:
                m._cells.clear()

    # -- export --------------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        """JSON-serializable snapshot: `json.loads(json.dumps(x)) == x`."""
        out: Dict[str, Any] = {}
        for m in self.metrics():
            entries = []
            for labels, value in m.samples():
                if m.kind == "histogram":
                    entries.append({"labels": labels, **value})
                else:
                    entries.append({"labels": labels, "value": float(value)})
            out[m.name] = {"type": m.kind, "description": m.description,
                          "labelnames": list(m.labelnames),
                          "samples": entries}
        return {"metrics": out}

    def prom_samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        """Flat (prometheus_name, labels, value) triples — the exact sample
        set `to_prometheus_text` renders (histograms expand to
        `_bucket`/`_sum`/`_count`)."""
        flat: List[Tuple[str, Dict[str, str], float]] = []
        for m in self.metrics():
            flat.extend(_samples_of(m, m.name.replace(".", "_")))
        return flat

    def to_prometheus_text(self) -> str:
        """Prometheus text exposition (text/plain; version 0.0.4)."""
        lines: List[str] = []
        for m in self.metrics():
            pname = m.name.replace(".", "_")
            if m.description:
                lines.append(f"# HELP {pname} " + _escape_help(m.description))
            lines.append(f"# TYPE {pname} {m.kind}")
            for sname, labels, value in _samples_of(m, pname):
                lines.append(_prom_line(sname, labels, value))
        return "\n".join(lines) + "\n"


def _samples_of(m: Metric, pname: str):
    for labels, value in m.samples():
        if m.kind == "histogram":
            for le, n in value["buckets"].items():
                yield pname + "_bucket", {**labels, "le": le}, float(n)
            yield pname + "_sum", labels, float(value["sum"])
            yield pname + "_count", labels, float(value["count"])
        else:
            yield pname, labels, float(value)


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_line(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape_label(str(labels[k]))}"'
                        for k in sorted(labels))
        return f"{name}{{{body}}} {repr(float(value))}"
    return f"{name} {repr(float(value))}"


_PROM_LINE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_PROM_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r'\\(.)')


def _unescape_label(value: str) -> str:
    # Only \n, \" and \\ are escapes in the exposition format; any other
    # backslash pair passes through verbatim (m.group(0), backslash kept) so
    # a literal like "C:\temp" written by a non-escaping producer survives a
    # parse -> re-expose round trip instead of silently losing backslashes.
    return _UNESCAPE_RE.sub(
        lambda m: {"n": "\n", '"': '"', "\\": "\\"}.get(m.group(1),
                                                        m.group(0)), value)


def parse_prometheus_text(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]:
    """Parse Prometheus text exposition back to {(name, labelitems): value}
    — the inverse of `to_prometheus_text` over `prom_samples` (used by the
    round-trip tests, metricsdump consumers, and fleetview's rank scraper).

    Records are split on "\n" ONLY — the exposition format's line
    terminator.  Label values may legally carry a raw \r, \v, \f or
    U+2028-style separator (only backslash, double-quote and newline are
    escaped on the wire), and str.splitlines() splits on all of those, so
    it would tear such a sample apart mid-value (regression-pinned with
    hostile label values in tests/test_metrics.py)."""
    out: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for line in text.split("\n"):
        line = line.strip(" \t\r")
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable prometheus line: {line!r}")
        name, labelstr, value = m.groups()
        labels = {}
        if labelstr:
            for lm in _PROM_LABEL_RE.finditer(labelstr):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
        out[(name, tuple(sorted(labels.items())))] = float(value)
    return out


# ---------------------------------------------------------------------------
# Process-wide default registry + module-level conveniences.
# ---------------------------------------------------------------------------
_default = MetricRegistry()


def default_registry() -> MetricRegistry:
    return _default


def counter(name: str, description: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _default.counter(name, description, labelnames)


def gauge(name: str, description: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _default.gauge(name, description, labelnames)


def histogram(name: str, description: str = "",
              labelnames: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _default.histogram(name, description, labelnames, buckets)


# ---------------------------------------------------------------------------
# Metrics history: bounded per-series rings fed by a self-sampler.
#
# The registry above is a point-in-time snapshot plane; the SLO engine
# (utils/slo.py) needs *retained* measurements to compute windowed burn
# rates.  `MetricsHistory.sample()` takes one pass over a registry and
# appends derived scalar series into bounded rings:
#
#   counters   -> ``name{k=v,...}:rate``  (delta / dt between ticks, plus an
#                 aggregate sum-rate under the bare ``name:rate`` for
#                 labeled families so e.g. total `serve.load_shed` rate is
#                 addressable without enumerating tenants)
#   gauges     -> ``name{k=v,...}``       (non-finite samples skipped)
#   histograms -> ``name{k=v,...}:p50`` / ``:p99`` computed over the BUCKET
#                 DELTAS since the previous tick — the windowed-percentile
#                 semantics of Prometheus `histogram_quantile(rate(...))`.
#                 A cumulative-cell percentile never recovers after a latency
#                 spike (old samples dominate forever); the per-interval
#                 estimate does, which is what makes alert *resolution*
#                 possible.  Ticks with no new observations emit nothing.
#
# Cursor contract: every appended sample carries a seq from one history-wide
# monotonic counter, and `read_since(series, since)` reports
# ``truncated=True`` iff the ring has evicted samples newer than `since` —
# the same verdict rule as FlightRecorder and the calibration Ledger, so
# pollers share one resume idiom across /flight, /ledger and /history.
# Downsampling is applied at read time (`max_points` even thinning, newest
# sample always kept) so the stored ring stays exact.
# ---------------------------------------------------------------------------


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Canonical history-series key: ``name`` or ``name{k=v,...}`` with keys
    sorted — the same rendering `stats()` uses for labeled samples."""
    if not labels:
        return name
    body = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{body}}}"


class SeriesRing:
    """Bounded ring of (seq, ts, value) samples for one history series."""

    __slots__ = ("_items", "_capacity", "_evicted_seq", "last_seq")

    def __init__(self, capacity: int = 1024):
        self._items: "deque" = _deque(maxlen=max(2, int(capacity)))
        self._capacity = max(2, int(capacity))
        self._evicted_seq = 0   # seq of the newest sample ever evicted
        self.last_seq = 0

    def append(self, seq: int, ts: float, value: float) -> None:
        if len(self._items) == self._capacity:
            self._evicted_seq = self._items[0][0]
        self._items.append((seq, float(ts), float(value)))
        self.last_seq = seq

    def read_since(self, since: int = 0) -> Tuple[List[Tuple[int, float, float]], bool]:
        """Samples with seq > since, oldest first, plus a truncated verdict:
        True iff the ring evicted samples the cursor never saw."""
        items = [s for s in self._items if s[0] > since]
        return items, since < self._evicted_seq

    def values_since_ts(self, since_ts: float) -> List[float]:
        """Values of samples with ts >= since_ts (the evaluator's window
        read)."""
        return [v for (_, ts, v) in self._items if ts >= since_ts]

    def __len__(self) -> int:
        return len(self._items)


class MetricsHistory:
    """Per-series `SeriesRing`s fed by `sample()` passes over a registry.

    Thread-safe: the sampler thread appends while HTTP scrape threads read.
    Series count is capped (`max_series`) as a label-cardinality backstop —
    once full, new series are silently not created (existing ones keep
    recording), and `dropped_series()` reports how many were refused.
    Series whose key starts with a *priority prefix* (the SLO engine
    registers its own ``slo.`` family plus every objective's metric) are
    exempt from the cap up to a 2× hard ceiling — a cardinality explosion
    elsewhere in the registry must not starve the alerting plane of the
    very series it alerts on."""

    def __init__(self, registry: Optional[MetricRegistry] = None,
                 capacity: int = 1024, max_series: int = 4096,
                 priority_prefixes: Optional[Iterable[str]] = None):
        self.registry = registry if registry is not None else _default
        self.capacity = max(2, int(capacity))
        self.max_series = max(1, int(max_series))
        self._priority: Tuple[str, ...] = tuple(priority_prefixes or ())
        self._series: Dict[str, SeriesRing] = {}
        self._lock = threading.Lock()
        self._seq = 0              # history-wide monotonic sample counter
        self._dropped = 0
        # per-series counter state: key -> (ts, cumulative total)
        self._last_counter: Dict[str, Tuple[float, float]] = {}
        # per-cell histogram state: key -> (count, bucket_counts tuple)
        self._last_hist: Dict[str, Tuple[int, Tuple[int, ...]]] = {}

    # -- sampling ------------------------------------------------------------
    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        """One snapshot pass: derive scalar samples from every registry
        metric and append them to the rings.  Returns {series: value} for
        this tick (the JSONL mirror's payload).  Never raises — a metric
        whose collection fails is skipped."""
        ts = time.time() if now is None else float(now)
        out: Dict[str, float] = {}
        for m in self.registry.metrics():
            try:
                if m.kind == "counter":
                    self._sample_counter(m, ts, out)
                elif m.kind == "gauge":
                    self._sample_gauge(m, out)
                elif m.kind == "histogram":
                    self._sample_histogram(m, out)
            except Exception:
                continue
        with self._lock:
            for key in sorted(out):
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= self.max_series and not (
                            self._is_priority(key)
                            and len(self._series) < 2 * self.max_series):
                        self._dropped += 1
                        continue
                    ring = self._series[key] = SeriesRing(self.capacity)
                self._seq += 1
                ring.append(self._seq, ts, out[key])
        return out

    def set_priority_prefixes(self, prefixes: Iterable[str]) -> None:
        """Replace the cap-exempt prefix set (the SLO engine calls this
        whenever its objective set changes)."""
        with self._lock:
            self._priority = tuple(dict.fromkeys(prefixes))

    def _is_priority(self, key: str) -> bool:
        return any(key.startswith(p) for p in self._priority)

    def _sample_counter(self, m: Metric, ts: float,
                        out: Dict[str, float]) -> None:
        agg, any_rate = 0.0, False
        for labels, total in m.samples():
            key = series_key(m.name, labels) + ":rate"
            last = self._last_counter.get(key)
            self._last_counter[key] = (ts, float(total))
            if last is None:
                continue
            dt = ts - last[0]
            delta = float(total) - last[1]
            if dt <= 0 or delta < 0:   # same tick, or counter reset
                continue
            rate = delta / dt
            out[key] = rate
            agg += rate
            any_rate = True
        if m.labelnames and any_rate:
            out[m.name + ":rate"] = agg

    def _sample_gauge(self, m: Metric, out: Dict[str, float]) -> None:
        for labels, value in m.samples():
            v = float(value)
            if math.isfinite(v):
                out[series_key(m.name, labels)] = v

    def _sample_histogram(self, m: Histogram, out: Dict[str, float]) -> None:
        for labels, stat in m.samples():
            base = series_key(m.name, labels)
            # stat["buckets"] is cumulative (prometheus-style le counts);
            # de-cumulate to per-bucket counts before differencing ticks
            cums = [int(stat["buckets"][_fmt_le(b)]) for b in m.buckets]
            counts = tuple(c - p for c, p in zip(cums, [0] + cums[:-1]))
            last = self._last_hist.get(base)
            self._last_hist[base] = (int(stat["count"]), counts)
            if last is None:
                continue
            deltas = [c - p for c, p in zip(counts, last[1])]
            dcount = int(stat["count"]) - last[0]
            if dcount <= 0 or any(d < 0 for d in deltas):
                continue   # no new observations, or the cell was reset
            hi_cap = float(stat["max"])
            out[base + ":p50"] = _delta_percentile(m.buckets, deltas, 50.0,
                                                   hi_cap)
            out[base + ":p99"] = _delta_percentile(m.buckets, deltas, 99.0,
                                                   hi_cap)

    # -- reads ---------------------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def dropped_series(self) -> int:
        with self._lock:
            return self._dropped

    def read_since(self, series: str, since: int = 0,
                   max_points: int = 0) -> Dict[str, Any]:
        """{"last_seq", "truncated", "samples": [[seq, ts, value], ...]} for
        one series (samples with seq > since, oldest first).  `max_points`
        > 0 thins the reply by even-stride downsampling that always keeps
        the newest sample; `truncated` keeps the ring-eviction meaning and
        is never set by thinning."""
        with self._lock:
            ring = self._series.get(series)
            if ring is None:
                return {"last_seq": 0, "truncated": False, "samples": []}
            items, truncated = ring.read_since(since)
            last = ring.last_seq
        if max_points and len(items) > max_points:
            stride = len(items) / float(max_points)
            picked = [items[min(len(items) - 1, int(i * stride))]
                      for i in range(max_points)]
            picked[-1] = items[-1]
            items = picked
        return {"last_seq": last, "truncated": truncated,
                "samples": [[s, ts, v] for (s, ts, v) in items]}

    def window_values(self, series: str, since_ts: float) -> List[float]:
        """Values recorded at ts >= since_ts for one series (the burn-rate
        evaluator's window read)."""
        with self._lock:
            ring = self._series.get(series)
            return ring.values_since_ts(since_ts) if ring else []

    def match_series(self, metric: str, suffix: str = "") -> List[str]:
        """Series for one metric family: the bare ``metric + suffix`` key
        plus every labeled ``metric{...}`` cell with that suffix."""
        prefix = metric + "{"
        with self._lock:
            return sorted(
                k for k in self._series
                if (k == metric + suffix
                    or (k.startswith(prefix) and k.endswith(suffix)
                        and (suffix or "}" == k[-1]))))

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._last_counter.clear()
            self._last_hist.clear()
            self._dropped = 0


def _delta_percentile(bounds: Sequence[float], deltas: Sequence[int],
                      q: float, hi_cap: float) -> float:
    """Percentile estimate over one inter-tick bucket-count delta — the
    interpolation of `Histogram._cell_percentile` applied to an increment
    instead of a cumulative cell.  `hi_cap` bounds the open +Inf bucket
    (the cell's lifetime max: the best honest upper bound available once
    per-interval extrema are gone)."""
    total = sum(deltas)
    if total <= 0:
        return math.nan
    rank = (q / 100.0) * total
    cum, lo = 0, 0.0
    for bound, n in zip(bounds, deltas):
        prev = cum
        cum += n
        if cum >= rank and n:
            hi = hi_cap if math.isinf(bound) else float(bound)
            if hi < lo:
                hi = lo
            return lo + (hi - lo) * ((rank - prev) / n)
        if not math.isinf(bound):
            lo = float(bound)
    return hi_cap


# ---------------------------------------------------------------------------
# Native StatRegistry compat shim (ref platform/monitor.h).
# ---------------------------------------------------------------------------
stat_add = _native.stat_add
stat_set = _native.stat_set
stat_get = _native.stat_get
stat_reset = _native.stat_reset


def stats() -> Dict[str, int]:
    """Flat int snapshot: native StatRegistry gauges merged with the default
    registry's counters and gauges (labeled samples render as
    ``name{k=v,...}``).  Always a fresh dict — PS-server/worker threads keep
    mutating the live stores while the caller iterates this copy."""
    out = dict(_native.stat_list())
    for m in _default.metrics():
        if m.kind not in ("counter", "gauge"):
            continue
        for labels, value in m.samples():
            if not math.isfinite(value):
                continue  # e.g. a percentile function gauge over an
                #           empty histogram samples nan — no int form
            if labels:
                body = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
                key = f"{m.name}{{{body}}}"
            else:
                key = m.name
            out[key] = int(value)
    return out
