"""Training goodput watchdog: step-time anomalies, loss health, and
wall-clock attribution — all computed in-process off the registries the
runtime already feeds.

Reference parity: the fleet elastic manager pairs its membership watchdog
with a *training* watchdog (hung-step and loss-NaN detection feeding the
relaunch decision); profiler folklore calls the productive fraction of
wall clock "goodput".  Here the same three signals come from instruments
earlier PRs installed, so the watchdog needs no hooks of its own:

* **step-time anomalies** — rolling median + MAD over the last ``window``
  step durations; a step beyond ``median + mad_threshold * 1.4826 * MAD``
  is flight-recorded ``watchdog_step_anomaly`` and counted in
  ``watchdog.anomalies{kind="step_time"}``.  Median/MAD (not mean/stddev)
  so the detector survives the very outliers it exists to catch.
* **loss health** — a NaN/Inf loss flight-records ``watchdog_nan_loss``
  and, when the ``watchdog_checkpoint_on_anomaly`` flag is set and a
  ``checkpoint_fn`` is wired, saves a pre-emptive elastic checkpoint
  *before* the divergence pollutes further optimizer state; a finite loss
  more than ``loss_spike_factor``× the rolling median is recorded
  ``watchdog_loss_spike``.
* **goodput** — every observed step also drains the flight-recorder ring
  through an ``events_since`` cursor and buckets attributed wall time:
  ``executor::trace_compile`` span ends → compile, ``elastic_restore`` /
  ``elastic_checkpoint`` events → restore/checkpoint, eviction markers →
  eviction; productive time is the summed step durations and everything
  left is idle (input pipeline, host sync, scheduling).  Published as the
  ``train.goodput_pct`` gauge plus ``watchdog.time_ms{category}``.
* **cross-rank stragglers** — :meth:`straggler_report` joins per-rank
  ``step``/``ts`` from the elastic heartbeat dir (the same files
  membership liveness reads), so one scrape of any rank's ``/healthz``
  names the rank holding the collective back.

Detection NEVER raises into the train loop: every observe path is wrapped,
a broken share or torn heartbeat degrades to "no report".  ``Model.fit``
attaches :class:`WatchdogCallback` automatically when the ``watchdog``
flag is on; the callback also registers the watchdog as the telemetry
plane's ``"watchdog"`` health provider so ``/healthz`` flips to 503 while
the job is diverging.
"""
from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from ..core import flags as _flags
from . import monitor as _monitor
from . import trace as _trace

__all__ = ["Watchdog", "WatchdogCallback", "rolling_median_mad"]

_MAD_SCALE = 1.4826  # MAD → stddev-equivalent under normality

_m_anomalies = _monitor.counter(
    "watchdog.anomalies", "Anomalies flagged by the training watchdog, by "
    "kind (step_time | nan_loss | loss_spike).", labelnames=("kind",))
_m_checkpoints = _monitor.counter(
    "watchdog.checkpoints", "Pre-emptive elastic checkpoints the watchdog "
    "saved on loss anomalies (watchdog_checkpoint_on_anomaly flag).")
_m_time = _monitor.counter(
    "watchdog.time_ms", "Attributed wall time, by category (productive | "
    "compile | restore | checkpoint | idle).", labelnames=("category",))
_m_goodput = _monitor.gauge(
    "train.goodput_pct", "Productive step time as a percentage of wall "
    "clock since the watchdog started — compile, checkpoint/restore and "
    "idle time are the non-goodput remainder.")


def rolling_median_mad(values) -> tuple:
    """(median, MAD) of a sequence — the robust location/scale pair the
    step-time detector thresholds against."""
    xs = sorted(values)
    if not xs:
        return (math.nan, math.nan)
    mid = len(xs) // 2
    med = xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])
    dev = sorted(abs(x - med) for x in xs)
    mad = dev[mid] if len(dev) % 2 else 0.5 * (dev[mid - 1] + dev[mid])
    return (med, mad)


class Watchdog:
    """In-process goodput watchdog.  Feed it one ``observe_step`` per train
    step; read ``report()`` (also served on ``/healthz``) any time.

    ``checkpoint_fn(reason: str) -> Any`` is invoked at most
    ``max_anomaly_checkpoints`` times, and only while the
    ``watchdog_checkpoint_on_anomaly`` flag is set — ``Model.fit`` wires a
    closure over the live fit state when it attaches the callback."""

    def __init__(self, window: int = 32, mad_threshold: float = 5.0,
                 min_samples: int = 8, loss_spike_factor: float = 10.0,
                 checkpoint_fn: Optional[Callable[[str], Any]] = None,
                 heartbeat_dir: Optional[str] = None,
                 straggler_factor: float = 2.0, straggler_min_lag: int = 5,
                 max_anomaly_checkpoints: int = 1):
        self.window = int(window)
        self.mad_threshold = float(mad_threshold)
        self.min_samples = max(3, int(min_samples))
        self.loss_spike_factor = float(loss_spike_factor)
        self.checkpoint_fn = checkpoint_fn
        self.heartbeat_dir = heartbeat_dir
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_lag = int(straggler_min_lag)
        self.max_anomaly_checkpoints = int(max_anomaly_checkpoints)
        self._durs: deque = deque(maxlen=self.window)
        self._losses: deque = deque(maxlen=self.window)
        self._lock = threading.Lock()
        self._t_start = time.time()
        self._cursor = _trace.flight_recorder().last_seq
        self._time_ms: Dict[str, float] = {
            "productive": 0.0, "compile": 0.0, "restore": 0.0,
            "checkpoint": 0.0, "idle": 0.0}
        self._counts = {"step_time": 0, "nan_loss": 0, "loss_spike": 0,
                        "ledger_drift": 0, "slo_alert": 0}
        self._flushed: Dict[str, float] = {}  # time_ms already exported
        self._ckpts_taken = 0
        self._steps = 0
        self._last_anomaly: Optional[Dict[str, Any]] = None

    # -- detection -----------------------------------------------------------
    def observe_step(self, step: int, dur_ms: float,
                     loss: Optional[float] = None) -> List[str]:
        """Record one train step; returns the anomaly kinds flagged (empty
        for a healthy step).  Never raises — detection failures degrade to
        an unflagged step, not a dead train loop."""
        try:
            return self._observe(int(step), float(dur_ms), loss)
        except Exception:
            return []

    def _observe(self, step: int, dur_ms: float,
                 loss: Optional[float]) -> List[str]:
        flagged: List[str] = []
        with self._lock:
            self._steps += 1
            self._time_ms["productive"] += dur_ms
            # threshold against the PRIOR window — the anomalous step must
            # not dilute the statistics that judge it
            if len(self._durs) >= self.min_samples:
                med, mad = rolling_median_mad(self._durs)
                limit = med + self.mad_threshold * _MAD_SCALE * max(
                    mad, 1e-3 * max(med, 1e-9))
                if dur_ms > limit:
                    flagged.append("step_time")
                    self._note(step, "step_time", dur_ms=round(dur_ms, 3),
                               median_ms=round(med, 3),
                               limit_ms=round(limit, 3))
            self._durs.append(dur_ms)
            if loss is not None:
                loss = float(loss)
                if not math.isfinite(loss):
                    flagged.append("nan_loss")
                    self._note(step, "nan_loss", loss=repr(loss))
                else:
                    prior = [l for l in self._losses if l > 0]
                    if len(prior) >= self.min_samples:
                        med, _ = rolling_median_mad(prior)
                        if loss > self.loss_spike_factor * med:
                            flagged.append("loss_spike")
                            self._note(step, "loss_spike",
                                       loss=round(loss, 6),
                                       median=round(med, 6))
                    self._losses.append(loss)
            self._drain_flight_locked()
            self._publish_locked()
        if ("nan_loss" in flagged or "loss_spike" in flagged):
            self._maybe_checkpoint(step, flagged)
        return flagged

    def _note(self, step: int, kind: str, **fields) -> None:
        self._counts[kind] += 1
        _m_anomalies.inc(kind=kind)
        self._last_anomaly = {"step": step, "kind": kind, **fields}
        _trace.flight_recorder().record(
            f"watchdog_{'step_anomaly' if kind == 'step_time' else kind}",
            name=f"step{step}", step=step, **fields)

    def _maybe_checkpoint(self, step: int, flagged: List[str]) -> None:
        if (self.checkpoint_fn is None
                or not _flags.get_flag("watchdog_checkpoint_on_anomaly")
                or self._ckpts_taken >= self.max_anomaly_checkpoints):
            return
        self._ckpts_taken += 1
        reason = ",".join(flagged)
        try:
            self.checkpoint_fn(reason)
        except Exception as e:
            _trace.flight_recorder().record(
                "watchdog_checkpoint_failed", name=reason, step=step,
                error=repr(e))
            return
        _m_checkpoints.inc()
        _trace.flight_recorder().record(
            "watchdog_checkpoint", name=reason, step=step, reason=reason)

    # -- goodput -------------------------------------------------------------
    _SPAN_CATEGORIES = {"executor::trace_compile": "compile"}
    _EVENT_CATEGORIES = {"elastic_restore": "restore",
                         "elastic_checkpoint": "checkpoint"}

    def _drain_flight_locked(self) -> None:
        fr = _trace.flight_recorder()
        events = fr.events_since(self._cursor)
        if events:
            self._cursor = max(e.get("seq", self._cursor) for e in events)
        for e in events:
            cat = None
            if e.get("kind") == "span_end":
                cat = self._SPAN_CATEGORIES.get(e.get("name", ""))
            else:
                cat = self._EVENT_CATEGORIES.get(e.get("kind", ""))
            if cat is not None:
                self._time_ms[cat] += float(e.get("dur_ms", 0.0) or 0.0)
            if e.get("kind") == "ledger_drift":
                # a cost model left its calibration band (utils/ledger.py):
                # counted as an anomaly so /healthz and watchdog.anomalies
                # surface estimator drift, but advisory — never unhealthy
                self._counts["ledger_drift"] += 1
                _m_anomalies.inc(kind="ledger_drift")
                self._last_anomaly = {
                    "kind": "ledger_drift",
                    "model": e.get("model", ""),
                    "drift": e.get("drift"),
                    "band": e.get("band"),
                    "program": e.get("program", ""),
                }
            if e.get("kind") == "slo_alert" and e.get("to") == "firing":
                # an SLO alert started firing (utils/slo.py): counted into
                # the watchdog's anomaly report; advisory here — the SLO
                # engine's own health provider is what flips /healthz on
                # page severity
                self._counts["slo_alert"] += 1
                _m_anomalies.inc(kind="slo_alert")
                self._last_anomaly = {
                    "kind": "slo_alert",
                    "slo": e.get("slo", ""),
                    "severity": e.get("severity", ""),
                    "burn_short": e.get("burn_short"),
                    "burn_long": e.get("burn_long"),
                }

    def _publish_locked(self) -> None:
        wall_ms = max((time.time() - self._t_start) * 1000.0, 1e-9)
        attributed = sum(v for k, v in self._time_ms.items() if k != "idle")
        self._time_ms["idle"] = max(wall_ms - attributed, 0.0)
        goodput = 100.0 * min(self._time_ms["productive"] / wall_ms, 1.0)
        _m_goodput.set(goodput)
        for cat, ms in self._time_ms.items():
            delta = ms - self._flushed.get(cat, 0.0)
            if delta > 0:
                _m_time.inc(delta, category=cat)
                self._flushed[cat] = ms

    def goodput_pct(self) -> float:
        with self._lock:
            wall_ms = max((time.time() - self._t_start) * 1000.0, 1e-9)
            return 100.0 * min(self._time_ms["productive"] / wall_ms, 1.0)

    # -- cross-rank attribution ----------------------------------------------
    def straggler_report(self, directory: Optional[str] = None,
                         now: Optional[float] = None) -> Dict[str, Any]:
        """Join per-rank ``step``/``ts`` heartbeats from the elastic
        membership dir: the front-runner step, each rank's lag, and the
        ranks whose lag exceeds ``straggler_factor``× the *other* ranks'
        median lag (leave-one-out, so a lone straggler cannot inflate its
        own baseline; absolute floor ``straggler_min_lag`` steps) — the
        collective's critical path, readable from any one rank."""
        from ..elastic import membership as _membership

        directory = directory or self.heartbeat_dir
        if not directory:
            return {"ranks": {}, "stragglers": []}
        hbs = _membership.read_heartbeats(directory)
        if not hbs:
            return {"ranks": {}, "stragglers": []}
        now = time.time() if now is None else now
        steps = {r: int(hb.get("step", 0)) for r, hb in hbs.items()}
        front = max(steps.values())
        lags = {r: front - s for r, s in steps.items()}
        stragglers = []
        for r, lag in lags.items():
            others = [l for o, l in lags.items() if o != r]
            if not others:
                continue
            med_other, _ = rolling_median_mad(others)
            if lag > max(self.straggler_min_lag,
                         self.straggler_factor * med_other):
                stragglers.append(r)
        stragglers.sort()
        for r in stragglers:
            _trace.flight_recorder().record(
                "watchdog_straggler", name=f"rank{r}", worker=r,
                step=steps[r], front=front, lag=lags[r])
        return {
            "front_step": front,
            "ranks": {str(r): {"step": steps[r], "lag": lags[r],
                               "hb_age_s": round(
                                   now - float(hbs[r].get("ts", 0.0)), 3)}
                      for r in sorted(hbs)},
            "stragglers": stragglers,
        }

    # -- reporting (telemetry /healthz section) ------------------------------
    def report(self) -> Dict[str, Any]:
        with self._lock:
            doc: Dict[str, Any] = {
                "healthy": self._counts["nan_loss"] == 0,
                "steps": self._steps,
                "goodput_pct": round(
                    100.0 * min(self._time_ms["productive"] / max(
                        (time.time() - self._t_start) * 1000.0, 1e-9), 1.0),
                    2),
                "time_ms": {k: round(v, 1)
                            for k, v in self._time_ms.items()},
                "anomalies": dict(self._counts),
            }
            if self._last_anomaly is not None:
                doc["last_anomaly"] = dict(self._last_anomaly)
        if self.heartbeat_dir:
            try:
                doc["stragglers"] = self.straggler_report()
            except Exception:
                pass
        return doc


class WatchdogCallback:
    """hapi Callback wrapping a :class:`Watchdog` (duck-typed like
    ElasticCheckpoint: CallbackList dispatches by attribute, so not
    inheriting avoids an import cycle).  Times each train batch, reads the
    lazy ``loss`` log (one device sync per step — the price of loss
    monitoring), and registers the watchdog on the telemetry plane.
    ``Model.fit`` attaches one automatically when the ``watchdog`` flag is
    set."""

    def __init__(self, watchdog: Optional[Watchdog] = None, **kwargs):
        self.model = None
        self.params: Dict[str, Any] = {}
        self.watchdog = watchdog or Watchdog(**kwargs)
        self._t0: Optional[float] = None
        self._gstep = 0
        from . import telemetry as _telemetry
        _telemetry.register_health_provider("watchdog",
                                            self.watchdog.report)

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is None:
            return
        dur_ms = (time.perf_counter() - self._t0) * 1000.0
        self._t0 = None
        loss = None
        if logs is not None:
            try:
                loss = logs.get("loss")  # forces the lazy thunk
            except Exception:
                loss = None
        self._gstep += 1
        self.watchdog.observe_step(self._gstep, dur_ms, loss=loss)
