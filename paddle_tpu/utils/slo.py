"""SLO engine: declarative objectives + multi-window burn-rate alerting.

The telemetry plane exposes live signals and the calibration ledger joins
predictions with measurements, but every scrape is a point-in-time
snapshot — nothing retains history, and nothing turns "p99 is bad" into a
*decision*.  The reference's platform/monitor.h StatValue plane existed to
feed exactly such threshold monitors; this module rebuilds that loop the
SRE way:

* **History** — a background self-sampler (``slo_sample_secs`` flag,
  default 5s) snapshots the metric registry into the bounded per-series
  rings of :class:`~paddle_tpu.utils.monitor.MetricsHistory` (counters as
  rates, gauges as values, histograms as inter-tick p50/p99), served at
  ``/history`` and optionally mirrored to per-rank JSONL
  (``history_dir`` flag / ``PDTPU_HISTORY_DIR``).
* **Objectives** — declarative :class:`SLO` records
  ``(name, metric, op, threshold, objective_pct, windows)`` registered in
  code or loaded from a TOML/JSON file (``slo_objectives`` flag;
  ``python -m tools.slocheck`` validates one against the metric
  inventory).  ``op`` is the *violation* comparator: a sample for which
  ``value <op> threshold`` holds is a bad sample.
* **Burn rates** — per evaluation tick, each objective's bad-sample
  fraction over every configured window is divided by the error budget
  ``(100 - objective_pct) / 100``; a burn rate of 1.0 consumes the budget
  exactly at the sustainable pace, 14.4 consumes a 30-day budget in ~2
  days (the classic page threshold).
* **Multi-window alerting** (Google SRE workbook ch.5): an alert
  condition requires the burn threshold to be exceeded on BOTH a short
  and a long window — the long window proves the burn is sustained (no
  paging on a blip), the short window makes the alert *resolve* quickly
  once the system recovers (bad samples age out of the short window
  first).  Each (slo, severity) pair runs a pending → firing → resolved
  state machine; every transition is flight-recorded (``slo_alert``
  events — the watchdog counts firings into its anomaly report) and
  exported as ``slo.alerts_firing{slo,severity}`` /
  ``slo.burn_rate{slo,window}``.  Firing page-severity alerts flip
  ``/healthz`` to 503 via the standard health-provider hook.

Observation-only, same contract as the calibration ledger: the engine
reads metrics and never touches the compile or dispatch path — zero
steady-state retraces and warm persistent-cache starts hold with the
``slo`` flag on (pinned in tests/test_slo.py).  Every hook is guarded:
a broken objective degrades to a skipped evaluation, never a failed run.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..core import flags as _flags
from . import monitor as _monitor
from . import trace as _trace

__all__ = [
    "HISTORY_DIR_ENV", "DEFAULT_WINDOWS", "VALID_OPS", "VALID_SEVERITIES",
    "Window", "SLO", "SLOEngine", "default_objectives", "load_objectives",
    "parse_objectives", "engine", "get_engine", "history", "start", "stop",
    "reset", "start_from_env",
]

HISTORY_DIR_ENV = "PDTPU_HISTORY_DIR"

VALID_OPS = (">", ">=", "<", "<=")
VALID_SEVERITIES = ("page", "ticket", "warn")
VALID_SIGNALS = ("value", "rate", "p50", "p99")

_m_burn = _monitor.gauge(
    "slo.burn_rate", "Latest error-budget burn rate per objective and "
    "evaluation window (1.0 = consuming budget exactly at the sustainable "
    "pace).", labelnames=("slo", "window"))
_m_firing = _monitor.gauge(
    "slo.alerts_firing", "1 while the (slo, severity) alert is firing, "
    "else 0.", labelnames=("slo", "severity"))
_m_evals = _monitor.counter(
    "slo.evaluations", "SLO evaluation ticks run by the engine.")


class Window:
    """One fast/slow burn-rate window pair with its alert severity.

    ``short_secs``/``long_secs`` are the lookback windows (seconds);
    ``burn`` is the burn-rate threshold BOTH windows must exceed for the
    alert condition to hold."""

    __slots__ = ("short_secs", "long_secs", "burn", "severity")

    def __init__(self, short_secs: float, long_secs: float, burn: float,
                 severity: str = "page"):
        self.short_secs = float(short_secs)
        self.long_secs = float(long_secs)
        self.burn = float(burn)
        self.severity = str(severity)
        if self.short_secs <= 0 or self.long_secs <= 0:
            raise ValueError("window seconds must be > 0")
        if self.short_secs >= self.long_secs:
            raise ValueError(
                f"short window ({self.short_secs}s) must be shorter than "
                f"the long window ({self.long_secs}s)")
        if self.burn <= 0:
            raise ValueError("burn threshold must be > 0")
        if self.severity not in VALID_SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {VALID_SEVERITIES}")

    def to_json(self) -> Dict[str, Any]:
        return {"short_secs": self.short_secs, "long_secs": self.long_secs,
                "burn": self.burn, "severity": self.severity}

    def __repr__(self):
        return (f"Window({self.short_secs:g}s/{self.long_secs:g}s, "
                f"burn>{self.burn:g}, {self.severity})")


# The SRE-workbook standard pairs: 5m+1h pages, 30m+6h tickets.  Burn
# thresholds assume a ~30-day budget (14.4 = budget gone in 2 days).
DEFAULT_WINDOWS = (Window(300.0, 3600.0, 14.4, "page"),
                   Window(1800.0, 21600.0, 6.0, "ticket"))


class SLO:
    """One declarative objective over a history series.

    ``metric`` names a registry metric family; ``signal`` picks which
    derived history series to judge: ``value`` (gauge samples), ``rate``
    (counter delta/dt), ``p50``/``p99`` (inter-tick histogram
    percentiles).  A sample is *bad* when ``value <op> threshold`` holds;
    ``objective_pct`` says what fraction of samples must be good, which
    fixes the error budget the burn rates are measured against.  Labeled
    families are judged per cell with the worst cell winning (one bad
    tenant pages like all-bad traffic would)."""

    def __init__(self, name: str, metric: str, op: str, threshold: float,
                 objective_pct: float = 99.0,
                 windows: Optional[Sequence[Window]] = None,
                 signal: str = "value", description: str = ""):
        self.name = str(name)
        self.metric = str(metric)
        self.op = str(op)
        self.threshold = float(threshold)
        self.objective_pct = float(objective_pct)
        self.windows = tuple(windows) if windows is not None \
            else DEFAULT_WINDOWS
        self.signal = str(signal)
        self.description = str(description)
        if not self.name:
            raise ValueError("SLO name must be non-empty")
        if not self.metric:
            raise ValueError(f"SLO {self.name!r}: metric must be non-empty")
        if self.op not in VALID_OPS:
            raise ValueError(
                f"SLO {self.name!r}: op {self.op!r} not in {VALID_OPS}")
        if not 0.0 < self.objective_pct < 100.0:
            raise ValueError(
                f"SLO {self.name!r}: objective_pct must be in (0, 100), "
                f"got {self.objective_pct}")
        if self.signal not in VALID_SIGNALS:
            raise ValueError(
                f"SLO {self.name!r}: signal {self.signal!r} not in "
                f"{VALID_SIGNALS}")
        if not self.windows:
            raise ValueError(f"SLO {self.name!r}: needs >= 1 window")
        for w in self.windows:
            if not isinstance(w, Window):
                raise TypeError(
                    f"SLO {self.name!r}: windows must be Window instances")

    @property
    def error_budget(self) -> float:
        """Allowed bad-sample fraction: (100 - objective_pct) / 100."""
        return (100.0 - self.objective_pct) / 100.0

    @property
    def series_suffix(self) -> str:
        """The history-series suffix the signal selects ('' for gauges)."""
        return "" if self.signal == "value" else ":" + self.signal

    def violates(self, value: float) -> bool:
        if self.op == ">":
            return value > self.threshold
        if self.op == ">=":
            return value >= self.threshold
        if self.op == "<":
            return value < self.threshold
        return value <= self.threshold

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "metric": self.metric, "op": self.op,
                "threshold": self.threshold,
                "objective_pct": self.objective_pct,
                "signal": self.signal, "description": self.description,
                "windows": [w.to_json() for w in self.windows]}

    def __repr__(self):
        return (f"SLO({self.name!r}: {self.metric}:{self.signal} "
                f"{self.op} {self.threshold:g} @ {self.objective_pct:g}%)")


def default_objectives() -> List[SLO]:
    """The shipped defaults: serving latency/shedding, training goodput,
    and cost-model calibration — one objective per operational surface the
    platform already instruments.  Fresh instances every call (engines
    mutate nothing, but tests clear/re-register freely)."""
    return [
        SLO("serve-ttft-p99", "serve.ttft_p99_ms", ">", 500.0,
            objective_pct=99.0, signal="value",
            description="End-to-end time-to-first-token p99 stays under "
                        "500ms."),
        SLO("serve-load-shed", "serve.load_shed", ">", 0.0,
            objective_pct=99.0, signal="rate",
            description="The admission controller is not shedding "
                        "requests."),
        SLO("train-goodput", "train.goodput_pct", "<", 50.0,
            objective_pct=95.0, signal="value",
            description="At least half of train wall time is productive "
                        "step time (watchdog accounting)."),
        SLO("ledger-drift", "ledger.drift_ratio", ">", 2.0,
            objective_pct=95.0, signal="value",
            description="Static cost-model predictions stay within 2x of "
                        "measurements (calibration ledger)."),
    ]


# ---------------------------------------------------------------------------
# Objective files: TOML (stdlib tomllib when available, else a minimal
# built-in subset parser) or JSON — both describe the same shape:
#
#   [[slo]]                          {"slo": [
#   name = "ttft"                      {"name": "ttft",
#   metric = "serve.ttft_p99_ms"        "metric": "serve.ttft_p99_ms",
#   op = ">"                            "op": ">",
#   threshold = 500.0                   "threshold": 500.0,
#   objective_pct = 99.0                "objective_pct": 99.0,
#   signal = "value"                    "signal": "value",
#   windows = [ { short_secs = 300, long_secs = 3600, burn = 14.4, severity = "page" } ]
#   ...                               ]}
# ---------------------------------------------------------------------------


def parse_objectives(doc: Dict[str, Any]) -> List[SLO]:
    """Build SLOs from a parsed objective document ({"slo": [table, ...]}).
    Raises ValueError on structural problems (slocheck surfaces these)."""
    tables = doc.get("slo")
    if not isinstance(tables, list) or not tables:
        raise ValueError("objective file needs a non-empty [[slo]] list "
                         "(JSON: a top-level \"slo\" array)")
    out: List[SLO] = []
    seen = set()
    for i, t in enumerate(tables):
        if not isinstance(t, dict):
            raise ValueError(f"slo[{i}] is not a table/object")
        unknown = set(t) - {"name", "metric", "op", "threshold",
                            "objective_pct", "windows", "signal",
                            "description"}
        if unknown:
            raise ValueError(f"slo[{i}]: unknown keys {sorted(unknown)}")
        windows = None
        if "windows" in t:
            windows = []
            for j, w in enumerate(t["windows"]):
                if not isinstance(w, dict):
                    raise ValueError(
                        f"slo[{i}].windows[{j}] is not a table/object")
                try:
                    windows.append(Window(
                        w.get("short_secs", 0), w.get("long_secs", 0),
                        w.get("burn", 0), w.get("severity", "page")))
                except (TypeError, ValueError) as e:
                    raise ValueError(f"slo[{i}].windows[{j}]: {e}")
        try:
            slo = SLO(t.get("name", ""), t.get("metric", ""),
                      t.get("op", ""), t.get("threshold", math.nan),
                      objective_pct=t.get("objective_pct", 99.0),
                      windows=windows, signal=t.get("signal", "value"),
                      description=t.get("description", ""))
        except (TypeError, ValueError) as e:
            raise ValueError(f"slo[{i}]: {e}")
        if not math.isfinite(slo.threshold):
            raise ValueError(f"slo[{i}] ({slo.name!r}): threshold must be "
                             "a finite number")
        if slo.name in seen:
            raise ValueError(f"duplicate SLO name {slo.name!r}")
        seen.add(slo.name)
        out.append(slo)
    return out


def load_objectives(path: str) -> List[SLO]:
    """Load an objective file: ``.json`` parses as JSON, anything else as
    TOML (stdlib ``tomllib`` when the interpreter ships it, else the
    built-in subset parser below)."""
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    if path.endswith(".json"):
        doc = json.loads(text)
    else:
        try:
            import tomllib  # Python >= 3.11
            doc = tomllib.loads(text)
        except ImportError:
            doc = _parse_toml_subset(text)
    return parse_objectives(doc)


def _parse_toml_value(s: str):
    """One scalar / inline value of the TOML subset."""
    s = s.strip()
    if (s.startswith('"') and s.endswith('"') and len(s) >= 2) or \
       (s.startswith("'") and s.endswith("'") and len(s) >= 2):
        return s[1:-1]
    if s == "true":
        return True
    if s == "false":
        return False
    if s.startswith("[") and s.endswith("]"):
        return [_parse_toml_value(p) for p in _split_toml_list(s[1:-1])]
    if s.startswith("{") and s.endswith("}"):
        table = {}
        for part in _split_toml_list(s[1:-1]):
            if "=" not in part:
                raise ValueError(f"bad inline-table entry {part!r}")
            k, _, v = part.partition("=")
            table[k.strip()] = _parse_toml_value(v)
        return table
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"unsupported TOML value {s!r}")


def _split_toml_list(body: str) -> List[str]:
    """Split a bracketed body on top-level commas (strings and nested
    brackets respected)."""
    parts, depth, quote, cur = [], 0, "", []
    for ch in body:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = ""
            continue
        if ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch in "[{":
            depth += 1
            cur.append(ch)
        elif ch in "]}":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append("".join(cur))
    return [p for p in (q.strip() for q in parts) if p]


def _parse_toml_subset(text: str) -> Dict[str, Any]:
    """Minimal TOML for objective files on interpreters without stdlib
    ``tomllib``: ``[[table]]`` array-of-tables headers, ``[table]``
    headers, and single-line ``key = value`` pairs with string / number /
    bool / inline-array / inline-table values.  Exactly the grammar the
    documented objective format uses; anything fancier should ship as
    JSON."""
    root: Dict[str, Any] = {}
    current: Dict[str, Any] = root
    for lineno, raw in enumerate(text.split("\n"), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            name = line[2:-2].strip()
            current = {}
            root.setdefault(name, []).append(current)
        elif line.startswith("[") and line.endswith("]"):
            name = line[1:-1].strip()
            current = root.setdefault(name, {})
        elif "=" in line:
            key, _, value = line.partition("=")
            try:
                current[key.strip()] = _parse_toml_value(value)
            except ValueError as e:
                raise ValueError(f"TOML line {lineno}: {e}")
        else:
            raise ValueError(f"TOML line {lineno}: unparseable {line!r}")
    return root


# ---------------------------------------------------------------------------
# The engine: sampler thread + evaluator + alert state machines.
# ---------------------------------------------------------------------------


class _AlertState:
    __slots__ = ("state", "since", "burn_short", "burn_long")

    def __init__(self):
        self.state = "ok"
        self.since = 0.0
        self.burn_short = 0.0
        self.burn_long = 0.0


class SLOEngine:
    """Owns the metrics history, the registered objectives, and the alert
    state machines; one daemon thread ("pdtpu-slo") ticks every
    ``slo_sample_secs``: sample the registry into the history, mirror the
    tick to the JSONL sink when configured, evaluate every objective.

    State machine per (slo, severity): ``ok`` → (condition) → ``pending``
    → (still holding after ``for_secs``; 0 by default, so the same tick)
    → ``firing`` → (condition clears) → ``resolved`` → (condition) →
    ``pending`` again.  ``pending`` that clears before confirmation goes
    back to ``ok``.  Every transition lands in the flight ring as an
    ``slo_alert`` event carrying the burn rates that caused it."""

    def __init__(self, registry: Optional[_monitor.MetricRegistry] = None,
                 capacity: int = 1024, for_secs: float = 0.0):
        self.history = _monitor.MetricsHistory(
            registry, capacity=capacity, priority_prefixes=("slo.",))
        self.for_secs = float(for_secs)
        self._objectives: Dict[str, SLO] = {}
        self._alerts: Dict[Tuple[str, str], _AlertState] = {}
        self._transitions: "deque" = deque(maxlen=256)
        self._transition_seq = 0
        self._lock = threading.RLock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._sample_override: Optional[float] = None
        self._sink_path: Optional[str] = None
        self._last_eval = 0.0

    # -- objectives -----------------------------------------------------------
    def register(self, slo: SLO) -> SLO:
        with self._lock:
            self._objectives[slo.name] = slo
            self._sync_priority()
        return slo

    def clear(self) -> None:
        """Drop every objective and alert state (tests / re-load)."""
        with self._lock:
            self._objectives.clear()
            self._alerts.clear()
            self._sync_priority()

    def _sync_priority(self) -> None:
        """Exempt the engine's own series and every objective's metric from
        the history's cardinality cap — an unrelated label explosion must
        not evict the series the alerts evaluate over.  Caller holds the
        lock."""
        self.history.set_priority_prefixes(
            ("slo.",) + tuple(s.metric for s in self._objectives.values()))

    def objectives(self) -> List[SLO]:
        with self._lock:
            return [self._objectives[n] for n in sorted(self._objectives)]

    def load_default_objectives(self) -> None:
        """Resolve objectives at start time: the ``slo_objectives`` file
        when set (a broken file is flight-recorded and the defaults stand
        in), else the shipped defaults.  No-op when objectives are already
        registered — code registration wins."""
        if self.objectives():
            return
        path = str(_flags.get_flag("slo_objectives") or "").strip()
        if path:
            try:
                for slo in load_objectives(path):
                    self.register(slo)
                return
            except (OSError, ValueError) as e:
                _trace.flight_recorder().record(
                    "slo_objectives_error", name=os.path.basename(path),
                    path=path, error=repr(e))
        for slo in default_objectives():
            self.register(slo)

    # -- evaluation -----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> None:
        """One evaluation pass over every objective against the history."""
        ts = time.time() if now is None else float(now)
        _m_evals.inc()
        with self._lock:
            objectives = list(self._objectives.values())
        for slo in objectives:
            try:
                self._evaluate_one(slo, ts)
            except Exception:
                continue
        self._last_eval = ts

    def _evaluate_one(self, slo: SLO, ts: float) -> None:
        series = self.history.match_series(slo.metric, slo.series_suffix)
        secs_needed = sorted({s for w in slo.windows
                              for s in (w.short_secs, w.long_secs)})
        budget = max(slo.error_budget, 1e-9)
        burn: Dict[float, float] = {}
        for secs in secs_needed:
            worst = 0.0
            for key in series:
                values = self.history.window_values(key, ts - secs)
                if not values:
                    continue
                bad = sum(1 for v in values if slo.violates(v))
                worst = max(worst, bad / len(values))
            burn[secs] = worst / budget
            _m_burn.set(burn[secs], slo=slo.name, window=f"{secs:g}s")
        for w in slo.windows:
            cond = (burn[w.short_secs] > w.burn
                    and burn[w.long_secs] > w.burn)
            self._step_alert(slo, w, cond,
                             burn[w.short_secs], burn[w.long_secs], ts)

    def _step_alert(self, slo: SLO, w: Window, cond: bool,
                    burn_short: float, burn_long: float, ts: float) -> None:
        key = (slo.name, w.severity)
        with self._lock:
            st = self._alerts.get(key)
            if st is None:
                st = self._alerts[key] = _AlertState()
            st.burn_short, st.burn_long = burn_short, burn_long
            prev = st.state
            if cond:
                if prev in ("ok", "resolved"):
                    self._transition(slo, w, st, "pending", ts)
                if st.state == "pending" and ts - st.since >= self.for_secs:
                    self._transition(slo, w, st, "firing", ts)
            else:
                if prev == "pending":
                    self._transition(slo, w, st, "ok", ts)
                elif prev == "firing":
                    self._transition(slo, w, st, "resolved", ts)
        _m_firing.set(1.0 if st.state == "firing" else 0.0,
                      slo=slo.name, severity=w.severity)

    def _transition(self, slo: SLO, w: Window, st: _AlertState,
                    state: str, ts: float) -> None:
        """(held under self._lock) Move one alert state machine and record
        the transition in both the engine ring and the flight ring."""
        prev, st.state, st.since = st.state, state, ts
        self._transition_seq += 1
        record = {
            "seq": self._transition_seq, "ts": ts, "slo": slo.name,
            "severity": w.severity, "from": prev, "to": state,
            "burn_short": round(st.burn_short, 4),
            "burn_long": round(st.burn_long, 4),
            "burn_threshold": w.burn,
            "windows": [w.short_secs, w.long_secs],
        }
        self._transitions.append(record)
        _trace.flight_recorder().record(
            "slo_alert", name=f"{slo.name}:{w.severity}", **{
                k: v for k, v in record.items() if k not in ("seq", "ts")})

    # -- reads ----------------------------------------------------------------
    def alerts_doc(self) -> Dict[str, Any]:
        """The ``/alerts`` document: every alert state, firing names, the
        recent transition chain, and the registered objectives."""
        with self._lock:
            alerts = []
            for (name, severity), st in sorted(self._alerts.items()):
                slo = self._objectives.get(name)
                alerts.append({
                    "slo": name, "severity": severity, "state": st.state,
                    "since": st.since,
                    "burn_short": round(st.burn_short, 4),
                    "burn_long": round(st.burn_long, 4),
                    "metric": slo.metric if slo else None,
                    "signal": slo.signal if slo else None,
                    "threshold": slo.threshold if slo else None,
                    "op": slo.op if slo else None,
                })
            transitions = list(self._transitions)
            objectives = [s.to_json()
                          for s in self._objectives.values()]
        return {
            "running": self.running,
            "evaluated_at": self._last_eval,
            "rank": _trace._rank(),
            "alerts": alerts,
            "firing": sorted(f"{a['slo']}:{a['severity']}" for a in alerts
                             if a["state"] == "firing"),
            "transitions": transitions,
            "objectives": objectives,
        }

    def health(self) -> Dict[str, Any]:
        """The /healthz section: unhealthy iff a page-severity alert is
        firing (ticket/warn severities degrade the doc, not the probe)."""
        with self._lock:
            firing = sorted(f"{n}:{sev}"
                            for (n, sev), st in self._alerts.items()
                            if st.state == "firing")
            pages = sorted(f"{n}:{sev}"
                           for (n, sev), st in self._alerts.items()
                           if st.state == "firing" and sev == "page")
            n_obj = len(self._objectives)
        return {"healthy": not pages, "firing": firing,
                "objectives": n_obj, "running": self.running,
                "evaluated_at": self._last_eval}

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- lifecycle ------------------------------------------------------------
    def _interval(self) -> float:
        if self._sample_override is not None:
            return self._sample_override
        try:
            return max(0.01, float(_flags.get_flag("slo_sample_secs")))
        except (TypeError, ValueError):
            return 5.0

    def tick(self, now: Optional[float] = None) -> Dict[str, float]:
        """One sampler+evaluator cycle (the thread body; callable directly
        from tests for deterministic time control)."""
        samples = self.history.sample(now)
        if samples and self._sink_path:
            self._mirror(samples, now)
        self.evaluate(now)
        return samples

    def _mirror(self, samples: Dict[str, float],
                now: Optional[float]) -> None:
        """One O_APPEND write per tick — atomic on POSIX local filesystems,
        same idiom as the ledger sink."""
        try:
            line = (json.dumps(
                {"ts": time.time() if now is None else float(now),
                 "rank": _trace._rank(), "samples": samples},
                sort_keys=True, default=repr) + "\n").encode("utf-8")
            fd = os.open(self._sink_path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError:
            pass  # a full/readonly disk must not take down the job

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.tick()
            except Exception:
                pass  # a broken tick must not kill the sampler
            self._stop_evt.wait(self._interval())

    def start(self, sample_secs: Optional[float] = None) -> "SLOEngine":
        """Resolve objectives + sink, register the health provider, start
        the sampler thread.  Idempotent while running."""
        if self.running:
            return self
        if sample_secs is not None:
            self._sample_override = max(0.01, float(sample_secs))
        self.load_default_objectives()
        self._sink_path = _history_sink_path()
        from . import telemetry as _telemetry
        _telemetry.register_health_provider("slo", self.health)
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._loop, name="pdtpu-slo",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        thread, self._thread = self._thread, None
        self._stop_evt.set()
        if thread is not None:
            thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Process-wide singleton + worker bootstrap.
# ---------------------------------------------------------------------------
_singleton: Optional[SLOEngine] = None
_singleton_lock = threading.Lock()


def _history_sink_path() -> Optional[str]:
    d = str(_flags.get_flag("history_dir") or "").strip() \
        or os.environ.get(HISTORY_DIR_ENV, "").strip()
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        return None
    return os.path.join(d, f"history.rank{_trace._rank()}.jsonl")


def engine() -> SLOEngine:
    """The process-wide engine (created on first use, NOT started — call
    :func:`start` or ``engine().start()``)."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = SLOEngine()
        return _singleton


def get_engine() -> Optional[SLOEngine]:
    """The singleton if it exists (``/alerts`` uses this so a scrape never
    implicitly creates an engine)."""
    return _singleton


def history() -> _monitor.MetricsHistory:
    """The singleton engine's history (``/history``'s data source)."""
    return engine().history


def start(sample_secs: Optional[float] = None) -> SLOEngine:
    """Start the process-wide engine (creating it if needed)."""
    return engine().start(sample_secs)


def stop() -> None:
    eng = get_engine()
    if eng is not None:
        eng.stop()


def reset() -> None:
    """Stop and drop the singleton (tests): the next engine() call starts
    a fresh history/cursor space and re-resolves the sink path."""
    global _singleton
    with _singleton_lock:
        eng, _singleton = _singleton, None
    if eng is not None:
        eng.stop()


def enabled() -> bool:
    """The engine auto-starts only when both the slo flag and the metrics
    plane are on — without metrics there is nothing to sample."""
    return bool(_flags.get_flag("slo")) and _monitor.enabled()


def start_from_env() -> Optional[SLOEngine]:
    """Worker bootstrap, called when the telemetry plane starts: bring the
    engine up when the ``slo`` flag is on.  Guarded — SLO evaluation must
    never kill a training job."""
    if not enabled():
        return None
    try:
        return start()
    except Exception:
        return None
