"""Auto-checkpoint: elastic epoch-level resume.

Reference parity: fluid/incubate/checkpoint/auto_checkpoint.py —
`AutoCheckpointChecker` (:71) reading the job environment,
`train_epoch_range` (the generator that wraps the epoch loop so a relaunched
job fast-forwards to the last saved epoch), and checkpoint_saver.py over the
fleet fs client (§5.3).  HDFS gives way to a local/NFS directory; the jax
state pytree is saved with utils.checkpoint (the reference's
save_persistables role).

Usage::

    acp = AutoCheckpoint("ckpt_dir", job_id="exp1")
    for epoch in acp.train_epoch_range(10):
        state = train_one_epoch(state)
        acp.save(epoch, state)          # atomic per-epoch snapshot
    # on restart, train_epoch_range resumes after the last saved epoch and
    # acp.restored_state holds the snapshot to continue from.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Iterator, Optional

from . import checkpoint as _ckpt

__all__ = ["AutoCheckpoint", "train_epoch_range"]

_ENV_JOB_ID = "PDTPU_JOB_ID"  # ref: the cloud job-id env the checker reads
_ENV_CKPT_DIR = "PDTPU_CHECKPOINT_DIR"


def _is_flat_array_dict(state: Any) -> bool:
    return isinstance(state, dict) and all(
        hasattr(v, "shape") and hasattr(v, "dtype") for v in state.values())


class AutoCheckpoint:
    """Epoch-granular checkpoint/resume manager."""

    def __init__(self, ckpt_dir: Optional[str] = None,
                 job_id: Optional[str] = None, keep_last: int = 2,
                 plan=None):
        self.ckpt_dir = ckpt_dir or os.environ.get(_ENV_CKPT_DIR)
        if not self.ckpt_dir:
            raise ValueError("pass ckpt_dir or set $" + _ENV_CKPT_DIR)
        self.job_id = job_id or os.environ.get(_ENV_JOB_ID, "default")
        self.keep_last = keep_last
        # with a ShardingPlan, flat dict states are written in the elastic
        # manifest format (elastic/checkpoint.py) so a relaunched job can
        # resume on a different mesh; other pytrees and plan=None keep the
        # legacy npz+tree layout, and load() reads either
        self.plan = plan
        self.root = os.path.join(self.ckpt_dir, self.job_id)
        os.makedirs(self.root, exist_ok=True)
        self.restored_state: Any = None
        self._restored_epoch = self._read_meta()

    # -- metadata -----------------------------------------------------------
    def _meta_path(self) -> str:
        return os.path.join(self.root, "meta.json")

    def _read_meta(self) -> int:
        try:
            with open(self._meta_path()) as f:
                return int(json.load(f)["last_epoch"])
        except (OSError, ValueError, KeyError):
            return -1

    def _write_meta(self, epoch: int) -> None:
        # write-then-rename: a crash mid-save never corrupts the pointer
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".meta")
        with os.fdopen(fd, "w") as f:
            json.dump({"last_epoch": epoch, "job_id": self.job_id}, f)
        os.replace(tmp, self._meta_path())

    # -- save/restore -------------------------------------------------------
    def _epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.root, f"epoch_{epoch}")

    def save(self, epoch: int, state: Any) -> None:
        """Atomic snapshot: state written to a temp dir, renamed into place,
        then the meta pointer advances — the order a crash can't corrupt."""
        tmp = self._epoch_dir(epoch) + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        if self.plan is not None and _is_flat_array_dict(state):
            from ..elastic import checkpoint as _eckpt

            _eckpt.write_state(os.path.join(tmp, "state"), state,
                               step=epoch, plan=self.plan)
        else:
            _ckpt.save(state, os.path.join(tmp, "state"))
        final = self._epoch_dir(epoch)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.replace(tmp, final)
        self._write_meta(epoch)
        self._gc(epoch)

    def _gc(self, newest: int) -> None:
        # saves are sequential, so at most the one dir that just fell out of
        # the keep window exists; stop at the first missing dir (O(1) per
        # save instead of scanning to epoch 0 — matters on NFS)
        for e in range(newest - self.keep_last, -1, -1):
            d = self._epoch_dir(e)
            if not os.path.exists(d):
                break
            shutil.rmtree(d)

    def load(self, epoch: int) -> Any:
        path = os.path.join(self._epoch_dir(epoch), "state")
        if self.plan is not None and os.path.isdir(path):
            from ..elastic import checkpoint as _eckpt

            if os.path.exists(os.path.join(path, _eckpt.MANIFEST_NAME)):
                state, _meta = _eckpt.read_state(path, plan=self.plan)
                return state
        return _ckpt.load(path)

    @property
    def last_epoch(self) -> int:
        return self._restored_epoch

    # -- the epoch range ----------------------------------------------------
    def train_epoch_range(self, max_epoch: int,
                          start: int = 0) -> Iterator[int]:
        """Yield epochs [start, max_epoch), fast-forwarding past epochs a
        previous incarnation of this job already saved (ref
        auto_checkpoint.py train_epoch_range)."""
        first = start
        if self._restored_epoch >= start:
            first = self._restored_epoch + 1
            try:
                self.restored_state = self.load(self._restored_epoch)
            except OSError as e:
                # fast-forwarding without the state would silently resume
                # later epochs from uninitialized weights — fail loudly
                raise RuntimeError(
                    f"meta.json points at epoch {self._restored_epoch} but "
                    f"its snapshot could not be loaded ({e}); remove "
                    f"{self.root} to restart from scratch") from e
        for epoch in range(first, max_epoch):
            yield epoch


def train_epoch_range(max_epoch: int, acp: AutoCheckpoint) -> Iterator[int]:
    """Free-function form of the reference API; takes the AutoCheckpoint the
    caller saves through (constructing one internally would leave the caller
    no handle for .save()/.restored_state, making resume impossible)."""
    yield from acp.train_epoch_range(max_epoch)
