"""Checkpoint save/load.

Reference parity: fluid/io.py save/load_persistables (:598), dygraph
save_dygraph/load_dygraph state-dict pickles, save_op/load_op tensor
serialization.  TPU-native: state dicts (arbitrary pytrees of arrays) are
written as .npz plus a structure pickle — host-side, no device involvement.

Writes are atomic (tmp file in the target directory + ``os.replace`` per
file; the .npz — the file ``load`` keys its existence check on — lands
last), so a crashed saver never leaves a load-able half checkpoint.
Sharded/resharding checkpoints live in elastic/checkpoint.py; ``load``
recognizes that manifest layout when handed one (a directory containing
``manifest.json``) and returns the gathered flat state dict, so callers
migrating formats keep a single load entry point.
"""
from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict

import jax
import numpy as np


def _flatten(tree) -> Dict[str, np.ndarray]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"arr_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    return arrays, treedef


def _atomic_write(path: str, writer) -> None:
    """Write via a tempfile in the destination directory + os.replace."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            writer(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save(state: Any, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    arrays, treedef = _flatten(state)
    npz_path = path + ".npz" if not path.endswith(".npz") else path
    # tree first, npz last: load() keys on the npz existing, so a crash in
    # between leaves nothing load() would accept
    _atomic_write(path + ".tree", lambda f: pickle.dump(treedef, f))
    _atomic_write(npz_path, lambda f: np.savez(f, **arrays))


def _manifest_dir(path: str) -> bool:
    from ..elastic import checkpoint as _eckpt

    return os.path.isdir(path) and os.path.exists(
        os.path.join(path, _eckpt.MANIFEST_NAME))


def load(path: str) -> Any:
    if _manifest_dir(path):
        from ..elastic import checkpoint as _eckpt

        state, _meta = _eckpt.read_state(path)
        return state
    npz_path = path + ".npz" if not path.endswith(".npz") else path
    if not os.path.exists(npz_path):
        raise FileNotFoundError(npz_path)
    data = np.load(npz_path, allow_pickle=False)
    with open(path + ".tree", "rb") as f:
        treedef = pickle.load(f)
    leaves = [data[f"arr_{i}"] for i in range(len(data.files))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_state_dict(state_dict: Dict[str, Any], path: str) -> None:
    save(state_dict, path)


def load_state_dict(path: str) -> Dict[str, Any]:
    return load(path)
