"""Checkpoint save/load.

Reference parity: fluid/io.py save/load_persistables (:598), dygraph
save_dygraph/load_dygraph state-dict pickles, save_op/load_op tensor
serialization.  TPU-native: state dicts (arbitrary pytrees of arrays) are
written as .npz plus a structure pickle — host-side, no device involvement;
async/sharded checkpointing (orbax-style) can layer on top later.
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Dict

import jax
import numpy as np


def _flatten(tree) -> Dict[str, np.ndarray]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"arr_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    return arrays, treedef


def save(state: Any, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    arrays, treedef = _flatten(state)
    np.savez(path + ".npz" if not path.endswith(".npz") else path, **arrays)
    with open(path + ".tree", "wb") as f:
        pickle.dump(treedef, f)


def load(path: str) -> Any:
    npz_path = path + ".npz" if not path.endswith(".npz") else path
    if not os.path.exists(npz_path):
        raise FileNotFoundError(npz_path)
    data = np.load(npz_path, allow_pickle=False)
    with open(path + ".tree", "rb") as f:
        treedef = pickle.load(f)
    leaves = [data[f"arr_{i}"] for i in range(len(data.files))]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_state_dict(state_dict: Dict[str, Any], path: str) -> None:
    save(state_dict, path)


def load_state_dict(path: str) -> Dict[str, Any]:
    return load(path)
