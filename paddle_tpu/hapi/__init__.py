"""hapi — high-level Model API (ref: python/paddle/hapi/model.py:788)."""
from . import callbacks
from .model import Model
