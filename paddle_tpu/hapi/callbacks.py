"""Training callbacks (ref: python/paddle/hapi/callbacks.py — Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler)."""
from __future__ import annotations

import time
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None, model=None,
                 params=None):
        self.callbacks = list(callbacks) if callbacks else []
        if params and params.get("verbose", 2) > 0:
            if not any(isinstance(c, ProgBarLogger) for c in self.callbacks):
                self.callbacks.insert(0, ProgBarLogger(
                    log_freq=params.get("log_freq", 10),
                    verbose=params.get("verbose", 2)))
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """ref: callbacks.py ProgBarLogger — per-epoch progress logging."""

    def __init__(self, log_freq=10, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose > 1 and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" for k, v in logs.items()
                               if isinstance(v, float))
            print(f"  step {step}{f'/{self.steps}' if self.steps else ''} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        if self.verbose:
            dur = time.time() - self._start
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in logs.items() if v is not None)
            print(f"  epoch {epoch + 1} done in {dur:.1f}s - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class EarlyStopping(Callback):
    """ref: callbacks.py EarlyStopping."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=False):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, value):
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor)
        if value is None:
            return
        if self._better(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping at epoch {epoch + 1}")


class MetricsLogger(Callback):
    """Publishes train-loop telemetry into the metrics registry
    (`utils.monitor`): `train.steps` / `train.epochs` counters, a
    `train.step_time_ms` histogram, and a `train.samples_per_sec` gauge
    computed from the `batch_size` fit parameter (or a `batch_size` entry
    in the step logs).  Collection obeys the `metrics` flag; pass a
    `MetricRegistry` to publish somewhere other than the process default."""

    def __init__(self, registry=None):
        super().__init__()
        from ..utils import monitor as _monitor

        reg = registry or _monitor.default_registry()
        self._steps = reg.counter(
            "train.steps", "Completed training steps (hapi Model.fit).")
        self._epochs = reg.counter(
            "train.epochs", "Completed training epochs (hapi Model.fit).")
        self._step_ms = reg.histogram(
            "train.step_time_ms", "Wall time per training step (ms).")
        self._sps = reg.gauge(
            "train.samples_per_sec", "Training throughput over the last "
            "step (needs batch_size in fit params or step logs).")
        self._t0 = None

    def on_train_begin(self, logs=None):
        self._t0 = None

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        now = time.perf_counter()
        if self._t0 is None:
            # no batch_begin seen (custom loop): chain end-to-end instead
            self._t0 = now
            return
        dt = now - self._t0
        self._t0 = now
        self._steps.inc()
        self._step_ms.observe(dt * 1000.0)
        batch = (logs or {}).get("batch_size") or self.params.get("batch_size")
        if batch and dt > 0:
            self._sps.set(float(batch) / dt)
        from ..utils import trace as _trace

        _trace.flight_recorder().record(
            "train_step", name=f"step{step}", dur_ms=dt * 1000.0)

    def on_epoch_end(self, epoch, logs=None):
        self._epochs.inc()


class LRSchedulerCallback(Callback):
    """Steps an LRScheduler once per epoch (ref: callbacks.py LRScheduler)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = self.model._optimizer
        from ..optimizer.lr import LRScheduler

        return opt._lr if opt and isinstance(opt._lr, LRScheduler) else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()
