"""High-level ``Model`` API — prepare / fit / evaluate / predict.

Reference parity: python/paddle/hapi/model.py:788 (``Model``; fit :1243,
evaluate :1443, predict :1539) with its Static/DynamicGraphAdapter split.
TPU-native design: there is exactly one adapter — ``prepare`` builds a jitted
functional train/eval step (params + optimizer state as explicit carries,
dropout keys threaded), so the whole step compiles to one XLA program.  That
replaces both reference adapters and is where the MXU actually gets fed.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..core import random as _random
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..nn.layer.base import Layer
from ..optimizer.optimizer import Optimizer
from . import callbacks as cb_mod


def _to_tuple(x):
    if x is None:
        return ()
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


class _LazyLogs(dict):
    """Step logs whose values materialize on first read.

    The jitted train step returns unmaterialized ``jax.Array`` scalars;
    forcing them to floats every batch is a device sync that serializes
    dispatch.  Values registered via :meth:`set_lazy` stay as pending thunks
    until a consumer (a callback, verbose logging, epoch summary) actually
    reads them — so ``fit(verbose=0)`` with no reading callbacks keeps the
    dispatch chain fully asynchronous."""

    def __init__(self, **eager):
        super().__init__(**eager)
        self._lazy = {}

    def set_lazy(self, key, thunk):
        super().pop(key, None)
        self._lazy[key] = thunk

    def _force(self, key):
        thunk = self._lazy.pop(key, None)
        if thunk is not None:
            super().__setitem__(key, thunk())

    def materialize(self) -> "_LazyLogs":
        for key in list(self._lazy):
            self._force(key)
        return self

    def __getitem__(self, key):
        self._force(key)
        return super().__getitem__(key)

    def get(self, key, default=None):
        self._force(key)
        return super().get(key, default)

    def __contains__(self, key):
        return key in self._lazy or super().__contains__(key)

    def __len__(self):
        return super().__len__() + len(self._lazy)

    def __iter__(self):
        self.materialize()
        return super().__iter__()

    def keys(self):
        self.materialize()
        return super().keys()

    def values(self):
        self.materialize()
        return super().values()

    def items(self):
        self.materialize()
        return super().items()

    def copy(self):
        return dict(self.materialize())


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        del inputs, labels  # static-graph InputSpec not needed under jit
        self.network = network
        self._optimizer: Optional[Optimizer] = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._eval_step = None
        self._pred_step = None
        self._opt_state = None
        self._fit_params = None  # live jit-path params, mid-epoch
        self.stop_training = False

    # -- setup ---------------------------------------------------------------
    def prepare(self, optimizer: Optional[Optimizer] = None, loss=None,
                metrics: Optional[Sequence[Metric]] = None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = list(metrics) if metrics else []
        self._amp = amp_configs or {}
        self._build_steps()

    def _build_steps(self):
        net = self.network
        loss_fn = self._loss
        opt = self._optimizer
        metrics = self._metrics

        def forward_loss(params, inputs, labels):
            outputs = autograd.functional_call(net, params, _to_tuple(inputs))
            outputs_t = _to_tuple(outputs)
            loss = loss_fn(*outputs_t, *_to_tuple(labels))
            metric_outs = tuple(m.compute(outputs_t[0], labels[0] if isinstance(
                labels, (list, tuple)) else labels) for m in metrics)
            return loss, (outputs_t, metric_outs)

        if opt is not None:
            def train_step(params, opt_state, rng, inputs, labels):
                def inner(p):
                    with _random.rng_scope(rng):
                        return forward_loss(p, inputs, labels)

                (loss, aux), grads = jax.value_and_grad(inner, has_aux=True)(params)
                params, opt_state = opt.update(grads, opt_state, params)
                return params, opt_state, loss, aux[1]

            self._train_step = jax.jit(train_step)

        def eval_step(params, inputs, labels):
            loss, (outputs, metric_outs) = forward_loss(params, inputs, labels)
            return loss, metric_outs

        self._eval_step = jax.jit(eval_step)

        def pred_step(params, inputs):
            return autograd.functional_call(net, params, _to_tuple(inputs))

        self._pred_step = jax.jit(pred_step)

    # -- data plumbing -------------------------------------------------------
    @staticmethod
    def _split_batch(batch):
        """(x, y) convention: last element is the label, rest are inputs
        (matches hapi's inputs/labels split)."""
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return tuple(batch[:-1]), batch[-1]
            return (batch[0],), None
        return (batch,), None

    def _loader(self, data, batch_size, shuffle, num_workers, drop_last=False):
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers, drop_last=drop_last)
        raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")

    # -- training loop -------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            prefetch_to_device=False):
        """``prefetch_to_device=True`` (or a device) overlaps host→device
        transfer of batch N+1 with compute of batch N via a DeviceFeeder
        thread (io/prefetch.py); step logs materialize lazily, so with
        ``verbose=0`` and no value-reading callbacks the whole epoch
        dispatches asynchronously."""
        assert self._optimizer is not None, "call prepare(optimizer, loss) first"
        from ..core import tape as _tape

        loader = self._loader(train_data, batch_size, shuffle, num_workers,
                              drop_last=drop_last)
        prefetch = prefetch_to_device and not getattr(
            loader, "prefetch_to_device", False)
        params = autograd.parameters_dict(self.network)
        if self._opt_state is None and not _tape.enabled():
            self._opt_state = self._optimizer.init(params)

        # periodic elastic checkpointing rides the callback list when the
        # elastic flags are set (fleet's ElasticConfig sets them)
        from ..core import flags as _flags

        if (int(_flags.get_flag("elastic_save_every")) > 0
                and _flags.get_flag("elastic_ckpt_dir")):
            from ..elastic.checkpoint import ElasticCheckpoint

            callbacks = list(callbacks) if callbacks else []
            if not any(isinstance(c, ElasticCheckpoint) for c in callbacks):
                callbacks.append(ElasticCheckpoint(
                    _flags.get_flag("elastic_ckpt_dir"),
                    save_every=int(_flags.get_flag("elastic_save_every")),
                    keep_last=int(_flags.get_flag("elastic_keep_last"))))
        # the goodput watchdog rides the callback list the same way when
        # the watchdog flag is on; with watchdog_checkpoint_on_anomaly +
        # elastic_ckpt_dir it also gets a checkpoint_fn over the live fit
        # state so a NaN/spiking loss saves a pre-divergence checkpoint
        if _flags.get_flag("watchdog"):
            from ..utils.watchdog import WatchdogCallback

            callbacks = list(callbacks) if callbacks else []
            if not any(isinstance(c, WatchdogCallback) for c in callbacks):
                wcb = WatchdogCallback(
                    heartbeat_dir=os.environ.get("PDTPU_ELASTIC_DIR"))
                ckpt_dir = _flags.get_flag("elastic_ckpt_dir")
                if (_flags.get_flag("watchdog_checkpoint_on_anomaly")
                        and ckpt_dir):
                    from ..elastic.checkpoint import (ElasticCheckpoint,
                                                      save_checkpoint)

                    # reuse ElasticCheckpoint's live-state flattening
                    # (fit's jit path keeps params in _fit_params mid-epoch)
                    saver = ElasticCheckpoint(ckpt_dir, save_every=0)
                    saver.set_model(self)

                    def _anomaly_ckpt(reason, _s=saver, _w=wcb):
                        return save_checkpoint(
                            str(ckpt_dir), _s._flat_state(), _w._gstep,
                            keep_last=int(
                                _flags.get_flag("elastic_keep_last")))

                    wcb.watchdog.checkpoint_fn = _anomaly_ckpt
                callbacks.append(wcb)
        cbs = cb_mod.CallbackList(callbacks, model=self,
                                  params={"epochs": epochs, "verbose": verbose,
                                          "steps": _safe_len(loader),
                                          "batch_size": batch_size,
                                          "log_freq": log_freq})
        cbs.on_train_begin()
        self.stop_training = False
        for epoch in range(epochs):
            cbs.on_epoch_begin(epoch)
            self.network.train()
            for m in self._metrics:
                m.reset()
            logs = {}
            from ..core import tape as _tape
            batches = loader
            if prefetch:
                from ..io.prefetch import device_prefetch

                batches = device_prefetch(
                    loader, device=None if prefetch_to_device is True
                    else prefetch_to_device)
            for step, batch in enumerate(batches):
                cbs.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                if _tape.enabled():
                    loss, metric_outs = self._tape_fit_step(inputs, labels)
                    params = autograd.parameters_dict(self.network)
                else:
                    rng = _random.next_key()
                    params, self._opt_state, loss, metric_outs = \
                        self._train_step(params, self._opt_state, rng, inputs,
                                         labels)
                    # the jit path carries params outside the network until
                    # epoch end; checkpoint callbacks need the live values
                    self._fit_params = params
                # lazy logs: float(loss) is a device sync — defer it until a
                # callback/verbose consumer actually reads the value so the
                # steady-state dispatch chain stays asynchronous
                logs = _LazyLogs(step=step)
                logs.set_lazy("loss", lambda l=loss: float(l))
                for m, mo in zip(self._metrics, metric_outs):
                    val = _metric_update(m, mo)
                    logs.set_lazy(
                        m.name(),
                        lambda v=val: (float(np.asarray(v).ravel()[0])
                                       if v is not None else None))
                cbs.on_train_batch_end(step, logs)
            autograd.load_parameters(self.network, params)
            epoch_logs = {"loss": logs.get("loss")}
            for m in self._metrics:
                epoch_logs[m.name()] = m.accumulate()
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                          verbose=0, num_workers=num_workers)
                epoch_logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbs.on_epoch_end(epoch, epoch_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if self.stop_training:
                break
        cbs.on_train_end()
        autograd.load_parameters(self.network, params)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        self.network.eval()
        for m in self._metrics:
            m.reset()
        params = autograd.parameters_dict(self.network)
        losses = []
        for batch in loader:
            inputs, labels = self._split_batch(batch)
            loss, metric_outs = self._eval_step(params, inputs, labels)
            # defer the scalar sync: batches keep dispatching while earlier
            # losses are still on device
            losses.append(loss)
            for m, mo in zip(self._metrics, metric_outs):
                _metric_update(m, mo)
        logs = {"loss": float(np.mean([np.asarray(l) for l in losses]))
                if losses else 0.0}
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        self.network.train()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=True,
                callbacks=None, verbose=1):
        loader = self._loader(test_data, batch_size, False, num_workers)
        self.network.eval()
        params = autograd.parameters_dict(self.network)
        outs = []
        for batch in loader:
            inputs, _ = self._split_batch(batch) if isinstance(batch, (tuple, list)) \
                else ((batch,), None)
            out = self._pred_step(params, inputs)
            # keep batch outputs on device until the loop ends — np.asarray
            # per batch is a sync that serializes dispatch
            outs.append(_to_tuple(out))
        self.network.train()
        n_outputs = len(outs[0]) if outs else 0
        if stack_outputs and outs:
            return [np.concatenate([np.asarray(b[i]) for b in outs], axis=0)
                    for i in range(n_outputs)]
        return [tuple(np.asarray(o) for o in b) for b in outs]

    def train_batch(self, inputs, labels=None):
        from ..core import tape as _tape

        if _tape.enabled():
            return self._train_batch_tape(inputs, labels)
        params = autograd.parameters_dict(self.network)
        if self._opt_state is None:
            self._opt_state = self._optimizer.init(params)
        rng = _random.next_key()
        params, self._opt_state, loss, _ = self._train_step(
            params, self._opt_state, rng, _to_tuple(inputs), labels)
        autograd.load_parameters(self.network, params)
        return float(loss)

    def _train_batch_tape(self, inputs, labels):
        """Eager tape path (ref DynamicGraphAdapter.train_batch,
        hapi/model.py:588: forward → loss.backward() → minimize →
        clear_gradients), used when dygraph.guard() is active."""
        loss, _ = self._tape_fit_step(inputs, labels)
        return float(loss)

    def _tape_fit_step(self, inputs, labels):
        opt = self._optimizer
        if opt._parameters is None:
            opt._parameters = self.network.parameters()
        outputs = _to_tuple(self.network(*_to_tuple(inputs)))
        loss = self._loss(*outputs, *_to_tuple(labels))
        loss.backward()
        opt.minimize(loss)
        self.network.clear_gradients()
        labels0 = labels[0] if isinstance(labels, (list, tuple)) else labels
        metric_outs = tuple(m.compute(outputs[0], labels0)
                            for m in self._metrics)
        return loss, metric_outs

    def eval_batch(self, inputs, labels=None):
        params = autograd.parameters_dict(self.network)
        loss, _ = self._eval_step(params, _to_tuple(inputs), labels)
        return float(loss)

    def predict_batch(self, inputs):
        params = autograd.parameters_dict(self.network)
        return np.asarray(self._pred_step(params, _to_tuple(inputs)))

    # -- persistence ---------------------------------------------------------
    def save(self, path, training=True):
        from ..utils import checkpoint

        checkpoint.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            # tape-mode fit updates the optimizer's own bound state
            # (optimizer._state); the jit path updates self._opt_state —
            # persist whichever actually trained
            opt_state = self._optimizer._state or self._opt_state
            if opt_state is not None:
                checkpoint.save({"opt": opt_state}, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..utils import checkpoint

        state = checkpoint.load(path + ".pdparams")
        self.network.set_state_dict(state)
        if not reset_optimizer:
            try:
                opt = checkpoint.load(path + ".pdopt")
                self._opt_state = opt["opt"]
                if self._optimizer is not None and self._optimizer._state:
                    self._optimizer._state = opt["opt"]
            except FileNotFoundError:
                pass

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        lines = [repr(self.network)]
        total = sum(int(np.prod(p.shape)) for p in self.network.parameters())
        lines.append(f"Total params: {total:,}")
        s = "\n".join(lines)
        print(s)
        return {"total_params": total}


def _safe_len(loader):
    try:
        return len(loader)
    except TypeError:
        return None


def _metric_update(metric, compute_out):
    """Metrics whose compute() passes (pred, label) through take two update
    args (Precision/Recall/Auc); Accuracy-style metrics take the single
    compute result (ref hapi unpacks compute outputs the same way)."""
    if isinstance(compute_out, tuple):
        return metric.update(*compute_out)
    return metric.update(compute_out)
