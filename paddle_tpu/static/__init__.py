"""paddle_tpu.static — the static-graph (Fluid-style) programming model.

Reference parity: the entire Fluid stack — ProgramDesc/Executor
(python/paddle/fluid/framework.py, executor.py; C++ executor.cc:180) and the
2.0 `paddle.static` namespace.  TPU-native: programs lower to single jitted
XLA computations instead of per-op kernel dispatch (see executor.py).

Minimum end-to-end slice (SURVEY.md §7 step 3): build MNIST with
static.layers, append_backward via an optimizer, train with Executor.run —
tests/test_static.py demonstrates exactly this.
"""
from . import layers, optimizer
from . import layers_tail  # noqa: F401 — fluid.layers DSL tail (attaches to layers)
from . import control_flow
from .backward import append_backward, gradients
from .control_flow import (
    StaticRNN,
    cond,
    equal,
    greater_equal,
    greater_than,
    increment,
    less_equal,
    less_than,
    logical_and,
    logical_not,
    logical_or,
    logical_xor,
    not_equal,
    while_loop,
)
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .executor import Executor, Scope, global_scope, scope_guard
from .framework import (
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
    unique_name,
)
from .io import (
    load,
    load_inference_model,
    load_persistables,
    save,
    save_inference_model,
    save_persistables,
)
from . import nets
from .analysis import (Diagnostic, check_program, check_program_cached,
                       infer_program, shape_rule_coverage, verify_program)
from .passes import (DEFAULT_PIPELINE, PassManager, available_passes,
                     golden_parity, optimize_for_executor)
from .shardcheck import check_plan, estimate_comm, verify_plan
from .registry import register_op, registered_ops
from . import op_version

data = layers.data
