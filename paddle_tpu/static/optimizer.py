"""Static-graph optimizers: append update ops to the program.

Reference parity: python/paddle/fluid/optimizer.py `Optimizer` (:56) —
`minimize` = append_backward + `_create_optimization_pass` emitting one
fused update op per parameter (sgd/momentum/adam ops, operators/optimizers/,
SURVEY.md N30), with slot ("accumulator") variables created as persistables.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..nn import initializer as I
from .backward import append_backward
from .framework import (
    Parameter,
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from .layers import create_parameter

__all__ = ["SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
           "Adam", "AdamOptimizer"]


class _StaticOptimizer:
    def __init__(self, learning_rate: float):
        self._lr_value = float(learning_rate)
        self._lr_var: Optional[Variable] = None

    def _lr(self) -> Variable:
        if self._lr_var is None or \
                not default_main_program().global_block().has_var(self._lr_var.name):
            self._lr_var = create_parameter(
                (), "float32", name=unique_name("learning_rate"),
                default_initializer=I.Constant(self._lr_value),
                trainable=False)
        return self._lr_var

    def _slot(self, param: Parameter, suffix: str, init=0.0, shape=None):
        return create_parameter(
            shape if shape is not None else param.shape, "float32",
            name=f"{param.name}_{suffix}",
            default_initializer=I.Constant(init), trainable=False)

    def minimize(self, loss: Variable, parameter_list=None
                 ) -> Tuple[None, List[Tuple[Parameter, Variable]]]:
        p_g = append_backward(loss, parameter_list)
        self.apply_gradients(p_g)
        return None, p_g

    def apply_gradients(self, params_grads):
        block = default_main_program().global_block()
        lr = self._lr()
        for p, g in params_grads:
            self._append_update(block, p, g, lr)

    def _append_update(self, block, p, g, lr):
        raise NotImplementedError


class SGD(_StaticOptimizer):
    """ref fluid/optimizer.py:947 SGDOptimizer → sgd op."""

    def _append_update(self, block, p, g, lr):
        block.append_op("sgd",
                        {"Param": [p.name], "Grad": [g.name],
                         "LearningRate": [lr.name]},
                        {"ParamOut": [p.name]})


class Momentum(_StaticOptimizer):
    """ref fluid/optimizer.py MomentumOptimizer → momentum op."""

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False):
        super().__init__(learning_rate)
        self.mu = momentum
        self.use_nesterov = use_nesterov

    def _append_update(self, block, p, g, lr):
        vel = self._slot(p, "velocity")
        block.append_op("momentum",
                        {"Param": [p.name], "Grad": [g.name],
                         "Velocity": [vel.name], "LearningRate": [lr.name]},
                        {"ParamOut": [p.name], "VelocityOut": [vel.name]},
                        {"mu": self.mu, "use_nesterov": self.use_nesterov})


class Adam(_StaticOptimizer):
    """ref fluid/optimizer.py:1821 AdamOptimizer → adam op (dense path)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_adam_like(self, block, p, g, lr, op_type, extra_attrs=None):
        """Shared wiring for the adam-family ops (adam/adamw/lamb): same
        moment1/moment2/beta-pow slots and IO contract, different op name
        plus op-specific attrs."""
        m1 = self._slot(p, "moment1")
        m2 = self._slot(p, "moment2")
        b1p = self._slot(p, "beta1_pow", init=1.0, shape=())
        b2p = self._slot(p, "beta2_pow", init=1.0, shape=())
        block.append_op(
            op_type,
            {"Param": [p.name], "Grad": [g.name], "Moment1": [m1.name],
             "Moment2": [m2.name], "LearningRate": [lr.name],
             "Beta1Pow": [b1p.name], "Beta2Pow": [b2p.name]},
            {"ParamOut": [p.name], "Moment1Out": [m1.name],
             "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
             "Beta2PowOut": [b2p.name]},
            {"beta1": self.beta1, "beta2": self.beta2,
             "epsilon": self.epsilon, **(extra_attrs or {})})

    def _append_update(self, block, p, g, lr):
        self._append_adam_like(block, p, g, lr, "adam")


SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdamOptimizer = Adam


class AdamW(Adam):
    """ref paddle AdamW — adamw op (decoupled decay attr ``coeff``)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01):
        super().__init__(learning_rate, beta1, beta2, epsilon)
        self.coeff = weight_decay

    def _append_update(self, block, p, g, lr):
        self._append_adam_like(block, p, g, lr, "adamw",
                               {"coeff": self.coeff})


class Adagrad(_StaticOptimizer):
    """ref fluid/optimizer.py AdagradOptimizer → adagrad op."""

    def __init__(self, learning_rate, epsilon=1e-6,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate)
        self.epsilon = epsilon
        self.init_acc = initial_accumulator_value

    def _append_update(self, block, p, g, lr):
        acc = self._slot(p, "moment", init=self.init_acc)
        block.append_op(
            "adagrad",
            {"Param": [p.name], "Grad": [g.name], "Moment": [acc.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [p.name], "MomentOut": [acc.name]},
            {"epsilon": self.epsilon})


class Adadelta(_StaticOptimizer):
    """ref fluid/optimizer.py AdadeltaOptimizer → adadelta op."""

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95):
        super().__init__(learning_rate)
        self.epsilon, self.rho = epsilon, rho

    def _append_update(self, block, p, g, lr):
        ag = self._slot(p, "avg_squared_grad")
        au = self._slot(p, "avg_squared_update")
        block.append_op(
            "adadelta",
            {"Param": [p.name], "Grad": [g.name],
             "AvgSquaredGrad": [ag.name], "AvgSquaredUpdate": [au.name]},
            {"ParamOut": [p.name], "AvgSquaredGradOut": [ag.name],
             "AvgSquaredUpdateOut": [au.name]},
            {"epsilon": self.epsilon, "rho": self.rho})


class RMSProp(_StaticOptimizer):
    """ref fluid/optimizer.py RMSPropOptimizer → rmsprop op."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False):
        super().__init__(learning_rate)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def _append_update(self, block, p, g, lr):
        ms = self._slot(p, "mean_square")
        mg = self._slot(p, "mean_grad")
        mom = self._slot(p, "momentum_acc")
        block.append_op(
            "rmsprop",
            {"Param": [p.name], "Grad": [g.name], "MeanSquare": [ms.name],
             "MeanGrad": [mg.name], "Moment": [mom.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [p.name], "MeanSquareOut": [ms.name],
             "MeanGradOut": [mg.name], "MomentOut": [mom.name]},
            {"decay": self.rho, "epsilon": self.epsilon,
             "momentum": self.momentum, "centered": self.centered})


class Lamb(_StaticOptimizer):
    """ref fluid/optimizer.py:2930 LambOptimizer → lamb op."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6):
        super().__init__(learning_rate)
        self.wd = lamb_weight_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, block, p, g, lr):
        Adam._append_adam_like(self, block, p, g, lr, "lamb",
                               {"weight_decay": self.wd})


class Ftrl(_StaticOptimizer):
    """ref fluid/optimizer.py FtrlOptimizer → ftrl op."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5):
        super().__init__(learning_rate)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def _append_update(self, block, p, g, lr):
        sq = self._slot(p, "squared_acc")
        lin = self._slot(p, "linear_acc")
        block.append_op(
            "ftrl",
            {"Param": [p.name], "Grad": [g.name],
             "SquaredAccumulator": [sq.name],
             "LinearAccumulator": [lin.name], "LearningRate": [lr.name]},
            {"ParamOut": [p.name], "SquaredAccumOut": [sq.name],
             "LinearAccumOut": [lin.name]},
            {"l1": self.l1, "l2": self.l2, "lr_power": self.lr_power})


class LarsMomentum(_StaticOptimizer):
    """ref fluid/optimizer.py:1591 LarsMomentumOptimizer → lars_momentum."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005):
        super().__init__(learning_rate)
        self.mu = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay

    def _append_update(self, block, p, g, lr):
        vel = self._slot(p, "velocity")
        block.append_op(
            "lars_momentum",
            {"Param": [p.name], "Grad": [g.name], "Velocity": [vel.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [p.name], "VelocityOut": [vel.name]},
            {"mu": self.mu, "lars_coeff": self.lars_coeff,
             "lars_weight_decay": self.lars_weight_decay})


class Dpsgd(_StaticOptimizer):
    """ref fluid/optimizer.py DpsgdOptimizer → dpsgd op."""

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0,
                 sigma=1.0):
        super().__init__(learning_rate)
        self.clip, self.batch_size, self.sigma = clip, batch_size, sigma

    def _append_update(self, block, p, g, lr):
        block.append_op(
            "dpsgd",
            {"Param": [p.name], "Grad": [g.name],
             "LearningRate": [lr.name]},
            {"ParamOut": [p.name]},
            {"clip": self.clip, "batch_size": self.batch_size,
             "sigma": self.sigma})


__all__ += ["AdamW", "AdamWOptimizer", "Adagrad", "AdagradOptimizer",
            "Adadelta", "AdadeltaOptimizer", "RMSProp", "RMSPropOptimizer",
            "Lamb", "LambOptimizer", "Ftrl", "FtrlOptimizer",
            "LarsMomentum", "LarsMomentumOptimizer", "Dpsgd",
            "DpsgdOptimizer"]
AdamWOptimizer = AdamW
AdagradOptimizer = Adagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
LambOptimizer = Lamb
FtrlOptimizer = Ftrl
LarsMomentumOptimizer = LarsMomentum
DpsgdOptimizer = Dpsgd
