"""Static-graph optimizers: append update ops to the program.

Reference parity: python/paddle/fluid/optimizer.py `Optimizer` (:56) —
`minimize` = append_backward + `_create_optimization_pass` emitting one
fused update op per parameter (sgd/momentum/adam ops, operators/optimizers/,
SURVEY.md N30), with slot ("accumulator") variables created as persistables.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..nn import initializer as I
from .backward import append_backward
from .framework import (
    Parameter,
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from .layers import create_parameter

__all__ = ["SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
           "Adam", "AdamOptimizer"]


class _StaticOptimizer:
    def __init__(self, learning_rate: float):
        self._lr_value = float(learning_rate)
        self._lr_var: Optional[Variable] = None

    def _lr(self) -> Variable:
        if self._lr_var is None or \
                not default_main_program().global_block().has_var(self._lr_var.name):
            self._lr_var = create_parameter(
                (), "float32", name=unique_name("learning_rate"),
                default_initializer=I.Constant(self._lr_value),
                trainable=False)
        return self._lr_var

    def _slot(self, param: Parameter, suffix: str, init=0.0, shape=None):
        return create_parameter(
            shape if shape is not None else param.shape, "float32",
            name=f"{param.name}_{suffix}",
            default_initializer=I.Constant(init), trainable=False)

    def minimize(self, loss: Variable, parameter_list=None
                 ) -> Tuple[None, List[Tuple[Parameter, Variable]]]:
        p_g = append_backward(loss, parameter_list)
        self.apply_gradients(p_g)
        return None, p_g

    def apply_gradients(self, params_grads):
        block = default_main_program().global_block()
        lr = self._lr()
        for p, g in params_grads:
            self._append_update(block, p, g, lr)

    def _append_update(self, block, p, g, lr):
        raise NotImplementedError


class SGD(_StaticOptimizer):
    """ref fluid/optimizer.py:947 SGDOptimizer → sgd op."""

    def _append_update(self, block, p, g, lr):
        block.append_op("sgd",
                        {"Param": [p.name], "Grad": [g.name],
                         "LearningRate": [lr.name]},
                        {"ParamOut": [p.name]})


class Momentum(_StaticOptimizer):
    """ref fluid/optimizer.py MomentumOptimizer → momentum op."""

    def __init__(self, learning_rate, momentum=0.9, use_nesterov=False):
        super().__init__(learning_rate)
        self.mu = momentum
        self.use_nesterov = use_nesterov

    def _append_update(self, block, p, g, lr):
        vel = self._slot(p, "velocity")
        block.append_op("momentum",
                        {"Param": [p.name], "Grad": [g.name],
                         "Velocity": [vel.name], "LearningRate": [lr.name]},
                        {"ParamOut": [p.name], "VelocityOut": [vel.name]},
                        {"mu": self.mu, "use_nesterov": self.use_nesterov})


class Adam(_StaticOptimizer):
    """ref fluid/optimizer.py:1821 AdamOptimizer → adam op (dense path)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8):
        super().__init__(learning_rate)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _append_update(self, block, p, g, lr):
        m1 = self._slot(p, "moment1")
        m2 = self._slot(p, "moment2")
        b1p = self._slot(p, "beta1_pow", init=1.0, shape=())
        b2p = self._slot(p, "beta2_pow", init=1.0, shape=())
        block.append_op(
            "adam",
            {"Param": [p.name], "Grad": [g.name], "Moment1": [m1.name],
             "Moment2": [m2.name], "LearningRate": [lr.name],
             "Beta1Pow": [b1p.name], "Beta2Pow": [b2p.name]},
            {"ParamOut": [p.name], "Moment1Out": [m1.name],
             "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
             "Beta2PowOut": [b2p.name]},
            {"beta1": self.beta1, "beta2": self.beta2,
             "epsilon": self.epsilon})


SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdamOptimizer = Adam
