"""Verified graph-rewrite passes over static ``Program``s.

Reference parity: the ``framework/ir`` pass stage — Graph/Pass/PassRegistry
(framework/ir/graph.h, pass.h) and its fusion family
(conv_bn_fuse_pass.cc, fc_fuse_pass.cc, fc_gru/lstm fuse, transpose-flatten
fuses) plus the inference-time IR passes (constant folding, identity-op
elimination).  TPU-native twist: XLA already does instruction-level CSE/DCE
*inside* the compiled computation, so these passes earn their keep at the
**Program** level — fewer traced ops (faster trace + lower Python overhead),
weight-space folds XLA cannot do (conv+BN folds a *parameter*, not an
activation), and layout decisions (NHWC) that must be made before
``lax.conv`` dimension numbers are chosen.

Every rewrite runs under the **VerifiedRewrite contract**:

1. passes operate on a ``Program.clone()`` — the caller's program is never
   mutated (its version, analysis memo, and hot-cache entries stay valid);
2. the clone is stamped with per-op ``rng_salt`` *before* any rewrite, so
   random ops keep their pre-rewrite PRNG streams even when op indices
   shift (golden parity for dropout/gaussian_random survives DCE);
3. ``infer_program`` symbolic shape/dtype snapshots are taken before and
   after: every fetch must remain *produced or fed* and keep its inferred
   shape/dtype — a violation raises ``ProgramVerificationError`` carrying
   a ``PV011`` diagnostic (see static/analysis.py's code table);
4. the rewritten program re-runs the full ``check_program`` walker
   (PV001–PV010), so a pass can never emit a program the verifier would
   reject at trace time.

The Executor runs the pipeline on its compile (cache-miss) path behind the
``opt_passes`` flag; a verification failure there *rolls back* to the
unrewritten program (``passes.rollbacks`` metric + flight-recorder event)
instead of failing the step — passes are an optimization, never a
correctness dependency.  ``python -m tools.passes`` drives the same
pipeline standalone with a per-pass diff report and an execution-level
golden-parity check (``golden_parity`` below: bitwise for ints, tolerance
for floats, final persistable state included).
"""
from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import errors as _errors
from ..utils import monitor as _monitor
from ..utils import trace as _trace
from .analysis import Diagnostic, _known, check_program, infer_program
from .framework import Block, Operator, Program

__all__ = [
    "PassManager", "PassContext", "PipelineReport", "ParityReport",
    "DEFAULT_PIPELINE", "QUANT_INFER_PIPELINE", "available_passes",
    "pipeline_from_flag",
    "optimize_for_executor", "golden_parity", "verify_rewrite",
    "use_def_chains", "liveness", "reachable_ops", "is_pure",
    "RANDOM_OPS", "CONTROL_FLOW_OPS",
]

# ---------------------------------------------------------------------------
# Op classification (the analyses' ground truth).
# ---------------------------------------------------------------------------

# Ops whose lowerings draw from the per-op PRNG stream (core.random
# next_key under executor._run_op_traced's rng_scope).  Never folded,
# never CSE'd (two identical random ops are *independent* draws), and
# their clones carry a pinned ``rng_salt`` so rewrites that shift op
# indices don't silently re-seed them.
RANDOM_OPS = frozenset({
    "gaussian_random", "uniform_random", "truncated_gaussian_random",
    "gaussian_random_batch_size_like", "uniform_random_batch_size_like",
    "randint", "randperm", "bernoulli", "multinomial", "sampling_id",
    "dropout", "random_crop", "shuffle_batch", "seed", "rrelu",
    "class_center_sample",
})

# Control-flow / executor pseudo-ops (executor._trace_ops dispatches these
# specially).  ``backward_region`` re-traces its whole block prefix, so it
# is additionally a liveness root for everything its Loss depends on.
CONTROL_FLOW_OPS = frozenset({
    "feed", "fetch", "backward_region", "conditional_block", "while",
    "static_rnn",
})

# Host-IO / stateful ops: the PL005 (proglint host-sync) families — save/
# load/print/py_func run ordered io_callbacks, the sparse-table ops mutate
# a host-side store, the array/LoD ops are order-dependent scope writers.
_SIDE_EFFECT_OPS = frozenset({
    "save", "save_combine", "load", "load_combine", "print", "py_func",
    "write_to_array", "read_from_array", "array_to_lod_tensor",
    "lod_tensor_to_array", "shrink_rnn_memory", "merge_lod_tensor",
    "split_lod_tensor", "lookup_sparse_table_merge", "merge_ids",
    "split_ids", "allreduce", "broadcast", "sync_batch_norm",
    "inplace_abn",
})


def has_side_effects(op_type: str) -> bool:
    """Host IO, collectives, or host-state mutation: a liveness root."""
    return (op_type in _SIDE_EFFECT_OPS
            or op_type.startswith(("c_", "push_", "pull_", "distributed_")))


def is_pure(op: Operator) -> bool:
    """Safe to fold/dedup/remove when its outputs are dead: deterministic,
    effect-free, and sub-block-free."""
    return (op.type not in RANDOM_OPS
            and op.type not in CONTROL_FLOW_OPS
            and not has_side_effects(op.type)
            and not op.sub_block_indices())


# ---------------------------------------------------------------------------
# Analyses: use-def chains, liveness, reachability.
# ---------------------------------------------------------------------------

def use_def_chains(block: Block) -> Tuple[Dict[str, List[Tuple[int, str]]],
                                          Dict[str, List[Tuple[int, str]]]]:
    """(defs, uses): var name -> [(op_index, slot)] over one block, in op
    order.  Names can be multiply defined (persistable write-backs like
    batch_norm's MeanOut alias their input) — consumers must check."""
    defs: Dict[str, List[Tuple[int, str]]] = {}
    uses: Dict[str, List[Tuple[int, str]]] = {}
    for idx, op in enumerate(block.ops):
        for slot, names in op.inputs.items():
            for n in names:
                uses.setdefault(n, []).append((idx, slot))
        for slot, names in op.outputs.items():
            for n in names:
                defs.setdefault(n, []).append((idx, slot))
    return defs, uses


def _root_reads(block: Block, fetch_names: Sequence[str]) -> Set[str]:
    """Names live-out of the block: fetches (the executor reads them from
    the env after the walk)."""
    return set(fetch_names or ())


def subblock_free_reads(op: Operator, block: Block) -> Set[str]:
    """Names the op's sub-blocks read from an enclosing scope.

    Walks every sub-block the op references (recursively), tracking which
    names are defined *by earlier ops within that sub-block*; any read of
    a name not so defined is a free read — the outer scope must keep it
    live for the whole duration of the carrying op (while/cond carries,
    rnn sequence inputs, backward_region's forward reads).  Names that
    turn out not to exist in the outer block are harmless over-approximation
    (the caller's live-set simply carries a name nobody produces)."""
    free: Set[str] = set()
    program = block.program

    def walk(block_idx: int, defined: Set[str]) -> None:
        sub = program.blocks[block_idx]
        local = set(defined)
        for sop in sub.ops:
            for n in sop.input_names():
                if n not in local:
                    free.add(n)
            for _attr, sbi in sop.sub_block_indices():
                walk(sbi, local)
            local.update(sop.output_names())

    for _attr, bi in op.sub_block_indices():
        walk(bi, set())
    return free


def _op_is_root(block: Block, op: Operator) -> bool:
    """Ops that must survive DCE regardless of dataflow: effects, control
    flow, and writes to persistable state (the executor writes persistable
    outputs back to the scope)."""
    if op.type in CONTROL_FLOW_OPS or has_side_effects(op.type):
        return True
    if op.sub_block_indices():
        return True
    for n in op.output_names():
        try:
            if block.var(n).persistable:
                return True
        except KeyError:
            pass
    return False


def liveness(block: Block, fetch_names: Sequence[str]
             ) -> Tuple[List[bool], List[Set[str]]]:
    """Backward liveness over one block.

    Returns ``(live_ops, live_after)``: per-op liveness (is the op needed
    for any fetch / persistable write / side effect?) and the set of names
    live *after* each op.  The classic kill-then-gen walk handles
    redefinition (a persistable written mid-block) correctly.

    Ops that carry sub-blocks (while/cond/rnn/backward_region) gen not
    just their declared inputs but every free read of their sub-blocks
    (``subblock_free_reads``) — a while carry read only inside the loop
    body must stay live across the whole loop."""
    n = len(block.ops)
    needed: Set[str] = _root_reads(block, fetch_names)
    live = [False] * n
    live_after: List[Set[str]] = [set()] * n
    for idx in range(n - 1, -1, -1):
        op = block.ops[idx]
        live_after[idx] = set(needed)
        outs = set(op.output_names())
        if _op_is_root(block, op) or (outs & needed):
            live[idx] = True
            needed -= outs
            needed |= set(op.input_names())
            if op.sub_block_indices():
                needed |= subblock_free_reads(op, block)
    return live, live_after


def reachable_ops(block: Block, fetch_names: Sequence[str]) -> Set[int]:
    """Indices of ops that (transitively) feed a fetch, a persistable
    write, or an effect — the complement is DCE's kill set."""
    live, _ = liveness(block, fetch_names)
    return {i for i, alive in enumerate(live) if alive}


# ---------------------------------------------------------------------------
# Pass context + shared rewrite helpers.
# ---------------------------------------------------------------------------

@dataclass
class PassContext:
    feed_names: Set[str] = field(default_factory=set)
    fetch_names: Tuple[str, ...] = ()

    def protected(self, block: Block, name: str) -> bool:
        """Names a pass must keep producing under their own identity:
        fetches, feeds, and persistable state."""
        if name in self.fetch_names or name in self.feed_names:
            return True
        try:
            v = block.var(name)
        except KeyError:
            return False
        return bool(v.persistable or v.is_data)


def _fresh_name(block: Block, base: str) -> str:
    """Deterministic name minting for pass-created vars.  The process-global
    ``unique_name`` counter would make the rewritten program's fingerprint
    (and therefore its compile-cache key) depend on how many programs were
    built earlier in the process — a warm start would silently MISS.  Names
    derive from the rewritten graph alone: the base, suffixed only on
    collision within this block."""
    if base not in block.vars:
        return base
    i = 0
    while f"{base}.{i}" in block.vars:
        i += 1
    return f"{base}.{i}"


def _rewrite_reads(block: Block, old: str, new: str,
                   start: int = 0) -> int:
    """Redirect every input read of ``old`` to ``new`` from op ``start``
    on.  In-place slot edit — bumps the program version explicitly (the
    pass-manager side of the Block mutation contract)."""
    count = 0
    for op in block.ops[start:]:
        for slot, names in op.inputs.items():
            if old in names:
                op.inputs[slot] = [new if n == old else n for n in names]
                count += 1
    if count:
        block.program.bump_version()
    return count


def _single_def_use(defs, uses, name) -> Optional[Tuple[int, str]]:
    """The unique (op_index, slot) consuming ``name`` when it has exactly
    one def and one use; else None."""
    if len(defs.get(name, ())) != 1 or len(uses.get(name, ())) != 1:
        return None
    return uses[name][0]


def _stamp_rng_salts(program: Program) -> None:
    """Pin every random op's PRNG salt to its PRE-rewrite (block, index)
    position — executor._run_op_traced honors ``op.rng_salt`` over the
    positional default, so draws survive op insertion/removal."""
    from .executor import _op_salt

    for block in program.blocks:
        for idx, op in enumerate(block.ops):
            if op.type in RANDOM_OPS and op.rng_salt is None:
                op.rng_salt = _op_salt(block.idx, idx)


def _canon_attr(v):
    if isinstance(v, (list, tuple)):
        return tuple(_canon_attr(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _canon_attr(x)) for k, x in v.items()))
    if isinstance(v, np.dtype):
        return str(v)
    if isinstance(v, np.generic):
        return v.item()
    return v


def _canon_attrs(attrs: Dict[str, Any]):
    return tuple(sorted((k, _canon_attr(v)) for k, v in attrs.items()))


# ---------------------------------------------------------------------------
# The passes.
# ---------------------------------------------------------------------------

class Pass:
    """One rewrite over a (cloned) Program.  ``run`` returns a stats dict;
    a truthy ``"changed"`` entry marks the program as rewritten."""

    name = "pass"

    def run(self, program: Program, ctx: PassContext) -> Dict[str, Any]:
        raise NotImplementedError


_FOLD_MAX_ELEMS = 4096  # don't bake big tensors into attrs

# seeds of constness: ops whose output is a function of attrs alone
_CONST_SOURCES = frozenset({"fill_constant", "assign_value", "eye",
                            "range", "linspace"})


class ConstantFolding(Pass):
    """Evaluate compile-time-constant subgraphs host-side and replace each
    root with a single ``assign_value`` (ref: the inference-time
    constant_folding_pass; here the fold runs the op's *own* jax lowering,
    so folded bits match traced bits exactly)."""

    name = "constant_folding"

    def run(self, program, ctx):
        from .registry import get_lowering

        block = program.global_block()
        const_vals: Dict[str, np.ndarray] = {}
        folded = 0
        for idx, op in enumerate(list(block.ops)):
            if not is_pure(op):
                for n in op.output_names():
                    const_vals.pop(n, None)
                continue
            is_source = op.type in _CONST_SOURCES and not op.inputs
            if not is_source and (not op.input_names() or any(
                    n not in const_vals for n in op.input_names())):
                for n in op.output_names():
                    const_vals.pop(n, None)
                continue
            outs = op.output_names()
            try:
                val = self._evaluate(get_lowering, op, const_vals)
            except Exception:
                for n in outs:
                    const_vals.pop(n, None)
                continue
            if val is None:
                for n in outs:
                    const_vals.pop(n, None)
                continue
            name = outs[0]
            const_vals[name] = val
            # replacing a source with assign_value is churn, not progress —
            # only rewrite ops that actually *consumed* constants
            if is_source or op.type == "assign_value":
                continue
            attrs = self._assign_value_attrs(val)
            if attrs is None:
                continue
            slot = next(iter(op.outputs))
            block.replace_op(idx, "assign_value", {}, {slot: [name]}, attrs)
            folded += 1
        return {"changed": folded > 0, "folded": folded}

    @staticmethod
    def _evaluate(get_lowering, op, const_vals):
        """Run the op's lowering on concrete inputs; single-output pure ops
        only, bounded result size."""
        import jax.numpy as jnp

        if sum(len(v) for v in op.outputs.values()) != 1:
            return None
        lowering = get_lowering(op.type)
        ins = {slot: [jnp.asarray(const_vals[n]) for n in names]
               for slot, names in op.inputs.items()}
        outs = lowering(ins, op.attrs, op)
        slot = next(iter(op.outputs))
        vals = outs.get(slot, [])
        if len(vals) != 1:
            return None
        val = np.asarray(vals[0])
        if val.size == 0 or val.size > _FOLD_MAX_ELEMS:
            return None
        return val

    @staticmethod
    def _assign_value_attrs(val: np.ndarray) -> Optional[Dict[str, Any]]:
        kind = val.dtype.kind
        if kind == "f" or val.dtype.name == "bfloat16":
            # Python floats are f64: exact carriers for f32/bf16 values
            values = {"fp32_values":
                      [float(x) for x in val.astype(np.float64).ravel()]}
        elif kind in ("i", "u", "b"):
            values = {"int32_values": [int(x) for x in val.ravel()]}
        else:
            return None
        return {"shape": [int(d) for d in val.shape],
                "dtype": val.dtype.name, **values}


class CSE(Pass):
    """Common-subexpression elimination by value numbering: two pure ops
    with the same type, attrs, and value-numbered inputs compute the same
    thing — the later one's reads are redirected to the first and the
    duplicate is deleted (ref framework/ir's identity/duplicate folds;
    random ops are never merged: same attrs, independent draws)."""

    name = "cse"

    def run(self, program, ctx):
        block = program.global_block()
        table: Dict[tuple, int] = {}
        vn: Dict[str, tuple] = {}
        renames: Dict[str, str] = {}
        dups: List[int] = []
        for idx, op in enumerate(block.ops):
            key = self._key(op, vn) if is_pure(op) else None
            if key is None:
                for n in op.output_names():
                    vn[n] = ("opaque", idx)
                continue
            first = table.setdefault(key, idx)
            if first == idx or not self._mergeable(block, ctx, op):
                for slot, names in op.outputs.items():
                    for i, n in enumerate(names):
                        vn[n] = ("cse", table[key], slot, i)
                continue
            # duplicate of block.ops[first]: alias outputs slot-by-slot
            prev = block.ops[first]
            for slot, names in op.outputs.items():
                for i, n in enumerate(names):
                    renames[n] = prev.outputs[slot][i]
                    vn[n] = ("cse", first, slot, i)
            dups.append(idx)
        if not dups:
            return {"changed": False, "deduped": 0}
        for idx, op in enumerate(block.ops):
            for slot, names in op.inputs.items():
                if any(n in renames for n in names):
                    op.inputs[slot] = [renames.get(n, n) for n in names]
        for idx in reversed(dups):
            block.remove_op(idx)
        return {"changed": True, "deduped": len(dups)}

    @staticmethod
    def _key(op, vn):
        try:
            return (op.type, _canon_attrs(op.attrs),
                    tuple(sorted((slot, tuple(vn.get(n, ("ext", n))
                                              for n in names))
                                 for slot, names in op.inputs.items())),
                    tuple(sorted((slot, len(names))
                                 for slot, names in op.outputs.items())))
        except TypeError:
            return None                      # unhashable attr: skip
    @staticmethod
    def _mergeable(block, ctx, op):
        return not any(ctx.protected(block, n) for n in op.output_names())


class DCE(Pass):
    """Dead-op + dead-var elimination: remove ops that reach no fetch, no
    persistable write, and no effect (liveness above), then drop var-table
    entries nothing references."""

    name = "dce"

    def run(self, program, ctx):
        block = program.global_block()
        live, _ = liveness(block, ctx.fetch_names)
        removed = 0
        for idx in range(len(block.ops) - 1, -1, -1):
            if not live[idx]:
                block.remove_op(idx)
                removed += 1
        dropped = self._sweep_vars(block, ctx)
        return {"changed": removed > 0 or dropped > 0,
                "ops_removed": removed, "vars_removed": dropped}

    @staticmethod
    def _sweep_vars(block, ctx):
        referenced: Set[str] = set()
        for op in block.ops:
            referenced.update(op.input_names())
            referenced.update(op.output_names())
        dead = [n for n, v in block.vars.items()
                if n not in referenced and not v.persistable
                and not v.is_data and n not in ctx.fetch_names
                and n not in ctx.feed_names]
        for n in dead:
            block.remove_var(n)
        return len(dead)


class FuseConvBNAct(Pass):
    """conv2d → batch_norm [→ act] ⇒ ``fused_conv2d_bn_act``
    (ref conv_bn_fuse_pass.cc + conv_elementwise_add_act_fuse_pass.cc).

    The generalized replacement for the r05 hand-fold: instead of every
    inference batch_norm paying a per-activation a·x+b
    (nn/functional/norm.py), the pass folds the BN into the conv *filter*
    (see static/ops_fused.py).  Training batch_norms fuse too: the fused
    op keeps the ``MeanOut``/``VarianceOut`` running-stat writes (which
    alias ``Mean``/``Variance`` in place, exactly as layers.batch_norm
    emits them) and records ``is_test``/``momentum``, and its lowering
    routes through nn.functional.norm.batch_norm_act — differentiable, so
    the pass no longer bails on programs with a ``backward_region`` (that
    pseudo-op references only Loss/Params by name, never intermediates,
    so single-use matching stays exact in training graphs)."""

    name = "fuse_conv_bn_act"

    def run(self, program, ctx):
        from .ops_fused import FUSABLE_ACTS

        block = program.global_block()
        fused = 0
        while True:
            match = self._find(block, ctx, FUSABLE_ACTS)
            if match is None:
                break
            self._apply(block, *match)
            fused += 1
        return {"changed": fused > 0, "fused": fused}

    def _find(self, block, ctx, fusable_acts):
        defs, uses = use_def_chains(block)
        for idx, conv in enumerate(block.ops):
            if conv.type != "conv2d":
                continue
            conv_out = conv.outputs.get("Output", [None])[0]
            if conv_out is None or ctx.protected(block, conv_out):
                continue
            use = _single_def_use(defs, uses, conv_out)
            if use is None or use[1] != "X":
                continue
            j = use[0]
            bn = block.ops[j]
            if bn.type != "batch_norm" or j <= idx:
                continue
            # the running-stat write-back must be the in-place alias (both
            # modes: is_test writes inputs unchanged, training updates the
            # same vars — either way the fused op preserves the contract)
            if (bn.outputs.get("MeanOut", [None])[0]
                    != bn.inputs.get("Mean", [None])[0]
                    or bn.outputs.get("VarianceOut", [None])[0]
                    != bn.inputs.get("Variance", [None])[0]):
                continue
            bn_y = bn.outputs.get("Y", [None])[0]
            if bn_y is None:
                continue
            k = None
            act = ""
            y_use = _single_def_use(defs, uses, bn_y)
            if (y_use is not None and y_use[1] == "X"
                    and not ctx.protected(block, bn_y)):
                cand = block.ops[y_use[0]]
                if (y_use[0] > j and cand.type in fusable_acts
                        and not cand.attrs
                        and len(cand.outputs.get("Out", ())) == 1):
                    k, act = y_use[0], cand.type
            return idx, j, k, act
        return None

    @staticmethod
    def _apply(block, idx, j, k, act):
        conv, bn = block.ops[idx], block.ops[j]
        final = (block.ops[k].outputs["Out"][0] if k is not None
                 else bn.outputs["Y"][0])
        ins = {"Input": conv.inputs["Input"],
               "Filter": conv.inputs["Filter"],
               "Mean": bn.inputs["Mean"], "Variance": bn.inputs["Variance"],
               "Scale": bn.inputs["Scale"], "BnBias": bn.inputs["Bias"]}
        if conv.inputs.get("Bias"):
            ins["Bias"] = conv.inputs["Bias"]
        attrs = {"strides": conv.attrs.get("strides", 1),
                 "paddings": conv.attrs.get("paddings", 0),
                 "dilations": conv.attrs.get("dilations", 1),
                 "groups": conv.attrs.get("groups", 1),
                 "data_format": conv.attrs.get("data_format", "NCHW"),
                 "epsilon": bn.attrs.get("epsilon", 1e-5), "act": act,
                 "is_test": bn.attrs.get("is_test", False),
                 "momentum": bn.attrs.get("momentum", 0.9)}
        outs = {"Output": [final]}
        if not attrs["is_test"]:
            # training: the running-stat updates are real — keep them
            outs["MeanOut"] = bn.outputs["MeanOut"]
            outs["VarianceOut"] = bn.outputs["VarianceOut"]
        block.replace_op(idx, "fused_conv2d_bn_act", ins, outs, attrs)
        for dead in sorted([x for x in (j, k) if x is not None],
                           reverse=True):
            block.remove_op(dead)


class FuseMatmulBiasAct(Pass):
    """mul → elementwise_add(1-D bias on the last axis) [→ act] ⇒
    ``fused_matmul_bias_act`` — the fc/transformer-MLP pattern, gelu
    included (ref fc_fuse_pass.cc; L.fc emits exactly this op triple)."""

    name = "fuse_matmul_bias_act"

    def run(self, program, ctx):
        from .ops_fused import FUSABLE_ACTS

        block = program.global_block()
        if any(op.type == "backward_region" for op in block.ops):
            return {"changed": False, "fused": 0}
        fused = 0
        while True:
            match = self._find(block, ctx, FUSABLE_ACTS)
            if match is None:
                break
            self._apply(block, *match)
            fused += 1
        return {"changed": fused > 0, "fused": fused}

    def _find(self, block, ctx, fusable_acts):
        defs, uses = use_def_chains(block)
        for idx, mm in enumerate(block.ops):
            if mm.type != "mul":
                continue
            out = mm.outputs.get("Out", [None])[0]
            if out is None or ctx.protected(block, out):
                continue
            use = _single_def_use(defs, uses, out)
            if use is None or use[1] != "X":
                continue
            j = use[0]
            add = block.ops[j]
            if add.type != "elementwise_add" or j <= idx:
                continue
            bias = add.inputs.get("Y", [None])[0]
            if bias is None or not self._last_axis_bias(block, add, out,
                                                        bias):
                continue
            add_out = add.outputs["Out"][0]
            k = None
            act = ""
            a_use = _single_def_use(defs, uses, add_out)
            if (a_use is not None and a_use[1] == "X"
                    and not ctx.protected(block, add_out)):
                cand = block.ops[a_use[0]]
                if (a_use[0] > j and cand.type in fusable_acts
                        and not cand.attrs
                        and len(cand.outputs.get("Out", ())) == 1):
                    k, act = a_use[0], cand.type
            return idx, j, k, act
        return None

    @staticmethod
    def _last_axis_bias(block, add, x_name, bias_name) -> bool:
        """The fused lowering broadcasts a 1-D bias over the LAST axis;
        accept only elementwise_adds that provably mean the same."""
        try:
            if len(block.var(bias_name).shape) != 1:
                return False
            rank = len(block.var(x_name).shape)
        except KeyError:
            return False
        axis = add.attrs.get("axis", -1)
        return axis == -1 or axis == rank - 1

    @staticmethod
    def _apply(block, idx, j, k, act):
        mm, add = block.ops[idx], block.ops[j]
        final = (block.ops[k].outputs["Out"][0] if k is not None
                 else add.outputs["Out"][0])
        ins = {"X": mm.inputs["X"], "Y": mm.inputs["Y"],
               "Bias": add.inputs["Y"]}
        attrs = {"x_num_col_dims": mm.attrs.get("x_num_col_dims", 1),
                 "y_num_col_dims": mm.attrs.get("y_num_col_dims", 1),
                 "act": act}
        block.replace_op(idx, "fused_matmul_bias_act", ins, {"Out": [final]},
                         attrs)
        for dead in sorted([x for x in (j, k) if x is not None],
                           reverse=True):
            block.remove_op(dead)


class QuantInfer(Pass):
    """PTQ artifacts ⇒ int8 inference ops: ``conv2d``/``mul`` carrying
    ``weight_scale`` attrs (left by QuantizationFreezePass / the static
    PostTrainingQuantization — slim/quant_static.py) whose activation
    input comes through a ``fake_quantize_dequantize_fixed_scale`` op
    become ``quant_conv2d`` / ``quant_mul`` with the input scale folded
    into attrs (and the qdq op deleted when nothing else reads it).

    The rewritten ops' lowerings (static/ops_fused.py) run the
    ops/pallas/int8 kernels when gated — int8 MXU dots, int32
    accumulation, fp32 per-channel dequant epilogue — and otherwise a
    *simulate* fallback that replays the exact fake-quant + float-op
    sequence this pass removed, so flag-off golden parity is bitwise.
    A trailing attr-free activation the int8 epilogue supports is
    absorbed like FuseConvBNAct does.  Not in the default pipeline:
    quantized inference opts in via ``opt_passes="quant_infer,..."`` or
    serving's ``quantize=`` tenant option."""

    name = "quant_infer"

    # op type -> (activation slot, output slot, quant op type)
    _TARGETS = {"conv2d": ("Input", "Output", "quant_conv2d"),
                "mul": ("X", "Out", "quant_mul")}
    # acts the int8 kernels take as epilogue (ops/pallas/int8.EPILOGUE_ACTS)
    _ACTS = frozenset({"relu", "relu6", "sigmoid", "tanh"})

    def run(self, program, ctx):
        block = program.global_block()
        if any(op.type == "backward_region" for op in block.ops):
            return {"changed": False, "fused": 0}   # inference-only rewrite
        rewritten = 0
        while True:
            match = self._find(block, ctx)
            if match is None:
                break
            self._apply(block, *match)
            rewritten += 1
        return {"changed": rewritten > 0, "fused": rewritten}

    def _find(self, block, ctx):
        defs, uses = use_def_chains(block)
        for idx, op in enumerate(block.ops):
            spec = self._TARGETS.get(op.type)
            if spec is None or "weight_scale" not in op.attrs:
                continue
            aslot, oslot, _qtype = spec
            a_name = op.inputs.get(aslot, [None])[0]
            if a_name is None:
                continue
            d = defs.get(a_name, ())
            if len(d) != 1:
                continue
            q_idx = d[0][0]
            qdq = block.ops[q_idx]
            if (qdq.type != "fake_quantize_dequantize_fixed_scale"
                    or q_idx >= idx or "scale" not in qdq.attrs):
                continue
            # qdq op removable only when this op is its sole reader
            removable = (len(uses.get(a_name, ())) == 1
                         and not ctx.protected(block, a_name))
            # absorb a trailing attr-free act the int8 epilogue supports
            out_name = op.outputs.get(oslot, [None])[0]
            k = None
            act = ""
            o_use = _single_def_use(defs, uses, out_name) \
                if out_name and not ctx.protected(block, out_name) else None
            if o_use is not None and o_use[1] == "X":
                cand = block.ops[o_use[0]]
                if (o_use[0] > idx and cand.type in self._ACTS
                        and not cand.attrs
                        and len(cand.outputs.get("Out", ())) == 1):
                    k, act = o_use[0], cand.type
            return idx, q_idx, removable, k, act
        return None

    def _apply(self, block, idx, q_idx, removable, k, act):
        op, qdq = block.ops[idx], block.ops[q_idx]
        aslot, oslot, qtype = self._TARGETS[op.type]
        ins = dict(op.inputs)
        ins[aslot] = list(qdq.inputs["X"])
        outs = {s: list(names) for s, names in op.outputs.items()}
        if k is not None:
            outs[oslot] = [block.ops[k].outputs["Out"][0]]
        attrs = dict(op.attrs)
        attrs["in_scale"] = float(qdq.attrs["scale"])
        attrs["in_bits"] = int(qdq.attrs.get("bit_length", 8))
        attrs["act"] = act
        block.replace_op(idx, qtype, ins, outs, attrs)
        _m_quant_ops.inc(**{"op": op.type})
        for dead in sorted([x for x in (k, q_idx if removable else None)
                            if x is not None], reverse=True):
            block.remove_op(dead)


_NCHW_TO_NHWC = (0, 2, 3, 1)
_NHWC_TO_NCHW = (0, 3, 1, 2)
# 4-D ops whose lowerings take data_format (ops.py _conv2d/_pool2d,
# ops_fused._fused_conv2d_bn_act via F.conv2d, ops_fused._quant_conv2d)
_LAYOUT_OPS = {"conv2d": ("Input", "Output"),
               "fused_conv2d_bn_act": ("Input", "Output"),
               "quant_conv2d": ("Input", "Output"),
               "pool2d": ("X", "Out")}
# value-wise single-input ops a transpose can sink through unchanged
_SINKABLE = frozenset({
    "relu", "gelu", "sigmoid", "tanh", "relu6", "silu", "swish",
    "leaky_relu", "hard_swish", "softplus", "mish", "elu", "scale", "cast",
    "abs", "exp", "log", "sqrt", "rsqrt", "square",
})


class LayoutNHWC(Pass):
    """End-to-end NHWC layout propagation (ref: the reference's
    conv-layout/transfer-layout IR passes; on TPU, NHWC is the native conv
    layout — see the accelerator guide's convolution section).

    Three phases, each exact:
    1. wrap every NCHW conv/fused-conv/pool in ``transpose2`` in/out pairs
       and flip the op's ``data_format`` to NHWC;
    2. sink transposes through value-wise ops (act between conv and pool),
       so back-to-back inverse pairs become adjacent;
    3. cancel adjacent inverse pairs (fetch-protected names get an
       ``assign`` instead of a rename).
    A chain conv→relu→pool thus runs NHWC throughout, with exactly one
    transpose at each NCHW boundary."""

    name = "layout_nhwc"

    def run(self, program, ctx):
        block = program.global_block()
        if any(op.type == "backward_region" for op in block.ops):
            return {"changed": False}
        wrapped = self._wrap(block)
        sunk = cancelled = 0
        if wrapped:
            for _ in range(64):                       # fixpoint, bounded
                s = self._sink(block)
                c = self._cancel(block, ctx)
                sunk += s
                cancelled += c
                if not s and not c:
                    break
        return {"changed": wrapped > 0, "converted": wrapped,
                "transposes_sunk": sunk, "transposes_cancelled": cancelled}

    # -- phase 1: local NHWC wrap -------------------------------------------
    def _wrap(self, block) -> int:
        converted = 0
        idx = 0
        while idx < len(block.ops):
            op = block.ops[idx]
            slots = _LAYOUT_OPS.get(op.type)
            if (slots is None
                    or op.attrs.get("data_format", "NCHW") != "NCHW"
                    or not self._rank4(block, op, slots)):
                idx += 1
                continue
            in_slot, out_slot = slots
            x = op.inputs[in_slot][0]
            out = op.outputs[out_slot][0]
            nhwc_in = self._tvar(block, x, _NCHW_TO_NHWC)
            nhwc_out = self._tvar(block, out, _NCHW_TO_NHWC)
            op.inputs[in_slot] = [nhwc_in]
            op.outputs[out_slot] = [nhwc_out]
            op.attrs["data_format"] = "NHWC"
            block.program.bump_version()
            block.insert_op(idx, "transpose2", {"X": [x]},
                            {"Out": [nhwc_in],
                             "XShape": [self._xshape(block, nhwc_in)]},
                            {"axis": list(_NCHW_TO_NHWC)})
            block.insert_op(idx + 2, "transpose2", {"X": [nhwc_out]},
                            {"Out": [out],
                             "XShape": [self._xshape(block, out)]},
                            {"axis": list(_NHWC_TO_NCHW)})
            converted += 1
            idx += 3
        return converted

    @staticmethod
    def _rank4(block, op, slots) -> bool:
        try:
            return (len(block.var(op.inputs[slots[0]][0]).shape) == 4
                    and len(block.var(op.outputs[slots[1]][0]).shape) == 4)
        except (KeyError, IndexError):
            return False

    @staticmethod
    def _tvar(block, name, perm):
        v = block.var(name)
        shape = tuple(v.shape[p] for p in perm)
        return block.create_var(_fresh_name(block, f"{name}.nhwc"), shape,
                                v.dtype).name

    @staticmethod
    def _xshape(block, base):
        return block.create_var(_fresh_name(block, f"{base}.xshape"),
                                (), "float32").name

    # -- phase 2: sink through value-wise ops -------------------------------
    def _sink(self, block) -> int:
        defs, uses = use_def_chains(block)
        for t_idx, t in enumerate(block.ops):
            if t.type != "transpose2":
                continue
            v = t.outputs["Out"][0]
            use = _single_def_use(defs, uses, v)
            if use is None or use[1] != "X":
                continue
            o_idx = use[0]
            op = block.ops[o_idx]
            if (o_idx <= t_idx or op.type not in _SINKABLE
                    or len(op.inputs.get("X", ())) != 1
                    or len(op.outputs.get("Out", ())) != 1):
                continue
            x = t.inputs["X"][0]
            w = op.outputs["Out"][0]
            try:
                v2_shape = block.var(x).shape
                w_dtype = block.var(w).dtype
            except KeyError:
                continue
            v2 = block.create_var(_fresh_name(block, f"{w}.sink"), v2_shape,
                                  w_dtype).name
            xshape = t.outputs.get("XShape", [self._xshape(block, w)])[0]
            axis = list(t.attrs["axis"])
            block.replace_op(t_idx, op.type, {"X": [x]}, {"Out": [v2]},
                             dict(op.attrs))
            block.replace_op(o_idx, "transpose2", {"X": [v2]},
                             {"Out": [w], "XShape": [xshape]},
                             {"axis": axis})
            return 1
        return 0

    # -- phase 3: cancel adjacent inverse pairs -----------------------------
    def _cancel(self, block, ctx) -> int:
        defs, uses = use_def_chains(block)
        for a_idx, a in enumerate(block.ops):
            if a.type != "transpose2":
                continue
            v = a.outputs["Out"][0]
            if ctx.protected(block, v):
                continue
            use = _single_def_use(defs, uses, v)
            if use is None or use[1] != "X":
                continue
            b_idx = use[0]
            b = block.ops[b_idx]
            if b.type != "transpose2" or b_idx <= a_idx:
                continue
            pa = [int(p) for p in a.attrs["axis"]]
            pb = [int(p) for p in b.attrs["axis"]]
            if [pa[p] for p in pb] != list(range(len(pa))):
                continue
            x = a.inputs["X"][0]
            w = b.outputs["Out"][0]
            if ctx.protected(block, w):
                block.replace_op(b_idx, "assign", {"X": [x]}, {"Out": [w]})
                block.remove_op(a_idx)
            else:
                _rewrite_reads(block, w, x)
                block.remove_op(b_idx)
                block.remove_op(a_idx)
            return 1
        return 0


# ---------------------------------------------------------------------------
# VerifiedRewrite: the PV011 interface contract.
# ---------------------------------------------------------------------------

def _norm_dim(d):
    return int(d) if _known(d) else "?"


def _interface_snapshot(program: Program, feed_names, fetch_names
                        ) -> Dict[str, tuple]:
    """fetch name -> (reachable, normalized shape, dtype string) from the
    infer_program symbolic engine.  ``reachable`` means the executor's env
    will actually hold the name after the walk: produced by an op, fed, or
    carried persistable state."""
    _diags, engine = infer_program(program, feed_names=feed_names,
                                   fetch_names=fetch_names)
    block = program.global_block()
    produced: Set[str] = set()
    for b in program.blocks:
        for op in b.ops:
            produced.update(op.output_names())
    snap = {}
    for n in fetch_names or ():
        try:
            v = block.var(n)
            fed = v.is_data or v.persistable
        except KeyError:
            fed = False
        fed = fed or n in (feed_names or ())
        reachable = n in produced or fed
        shape = engine.shape_of(block, n)
        dtype = engine.dtype_of(block, n)
        snap[n] = (reachable,
                   None if shape is None else tuple(_norm_dim(d)
                                                    for d in shape),
                   None if dtype is None else str(dtype))
    return snap


def _verify_interface(before: Dict[str, tuple], after: Dict[str, tuple]
                      ) -> List[Diagnostic]:
    """PV011: the fetch-reachable interface must survive the rewrite."""
    diags = []
    for name, (was_reachable, shape0, dtype0) in before.items():
        reachable, shape1, dtype1 = after.get(name, (False, None, None))
        if was_reachable and not reachable:
            diags.append(Diagnostic(
                "PV011", "error",
                f"rewrite broke the fetch interface: {name!r} is no longer "
                "produced or fed", var=name,
                hint="a pass removed or renamed the producing op"))
            continue
        if shape0 is not None and shape1 is not None:
            bad_rank = len(shape0) != len(shape1)
            bad_dim = not bad_rank and any(
                a != "?" and b != "?" and a != b
                for a, b in zip(shape0, shape1))
            if bad_rank or bad_dim:
                diags.append(Diagnostic(
                    "PV011", "error",
                    f"rewrite changed fetch {name!r} inferred shape "
                    f"{shape0} -> {shape1}", var=name,
                    hint="passes must preserve every fetch's shape"))
        if dtype0 is not None and dtype1 is not None and dtype0 != dtype1:
            diags.append(Diagnostic(
                "PV011", "error",
                f"rewrite changed fetch {name!r} inferred dtype "
                f"{dtype0} -> {dtype1}", var=name,
                hint="passes must preserve every fetch's dtype"))
    return diags


def verify_rewrite(original: Program, rewritten: Program,
                   feed_names: Optional[Sequence[str]] = None,
                   fetch_names: Optional[Sequence[str]] = None) -> None:
    """Standalone VerifiedRewrite check between two programs: proves the
    rewritten program still serves the original's fetch interface (PV011
    on violation) and re-runs the full program walker on it.  Raises
    ``ProgramVerificationError``; returns None when the rewrite holds."""
    feeds = set(feed_names or ())
    fetches = tuple(fetch_names or ())
    diags = _verify_interface(
        _interface_snapshot(original, feeds, fetches),
        _interface_snapshot(rewritten, feeds, fetches))
    if diags:
        raise _errors.ProgramVerificationError(
            "graph-rewrite verification failed (PV011):\n"
            + _errors.render_diagnostics(diags), diagnostics=diags)
    check_program(rewritten, feed_names=sorted(feeds) or None,
                  fetch_names=fetches or None)


# ---------------------------------------------------------------------------
# PassManager + pipeline.
# ---------------------------------------------------------------------------

_PASSES_SCHEMA = 1  # bump on any semantics change: rides the compile-cache key

_REGISTRY: Dict[str, Pass] = {p.name: p for p in (
    ConstantFolding(), CSE(), FuseConvBNAct(), FuseMatmulBiasAct(),
    QuantInfer(), LayoutNHWC(), DCE(),
)}

DEFAULT_PIPELINE = ("constant_folding", "cse", "fuse_conv_bn_act",
                    "fuse_matmul_bias_act", "layout_nhwc", "dce")

# the opt-in pipeline for PTQ-calibrated inference programs: fold the quant
# artifacts to int8 ops first, then lay out NHWC (quant_conv2d is in
# _LAYOUT_OPS) and sweep the orphaned qdq chains
QUANT_INFER_PIPELINE = ("constant_folding", "cse", "quant_infer",
                        "fuse_matmul_bias_act", "layout_nhwc", "dce")


def available_passes() -> List[str]:
    return sorted(_REGISTRY)


_m_runs = _monitor.counter(
    "passes.runs", "Pass-pipeline applications (one per Executor compile "
    "with opt_passes on, plus CLI/test runs).")
_m_rollbacks = _monitor.counter(
    "passes.rollbacks", "Pipelines abandoned because rewrite verification "
    "(PV011 / re-check) failed — the Executor fell back to the original "
    "program.")
_m_ops_removed = _monitor.counter(
    "passes.ops_removed", "Ops removed by rewrite passes, labeled by pass.",
    labelnames=("pass",))
_m_ops_fused = _monitor.counter(
    "passes.ops_fused", "Op patterns collapsed into fused ops, labeled by "
    "pass.", labelnames=("pass",))
_m_pipeline_ms = _monitor.histogram(
    "passes.pipeline_ms", "Wall-clock of one pipeline application "
    "(clone + passes + verification).")
_m_quant_ops = _monitor.counter(
    "quant.ops_rewritten", "float ops rewritten to int8 quant ops by the "
    "quant_infer pass, labeled by the original op type.",
    labelnames=("op",))


@dataclass
class PassReport:
    name: str
    changed: bool
    ops_before: int
    ops_after: int
    stats: Dict[str, Any] = field(default_factory=dict)


@dataclass
class PipelineReport:
    passes: List[PassReport] = field(default_factory=list)
    ops_before: int = 0
    ops_after: int = 0
    elapsed_ms: float = 0.0
    skipped: Optional[str] = None
    fingerprint: str = ""

    @property
    def changed(self) -> bool:
        return any(p.changed for p in self.passes)

    def to_text(self) -> str:
        if self.skipped:
            return f"pipeline skipped: {self.skipped}"
        lines = [f"pipeline {self.fingerprint}: "
                 f"{self.ops_before} -> {self.ops_after} ops "
                 f"({self.elapsed_ms:.1f} ms)"]
        for p in self.passes:
            extra = ", ".join(f"{k}={v}" for k, v in p.stats.items()
                              if k != "changed" and v)
            lines.append(f"  {p.name:<22} {p.ops_before:>4} -> "
                         f"{p.ops_after:<4}{'  ' + extra if extra else ''}")
        return "\n".join(lines)


class PassManager:
    """Apply a named pass pipeline under the VerifiedRewrite contract.

    ``apply`` never mutates its argument: it clones, stamps PRNG salts,
    rewrites the clone, proves the fetch interface held (PV011), re-runs
    the full program verifier, and only then returns the rewritten
    program.  Any violation raises ``ProgramVerificationError``."""

    def __init__(self, passes: Sequence[str] = DEFAULT_PIPELINE):
        unknown = [p for p in passes if p not in _REGISTRY]
        if unknown:
            raise ValueError(
                f"unknown pass(es) {unknown}; available: "
                f"{available_passes()}")
        self.pass_names = tuple(passes)

    def fingerprint(self) -> str:
        """Human-readable pipeline identity; joins the compile-cache key so
        optimized and unoptimized artifacts never collide."""
        return f"v{_PASSES_SCHEMA}:" + "+".join(self.pass_names)

    def apply(self, program: Program,
              feed_names: Optional[Sequence[str]] = None,
              fetch_names: Optional[Sequence[str]] = None
              ) -> Tuple[Program, PipelineReport]:
        t0 = time.perf_counter()
        report = PipelineReport(fingerprint=self.fingerprint())
        report.ops_before = sum(len(b.ops) for b in program.blocks)
        if len(program.blocks) > 1:
            # Program.clone is block-0 only and sub-block rewrites would
            # need cross-block dataflow — control-flow programs run as-is
            report.skipped = "program has sub-blocks"
            report.ops_after = report.ops_before
            return program, report
        _m_runs.inc()
        fetches = tuple(fetch_names or ())
        ctx = PassContext(feed_names=set(feed_names or ()),
                          fetch_names=fetches)
        before = _interface_snapshot(program, ctx.feed_names, fetches)
        work = program.clone()
        _stamp_rng_salts(work)
        for name in self.pass_names:
            p = _REGISTRY[name]
            n0 = len(work.global_block().ops)
            with _trace.span(f"passes::{name}"):
                stats = p.run(work, ctx)
            n1 = len(work.global_block().ops)
            report.passes.append(PassReport(
                name, bool(stats.get("changed")), n0, n1, stats))
            if n0 > n1:
                _m_ops_removed.inc(n0 - n1, **{"pass": name})
            if stats.get("fused"):
                _m_ops_fused.inc(stats["fused"], **{"pass": name})
        report.ops_after = len(work.global_block().ops)
        after = _interface_snapshot(work, ctx.feed_names, fetches)
        diags = _verify_interface(before, after)
        if diags:
            raise _errors.ProgramVerificationError(
                "graph-rewrite verification failed (PV011):\n"
                + _errors.render_diagnostics(diags), diagnostics=diags)
        # the rewritten program must satisfy the full PV001-PV010 walker
        check_program(work, feed_names=sorted(ctx.feed_names) or None,
                      fetch_names=fetches or None)
        report.elapsed_ms = (time.perf_counter() - t0) * 1000.0
        _m_pipeline_ms.observe(report.elapsed_ms)
        _trace.flight_recorder().record(
            "opt_passes", name=self.fingerprint(),
            ops_before=report.ops_before, ops_after=report.ops_after,
            changed=report.changed)
        return work, report


def pipeline_from_flag(value) -> Optional[PassManager]:
    """Parse the ``opt_passes`` flag: "" -> off; "1"/"true"/"default" ->
    the default pipeline; a comma list -> exactly those passes."""
    if not value:
        return None
    text = str(value).strip()
    if text.lower() in ("1", "true", "default", "on"):
        return PassManager(DEFAULT_PIPELINE)
    return PassManager(tuple(s.strip() for s in text.split(",") if s.strip()))


def optimize_for_executor(program: Program, flag_value,
                          feed_names, fetch_names,
                          plan=None, feed_arrays=None
                          ) -> Tuple[Program, str]:
    """Executor compile-path entry: returns (program to trace, pipeline
    fingerprint for the compile-cache key).  Prod-safe: any verification
    failure rolls back to the original program and records why — the step
    still compiles, just unoptimized."""
    pm = pipeline_from_flag(flag_value)
    if pm is None:
        return program, ""
    try:
        work, report = pm.apply(program, feed_names, fetch_names)
        if report.skipped:
            return program, ""
        if plan is not None and feed_arrays is not None:
            from ..core import flags as _flags

            if _flags.get_flag("check_sharding"):
                from .shardcheck import check_with_plan

                check_with_plan(work, plan, feed_arrays)
        return work, pm.fingerprint()
    except Exception as e:  # noqa: BLE001 — rollback is the contract
        _m_rollbacks.inc()
        _trace.flight_recorder().record(
            "opt_passes_rollback", name=pm.fingerprint(), error=repr(e))
        return program, ""


# ---------------------------------------------------------------------------
# Golden-parity harness: execute original vs rewritten, compare bits.
# ---------------------------------------------------------------------------

@dataclass
class ParityReport:
    ok: bool
    max_abs_err: float
    per_fetch: Dict[str, float]
    state_max_err: float
    message: str = ""

    def to_text(self) -> str:
        verdict = "PARITY OK" if self.ok else "PARITY FAILED"
        per = ", ".join(f"{k}={v:.3g}" for k, v in self.per_fetch.items())
        return (f"{verdict}: max|err|={self.max_abs_err:.3g} "
                f"(state {self.state_max_err:.3g}) [{per}]"
                + (f" — {self.message}" if self.message else ""))


def golden_parity(original: Program, rewritten: Program, feed: Dict,
                  fetch_names: Sequence[str],
                  state: Optional[Dict[str, Any]] = None,
                  rtol: float = 1e-5, atol: float = 1e-6) -> ParityReport:
    """Run both programs from identical state and compare: bitwise equal
    for integer/bool fetches, ``rtol/atol`` for floats; final persistable
    state is compared too (a fused op must not silently stop a state
    write-back the original performed meaningfully)."""
    from .executor import Executor, Scope

    def run(prog):
        scope = Scope()
        for k, v in (state or {}).items():
            scope.set(k, np.array(v, copy=True))
        exe = Executor()
        outs = exe.run(prog, feed={k: np.asarray(v) for k, v in feed.items()},
                       fetch_list=list(fetch_names), scope=scope,
                       return_numpy=True)
        final = {k: np.asarray(scope.find_var(k)) for k in (state or {})}
        return outs, final

    outs0, state0 = run(original)
    outs1, state1 = run(rewritten)
    per_fetch: Dict[str, float] = {}
    ok = True
    msg = ""
    max_err = 0.0
    for name, a, b in zip(fetch_names, outs0, outs1):
        a, b = np.asarray(a), np.asarray(b)
        if a.shape != b.shape or a.dtype != b.dtype:
            ok, msg = False, (f"fetch {name!r}: {a.dtype}{a.shape} vs "
                              f"{b.dtype}{b.shape}")
            per_fetch[name] = float("inf")
            continue
        if a.dtype.kind in ("i", "u", "b"):
            err = float(np.max(np.abs(a.astype(np.int64)
                                      - b.astype(np.int64)))) if a.size \
                else 0.0
            if err != 0.0:
                ok, msg = False, f"integer fetch {name!r} differs"
        else:
            err = float(np.max(np.abs(a.astype(np.float64)
                                      - b.astype(np.float64)))) if a.size \
                else 0.0
            if not np.allclose(a.astype(np.float64), b.astype(np.float64),
                               rtol=rtol, atol=atol):
                ok, msg = False, f"float fetch {name!r} out of tolerance"
        per_fetch[name] = err
        max_err = max(max_err, err)
    state_err = 0.0
    for k in state0:
        a, b = state0[k], state1.get(k)
        if b is None or a.shape != b.shape:
            ok, msg = False, f"state {k!r} shape/presence diverged"
            state_err = float("inf")
            continue
        if a.dtype.kind in ("i", "u", "b"):
            e = float(np.max(np.abs(a.astype(np.int64)
                                    - b.astype(np.int64)))) if a.size else 0.0
            if e != 0.0:
                ok, msg = False, f"integer state {k!r} differs"
        else:
            e = float(np.max(np.abs(a.astype(np.float64)
                                    - b.astype(np.float64)))) if a.size \
                else 0.0
            if not np.allclose(a.astype(np.float64), b.astype(np.float64),
                               rtol=rtol, atol=atol):
                ok, msg = False, f"float state {k!r} out of tolerance"
        state_err = max(state_err, e)
    return ParityReport(ok, max_err, per_fetch, state_err, msg)
