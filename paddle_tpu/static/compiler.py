"""CompiledProgram: multi-device execution of static Programs.

Reference parity: `CompiledProgram` / `with_data_parallel`
(python/paddle/fluid/compiler.py:87/:160), which wraps ParallelExecutor —
the multi-device SSA graph builder clones the graph per device and inserts
per-gradient allreduce op-handles
(paddle/fluid/framework/ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:175,
:464 CreateAllReduceOp).

TPU-native design: none of that machinery survives — the Executor already
lowers the whole Program to ONE XLA computation, so data parallelism is
purely a *sharding* decision: jit the same computation over a 1-axis device
mesh with feed arrays sharded on their batch (leading) dimension and every
persistable replicated.  GSPMD then partitions the forward, and the
gradient summation that `append_backward`'s replay produces against
replicated parameters lowers to the same all-reduce the reference inserted
by hand.  Fetches come back replicated (a mean loss equals the
single-device full-batch loss — the reference's TestDistBase parity
contract).
"""
from __future__ import annotations

from typing import Optional, Sequence

from .framework import Program

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """ref framework/details/build_strategy.h:58.  The SSA-graph knobs
    (reduce strategy, fusion, hierarchical allreduce) are XLA/GSPMD's job
    now; the class exists for API parity and records its fields."""

    def __init__(self):
        self.reduce_strategy = "AllReduce"
        self.gradient_scale_strategy = "CoeffNumDevice"
        self.fuse_all_reduce_ops = True  # GSPMD always effectively fuses
        self.memory_optimize = True      # XLA buffer assignment
        self.enable_inplace = True


class ExecutionStrategy:
    """ref framework/details/execution_strategy.h — thread-pool sizing for
    the SSA executors; meaningless under one fused XLA program."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1


class CompiledProgram:
    """ref fluid/compiler.py:87.

    Usage (same shape as the reference)::

        compiled = static.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        exe.run(compiled, feed={...}, fetch_list=[loss])

    The feed carries the GLOBAL batch; it is split evenly across devices
    (reference: with_data_parallel feed splitting, fluid/executor.py:855
    _run_parallel).  Batch dims must divide the device count.
    """

    def __init__(self, program: Program, build_strategy: Optional[BuildStrategy] = None):
        if not isinstance(program, Program):
            raise TypeError(
                f"CompiledProgram wraps a static.Program, got {type(program)}")
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._data_parallel = False
        self._loss_name: Optional[str] = None
        self._places: Optional[Sequence] = None
        self._plan = None  # parallel.sharding.ShardingPlan, built lazily
        self._auto_shard = False   # plan="auto": resolve via autoplan
        self._auto_mesh = None

    def with_data_parallel(self, loss_name: Optional[str] = None,
                           build_strategy: Optional[BuildStrategy] = None,
                           exec_strategy: Optional[ExecutionStrategy] = None,
                           places: Optional[Sequence] = None) -> "CompiledProgram":
        """ref fluid/compiler.py:160.  `places` defaults to every local
        device (the reference's CUDAPlace list ≈ jax.devices())."""
        self._data_parallel = True
        self._loss_name = loss_name
        self._places = places
        if build_strategy is not None:
            self._build_strategy = build_strategy
        return self

    def with_sharding(self, mesh=None, rules=None, annotations=None,
                      zero_stage: int = 0, batch_axes=None, seq_axis=None,
                      donate: bool = True, comm_quantize: str = "",
                      comm_block_size: int = 256,
                      comm_buffer_mb: float = 25.0,
                      comm_hierarchy="auto",
                      embedding_shard=None,
                      embedding_capacity=None,
                      embedding_quantize: str = "",
                      plan=None) -> "CompiledProgram":
        """Run this program's compiled step under NamedShardings on a mesh —
        the full hybrid-parallel face of the Executor fast path.

        Unlike ``with_data_parallel`` (replicated state, place-once, no
        donation), a sharded plan keeps the *sharded* persistable pytree
        device-resident shard-by-shard across steps and donates it into the
        compiled step (``donate=True`` default; platform-gated like the
        single-device path), so multi-chip steady state pays the same
        near-zero host rim PR 4's fast path bought single-chip.  ``mesh``
        defaults to the process mesh (`parallel.mesh.current_mesh`);
        ``rules``/``annotations``/``zero_stage`` follow
        `parallel.sharding.infer_sharding` precedence for state placement;
        ``batch_axes``/``seq_axis`` shard the feeds (defaults: batch over
        ``dp``).

        ``comm_quantize``/``comm_block_size``/``comm_buffer_mb``/
        ``comm_hierarchy`` make gradient-communication options ambient while
        the step is traced (parallel/compress.py `comm_scope`): axis-bound
        collectives inside the program pick up quantized payloads and
        hierarchical scheduling, and the options key the persistent compile
        cache through the plan fingerprint.

        ``embedding_shard`` (an axis name, or {table-name-regex: axis})
        vocab-shards every covered ``lookup_table`` table over that mesh
        axis and routes its lookups through the dedup + all_to_all
        exchange (parallel/embedding.py); ``embedding_capacity`` /
        ``embedding_quantize`` tune the exchange buffers and the backward
        wire payload.

        ``plan`` short-circuits all of the above: a ready
        ``ShardingPlan`` instance runs as-is, and the string ``"auto"``
        defers to the cost-model search (parallel/autoplan.py) — the plan
        is chosen at first run (memoized by program x mesh fingerprints,
        so repeat programs and restarted processes re-derive the same
        choice and keep their compile-cache warm starts); ``mesh`` then
        names the device set to search over (default: the process
        mesh/every local device)."""
        from ..parallel import mesh as _pmesh
        from ..parallel.sharding import ShardingPlan

        if plan is not None:
            if isinstance(plan, ShardingPlan):
                self._plan = plan
                return self
            if plan == "auto":
                self._plan = None
                self._auto_shard = True
                self._auto_mesh = mesh
                return self
            raise ValueError(
                f"plan={plan!r}: expected a ShardingPlan or 'auto'")
        self._plan = ShardingPlan(
            mesh=mesh, rules=rules, annotations=annotations,
            zero_stage=zero_stage,
            batch_axes=tuple(batch_axes) if batch_axes else (_pmesh.DP_AXIS,),
            seq_axis=seq_axis, donate=donate, comm_quantize=comm_quantize,
            comm_block_size=comm_block_size, comm_buffer_mb=comm_buffer_mb,
            comm_hierarchy=comm_hierarchy, embedding_shard=embedding_shard,
            embedding_capacity=embedding_capacity,
            embedding_quantize=embedding_quantize)
        return self

    def _sharding_plan(self, feed=None, fetch_list=None):
        """The plan the Executor runs under (lazy: with_data_parallel only
        commits to a device list at first run, like the reference's deferred
        ParallelExecutor construction; plan="auto" commits at first run so
        the search prices the real feed shapes).  None = single-device
        path."""
        if self._plan is None and self._auto_shard:
            from ..parallel import autoplan as _autoplan
            from .framework import Variable

            fetch_names = tuple(
                v.name if isinstance(v, Variable) else str(v)
                for v in (fetch_list or ()))
            self._plan = _autoplan.resolve_auto(
                self._program, mesh=self._auto_mesh, feed=feed,
                fetch_names=fetch_names)
        if self._plan is None and self._data_parallel:
            devices = self._devices()
            if len(devices) > 1:
                from ..parallel.sharding import ShardingPlan

                # replicated state + batch-sharded feeds, and NO donation:
                # the DP place-once contract pins buffer identity across
                # steps (tests/test_static_dp.py)
                self._plan = ShardingPlan(devices=devices, donate=False)
        return self._plan

    @property
    def program(self) -> Program:
        return self._program

    def _devices(self):
        import jax

        if self._places is None:
            return list(jax.devices())
        devs = []
        for p in self._places:
            # accept jax.Device, Place-like with .device, or int index
            if hasattr(p, "device_kind"):
                devs.append(p)
            elif hasattr(p, "device"):
                devs.append(p.device)
            elif isinstance(p, int):
                devs.append(jax.devices()[p])
            else:
                raise TypeError(f"unsupported place {p!r}")
        return devs
