"""Program verifier: static analysis over Program/Block/Operator IR.

Reference parity: the reference runs an entire pass ecosystem over
ProgramDesc before execution — `framework/ir/` graph passes,
`inference/analysis/` (analyzer.cc → ir_pass_manager.cc), and every
`PADDLE_ENFORCE*` site in `platform/enforce.h` carrying a typed error code.
Our TPU-native Executor traces a Program straight into jax.jit, so a
malformed program used to surface as an opaque JAX tracer error deep inside
a lowering rule.  This module is the missing compilation stage: it walks
every Block (descending through ``SUB_BLOCK_ATTRS``) *before any tracing*
and reports structured diagnostics.

Checks (diagnostic codes):

- ``PV001`` dataflow: an op input is not produced by an earlier op, a feed,
  a persistable, or a parameter (the trace would KeyError in the env dict).
- ``PV002`` dataflow (warning): a non-persistable temporary is written but
  never read or fetched — it silently inflates the trace.
- ``PV003`` registry: op type has no registered lowering and no DESCOPED
  rationale; a difflib nearest-name suggestion is attached.
- ``PV004`` registry: op type is DESCOPED (rationale attached) — it can
  never lower here.
- ``PV005`` structure: a sub-block index is out of range / not an int, or a
  known control-flow op is missing its block attr.
- ``PV006`` structure: an op carries a block-reference attr that is NOT in
  ``SUB_BLOCK_ATTRS`` — dataflow walkers (backward._effective_io, the
  Executor's _first_access scan) would go blind to reads inside its body
  (the hazard documented at framework.SUB_BLOCK_ATTRS).
- ``PV007`` structure: a ``@GRAD`` variable has no primal counterpart.
- ``PV008`` structure: a persistable read by the main program is never
  initialized by the startup program (only checked when a startup program
  is supplied).
- ``PV009`` shape/dtype: a per-op-type inference table propagates shapes
  through the block and flags statically-certain rank/dim/dtype
  mismatches (-1 / unknown dims are wildcards — never flagged).
- ``PV010`` shape/dtype (warning): the symbolic engine's inferred output
  shape contradicts the variable's *declared* shape — the declaration is
  stale or wrong (the trace would still succeed; downstream PV009 checks
  run on the inferred shape, not the stale declaration).
- ``PV011`` rewrite safety (emitted by static/passes.py, not by this
  walker): a graph-rewrite pass broke the fetch-reachable interface — a
  fetch vanished or its inferred shape/dtype changed between the
  ``infer_program`` snapshots taken before and after the rewrite.  The
  pass manager raises ``ProgramVerificationError`` carrying these.

The PV009 table is fed by a forward **symbolic inference engine**
(``_ShapeEnv``): every ``-1``/undeclared dim becomes a stable symbol
(``Sym``), op-type rules in ``_INFER_RULES`` propagate shapes and dtypes
through blocks and sub-blocks (with env snapshot/restore around each
descent, mirroring executor._lower_cond/_lower_while), and ``@GRAD``
outputs of ``backward_region`` inherit their primal's shape/dtype.  That
means a wildcard batch dim flows through a conv→pool→reshape→matmul chain
and a *concrete* mismatch five ops downstream is still caught.  Sub-block
output clashes (cond branches with different inferred shapes, while
carries not shape-invariant against the body) are recorded on the engine
(``subblock_findings``) for the sharding-plan verifier
(``static/shardcheck.py``, diagnostic SC006) rather than emitted here —
``verify_program``'s own diagnostic surface is unchanged.
``shape_rule_coverage()`` reports which registered ops the engine covers.

Severity ``error`` aborts ``Executor.run`` (flag ``check_program``, default
on; ``PDTPU_FLAGS_check_program=0`` or ``set_flags({"check_program":
False})`` to skip); ``warning`` never does.  Diagnostics render through
``core.errors.render_diagnostics`` and raise
``core.errors.ProgramVerificationError``.

``check_program_cached`` is the Executor entry point: it memoizes the
(warning-only) result by program version × feed/fetch signature on the
Program object itself, so serving buckets and repeated cold runs re-walk
nothing, and logs every program that passed so the test suite's conftest
can re-assert zero errors at session end.  Counters:
``analysis.programs_checked`` (actual walks) and
``analysis.violations{code=...}``.
"""
from __future__ import annotations

import itertools
import threading
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

import numpy as np

from ..core import errors as _errors
from ..utils import monitor as _monitor
from .backward import GRAD_SUFFIX
from .framework import SUB_BLOCK_ATTRS, Parameter, Program

__all__ = ["Diagnostic", "Sym", "verify_program", "check_program",
           "check_program_cached", "infer_program", "shape_rule_coverage"]

_m_programs_checked = _monitor.counter(
    "analysis.programs_checked",
    "Full verifier walks (cache misses of check_program_cached plus every "
    "direct verify_program call).")
_m_violations = _monitor.counter(
    "analysis.violations",
    "Diagnostics found by the program verifier, by code.",
    labelnames=("code",))


# Op types realized by the Executor itself (trace-time dispatch in
# executor._trace_ops) — they have no registry entry by design.
EXECUTOR_OPS = frozenset({
    "feed", "fetch", "backward_region", "conditional_block", "while",
    "static_rnn",
})

# Control-flow ops and the SUB_BLOCK_ATTRS attrs each must carry, plus the
# names their lowering injects into the sub-block env before tracing it
# (executor._lower_cond/_lower_while/_lower_static_rnn).
_BLOCK_OP_REQUIRED_ATTRS = {
    "conditional_block": ("true_block", "false_block"),
    "while": ("cond_block", "body_block"),
    "static_rnn": ("rnn_block",),
}

# Attrs whose values are *variable names read by the executor's lowering*
# (branch outputs, loop carries...) — they count as reads for PV002.
_NAME_LIST_ATTRS = ("true_outs", "false_outs", "body_outs", "mem_next",
                    "out_names")
_NAME_ATTRS = ("cond_out",)

# After walking a sub-block, these are the names whose inferred shapes the
# engine captures (the values the executor's lowering returns out of the
# traced sub-env): branch outputs, the while condition/carries, RNN slots.
_RECORD_ATTRS = {
    "true_block": ("true_outs",),
    "false_block": ("false_outs",),
    "cond_block": ("cond_out",),
    "body_block": ("body_outs",),
    "rnn_block": ("out_names", "mem_next"),
}


@dataclass
class Diagnostic:
    """One structured finding (code, severity, location, fix-hint)."""

    code: str
    severity: str                 # "error" | "warning"
    message: str
    block: int = 0
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None

    def __str__(self):
        return _errors.render_diagnostics([self])


# ---------------------------------------------------------------------------
# Symbolic dimensions.  A shape in the engine is a tuple whose entries are
# non-negative ints (known) or Sym objects (unknown-but-tracked: the same
# -1 dim of the same variable is the same Sym everywhere it flows, so
# "batch" stays one symbol through an arbitrarily long chain).  None means
# "shape entirely unknown" (the IR's undeclared `()`).
# ---------------------------------------------------------------------------

class Sym:
    """One unknown dimension.  Identity is equality: two Syms compare equal
    only when they are the same object, so unification is pointer-cheap."""

    __slots__ = ("id", "origin")
    _ids = itertools.count()

    def __init__(self, origin: str = ""):
        self.id = next(Sym._ids)
        self.origin = origin

    def __repr__(self):
        return f"s{self.id}" + (f"<{self.origin}>" if self.origin else "")


Dim = Union[int, Sym]
SymShape = Optional[Tuple[Dim, ...]]


def _known(d) -> bool:
    """True for a concrete, usable dimension (non-bool int >= 0)."""
    return (isinstance(d, (int, np.integer)) and not isinstance(d, bool)
            and int(d) >= 0)


def _legacy(shape: SymShape):
    """Engine shape → the legacy checker form (ints with -1 wildcards)."""
    if shape is None:
        return None
    return tuple(int(d) if _known(d) else -1 for d in shape)


def _dims_equal(a: Dim, b: Dim) -> bool:
    if _known(a) and _known(b):
        return int(a) == int(b)
    return a is b


class _ShapeEnv:
    """Flat name→(shape, dtype) environment mirroring the executor's trace
    env (one dict, sub-blocks snapshot/restore around descent).  Falls back
    to the *declared* Variable shape with -1 dims memoized into per-(name,
    dim) symbols, so the engine degrades gracefully to exactly the old
    declared-shape behavior for any op it has no rule for."""

    def __init__(self, program: Program):
        self.program = program
        self.shapes: Dict[str, SymShape] = {}
        self.dtypes: Dict[str, Optional[np.dtype]] = {}
        self._sym_memo: Dict[Tuple[str, int], Sym] = {}
        # control-flow consistency findings for shardcheck (SC006); never
        # emitted by verify_program itself
        self.subblock_findings: List[Diagnostic] = []
        # (id(op), attr) -> [(name, shape, dtype)] captured at sub-block end
        self.records: Dict[Tuple[int, str], List[tuple]] = {}

    # -- lookups -------------------------------------------------------------
    def _declared_shape(self, block, name) -> SymShape:
        try:
            v = block.var(name)
        except KeyError:
            return None
        s = tuple(v.shape)
        if not s:
            return None                 # () is "undeclared" in this IR
        return tuple(int(d) if _known(d) else self._sym(name, i)
                     for i, d in enumerate(s))

    def _declared_dtype(self, block, name) -> Optional[np.dtype]:
        try:
            return np.dtype(block.var(name).dtype)
        except (KeyError, TypeError):
            return None

    def _sym(self, name: str, i: int) -> Sym:
        key = (name, i)
        s = self._sym_memo.get(key)
        if s is None:
            s = self._sym_memo[key] = Sym(f"{name}[{i}]")
        return s

    def shape_of(self, block, name: str) -> SymShape:
        if name in self.shapes:
            return self.shapes[name]
        return self._declared_shape(block, name)

    def dtype_of(self, block, name: str) -> Optional[np.dtype]:
        if name in self.dtypes:
            return self.dtypes[name]
        return self._declared_dtype(block, name)

    # -- mutation ------------------------------------------------------------
    def bind(self, name: str, shape: SymShape, dtype: Optional[np.dtype]):
        self.shapes[name] = shape
        self.dtypes[name] = dtype

    def bind_declared(self, block, name: str):
        self.bind(name, self._declared_shape(block, name),
                  self._declared_dtype(block, name))

    def snapshot(self):
        return dict(self.shapes), dict(self.dtypes)

    def restore(self, snap):
        self.shapes, self.dtypes = snap

    def inject(self, names, block):
        """Bind sub-block-scoped names (loop memories, step inputs) to their
        declared shapes in `block`, shadowing any outer binding."""
        for n in names:
            self.bind_declared(block, n)

    def capture(self, op, attr: str, block):
        """Record the inferred (shape, dtype) of each name the executor's
        lowering reads back out of this sub-block's env."""
        rec = []
        for src in _RECORD_ATTRS.get(attr, ()):
            val = op.attrs.get(src)
            names = [val] if isinstance(val, str) else list(val or ())
            for n in names:
                if isinstance(n, str):
                    rec.append((n, self.shape_of(block, n),
                                self.dtype_of(block, n)))
        self.records[(id(op), attr)] = rec


class _Verifier:
    def __init__(self, program: Program, startup: Optional[Program],
                 feed_names: Optional[Sequence[str]],
                 fetch_names: Optional[Sequence[str]]):
        self.program = program
        self.startup = startup
        # feed_names=None means "verifying without a concrete run": any
        # is_data var is assumed feedable.  A concrete feed dict narrows
        # that to the names actually fed.
        self.feed_names = None if feed_names is None else set(feed_names)
        self.fetch_names = set(fetch_names or ())
        self.diags: List[Diagnostic] = []
        self.reads: Set[str] = set()
        self.writes: Dict[str, Tuple[int, int, str]] = {}  # name -> site
        self.engine = _ShapeEnv(program)
        self._op_flagged = False        # PV009 fired for the current op

    # -- reporting -----------------------------------------------------------
    def _emit(self, code, severity, message, block=0, op_index=None,
              op_type=None, var=None, hint=None):
        self.diags.append(Diagnostic(code, severity, message, block,
                                     op_index, op_type, var, hint))

    # -- entry ---------------------------------------------------------------
    def run(self) -> List[Diagnostic]:
        self._check_grad_pairing()
        if self.startup is not None:
            self._check_startup_init()
        defined = self._initial_defined(self.program.global_block())
        self._walk_block(0, defined, set())
        self._check_dead_temps()
        return self.diags

    # -- initial environment -------------------------------------------------
    def _initial_defined(self, block) -> Set[str]:
        """Names bound into the env before any op runs: feeds + persistable
        state (executor.run seeds env from `state` then `feeds`)."""
        defined = set()
        for v in self.program.list_vars():
            if v.persistable or isinstance(v, Parameter):
                defined.add(v.name)
            elif v.is_data:
                if self.feed_names is None or v.name in self.feed_names:
                    defined.add(v.name)
        if self.feed_names:
            defined |= self.feed_names
        return defined

    # -- block walk ----------------------------------------------------------
    def _walk_block(self, block_idx: int, defined: Set[str],
                    visiting: Set[int]) -> Set[str]:
        """Walk one block in execution order, growing `defined`; returns the
        defined-set after the last op (used for sub-block out checks)."""
        if block_idx in visiting:        # cyclic sub-block reference
            return defined
        visiting = visiting | {block_idx}
        block = self.program.blocks[block_idx]
        for op_idx, op in enumerate(block.ops):
            self._check_registry(block_idx, op_idx, op)
            self._check_structure(block_idx, op_idx, op)
            if op.type in ("feed", "fetch"):
                # executor skips these; feed outputs are env-bound by name
                for name in op.output_names():
                    defined.add(name)
                    self.engine.bind_declared(block, name)
                continue
            # dataflow: every input must already be defined
            for name in op.input_names():
                self.reads.add(name)
                if name not in defined:
                    self._emit(
                        "PV001", "error",
                        f"op {op.type!r} reads {name!r} which is not "
                        "produced by any earlier op, feed, persistable, or "
                        "parameter",
                        block_idx, op_idx, op.type, name,
                        hint=self._pv001_hint(block, name))
            for attr in _NAME_LIST_ATTRS:
                for name in op.attrs.get(attr, ()) or ():
                    if isinstance(name, str):
                        self.reads.add(name)
            for attr in _NAME_ATTRS:
                name = op.attrs.get(attr)
                if isinstance(name, str):
                    self.reads.add(name)
            # descend into sub-blocks with the defined-set AT this op (the
            # lowering snapshots the env here: executor._arrays_only)
            for attr, sub_idx in self._sub_blocks(op):
                if not self._valid_block_idx(sub_idx):
                    continue            # PV005 already emitted
                injected = self._injected_names(op, attr)
                sub_defined = set(defined) | injected
                sub_block = self.program.blocks[int(sub_idx)]
                snap = self.engine.snapshot()
                if op.type != "while":
                    # while carries keep their (possibly more concrete)
                    # outer bindings — the executor passes the env values
                    # of X straight into the body trace
                    self.engine.inject(injected, sub_block)
                self._walk_block(int(sub_idx), sub_defined, visiting)
                self.engine.capture(op, attr, sub_block)
                self.engine.restore(snap)
            self._op_flagged = False
            self._check_shapes(block_idx, op_idx, op)
            self._infer_op(block_idx, op_idx, op)
            for name in op.output_names():
                defined.add(name)
                self.writes.setdefault(name, (block_idx, op_idx, op.type))
        return defined

    def _pv001_hint(self, block, name) -> str:
        if not block.has_var(name):
            return (f"{name!r} is not declared in block {block.idx} or any "
                    "ancestor — check the op's input names")
        v = block.var(name)
        if v.is_data:
            return (f"{name!r} is a data var but was not fed — add it to "
                    "the feed dict")
        return (f"declare {name!r} persistable, feed it, or reorder the "
                "producing op before this one")

    @staticmethod
    def _sub_blocks(op):
        return op.sub_block_indices()

    def _valid_block_idx(self, idx) -> bool:
        return (isinstance(idx, (int, np.integer))
                and not isinstance(idx, bool)
                and 0 <= int(idx) < len(self.program.blocks))

    def _injected_names(self, op, attr) -> Set[str]:
        """Names the executor binds into a sub-block env before tracing it."""
        if op.type == "while":
            return set(op.inputs.get("X", ()))
        if op.type == "static_rnn":
            return (set(op.attrs.get("mem_names", ()))
                    | set(op.attrs.get("step_in_names", ())))
        return set()

    # -- registry soundness --------------------------------------------------
    def _check_registry(self, block_idx, op_idx, op):
        from . import ops as _ops  # noqa: F401 — populate the registry
        from .op_coverage import DESCOPED
        from .registry import is_registered, suggest_names

        if op.type in EXECUTOR_OPS or is_registered(op.type):
            return
        if op.type in DESCOPED:
            self._emit(
                "PV004", "error",
                f"op type {op.type!r} is descoped and can never lower here",
                block_idx, op_idx, op.type,
                hint=f"rationale: {DESCOPED[op.type]}")
            return
        suggestion = suggest_names(op.type)
        self._emit(
            "PV003", "error",
            f"op type {op.type!r} has no registered lowering",
            block_idx, op_idx, op.type,
            hint=suggestion or "register one with static.register_op")

    # -- structural soundness ------------------------------------------------
    def _check_structure(self, block_idx, op_idx, op):
        n_blocks = len(self.program.blocks)
        for attr in _BLOCK_OP_REQUIRED_ATTRS.get(op.type, ()):
            if attr not in op.attrs:
                self._emit(
                    "PV005", "error",
                    f"control-flow op {op.type!r} is missing its "
                    f"{attr!r} sub-block attr",
                    block_idx, op_idx, op.type,
                    hint="build it through static.cond/while_loop/StaticRNN")
        for attr, sub_idx in self._sub_blocks(op):
            if not self._valid_block_idx(sub_idx):
                self._emit(
                    "PV005", "error",
                    f"op {op.type!r} attr {attr!r} references block "
                    f"{sub_idx!r} but the program has {n_blocks} blocks",
                    block_idx, op_idx, op.type,
                    hint="sub-block attrs hold an index into program.blocks")
        # block-reference attrs the walkers cannot see (the framework.py
        # "walkers go blind" hazard): an int attr named *_block outside
        # SUB_BLOCK_ATTRS almost certainly references a block
        for attr, value in op.attrs.items():
            if (attr.endswith("_block") and attr not in SUB_BLOCK_ATTRS
                    and isinstance(value, (int, np.integer))
                    and not isinstance(value, bool)):
                self._emit(
                    "PV006", "error",
                    f"op {op.type!r} attr {attr!r} looks like a sub-block "
                    "reference but is not listed in "
                    "framework.SUB_BLOCK_ATTRS — dataflow walkers will not "
                    "descend into that block",
                    block_idx, op_idx, op.type,
                    hint="add the attr name to framework.SUB_BLOCK_ATTRS")

    # -- grad pairing --------------------------------------------------------
    def _check_grad_pairing(self):
        # program-wide primal pool: append_backward puts param grads in
        # block 0 even when the primal was created inside a sub-block
        # (StaticRNN parameters), so block-scoped lookup would false-flag
        all_names = {n for b in self.program.blocks for n in b.vars}
        for block in self.program.blocks:
            for name, v in block.vars.items():
                if not name.endswith(GRAD_SUFFIX):
                    continue
                primal = name[: -len(GRAD_SUFFIX)]
                if not block.has_var(primal) and primal not in all_names:
                    self._emit(
                        "PV007", "error",
                        f"grad var {name!r} has no primal {primal!r} "
                        "anywhere in the program",
                        block.idx, var=name,
                        hint="grad vars are created by append_backward/"
                             "gradients next to their primal")

    # -- startup coverage ----------------------------------------------------
    def _check_startup_init(self):
        initialized = set()
        for block in self.startup.blocks:
            for op in block.ops:
                initialized |= set(op.output_names())
        # a persistable the main program READS before any main-program op
        # writes it must come from startup (executor._needs_value semantics)
        for v in self.program.list_vars():
            if not v.persistable or v.name in initialized:
                continue
            if self._first_access(self.program.global_block(), v.name) == "read":
                self._emit(
                    "PV008", "error",
                    f"persistable {v.name!r} is read by the main program "
                    "but never initialized by the startup program",
                    var=v.name,
                    hint="append an init op for it to the startup program "
                         "(layers.create_parameter does this automatically)")

    def _first_access(self, block, name):
        for op in block.ops:
            if name in op.input_names():
                return "read"
            for _attr, sub_idx in self._sub_blocks(op):
                if self._valid_block_idx(sub_idx):
                    sub = self._first_access(self.program.blocks[sub_idx],
                                             name)
                    if sub == "read":
                        return "read"
            if name in op.output_names():
                return "write"
        return None

    # -- dead temporaries ----------------------------------------------------
    def _check_dead_temps(self):
        for name, (block_idx, op_idx, op_type) in self.writes.items():
            if name in self.reads or name in self.fetch_names:
                continue
            block = self.program.blocks[block_idx]
            try:
                v = block.var(name)
            except KeyError:
                v = None
            if v is not None and (v.persistable or v.is_data):
                continue
            self._emit(
                "PV002", "warning",
                f"temporary {name!r} (written by op {op_type!r}) is never "
                "read or fetched — it inflates the trace for nothing",
                block_idx, op_idx, op_type, name,
                hint="drop the op or fetch the value")

    # -- shape / dtype plausibility ------------------------------------------
    def _var_shape(self, block, name) -> Optional[Tuple[int, ...]]:
        try:
            v = block.var(name)
        except KeyError:
            return None
        shape = tuple(v.shape)
        return shape if shape else None   # () is "undeclared" in this IR

    def _var_dtype(self, block, name):
        try:
            return np.dtype(block.var(name).dtype)
        except KeyError:
            return None

    def _check_shapes(self, block_idx, op_idx, op):
        checker = _SHAPE_CHECKERS.get(op.type)
        if checker is None:
            return
        block = self.program.blocks[block_idx]

        # the legacy table consumes (ints, -1 wildcards) — feed it the
        # ENGINE's propagated shapes so a concrete dim inferred upstream is
        # checked here even when the variable was declared with -1/()
        def shape(slot, i=0):
            names = op.inputs.get(slot, ())
            return (_legacy(self.engine.shape_of(block, names[i]))
                    if i < len(names) else None)

        def dtype(slot, i=0):
            names = op.inputs.get(slot, ())
            return (self.engine.dtype_of(block, names[i])
                    if i < len(names) else None)

        for message, hint in checker(op, shape, dtype):
            self._op_flagged = True
            self._emit("PV009", "error", message, block_idx, op_idx,
                       op.type, hint=hint)

    # -- forward symbolic inference ------------------------------------------
    def _infer_op(self, block_idx, op_idx, op):
        """Propagate shapes/dtypes through one op via _INFER_RULES; ops
        without a rule fall back to their declared output shapes."""
        block = self.program.blocks[block_idx]
        eng = self.engine
        if op.type == "backward_region":
            params = list(op.inputs.get("Params", ()))
            for i, g in enumerate(op.outputs.get("Grads", ())):
                if i < len(params):
                    eng.bind(g, eng.shape_of(block, params[i]),
                             eng.dtype_of(block, params[i]))
                else:
                    eng.bind_declared(block, g)
            return
        if op.type == "conditional_block":
            self._infer_cond(block_idx, op_idx, op)
            return
        if op.type == "while":
            self._infer_while(block_idx, op_idx, op)
            return
        rule = _INFER_RULES.get(op.type)
        if rule is not None:
            ctx = _InferCtx(self, block_idx, op_idx, op)
            try:
                rule(ctx)
            except Exception:           # a broken rule must never block
                ctx.failed = True       # the trace — degrade to declared
            bound = ctx.bound
        else:
            bound = set()
        for name in op.output_names():
            if name not in bound:
                eng.bind_declared(block, name)

    def _infer_cond(self, block_idx, op_idx, op):
        """lax.cond requires identical branch avals: compare the inferred
        true/false outputs positionally; record clashes for shardcheck
        (SC006) and bind Out from the unified result."""
        eng = self.engine
        block = self.program.blocks[block_idx]
        t_rec = eng.records.get((id(op), "true_block"), [])
        f_rec = eng.records.get((id(op), "false_block"), [])
        outs = list(op.outputs.get("Out", ()))
        for i, name in enumerate(outs):
            t = t_rec[i] if i < len(t_rec) else None
            f = f_rec[i] if i < len(f_rec) else None
            if t is None or f is None:
                eng.bind_declared(block, name)
                continue
            (tn, ts, td), (fn, fs, fd) = t, f
            clash = _shape_clash(ts, fs)
            if clash:
                eng.subblock_findings.append(Diagnostic(
                    "SC006", "error",
                    f"cond branches disagree on output {i} "
                    f"({tn!r} vs {fn!r}): {clash} — lax.cond requires "
                    "identical branch avals",
                    block_idx, op_idx, op.type, var=name,
                    hint="make both branches produce the same shape"))
            elif (td is not None and fd is not None and td != fd
                  and tn in eng.dtypes and fn in eng.dtypes):
                # dtype clash only when both sides were RULE-inferred (a
                # declared-default float32 on one side must not false-flag)
                eng.subblock_findings.append(Diagnostic(
                    "SC006", "error",
                    f"cond branches disagree on output {i} dtype "
                    f"({tn!r} is {td}, {fn!r} is {fd}) — lax.cond "
                    "requires identical branch avals",
                    block_idx, op_idx, op.type, var=name,
                    hint="cast one branch to the other's dtype"))
            eng.bind(name, _shape_unify(ts, fs), td if td == fd else None)

    def _infer_while(self, block_idx, op_idx, op):
        """lax.while_loop carries must be shape-invariant: compare each
        carry's entry shape against the body's inferred output shape.
        Shape-only — the executor casts body outputs back to the carry
        dtype, so dtype drift is legal at runtime."""
        eng = self.engine
        block = self.program.blocks[block_idx]
        carries = list(op.inputs.get("X", ()))
        b_rec = eng.records.get((id(op), "body_block"), [])
        outs = list(op.outputs.get("Out", ()))
        for i, name in enumerate(outs):
            cs = (eng.shape_of(block, carries[i])
                  if i < len(carries) else None)
            cd = (eng.dtype_of(block, carries[i])
                  if i < len(carries) else None)
            if i < len(b_rec):
                bn, bs, _bd = b_rec[i]
                clash = _shape_clash(cs, bs)
                if clash:
                    eng.subblock_findings.append(Diagnostic(
                        "SC006", "error",
                        f"while carry {i} ({carries[i]!r}) is not "
                        f"shape-invariant: body output {bn!r} — {clash}",
                        block_idx, op_idx, op.type, var=name,
                        hint="lax.while_loop carries must keep their shape"))
            eng.bind(name, cs, cd)


# ---------------------------------------------------------------------------
# Shape/dtype inference table.  Each checker yields (message, hint) pairs;
# -1 and undeclared shapes are wildcards — only statically-certain
# mismatches are flagged.
# ---------------------------------------------------------------------------

def _dims_clash(a: int, b: int) -> bool:
    return a != -1 and b != -1 and a != b


def _broadcast_clash(x, y, axis):
    """Reference elementwise broadcasting (ops._bcast_axis): y aligns to x
    starting at `axis`; equal ranks and axis in (None, -1) fall back to
    numpy trailing alignment.  Dims clash only when both are known, neither
    is 1, and they differ."""
    if x is None or y is None:
        return None
    if len(y) > len(x):
        return None                      # x broadcasts into y; jnp handles it
    if len(y) == len(x) or axis in (None, -1):
        for i in range(1, len(y) + 1):
            dx, dy = x[-i], y[-i]
            if dx != 1 and dy != 1 and _dims_clash(dx, dy):
                return (f"trailing dim -{i}: x has {dx}, y has {dy} "
                        "(not broadcastable)")
        return None
    start = axis
    if start < 0 or start + len(y) > len(x):
        return f"y rank {len(y)} does not fit into x rank {len(x)} at axis {axis}"
    for i, dy in enumerate(y):
        dx = x[start + i]
        if dx != 1 and dy != 1 and _dims_clash(dx, dy):
            return (f"dim {start + i}: x has {dx}, y has {dy} "
                    "(not broadcastable)")
    return None


def _chk_elementwise(op, shape, dtype):
    clash = _broadcast_clash(shape("X"), shape("Y"),
                             op.attrs.get("axis", -1))
    if clash:
        yield (f"elementwise {op.type!r}: {clash}",
               "shapes must broadcast under the reference axis rule")


def _chk_mul(op, shape, dtype):
    x, y = shape("X"), shape("Y")
    if x is None or y is None:
        return
    xn = op.attrs.get("x_num_col_dims", 1)
    yn = op.attrs.get("y_num_col_dims", 1)
    xin = x[xn:]
    yin = y[:yn]
    if any(d == -1 for d in xin) or any(d == -1 for d in yin):
        return
    a, b = int(np.prod(xin or (1,))), int(np.prod(yin or (1,)))
    if a != b:
        yield (f"mul: x flattens to inner dim {a} (shape {x} at "
               f"x_num_col_dims={xn}) but y provides {b} (shape {y})",
               "inner dimensions must match")


def _chk_matmul(op, shape, dtype):
    x, y = shape("X"), shape("Y")
    if x is None or y is None or len(x) < 1 or len(y) < 1:
        return
    kx = x[-2] if (op.attrs.get("transpose_X") and len(x) >= 2) else x[-1]
    if len(y) == 1:
        ky = y[0]
    else:
        ky = y[-1] if op.attrs.get("transpose_Y") else y[-2]
    if _dims_clash(kx, ky):
        yield (f"matmul: contraction dims differ — x contributes {kx} "
               f"(shape {x}), y contributes {ky} (shape {y})",
               "check transpose_X/transpose_Y and operand shapes")


def _chk_cast(op, shape, dtype):
    if "out_dtype" not in op.attrs:
        yield ("cast: missing required attr 'out_dtype'",
               "set attrs={'out_dtype': <dtype>}")


def _chk_fill_constant(op, shape, dtype):
    if "shape" not in op.attrs:
        yield ("fill_constant: missing required attr 'shape'",
               "set attrs={'shape': (...), 'value': v}")


def _chk_concat(op, shape, dtype):
    ranks = set()
    for i, _ in enumerate(op.inputs.get("X", ())):
        s = shape("X", i)
        if s is not None:
            ranks.add(len(s))
    if len(ranks) > 1:
        yield (f"concat: inputs have differing ranks {sorted(ranks)}",
               "all concat inputs must share a rank")


def _chk_softmax_ce(op, shape, dtype):
    if op.attrs.get("soft_label", False):
        return
    dt = dtype("Label")
    if dt is not None and dt.kind not in ("i", "u"):
        yield (f"softmax_with_cross_entropy: hard labels must be integer, "
               f"got {dt.name}",
               "cast the label to int64 or set soft_label=True")
    lx, ll = shape("Logits"), shape("Label")
    if lx is not None and ll is not None and len(ll) == len(lx):
        if _dims_clash(ll[-1], 1):
            yield (f"softmax_with_cross_entropy: hard label last dim must "
                   f"be 1, got {ll}",
                   "labels carry one class index per row")


def _chk_lookup_table(op, shape, dtype):
    dt = dtype("Ids")
    if dt is not None and dt.kind not in ("i", "u"):
        yield (f"{op.type}: Ids must be integer, got {dt.name}",
               "cast the ids to int64")


def _chk_conv2d(op, shape, dtype):
    x, w = shape("Input"), shape("Filter")
    if x is None or w is None or len(x) != 4 or len(w) != 4:
        return
    groups = op.attrs.get("groups", 1) or 1
    cin = x[1] if op.attrs.get("data_format", "NCHW") == "NCHW" else x[-1]
    if _dims_clash(cin, w[1] * groups):
        yield (f"conv2d: input channels {cin} != filter in-channels "
               f"{w[1]} * groups {groups}",
               "filter shape is (out_c, in_c/groups, kh, kw)")


def _chk_reshape(op, shape, dtype):
    x = shape("X")
    tgt = op.attrs.get("shape")
    if x is None or not tgt or any(d == -1 for d in x):
        return
    tgt = tuple(int(d) for d in tgt)
    if any(d == -1 for d in tgt) or 0 in tgt:
        return
    if int(np.prod(x)) != int(np.prod(tgt)):
        yield (f"reshape: cannot reshape {x} ({int(np.prod(x))} elements) "
               f"to {tgt} ({int(np.prod(tgt))} elements)",
               "element counts must match (use -1 for one inferred dim)")


_SHAPE_CHECKERS = {
    "mul": _chk_mul,
    "matmul": _chk_matmul,
    "cast": _chk_cast,
    "fill_constant": _chk_fill_constant,
    "concat": _chk_concat,
    "softmax_with_cross_entropy": _chk_softmax_ce,
    "lookup_table": _chk_lookup_table,
    "embedding": _chk_lookup_table,
    "c_embedding": _chk_lookup_table,
    "conv2d": _chk_conv2d,
    "reshape": _chk_reshape,
    "reshape2": _chk_reshape,
}
for _name in ("elementwise_add", "elementwise_sub", "elementwise_mul",
              "elementwise_div", "elementwise_max", "elementwise_min",
              "elementwise_pow", "elementwise_mod", "elementwise_floordiv"):
    _SHAPE_CHECKERS[_name] = _chk_elementwise


# ---------------------------------------------------------------------------
# Forward inference rules.  Each rule reads propagated input shapes/dtypes
# through an _InferCtx and binds output slots; anything it cannot determine
# stays None/declared (never guess — a wrong concrete dim would cascade
# into false PV009s downstream).  Rules mirror the registered lowerings in
# static/ops*.py (slot names, attr defaults) — a rule here without a
# matching lowering semantic is a bug.
# ---------------------------------------------------------------------------

def _shape_clash(a: SymShape, b: SymShape) -> Optional[str]:
    """Human-readable description of a statically-certain disagreement
    between two inferred shapes, or None (unknowns never clash)."""
    if a is None or b is None:
        return None
    if len(a) != len(b):
        return f"rank {len(a)} ({_legacy(a)}) vs rank {len(b)} ({_legacy(b)})"
    for i, (da, db) in enumerate(zip(a, b)):
        if _known(da) and _known(db) and int(da) != int(db):
            return f"dim {i}: {int(da)} vs {int(db)}"
    return None


def _shape_unify(a: SymShape, b: SymShape) -> SymShape:
    if a is None:
        return b
    if b is None or len(a) != len(b):
        return a
    return tuple(da if _known(da) else (db if _known(db) else da)
                 for da, db in zip(a, b))


def _bdim(a: Dim, b: Dim) -> Dim:
    """One broadcast output dim (clashes are the checker's job, not ours)."""
    if _known(a) and int(a) == 1:
        return b
    if _known(b) and int(b) == 1:
        return a
    if _known(a):
        return int(a)
    if _known(b):
        return int(b)
    return a


def _sym_broadcast(x: SymShape, y: SymShape, axis=-1) -> SymShape:
    """Output shape of the reference elementwise broadcast (_bcast_axis: y
    aligns into x at `axis`; trailing alignment otherwise)."""
    if x is None or y is None:
        return None
    if len(y) > len(x):
        x, y, axis = y, x, -1           # plain jnp broadcasting kicks in
    out = list(x)
    if len(y) == len(x) or axis in (None, -1):
        for i in range(1, len(y) + 1):
            out[-i] = _bdim(x[-i], y[-i])
        return tuple(out)
    if axis < 0 or axis + len(y) > len(x):
        return None
    for i, dy in enumerate(y):
        out[axis + i] = _bdim(x[axis + i], dy)
    return tuple(out)


def _prod_dim(dims) -> Dim:
    """Product of a dim run: concrete when every factor is, else a fresh
    anonymous Sym (NOT memoized — a different run is a different unknown)."""
    dims = tuple(dims)
    if all(_known(d) for d in dims):
        return int(np.prod([int(d) for d in dims], dtype=np.int64)) \
            if dims else 1
    return Sym("prod")


class _InferCtx:
    """The narrow surface a rule sees: propagated inputs, op attrs, and
    set_out (which also cross-checks inferred-vs-declared → PV010)."""

    def __init__(self, verifier: "_Verifier", block_idx, op_idx, op):
        self.v = verifier
        self.block_idx, self.op_idx, self.op = block_idx, op_idx, op
        self.block = verifier.program.blocks[block_idx]
        self.eng = verifier.engine
        self.bound: Set[str] = set()
        self.failed = False

    def in_shape(self, slot, i=0) -> SymShape:
        names = self.op.inputs.get(slot, ())
        return (self.eng.shape_of(self.block, names[i])
                if i < len(names) else None)

    def in_dtype(self, slot, i=0):
        names = self.op.inputs.get(slot, ())
        return (self.eng.dtype_of(self.block, names[i])
                if i < len(names) else None)

    def n_inputs(self, slot) -> int:
        return len(self.op.inputs.get(slot, ()))

    def attr(self, name, default=None):
        return self.op.attrs.get(name, default)

    def fail(self, message, hint=None):
        """A statically-certain lowering failure found while inferring —
        same severity and code as the plausibility table (PV009)."""
        self.v._op_flagged = True
        self.v._emit("PV009", "error", message, self.block_idx,
                     self.op_idx, self.op.type, hint=hint)

    def set_out(self, slot, shape: SymShape, dtype=None, i=0):
        names = self.op.outputs.get(slot, ())
        if i >= len(names):
            return
        name = names[i]
        if dtype is None:
            dtype = self.eng._declared_dtype(self.block, name)
        self.eng.bind(name, shape, dtype)
        self.bound.add(name)
        if shape is None or self.v._op_flagged:
            return
        # PV010: a rule-inferred concrete dim contradicting the DECLARED
        # shape means the declaration is stale/wrong (warning only — the
        # executor traces from values, not declarations)
        try:
            declared = tuple(self.block.var(name).shape)
        except KeyError:
            return
        if not declared:
            return
        if len(declared) != len(shape):
            self.v._emit(
                "PV010", "warning",
                f"{self.op.type}: inferred shape of {name!r} is "
                f"{_legacy(shape)} (rank {len(shape)}) but it is declared "
                f"as {declared} (rank {len(declared)})",
                self.block_idx, self.op_idx, self.op.type, var=name,
                hint="fix the declared shape — downstream checks use the "
                     "inferred one")
            return
        for j, (a, b) in enumerate(zip(shape, declared)):
            if _known(a) and _known(b) and int(a) != int(b):
                self.v._emit(
                    "PV010", "warning",
                    f"{self.op.type}: inferred {name!r} dim {j} = {int(a)} "
                    f"contradicts its declared shape {declared}",
                    self.block_idx, self.op_idx, self.op.type, var=name,
                    hint="fix the declared shape — downstream checks use "
                         "the inferred one")
                return


# -- rule bodies -------------------------------------------------------------

def _rule_unary(ctx):
    ctx.set_out("Out", ctx.in_shape("X"), ctx.in_dtype("X"))


def _rule_elementwise(ctx):
    out = _sym_broadcast(ctx.in_shape("X"), ctx.in_shape("Y"),
                         ctx.attr("axis", -1))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_compare(ctx):
    out = _sym_broadcast(ctx.in_shape("X"), ctx.in_shape("Y"), -1)
    ctx.set_out("Out", out, np.dtype(bool))


def _rule_logical_not(ctx):
    ctx.set_out("Out", ctx.in_shape("X"), np.dtype(bool))


def _rule_reduce(ctx):
    x = ctx.in_shape("X")
    if x is None or not len(x):
        ctx.set_out("Out", None if x is None else (), ctx.in_dtype("X"))
        return
    dim = ctx.attr("dim")
    if ctx.attr("reduce_all", False) or dim is None:
        dims = set(range(len(x)))
    else:
        axes = (dim,) if isinstance(dim, (int, np.integer)) else tuple(dim)
        dims = {int(d) % len(x) for d in axes}
    if ctx.attr("keep_dim", False):
        out = tuple(1 if i in dims else d for i, d in enumerate(x))
    else:
        out = tuple(d for i, d in enumerate(x) if i not in dims)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_mean(ctx):
    ctx.set_out("Out", (), ctx.in_dtype("X"))


def _rule_sum(ctx):
    ctx.set_out("Out", ctx.in_shape("X", 0), ctx.in_dtype("X", 0))


def _rule_mul(ctx):
    x, y = ctx.in_shape("X"), ctx.in_shape("Y")
    xn = int(ctx.attr("x_num_col_dims", 1))
    yn = int(ctx.attr("y_num_col_dims", 1))
    out = None
    if x is not None and y is not None and len(x) >= xn and len(y) >= yn:
        out = tuple(x[:xn]) + tuple(y[yn:])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_matmul(ctx):
    x, y = ctx.in_shape("X"), ctx.in_shape("Y")
    if x is None or y is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    if ctx.attr("transpose_X", ctx.attr("trans_x", False)) and len(x) >= 2:
        x = x[:-2] + (x[-1], x[-2])
    if ctx.attr("transpose_Y", ctx.attr("trans_y", False)) and len(y) >= 2:
        y = y[:-2] + (y[-1], y[-2])
    if len(x) >= 2 and len(y) >= 2:
        batch = _sym_broadcast(x[:-2], y[:-2], -1)
        out = None if batch is None else batch + (x[-2], y[-1])
    elif len(x) >= 2 and len(y) == 1:
        out = x[:-1]
    elif len(x) == 1 and len(y) >= 2:
        out = y[:-2] + (y[-1],)
    else:
        out = ()
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_fc(ctx):
    x, w = ctx.in_shape("Input"), ctx.in_shape("W")
    ncol = int(ctx.attr("in_num_col_dims", 1))
    out = None
    if x is not None and w is not None and len(w) >= 2 and len(x) >= ncol:
        out = tuple(x[:ncol]) + (w[1],)
    ctx.set_out("Out", out, ctx.in_dtype("Input"))


def _rule_cast(ctx):
    dt = ctx.attr("out_dtype")
    try:
        dt = np.dtype(dt) if dt is not None else None
    except TypeError:
        dt = None
    ctx.set_out("Out", ctx.in_shape("X"), dt)


def _rule_fill_constant(ctx):
    shape = ctx.attr("shape")
    dt = ctx.attr("dtype", "float32")
    try:
        dt = np.dtype(dt)
    except TypeError:
        dt = None
    ctx.set_out("Out",
                None if shape is None else tuple(int(d) for d in shape), dt)


def _rule_fill_like(ctx):
    ctx.set_out("Out", ctx.in_shape("X"), ctx.in_dtype("X"))


def _rule_concat(ctx):
    n = ctx.n_inputs("X")
    shapes = [ctx.in_shape("X", i) for i in range(n)]
    if not shapes or any(s is None for s in shapes) \
            or len({len(s) for s in shapes}) != 1:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    axis = int(ctx.attr("axis", 0)) % len(shapes[0]) if len(shapes[0]) \
        else 0
    out = list(shapes[0])
    cat = [s[axis] for s in shapes]
    out[axis] = (int(sum(int(d) for d in cat))
                 if all(_known(d) for d in cat) else Sym("concat"))
    for j in range(len(out)):
        if j != axis and not _known(out[j]):
            for s in shapes[1:]:        # any sibling's concrete dim wins
                if _known(s[j]):
                    out[j] = int(s[j])
                    break
    ctx.set_out("Out", tuple(out), ctx.in_dtype("X"))


def _rule_stack(ctx):
    n = ctx.n_inputs("X")
    x = ctx.in_shape("X", 0)
    if x is None:
        ctx.set_out("Y", None, ctx.in_dtype("X"))
        return
    axis = int(ctx.attr("axis", 0))
    if axis < 0:
        axis += len(x) + 1
    if not 0 <= axis <= len(x):
        ctx.set_out("Y", None, ctx.in_dtype("X"))
        return
    ctx.set_out("Y", tuple(x[:axis]) + (n,) + tuple(x[axis:]),
                ctx.in_dtype("X"))


def _rule_reshape(ctx):
    x = ctx.in_shape("X")
    tgt = ctx.attr("shape")
    if tgt is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    tgt = [int(d) for d in tgt]
    out = []
    for i, d in enumerate(tgt):
        if d == 0:                      # reference semantics: copy input dim
            out.append(x[i] if x is not None and i < len(x) else Sym("resh"))
        elif d == -1:
            out.append(None)            # placeholder, solved below
        else:
            out.append(d)
    if None in out:
        hole = out.index(None)
        rest = [d for d in out if d is not None]
        total = _prod_dim(x) if x is not None else Sym("resh")
        if _known(total) and all(_known(d) for d in rest):
            denom = int(np.prod([int(d) for d in rest], dtype=np.int64)) \
                if rest else 1
            out[hole] = int(total) // denom if denom and \
                int(total) % denom == 0 else Sym("resh")
        else:
            out[hole] = Sym("resh")
    ctx.set_out("Out", tuple(out), ctx.in_dtype("X"))


def _rule_transpose(ctx):
    x = ctx.in_shape("X")
    perm = ctx.attr("axis")
    if x is None or perm is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    perm = [int(p) for p in perm]
    if sorted(p % len(x) if len(x) else p for p in perm) \
            != list(range(len(x))):
        ctx.fail(
            f"transpose: perm {perm} is not a permutation of rank "
            f"{len(x)} input {_legacy(x)}",
            "attrs['axis'] must list each input axis exactly once")
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    ctx.set_out("Out", tuple(x[p % len(x)] for p in perm),
                ctx.in_dtype("X"))


def _rule_flatten(ctx):
    x = ctx.in_shape("X")
    ax = int(ctx.attr("axis", 1))
    if x is None or not 0 <= ax <= len(x):
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    ctx.set_out("Out", (_prod_dim(x[:ax]) if ax else 1, _prod_dim(x[ax:])),
                ctx.in_dtype("X"))


def _rule_squeeze(ctx):
    x = ctx.in_shape("X")
    axes = tuple(int(a) for a in ctx.attr("axes", ()) or ())
    if x is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    if not axes:
        if not all(_known(d) for d in x):
            ctx.set_out("Out", None, ctx.in_dtype("X"))
            return
        out = tuple(d for d in x if int(d) != 1)
    else:
        drop = {a % len(x) for a in axes} if len(x) else set()
        out = tuple(d for i, d in enumerate(x) if i not in drop)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_unsqueeze(ctx):
    x = ctx.in_shape("X")
    axes = ctx.attr("axes")
    if x is None or axes is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    out = list(x)
    for a in sorted(int(a) for a in axes):
        if not -len(out) - 1 <= a <= len(out):
            ctx.set_out("Out", None, ctx.in_dtype("X"))
            return
        out.insert(a if a >= 0 else a + len(out) + 1, 1)
    ctx.set_out("Out", tuple(out), ctx.in_dtype("X"))


def _conv_spatial(size: Dim, k: int, s: int, p: int, d: int = 1) -> Dim:
    if not _known(size):
        return Sym("conv")
    eff = d * (k - 1) + 1
    return (int(size) + 2 * p - eff) // s + 1


def _rule_conv2d(ctx):
    x, w = ctx.in_shape("Input"), ctx.in_shape("Filter")
    if x is None or w is None or len(x) != 4 or len(w) != 4:
        ctx.set_out("Output", None, ctx.in_dtype("Input"))
        return
    st = tuple(ctx.attr("strides", (1, 1)))
    pd = tuple(ctx.attr("paddings", (0, 0)))
    dl = tuple(ctx.attr("dilations", (1, 1)))
    nchw = ctx.attr("data_format", "NCHW") == "NCHW"
    h_in, w_in = (x[2], x[3]) if nchw else (x[1], x[2])
    if not (_known(w[2]) and _known(w[3])):
        ctx.set_out("Output", None, ctx.in_dtype("Input"))
        return
    h = _conv_spatial(h_in, int(w[2]), int(st[0]), int(pd[0]), int(dl[0]))
    wd = _conv_spatial(w_in, int(w[3]), int(st[1]), int(pd[1]), int(dl[1]))
    out = (x[0], w[0], h, wd) if nchw else (x[0], h, wd, w[0])
    ctx.set_out("Output", out, ctx.in_dtype("Input"))


def _rule_pool2d(ctx):
    x = ctx.in_shape("X")
    if x is None or len(x) != 4:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    nchw = ctx.attr("data_format", "NCHW") == "NCHW"
    c = x[1] if nchw else x[3]
    h_in, w_in = (x[2], x[3]) if nchw else (x[1], x[2])

    def _emit(h, w):
        out = (x[0], c, h, w) if nchw else (x[0], h, w, c)
        ctx.set_out("Out", out, ctx.in_dtype("X"))

    if ctx.attr("global_pooling", False):
        _emit(1, 1)
        return
    ks = tuple(int(k) for k in ctx.attr("ksize", (1, 1)))
    if ctx.attr("adaptive", False):
        _emit(*ks)
        return
    st = tuple(int(s) for s in ctx.attr("strides", ks))
    pd = tuple(int(p) for p in ctx.attr("paddings", (0, 0)))
    if ctx.attr("ceil_mode", False):
        _emit(Sym("pool"), Sym("pool"))
        return
    _emit(_conv_spatial(h_in, ks[0], st[0], pd[0]),
          _conv_spatial(w_in, ks[1], st[1], pd[1]))


def _rule_batch_norm(ctx):
    ctx.set_out("Y", ctx.in_shape("X"), ctx.in_dtype("X"))


def _rule_layer_norm(ctx):
    ctx.set_out("Y", ctx.in_shape("X"), ctx.in_dtype("X"))


def _rule_lookup_table(ctx):
    ids, w = ctx.in_shape("Ids"), ctx.in_shape("W")
    out = None
    if ids is not None and w is not None and len(w) >= 1 and len(ids) >= 1:
        # lookup_table squeezes the trailing ids dim (jnp.take of ids[...,0])
        out = tuple(ids[:-1]) + tuple(w[1:])
    ctx.set_out("Out", out, ctx.in_dtype("W"))


def _rule_embedding(ctx):
    ids, w = ctx.in_shape("Ids"), ctx.in_shape("W")
    out = None
    if ids is not None and w is not None and len(w) >= 1:
        out = tuple(ids) + tuple(w[1:])   # F.embedding: no squeeze
    ctx.set_out("Out", out, ctx.in_dtype("W"))


def _rule_softmax_ce(ctx):
    logits = ctx.in_shape("Logits")
    if logits is None or not len(logits):
        return
    ctx.set_out("Loss", tuple(logits[:-1]) + (1,), ctx.in_dtype("Logits"))
    ctx.set_out("Softmax", logits, ctx.in_dtype("Logits"))


def _rule_one_hot(ctx):
    x = ctx.in_shape("X")
    depth = ctx.attr("depth")
    out = None
    if x is not None and depth is not None:
        out = tuple(x) + (int(depth),)
    ctx.set_out("Out", out)


def _rule_top_k(ctx):
    x = ctx.in_shape("X")
    k = ctx.attr("k", 1)
    out = None
    if x is not None and len(x):
        out = tuple(x[:-1]) + (int(k),)
    ctx.set_out("Out", out, ctx.in_dtype("X"))
    ctx.set_out("Indices", out, np.dtype(np.int64))


def _rule_arg_reduce(ctx):
    x = ctx.in_shape("X")
    if x is None or not len(x):
        ctx.set_out("Out", None, np.dtype(np.int64))
        return
    axis = int(ctx.attr("axis", -1)) % len(x)
    keep = ctx.attr("keepdims", False)
    out = tuple(1 if i == axis else d for i, d in enumerate(x)) if keep \
        else tuple(d for i, d in enumerate(x) if i != axis)
    ctx.set_out("Out", out, np.dtype(np.int64))


def _rule_param_out(ctx):
    """Optimizer update ops: every '<Slot>Out' output mirrors its '<Slot>'
    input (sgd/momentum/adam/... all follow the ref naming convention);
    unmatched outputs degrade to their declared shapes."""
    for slot in ctx.op.outputs:
        src = slot[:-3] if slot.endswith("Out") else None
        if src and src in ctx.op.inputs:
            ctx.set_out(slot, ctx.in_shape(src), ctx.in_dtype(src))


def _rule_gather(ctx):
    x, idx = ctx.in_shape("X"), ctx.in_shape("Index")
    out = None
    if x is not None and idx is not None and len(x):
        axis = int(ctx.attr("axis", 0)) % len(x)
        out = tuple(x[:axis]) + tuple(idx) + tuple(x[axis + 1:])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_index_select(ctx):
    x, idx = ctx.in_shape("X"), ctx.in_shape("Index")
    out = None
    if x is not None and idx is not None and len(x) and len(idx) == 1:
        d = int(ctx.attr("dim", 0)) % len(x)
        out = tuple(x[:d]) + (idx[0],) + tuple(x[d + 1:])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _slice_len(dim: Dim, s: int, e: int, stride: int = 1) -> Dim:
    if not _known(dim):
        return Sym("slice")
    d = int(dim)
    s = s + d if s < 0 else s
    e = e + d if e < 0 else e
    s, e = max(0, min(s, d)), max(0, min(e, d))
    return max(0, -(-(e - s) // stride))


def _rule_slice(ctx):
    x = ctx.in_shape("Input")
    axes = ctx.attr("axes")
    if x is None or axes is None:
        ctx.set_out("Out", None, ctx.in_dtype("Input"))
        return
    starts = tuple(ctx.attr("starts", ()))
    ends = tuple(ctx.attr("ends", ()))
    strides = tuple(ctx.attr("strides", (1,) * len(axes)))
    out = list(x)
    for ax, s, e, st in zip(axes, starts, ends, strides):
        if 0 <= ax < len(out):
            out[ax] = _slice_len(out[ax], int(s), int(e), int(st))
    ctx.set_out("Out", tuple(out), ctx.in_dtype("Input"))


def _rule_expand(ctx):
    # expand/tile: jnp.tile — reps shorter than rank apply trailing,
    # reps longer than rank prepend dims
    x = ctx.in_shape("X")
    reps = ctx.attr("expand_times", ctx.attr("repeat_times"))
    if x is None or reps is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    reps = tuple(int(r) for r in reps)
    if len(reps) < len(x):
        reps = (1,) * (len(x) - len(reps)) + reps
    xs = (1,) * (len(reps) - len(x)) + tuple(x)
    out = tuple(int(d) * r if _known(d) else Sym("tile")
                for d, r in zip(xs, reps))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_expand_v2(ctx):
    x, shape = ctx.in_shape("X"), ctx.attr("shape")
    out = None
    if x is not None and shape is not None and len(shape) == len(x):
        out = tuple(x[i] if int(s) == -1 else int(s)
                    for i, s in enumerate(shape))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_expand_as(ctx):
    target = ctx.attr("target_shape")
    shape = tuple(int(s) for s in target) if target else (
        ctx.in_shape("target_tensor") if ctx.n_inputs("target_tensor")
        else ctx.in_shape("Y"))
    ctx.set_out("Out", shape, ctx.in_dtype("X"))


def _rule_shape_op(ctx):
    x = ctx.in_shape("Input")
    ctx.set_out("Out", (len(x),) if x is not None else None,
                np.dtype(np.int32))


def _rule_size(ctx):
    ctx.set_out("Out", (), np.dtype(np.int64))


def _rule_fill_batch_like(ctx):
    ref, shape = ctx.in_shape("Input"), ctx.attr("shape")
    if shape is None:
        return
    out = [int(s) for s in shape]
    odim = int(ctx.attr("output_dim_idx", 0))
    idim = int(ctx.attr("input_dim_idx", 0))
    if ref is not None and idim < len(ref) and odim < len(out):
        out[odim] = ref[idim]
    dt = ctx.attr("dtype")
    try:
        dt = np.dtype(dt) if dt is not None else None
    except TypeError:
        dt = None
    ctx.set_out("Out", tuple(out), dt)


def _rule_pad(ctx):
    x, p = ctx.in_shape("X"), ctx.attr("paddings")
    out = None
    if x is not None and p is not None and len(p) >= 2 * len(x):
        out = tuple(_bdim(d, int(p[2 * i]) + int(p[2 * i + 1]))
                    for i, d in enumerate(x))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_pad2d(ctx):
    x, p = ctx.in_shape("X"), ctx.attr("paddings")
    out = None
    if x is not None and len(x) == 4 and p is not None and len(p) >= 4:
        # NCHW, paddings [top, bottom, left, right]
        out = (x[0], x[1], _bdim(x[2], int(p[0]) + int(p[1])),
               _bdim(x[3], int(p[2]) + int(p[3])))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_interp(mode):
    def rule(ctx):
        x = ctx.in_shape("X")
        spatial_rank = {"linear": 1, "trilinear": 3}.get(mode, 2)
        if x is None or len(x) != 2 + spatial_rank:
            ctx.set_out("Out", None, ctx.in_dtype("X"))
            return
        if ctx.n_inputs("OutSize"):       # runtime-tensor size: unknown
            spatial = tuple(Sym("interp") for _ in range(spatial_rank))
        elif ctx.attr("out_shape"):
            spatial = tuple(int(v) for v in ctx.attr("out_shape"))
        elif mode == "trilinear":
            spatial = (ctx.attr("out_d"), ctx.attr("out_h"),
                       ctx.attr("out_w"))
        elif mode == "linear":
            spatial = (ctx.attr("out_w"),)
        else:
            spatial = (ctx.attr("out_h"), ctx.attr("out_w"))
        if any(s is None for s in spatial):
            ctx.set_out("Out", None, ctx.in_dtype("X"))
            return
        spatial = tuple(s if isinstance(s, Sym) else int(s)
                        for s in spatial)
        ctx.set_out("Out", (x[0], x[1]) + spatial, ctx.in_dtype("X"))

    return rule


def _rule_resize_interp(ctx):
    x, sz = ctx.in_shape("X"), ctx.attr("out_shape")
    out = None
    if x is not None and len(x) == 4 and sz is not None and len(sz) == 2:
        out = (x[0], x[1], int(sz[0]), int(sz[1]))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_unstack(ctx):
    x = ctx.in_shape("X")
    slot = "Y" if "Y" in ctx.op.outputs else "Out"
    n = len(ctx.op.outputs.get(slot, ()))
    if x is None or not len(x):
        for i in range(n):
            ctx.set_out(slot, None, ctx.in_dtype("X"), i=i)
        return
    axis = int(ctx.attr("axis", 0)) % len(x)
    out = tuple(d for i, d in enumerate(x) if i != axis)
    for i in range(n):
        ctx.set_out(slot, out, ctx.in_dtype("X"), i=i)


def _rule_argsort(ctx):
    x = ctx.in_shape("X")
    ctx.set_out("Out", x, ctx.in_dtype("X"))
    ctx.set_out("Indices", x)           # int width is platform-dependent


def _rule_keepdim_batch(out_slot, extra_slots=()):
    """Losses reducing all non-batch dims with keepdims: (N, 1, ..., 1)."""
    def rule(ctx):
        x = ctx.in_shape("X")
        out = None
        if x is not None and len(x):
            out = (x[0],) + (1,) * (len(x) - 1)
        ctx.set_out(out_slot, out, ctx.in_dtype("X"))
        for s in extra_slots:
            ctx.set_out(s, x, ctx.in_dtype("X"))

    return rule


def _rule_cross_entropy(ctx):
    x = ctx.in_shape("X")
    out = None
    if x is not None and len(x):
        out = tuple(x[:-1]) + (1,)
    ctx.set_out("Y", out, ctx.in_dtype("X"))


def _rule_accuracy(ctx):
    ctx.set_out("Accuracy", (), ctx.in_dtype("Out"))
    ctx.set_out("Correct", (), np.dtype(np.int32))
    ctx.set_out("Total", (), np.dtype(np.int32))


def _rule_squared_l2_norm(ctx):
    ctx.set_out("Out", (1,), ctx.in_dtype("X"))


def _rule_norm(ctx):
    x = ctx.in_shape("X")
    ctx.set_out("Out", x, ctx.in_dtype("X"))
    if x is not None and len(x):
        axis = int(ctx.attr("axis", -1)) % len(x)
        ctx.set_out("Norm", tuple(1 if i == axis else d
                                  for i, d in enumerate(x)),
                    ctx.in_dtype("X"))


def _rule_kldiv_loss(ctx):
    red = ctx.attr("reduction", "mean")
    x = ctx.in_shape("X")
    ctx.set_out("Loss", x if red == "none" else (), ctx.in_dtype("X"))


def _rule_maxout(ctx):
    x, g = ctx.in_shape("X"), ctx.attr("groups")
    out = None
    if x is not None and len(x) >= 2 and g:
        c = x[1]
        out = (x[0], int(c) // int(g) if _known(c) else Sym("maxout")) \
            + tuple(x[2:])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_crop(ctx):
    shape = ctx.attr("shape")
    out = None
    if shape and all(int(s) > 0 for s in shape):
        out = tuple(int(s) for s in shape)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_same_as(in_slot, out_slot, dtype=None):
    """Output mirrors one input's shape (value-wise op with custom slot
    names); dtype overrides for predicate outputs."""
    def rule(ctx):
        ctx.set_out(out_slot, ctx.in_shape(in_slot),
                    dtype if dtype is not None else ctx.in_dtype(in_slot))

    return rule


# Ops whose lowering is value-wise: output 0 has exactly X's shape+dtype.
_SAME_SHAPE_OPS = (
    # ops.py unary families
    "relu", "sigmoid", "tanh", "gelu", "exp", "log", "sqrt", "square",
    "abs", "floor", "ceil", "softsign", "sin", "cos", "tan", "asin",
    "acos", "atan", "sinh", "cosh", "rsqrt", "reciprocal", "round",
    "sign", "log2", "log10", "log1p", "expm1", "erf", "softplus", "silu",
    "swish", "mish", "relu6", "hard_swish", "selu", "logsigmoid",
    "leaky_relu", "elu", "softmax", "scale", "clip", "assign",
    "increment", "dropout", "cumsum", "label_smooth", "log_softmax",
    "hard_sigmoid", "hard_shrink", "soft_shrink", "softshrink",
    "tanh_shrink", "thresholded_relu", "pow", "stanh",
    "bernoulli", "flip", "roll",
    # ops_tail families verified value-wise (activations, clips, masks,
    # selected-rows passthroughs, element-wise losses)
    "brelu", "hard_tanh", "soft_relu", "clip_by_norm", "prelu",
    "tril_triu", "reverse", "inverse", "shard_index", "scatter",
    "scatter_nd_add", "relu_grad_passthrough", "where",
    "get_tensor_from_selected_rows", "merge_selected_rows",
    "bce_loss", "sigmoid_cross_entropy_with_logits",
)

_INFER_RULES: Dict[str, object] = {
    "mul": _rule_mul,
    "matmul": _rule_matmul,
    "matmul_v2": _rule_matmul,
    "bmm": _rule_matmul,
    "fc": _rule_fc,
    "cast": _rule_cast,
    "fill_constant": _rule_fill_constant,
    "gaussian_random": _rule_fill_constant,
    "uniform_random": _rule_fill_constant,
    "truncated_gaussian_random": _rule_fill_constant,
    "fill_zeros_like": _rule_fill_like,
    "fill_any_like": _rule_fill_like,
    "concat": _rule_concat,
    "stack": _rule_stack,
    "reshape": _rule_reshape,
    "reshape2": _rule_reshape,
    "transpose": _rule_transpose,
    "transpose2": _rule_transpose,
    "flatten": _rule_flatten,
    "flatten2": _rule_flatten,
    "squeeze": _rule_squeeze,
    "squeeze2": _rule_squeeze,
    "unsqueeze": _rule_unsqueeze,
    "unsqueeze2": _rule_unsqueeze,
    "conv2d": _rule_conv2d,
    "depthwise_conv2d": _rule_conv2d,
    "pool2d": _rule_pool2d,
    "batch_norm": _rule_batch_norm,
    "layer_norm": _rule_layer_norm,
    "lookup_table": _rule_lookup_table,
    "lookup_table_v2": _rule_embedding,
    "embedding": _rule_embedding,
    "c_embedding": _rule_embedding,
    "softmax_with_cross_entropy": _rule_softmax_ce,
    "one_hot": _rule_one_hot,
    "one_hot_v2": _rule_one_hot,
    "top_k": _rule_top_k,
    "top_k_v2": _rule_top_k,
    "arg_max": _rule_arg_reduce,
    "arg_min": _rule_arg_reduce,
    "mean": _rule_mean,
    "sum": _rule_sum,
    "logical_not": _rule_logical_not,
    # data movement / indexing
    "gather": _rule_gather,
    "index_select": _rule_index_select,
    "slice": _rule_slice,
    "strided_slice": _rule_slice,
    "expand": _rule_expand,
    "tile": _rule_expand,
    "expand_v2": _rule_expand_v2,
    "expand_as": _rule_expand_as,
    "expand_as_v2": _rule_expand_as,
    "shape": _rule_shape_op,
    "size": _rule_size,
    "fill_constant_batch_size_like": _rule_fill_batch_like,
    "gaussian_random_batch_size_like": _rule_fill_batch_like,
    "uniform_random_batch_size_like": _rule_fill_batch_like,
    "pad": _rule_pad,
    "pad2d": _rule_pad2d,
    "resize_interp": _rule_resize_interp,
    "unstack": _rule_unstack,
    "unbind": _rule_unstack,
    "argsort": _rule_argsort,
    "crop": _rule_crop,
    "crop_tensor": _rule_crop,
    "maxout": _rule_maxout,
    # losses / metrics with non-X slots or reduced shapes
    "cross_entropy": _rule_cross_entropy,
    "cross_entropy2": _rule_cross_entropy,
    "accuracy": _rule_accuracy,
    "squared_l2_norm": _rule_squared_l2_norm,
    "norm": _rule_norm,
    "kldiv_loss": _rule_kldiv_loss,
    "smooth_l1_loss": _rule_keepdim_batch("Out", extra_slots=("Diff",)),
    "cos_sim_v2": _rule_keepdim_batch("Out", extra_slots=("sub_result",)),
    "square_error_cost": _rule_same_as("X", "Out"),
    "huber_loss": _rule_same_as("X", "Out"),
    "log_loss": _rule_same_as("Predicted", "Loss"),
    "hinge_loss": _rule_same_as("Logits", "Loss"),
    "margin_rank_loss": _rule_same_as("X1", "Out"),
    "label_smooth": _rule_same_as("X", "Out"),
    # norm layers writing slot Y
    "group_norm": _rule_same_as("X", "Y"),
    "instance_norm": _rule_same_as("X", "Y"),
    "data_norm": _rule_same_as("X", "Y"),
    # predicates (bool out, X's shape)
    "isfinite_v2": _rule_same_as("X", "Out", np.dtype(np.bool_)),
    "isinf_v2": _rule_same_as("X", "Out", np.dtype(np.bool_)),
    "isnan_v2": _rule_same_as("X", "Out", np.dtype(np.bool_)),
    # collectives: shape-preserving reductions over the data axis
    "c_allreduce_sum": _rule_same_as("X", "Out"),
    "c_allreduce_max": _rule_same_as("X", "Out"),
    "c_allreduce_min": _rule_same_as("X", "Out"),
    "c_allreduce_prod": _rule_same_as("X", "Out"),
}
for _name in ("sgd", "momentum", "adam", "adamw", "adamax", "adagrad",
              "adadelta", "rmsprop", "ftrl", "lamb", "lars_momentum",
              "decayed_adagrad", "dpsgd", "proximal_adagrad",
              "proximal_gd", "dgc_momentum"):
    _INFER_RULES[_name] = _rule_param_out
for _name, _mode in (("bilinear_interp", "bilinear"),
                     ("bilinear_interp_v2", "bilinear"),
                     ("nearest_interp", "nearest"),
                     ("nearest_interp_v2", "nearest"),
                     ("bicubic_interp", "bicubic"),
                     ("bicubic_interp_v2", "bicubic"),
                     ("trilinear_interp", "trilinear"),
                     ("trilinear_interp_v2", "trilinear"),
                     ("linear_interp", "linear"),
                     ("linear_interp_v2", "linear")):
    _INFER_RULES[_name] = _rule_interp(_mode)
for _name in ("maximum", "minimum"):
    _INFER_RULES[_name] = _rule_elementwise
for _name in _SAME_SHAPE_OPS:
    _INFER_RULES[_name] = _rule_unary
for _name in ("elementwise_add", "elementwise_sub", "elementwise_mul",
              "elementwise_div", "elementwise_max", "elementwise_min",
              "elementwise_pow", "elementwise_mod", "elementwise_floordiv"):
    _INFER_RULES[_name] = _rule_elementwise
for _name in ("less_than", "less_equal", "greater_than", "greater_equal",
              "equal", "not_equal", "logical_and", "logical_or",
              "logical_xor"):
    _INFER_RULES[_name] = _rule_compare
for _name in ("reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
              "reduce_prod"):
    _INFER_RULES[_name] = _rule_reduce


# -- pass-relevant op families (PR 11 satellite): conv/pool/transpose
#    variants the fusion+layout passes rewrite, matmul variants, scalar
#    reductions, fills, and data movement.  Every rule mirrors its
#    registered lowering (static/ops*.py) — shapes first, declared-dtype
#    fallback where the lowering preserves input dtype. ----------------------

def _tuplen(v, n):
    """Scalar-or-sequence attr -> n-tuple (the F.* layer convention)."""
    if v is None:
        return (0,) * n
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    t = tuple(int(x) for x in v)
    return t * n if len(t) == 1 else t


def _deconv_spatial(size, k, s, p, d=1, op_=0):
    if not _known(size):
        return Sym("deconv")
    return (int(size) - 1) * s - 2 * p + d * (k - 1) + 1 + op_


def _rule_conv_nd(spatial, transpose=False):
    """conv3d / conv*_transpose: filter (O, I/g, *k) — transposed filters
    are (I, O/g, *k), so out channels = w[1] * groups."""
    def rule(ctx):
        x, w = ctx.in_shape("Input"), ctx.in_shape("Filter")
        rank = 2 + spatial
        if x is None or w is None or len(x) != rank or len(w) != rank:
            ctx.set_out("Output", None, ctx.in_dtype("Input"))
            return
        if not all(_known(w[2 + i]) for i in range(spatial)):
            ctx.set_out("Output", None, ctx.in_dtype("Input"))
            return
        st = _tuplen(ctx.attr("strides", 1), spatial)
        pd = _tuplen(ctx.attr("paddings", 0), spatial)
        dl = _tuplen(ctx.attr("dilations", 1), spatial)
        if transpose:
            g = ctx.attr("groups", 0) or (
                int(x[1]) if _known(x[1]) else None)
            op_ = _tuplen(ctx.attr("output_padding", 0), spatial)
            ch = int(w[1]) * int(g) if g and _known(w[1]) else Sym("deconv_c")
            dims = tuple(_deconv_spatial(x[2 + i], int(w[2 + i]), st[i],
                                         pd[i], dl[i], op_[i])
                         for i in range(spatial))
        else:
            ch = w[0]
            dims = tuple(_conv_spatial(x[2 + i], int(w[2 + i]), st[i],
                                       pd[i], dl[i])
                         for i in range(spatial))
        ctx.set_out("Output", (x[0], ch) + dims, ctx.in_dtype("Input"))

    return rule


def _rule_pool3d(ctx):
    x = ctx.in_shape("X")
    if x is None or len(x) != 5:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    if ctx.attr("global_pooling", False):
        ctx.set_out("Out", (x[0], x[1], 1, 1, 1), ctx.in_dtype("X"))
        return
    ks = _tuplen(ctx.attr("ksize", 1), 3)
    st = _tuplen(ctx.attr("strides", None), 3) if ctx.attr("strides") else ks
    pd = _tuplen(ctx.attr("paddings", 0), 3)
    dims = tuple(_conv_spatial(x[2 + i], ks[i], st[i], pd[i])
                 for i in range(3))
    ctx.set_out("Out", (x[0], x[1]) + dims, ctx.in_dtype("X"))


def _rule_pool_with_index(spatial):
    def rule(ctx):
        x = ctx.in_shape("X")
        rank = 2 + spatial
        if x is None or len(x) != rank:
            ctx.set_out("Out", None, ctx.in_dtype("X"))
            ctx.set_out("Mask", None)
            return
        ks = _tuplen(ctx.attr("ksize", 1), spatial)
        st = _tuplen(ctx.attr("strides", None), spatial) \
            if ctx.attr("strides") else ks
        pd = _tuplen(ctx.attr("paddings", 0), spatial)
        dims = tuple(_conv_spatial(x[2 + i], ks[i], st[i], pd[i])
                     for i in range(spatial))
        out = (x[0], x[1]) + dims
        ctx.set_out("Out", out, ctx.in_dtype("X"))
        ctx.set_out("Mask", out)

    return rule


def _rule_unfold(ctx):
    x = ctx.in_shape("X")
    if x is None or len(x) != 4:
        ctx.set_out("Y", None, ctx.in_dtype("X"))
        return
    kh, kw = _tuplen(ctx.attr("kernel_sizes"), 2)
    sh, sw = _tuplen(ctx.attr("strides", 1), 2)
    dh, dw = _tuplen(ctx.attr("dilations", 1), 2)
    p = list(ctx.attr("paddings", (0, 0, 0, 0)))
    if len(p) == 2:
        pads = (p[0], p[1])
    else:                    # (up, left, down, right): symmetric sums halved
        pads = None
    c = int(x[1]) if _known(x[1]) else None
    if pads is not None:
        ho = _conv_spatial(x[2], kh, sh, pads[0], dh)
        wo = _conv_spatial(x[3], kw, sw, pads[1], dw)
        length = (int(ho) * int(wo)
                  if _known(ho) and _known(wo) else Sym("unfold"))
    else:
        length = Sym("unfold")
    ctx.set_out("Y", (x[0], c * kh * kw if c else Sym("unfold_c"), length),
                ctx.in_dtype("X"))


def _rule_pad3d(ctx):
    x, p = ctx.in_shape("X"), ctx.attr("paddings")
    out = None
    if x is not None and len(x) == 5 and p is not None and len(p) >= 6:
        # NCDHW with paddings (l, r, t, b, front, back)
        out = (x[0], x[1], _bdim(x[2], int(p[4]) + int(p[5])),
               _bdim(x[3], int(p[2]) + int(p[3])),
               _bdim(x[4], int(p[0]) + int(p[1])))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_spp(ctx):
    x, h = ctx.in_shape("X"), ctx.attr("pyramid_height")
    out = None
    if x is not None and len(x) == 4 and h:
        c = x[1]
        feat = (int(c) * (4 ** int(h) - 1) // 3 if _known(c)
                else Sym("spp"))
        out = (x[0], feat)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_pixel_shuffle(ctx):
    x, r = ctx.in_shape("X"), ctx.attr("upscale_factor")
    out = None
    if x is not None and len(x) == 4 and r:
        r = int(r)
        c = int(x[1]) // (r * r) if _known(x[1]) else Sym("pxs")
        out = (x[0], c, _scaled(x[2], r), _scaled(x[3], r))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _scaled(d, mult):
    return int(d) * mult if _known(d) else Sym("scaled")


def _rule_space_to_depth(ctx):
    x, b = ctx.in_shape("X"), ctx.attr("blocksize")
    out = None
    if x is not None and len(x) == 4 and b:
        b = int(b)
        c = int(x[1]) * b * b if _known(x[1]) else Sym("s2d")
        h = int(x[2]) // b if _known(x[2]) else Sym("s2d")
        w = int(x[3]) // b if _known(x[3]) else Sym("s2d")
        out = (x[0], c, h, w)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_dot(ctx):
    x = ctx.in_shape("X")
    ctx.set_out("Out", tuple(x[:-1]) if x is not None and len(x) else None,
                ctx.in_dtype("X"))


def _rule_addmm(ctx):
    x, y = ctx.in_shape("X"), ctx.in_shape("Y")
    out = None
    if x is not None and y is not None and len(x) == 2 and len(y) == 2:
        out = (x[0], y[1])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_batch_fc(ctx):
    x, w = ctx.in_shape("Input"), ctx.in_shape("W")
    out = None
    if x is not None and w is not None and len(x) == 3 and len(w) == 3:
        out = (x[0], x[1], w[2])
    ctx.set_out("Out", out, ctx.in_dtype("Input"))


def _rule_bilinear_tp(ctx):
    x, w = ctx.in_shape("X"), ctx.in_shape("Weight")
    out = None
    if x is not None and w is not None and len(x) == 2 and len(w) == 3:
        out = (x[0], w[0])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_scalar(out_slot="Out", dtype=None):
    def rule(ctx):
        ctx.set_out(out_slot, (),
                    dtype if dtype is not None else ctx.in_dtype("X"))

    return rule


def _rule_keepdim_reduce(axis_attr, keep_attr):
    """logsumexp/frobenius_norm-style: axis list attr or all-dims."""
    def rule(ctx):
        x = ctx.in_shape("X")
        if x is None or not len(x):
            ctx.set_out("Out", None if x is None else (), ctx.in_dtype("X"))
            return
        ax = ctx.attr(axis_attr)
        dims = set(range(len(x))) if not ax else \
            {int(d) % len(x) for d in
             ((ax,) if isinstance(ax, (int, np.integer)) else tuple(ax))}
        if ctx.attr(keep_attr, False):
            out = tuple(1 if i in dims else d for i, d in enumerate(x))
        else:
            out = tuple(d for i, d in enumerate(x) if i not in dims)
        ctx.set_out("Out", out, ctx.in_dtype("X"))

    return rule


def _rule_p_norm(ctx):
    x = ctx.in_shape("X")
    if x is None:
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    ax = ctx.attr("axis")
    keep = ctx.attr("keepdim", False)
    if ax is None:                       # ravel() then reduce axis 0
        ctx.set_out("Out", (1,) if keep else (), ctx.in_dtype("X"))
        return
    dims = {int(ax) % len(x)} if len(x) else set()
    out = tuple(1 if i in dims else d for i, d in enumerate(x)) if keep \
        else tuple(d for i, d in enumerate(x) if i not in dims)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_trace_op(ctx):
    x = ctx.in_shape("Input")
    out = None
    if x is not None and len(x) >= 2:
        a1 = int(ctx.attr("axis1", 0)) % len(x)
        a2 = int(ctx.attr("axis2", 1)) % len(x)
        out = tuple(d for i, d in enumerate(x) if i not in (a1, a2))
    ctx.set_out("Out", out, ctx.in_dtype("Input"))


def _rule_histogram(ctx):
    ctx.set_out("Out", (int(ctx.attr("bins", 100)),), np.dtype(np.int64))


def _rule_eye(ctx):
    rows = ctx.attr("num_rows")
    if rows is None:
        return
    cols = int(ctx.attr("num_columns", -1) or -1)
    out = (int(rows), cols if cols > 0 else int(rows))
    ctx.set_out("Out", out, _attr_dtype(ctx, "float32"))


def _attr_dtype(ctx, default=None):
    dt = ctx.attr("dtype", default)
    try:
        return np.dtype(dt) if dt is not None else None
    except TypeError:
        return None


def _rule_fill_values(ctx):
    shape = ctx.attr("shape")
    ctx.set_out("Out",
                None if shape is None else tuple(int(d) for d in shape),
                _attr_dtype(ctx, "float32"))


def _rule_diag(ctx):
    x = ctx.in_shape("Diagonal")
    out = None
    if x is not None and len(x) == 1 and _known(x[0]):
        out = (int(x[0]), int(x[0]))
    ctx.set_out("Out", out, ctx.in_dtype("Diagonal"))


def _rule_diag_v2(ctx):
    x = ctx.in_shape("X")
    off = abs(int(ctx.attr("offset", 0)))
    out = None
    if x is not None and len(x) == 1:
        n = int(x[0]) + off if _known(x[0]) else Sym("diag")
        out = (n, n)
    elif x is not None and len(x) == 2:
        out = None                      # diagonal length: declared fallback
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_diag_embed(ctx):
    x = ctx.in_shape("X")
    out = None
    if (x is not None and len(x) >= 1
            and int(ctx.attr("dim1", -2)) == -2
            and int(ctx.attr("dim2", -1)) == -1):
        off = abs(int(ctx.attr("offset", 0)))
        n = int(x[-1]) + off if _known(x[-1]) else Sym("diag")
        out = tuple(x[:-1]) + (n, n)
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_randperm(ctx):
    n = ctx.attr("n")
    ctx.set_out("Out", (int(n),) if n else None,
                _attr_dtype(ctx, "int64"))


def _rule_linspace(ctx):
    num = ctx.attr("num")
    ctx.set_out("Out", (int(num),) if num else (Sym("linspace"),),
                _attr_dtype(ctx, "float32"))


def _rule_range_op(ctx):
    # bounds are value-dependent: rank/dtype only
    ctx.set_out("Out", (Sym("range"),), ctx.in_dtype("Start"))


def _rule_meshgrid(ctx):
    n = ctx.n_inputs("X")
    shapes = [ctx.in_shape("X", i) for i in range(n)]
    if any(s is None or len(s) != 1 for s in shapes):
        return
    grid = tuple(s[0] for s in shapes)
    for i in range(len(ctx.op.outputs.get("Out", ()))):
        ctx.set_out("Out", grid, ctx.in_dtype("X", min(i, n - 1)), i=i)


def _rule_split(ctx):
    x = ctx.in_shape("X")
    outs = ctx.op.outputs.get("Out", ())
    if x is None or not len(x):
        for i in range(len(outs)):
            ctx.set_out("Out", None, ctx.in_dtype("X"), i=i)
        return
    axis = int(ctx.attr("axis", 0)) % len(x)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections")
    for i in range(len(outs)):
        out = list(x)
        if sections:
            out[axis] = int(sections[i]) if i < len(sections) else None
        elif num:
            out[axis] = (int(x[axis]) // int(num) if _known(x[axis])
                         else Sym("split"))
        else:
            out[axis] = Sym("split")
        ctx.set_out("Out", tuple(out), ctx.in_dtype("X"), i=i)


def _rule_flatten_range(ctx):
    x = ctx.in_shape("X")
    if x is None or not len(x):
        ctx.set_out("Out", None, ctx.in_dtype("X"))
        return
    start = int(ctx.attr("start_axis", 1)) % len(x)
    stop = int(ctx.attr("stop_axis", -1)) % len(x)
    mid = x[start:stop + 1]
    flat = int(np.prod([int(d) for d in mid])) \
        if all(_known(d) for d in mid) else Sym("flatten")
    ctx.set_out("Out", tuple(x[:start]) + (flat,) + tuple(x[stop + 1:]),
                ctx.in_dtype("X"))


def _rule_gather_nd(ctx):
    x, idx = ctx.in_shape("X"), ctx.in_shape("Index")
    out = None
    if (x is not None and idx is not None and len(idx) >= 1
            and _known(idx[-1]) and int(idx[-1]) <= len(x)):
        out = tuple(idx[:-1]) + tuple(x[int(idx[-1]):])
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_sequence_mask(ctx):
    x, maxlen = ctx.in_shape("X"), ctx.attr("maxlen")
    out = None
    if x is not None and maxlen:
        out = tuple(x) + (int(maxlen),)
    ctx.set_out("Y", out)


def _rule_multiplex(ctx):
    ctx.set_out("Out", ctx.in_shape("X", 0), ctx.in_dtype("X", 0))


def _rule_quant_cast(dtype):
    def rule(ctx):
        ctx.set_out("Output", ctx.in_shape("Input"), np.dtype(dtype))

    return rule


_INFER_RULES.update({
    # conv/pool variants (the layout + fusion pass families)
    "conv3d": _rule_conv_nd(3),
    "conv2d_transpose": _rule_conv_nd(2, transpose=True),
    "depthwise_conv2d_transpose": _rule_conv_nd(2, transpose=True),
    "conv3d_transpose": _rule_conv_nd(3, transpose=True),
    "pool3d": _rule_pool3d,
    "max_pool2d_with_index": _rule_pool_with_index(2),
    "max_pool3d_with_index": _rule_pool_with_index(3),
    "unfold": _rule_unfold,
    "pad3d": _rule_pad3d,
    "spp": _rule_spp,
    "pixel_shuffle": _rule_pixel_shuffle,
    "space_to_depth": _rule_space_to_depth,
    # BN/affine/channel-wise variants: value-wise in X
    "sync_batch_norm": _rule_same_as("X", "Y"),
    "affine_channel": _rule_same_as("X", "Out"),
    "temporal_shift": _rule_same_as("X", "Out"),
    "shuffle_channel": _rule_same_as("X", "Out"),
    "lrn": _rule_same_as("X", "Out"),
    "spectral_norm": _rule_same_as("Weight", "Out"),
    "conv_shift": _rule_same_as("X", "Out"),
    "pad_constant_like": _rule_same_as("X", "Out"),
    "lod_reset": _rule_same_as("X", "Out"),
    "fill_zeros_like2": _rule_same_as("X", "Out"),
    "cvm": _rule_same_as("X", "Y"),
    # collectives: shape-preserving on every member
    "allreduce": _rule_same_as("X", "Out"),
    "broadcast": _rule_same_as("X", "Out"),
    "c_broadcast": _rule_same_as("X", "Out"),
    "c_reduce_sum": _rule_same_as("X", "Out"),
    "c_reduce_max": _rule_same_as("X", "Out"),
    "c_reduce_min": _rule_same_as("X", "Out"),
    "c_reduce_prod": _rule_same_as("X", "Out"),
    # matmul variants
    "dot": _rule_dot,
    "addmm": _rule_addmm,
    "batch_fc": _rule_batch_fc,
    "bilinear_tensor_product": _rule_bilinear_tp,
    "cos_sim": _rule_keepdim_batch("Out"),
    "minus": _rule_elementwise,
    "smooth_l1": _rule_keepdim_batch("Out", extra_slots=("Diff",)),
    "squared_l2_distance": _rule_keepdim_batch(
        "Out", extra_slots=("sub_result",)),
    "rank_loss": _rule_same_as("Label", "Out"),
    # reductions to scalars / reduced shapes
    "reduce_all": _rule_reduce,
    "reduce_any": _rule_reduce,
    "logsumexp": _rule_keepdim_reduce("axis", "keepdim"),
    "frobenius_norm": _rule_keepdim_reduce("dim", "keep_dim"),
    "p_norm": _rule_p_norm,
    "l1_norm": _rule_scalar(),
    "dist": _rule_scalar(),
    "allclose": _rule_scalar(dtype=np.dtype(np.bool_)),
    "trace": _rule_trace_op,
    "histogram": _rule_histogram,
    # fills / generators
    "eye": _rule_eye,
    "fill": _rule_fill_values,
    "assign_value": _rule_fill_values,
    "diag": _rule_diag,
    "diag_v2": _rule_diag_v2,
    "diag_embed": _rule_diag_embed,
    "randint": _rule_fill_values,
    "randperm": _rule_randperm,
    "linspace": _rule_linspace,
    "range": _rule_range_op,
    "meshgrid": _rule_meshgrid,
    # data movement
    "split": _rule_split,
    "flatten_contiguous_range": _rule_flatten_range,
    "gather_nd": _rule_gather_nd,
    "sequence_mask": _rule_sequence_mask,
    "multiplex": _rule_multiplex,
    # int8 deployment path
    "quantize": _rule_quant_cast(np.int8),
    "dequantize": _rule_quant_cast(np.float32),
    "requantize": _rule_quant_cast(np.int8),
    # pass-emitted fused ops (static/passes.py): the fusion absorbs only
    # value-wise act + channel-wise BN / 1-D bias, so the output contract
    # is exactly the anchor op's (conv2d / mul respectively)
    "fused_conv2d_bn_act": _rule_conv2d,
    "fused_matmul_bias_act": _rule_mul,
    # quant_infer-emitted int8 inference ops (static/passes.py): int8
    # compute is internal, the op's IO contract is the float anchor's
    "quant_conv2d": _rule_conv2d,
    "quant_mul": _rule_mul,
})


# -- QAT fake-quant family (static/ops_tail.py): value-wise passthrough
#    (the quantized carrier keeps X's float dtype) plus a scale output ------

def _rule_fake_quant(ctx):
    x, dt = ctx.in_shape("X"), ctx.in_dtype("X")
    ctx.set_out("Out", x, dt)
    ctx.set_out("OutScale", (1,), dt)


def _rule_fake_quant_channel(ctx):
    """Channel-wise variants: OutScale has one entry per quant_axis slice."""
    x, dt = ctx.in_shape("X"), ctx.in_dtype("X")
    ctx.set_out("Out", x, dt)
    c = None
    if x is not None and len(x):
        c = x[int(ctx.attr("quant_axis", 0)) % len(x)]
    ctx.set_out("OutScale", None if c is None else (c,), dt)


def _rule_roi(ctx):
    """roi_align / roi_pool (static/ops.py): (R, C, ph, pw) where R is the
    ROI count and C is X's channel dim ((1,C,H,W) or (C,H,W))."""
    x, rois = ctx.in_shape("X"), ctx.in_shape("ROIs")
    out = None
    if x is not None and rois is not None and len(x) >= 3:
        c = x[1] if len(x) == 4 else x[0]
        out = (rois[0], c, int(ctx.attr("pooled_height", 1)),
               int(ctx.attr("pooled_width", 1)))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_grid_sampler(ctx):
    """Output spatial dims come from Grid (N, Hg, Wg, 2), channels from X."""
    x, g = ctx.in_shape("X"), ctx.in_shape("Grid")
    out = None
    if x is not None and g is not None and len(x) == 4 and len(g) == 4:
        out = (x[0], x[1], g[1], g[2])
    ctx.set_out("Output", out, ctx.in_dtype("X"))


def _rule_affine_grid(ctx):
    os = ctx.attr("output_shape")
    out = None
    if os is not None and len(os) == 4 and all(int(d) > 0 for d in os):
        out = (int(os[0]), int(os[2]), int(os[3]), 2)
    elif (th := ctx.in_shape("Theta")) is not None and len(th) == 3:
        out = (th[0], None, None, 2) if _known(th[0]) else None
    ctx.set_out("Output", out, ctx.in_dtype("Theta"))


def _rule_nll_loss(ctx):
    x, red = ctx.in_shape("X"), ctx.attr("reduction", "mean")
    out = None
    if x is not None:
        out = (x[0],) if red == "none" else ()
    ctx.set_out("Out", out, ctx.in_dtype("X"))
    ctx.set_out("Total_weight", (), np.dtype(np.float32))


def _rule_mean_iou(ctx):
    k = ctx.attr("num_classes")
    kshape = None if not k else (int(k),)
    ctx.set_out("OutMeanIou", (), np.dtype(np.float32))
    ctx.set_out("OutWrong", kshape, np.dtype(np.float32))
    ctx.set_out("OutCorrect", kshape, np.dtype(np.float32))


def _rule_unique_padded(ctx):
    """unique / unique_with_counts (static/ops_tail4.py): static-shape
    lowering pads Out/Index/Count(s) to len(X); ValidCount is scalar."""
    x = ctx.in_shape("X")
    idt = np.dtype(np.int64 if int(ctx.attr("dtype", 3)) == 3 else np.int32)
    ctx.set_out("Out", x, ctx.in_dtype("X"))
    for slot in ("Index", "Counts", "Count"):
        ctx.set_out(slot, x, idt)
    ctx.set_out("ValidCount", (), idt)


def _rule_where_index(ctx):
    """where_index (nonzero): padded (numel, rank) int64 + ValidCount."""
    x = ctx.in_shape("X")
    out = None
    if x is not None:
        if all(_known(d) for d in x):
            n = 1
            for d in x:
                n *= int(d)
            out = (n, max(1, len(x)))
        elif len(x) == 1:
            out = (x[0], 1)
    ctx.set_out("Out", out, np.dtype(np.int64))
    ctx.set_out("ValidCount", (), np.dtype(np.int64))


def _rule_amp_check(ctx):
    """amp_check_finite_and_scale: Out list mirrors the X list; the found-
    infinite flag is a (1,) bool."""
    for i in range(ctx.n_inputs("X")):
        ctx.set_out("Out", ctx.in_shape("X", i), ctx.in_dtype("X", i), i=i)
    ctx.set_out("FoundInfinite", (1,), np.dtype(np.bool_))


def _rule_edit_distance(ctx):
    h = ctx.in_shape("Hyps")
    ctx.set_out("Out", None if h is None else (h[0], 1),
                np.dtype(np.float32))
    ctx.set_out("SequenceNum", (1,), np.dtype(np.int32))


def _rule_kron(ctx):
    x, y = ctx.in_shape("X"), ctx.in_shape("Y")
    out = None
    if (x is not None and y is not None and len(x) == len(y)
            and all(_known(d) for d in x) and all(_known(d) for d in y)):
        out = tuple(int(a) * int(b) for a, b in zip(x, y))
    ctx.set_out("Out", out, ctx.in_dtype("X"))


def _rule_batch_column(out_slot, in_slot="X"):
    """Per-example losses that emit a (B, 1) column from a (B, C) input."""
    def rule(ctx):
        x = ctx.in_shape(in_slot)
        ctx.set_out(out_slot, None if x is None or not len(x) else (x[0], 1),
                    ctx.in_dtype(in_slot))

    return rule


def _rule_modified_huber(ctx):
    x, dt = ctx.in_shape("X"), ctx.in_dtype("X")
    ctx.set_out("IntermediateVal", x, dt)
    ctx.set_out("Out", x, dt)


_INFER_RULES.update({
    # QAT fake-quant / dequant (static/ops_tail.py, ops_tail5.py)
    "fake_quantize_abs_max": _rule_fake_quant,
    "fake_quantize_dequantize_abs_max": _rule_fake_quant,
    "fake_quantize_moving_average_abs_max": _rule_fake_quant,
    "fake_quantize_dequantize_moving_average_abs_max": _rule_fake_quant,
    "fake_quantize_range_abs_max": _rule_fake_quant,
    "moving_average_abs_max_scale": _rule_fake_quant,
    "fake_channel_wise_quantize_abs_max": _rule_fake_quant_channel,
    "fake_channel_wise_quantize_dequantize_abs_max":
        _rule_fake_quant_channel,
    "fake_quantize_dequantize_fixed_scale": _rule_unary,
    "fake_dequantize_max_abs": _rule_same_as(
        "X", "Out", dtype=np.dtype(np.float32)),
    "dequantize_abs_max": _rule_same_as(
        "X", "Out", dtype=np.dtype(np.float32)),
    "fake_channel_wise_dequantize_max_abs": _rule_same_as(
        "X", "Out", dtype=np.dtype(np.float32)),
    "dequantize_log": _rule_same_as(
        "X", "Out", dtype=np.dtype(np.float32)),
    # value-wise tails (verified against their lowerings)
    "row_conv": _rule_unary,
    "add_position_encoding": _rule_unary,
    "cross": _rule_unary,
    "cholesky": _rule_unary,
    "sigmoid_focal_loss": _rule_unary,
    "print": _rule_same_as("In", "Out"),
    "gather_tree": _rule_same_as("Ids", "Out"),
    "modified_huber_loss": _rule_modified_huber,
    "index_sample": _rule_same_as("Index", "Out"),
    # scalars / fixed shapes
    "is_empty": _rule_scalar(dtype=np.dtype(np.bool_)),
    "isfinite": lambda ctx: ctx.set_out("Out", (1,), np.dtype(np.bool_)),
    "seed": lambda ctx: ctx.set_out("Out", (1,), np.dtype(np.int32)),
    # losses
    "bpr_loss": _rule_batch_column("Y"),
    "teacher_student_sigmoid_loss": _rule_batch_column("Y"),
    "nll_loss": _rule_nll_loss,
    "mean_iou": _rule_mean_iou,
    "edit_distance": _rule_edit_distance,
    # search / movement with static-shape (padded) lowerings
    "unique": _rule_unique_padded,
    "unique_with_counts": _rule_unique_padded,
    "where_index": _rule_where_index,
    # masked_select's length is data-dependent: propagate dtype only
    "masked_select": lambda ctx: ctx.set_out("Y", None, ctx.in_dtype("X")),
    "amp_check_finite_and_scale": _rule_amp_check,
    # vision
    "roi_align": _rule_roi,
    "roi_pool": _rule_roi,
    "grid_sampler": _rule_grid_sampler,
    "affine_grid": _rule_affine_grid,
    # math
    "kron": _rule_kron,
})


def shape_rule_coverage() -> Dict[str, object]:
    """Declared engine coverage over the registered op set: which ops have
    a forward inference rule and/or a PV009 plausibility checker.  The
    uncovered list is the worklist — an uncovered op degrades gracefully
    (declared shapes), it does not go unchecked for dataflow/registry."""
    from . import ops as _ops  # noqa: F401 — populate the registry
    from .registry import registered_ops

    registered = set(registered_ops())
    inferred = set(_INFER_RULES) & registered
    checked = set(_SHAPE_CHECKERS) & registered
    covered = inferred | checked
    return {
        "registered": len(registered),
        "inference_rules": len(inferred),
        "plausibility_checkers": len(checked),
        "covered": len(covered),
        "coverage": round(len(covered) / max(1, len(registered)), 4),
        "uncovered": sorted(registered - covered),
    }


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def verify_program(program: Program, startup: Optional[Program] = None,
                   feed_names: Optional[Sequence[str]] = None,
                   fetch_names: Optional[Sequence[str]] = None
                   ) -> List[Diagnostic]:
    """Statically verify `program`; returns all diagnostics (errors and
    warnings).  Supplying `startup` additionally checks persistable
    initialization coverage (PV008); supplying `feed_names`/`fetch_names`
    narrows the feed assumption / marks fetches as reads."""
    diags, _engine = infer_program(program, startup, feed_names, fetch_names)
    return diags


def infer_program(program: Program, startup: Optional[Program] = None,
                  feed_names: Optional[Sequence[str]] = None,
                  fetch_names: Optional[Sequence[str]] = None):
    """verify_program, additionally returning the populated ``_ShapeEnv``
    (propagated shapes/dtypes, sub-block findings) — the input to the
    sharding-plan verifier in static/shardcheck.py."""
    v = _Verifier(program, startup, feed_names, fetch_names)
    diags = v.run()
    _m_programs_checked.inc()
    for d in diags:
        _m_violations.inc(code=d.code)
    return diags, v.engine


def check_program(program: Program, startup: Optional[Program] = None,
                  feed_names: Optional[Sequence[str]] = None,
                  fetch_names: Optional[Sequence[str]] = None
                  ) -> List[Diagnostic]:
    """verify_program + raise ``ProgramVerificationError`` carrying the
    structured diagnostics when any error-severity finding exists.  Returns
    the (warning-only) diagnostics otherwise."""
    diags = verify_program(program, startup, feed_names, fetch_names)
    errs = [d for d in diags if d.severity == "error"]
    if errs:
        raise _errors.ProgramVerificationError(
            "program verification failed (set "
            "PDTPU_FLAGS_check_program=0 to bypass):\n"
            + _errors.render_diagnostics(errs), diagnostics=errs)
    return diags


# ---------------------------------------------------------------------------
# Memoized Executor entry point + session log.
# ---------------------------------------------------------------------------

_memo_lock = threading.Lock()
# weakrefs to every Program that PASSED a cached check, with the version it
# passed at — tests/conftest.py re-verifies these at session end
_PASSED_PROGRAMS: List[tuple] = []


def check_program_cached(program: Program,
                         feed_names: Optional[Sequence[str]] = None,
                         fetch_names: Optional[Sequence[str]] = None
                         ) -> List[Diagnostic]:
    """check_program memoized by (program._version, feed-name set, fetch
    tuple) on the Program object itself (the memo dies with the program and
    invalidates on any mutation — Program bumps ``_version`` in append_op/
    create_var).  Serving buckets of one program share a single walk; a
    cold Executor.run of an already-checked program re-walks nothing.
    Failures are not memoized (they raise, and the build aborts anyway)."""
    key = (program._version,
           None if feed_names is None else frozenset(feed_names),
           tuple(fetch_names or ()))
    with _memo_lock:
        memo = getattr(program, "_analysis_memo", None)
        if memo is None:
            memo = program._analysis_memo = {}
        hit = memo.get(key)
    if hit is not None:
        return hit
    diags = check_program(program, feed_names=feed_names,
                          fetch_names=fetch_names)
    with _memo_lock:
        memo[key] = diags
        _PASSED_PROGRAMS.append(
            (weakref.ref(program), program._version, key[1], key[2]))
    return diags


def session_passed_programs():
    """Live (program, version, feed_names, fetch_names) tuples for every
    program that passed ``check_program_cached`` and is still alive —
    consumed by the test suite's end-of-session re-verification."""
    out = []
    with _memo_lock:
        entries = list(_PASSED_PROGRAMS)
    for ref, version, feeds, fetches in entries:
        prog = ref()
        if prog is not None:
            out.append((prog, version, feeds, fetches))
    return out
