"""Program verifier: static analysis over Program/Block/Operator IR.

Reference parity: the reference runs an entire pass ecosystem over
ProgramDesc before execution — `framework/ir/` graph passes,
`inference/analysis/` (analyzer.cc → ir_pass_manager.cc), and every
`PADDLE_ENFORCE*` site in `platform/enforce.h` carrying a typed error code.
Our TPU-native Executor traces a Program straight into jax.jit, so a
malformed program used to surface as an opaque JAX tracer error deep inside
a lowering rule.  This module is the missing compilation stage: it walks
every Block (descending through ``SUB_BLOCK_ATTRS``) *before any tracing*
and reports structured diagnostics.

Checks (diagnostic codes):

- ``PV001`` dataflow: an op input is not produced by an earlier op, a feed,
  a persistable, or a parameter (the trace would KeyError in the env dict).
- ``PV002`` dataflow (warning): a non-persistable temporary is written but
  never read or fetched — it silently inflates the trace.
- ``PV003`` registry: op type has no registered lowering and no DESCOPED
  rationale; a difflib nearest-name suggestion is attached.
- ``PV004`` registry: op type is DESCOPED (rationale attached) — it can
  never lower here.
- ``PV005`` structure: a sub-block index is out of range / not an int, or a
  known control-flow op is missing its block attr.
- ``PV006`` structure: an op carries a block-reference attr that is NOT in
  ``SUB_BLOCK_ATTRS`` — dataflow walkers (backward._effective_io, the
  Executor's _first_access scan) would go blind to reads inside its body
  (the hazard documented at framework.SUB_BLOCK_ATTRS).
- ``PV007`` structure: a ``@GRAD`` variable has no primal counterpart.
- ``PV008`` structure: a persistable read by the main program is never
  initialized by the startup program (only checked when a startup program
  is supplied).
- ``PV009`` shape/dtype: a per-op-type inference table propagates shapes
  through the block and flags statically-certain rank/dim/dtype
  mismatches (-1 / unknown dims are wildcards — never flagged).

Severity ``error`` aborts ``Executor.run`` (flag ``check_program``, default
on; ``PDTPU_FLAGS_check_program=0`` or ``set_flags({"check_program":
False})`` to skip); ``warning`` never does.  Diagnostics render through
``core.errors.render_diagnostics`` and raise
``core.errors.ProgramVerificationError``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import errors as _errors
from .backward import GRAD_SUFFIX
from .framework import SUB_BLOCK_ATTRS, Parameter, Program

__all__ = ["Diagnostic", "verify_program", "check_program"]


# Op types realized by the Executor itself (trace-time dispatch in
# executor._trace_ops) — they have no registry entry by design.
EXECUTOR_OPS = frozenset({
    "feed", "fetch", "backward_region", "conditional_block", "while",
    "static_rnn",
})

# Control-flow ops and the SUB_BLOCK_ATTRS attrs each must carry, plus the
# names their lowering injects into the sub-block env before tracing it
# (executor._lower_cond/_lower_while/_lower_static_rnn).
_BLOCK_OP_REQUIRED_ATTRS = {
    "conditional_block": ("true_block", "false_block"),
    "while": ("cond_block", "body_block"),
    "static_rnn": ("rnn_block",),
}

# Attrs whose values are *variable names read by the executor's lowering*
# (branch outputs, loop carries...) — they count as reads for PV002.
_NAME_LIST_ATTRS = ("true_outs", "false_outs", "body_outs", "mem_next",
                    "out_names")
_NAME_ATTRS = ("cond_out",)


@dataclass
class Diagnostic:
    """One structured finding (code, severity, location, fix-hint)."""

    code: str
    severity: str                 # "error" | "warning"
    message: str
    block: int = 0
    op_index: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    hint: Optional[str] = None

    def __str__(self):
        return _errors.render_diagnostics([self])


class _Verifier:
    def __init__(self, program: Program, startup: Optional[Program],
                 feed_names: Optional[Sequence[str]],
                 fetch_names: Optional[Sequence[str]]):
        self.program = program
        self.startup = startup
        # feed_names=None means "verifying without a concrete run": any
        # is_data var is assumed feedable.  A concrete feed dict narrows
        # that to the names actually fed.
        self.feed_names = None if feed_names is None else set(feed_names)
        self.fetch_names = set(fetch_names or ())
        self.diags: List[Diagnostic] = []
        self.reads: Set[str] = set()
        self.writes: Dict[str, Tuple[int, int, str]] = {}  # name -> site

    # -- reporting -----------------------------------------------------------
    def _emit(self, code, severity, message, block=0, op_index=None,
              op_type=None, var=None, hint=None):
        self.diags.append(Diagnostic(code, severity, message, block,
                                     op_index, op_type, var, hint))

    # -- entry ---------------------------------------------------------------
    def run(self) -> List[Diagnostic]:
        self._check_grad_pairing()
        if self.startup is not None:
            self._check_startup_init()
        defined = self._initial_defined(self.program.global_block())
        self._walk_block(0, defined, set())
        self._check_dead_temps()
        return self.diags

    # -- initial environment -------------------------------------------------
    def _initial_defined(self, block) -> Set[str]:
        """Names bound into the env before any op runs: feeds + persistable
        state (executor.run seeds env from `state` then `feeds`)."""
        defined = set()
        for v in self.program.list_vars():
            if v.persistable or isinstance(v, Parameter):
                defined.add(v.name)
            elif v.is_data:
                if self.feed_names is None or v.name in self.feed_names:
                    defined.add(v.name)
        if self.feed_names:
            defined |= self.feed_names
        return defined

    # -- block walk ----------------------------------------------------------
    def _walk_block(self, block_idx: int, defined: Set[str],
                    visiting: Set[int]) -> Set[str]:
        """Walk one block in execution order, growing `defined`; returns the
        defined-set after the last op (used for sub-block out checks)."""
        if block_idx in visiting:        # cyclic sub-block reference
            return defined
        visiting = visiting | {block_idx}
        block = self.program.blocks[block_idx]
        for op_idx, op in enumerate(block.ops):
            self._check_registry(block_idx, op_idx, op)
            self._check_structure(block_idx, op_idx, op)
            if op.type in ("feed", "fetch"):
                # executor skips these; feed outputs are env-bound by name
                defined |= set(op.output_names())
                continue
            # dataflow: every input must already be defined
            for name in op.input_names():
                self.reads.add(name)
                if name not in defined:
                    self._emit(
                        "PV001", "error",
                        f"op {op.type!r} reads {name!r} which is not "
                        "produced by any earlier op, feed, persistable, or "
                        "parameter",
                        block_idx, op_idx, op.type, name,
                        hint=self._pv001_hint(block, name))
            for attr in _NAME_LIST_ATTRS:
                for name in op.attrs.get(attr, ()) or ():
                    if isinstance(name, str):
                        self.reads.add(name)
            for attr in _NAME_ATTRS:
                name = op.attrs.get(attr)
                if isinstance(name, str):
                    self.reads.add(name)
            # descend into sub-blocks with the defined-set AT this op (the
            # lowering snapshots the env here: executor._arrays_only)
            for attr, sub_idx in self._sub_blocks(op):
                if not self._valid_block_idx(sub_idx):
                    continue            # PV005 already emitted
                injected = self._injected_names(op, attr)
                sub_defined = set(defined) | injected
                self._walk_block(int(sub_idx), sub_defined, visiting)
            self._check_shapes(block_idx, op_idx, op)
            for name in op.output_names():
                defined.add(name)
                self.writes.setdefault(name, (block_idx, op_idx, op.type))
        return defined

    def _pv001_hint(self, block, name) -> str:
        if not block.has_var(name):
            return (f"{name!r} is not declared in block {block.idx} or any "
                    "ancestor — check the op's input names")
        v = block.var(name)
        if v.is_data:
            return (f"{name!r} is a data var but was not fed — add it to "
                    "the feed dict")
        return (f"declare {name!r} persistable, feed it, or reorder the "
                "producing op before this one")

    @staticmethod
    def _sub_blocks(op):
        return op.sub_block_indices()

    def _valid_block_idx(self, idx) -> bool:
        return (isinstance(idx, (int, np.integer))
                and not isinstance(idx, bool)
                and 0 <= int(idx) < len(self.program.blocks))

    def _injected_names(self, op, attr) -> Set[str]:
        """Names the executor binds into a sub-block env before tracing it."""
        if op.type == "while":
            return set(op.inputs.get("X", ()))
        if op.type == "static_rnn":
            return (set(op.attrs.get("mem_names", ()))
                    | set(op.attrs.get("step_in_names", ())))
        return set()

    # -- registry soundness --------------------------------------------------
    def _check_registry(self, block_idx, op_idx, op):
        from . import ops as _ops  # noqa: F401 — populate the registry
        from .op_coverage import DESCOPED
        from .registry import is_registered, suggest_names

        if op.type in EXECUTOR_OPS or is_registered(op.type):
            return
        if op.type in DESCOPED:
            self._emit(
                "PV004", "error",
                f"op type {op.type!r} is descoped and can never lower here",
                block_idx, op_idx, op.type,
                hint=f"rationale: {DESCOPED[op.type]}")
            return
        suggestion = suggest_names(op.type)
        self._emit(
            "PV003", "error",
            f"op type {op.type!r} has no registered lowering",
            block_idx, op_idx, op.type,
            hint=suggestion or "register one with static.register_op")

    # -- structural soundness ------------------------------------------------
    def _check_structure(self, block_idx, op_idx, op):
        n_blocks = len(self.program.blocks)
        for attr in _BLOCK_OP_REQUIRED_ATTRS.get(op.type, ()):
            if attr not in op.attrs:
                self._emit(
                    "PV005", "error",
                    f"control-flow op {op.type!r} is missing its "
                    f"{attr!r} sub-block attr",
                    block_idx, op_idx, op.type,
                    hint="build it through static.cond/while_loop/StaticRNN")
        for attr, sub_idx in self._sub_blocks(op):
            if not self._valid_block_idx(sub_idx):
                self._emit(
                    "PV005", "error",
                    f"op {op.type!r} attr {attr!r} references block "
                    f"{sub_idx!r} but the program has {n_blocks} blocks",
                    block_idx, op_idx, op.type,
                    hint="sub-block attrs hold an index into program.blocks")
        # block-reference attrs the walkers cannot see (the framework.py
        # "walkers go blind" hazard): an int attr named *_block outside
        # SUB_BLOCK_ATTRS almost certainly references a block
        for attr, value in op.attrs.items():
            if (attr.endswith("_block") and attr not in SUB_BLOCK_ATTRS
                    and isinstance(value, (int, np.integer))
                    and not isinstance(value, bool)):
                self._emit(
                    "PV006", "error",
                    f"op {op.type!r} attr {attr!r} looks like a sub-block "
                    "reference but is not listed in "
                    "framework.SUB_BLOCK_ATTRS — dataflow walkers will not "
                    "descend into that block",
                    block_idx, op_idx, op.type,
                    hint="add the attr name to framework.SUB_BLOCK_ATTRS")

    # -- grad pairing --------------------------------------------------------
    def _check_grad_pairing(self):
        # program-wide primal pool: append_backward puts param grads in
        # block 0 even when the primal was created inside a sub-block
        # (StaticRNN parameters), so block-scoped lookup would false-flag
        all_names = {n for b in self.program.blocks for n in b.vars}
        for block in self.program.blocks:
            for name, v in block.vars.items():
                if not name.endswith(GRAD_SUFFIX):
                    continue
                primal = name[: -len(GRAD_SUFFIX)]
                if not block.has_var(primal) and primal not in all_names:
                    self._emit(
                        "PV007", "error",
                        f"grad var {name!r} has no primal {primal!r} "
                        "anywhere in the program",
                        block.idx, var=name,
                        hint="grad vars are created by append_backward/"
                             "gradients next to their primal")

    # -- startup coverage ----------------------------------------------------
    def _check_startup_init(self):
        initialized = set()
        for block in self.startup.blocks:
            for op in block.ops:
                initialized |= set(op.output_names())
        # a persistable the main program READS before any main-program op
        # writes it must come from startup (executor._needs_value semantics)
        for v in self.program.list_vars():
            if not v.persistable or v.name in initialized:
                continue
            if self._first_access(self.program.global_block(), v.name) == "read":
                self._emit(
                    "PV008", "error",
                    f"persistable {v.name!r} is read by the main program "
                    "but never initialized by the startup program",
                    var=v.name,
                    hint="append an init op for it to the startup program "
                         "(layers.create_parameter does this automatically)")

    def _first_access(self, block, name):
        for op in block.ops:
            if name in op.input_names():
                return "read"
            for _attr, sub_idx in self._sub_blocks(op):
                if self._valid_block_idx(sub_idx):
                    sub = self._first_access(self.program.blocks[sub_idx],
                                             name)
                    if sub == "read":
                        return "read"
            if name in op.output_names():
                return "write"
        return None

    # -- dead temporaries ----------------------------------------------------
    def _check_dead_temps(self):
        for name, (block_idx, op_idx, op_type) in self.writes.items():
            if name in self.reads or name in self.fetch_names:
                continue
            block = self.program.blocks[block_idx]
            try:
                v = block.var(name)
            except KeyError:
                v = None
            if v is not None and (v.persistable or v.is_data):
                continue
            self._emit(
                "PV002", "warning",
                f"temporary {name!r} (written by op {op_type!r}) is never "
                "read or fetched — it inflates the trace for nothing",
                block_idx, op_idx, op_type, name,
                hint="drop the op or fetch the value")

    # -- shape / dtype plausibility ------------------------------------------
    def _var_shape(self, block, name) -> Optional[Tuple[int, ...]]:
        try:
            v = block.var(name)
        except KeyError:
            return None
        shape = tuple(v.shape)
        return shape if shape else None   # () is "undeclared" in this IR

    def _var_dtype(self, block, name):
        try:
            return np.dtype(block.var(name).dtype)
        except KeyError:
            return None

    def _check_shapes(self, block_idx, op_idx, op):
        checker = _SHAPE_CHECKERS.get(op.type)
        if checker is None:
            return
        block = self.program.blocks[block_idx]

        def shape(slot, i=0):
            names = op.inputs.get(slot, ())
            return (self._var_shape(block, names[i])
                    if i < len(names) else None)

        def dtype(slot, i=0):
            names = op.inputs.get(slot, ())
            return (self._var_dtype(block, names[i])
                    if i < len(names) else None)

        for message, hint in checker(op, shape, dtype):
            self._emit("PV009", "error", message, block_idx, op_idx,
                       op.type, hint=hint)


# ---------------------------------------------------------------------------
# Shape/dtype inference table.  Each checker yields (message, hint) pairs;
# -1 and undeclared shapes are wildcards — only statically-certain
# mismatches are flagged.
# ---------------------------------------------------------------------------

def _dims_clash(a: int, b: int) -> bool:
    return a != -1 and b != -1 and a != b


def _broadcast_clash(x, y, axis):
    """Reference elementwise broadcasting (ops._bcast_axis): y aligns to x
    starting at `axis`; equal ranks and axis in (None, -1) fall back to
    numpy trailing alignment.  Dims clash only when both are known, neither
    is 1, and they differ."""
    if x is None or y is None:
        return None
    if len(y) > len(x):
        return None                      # x broadcasts into y; jnp handles it
    if len(y) == len(x) or axis in (None, -1):
        for i in range(1, len(y) + 1):
            dx, dy = x[-i], y[-i]
            if dx != 1 and dy != 1 and _dims_clash(dx, dy):
                return (f"trailing dim -{i}: x has {dx}, y has {dy} "
                        "(not broadcastable)")
        return None
    start = axis
    if start < 0 or start + len(y) > len(x):
        return f"y rank {len(y)} does not fit into x rank {len(x)} at axis {axis}"
    for i, dy in enumerate(y):
        dx = x[start + i]
        if dx != 1 and dy != 1 and _dims_clash(dx, dy):
            return (f"dim {start + i}: x has {dx}, y has {dy} "
                    "(not broadcastable)")
    return None


def _chk_elementwise(op, shape, dtype):
    clash = _broadcast_clash(shape("X"), shape("Y"),
                             op.attrs.get("axis", -1))
    if clash:
        yield (f"elementwise {op.type!r}: {clash}",
               "shapes must broadcast under the reference axis rule")


def _chk_mul(op, shape, dtype):
    x, y = shape("X"), shape("Y")
    if x is None or y is None:
        return
    xn = op.attrs.get("x_num_col_dims", 1)
    yn = op.attrs.get("y_num_col_dims", 1)
    xin = x[xn:]
    yin = y[:yn]
    if any(d == -1 for d in xin) or any(d == -1 for d in yin):
        return
    a, b = int(np.prod(xin or (1,))), int(np.prod(yin or (1,)))
    if a != b:
        yield (f"mul: x flattens to inner dim {a} (shape {x} at "
               f"x_num_col_dims={xn}) but y provides {b} (shape {y})",
               "inner dimensions must match")


def _chk_matmul(op, shape, dtype):
    x, y = shape("X"), shape("Y")
    if x is None or y is None or len(x) < 1 or len(y) < 1:
        return
    kx = x[-2] if (op.attrs.get("transpose_X") and len(x) >= 2) else x[-1]
    if len(y) == 1:
        ky = y[0]
    else:
        ky = y[-1] if op.attrs.get("transpose_Y") else y[-2]
    if _dims_clash(kx, ky):
        yield (f"matmul: contraction dims differ — x contributes {kx} "
               f"(shape {x}), y contributes {ky} (shape {y})",
               "check transpose_X/transpose_Y and operand shapes")


def _chk_cast(op, shape, dtype):
    if "out_dtype" not in op.attrs:
        yield ("cast: missing required attr 'out_dtype'",
               "set attrs={'out_dtype': <dtype>}")


def _chk_fill_constant(op, shape, dtype):
    if "shape" not in op.attrs:
        yield ("fill_constant: missing required attr 'shape'",
               "set attrs={'shape': (...), 'value': v}")


def _chk_concat(op, shape, dtype):
    ranks = set()
    for i, _ in enumerate(op.inputs.get("X", ())):
        s = shape("X", i)
        if s is not None:
            ranks.add(len(s))
    if len(ranks) > 1:
        yield (f"concat: inputs have differing ranks {sorted(ranks)}",
               "all concat inputs must share a rank")


def _chk_softmax_ce(op, shape, dtype):
    if op.attrs.get("soft_label", False):
        return
    dt = dtype("Label")
    if dt is not None and dt.kind not in ("i", "u"):
        yield (f"softmax_with_cross_entropy: hard labels must be integer, "
               f"got {dt.name}",
               "cast the label to int64 or set soft_label=True")
    lx, ll = shape("Logits"), shape("Label")
    if lx is not None and ll is not None and len(ll) == len(lx):
        if _dims_clash(ll[-1], 1):
            yield (f"softmax_with_cross_entropy: hard label last dim must "
                   f"be 1, got {ll}",
                   "labels carry one class index per row")


def _chk_lookup_table(op, shape, dtype):
    dt = dtype("Ids")
    if dt is not None and dt.kind not in ("i", "u"):
        yield (f"{op.type}: Ids must be integer, got {dt.name}",
               "cast the ids to int64")


def _chk_conv2d(op, shape, dtype):
    x, w = shape("Input"), shape("Filter")
    if x is None or w is None or len(x) != 4 or len(w) != 4:
        return
    groups = op.attrs.get("groups", 1) or 1
    cin = x[1] if op.attrs.get("data_format", "NCHW") == "NCHW" else x[-1]
    if _dims_clash(cin, w[1] * groups):
        yield (f"conv2d: input channels {cin} != filter in-channels "
               f"{w[1]} * groups {groups}",
               "filter shape is (out_c, in_c/groups, kh, kw)")


def _chk_reshape(op, shape, dtype):
    x = shape("X")
    tgt = op.attrs.get("shape")
    if x is None or not tgt or any(d == -1 for d in x):
        return
    tgt = tuple(int(d) for d in tgt)
    if any(d == -1 for d in tgt) or 0 in tgt:
        return
    if int(np.prod(x)) != int(np.prod(tgt)):
        yield (f"reshape: cannot reshape {x} ({int(np.prod(x))} elements) "
               f"to {tgt} ({int(np.prod(tgt))} elements)",
               "element counts must match (use -1 for one inferred dim)")


_SHAPE_CHECKERS = {
    "mul": _chk_mul,
    "matmul": _chk_matmul,
    "cast": _chk_cast,
    "fill_constant": _chk_fill_constant,
    "concat": _chk_concat,
    "softmax_with_cross_entropy": _chk_softmax_ce,
    "lookup_table": _chk_lookup_table,
    "embedding": _chk_lookup_table,
    "conv2d": _chk_conv2d,
    "reshape": _chk_reshape,
    "reshape2": _chk_reshape,
}
for _name in ("elementwise_add", "elementwise_sub", "elementwise_mul",
              "elementwise_div", "elementwise_max", "elementwise_min",
              "elementwise_pow", "elementwise_mod", "elementwise_floordiv"):
    _SHAPE_CHECKERS[_name] = _chk_elementwise


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def verify_program(program: Program, startup: Optional[Program] = None,
                   feed_names: Optional[Sequence[str]] = None,
                   fetch_names: Optional[Sequence[str]] = None
                   ) -> List[Diagnostic]:
    """Statically verify `program`; returns all diagnostics (errors and
    warnings).  Supplying `startup` additionally checks persistable
    initialization coverage (PV008); supplying `feed_names`/`fetch_names`
    narrows the feed assumption / marks fetches as reads."""
    return _Verifier(program, startup, feed_names, fetch_names).run()


def check_program(program: Program, startup: Optional[Program] = None,
                  feed_names: Optional[Sequence[str]] = None,
                  fetch_names: Optional[Sequence[str]] = None
                  ) -> List[Diagnostic]:
    """verify_program + raise ``ProgramVerificationError`` carrying the
    structured diagnostics when any error-severity finding exists.  Returns
    the (warning-only) diagnostics otherwise."""
    diags = verify_program(program, startup, feed_names, fetch_names)
    errs = [d for d in diags if d.severity == "error"]
    if errs:
        raise _errors.ProgramVerificationError(
            "program verification failed (set "
            "PDTPU_FLAGS_check_program=0 to bypass):\n"
            + _errors.render_diagnostics(errs), diagnostics=errs)
    return diags
