"""Static-op long tail, batch 4: the audited registry stragglers.

Reference parity targets: unique_op.cc / unique_with_counts_op.cc
(first-appearance dedup with inverse index), where_index_op.cc (nonzero
coordinates), hash_op.h (row-content hashing, num_hash seeds mod mod_by),
sequence_ops/sequence_enumerate_op.h (sliding id windows) and
sequence_erase_op.h (token removal), optimizers/proximal_adagrad_op.h +
proximal_gd_op.h (prox-operator updates), positive_negative_pair_op.h
(query-grouped ranking pair counts), the DGC family dgc_op.h /
optimizers/dgc_momentum_op.h / dgc_clip_by_norm_op.h, and root-collective
static parity for collective/c_reduce_op.h, c_scatter_op.cc, barrier_op.cc.

TPU-native contracts (static shapes, MXU/VPU-friendly):

- **Padded dynamic outputs**: ops whose reference output shape is
  data-dependent (`unique`, `where_index`, `sequence_erase`) emit a
  FIXED-shape tensor padded at the tail plus a scalar valid-count.  The
  count is returned under an EXTRA optional output slot (``ValidCount`` /
  ``Length``) that our DSL declares and an imported reference program
  simply omits — the executor binds only declared slots.  Valid entries
  always come first and keep reference order; pad entries are zeros.
- **unique order**: first-appearance order exactly like the reference's
  unordered_map walk (NOT sorted), via an O(n^2) equality matrix — unique
  is a host-side vocab-building op in every reference usage, so n is
  small and the matrix beats a serial scan on the VPU.
- **hash**: the reference hashes each row's raw bytes with XXH64(seed=i)
  % mod_by.  XXH64's 64-bit state doesn't vectorize on 32-bit VPU lanes;
  this lowering keeps the CONTRACT (deterministic hash of the whole row's
  content, num_hash independent seeds, values in [0, mod_by)) with an
  FNV-1a/avalanche mix in uint32 — any consumer (pyramid_hash embedding
  lookups) needs family determinism, not XXH64 bit-equality (documented
  divergence).
- **DGC top-k** is a magnitude-quantile threshold mask over the dense
  velocity buffer (ties may admit a few extra elements) — identical to
  the fleet DGC integration (optimizer/extras.dgc_compress); the
  reference's index+value encoding is a NCCL-gather wire format with no
  ICI counterpart.
- **c_reduce_* / c_scatter** keep root semantics on non-root members by
  passing the input through unchanged (the reference leaves non-root
  buffers untouched); `barrier` is an optimization_barrier — XLA's
  dataflow ordering makes a blocking rendezvous structurally unnecessary
  inside one program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod
from .registry import get_lowering, register_op


def _one(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


# =========================================================================
# unique / unique_with_counts (ref unique_op.cc UniqueOpFunctor)
# =========================================================================

def _unique_parts(x, index_dtype):
    """First-appearance unique of a 1-D array with static shapes.

    Returns (out_padded, inverse_index, counts_padded, valid_count):
    out_padded[r] = r-th distinct value in first-appearance order for
    r < valid_count, else 0.
    """
    n = x.shape[0]
    eq = x[:, None] == x[None, :]                    # (n, n)
    firstpos = jnp.argmax(eq, axis=1)                # first j with x[j]==x[i]
    is_first = firstpos == jnp.arange(n)
    rank = jnp.cumsum(is_first) - 1                  # dense id per first-occ
    index = rank[firstpos].astype(index_dtype)       # reference Index output
    # len(X)-padded static-shape contract  # proglint: dense-intermediate-ok
    out = jnp.zeros_like(x).at[
        jnp.where(is_first, rank, n)].set(x, mode="drop")
    counts = jnp.zeros((n,), index_dtype).at[index].add(1)
    valid = is_first.sum().astype(index_dtype)
    return out, index, counts, valid


def _index_dtype(attrs):
    d = attrs.get("dtype", "int64")
    if isinstance(d, str):
        return _dtype_mod.convert_dtype(d)
    return _dtype_mod.convert_dtype(d if d is not None else "int64")


@register_op("unique")
def _unique(ins, attrs, op):
    """ref unique_op.cc (is_sorted=False v1 path): 1-D X -> Out distinct
    values in first-appearance order + Index inverse mapping.  Padded
    contract above; ValidCount is the optional count slot."""
    x = _one(ins, "X")
    out, index, counts, valid = _unique_parts(x, _index_dtype(attrs))
    return {"Out": [out], "Index": [index], "Counts": [counts],
            "ValidCount": [valid]}


@register_op("unique_with_counts")
def _unique_with_counts(ins, attrs, op):
    """ref unique_with_counts_op.cc: unique + per-distinct-value Count
    (padded to len(X) like Out)."""
    x = _one(ins, "X")
    out, index, counts, valid = _unique_parts(x, _index_dtype(attrs))
    return {"Out": [out], "Index": [index], "Count": [counts],
            "ValidCount": [valid]}


@register_op("where_index")
def _where_index(ins, attrs, op):
    """ref where_index_op.cc (the `nonzero` static op): coordinates of
    nonzero elements, row-major order, int64 (numel, rank) — padded with
    zero rows past ValidCount."""
    x = _one(ins, "Condition")
    if x is None:
        x = _one(ins, "X")
    mask = jnp.reshape(x != 0, (-1,))
    n = mask.shape[0]
    coords = jnp.stack(
        jnp.unravel_index(jnp.arange(n), x.shape), axis=1).astype(jnp.int64)
    tgt = jnp.cumsum(mask) - 1
    out = jnp.zeros((n, x.ndim), jnp.int64).at[
        jnp.where(mask, tgt, n)].set(coords, mode="drop")
    return {"Out": [out], "ValidCount": [mask.sum().astype(jnp.int64)]}


# =========================================================================
# hash (ref hash_op.h HashKernel)
# =========================================================================

@register_op("hash")
def _hash(ins, attrs, op):
    """ref hash_op.h: Out[..., i, 0] = H_i(row bytes) % mod_by for
    num_hash seeds i.  Hash family divergence documented in the module
    docstring (uint32 FNV-1a + avalanche instead of XXH64)."""
    x = _one(ins, "X")
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    rows = x.reshape((-1, x.shape[-1])).astype(jnp.uint32)

    seeds = jnp.arange(num_hash, dtype=jnp.uint32)
    h = jnp.uint32(2166136261) ^ (seeds * jnp.uint32(0x9E3779B9))
    h = jnp.broadcast_to(h[None, :], (rows.shape[0], num_hash))

    def step(h, col):
        h = (h ^ col[:, None]) * jnp.uint32(16777619)        # FNV-1a round
        h = h ^ (h >> 15)
        h = h * jnp.uint32(0x85EBCA6B)                        # murmur avalanche
        return h ^ (h >> 13), None

    h, _ = jax.lax.scan(step, h, rows.T)
    out = (h % jnp.uint32(mod_by)).astype(jnp.int64)
    return {"Out": [out.reshape(x.shape[:-1] + (num_hash, 1))]}


# =========================================================================
# sequence_enumerate / sequence_erase (dense (B, T) + Length layout, the
# same contract as every sequence op in this rebuild)
# =========================================================================

@register_op("sequence_enumerate")
def _sequence_enumerate(ins, attrs, op):
    """ref sequence_enumerate_op.h: per position t of each sequence emit
    the window [x[t], ..., x[t+win-1]] with positions past the sequence
    end replaced by pad_value.  Dense: X (B, T) ids + Length (B,) ->
    Out (B, T, win_size); rows at t >= length are all pad."""
    x = _one(ins, "X")
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
    lengths = _one(ins, "Length")
    B, T = x.shape
    win = int(attrs["win_size"])
    pad = jnp.asarray(attrs.get("pad_value", 0), x.dtype)
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    pos = jnp.arange(T)[:, None] + jnp.arange(win)[None, :]       # (T, win)
    gathered = x[:, jnp.minimum(pos, T - 1)]                      # (B, T, win)
    valid = pos[None, :, :] < lengths.astype(jnp.int32)[:, None, None]
    return {"Out": [jnp.where(valid, gathered, pad)]}


@register_op("sequence_erase")
def _sequence_erase(ins, attrs, op):
    """ref sequence_erase_op.h: drop every occurrence of attr `tokens`
    from each sequence, left-compacting survivors.  Dense: X (B, T) +
    Length (B,) -> Out (B, T) zero-padded + new lengths under the
    optional Length output slot (the reference carries them as LoD)."""
    x = _one(ins, "X")
    squeeze = x.ndim == 3 and x.shape[-1] == 1
    if squeeze:
        x = x[..., 0]
    lengths = _one(ins, "Length")
    B, T = x.shape
    if lengths is None:
        lengths = jnp.full((B,), T, jnp.int32)
    in_len = jnp.arange(T)[None, :] < lengths.astype(jnp.int32)[:, None]
    tokens = np.asarray(list(attrs.get("tokens", [])), np.int64)
    hit = jnp.zeros_like(x, dtype=bool)
    for t in tokens:
        hit = hit | (x == jnp.asarray(t, x.dtype))
    keep = in_len & ~hit
    tgt = jnp.cumsum(keep, axis=1) - 1                            # (B, T)
    # same-shape compaction contract  # proglint: dense-intermediate-ok
    out = jnp.zeros_like(x).at[
        jnp.arange(B)[:, None],
        jnp.where(keep, tgt, T)].set(x, mode="drop")
    new_len = keep.sum(axis=1).astype(jnp.int64)
    if squeeze:
        out = out[..., None]
    return {"Out": [out], "Length": [new_len]}


# =========================================================================
# proximal optimizers (ref optimizers/proximal_{adagrad,gd}_op.h)
# =========================================================================

def _prox(prox_param, lr, l1, l2):
    """The prox operator both kernels share: soft-threshold by lr*l1 then
    shrink by 1/(1+lr*l2)."""
    if l1 > 0:
        return (jnp.sign(prox_param)
                * jnp.maximum(jnp.abs(prox_param) - lr * l1, 0.0)
                / (1.0 + lr * l2))
    return prox_param / (1.0 + lr * l2)


@register_op("proximal_adagrad")
def _proximal_adagrad(ins, attrs, op):
    """ref proximal_adagrad_op.h: m += g^2; prox(p - lr*g/sqrt(m))."""
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m = _one(ins, "Moment")
    lr = _one(ins, "LearningRate").astype(p.dtype).reshape(())
    l1, l2 = float(attrs.get("l1", 0.0)), float(attrs.get("l2", 0.0))
    m_out = m + g * g
    prox_param = p - lr * g / jnp.sqrt(m_out)
    return {"ParamOut": [_prox(prox_param, lr, l1, l2)], "MomentOut": [m_out]}


@register_op("proximal_gd")
def _proximal_gd(ins, attrs, op):
    """ref proximal_gd_op.h: prox(p - lr*g)."""
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    lr = _one(ins, "LearningRate").astype(p.dtype).reshape(())
    l1, l2 = float(attrs.get("l1", 0.0)), float(attrs.get("l2", 0.0))
    return {"ParamOut": [_prox(p - lr * g, lr, l1, l2)]}


# =========================================================================
# positive_negative_pair (ref positive_negative_pair_op.h)
# =========================================================================

@register_op("positive_negative_pair")
def _positive_negative_pair(ins, attrs, op):
    """ref positive_negative_pair_op.h: over every same-query pair with
    differing labels, a pair is positive when score and label order agree,
    otherwise negative; equal scores ALSO count as neutral (the reference
    adds the pair to both neutral and negative — kept bit-for-bit).
    Dense O(B^2) pair matrix instead of the per-query hash-map walk."""
    score = _one(ins, "Score")
    label = _one(ins, "Label").reshape(-1).astype(score.dtype)
    query = _one(ins, "QueryID").reshape(-1)
    weight = _one(ins, "Weight")
    w = (weight.reshape(-1).astype(score.dtype) if weight is not None
         else jnp.ones_like(label))
    col = int(attrs.get("column", -1))
    s = score[:, col]
    n = s.shape[0]
    i = jnp.arange(n)
    pair = (i[:, None] < i[None, :]) & (query[:, None] == query[None, :]) \
        & (label[:, None] != label[None, :])
    wij = (w[:, None] + w[None, :]) * 0.5
    agree = (s[:, None] - s[None, :]) * (label[:, None] - label[None, :]) > 0
    tie = s[:, None] == s[None, :]
    zero = jnp.zeros((), score.dtype)
    pos = jnp.where(pair & agree, wij, zero).sum()
    neg = jnp.where(pair & ~agree, wij, zero).sum()
    neu = jnp.where(pair & tie, wij, zero).sum()
    for slot, acc in (("AccumulatePositivePair", "pos"),
                      ("AccumulateNegativePair", "neg"),
                      ("AccumulateNeutralPair", "neu")):
        a = _one(ins, slot)
        if a is not None:
            if acc == "pos":
                pos = pos + a.reshape(())
            elif acc == "neg":
                neg = neg + a.reshape(())
            else:
                neu = neu + a.reshape(())
    one = jnp.ones((1,), score.dtype)
    return {"PositivePair": [pos * one], "NegativePair": [neg * one],
            "NeutralPair": [neu * one]}


# =========================================================================
# DGC op family (ref dgc_op.h, optimizers/dgc_momentum_op.h,
# dgc_clip_by_norm_op.h) — the same math the fleet dp-axis integration
# uses (optimizer/extras.dgc_compress), exposed under the reference op
# names/slots for program parity.
# =========================================================================

def _scalar(v, default=0.0):
    return jnp.reshape(v, ()) if v is not None else jnp.asarray(default)


@register_op("dgc")
def _dgc(ins, attrs, op):
    """ref dgc_op.h DGCOpKernel: regularize grad (x nranks), momentum
    correction into U/V, magnitude top-k of V as the communicated sparse
    gradient, residual error feedback left in V.  Gated on
    current_step >= rampup_begin_step (before the gate: plain pass
    through, Grad_out still regularized — matching the kernel's early
    return after writing Grad_out)."""
    u, v, g, p = (_one(ins, "U"), _one(ins, "V"), _one(ins, "Grad"),
                  _one(ins, "Param"))
    step = _scalar(_one(ins, "current_step"))
    nranks = _scalar(_one(ins, "nranks"), 1.0).astype(g.dtype)
    m = float(attrs.get("m", 0.9))
    use_nesterov = bool(attrs.get("use_nesterov", False))
    sparsity = [float(x) for x in attrs.get("sparsity", [0.999])]
    rampup_begin = float(attrs.get("rampup_begin_step", 0.0))
    rampup_step = float(attrs.get("rampup_step", 1.0))
    coeff = float(attrs.get("regular_coeff", 0.0))
    rtype = int(attrs.get("regular_type", 0))

    grad_out = nranks * g
    if rtype == 1:
        grad_out = grad_out + coeff * jnp.sign(p)
    elif rtype == 2:
        grad_out = grad_out + coeff * p

    # period sparsity (get_period_sparcity): index into the warmup table
    cur = jnp.maximum(step - rampup_begin, 0.0)
    tbl = jnp.asarray(sparsity, jnp.float32)
    idx = jnp.minimum((cur * len(sparsity) / rampup_step).astype(jnp.int32),
                      len(sparsity) - 1)
    ratio = 1.0 - tbl[idx]

    if use_nesterov:
        u_new = m * (u + grad_out)
        v_new = u_new + v + grad_out
    else:
        u_new = m * u + grad_out
        v_new = v + u_new

    # top-k by magnitude via quantile threshold (module docstring)
    thr = jnp.quantile(jnp.abs(v_new).ravel().astype(jnp.float32),
                       jnp.clip(1.0 - ratio, 0.0, 1.0))
    mask = jnp.abs(v_new) >= thr.astype(v_new.dtype)
    encode = jnp.where(mask, v_new, jnp.zeros_like(v_new))

    use_dgc = step >= rampup_begin
    k = jnp.where(use_dgc, ratio * v_new.size, float(v_new.size))
    return {
        "U_out": [jnp.where(use_dgc, u_new, u)],
        "V_out": [jnp.where(use_dgc, v_new - encode, v)],
        "EncodeGrad": [jnp.where(use_dgc, encode, grad_out)],
        "Grad_out": [grad_out],
        "k": [k.astype(jnp.float32).reshape(1)],
        "GatherBuff": [jnp.zeros_like(g)],  # NCCL gather scratch: unused on ICI
    }


@register_op("dgc_momentum")
def _dgc_momentum(ins, attrs, op):
    """ref dgc_momentum_op.h: Grad_out = g/nranks always; before the
    rampup gate run the momentum update, after it plain SGD (both on the
    ORIGINAL Grad input, like the delegated kernels)."""
    g = _one(ins, "Grad")
    v = _one(ins, "Velocity")
    step = _scalar(_one(ins, "current_step"))
    nranks = _scalar(_one(ins, "nranks"), 1.0).astype(g.dtype)
    rampup_begin = float(attrs.get("rampup_begin_step", 0.0))

    mom = get_lowering("momentum")(ins, attrs, op)
    sgd = get_lowering("sgd")(ins, attrs, op)
    use_sgd = step >= rampup_begin
    return {
        "ParamOut": [jnp.where(use_sgd, sgd["ParamOut"][0],
                               mom["ParamOut"][0])],
        "VelocityOut": [jnp.where(use_sgd, v, mom["VelocityOut"][0])],
        "Grad_out": [g / nranks],
    }


@register_op("dgc_clip_by_norm")
def _dgc_clip_by_norm(ins, attrs, op):
    """ref dgc_clip_by_norm_op.h: clip_by_norm, active only once
    current_step >= rampup_begin_step."""
    x = _one(ins, "X")
    step = _scalar(_one(ins, "current_step"))
    rampup_begin = float(attrs.get("rampup_begin_step", 0.0))
    clipped = get_lowering("clip_by_norm")(ins, attrs, op)["Out"][0]
    return {"Out": [jnp.where(step >= rampup_begin, clipped, x)]}


# =========================================================================
# root collectives (ref collective/c_reduce_op.h, c_scatter_op.cc,
# collective/barrier_op.cc) — static parity for the eager
# parallel/collective.py family
# =========================================================================

def _data_axis():
    from ..parallel import collective as _coll

    return _coll.bound_data_axis()


def _c_reduce(reduce_fn):
    def rule(ins, attrs, op):
        x = _one(ins, "X")
        axis = _data_axis()
        if axis is None:
            return {"Out": [x]}
        root = int(attrs.get("root_id", attrs.get("root", 0)))
        red = reduce_fn(x, axis)
        # non-root members keep their input unchanged (c_reduce_op.h only
        # writes the root's recv buffer)
        return {"Out": [jnp.where(jax.lax.axis_index(axis) == root, red, x)]}

    return rule


register_op("c_reduce_sum")(_c_reduce(jax.lax.psum))
register_op("c_reduce_max")(_c_reduce(jax.lax.pmax))
register_op("c_reduce_min")(_c_reduce(jax.lax.pmin))
register_op("c_reduce_prod")(_c_reduce(
    # NOT exp(psum(log)): negatives must keep their sign
    lambda x, ax: jnp.prod(jax.lax.all_gather(x, ax), axis=0)))


@register_op("c_scatter")
def _c_scatter(ins, attrs, op):
    """ref c_scatter_op.cc: the root's (nranks*per, ...) buffer is split
    along dim 0; member i receives slice i."""
    x = _one(ins, "X")
    axis = _data_axis()
    if axis is None:
        return {"Out": [x]}
    root = int(attrs.get("root", attrs.get("root_id", 0)))
    idx = jax.lax.axis_index(axis)
    xroot = jax.lax.psum(
        jnp.where(idx == root, x, jnp.zeros_like(x)), axis)
    n = int(attrs.get("nranks", 0)) or jax.lax.psum(1, axis)
    per = x.shape[0] // n
    return {"Out": [jax.lax.dynamic_slice_in_dim(xroot, idx * per, per, 0)]}


@register_op("barrier")
def _barrier(ins, attrs, op):
    """ref collective/barrier_op.cc: a blocking rendezvous around NCCL
    streams.  Inside one XLA program ordering is dataflow; the closest
    faithful artifact is an optimization barrier (prevents reordering /
    fusion across the point) plus a real psum rendezvous when an axis is
    bound."""
    xs = ins.get("X", [])
    if not xs:
        return {}
    axis = _data_axis()
    outs = [jax.lax.optimization_barrier(x) for x in xs]
    if axis is not None:
        token = jax.lax.psum(jnp.zeros((), outs[0].dtype), axis)
        outs = [o + token.astype(o.dtype) for o in outs]
    return {"Out": outs}
