"""Static-graph program model: Program / Block / Operator / Variable.

Reference parity: python/paddle/fluid/framework.py — `Variable` (:869),
`Operator` (:1861), `Block` (:2452), `Program` (:3914), `Parameter` (:5033),
global default programs (:5243/:5277), program_guard; the serialized form in
the reference is framework.proto (ProgramDesc :212 ⊃ BlockDesc :174 ⊃
OpDesc :42 / VarDesc :165).

TPU-native design (SURVEY.md §7 step 1-3): the program IS the IR, but its
execution semantics are "lower to one jaxpr/HLO per (program, feed-spec) and
jit" rather than a per-op interpreter loop — see static/executor.py.  Ops
therefore carry no kernels; each op type has a registered *lowering rule*
(static/registry.py) that emits jax computations when the Executor traces the
block.  Grad ops are not materialized per-op: append_backward records a
backward region differentiated with jax.grad at lowering time
(static/backward.py), which XLA fuses/CSEs with the forward.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core import dtype as _dtype_mod

__all__ = [
    "Variable", "Parameter", "Operator", "Block", "Program",
    "default_main_program", "default_startup_program", "program_guard",
    "unique_name", "name_scope", "SUB_BLOCK_ATTRS",
]

# Every attr name through which a control-flow op references a sub-block
# (by block index).  Dataflow walkers (backward._effective_io, the
# Executor's _first_access precondition scan) descend through these; a new
# block-carrying op MUST add its attr here or those walkers go blind to
# reads inside its body.
SUB_BLOCK_ATTRS = ("true_block", "false_block", "cond_block", "body_block",
                   "rnn_block")


class _UniqueNames(threading.local):
    def __init__(self):
        self.counters: Dict[str, int] = {}

    def generate(self, prefix: str) -> str:
        i = self.counters.get(prefix, 0)
        self.counters[prefix] = i + 1
        return f"{prefix}_{i}"


_unique = _UniqueNames()


def unique_name(prefix: str = "tmp") -> str:
    """ref: fluid/unique_name.py generate()."""
    return _unique.generate(prefix)


class Variable:
    """Symbolic tensor in a Block (ref framework.py:869).  Shape may contain
    -1 (batch) — concrete shapes bind at feed time."""

    def __init__(self, block: "Block", name: str, shape: Sequence[int],
                 dtype="float32", persistable: bool = False,
                 stop_gradient: bool = False, is_data: bool = False):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = _dtype_mod.convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data

    @property
    def ndim(self):
        return len(self.shape)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={np.dtype(self.dtype).name}, "
                f"persistable={self.persistable})")

    # operator sugar lowers to ops in the current block (ref Variable's
    # monkey-patched math ops, fluid/layers/math_op_patch.py)
    def _binary(self, other, op_type):
        from . import layers as L
        return L._elementwise(op_type, self, other)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")


class Parameter(Variable):
    """Persistable trainable variable (ref framework.py:5033); `trainable`
    and `initializer` drive append_backward and the startup program."""

    def __init__(self, block, name, shape, dtype="float32", trainable=True,
                 initializer=None, regularizer=None):
        super().__init__(block, name, shape, dtype, persistable=True,
                         stop_gradient=not trainable)
        self.trainable = trainable
        self.initializer = initializer
        self.regularizer = regularizer


class Operator:
    """One node: type + named input/output slots (lists of var names) + attrs
    (ref OpDesc framework.proto:42; framework.py:1861)."""

    def __init__(self, block: "Block", type: str,
                 inputs: Optional[Dict[str, List[str]]] = None,
                 outputs: Optional[Dict[str, List[str]]] = None,
                 attrs: Optional[Dict[str, Any]] = None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # Stable PRNG salt: the Executor salts per-op randomness by (block,
        # op index) unless this is set.  The pass manager stamps rewritten
        # programs with each op's pre-rewrite index so random draws survive
        # op insertion/removal (golden parity depends on it).
        self.rng_salt: Optional[int] = None

    def input_names(self) -> List[str]:
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self) -> List[str]:
        return [n for vs in self.outputs.values() for n in vs]

    def sub_block_indices(self) -> List[tuple]:
        """(attr_name, block_index) for every sub-block this op references —
        the one sanctioned way for dataflow walkers (backward._effective_io,
        Executor._first_access, static/analysis.py) to descend, so a new
        block-carrying op only has to extend SUB_BLOCK_ATTRS."""
        return [(a, self.attrs[a]) for a in SUB_BLOCK_ATTRS
                if a in self.attrs]

    def __repr__(self):
        return f"Operator({self.type}, in={self.inputs}, out={self.outputs})"


class Block:
    """Ordered op list + var table (ref BlockDesc; framework.py:2452)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    def create_var(self, name=None, shape=(), dtype="float32", **kw) -> Variable:
        name = name or unique_name("tmp")
        v = Variable(self, name, shape, dtype, **kw)
        self.vars[name] = v  # proglint: raw-mutation-ok — Block IS the API
        self.program._version += 1
        return v

    def create_parameter(self, name, shape, dtype="float32", trainable=True,
                         initializer=None, regularizer=None) -> Parameter:
        p = Parameter(self, name, shape, dtype, trainable, initializer,
                      regularizer)
        self.vars[name] = p  # proglint: raw-mutation-ok — Block IS the API
        self.program._parameters[name] = p
        self.program._version += 1
        return p

    def var(self, name: str) -> Variable:
        b: Optional[Block] = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = (self.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        raise KeyError(f"variable {name!r} not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def append_op(self, type: str, inputs=None, outputs=None, attrs=None
                  ) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)  # proglint: raw-mutation-ok — Block IS the API
        self.program._version += 1
        return op

    # -- sanctioned structural mutation (the pass-manager API) --------------
    # Every mutation bumps `program._version`: the analysis memo
    # (check_program_cached), the shardcheck memo, and the Executor's hot
    # cache are all version-keyed, so a mutated program can never be served
    # a stale verdict or a stale executable.  Mutating `block.ops` directly
    # bypasses that invalidation — proglint PL006 flags it.

    def insert_op(self, index: int, type: str, inputs=None, outputs=None,
                  attrs=None) -> Operator:
        """Insert an op at `index` (ref BlockDesc::InsertOp)."""
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)  # proglint: raw-mutation-ok
        self.program._version += 1
        return op

    def remove_op(self, index: int) -> Operator:
        """Remove and return the op at `index` (ref BlockDesc::RemoveOp)."""
        op = self.ops.pop(index)  # proglint: raw-mutation-ok
        self.program._version += 1
        return op

    def replace_op(self, index: int, type: str, inputs=None, outputs=None,
                   attrs=None) -> Operator:
        """Replace the op at `index` in place, preserving its position (and
        therefore the PRNG salts of every other op)."""
        op = Operator(self, type, inputs, outputs, attrs)
        op.rng_salt = self.ops[index].rng_salt
        self.ops[index] = op  # proglint: raw-mutation-ok
        self.program._version += 1
        return op

    def set_ops(self, new_ops) -> None:
        """Bulk-replace this block's op list — for whole-graph rewrites
        that rebuild the list in one sweep (slim's quant passes)."""
        self.ops = list(new_ops)  # proglint: raw-mutation-ok
        self.program._version += 1

    def remove_var(self, name: str) -> None:
        """Drop a var from this block's table (dead-var elimination)."""
        if name in self.vars:
            del self.vars[name]  # proglint: raw-mutation-ok
            self.program._parameters.pop(name, None)
            self.program._version += 1

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]


class Program:
    """ref framework.py:3914.  `_version` invalidates the Executor's compiled
    cache whenever the graph mutates."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self._parameters: Dict[str, Parameter] = {}
        self._version = 0
        self.random_seed: Optional[int] = None
        self._current_block_idx = 0

    def bump_version(self) -> int:
        """Explicitly invalidate version-keyed caches (analysis memo,
        shardcheck memo, Executor hot cache).  The Block mutation API calls
        this path implicitly; passes that edit op slots/attrs in place must
        call it themselves."""
        self._version += 1
        return self._version

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        """Open a sub-block (ref Program._create_block): ops appended while it
        is current land in it — the control-flow builders (cond/while_loop)
        wrap callbacks with this."""
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)  # proglint: raw-mutation-ok — Program IS the API
        self._current_block_idx = b.idx
        self._version += 1
        return b

    def _rollback(self) -> None:
        """Close the current sub-block (ref Program._rollback)."""
        self._current_block_idx = self.current_block().parent_idx
        if self._current_block_idx < 0:
            self._current_block_idx = 0

    def all_parameters(self) -> List[Parameter]:
        return list(self._parameters.values())

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        """Shallow structural clone (ref Program.clone): for_test drops ops
        after the last fetchable var is produced is NOT emulated; instead,
        `is_test`-sensitive ops (dropout, batch_norm) read the attr set
        here."""
        import copy
        p = Program()
        p.random_seed = self.random_seed
        b = p.global_block()
        src = self.global_block()
        for name, v in src.vars.items():
            if isinstance(v, Parameter):
                b.create_parameter(name, v.shape, v.dtype, v.trainable,
                                   v.initializer, v.regularizer)
            else:
                b.create_var(name, v.shape, v.dtype,
                             persistable=v.persistable,
                             stop_gradient=v.stop_gradient,
                             is_data=v.is_data)
        for op in src.ops:
            attrs = dict(op.attrs)
            if for_test and op.type in ("dropout", "batch_norm"):
                attrs["is_test"] = True
            b.append_op(op.type, op.inputs, op.outputs,
                        attrs).rng_salt = op.rng_salt
        return p

    def to_string(self, throw_on_error=False) -> str:
        lines = [f"Program(version={self._version})"]
        for blk in self.blocks:
            lines.append(f" Block {blk.idx}:")
            for v in blk.vars.values():
                lines.append(f"  {v!r}")
            for op in blk.ops:
                lines.append(f"  {op!r}")
        return "\n".join(lines)

    def __repr__(self):
        return self.to_string()


class _ProgramState(threading.local):
    def __init__(self):
        self.main = Program()
        self.startup = Program()


_state = _ProgramState()


def default_main_program() -> Program:
    """ref framework.py:5277."""
    return _state.main


def default_startup_program() -> Program:
    """ref framework.py:5243."""
    return _state.startup


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """ref framework.py program_guard."""
    old_main, old_startup = _state.main, _state.startup
    _state.main = main_program
    if startup_program is not None:
        _state.startup = startup_program
    try:
        yield
    finally:
        _state.main, _state.startup = old_main, old_startup


@contextlib.contextmanager
def name_scope(prefix: str):
    """ref framework.py name_scope — cosmetic; names stay flat here."""
    yield
