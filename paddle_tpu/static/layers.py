"""Static-graph layers DSL: functions that append ops to the default program.

Reference parity: python/paddle/fluid/layers/nn.py (~200 functions appending
OpDescs through `LayerHelper.append_op`, layer_helper.py:42) — this is the
working subset that builds the book models (MNIST MLP/LeNet, word2vec-class
embedding models): data, fc, conv2d, pool2d, batch_norm, embedding,
activations, losses, metrics, shape ops.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

from ..nn import initializer as I
from .framework import (
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)

__all__ = [
    "data", "fc", "conv2d", "pool2d", "batch_norm", "embedding", "dropout",
    "relu", "sigmoid", "tanh", "softmax", "cross_entropy", "square_error_cost",
    "softmax_with_cross_entropy", "mean", "reduce_sum", "reduce_mean",
    "accuracy", "reshape", "transpose", "concat", "split", "flatten", "cast",
    "scale", "fill_constant", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_mod",
    "elementwise_floordiv", "elementwise_max", "elementwise_min",
    "elementwise_pow", "matmul", "topk", "argmax", "argmin", "clip",
    "create_parameter",
    # long tail (same registry coverage as static/ops.py)
    "exp", "log", "sqrt", "square", "abs", "floor", "ceil", "round", "sign",
    "erf", "reciprocal", "rsqrt", "sin", "cos", "tan", "asin", "acos",
    "atan", "sinh", "cosh", "logsigmoid", "gelu", "leaky_relu", "elu",
    "relu6", "selu", "mish", "silu", "swish", "softplus", "softsign",
    "hard_sigmoid", "hard_swish", "log_softmax", "pow", "shape", "squeeze",
    "unsqueeze", "stack", "expand", "tile", "slice", "gather", "gather_nd",
    "scatter", "where", "one_hot", "cumsum", "fill_zeros_like", "pad",
    "layer_norm", "sigmoid_cross_entropy_with_logits", "log_loss",
    "label_smooth", "l2_normalize", "huber_loss", "smooth_l1", "kldiv_loss",
    "mse_loss",
]


# -- helper (ref LayerHelper, fluid/layer_helper.py) -------------------------

def _main_block():
    return default_main_program().current_block()


def _startup_block():
    return default_startup_program().current_block()


def _init_attrs(initializer, shape, dtype):
    """Map an nn.initializer instance to a startup init op (type, attrs) —
    the reference does this via initializer ops appended to the startup
    program (fluid/initializer.py)."""
    shape = list(shape)
    base = {"shape": shape, "dtype": np.dtype(dtype).name}
    if initializer is None or isinstance(initializer, I.XavierUniform):
        fan_in = shape[0] if len(shape) >= 1 else 1
        fan_out = shape[1] if len(shape) >= 2 else fan_in
        if len(shape) > 2:  # conv kernels: receptive field scaling
            rf = int(np.prod(shape[2:]))
            fan_in, fan_out = shape[1] * rf, shape[0] * rf
        bound = math.sqrt(6.0 / (fan_in + fan_out))
        return "uniform_random", {**base, "min": -bound, "max": bound}
    if isinstance(initializer, I.Constant):
        return "fill_constant", {**base, "value": float(initializer.value)}
    if isinstance(initializer, I.Normal):
        return "gaussian_random", {**base, "mean": initializer.mean,
                                   "std": initializer.std}
    if isinstance(initializer, I.TruncatedNormal):
        return "truncated_gaussian_random", {**base, "mean": initializer.mean,
                                             "std": initializer.std}
    if isinstance(initializer, I.Uniform):
        return "uniform_random", {**base, "min": initializer.low,
                                  "max": initializer.high}
    raise NotImplementedError(
        f"no startup-op mapping for initializer {type(initializer).__name__}")


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     default_initializer=None, trainable=True) -> Parameter:
    """Create a Parameter in the main program + its init op in startup
    (ref layer_helper_base.py create_parameter).

    A string ``attr`` (or ``ParamAttr(name=...)``) names the parameter;
    re-using a name SHARES the existing parameter (the reference's
    ``param_attr='shared_w'`` weight-sharing idiom, e.g. the word2vec book
    model's common embedding table) — shapes must then match."""
    initializer = getattr(attr, "initializer", None) or default_initializer
    attr_name = attr if isinstance(attr, str) else getattr(attr, "name", None)
    name = name or attr_name or unique_name("param")
    existing = _main_block().program._parameters.get(name)
    if existing is not None:
        if tuple(existing.shape) != tuple(shape):
            raise ValueError(
                f"shared parameter {name!r} has shape {existing.shape}, "
                f"requested {tuple(shape)}")
        if np.dtype(existing.dtype) != np.dtype(dtype):
            raise ValueError(
                f"shared parameter {name!r} has dtype {existing.dtype}, "
                f"requested {dtype}")
        # first creation wins for trainable/initializer (the reference's
        # ParamAttr sharing semantics); shape+dtype are validated above
        return existing
    p = _main_block().create_parameter(name, shape, dtype, trainable,
                                       initializer)
    sp = _startup_block()
    sp.create_parameter(name, shape, dtype, trainable, initializer)
    op_type, attrs = _init_attrs(initializer, shape, dtype)
    sp.append_op(op_type, outputs={"Out": [name]}, attrs=attrs)
    return p


def _out(dtype="float32", shape=()):
    return _main_block().create_var(shape=shape, dtype=dtype)


def _append(op_type, inputs, outputs, attrs=None):
    return _main_block().append_op(op_type, inputs, outputs, attrs)


def _apply_act(out: Variable, act: Optional[str]) -> Variable:
    if act is None:
        return out
    res = _out(out.dtype, out.shape)
    _append(act, {"X": [out.name]}, {"Out": [res.name]})
    return res


# -- inputs ------------------------------------------------------------------

def data(name, shape, dtype="float32", append_batch_size=True) -> Variable:
    """ref fluid/layers/io.py data / fluid.data."""
    shape = list(shape)
    if append_batch_size and (not shape or shape[0] != -1):
        shape = [-1] + shape
    v = _main_block().create_var(name=name, shape=shape, dtype=dtype,
                                 is_data=True, stop_gradient=True)
    return v


# -- dense / conv ------------------------------------------------------------

def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None) -> Variable:
    """ref fluid/layers/nn.py fc — mul + elementwise_add + act."""
    in_dim = int(np.prod(input.shape[num_flatten_dims:]))
    w = create_parameter((in_dim, size), input.dtype, attr=param_attr,
                         name=f"{name}.w" if name else None)
    out_shape = tuple(input.shape[:num_flatten_dims]) + (size,)
    tmp = _out(input.dtype, out_shape)
    _append("mul", {"X": [input.name], "Y": [w.name]}, {"Out": [tmp.name]},
            {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
    if bias_attr is not False:
        b = create_parameter((size,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0),
                             name=f"{name}.b" if name else None)
        tmp2 = _out(input.dtype, out_shape)
        _append("elementwise_add", {"X": [tmp.name], "Y": [b.name]},
                {"Out": [tmp2.name]}, {"axis": len(out_shape) - 1})
        tmp = tmp2
    return _apply_act(tmp, act)


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _spatial_out(size, k, s, p, d=1, ceil=False):
    if size < 0:
        return -1
    eff = d * (k - 1) + 1
    num = size + 2 * p - eff
    return (num + s - 1) // s + 1 if ceil else num // s + 1


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None
           ) -> Variable:
    """ref fluid/layers/nn.py conv2d (NCHW)."""
    ks = _pair(filter_size)
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    cin = input.shape[1]
    w = create_parameter((num_filters, cin // groups, ks[0], ks[1]),
                         input.dtype, attr=param_attr,
                         name=f"{name}.w" if name else None)
    h = _spatial_out(input.shape[2], ks[0], st[0], pd[0], dl[0])
    wd = _spatial_out(input.shape[3], ks[1], st[1], pd[1], dl[1])
    out = _out(input.dtype, (input.shape[0], num_filters, h, wd))
    inputs = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = create_parameter((num_filters,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0),
                             name=f"{name}.b" if name else None)
        inputs["Bias"] = [b.name]
    _append("conv2d", inputs, {"Output": [out.name]},
            {"strides": stride, "paddings": padding, "dilations": dilation,
             "groups": groups})
    return _apply_act(out, act)


def pool2d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, adaptive=False) -> Variable:
    """ref fluid/layers/nn.py pool2d."""
    ks = _pair(pool_size)
    st = _pair(pool_stride if pool_stride is not None else pool_size)
    pd = _pair(pool_padding)
    if global_pooling:
        shape = (input.shape[0], input.shape[1], 1, 1)
    elif adaptive:
        shape = (input.shape[0], input.shape[1], ks[0], ks[1])
    else:
        shape = (input.shape[0], input.shape[1],
                 _spatial_out(input.shape[2], ks[0], st[0], pd[0]),
                 _spatial_out(input.shape[3], ks[1], st[1], pd[1]))
    out = _out(input.dtype, shape)
    _append("pool2d", {"X": [input.name]}, {"Out": [out.name]},
            {"pooling_type": pool_type, "ksize": pool_size,
             "strides": pool_stride if pool_stride is not None else pool_size,
             "paddings": pool_padding, "global_pooling": global_pooling,
             "adaptive": adaptive})
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, is_test=False,
               param_attr=None, bias_attr=None, name=None) -> Variable:
    """ref fluid/layers/nn.py batch_norm — scale/bias trainable, mean/var
    persistable non-trainable state updated by the op."""
    c = input.shape[1]
    base = name or unique_name("batch_norm")
    scale = create_parameter((c,), input.dtype, attr=param_attr,
                             default_initializer=I.Constant(1.0),
                             name=f"{base}.scale")
    bias = create_parameter((c,), input.dtype, attr=bias_attr,
                            default_initializer=I.Constant(0.0),
                            name=f"{base}.bias")
    mean = create_parameter((c,), input.dtype, trainable=False,
                            default_initializer=I.Constant(0.0),
                            name=f"{base}.mean")
    var = create_parameter((c,), input.dtype, trainable=False,
                           default_initializer=I.Constant(1.0),
                           name=f"{base}.var")
    out = _out(input.dtype, input.shape)
    _append("batch_norm",
            {"X": [input.name], "Scale": [scale.name], "Bias": [bias.name],
             "Mean": [mean.name], "Variance": [var.name]},
            {"Y": [out.name], "MeanOut": [mean.name],
             "VarianceOut": [var.name]},
            {"momentum": momentum, "epsilon": epsilon, "is_test": is_test})
    return _apply_act(out, act)


def embedding(input, size, padding_idx=None, param_attr=None,
              dtype="float32", name=None, is_sparse=False) -> Variable:
    """ref fluid/layers/nn.py embedding (lookup_table_v2).  ``is_sparse``
    selects the dedup'd segment-sum gradient (SelectedRows analogue)."""
    w = create_parameter(size, dtype, attr=param_attr,
                         default_initializer=I.Normal(0.0, 1.0),
                         name=f"{name}.w" if name else None)
    out = _out(dtype, tuple(input.shape) + (size[1],))
    _append("lookup_table_v2", {"Ids": [input.name], "W": [w.name]},
            {"Out": [out.name]},
            {"padding_idx": -1 if padding_idx is None else padding_idx,
             "is_sparse": bool(is_sparse)})
    return out


def dropout(x, dropout_prob=0.5, is_test=False,
            dropout_implementation="upscale_in_train") -> Variable:
    out = _out(x.dtype, x.shape)
    _append("dropout", {"X": [x.name]}, {"Out": [out.name]},
            {"dropout_prob": dropout_prob, "is_test": is_test,
             "dropout_implementation": dropout_implementation})
    return out


# -- activations / math ------------------------------------------------------

def _unary(op_type, x) -> Variable:
    out = _out(x.dtype, x.shape)
    _append(op_type, {"X": [x.name]}, {"Out": [out.name]})
    return out


def relu(x):
    return _unary("relu", x)


def sigmoid(x):
    return _unary("sigmoid", x)


def tanh(x):
    return _unary("tanh", x)


def softmax(x, axis=-1) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("softmax", {"X": [x.name]}, {"Out": [out.name]}, {"axis": axis})
    return out


def _to_variable(x, like: Variable) -> Variable:
    if isinstance(x, Variable):
        return x
    v = _out(like.dtype, ())
    _append("fill_constant", {}, {"Out": [v.name]},
            {"shape": [], "dtype": np.dtype(like.dtype).name,
             "value": float(x)})
    return v


def _elementwise(op_type, x, y, axis=-1) -> Variable:
    y = _to_variable(y, x)
    out = _out(x.dtype, x.shape)
    _append(op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]},
            {"axis": axis})
    return out


def elementwise_add(x, y, axis=-1, act=None):
    return _apply_act(_elementwise("elementwise_add", x, y, axis), act)


def elementwise_sub(x, y, axis=-1):
    return _elementwise("elementwise_sub", x, y, axis)


def elementwise_mul(x, y, axis=-1):
    return _elementwise("elementwise_mul", x, y, axis)


def elementwise_div(x, y, axis=-1):
    return _elementwise("elementwise_div", x, y, axis)


def elementwise_mod(x, y, axis=-1):
    return _elementwise("elementwise_mod", x, y, axis)


def elementwise_floordiv(x, y, axis=-1):
    return _elementwise("elementwise_floordiv", x, y, axis)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0) -> Variable:
    out = _out(x.dtype, (-1,) * max(x.ndim, y.ndim))
    _append("matmul", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]},
            {"transpose_X": transpose_x, "transpose_Y": transpose_y,
             "alpha": alpha})
    return out


def mean(x) -> Variable:
    out = _out(x.dtype, ())
    _append("mean", {"X": [x.name]}, {"Out": [out.name]})
    return out


def reduce_sum(x, dim=None, keep_dim=False) -> Variable:
    out = _out(x.dtype, (-1,) * x.ndim if keep_dim else ())
    _append("reduce_sum", {"X": [x.name]}, {"Out": [out.name]},
            {"dim": [dim] if isinstance(dim, int) else dim,
             "keep_dim": keep_dim, "reduce_all": dim is None})
    return out


def reduce_mean(x, dim=None, keep_dim=False) -> Variable:
    out = _out(x.dtype, (-1,) * x.ndim if keep_dim else ())
    _append("reduce_mean", {"X": [x.name]}, {"Out": [out.name]},
            {"dim": [dim] if isinstance(dim, int) else dim,
             "keep_dim": keep_dim, "reduce_all": dim is None})
    return out


def cast(x, dtype) -> Variable:
    out = _out(dtype, x.shape)
    _append("cast", {"X": [x.name]}, {"Out": [out.name]},
            {"out_dtype": np.dtype(dtype).name if not isinstance(dtype, str)
             else dtype})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("scale", {"X": [x.name]}, {"Out": [out.name]},
            {"scale": scale, "bias": bias,
             "bias_after_scale": bias_after_scale})
    return out


def clip(x, min, max) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("clip", {"X": [x.name]}, {"Out": [out.name]},
            {"min": min, "max": max})
    return out


def fill_constant(shape, dtype, value) -> Variable:
    out = _out(dtype, tuple(shape))
    _append("fill_constant", {}, {"Out": [out.name]},
            {"shape": list(shape), "dtype": np.dtype(dtype).name
             if not isinstance(dtype, str) else dtype, "value": value})
    return out


# -- shape ops ---------------------------------------------------------------

def reshape(x, shape) -> Variable:
    out = _out(x.dtype, tuple(shape))
    xshape = _out(x.dtype, ())
    _append("reshape2", {"X": [x.name]},
            {"Out": [out.name], "XShape": [xshape.name]},
            {"shape": list(shape)})
    return out


def transpose(x, perm) -> Variable:
    out = _out(x.dtype, tuple(x.shape[p] for p in perm))
    xshape = _out(x.dtype, ())
    _append("transpose2", {"X": [x.name]},
            {"Out": [out.name], "XShape": [xshape.name]}, {"axis": list(perm)})
    return out


def flatten(x, axis=1) -> Variable:
    lead = x.shape[:axis]
    tail = x.shape[axis:]
    d0 = -1 if any(s < 0 for s in lead) else int(np.prod(lead)) if lead else 1
    d1 = -1 if any(s < 0 for s in tail) else int(np.prod(tail))
    out = _out(x.dtype, (d0, d1))
    xshape = _out(x.dtype, ())
    _append("flatten2", {"X": [x.name]},
            {"Out": [out.name], "XShape": [xshape.name]}, {"axis": axis})
    return out


def concat(inputs, axis=0) -> Variable:
    # infer shape: concat dim sums (unknown if any input unknown), other
    # dims copy the first statically-known size (downstream fc/create_
    # parameter derive weight shapes from this metadata)
    ndim = inputs[0].ndim
    ax = axis % ndim
    shape = []
    for d in range(ndim):
        dims = [v.shape[d] for v in inputs]
        if d == ax:
            shape.append(-1 if any(s < 0 for s in dims) else int(sum(dims)))
        else:
            known = [s for s in dims if s >= 0]
            shape.append(known[0] if known else -1)
    out = _out(inputs[0].dtype, tuple(shape))
    _append("concat", {"X": [v.name for v in inputs]}, {"Out": [out.name]},
            {"axis": axis})
    return out


def split(x, num_or_sections, dim=0):
    ax = dim % x.ndim
    if isinstance(num_or_sections, int):
        n = num_or_sections
        attrs = {"num": n, "axis": dim}
        if x.shape[ax] >= 0 and x.shape[ax] % n != 0:
            raise ValueError(
                f"split: dimension {ax} of size {x.shape[ax]} is not "
                f"divisible into {n} equal sections")
        sizes = [x.shape[ax] // n if x.shape[ax] >= 0 else -1] * n
    else:
        n = len(num_or_sections)
        attrs = {"sections": list(num_or_sections), "num": 0, "axis": dim}
        sizes = [int(v) for v in num_or_sections]
    shapes = [tuple(sz if d == ax else x.shape[d] for d in range(x.ndim))
              for sz in sizes]
    outs = [_out(x.dtype, shp) for shp in shapes]
    _append("split", {"X": [x.name]}, {"Out": [o.name for o in outs]}, attrs)
    return outs


# -- loss / metrics ----------------------------------------------------------

def square_error_cost(input, label) -> Variable:
    """ref fluid/layers/loss.py square_error_cost: (input - label)^2."""
    out = _out(input.dtype, input.shape)
    _append("square_error_cost", {"X": [input.name], "Label": [label.name]},
            {"Out": [out.name]})
    return out


def cross_entropy(input, label, soft_label=False) -> Variable:
    out = _out(input.dtype, input.shape[:-1] + (1,))
    _append("cross_entropy", {"X": [input.name], "Label": [label.name]},
            {"Y": [out.name]}, {"soft_label": soft_label})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False):
    loss = _out(logits.dtype, logits.shape[:-1] + (1,))
    sm = _out(logits.dtype, logits.shape)
    _append("softmax_with_cross_entropy",
            {"Logits": [logits.name], "Label": [label.name]},
            {"Loss": [loss.name], "Softmax": [sm.name]},
            {"soft_label": soft_label, "ignore_index": ignore_index})
    return (loss, sm) if return_softmax else loss


def accuracy(input, label, k=1) -> Variable:
    acc = _out("float32", ())
    correct = _out("int32", ())
    total = _out("int32", ())
    _append("accuracy", {"Out": [input.name], "Label": [label.name]},
            {"Accuracy": [acc.name], "Correct": [correct.name],
             "Total": [total.name]}, {"k": k})
    return acc


def topk(x, k=1):
    vals = _out(x.dtype, x.shape[:-1] + (k,))
    idx = _out("int32", x.shape[:-1] + (k,))
    _append("top_k", {"X": [x.name]},
            {"Out": [vals.name], "Indices": [idx.name]}, {"k": k})
    return vals, idx


def argmax(x, axis=-1) -> Variable:
    out = _out("int64", x.shape[:axis] + x.shape[axis + 1:])
    _append("arg_max", {"X": [x.name]}, {"Out": [out.name]}, {"axis": axis})
    return out


# -- DSL long tail (ref fluid/layers/nn.py ~200 fns; this block closes the
# gap for every lowering static/ops.py already registers) ---------------------

def _unary_attr(op_type, x, **attrs) -> Variable:
    out = _out(x.dtype, x.shape)
    _append(op_type, {"X": [x.name]}, {"Out": [out.name]}, attrs or None)
    return out


def exp(x):
    return _unary("exp", x)


def log(x):
    return _unary("log", x)


def sqrt(x):
    return _unary("sqrt", x)


def square(x):
    return _unary("square", x)


def abs(x):  # noqa: A001 — fluid.layers.abs shadows builtins there too
    return _unary("abs", x)


def floor(x):
    return _unary("floor", x)


def ceil(x):
    return _unary("ceil", x)


def round(x):  # noqa: A001
    return _unary("round", x)


def sign(x):
    return _unary("sign", x)


def erf(x):
    return _unary("erf", x)


def reciprocal(x):
    return _unary("reciprocal", x)


def rsqrt(x):
    return _unary("rsqrt", x)


def sin(x):
    return _unary("sin", x)


def cos(x):
    return _unary("cos", x)


def tan(x):
    return _unary("tan", x)


def asin(x):
    return _unary("asin", x)


def acos(x):
    return _unary("acos", x)


def atan(x):
    return _unary("atan", x)


def sinh(x):
    return _unary("sinh", x)


def cosh(x):
    return _unary("cosh", x)


def logsigmoid(x):
    return _unary("logsigmoid", x)


def gelu(x):
    return _unary("gelu", x)


def leaky_relu(x, alpha=0.02):
    return _unary_attr("leaky_relu", x, alpha=alpha)


def elu(x, alpha=1.0):
    return _unary_attr("elu", x, alpha=alpha)


def relu6(x):
    return _unary("relu6", x)


def selu(x):
    return _unary("selu", x)


def mish(x):
    return _unary("mish", x)


def silu(x):
    return _unary("silu", x)


def swish(x):
    return _unary("swish", x)


def softplus(x):
    return _unary("softplus", x)


def softsign(x):
    return _unary("softsign", x)


def hard_sigmoid(x, slope=0.2, offset=0.5):
    return _unary_attr("hard_sigmoid", x, slope=slope, offset=offset)


def hard_swish(x):
    return _unary("hard_swish", x)


def log_softmax(x, axis=-1):
    return _unary_attr("log_softmax", x, axis=axis)


def pow(x, factor=1.0):  # noqa: A001
    return _unary_attr("pow", x, factor=factor)


def elementwise_max(x, y, axis=-1):
    return _elementwise("elementwise_max", x, y, axis)


def elementwise_min(x, y, axis=-1):
    return _elementwise("elementwise_min", x, y, axis)


def elementwise_pow(x, y, axis=-1):
    return _elementwise("elementwise_pow", x, y, axis)


# -- shape / index manipulation ----------------------------------------------

def shape(x) -> Variable:
    out = _out("int64", (x.ndim,))
    _append("shape", {"Input": [x.name]}, {"Out": [out.name]})
    return out


def squeeze(x, axes=()) -> Variable:
    shp = [s for i, s in enumerate(x.shape)
           if not ((axes and i in axes) or (not axes and s == 1))]
    out = _out(x.dtype, tuple(shp))
    xshape = _out(x.dtype, ())
    _append("squeeze2", {"X": [x.name]},
            {"Out": [out.name], "XShape": [xshape.name]},
            {"axes": list(axes)})
    return out


def unsqueeze(x, axes) -> Variable:
    axes = [axes] if isinstance(axes, int) else list(axes)
    shp = list(x.shape)
    for a in sorted(axes):
        shp.insert(a if a >= 0 else a + len(shp) + 1, 1)
    out = _out(x.dtype, tuple(shp))
    xshape = _out(x.dtype, ())
    _append("unsqueeze2", {"X": [x.name]},
            {"Out": [out.name], "XShape": [xshape.name]}, {"axes": axes})
    return out


def stack(inputs, axis=0) -> Variable:
    shp = list(inputs[0].shape)
    shp.insert(axis if axis >= 0 else axis + len(shp) + 1, len(inputs))
    out = _out(inputs[0].dtype, tuple(shp))
    _append("stack", {"X": [v.name for v in inputs]}, {"Y": [out.name]},
            {"axis": axis})
    return out


def expand(x, shape) -> Variable:
    out = _out(x.dtype, tuple(shape))
    _append("expand_v2", {"X": [x.name]}, {"Out": [out.name]},
            {"shape": list(shape)})
    return out


def tile(x, repeat_times) -> Variable:
    shp = tuple(-1 if s < 0 else s * r
                for s, r in zip(x.shape, repeat_times))
    out = _out(x.dtype, shp)
    _append("tile", {"X": [x.name]}, {"Out": [out.name]},
            {"repeat_times": list(repeat_times)})
    return out


def slice(x, axes, starts, ends) -> Variable:  # noqa: A001
    shp = list(x.shape)
    for a, s, e in zip(axes, starts, ends):
        if shp[a] >= 0:
            lo = s if s >= 0 else shp[a] + s
            hi = min(e, shp[a]) if e >= 0 else shp[a] + e
            shp[a] = max(hi - lo, 0)
    out = _out(x.dtype, tuple(shp))
    _append("slice", {"Input": [x.name]}, {"Out": [out.name]},
            {"axes": list(axes), "starts": list(starts), "ends": list(ends)})
    return out


def gather(x, index, axis=0) -> Variable:
    shp = list(x.shape)
    shp[axis] = index.shape[0] if index.ndim else 1
    out = _out(x.dtype, tuple(shp))
    _append("gather", {"X": [x.name], "Index": [index.name]},
            {"Out": [out.name]}, {"axis": axis})
    return out


def gather_nd(x, index) -> Variable:
    out = _out(x.dtype, tuple(index.shape[:-1]))
    _append("gather_nd", {"X": [x.name], "Index": [index.name]},
            {"Out": [out.name]})
    return out


def scatter(x, index, updates, overwrite=True) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("scatter", {"X": [x.name], "Ids": [index.name],
                        "Updates": [updates.name]},
            {"Out": [out.name]}, {"overwrite": overwrite})
    return out


def where(condition, x, y) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("where", {"Condition": [condition.name], "X": [x.name],
                      "Y": [y.name]}, {"Out": [out.name]})
    return out


def one_hot(x, depth) -> Variable:
    out = _out("float32", tuple(x.shape) + (depth,))
    _append("one_hot_v2", {"X": [x.name]}, {"Out": [out.name]},
            {"depth": depth})
    return out


def cumsum(x, axis=None, exclusive=False, reverse=False) -> Variable:
    out = _out(x.dtype, x.shape if axis is not None else (-1,))
    _append("cumsum", {"X": [x.name]}, {"Out": [out.name]},
            {"axis": axis, "exclusive": exclusive, "reverse": reverse,
             "flatten": axis is None})
    return out


def argmin(x, axis=-1) -> Variable:
    shp = tuple(s for i, s in enumerate(x.shape)
                if i != (axis if axis >= 0 else axis + x.ndim))
    out = _out("int64", shp)
    _append("arg_min", {"X": [x.name]}, {"Out": [out.name]}, {"axis": axis})
    return out


def fill_zeros_like(x) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("fill_zeros_like", {"X": [x.name]}, {"Out": [out.name]})
    return out


def pad(x, paddings, pad_value=0.0) -> Variable:
    shp = tuple(s + paddings[2 * i] + paddings[2 * i + 1] if s >= 0 else -1
                for i, s in enumerate(x.shape))
    out = _out(x.dtype, shp)
    _append("pad", {"X": [x.name]}, {"Out": [out.name]},
            {"paddings": list(paddings), "pad_value": pad_value})
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None) -> Variable:
    """ref fluid/layers/nn.py layer_norm."""
    n = int(np.prod(input.shape[begin_norm_axis:]))
    ins = {"X": [input.name]}
    if scale:
        s = create_parameter((n,), input.dtype, attr=param_attr,
                             default_initializer=I.Constant(1.0))
        ins["Scale"] = [s.name]
    if shift:
        b = create_parameter((n,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0))
        ins["Bias"] = [b.name]
    out = _out(input.dtype, input.shape)
    mean = _out("float32", input.shape[:begin_norm_axis])
    var = _out("float32", input.shape[:begin_norm_axis])
    _append("layer_norm", ins,
            {"Y": [out.name], "Mean": [mean.name], "Variance": [var.name]},
            {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon})
    return out


# -- losses -------------------------------------------------------------------

def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      normalize=False) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("sigmoid_cross_entropy_with_logits",
            {"X": [x.name], "Label": [label.name]}, {"Out": [out.name]},
            {"ignore_index": ignore_index, "normalize": normalize})
    return out


def log_loss(input, label, epsilon=1e-4) -> Variable:
    out = _out(input.dtype, input.shape)
    _append("log_loss", {"Predicted": [input.name], "Labels": [label.name]},
            {"Loss": [out.name]}, {"epsilon": epsilon})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1) -> Variable:
    out = _out(label.dtype, label.shape)
    ins = {"X": [label.name]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist.name]
    _append("label_smooth", ins, {"Out": [out.name]}, {"epsilon": epsilon})
    return out


def l2_normalize(x, axis=-1, epsilon=1e-10) -> Variable:
    out = _out(x.dtype, x.shape)
    norm = _out(x.dtype, x.shape[:-1] + (1,))
    _append("norm", {"X": [x.name]}, {"Out": [out.name], "Norm": [norm.name]},
            {"axis": axis, "epsilon": epsilon})
    return out


def huber_loss(input, label, delta=1.0) -> Variable:
    out = _out(input.dtype, input.shape)
    _append("huber_loss", {"X": [input.name], "Y": [label.name]},
            {"Out": [out.name]}, {"delta": delta})
    return out


def smooth_l1(x, y, sigma=1.0) -> Variable:
    out = _out(x.dtype, x.shape)
    _append("smooth_l1_loss", {"X": [x.name], "Y": [y.name]},
            {"Out": [out.name]}, {"sigma": sigma})
    return out


def kldiv_loss(x, target, reduction="mean") -> Variable:
    shp = () if reduction in ("mean", "sum", "batchmean") else x.shape
    out = _out(x.dtype, shp)
    _append("kldiv_loss", {"X": [x.name], "Target": [target.name]},
            {"Loss": [out.name]}, {"reduction": reduction})
    return out


def mse_loss(input, label) -> Variable:
    """ref fluid/layers mse_loss — mean of squared error."""
    return mean(square_error_cost(input, label))


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0) -> Variable:
    """ref fluid/layers fill_constant_batch_size_like: constant tensor whose
    dim ``output_dim_idx`` copies ``input``'s runtime dim ``input_dim_idx``
    (the standard way to build batch-shaped RNN initial states when the
    batch dim is unknown at build time)."""
    out_shape = list(shape)
    out_shape[output_dim_idx] = input.shape[input_dim_idx]
    out = _out(dtype, tuple(out_shape))
    _append("fill_constant_batch_size_like", {"Input": [input.name]},
            {"Out": [out.name]},
            {"shape": list(shape), "dtype": dtype, "value": float(value),
             "input_dim_idx": input_dim_idx, "output_dim_idx": output_dim_idx})
    return out


# -- padded sequence layers --------------------------------------------------

def sequence_mask(x, maxlen, dtype="float32") -> Variable:
    """(b,) lengths -> (b, maxlen) 0/1 mask (ref fluid/layers/nn.py
    sequence_mask; padded TPU layout per SURVEY §7 LoD policy)."""
    out = _out(dtype, (x.shape[0], int(maxlen)))
    _append("sequence_mask", {"X": [x.name]}, {"Y": [out.name]},
            {"maxlen": int(maxlen), "out_dtype": dtype})
    return out


def sequence_last_step(input, sequence_length) -> Variable:
    """Last valid timestep of a padded (b, s, d) sequence batch (ref
    fluid/layers sequence_last_step over LoD; here a masked gather)."""
    out = _out(input.dtype, (input.shape[0], input.shape[2]))
    _append("sequence_last_step_padded",
            {"X": [input.name], "Lengths": [sequence_length.name]},
            {"Out": [out.name]}, {})
    return out


def dynamic_lstm(input, size, sequence_length=None, h0=None, c0=None,
                 param_attr=None, bias_attr=None, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 name=None):
    """LSTM over a padded (batch, seq, 4H) pre-projected input (ref
    fluid/layers/nn.py dynamic_lstm -> lstm_op.cc).

    The reference consumes a LoD-packed (sum_len, 4H) tensor; the TPU-native
    layout is padded batch-major plus ``sequence_length`` (SURVEY §7 LoD
    policy), and the recurrence lowers to lax.scan via StaticRNN.  As in the
    reference, callers pre-project the input with an fc of size 4H; this
    layer owns only the recurrent weight (H, 4H) and bias (4H).  Gate chunk
    order is (i, f, g, o), matching nn.layer.rnn.LSTMCell's weight-layout
    parity contract.  Returns (hidden, cell), each (batch, seq, H).
    """
    from .control_flow import StaticRNN

    if size % 4:
        raise ValueError(f"dynamic_lstm size must be 4*hidden, got {size}")
    H = size // 4
    b, s = int(input.shape[0]), int(input.shape[1])
    if s < 0:
        raise ValueError(
            "dynamic_lstm requires a static (padded) sequence length in "
            "input.shape[1]; got -1.  Pad sequences to a fixed max length "
            "(SURVEY §7 LoD policy) and pass sequence_length for masking.")
    acts = {"sigmoid": sigmoid, "tanh": tanh, "relu": relu,
            "identity": lambda v: v}
    try:
        gate_act = acts[gate_activation]
        cell_act = acts[cell_activation]
        cand_act = acts[candidate_activation]
    except KeyError as e:
        raise ValueError(f"dynamic_lstm: unsupported activation {e}; "
                         f"one of {sorted(acts)}") from None

    w = create_parameter((H, 4 * H), input.dtype, attr=param_attr,
                         name=f"{name}.w" if name else None)
    bias = create_parameter((4 * H,), input.dtype, attr=bias_attr,
                            default_initializer=I.Constant(0.0),
                            name=f"{name}.b" if name else None)
    if h0 is None:
        h0 = fill_constant_batch_size_like(input, (b, H), input.dtype, 0.0)
    if c0 is None:
        c0 = fill_constant_batch_size_like(input, (b, H), input.dtype, 0.0)

    x_tm = transpose(input, [1, 0, 2])                     # (s, b, 4H)
    if sequence_length is not None:
        mask = sequence_mask(sequence_length, s, dtype=input.dtype)
        mask_tm = unsqueeze(transpose(mask, [1, 0]), [2])  # (s, b, 1)

    rnn = StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x_tm)                          # (b, 4H)
        mt = rnn.step_input(mask_tm) if sequence_length is not None else None
        h_prev = rnn.memory(init=h0)
        c_prev = rnn.memory(init=c0)
        gates = elementwise_add(elementwise_add(xt, matmul(h_prev, w)), bias)
        gi, gf, gg, go = split(gates, 4, dim=1)
        c_new = elementwise_add(elementwise_mul(gate_act(gf), c_prev),
                                elementwise_mul(gate_act(gi), cand_act(gg)))
        h_new = elementwise_mul(gate_act(go), cell_act(c_new))
        if mt is not None:
            inv = elementwise_sub(
                fill_constant_batch_size_like(mt, (b, 1), input.dtype, 1.0), mt)
            h_new = elementwise_add(elementwise_mul(h_new, mt),
                                    elementwise_mul(h_prev, inv))
            c_new = elementwise_add(elementwise_mul(c_new, mt),
                                    elementwise_mul(c_prev, inv))
        rnn.update_memory(h_prev, h_new)
        rnn.update_memory(c_prev, c_new)
        rnn.step_output(h_new)
        rnn.step_output(c_new)
    h_tm, c_tm = rnn()
    return transpose(h_tm, [1, 0, 2]), transpose(c_tm, [1, 0, 2])


def sequence_pool(input, pool_type, sequence_length, pad_value=0.0) -> Variable:
    """Pool over each padded sequence's valid steps (ref fluid/layers
    sequence_pool over LoD -> sequence_ops/sequence_pool_op)."""
    out = _out(input.dtype, (input.shape[0],) + tuple(input.shape[2:]))
    _append("sequence_pool_padded",
            {"X": [input.name], "Lengths": [sequence_length.name]},
            {"Out": [out.name]},
            {"pooltype": pool_type, "pad_value": float(pad_value)})
    return out


def sequence_first_step(input, sequence_length) -> Variable:
    """ref fluid/layers sequence_first_step."""
    out = _out(input.dtype, (input.shape[0],) + tuple(input.shape[2:]))
    _append("sequence_first_step_padded",
            {"X": [input.name], "Lengths": [sequence_length.name]},
            {"Out": [out.name]}, {})
    return out


def sequence_softmax(input, sequence_length) -> Variable:
    """ref fluid/layers sequence_softmax (softmax within each sequence)."""
    out = _out(input.dtype, input.shape)
    _append("sequence_softmax_padded",
            {"X": [input.name], "Lengths": [sequence_length.name]},
            {"Out": [out.name]}, {})
    return out


def sequence_reverse(input, sequence_length) -> Variable:
    """ref fluid/layers sequence_reverse (valid prefix reversed in place)."""
    out = _out(input.dtype, input.shape)
    _append("sequence_reverse_padded",
            {"X": [input.name], "Lengths": [sequence_length.name]},
            {"Y": [out.name]}, {})
    return out


def dynamic_gru(input, size, sequence_length=None, h0=None, param_attr=None,
                bias_attr=None, is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", name=None) -> Variable:
    """GRU over a padded (batch, seq, 3H) pre-projected input (ref
    fluid/layers/nn.py dynamic_gru -> gru_op.cc).

    Like dynamic_lstm, callers pre-project the input with an fc of size 3H;
    this layer owns the recurrent weight (H, 3H) and bias (3H).  Gate chunk
    order (r, z, c) with the reset gate applied AFTER the hidden matmul,
    matching nn.layer.rnn.GRUCell's weight-layout parity contract.
    ``is_reverse`` runs the recurrence right-to-left over the valid prefix
    (the reference attribute), implemented by sequence_reverse on both ends.
    Returns hidden (batch, seq, H).
    """
    from .control_flow import StaticRNN

    if size % 3:
        raise ValueError(f"dynamic_gru size must be 3*hidden, got {size}")
    H = size // 3
    b, s = int(input.shape[0]), int(input.shape[1])
    if s < 0:
        raise ValueError(
            "dynamic_gru requires a static (padded) sequence length in "
            "input.shape[1]; pad sequences and pass sequence_length")
    acts = {"sigmoid": sigmoid, "tanh": tanh, "relu": relu,
            "identity": lambda v: v}
    try:
        gate_act = acts[gate_activation]
        cand_act = acts[candidate_activation]
    except KeyError as e:
        raise ValueError(f"dynamic_gru: unsupported activation {e}; "
                         f"one of {sorted(acts)}") from None

    if is_reverse:
        if sequence_length is None:
            raise ValueError("dynamic_gru(is_reverse=True) needs "
                             "sequence_length to locate each valid prefix")
        input = sequence_reverse(input, sequence_length)

    w = create_parameter((H, 3 * H), input.dtype, attr=param_attr,
                         name=f"{name}.w" if name else None)
    bias = create_parameter((3 * H,), input.dtype, attr=bias_attr,
                            default_initializer=I.Constant(0.0),
                            name=f"{name}.b" if name else None)
    if h0 is None:
        h0 = fill_constant_batch_size_like(input, (b, H), input.dtype, 0.0)

    x_tm = transpose(input, [1, 0, 2])                     # (s, b, 3H)
    if sequence_length is not None:
        mask = sequence_mask(sequence_length, s, dtype=input.dtype)
        mask_tm = unsqueeze(transpose(mask, [1, 0]), [2])  # (s, b, 1)

    w_rz, w_c = split(w, [2 * H, H], dim=1)
    b_rz, b_c = split(bias, [2 * H, H], dim=0)

    rnn = StaticRNN()
    with rnn.step():
        xt = rnn.step_input(x_tm)                          # (b, 3H)
        mt = rnn.step_input(mask_tm) if sequence_length is not None else None
        h_prev = rnn.memory(init=h0)
        x_rz, x_c = split(xt, [2 * H, H], dim=1)
        rz = gate_act(elementwise_add(
            elementwise_add(x_rz, matmul(h_prev, w_rz)), b_rz))
        r, z = split(rz, 2, dim=1)
        c = cand_act(elementwise_add(
            elementwise_add(x_c, elementwise_mul(r, matmul(h_prev, w_c))),
            b_c))
        one = fill_constant_batch_size_like(xt, (b, 1), input.dtype, 1.0)
        h_new = elementwise_add(elementwise_mul(z, h_prev),
                                elementwise_mul(elementwise_sub(one, z), c))
        if mt is not None:
            inv = elementwise_sub(one, mt)
            h_new = elementwise_add(elementwise_mul(h_new, mt),
                                    elementwise_mul(h_prev, inv))
        rnn.update_memory(h_prev, h_new)
        rnn.step_output(h_new)
    h_tm = rnn()
    hidden = transpose(h_tm, [1, 0, 2])
    if is_reverse:
        hidden = sequence_reverse(hidden, sequence_length)
    return hidden


# -- conv-transpose / norm / vision long tail --------------------------------

def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None) -> Variable:
    """ref fluid/layers/nn.py conv2d_transpose (NCHW; weight layout
    (in_c, out_c/groups, kh, kw) like conv_transpose_op.cc)."""
    ks, st = _pair(filter_size), _pair(stride)
    pd, dl, op_ = _pair(padding), _pair(dilation), _pair(output_padding)
    cin = input.shape[1]
    w = create_parameter((cin, num_filters // groups, ks[0], ks[1]),
                         input.dtype, attr=param_attr,
                         name=f"{name}.w" if name else None)

    def _tout(sz, k, s, p, d, o):
        if sz < 0:
            return -1
        return (sz - 1) * s - 2 * p + (k - 1) * d + 1 + o

    h = _tout(input.shape[2], ks[0], st[0], pd[0], dl[0], op_[0])
    wd = _tout(input.shape[3], ks[1], st[1], pd[1], dl[1], op_[1])
    out = _out(input.dtype, (input.shape[0], num_filters, h, wd))
    inputs = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = create_parameter((num_filters,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0),
                             name=f"{name}.b" if name else None)
        inputs["Bias"] = [b.name]
    _append("conv2d_transpose", inputs, {"Output": [out.name]},
            {"strides": stride, "paddings": padding, "dilations": dilation,
             "output_padding": output_padding, "groups": groups})
    return _apply_act(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None) -> Variable:
    """ref fluid/layers/nn.py group_norm -> group_norm_op.cc (NCHW)."""
    C = input.shape[1]
    scale = create_parameter((C,), input.dtype, attr=param_attr,
                             default_initializer=I.Constant(1.0),
                             name=f"{name}.w" if name else None)
    bias = create_parameter((C,), input.dtype, attr=bias_attr,
                            default_initializer=I.Constant(0.0),
                            name=f"{name}.b" if name else None)
    out = _out(input.dtype, input.shape)
    _append("group_norm", {"X": [input.name], "Scale": [scale.name],
                           "Bias": [bias.name]}, {"Y": [out.name]},
            {"groups": int(groups), "epsilon": float(epsilon)})
    return _apply_act(out, act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None) -> Variable:
    """ref fluid/layers/nn.py instance_norm -> instance_norm_op.cc."""
    C = input.shape[1]
    scale = create_parameter((C,), input.dtype, attr=param_attr,
                             default_initializer=I.Constant(1.0),
                             name=f"{name}.w" if name else None)
    bias = create_parameter((C,), input.dtype, attr=bias_attr,
                            default_initializer=I.Constant(0.0),
                            name=f"{name}.b" if name else None)
    out = _out(input.dtype, input.shape)
    _append("instance_norm", {"X": [input.name], "Scale": [scale.name],
                              "Bias": [bias.name]}, {"Y": [out.name]},
            {"epsilon": float(epsilon)})
    return out


def prelu(x, mode="all", param_attr=None, name=None) -> Variable:
    """ref fluid/layers/nn.py prelu (mode: all|channel)."""
    if mode == "all":
        alpha_shape = (1,)
    elif mode == "channel":
        alpha_shape = (x.shape[1],)
    else:
        raise ValueError("prelu mode must be 'all' or 'channel' "
                         "(per-'element' alpha is descoped)")
    alpha = create_parameter(alpha_shape, x.dtype, attr=param_attr,
                             default_initializer=I.Constant(0.25),
                             name=f"{name}.alpha" if name else None)
    out = _out(x.dtype, x.shape)
    _append("prelu", {"X": [x.name], "Alpha": [alpha.name]},
            {"Out": [out.name]}, {"mode": mode})
    return out


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          name=None) -> Variable:
    """ref fluid/layers/nn.py pad2d (NCHW, [top, bottom, left, right])."""
    t, b, l, r = paddings
    shape = list(input.shape)
    if shape[2] >= 0:
        shape[2] += t + b
    if shape[3] >= 0:
        shape[3] += l + r
    out = _out(input.dtype, tuple(shape))
    _append("pad2d", {"X": [input.name]}, {"Out": [out.name]},
            {"paddings": list(paddings), "mode": mode,
             "pad_value": float(pad_value)})
    return out


def _resize(input, out_shape, method, align_corners):
    out = _out(input.dtype,
               (input.shape[0], input.shape[1]) + tuple(out_shape))
    _append("resize_interp", {"X": [input.name]}, {"Out": [out.name]},
            {"out_shape": list(out_shape), "interp_method": method,
             "align_corners": bool(align_corners)})
    return out


def resize_bilinear(input, out_shape, align_corners=True, name=None):
    """ref fluid/layers/nn.py resize_bilinear -> bilinear_interp_op
    (fluid defaults align_corners=True)."""
    return _resize(input, out_shape, "bilinear", align_corners)


def resize_nearest(input, out_shape, align_corners=True, name=None):
    """ref fluid/layers/nn.py resize_nearest -> nearest_interp_op
    (fluid defaults align_corners=True)."""
    return _resize(input, out_shape, "nearest", align_corners)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """ref fluid/layers/detection.py prior_box -> prior_box_op.cc.
    Returns (boxes, variances), each (H, W, num_priors, 4)."""
    from ..ops.vision import expand_aspect_ratios

    # shared with the eager kernel so count inference can never drift
    n_ratio = len(expand_aspect_ratios(aspect_ratios, flip))
    num = len(min_sizes) * n_ratio + len(max_sizes or [])
    H, W = input.shape[2], input.shape[3]
    boxes = _out(input.dtype, (H, W, num, 4))
    variances = _out(input.dtype, (H, W, num, 4))
    _append("prior_box", {"Input": [input.name], "Image": [image.name]},
            {"Boxes": [boxes.name], "Variances": [variances.name]},
            {"min_sizes": list(min_sizes),
             "max_sizes": list(max_sizes or []),
             "aspect_ratios": list(aspect_ratios),
             "variances": list(variance), "flip": flip, "clip": clip,
             "steps": list(steps), "offset": offset})
    return boxes, variances


def box_coder(prior_box, prior_box_var, target_box, code_type,
              box_normalized=True, axis=0, name=None) -> Variable:
    """ref fluid/layers/detection.py box_coder -> box_coder_op.cc.
    encode_center_size: target (N, 4) x priors (M, 4) -> (N, M, 4);
    decode_center_size keeps the target's shape."""
    if str(code_type).startswith("encode"):
        out_shape = (target_box.shape[0], prior_box.shape[0], 4)
    else:
        out_shape = target_box.shape
    out = _out(target_box.dtype, out_shape)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    _append("box_coder", inputs, {"OutputBox": [out.name]},
            {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None) -> Variable:
    """ref fluid/layers/detection.py roi_align -> roi_align_op.cc
    (batch-1 static-shape policy; see the lowering's docstring)."""
    C = input.shape[1]
    out = _out(input.dtype, (rois.shape[0], C, pooled_height, pooled_width))
    _append("roi_align", {"X": [input.name], "ROIs": [rois.name]},
            {"Out": [out.name]},
            {"pooled_height": pooled_height, "pooled_width": pooled_width,
             "spatial_scale": spatial_scale,
             "sampling_ratio": sampling_ratio})
    return out


def linear_chain_crf(input, label, length, param_attr=None,
                     name=None) -> Variable:
    """ref fluid/layers/nn.py linear_chain_crf -> linear_chain_crf_op.h.
    Owns the (num_tags + 2, num_tags) transition parameter (start/stop
    rows + pairwise); share it with crf_decoding via param_attr name.
    Returns the per-sequence NLL (b, 1) (the reference's negated
    log-likelihood output)."""
    D = input.shape[-1]
    # layer `name` must NOT rename the parameter (it would break the
    # param_attr sharing contract with crf_decoding); fluid's name arg is a
    # display name only
    transition = create_parameter((D + 2, D), input.dtype, attr=param_attr)
    out = _out(input.dtype, (input.shape[0], 1))
    _append("linear_chain_crf",
            {"Emission": [input.name], "Label": [label.name],
             "Transition": [transition.name], "Length": [length.name]},
            {"LogLikelihood": [out.name]}, {})
    return out


def crf_decoding(input, length, param_attr=None, name=None) -> Variable:
    """ref fluid/layers/nn.py crf_decoding -> crf_decoding_op.h (Viterbi);
    pass the SAME param_attr name used for linear_chain_crf."""
    D = input.shape[-1]
    transition = create_parameter((D + 2, D), input.dtype, attr=param_attr)
    out = _out("int32", input.shape[:-1])
    _append("crf_decoding",
            {"Emission": [input.name], "Transition": [transition.name],
             "Length": [length.name]},
            {"ViterbiPath": [out.name]}, {})
    return out


# -- misc op-parity layer functions ------------------------------------------

def _same_shape_op(op_type, x, attrs=None, in_name="X", out_name="Out",
                   out_shape=None, out_dtype=None):
    out = _out(out_dtype or x.dtype, out_shape if out_shape is not None
               else x.shape)
    _append(op_type, {in_name: [x.name]}, {out_name: [out.name]}, attrs or {})
    return out


def pixel_shuffle(x, upscale_factor) -> Variable:
    """ref pixel_shuffle layer (2.x nn.functional.pixel_shuffle)."""
    r = int(upscale_factor)
    n, c, h, w = x.shape
    shape = (n, c // (r * r) if c >= 0 else -1,
             h * r if h >= 0 else -1, w * r if w >= 0 else -1)
    return _same_shape_op("pixel_shuffle", x, {"upscale_factor": r},
                          out_shape=shape)


def space_to_depth(x, blocksize) -> Variable:
    """ref fluid/layers/nn.py space_to_depth."""
    b = int(blocksize)
    n, c, h, w = x.shape
    shape = (n, c * b * b if c >= 0 else -1,
             h // b if h >= 0 else -1, w // b if w >= 0 else -1)
    return _same_shape_op("space_to_depth", x, {"blocksize": b},
                          out_shape=shape)


def shuffle_channel(x, group) -> Variable:
    """ref fluid/layers/nn.py shuffle_channel."""
    return _same_shape_op("shuffle_channel", x, {"group": int(group)})


def temporal_shift(x, seg_num, shift_ratio=0.25) -> Variable:
    """ref fluid/layers/nn.py temporal_shift."""
    return _same_shape_op("temporal_shift", x,
                          {"seg_num": int(seg_num),
                           "shift_ratio": float(shift_ratio)})


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75) -> Variable:
    """ref fluid/layers/nn.py lrn."""
    return _same_shape_op("lrn", input,
                          {"n": n, "k": k, "alpha": alpha, "beta": beta})


def cos_sim(X, Y) -> Variable:
    """ref fluid/layers/nn.py cos_sim."""
    out = _out(X.dtype, (X.shape[0], 1))
    _append("cos_sim", {"X": [X.name], "Y": [Y.name]}, {"Out": [out.name]})
    return out


def multiplex(inputs, index) -> Variable:
    """ref fluid/layers/nn.py multiplex."""
    out = _out(inputs[0].dtype, inputs[0].shape)
    _append("multiplex", {"X": [v.name for v in inputs],
                          "Ids": [index.name]}, {"Out": [out.name]})
    return out


def rank_loss(label, left, right) -> Variable:
    """ref fluid/layers/loss.py rank_loss."""
    out = _out(left.dtype, left.shape)
    _append("rank_loss", {"Label": [label.name], "Left": [left.name],
                          "Right": [right.name]}, {"Out": [out.name]})
    return out


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25) -> Variable:
    """ref fluid/layers/detection.py sigmoid_focal_loss."""
    out = _out(x.dtype, x.shape)
    _append("sigmoid_focal_loss",
            {"X": [x.name], "Label": [label.name], "FgNum": [fg_num.name]},
            {"Out": [out.name]}, {"gamma": gamma, "alpha": alpha})
    return out


def affine_grid(theta, out_shape) -> Variable:
    """ref fluid/layers/nn.py affine_grid."""
    n, _, h, w = out_shape
    out = _out(theta.dtype, (n, h, w, 2))
    _append("affine_grid", {"Theta": [theta.name]}, {"Output": [out.name]},
            {"output_shape": list(out_shape)})
    return out


def grid_sampler(x, grid, mode="bilinear", padding_mode="zeros",
                 align_corners=True) -> Variable:
    """ref fluid/layers/nn.py grid_sampler."""
    out = _out(x.dtype, (x.shape[0], x.shape[1], grid.shape[1],
                         grid.shape[2]))
    _append("grid_sampler", {"X": [x.name], "Grid": [grid.name]},
            {"Output": [out.name]},
            {"mode": mode, "padding_mode": padding_mode,
             "align_corners": align_corners})
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0) -> Variable:
    """ref fluid/layers/detection.py roi_pool (batch-1 static policy)."""
    out = _out(input.dtype, (rois.shape[0], input.shape[1], pooled_height,
                             pooled_width))
    _append("roi_pool", {"X": [input.name], "ROIs": [rois.name]},
            {"Out": [out.name]},
            {"pooled_height": pooled_height, "pooled_width": pooled_width,
             "spatial_scale": spatial_scale})
    return out


def row_conv(input, future_context_size, sequence_length=None,
             param_attr=None) -> Variable:
    """ref fluid/layers/nn.py row_conv (owns the lookahead filter)."""
    d = input.shape[-1]
    w = create_parameter((future_context_size + 1, d), input.dtype,
                         attr=param_attr)
    out = _out(input.dtype, input.shape)
    inputs = {"X": [input.name], "Filter": [w.name]}
    if sequence_length is not None:
        inputs["Lengths"] = [sequence_length.name]
    _append("row_conv", inputs, {"Out": [out.name]}, {})
    return out


def sequence_conv(input, num_filters, filter_size=3, padding_start=None,
                  sequence_length=None, param_attr=None, bias_attr=None,
                  act=None, name=None) -> Variable:
    """ref fluid/layers/sequence_lod.py sequence_conv -> sequence_conv_op:
    windowed conv over each padded sequence's time axis."""
    din = input.shape[-1]
    w = create_parameter((filter_size * din, num_filters), input.dtype,
                         attr=param_attr, name=f"{name}.w" if name else None)
    out = _out(input.dtype, tuple(input.shape[:-1]) + (num_filters,))
    inputs = {"X": [input.name], "Filter": [w.name]}
    if sequence_length is not None:
        inputs["Lengths"] = [sequence_length.name]
    _append("sequence_conv_padded", inputs, {"Out": [out.name]},
            {"contextLength": int(filter_size),
             "contextStart": padding_start})
    res = out
    if bias_attr is not False:
        b = create_parameter((num_filters,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0),
                             name=f"{name}.b" if name else None)
        res = elementwise_add(out, b, axis=len(out.shape) - 1)
    return _apply_act(res, act)


def nce(input, label, num_total_classes, sample_ids, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None) -> Variable:
    """ref fluid/layers/nn.py nce -> nce_op.cc.  The reference samples
    negatives inside the op; the TPU-native contract takes explicit
    ``sample_ids`` (batch, num_neg) — sampling is data-pipeline work, and
    an in-graph sampler would re-trace per draw."""
    if num_neg_samples is not None and \
            int(num_neg_samples) != int(sample_ids.shape[-1]):
        raise ValueError(
            f"nce: num_neg_samples={num_neg_samples} disagrees with "
            f"sample_ids width {sample_ids.shape[-1]} — the noise prior "
            "comes from the drawn negatives")
    dim = input.shape[-1]
    w = create_parameter((num_total_classes, dim), input.dtype,
                         attr=param_attr, name=f"{name}.w" if name else None)
    if bias_attr is not False:
        b = create_parameter((num_total_classes,), input.dtype,
                             attr=bias_attr,
                             default_initializer=I.Constant(0.0),
                             name=f"{name}.b" if name else None)
        bias_name = b.name
    else:
        zb = fill_constant((num_total_classes,), input.dtype, 0.0)
        bias_name = zb.name
    out = _out(input.dtype, (input.shape[0], 1))
    _append("nce", {"Input": [input.name], "Label": [label.name],
                    "Weight": [w.name], "Bias": [bias_name],
                    "SampleIds": [sample_ids.name]},
            {"Cost": [out.name]},
            {"num_total_classes": int(num_total_classes)})
    return out


# -- CTC / sequence distance (ref fluid/layers/loss.py warpctc,
#    fluid/layers/nn.py edit_distance, ctc_greedy_decoder) -------------------

def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None) -> Variable:
    """ref fluid/layers/loss.py warpctc -> warpctc_op.cc (padded mode:
    input (T, B, C), label (B, L), lengths (B,))."""
    if input_length is None or label_length is None:
        raise ValueError("padded-mode warpctc needs input_length and "
                         "label_length (LoD mode is descoped: README)")
    B = input.shape[1]
    loss = _out(input.dtype, (B, 1))
    _append("warpctc",
            {"Logits": [input.name], "Label": [label.name],
             "LogitsLength": [input_length.name],
             "LabelLength": [label_length.name]},
            {"Loss": [loss.name]},
            {"blank": blank, "norm_by_times": norm_by_times})
    return loss


def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    """ref fluid/layers/nn.py edit_distance -> edit_distance_op.cc.
    Returns (distance (B,1), seq_num (1,))."""
    B = input.shape[0]
    dist = _out("float32", (B, 1))
    num = _out("int32", (1,))
    ins = {"Hyps": [input.name], "Refs": [label.name]}
    if input_length is not None:
        ins["HypsLength"] = [input_length.name]
    if label_length is not None:
        ins["RefsLength"] = [label_length.name]
    _append("edit_distance", ins,
            {"Out": [dist.name], "SequenceNum": [num.name]},
            {"normalized": normalized})
    return dist, num


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0):
    """ref fluid/layers/nn.py ctc_greedy_decoder (padded mode) ->
    ctc_align_op: input (B, T, C).  Returns (decoded (B,T), lengths (B,))."""
    B, T = input.shape[0], input.shape[1]
    out = _out("int32", (B, T))
    lens = _out("int32", (B,))
    ins = {"Input": [input.name]}
    if input_length is not None:
        ins["InputLength"] = [input_length.name]
    _append("ctc_align", ins,
            {"Output": [out.name], "OutputLength": [lens.name]},
            {"blank": blank, "padding_value": padding_value})
    return out, lens


# -- 3D conv/pool family (ref fluid/layers/nn.py conv3d/pool3d/...) ----------

def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * 3


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None
           ) -> Variable:
    """ref fluid/layers/nn.py conv3d (NCDHW) -> conv3d op."""
    ks = _triple(filter_size)
    st, pd, dl = _triple(stride), _triple(padding), _triple(dilation)
    cin = input.shape[1]
    w = create_parameter((num_filters, cin // groups) + ks, input.dtype,
                         attr=param_attr)
    spatial = tuple(
        -1 if input.shape[2 + i] < 0 else
        (input.shape[2 + i] + 2 * pd[i] - (dl[i] * (ks[i] - 1) + 1))
        // st[i] + 1 for i in range(3))
    out = _out(input.dtype, (input.shape[0], num_filters) + spatial)
    ins = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = create_parameter((num_filters,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0))
        ins["Bias"] = [b.name]
    _append("conv3d", ins, {"Output": [out.name]},
            {"strides": list(st), "paddings": list(pd),
             "dilations": list(dl), "groups": groups})
    return _apply_act(out, act)


def conv3d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1, param_attr=None,
                     bias_attr=None, act=None, name=None) -> Variable:
    """ref fluid/layers/nn.py conv3d_transpose -> conv3d_transpose op."""
    ks = _triple(filter_size)
    st, pd, dl = _triple(stride), _triple(padding), _triple(dilation)
    opd = _triple(output_padding)
    cin = input.shape[1]
    w = create_parameter((cin, num_filters // groups) + ks, input.dtype,
                         attr=param_attr)
    spatial = tuple(
        -1 if input.shape[2 + i] < 0 else
        (input.shape[2 + i] - 1) * st[i] - 2 * pd[i]
        + dl[i] * (ks[i] - 1) + 1 + opd[i] for i in range(3))
    out = _out(input.dtype, (input.shape[0], num_filters) + spatial)
    ins = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = create_parameter((num_filters,), input.dtype, attr=bias_attr,
                             default_initializer=I.Constant(0.0))
        ins["Bias"] = [b.name]
    _append("conv3d_transpose", ins, {"Output": [out.name]},
            {"strides": list(st), "paddings": list(pd),
             "dilations": list(dl), "groups": groups,
             "output_padding": list(opd)})
    return _apply_act(out, act)


def pool3d(input, pool_size=2, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, exclusive=True,
           name=None) -> Variable:
    """ref fluid/layers/nn.py pool3d -> pool3d op (NCDHW)."""
    ks = _triple(pool_size)
    st = _triple(pool_stride if pool_stride is not None else pool_size)
    pd = _triple(pool_padding)
    if global_pooling:
        spatial = (1, 1, 1)
    else:
        spatial = tuple(
            -1 if input.shape[2 + i] < 0 else
            (input.shape[2 + i] + 2 * pd[i] - ks[i]) // st[i] + 1
            for i in range(3))
    out = _out(input.dtype, (input.shape[0], input.shape[1]) + spatial)
    _append("pool3d", {"X": [input.name]}, {"Out": [out.name]},
            {"ksize": list(ks), "strides": list(st), "paddings": list(pd),
             "pooling_type": pool_type, "global_pooling": global_pooling,
             "exclusive": exclusive})
    return out


# -- detection DSL (ref fluid/layers/detection.py) ---------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0, name=None):
    """ref detection.py yolo_box -> yolo_box op.  Returns (boxes, scores)."""
    n = x.shape[0]
    an = len(anchors) // 2
    hw = x.shape[2] * x.shape[3] if x.shape[2] > 0 and x.shape[3] > 0 else -1
    cnt = an * hw if hw > 0 else -1
    boxes = _out(x.dtype, (n, cnt, 4))
    scores = _out(x.dtype, (n, cnt, class_num))
    _append("yolo_box", {"X": [x.name], "ImgSize": [img_size.name]},
            {"Boxes": [boxes.name], "Scores": [scores.name]},
            {"anchors": list(anchors), "class_num": class_num,
             "conf_thresh": conf_thresh,
             "downsample_ratio": downsample_ratio, "clip_bbox": clip_bbox,
             "scale_x_y": scale_x_y})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, scale_x_y=1.0, name=None) -> Variable:
    """ref detection.py yolov3_loss -> yolov3_loss op."""
    loss = _out(x.dtype, (x.shape[0],))
    ins = {"X": [x.name], "GTBox": [gt_box.name], "GTLabel": [gt_label.name]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score.name]
    _append("yolov3_loss", ins, {"Loss": [loss.name]},
            {"anchors": list(anchors), "anchor_mask": list(anchor_mask),
             "class_num": class_num, "ignore_thresh": ignore_thresh,
             "downsample_ratio": downsample_ratio,
             "use_label_smooth": use_label_smooth, "scale_x_y": scale_x_y})
    return loss


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   background_label=0, name=None) -> Variable:
    """ref detection.py multiclass_nms -> multiclass_nms op (dense padded
    output, (N, keep, 6))."""
    n = bboxes.shape[0]
    keep = keep_top_k if keep_top_k > 0 else -1
    out = _out(bboxes.dtype, (n, keep, 6))
    num = _out("int32", (n,))
    _append("multiclass_nms",
            {"BBoxes": [bboxes.name], "Scores": [scores.name]},
            {"Out": [out.name], "NmsRoisNum": [num.name]},
            {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
             "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
             "normalized": normalized,
             "background_label": background_label})
    return out


def density_prior_box(input, image, densities, fixed_sizes,
                      fixed_ratios=(1.0,), variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """ref detection.py density_prior_box -> density_prior_box op."""
    num = sum(d * d for d in densities for _ in fixed_ratios)
    H, W = input.shape[2], input.shape[3]
    shape = (-1, 4) if flatten_to_2d else (H, W, num, 4)
    boxes = _out(input.dtype, shape)
    variances = _out(input.dtype, shape)
    _append("density_prior_box",
            {"Input": [input.name], "Image": [image.name]},
            {"Boxes": [boxes.name], "Variances": [variances.name]},
            {"densities": list(densities), "fixed_sizes": list(fixed_sizes),
             "fixed_ratios": list(fixed_ratios), "variances": list(variance),
             "clip": clip, "step_w": steps[0], "step_h": steps[1],
             "offset": offset, "flatten_to_2d": flatten_to_2d})
    return boxes, variances


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=1, deformable_groups=1,
                    im2col_step=1, param_attr=None, bias_attr=None,
                    modulated=True, name=None) -> Variable:
    """ref fluid/layers/nn.py deformable_conv -> deformable_conv(_v1) op."""
    ks = _pair(filter_size)
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    cin = input.shape[1]
    w = create_parameter((num_filters, cin // groups) + ks, input.dtype,
                         attr=param_attr)
    spatial = tuple(
        -1 if input.shape[2 + i] < 0 else
        (input.shape[2 + i] + 2 * pd[i] - (dl[i] * (ks[i] - 1) + 1))
        // st[i] + 1 for i in range(2))
    out = _out(input.dtype, (input.shape[0], num_filters) + spatial)
    ins = {"Input": [input.name], "Offset": [offset.name],
           "Filter": [w.name]}
    op_type = "deformable_conv" if modulated else "deformable_conv_v1"
    if modulated:
        ins["Mask"] = [mask.name]
    _append(op_type, ins, {"Output": [out.name]},
            {"strides": list(st), "paddings": list(pd),
             "dilations": list(dl), "groups": groups,
             "deformable_groups": deformable_groups,
             "im2col_step": im2col_step})
    return out


def psroi_pool(input, rois, rois_batch_id, output_channels, spatial_scale,
               pooled_height, pooled_width, name=None) -> Variable:
    """ref detection.py psroi_pool -> psroi_pool op."""
    out = _out(input.dtype,
               (rois.shape[0], output_channels, pooled_height, pooled_width))
    _append("psroi_pool",
            {"X": [input.name], "ROIs": [rois.name],
             "RoisBatchId": [rois_batch_id.name]},
            {"Out": [out.name]},
            {"output_channels": output_channels,
             "pooled_height": pooled_height, "pooled_width": pooled_width,
             "spatial_scale": spatial_scale})
    return out


# -- misc new statics --------------------------------------------------------

def affine_channel(x, scale, bias, name=None) -> Variable:
    """ref fluid/layers/nn.py affine_channel."""
    out = _out(x.dtype, x.shape)
    _append("affine_channel",
            {"X": [x.name], "Scale": [scale.name], "Bias": [bias.name]},
            {"Out": [out.name]}, {})
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None
           ) -> Variable:
    """ref fluid/layers/nn.py unfold -> unfold op (im2col)."""
    ks = _pair(kernel_sizes)
    st, pd, dl = _pair(strides), _pair(paddings), _pair(dilations)
    n, c, h, w = x.shape
    lh = -1 if h < 0 else (h + 2 * pd[0] - (dl[0] * (ks[0] - 1) + 1)) \
        // st[0] + 1
    lw = -1 if w < 0 else (w + 2 * pd[1] - (dl[1] * (ks[1] - 1) + 1)) \
        // st[1] + 1
    L = -1 if (lh < 0 or lw < 0) else lh * lw
    out = _out(x.dtype, (n, c * ks[0] * ks[1], L))
    _append("unfold", {"X": [x.name]}, {"Y": [out.name]},
            {"kernel_sizes": list(ks), "strides": list(st),
             "paddings": list(pd), "dilations": list(dl)})
    return out


def maxout(x, groups, name=None) -> Variable:
    """ref fluid/layers/nn.py maxout."""
    out = _out(x.dtype,
               (x.shape[0], x.shape[1] // groups) + tuple(x.shape[2:]))
    _append("maxout", {"X": [x.name]}, {"Out": [out.name]},
            {"groups": groups})
    return out


def mean_iou(input, label, num_classes):
    """ref fluid/layers/nn.py mean_iou.  Returns (mean_iou, out_wrong,
    out_correct)."""
    miou = _out("float32", ())
    wrong = _out("float32", (num_classes,))
    correct = _out("float32", (num_classes,))
    _append("mean_iou",
            {"Predictions": [input.name], "Labels": [label.name]},
            {"OutMeanIou": [miou.name], "OutWrong": [wrong.name],
             "OutCorrect": [correct.name]},
            {"num_classes": num_classes})
    return miou, wrong, correct


def argsort(x, axis=-1, descending=False, name=None):
    """ref fluid/layers/tensor.py argsort.  Returns (sorted, indices)."""
    out = _out(x.dtype, x.shape)
    idx = _out("int64", x.shape)
    _append("argsort", {"X": [x.name]},
            {"Out": [out.name], "Indices": [idx.name]},
            {"axis": axis, "descending": descending})
    return out, idx
