"""Op lowering registry: op type -> jax-emitting rule.

Reference parity: the op registry + kernel dispatch machinery
(paddle/fluid/framework/op_registry.h:223 REGISTER_OPERATOR,
operator.cc:944 RunImpl → ChooseKernel :977).  TPU-native design: an op's
"kernel" is a *lowering rule* called while the Executor traces the block
under jit — it receives {slot: [jax arrays]} plus attrs and returns
{slot: [jax arrays]}.  There is no per-place kernel table: XLA owns code
generation for every backend (SURVEY.md §7 design stance).
"""
from __future__ import annotations

import difflib
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils import monitor as _monitor

Lowering = Callable[..., Dict[str, List[Any]]]

_REGISTRY: Dict[str, Lowering] = {}

_lowering_calls = _monitor.counter(
    "registry.lowering_calls",
    "get_lowering resolutions per op type (trace-time only: a resolution "
    "happens once per op per compile-cache miss, not per step).",
    labelnames=("op",))


def register_op(type_name: str):
    """Decorator: register `fn(inputs, attrs, op) -> outputs_by_slot`."""

    def deco(fn: Lowering) -> Lowering:
        if type_name in _REGISTRY:
            raise ValueError(f"op {type_name!r} registered twice")
        _REGISTRY[type_name] = fn
        return fn

    return deco


def get_lowering(type_name: str) -> Lowering:
    try:
        rule = _REGISTRY[type_name]
        _lowering_calls.inc(op=type_name)
        return rule
    except KeyError:
        from ..core.errors import UnimplementedError

        suggestion = suggest_names(type_name)
        raise UnimplementedError(
            f"no lowering registered for op type {type_name!r} "
            f"({len(_REGISTRY)} ops registered)"
            + (f"; {suggestion}" if suggestion else "")) from None


def is_registered(type_name: str) -> bool:
    return type_name in _REGISTRY


def suggest_names(name: str, candidates: Optional[Sequence[str]] = None,
                  n: int = 3) -> Optional[str]:
    """Nearest-name hint for a miss against `candidates` (default: the
    registry).  Shared by get_lowering and the program verifier
    (static/analysis.py) so both render the same 'did you mean' text
    instead of dumping hundreds of registry entries."""
    pool = list(_REGISTRY) if candidates is None else list(candidates)
    close = difflib.get_close_matches(name, pool, n=n, cutoff=0.6)
    if not close:
        return None
    return "did you mean " + " / ".join(repr(c) for c in close) + "?"


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)
