"""Op lowering registry: op type -> jax-emitting rule.

Reference parity: the op registry + kernel dispatch machinery
(paddle/fluid/framework/op_registry.h:223 REGISTER_OPERATOR,
operator.cc:944 RunImpl → ChooseKernel :977).  TPU-native design: an op's
"kernel" is a *lowering rule* called while the Executor traces the block
under jit — it receives {slot: [jax arrays]} plus attrs and returns
{slot: [jax arrays]}.  There is no per-place kernel table: XLA owns code
generation for every backend (SURVEY.md §7 design stance).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List

Lowering = Callable[..., Dict[str, List[Any]]]

_REGISTRY: Dict[str, Lowering] = {}


def register_op(type_name: str):
    """Decorator: register `fn(inputs, attrs, op) -> outputs_by_slot`."""

    def deco(fn: Lowering) -> Lowering:
        if type_name in _REGISTRY:
            raise ValueError(f"op {type_name!r} registered twice")
        _REGISTRY[type_name] = fn
        return fn

    return deco


def get_lowering(type_name: str) -> Lowering:
    try:
        return _REGISTRY[type_name]
    except KeyError:
        from ..core.errors import UnimplementedError

        raise UnimplementedError(
            f"no lowering registered for op type {type_name!r}; known: "
            f"{sorted(_REGISTRY)}") from None


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)
