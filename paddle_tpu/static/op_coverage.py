"""Machine-checked registry coverage vs the reference's operator macros.

``tests/test_registry_exhaustive.py`` greps every ``REGISTER_OPERATOR`` /
``REGISTER_OP_WITHOUT_GRADIENT`` in ``/root/reference/paddle/fluid`` (non-
test files) and asserts that every base op name is either (a) a registered
lowering, or (b) listed HERE with a rationale.  README.md's "the rest,
exhaustively" claim points at this table — adding a reference op without a
lowering or an entry breaks the suite, so the claim cannot silently rot.

Rationale categories:
- ``executor``: realized by the Executor/jit runtime itself, not a per-op
  lowering (control flow, feed/fetch, readers).
- ``engine``: subgraph/fusion engines that XLA replaces wholesale.
- ``service``: RPC/pslib/BoxPS control- or data-plane clients of services
  that live OUTSIDE jitted programs here (distributed/ps_server.py is the
  capability re-scope; VERDICT r03/r04 accepted the descope).
- ``host``: ops whose contract is inherently host-side/dynamic in a way
  the static TPU path re-scopes elsewhere (named alternative given).
"""
from __future__ import annotations

DESCOPED = {
    # -- executor-realized (not per-op lowerings) -------------------------
    "conditional_block": "executor: cond builders lower straight to "
                         "lax.cond (executor._lower_cond); the block-op "
                         "encoding never materializes",
    "conditional_block_infer": "executor: same as conditional_block (the "
                               "infer variant skips scope retention, which "
                               "the functional lowering never needed)",
    "while": "executor: _lower_while emits lax.while_loop",
    "recurrent": "executor: StaticRNN collapses to lax.scan "
                 "(_lower_static_rnn); the block-op encoding is internal",
    "feed": "executor: feeds bind via the env dict (executor.py run())",
    "fetch": "executor: fetch_list reads from the env dict",
    "read": "executor: DataLoader feeds arrays; no reader op graph node",
    "create_custom_reader": "executor: reader decorators collapse into the "
                            "python DataLoader pipeline (io/)",
    "enqueue": "executor: queue runtime belongs to DataLoader workers",
    "dequeue": "executor: same",
    "queue_generator": "executor: same",
    "get_places": "executor: device enumeration is core.device.Place / "
                  "jax.devices(), never a graph op",
    "delete_var": "executor: GC is XLA buffer lifetime + env dict scoping",
    "dummy": "executor: placeholder op with no semantics",
    "rnn_memory_helper": "executor: dygraph-era RNN memory plumbing; "
                         "lax.scan carries state explicitly",
    "lod_rank_table": "executor: LoD rank tables order variable-length "
                      "sequences for DynamicRNN; the dense (B, T)+Length "
                      "layout (core/lod.py) sorts with argsort instead",
    "reorder_lod_tensor_by_rank": "executor: same rank-table machinery",
    "max_sequence_len": "executor: lengths.max() on the explicit Length "
                        "vector (dense sequence contract)",
    "lod_array_length": "executor: tensor-array length is len() of the "
                        "env's python list (ops_tail2 tensor-array note)",
    "tensor_array_to_tensor": "executor: jnp.stack/concat of the env "
                              "list; write_to_array/read_from_array are "
                              "registered, the pack step is jnp",
    "fill_zeros_like2": None,  # registered in ops_tail5
    # -- engines / fused kernels XLA owns --------------------------------
    "tensorrt_engine": "engine: XLA is the engine",
    "lite_engine": "engine: XLA is the engine",
    "fusion_group": "engine: NVRTC runtime codegen; XLA fusion replaces it",
    "conv2d_fusion": "engine: cuDNN fused conv+bias+act; XLA fuses the "
                     "same epilogue automatically",
    "conv2d_inception_fusion": "engine: same (cuDNN-specific)",
    "multihead_matmul": "engine: TRT-era fused attention; the Pallas "
                        "flash kernels are the TPU counterpart",
    "fused_batch_norm_act": "engine: XLA fuses BN+act epilogues; the "
                            "r05 vision ladder measures this fusion",
    "fused_elemwise_activation": "engine: generic elementwise fusion is "
                                 "XLA's bread and butter",
    "fused_embedding_eltwise_layernorm": "engine: TRT fused kernel; "
                                         "XLA + Pallas LN cover it",
    "fused_fc_elementwise_layernorm": "engine: same",
    "fused_embedding_seq_pool": "engine: lookup+pool fuses under jit "
                                "(embedding + sequence_pool lowerings)",
    "fusion_seqpool_cvm_concat": "engine: fusion_seqpool_concat + cvm "
                                 "lowerings fuse under jit",
    "fusion_transpose_flatten_concat": "engine: transpose+reshape+concat "
                                       "is a pure-layout chain XLA folds",
    "nccl": "engine: NCCL init/comm ops; ICI collectives are built into "
            "the mesh runtime (parallel/)",
    # -- RPC / pslib / BoxPS service clients ------------------------------
    "listen_and_serv": "service: the PS serve loop is "
                       "distributed/ps_server.py (PSServer), a process, "
                       "not a graph op",
    "fl_listen_and_serv": "service: federated-learning variant of the "
                          "same serve loop",
    "send": "service: transport lives in ps_server._Conn",
    "recv": "service: same",
    "send_barrier": "service: PSServer barrier op (_OP_BARRIER)",
    "fetch_barrier": "service: same",
    "send_and_recv": "service: same transport",
    "recv_save": "service: server-side checkpoint of remote vars; "
                 "SparseTable.state_dict + utils/fs cover the capability",
    "checkpoint_notify": "service: same",
    "prefetch": "service: sparse-table prefetch RPC; RemoteSparseTable "
                "pulls synchronously (documented N23 descope)",
    "ref_by_trainer_id": "service: PS-side per-trainer slicing",
    "pull_box_sparse": "service: BoxPS (Baidu KV service) client; "
                       "host-RAM SparseTable is the re-scope",
    "pull_box_extended_sparse": "service: same",
    "push_box_sparse": "service: same",
    "push_box_extended_sparse": "service: same",
    "push_dense": "service: pslib dense push; fleet dp allreduce covers it",
    "lookup_sparse_table_init": "service: pslib large-scale-KV init; "
                                "SparseTable ctor is the re-scope",
    "lookup_sparse_table_read": "service: SparseTable.pull",
    "lookup_sparse_table_write": "service: SparseTable.push",
    "lookup_sparse_table_grad_split": "service: GeoCommunicator delta "
                                      "splitting covers the capability",
    "lookup_table_dequant": "service: quantized pslib table read; "
                            "slim/ dequant ops + SparseTable cover the "
                            "pieces",
    # -- host-side / contrib re-scopes ------------------------------------
    "run_program": "host: dygraph partial-program op; jit/dy2static.py "
                   "converts at the AST level instead",
    "rank_attention": "host: contrib op marked 'not shown to the public' "
                      "in its own AddComment",
    "similarity_focus": "host: contrib attention-visualization op with "
                        "serial per-channel dedup semantics; no model in "
                        "the reference zoo consumes it",
    "tdm_child": None,  # registered in ops_tail7
    "tdm_sampler": None,  # registered in ops_tail7
    "match_matrix_tensor": None,  # registered in ops_tail7
    "sequence_topk_avg_pooling": None,  # registered in ops_tail7
    "var_conv_2d": None,  # registered in ops_tail3
    # -- detection label-generation (RCNN/RetinaNet training pipelines) ---
    "generate_proposals": None,  # registered in ops_tail6
    "generate_proposal_labels": None,  # registered in ops_tail7
    "generate_mask_labels": "host: Mask R-CNN mask-target generation "
                            "rasterizes per-instance POLYGON annotations "
                            "(Poly2Mask, variable vertex counts per gt) "
                            "into roi-cropped grids — the polygon inputs "
                            "are inherently ragged host data, unlike the "
                            "box-only sampling of the now-registered "
                            "generate_proposal_labels",
    "rpn_target_assign": None,    # registered in ops_tail6
    "retinanet_target_assign": None,  # registered in ops_tail7
    "retinanet_detection_output": None,  # registered in ops_tail7
    "distribute_fpn_proposals": None,  # registered in ops_tail6
    "collect_fpn_proposals": None,     # registered in ops_tail6
    "box_decoder_and_assign": None,  # registered in ops_tail6
    "deformable_psroi_pooling": None,  # registered in ops_tail7
    "locality_aware_nms": "host: OCR-specific NMS variant of the "
                          "registered multiclass_nms",
    "matrix_nms": None,           # registered in ops_tail6
    "roi_perspective_transform": None,  # registered in ops_tail7
    "mine_hard_examples": None,   # registered in ops_tail5
    "detection_map": "host: mAP metric with per-class ragged accumulation; "
                     "metric/metrics.py DetectionMAP is the eager "
                     "re-scope",
    "bipartite_match": None,      # registered in ops_tail5
    "target_assign": None,        # registered in ops_tail5
    "polygon_box_transform": None,  # registered in ops_tail5
    # -- misc ------------------------------------------------------------
    "hierarchical_sigmoid": None,  # registered in ops_tail5
    "cross_entropy_grad2": "executor: paired grad kernel; gradients come "
                           "from AD-of-replay",
}

# prune the None markers (ops that WERE registered after the table was
# first written — kept as comments for audit history)
DESCOPED = {k: v for k, v in DESCOPED.items() if v is not None}
