"""Op version registry: checkpoint/program compatibility across op changes.

Reference parity: ``paddle/fluid/framework/op_version_registry.h`` —
``REGISTER_OP_VERSION(op).AddCheckpoint(note, changes...)`` records each
op's version history; saved programs carry the op-version map and loaders
compare it against the running registry (``op_version_proto``,
``save/load`` compatibility checks).

TPU-native design: the registry also carries optional CONVERTERS — pure
functions upgrading a saved op's ``(inputs, outputs, attrs)`` dicts from
version N to N+1 — so ``static.load`` doesn't merely detect skew, it
migrates old packages forward at load time (the part the reference leaves
to manual release notes).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["register_op_version", "op_version", "op_version_map",
           "apply_converters", "check_compatible", "OpVersionDesc"]


class OpVersionDesc:
    __slots__ = ("version", "note", "converter")

    def __init__(self, version: int, note: str,
                 converter: Optional[Callable] = None):
        self.version = version
        self.note = note
        # converter(inputs: dict, outputs: dict, attrs: dict) -> same
        # triple, upgrading FROM version-1 TO version
        self.converter = converter


# op_type -> ordered checkpoints (versions 1..n; absent = version 0)
_REGISTRY: Dict[str, List[OpVersionDesc]] = {}


def register_op_version(op_type: str, note: str,
                        converter: Optional[Callable] = None) -> int:
    """Add a checkpoint to ``op_type``'s history (ref AddCheckpoint);
    returns the new current version."""
    cps = _REGISTRY.setdefault(op_type, [])
    cps.append(OpVersionDesc(len(cps) + 1, note, converter))
    return len(cps)


def op_version(op_type: str) -> int:
    return len(_REGISTRY.get(op_type, ()))


def op_version_map() -> Dict[str, int]:
    """Current {op_type: version} for every versioned op — what ``save``
    stamps into the package (ref op_version_proto pb map)."""
    return {t: len(cps) for t, cps in _REGISTRY.items()}


def apply_converters(op_type: str, saved_version: int, inputs: dict,
                     outputs: dict, attrs: dict
                     ) -> Tuple[dict, dict, dict]:
    """Upgrade one op desc from ``saved_version`` to the current version,
    running each checkpoint's converter in order.  A checkpoint without a
    converter is a semantic note only (reference behavior: detection, no
    migration) and passes the desc through unchanged."""
    for desc in _REGISTRY.get(op_type, ())[saved_version:]:
        if desc.converter is not None:
            inputs, outputs, attrs = desc.converter(inputs, outputs, attrs)
    return inputs, outputs, attrs


def check_compatible(saved_map: Dict[str, int]) -> List[str]:
    """Problems loading a package saved with ``saved_map``: ops saved with
    a NEWER version than this runtime knows (forward-incompatible)."""
    problems = []
    for op_type, v in saved_map.items():
        cur = op_version(op_type)
        if v > cur:
            problems.append(
                f"op {op_type!r} was saved at version {v} but this runtime "
                f"knows version {cur} — upgrade paddle_tpu to load it")
    return problems


# -- seeded history (mirrors reference op_version.yaml-era checkpoints for
#    ops whose semantics changed across this rebuild's rounds) --------------

def _seq_pad_rename(inputs, outputs, attrs):
    # round-3 packages used attr "max_len"; current op takes "maxlen"
    if "max_len" in attrs and "maxlen" not in attrs:
        attrs = dict(attrs)
        attrs["maxlen"] = attrs.pop("max_len")
    return inputs, outputs, attrs


register_op_version(
    "sequence_pad",
    "rename attr max_len -> maxlen (dense-layout contract)",
    _seq_pad_rename)
register_op_version(
    "multiclass_nms",
    "drop the unproduced Index output slot (executor binds Out/NmsRoisNum)",
    lambda i, o, a: (i, {k: v for k, v in o.items() if k != "Index"}, a))
register_op_version(
    "linspace",
    "Num moved from a (traced) input tensor to the static attr 'num'")
