"""Persistent on-disk AOT executable cache for the Executor fast path.

Reference parity: the closest ancestors are the reference's in-process
prepared-context cache (fluid/executor.py:1272 — a dict of Prepared
contexts keyed on program id, gone when the process dies) and
ParallelExecutor's per-device program clones, both of which re-lower the
ProgramDesc in every worker of a fleet.  TPU-native design: jax-
compilation-cache-style — the traced-and-lowered step function is
serialized with ``jax.export`` (StableHLO + input shardings + calling
convention) and written under ``compile_cache_dir``; a later process —
another fleet worker, a restarted trainer, a serving replica — deserializes
the artifact and jits its ``call`` (donation re-applied via
``donate_argnums``), skipping the program trace and XLA lowering entirely.

Key discipline (a wrong hit is silent corruption, so everything that can
change the compiled artifact is in the key):

* schema version of this file format,
* jax + jaxlib versions and the backend platform/device kind,
* the program *content* fingerprint (canonical walk of every block: op
  types, sorted input/output slots, canonicalized attrs, var
  shape/dtype/persistable) — not object identity,
* the PRNG seed baked into the compiled step,
* fetch names, feed signature, donated/carried state signatures, donation,
* the mesh shape × sharding-plan fingerprint (parallel/sharding.py
  ``ShardingPlan.fingerprint``; ``"single"`` off-mesh).

Entries are self-checking: ``PDTC`` magic + schema + SHA-256 over the
payload, written atomically (tmp + ``os.replace``) so a crashed writer
never leaves a half entry.  ``load`` returns ``None`` on ANY failure —
truncation, bit-rot, version skew, a hand-edited file — and the caller
falls back to a normal compile; a corrupt cache can cost time, never
correctness.
"""
from __future__ import annotations

import hashlib
import os
import struct
import tempfile
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..utils import monitor as _monitor
from ..utils import trace as _trace

__all__ = ["CompileCache", "active_cache", "program_fingerprint",
           "build_cache_key"]

# -- telemetry (registered at import so metricsdump lists them) --------------
_m_cc_hit = _monitor.counter(
    "executor.compile_cache_hit",
    "Persistent compile-cache hits: compiled steps deserialized from "
    "compile_cache_dir instead of traced + lowered.")
_m_cc_miss = _monitor.counter(
    "executor.compile_cache_miss",
    "Persistent compile-cache misses: steps traced, lowered, and (when the "
    "export succeeded) serialized into compile_cache_dir.")
_m_cold_ms = _monitor.histogram(
    "executor.cold_start_ms",
    "Cold-start wall time of an Executor compile-cache-entry build (ms): "
    "everything between the in-memory cache miss and the first step's "
    "dispatch, labeled by where the executable came from (cache=hit: "
    "deserialized from compile_cache_dir; miss: compiled then stored; "
    "off: persistent cache disabled).", labelnames=("cache",))

_MAGIC = b"PDTC"
_SCHEMA = 1


def _canon(value) -> str:
    """Canonical stable repr for attr/spec values (dict order, numpy arrays,
    and container types normalized; floats via repr so 0.1 survives)."""
    if isinstance(value, np.ndarray):
        return (f"nd({value.dtype}:{value.shape}:"
                f"{hashlib.sha256(np.ascontiguousarray(value).tobytes()).hexdigest()[:16]})")
    if isinstance(value, np.generic):
        return f"np({value.dtype}:{value!r})"
    if isinstance(value, dict):
        items = ",".join(f"{_canon(k)}:{_canon(v)}"
                         for k, v in sorted(value.items(), key=lambda kv: str(kv[0])))
        return "{" + items + "}"
    if isinstance(value, (list, tuple)):
        return "[" + ",".join(_canon(v) for v in value) + "]"
    if isinstance(value, bytes):
        return f"b({hashlib.sha256(value).hexdigest()[:16]})"
    return f"{type(value).__name__}:{value!r}"


def program_fingerprint(program) -> str:
    """Content hash of a static Program: every block's ops (type, sorted
    input/output slots, canonical attrs) and vars (shape/dtype/persistable).
    Identity- and process-independent — two workers building the same graph
    get the same fingerprint."""
    h = hashlib.sha256()
    for block in program.blocks:
        h.update(f"block{block.idx}".encode())
        for name in sorted(getattr(block, "vars", {})):
            v = block.vars[name]
            h.update(f"var:{name}:{getattr(v, 'shape', None)}:"
                     f"{getattr(v, 'dtype', None)}:"
                     f"{int(bool(getattr(v, 'persistable', False)))};".encode())
        for op in block.ops:
            ins = ",".join(f"{k}={sorted(v)}"
                           for k, v in sorted(op.inputs.items()))
            outs = ",".join(f"{k}={sorted(v)}"
                            for k, v in sorted(op.outputs.items()))
            attrs = ",".join(f"{k}={_canon(v)}"
                             for k, v in sorted(op.attrs.items()))
            h.update(f"op:{op.type}|{ins}|{outs}|{attrs};".encode())
    return h.hexdigest()


def _sig(arrays: Dict[str, Any]) -> str:
    return ";".join(f"{k}:{tuple(np.shape(v))}:{np.asarray(v).dtype if not hasattr(v, 'dtype') else v.dtype}"
                    for k, v in sorted(arrays.items()))


def build_cache_key(program, seed: int, fetch_names: Sequence[str],
                    feed_arrays: Dict[str, Any], donated: Dict[str, Any],
                    carried: Dict[str, Any], donate: bool,
                    plan_fingerprint: Optional[str],
                    entry: str = "", passes: str = "",
                    kernel: str = "") -> str:
    """SHA-256 key for one compiled step artifact (see module docstring for
    what is deliberately included).  ``entry`` is the Executor's entry-key
    partition (serving shape buckets); ``passes`` is the graph-rewrite
    pipeline fingerprint (static/passes.py) the program was compiled under;
    ``kernel`` is the effective Pallas kernel-config fingerprint
    (ops/pallas/config.py) — kernel selection happens at trace time, so
    artifacts traced under different kernel sets are different executables.
    Each rides the key only when set, so bucket-keyed / pass-optimized /
    kernel-gated artifacts never collide with the default's and legacy
    keys are unchanged."""
    import jax
    import jaxlib

    backend = jax.default_backend()
    kind = "?"
    try:
        kind = jax.devices(backend)[0].device_kind
    except Exception:
        pass
    parts = (
        f"schema={_SCHEMA}",
        f"jax={jax.__version__}",
        f"jaxlib={jaxlib.__version__}",
        f"backend={backend}:{kind}:{jax.device_count()}",
        f"program={program_fingerprint(program)}",
        f"seed={int(seed)}",
        f"fetch={list(fetch_names)}",
        f"feed={_sig(feed_arrays)}",
        f"donated={_sig(donated)}",
        f"carried={_sig(carried)}",
        f"donate={int(bool(donate))}",
        f"plan={plan_fingerprint or 'single'}",
    )
    if entry:
        parts = parts + (f"entry={entry}",)
    if passes:
        parts = parts + (f"passes={passes}",)
    if kernel:
        parts = parts + (f"kernel={kernel}",)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


class CompileCache:
    """Content-addressed store of serialized ``jax.export`` artifacts.

    One file per key under ``root``; writes are atomic (tmp file in the same
    directory + ``os.replace``) and reads are checksum-verified, so a
    corrupted or torn entry deserializes to ``None`` — never to a wrong
    executable."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.pdtc")

    def load(self, key: str) -> Optional[bytes]:
        """The stored payload, or None on miss OR any corruption/skew — the
        caller recompiles; a bad cache entry must never raise."""
        try:
            with open(self.path(key), "rb") as f:
                data = f.read()
            if len(data) < 4 + 4 + 32 or data[:4] != _MAGIC:
                return None
            (schema,) = struct.unpack("<I", data[4:8])
            if schema != _SCHEMA:
                return None
            digest, payload = data[8:40], data[40:]
            if hashlib.sha256(payload).digest() != digest:
                _trace.flight_recorder().record(
                    "compile_cache_corrupt", key=key[:16],
                    path=self.path(key))
                return None
            return payload
        except Exception:
            return None

    def store(self, key: str, payload: bytes) -> bool:
        """Atomically persist one artifact; failures (read-only dir, disk
        full) are non-fatal — the in-memory executable still runs."""
        try:
            blob = (_MAGIC + struct.pack("<I", _SCHEMA)
                    + hashlib.sha256(payload).digest() + payload)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, self.path(key))
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            return True
        except Exception as e:
            _trace.flight_recorder().record(
                "compile_cache_store_failed", key=key[:16], error=repr(e))
            return False


def active_cache() -> Optional[CompileCache]:
    """The process cache per the ``compile_cache_dir`` flag (None = off)."""
    from ..core import flags as _flags

    root = _flags.get_flag("compile_cache_dir")
    if not root:
        return None
    try:
        return CompileCache(root)
    except Exception as e:
        _trace.flight_recorder().record(
            "compile_cache_unavailable", root=str(root), error=repr(e))
        return None
