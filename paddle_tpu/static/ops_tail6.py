"""Static-op long tail, batch 6: the RCNN/FPN detection training tail.

Reference parity targets: detection/generate_proposals_op.cc (RPN
proposal stage: top-k → BoxCoder decode → clip → min-size filter → NMS),
rpn_target_assign_op.cc (anchor fg/bg sampling), matrix_nms_op.cc
(PP-YOLO's parallel soft-NMS), box_decoder_and_assign_op.h (per-class
decode + argmax-class assign), distribute_fpn_proposals_op.h /
collect_fpn_proposals_op.h (FPN level routing and its inverse).

TPU-native contracts (static shapes; same padded + valid-count policy
as batches 4/5 — valid entries first, zero/-1 pad, counts under an
optional output slot):
- generate_proposals emits (N, post_nms_topN, 4) rois + (N, topN, 1)
  probs + RpnRoisNum valid counts; the adaptive-eta NMS re-threshold
  loop (eta < 1) is descoped to the standard fixed-threshold NMS the
  reference defaults to (eta=1).
- rpn_target_assign's random fg/bg subsampling uses the executor's
  per-op PRNG scope (deterministic under `paddle_tpu.seed`); outputs are
  (N, batch_size_per_im) padded index lists per image plus counts —
  the reference's ragged concatenation collapses to per-image rows.
- matrix_nms is the ONE reference NMS that is embarrassingly parallel
  (decay over a pairwise IoU matrix, no sequential suppression) — it
  maps onto the TPU better than classic NMS: one (topk, topk) matrix
  per class, no loop.
- distribute_fpn_proposals returns per-level (R, 4) tensors padded to
  the full roi count + per-level counts + RestoreIndex; collect reverses
  it with score-ordered top-k.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from .registry import register_op


def _one(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


def _iou_xyxy(a, b, normalized=True):
    """Pairwise IoU of (n, 4) x (m, 4) corner boxes."""
    off = 0.0 if normalized else 1.0
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * \
        jnp.maximum(a[:, 3] - a[:, 1] + off, 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + off, 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def _greedy_nms_mask(boxes, scores, thresh, max_out, class_ids=None,
                     valid=None, normalized=True):
    """Greedy NMS over score-sorted boxes: returns (order, keep_mask) with
    at most max_out kept.  boxes (n, 4) corner form.  ``class_ids``
    restricts suppression to SAME-CLASS pairs (one loop instead of one
    per class); ``valid`` pre-drops rows."""
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_xyxy(b, b, normalized=normalized)
    if class_ids is not None:
        c = class_ids[order]
        iou = jnp.where(c[:, None] == c[None, :], iou, 0.0)
    v = None if valid is None else valid[order]

    def body(i, keep):
        # suppressed if any higher-ranked KEPT box overlaps > thresh
        sup = jnp.max(jnp.where(jnp.arange(n) < i,
                                iou[i] * keep.astype(iou.dtype),
                                0.0)) > thresh
        drop = sup if v is None else (sup | ~v[i])
        return keep.at[i].set(jnp.where(drop, 0, 1))

    keep = jax.lax.fori_loop(0, n, body, jnp.zeros((n,), jnp.int32))
    # cap at max_out: rank among kept
    kept_rank = jnp.cumsum(keep) - 1
    keep = keep * (kept_rank < max_out)
    return order, keep.astype(bool)


@register_op("generate_proposals")
def _generate_proposals(ins, attrs, op):
    """ref detection/generate_proposals_op.cc (RPN stage).  Scores
    (N, A, H, W), BboxDeltas (N, 4A, H, W), Anchors/Variances
    (H, W, A, 4) or (A*H*W, 4), ImInfo (N, 3)."""
    scores = _one(ins, "Scores")
    deltas = _one(ins, "BboxDeltas")
    im_info = _one(ins, "ImInfo")
    anchors = _one(ins, "Anchors").reshape(-1, 4).astype(jnp.float32)
    variances = _one(ins, "Variances")
    variances = (variances.reshape(-1, 4).astype(jnp.float32)
                 if variances is not None else jnp.ones_like(anchors))
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.1))

    N, A, H, W = scores.shape
    M = A * H * W
    # (N, A, H, W) -> (N, H, W, A) -> flat, matching the kernel's
    # transpose so flat index i maps to the same anchor row
    sc = scores.transpose(0, 2, 3, 1).reshape(N, M).astype(jnp.float32)
    dl = deltas.reshape(N, A, 4, H, W).transpose(0, 3, 4, 1, 2) \
        .reshape(N, M, 4).astype(jnp.float32)
    pre_n = min(pre_n if pre_n > 0 else M, M)
    post_n = min(post_n, pre_n)

    def one_image(sc_i, dl_i, info):
        top_sc, idx = jax.lax.top_k(sc_i, pre_n)
        anc = anchors[idx]
        var = variances[idx]
        d = dl_i[idx]
        # BoxCoder (generate_proposals_op.cc:69): +1 widths, var-scaled
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + 0.5 * aw
        acy = anc[:, 1] + 0.5 * ah
        kclip = jnp.log(1000.0 / 16.0)
        cx = var[:, 0] * d[:, 0] * aw + acx
        cy = var[:, 1] * d[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(var[:, 2] * d[:, 2], kclip)) * aw
        h = jnp.exp(jnp.minimum(var[:, 3] * d[:, 3], kclip)) * ah
        props = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                           cx + 0.5 * w - 1.0, cy + 0.5 * h - 1.0], -1)
        # clip to image (im_info = (h, w, scale))
        props = jnp.clip(props,
                         jnp.zeros((4,)),
                         jnp.stack([info[1] - 1, info[0] - 1,
                                    info[1] - 1, info[0] - 1]))
        # min-size filter in ORIGINAL image scale (FilterBoxes,
        # generate_proposals_op.cc:161: keep iff (x2-x1)/scale + 1 >= ms)
        ms = jnp.maximum(min_size, 1.0)
        keep_sz = ((props[:, 2] - props[:, 0]) / info[2] + 1.0 >= ms) & \
            ((props[:, 3] - props[:, 1]) / info[2] + 1.0 >= ms)
        sc_f = jnp.where(keep_sz, top_sc, -jnp.inf)
        order, keep = _greedy_nms_mask(props, sc_f, nms_thresh, post_n)
        ordered = props[order]
        osc = sc_f[order]
        okeep = keep & jnp.isfinite(osc)
        tgt = jnp.cumsum(okeep) - 1
        rois = jnp.zeros((post_n, 4), jnp.float32).at[
            jnp.where(okeep, tgt, post_n)].set(ordered, mode="drop")
        probs = jnp.zeros((post_n,), jnp.float32).at[
            jnp.where(okeep, tgt, post_n)].set(
            jnp.where(okeep, osc, 0.0), mode="drop")
        return rois, probs[:, None], okeep.sum().astype(jnp.int64)

    rois, probs, counts = jax.vmap(one_image)(sc, dl,
                                              im_info.astype(jnp.float32))
    return {"RpnRois": [rois], "RpnRoiProbs": [probs],
            "RpnRoisNum": [counts], "RpnRoisLod": [jnp.cumsum(counts)]}


@register_op("rpn_target_assign")
def _rpn_target_assign(ins, attrs, op):
    """ref rpn_target_assign_op.cc: per image, anchors >= pos_overlap IoU
    with some gt (plus each gt's argmax anchor) are foreground,
    < neg_overlap are background; subsample to rpn_batch_size_per_im at
    rpn_fg_fraction.  Dense: Anchor (A, 4), GtBoxes (N, G, 4) (-row pad
    with w<=0), outputs per-image padded index rows + counts."""
    anchors = _one(ins, "Anchor").astype(jnp.float32)
    gt = _one(ins, "GtBoxes").astype(jnp.float32)
    if gt.ndim == 2:
        gt = gt[None]
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    pos_th = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_th = float(attrs.get("rpn_negative_overlap", 0.3))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    use_random = bool(attrs.get("use_random", True))
    A = anchors.shape[0]
    fg_cap = int(batch * fg_frac)
    key = _random.next_key()

    def one_image(gt_i, key):
        valid_gt = gt_i[:, 2] > gt_i[:, 0]
        iou = _iou_xyxy(anchors, gt_i, normalized=False)
        iou = jnp.where(valid_gt[None, :], iou, 0.0)
        a2g_max = iou.max(axis=1)
        a2g_arg = iou.argmax(axis=1).astype(jnp.int32)
        g2a_max = iou.max(axis=0)
        # fg: >= pos_th, plus the argmax anchor of every gt
        is_best = jnp.any((iou == g2a_max[None, :]) & (g2a_max[None, :] > 0)
                          & valid_gt[None, :], axis=1)
        fg = (a2g_max >= pos_th) | is_best
        bg = (a2g_max < neg_th) & ~fg
        kf, kb = jax.random.split(key)
        rf = jax.random.uniform(kf, (A,))
        rb = jax.random.uniform(kb, (A,))
        if not use_random:
            rf = jnp.arange(A) / A
            rb = jnp.arange(A) / A
        # random subsample: rank the candidates by a random draw and keep
        # the first fg_cap / (batch - n_fg)
        fg_rank = jnp.argsort(jnp.argsort(jnp.where(fg, rf, 2.0)))
        fg_sel = fg & (fg_rank < fg_cap)
        n_fg = fg_sel.sum()
        bg_cap = batch - n_fg
        bg_rank = jnp.argsort(jnp.argsort(jnp.where(bg, rb, 2.0)))
        bg_sel = bg & (bg_rank < bg_cap)

        def compact(mask, fill):
            tgt = jnp.cumsum(mask) - 1
            out = jnp.full((batch,), fill, jnp.int32).at[
                jnp.where(mask, tgt, batch)].set(
                jnp.arange(A, dtype=jnp.int32), mode="drop")
            return out

        loc_index = compact(fg_sel, -1)
        score_sel = fg_sel | bg_sel
        score_index = compact(score_sel, -1)
        tgt_lbl = jnp.zeros((batch,), jnp.int32).at[
            jnp.where(fg_sel, jnp.cumsum(score_sel) - 1, batch)].set(
            1, mode="drop")
        gt_of_fg = jnp.full((batch,), -1, jnp.int32).at[
            jnp.where(fg_sel, jnp.cumsum(fg_sel) - 1, batch)].set(
            a2g_arg, mode="drop")
        # TargetBBox carries the MATCHED GT BOXES (the reference's {-1,4}
        # contract, rpn_target_assign_op.cc:76) ready for smooth-L1
        target_bbox = jnp.where((gt_of_fg >= 0)[:, None],
                                gt_i[jnp.maximum(gt_of_fg, 0)], 0.0)
        return (loc_index, score_index, tgt_lbl, target_bbox, gt_of_fg,
                n_fg.astype(jnp.int64), score_sel.sum().astype(jnp.int64))

    N = gt.shape[0]
    keys = jax.random.split(key, N)
    loc, score, lbl, tbox, gtidx, nfg, nsc = jax.vmap(one_image)(gt, keys)
    return {"LocationIndex": [loc], "ScoreIndex": [score],
            "TargetLabel": [lbl], "TargetBBox": [tbox],
            "MatchedGtIndex": [gtidx],
            "BBoxInsideWeight": [jnp.broadcast_to(
                (loc >= 0).astype(jnp.float32)[..., None],
                tbox.shape)],
            "ForegroundNumber": [nfg], "ScoreNumber": [nsc]}


@register_op("matrix_nms")
def _matrix_nms(ins, attrs, op):
    """ref matrix_nms_op.cc: parallel soft-NMS — each box's score decays
    by min over higher-ranked boxes of decay(iou, max_iou); no sequential
    suppression, so it vectorizes as one (k, k) matrix per class.
    Dense: BBoxes (N, M, 4), Scores (N, C, M); Out (N, keep_top_k, 6)
    rows [class, score, x1, y1, x2, y2] zero-padded + RoisNum."""
    bboxes = _one(ins, "BBoxes").astype(jnp.float32)
    scores = _one(ins, "Scores").astype(jnp.float32)
    score_th = float(attrs.get("score_threshold", 0.05))
    post_th = float(attrs.get("post_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", 400))
    keep_top_k = int(attrs.get("keep_top_k", 200))
    use_gaussian = bool(attrs.get("use_gaussian", False))
    sigma = float(attrs.get("gaussian_sigma", 2.0))
    background = int(attrs.get("background_label", 0))
    normalized = bool(attrs.get("normalized", True))

    N, C, M = scores.shape
    k = min(nms_top_k if nms_top_k > 0 else M, M)

    def one_class(boxes, sc):
        top_sc, idx = jax.lax.top_k(sc, k)
        valid = top_sc > score_th
        b = boxes[idx]
        iou = _iou_xyxy(b, b, normalized=normalized)
        tri = jnp.tril(jnp.ones((k, k), bool), -1)  # j < i
        iou_l = jnp.where(tri, iou, 0.0)
        iou_max = jnp.max(iou_l, axis=1)            # max iou vs higher-ranked
        if use_gaussian:
            # ref matrix_nms_op.cc:83: exp((max_iou^2 - iou^2) * sigma)
            decay = jnp.exp((iou_max[None, :] ** 2 - iou_l ** 2) * sigma)
        else:
            decay = (1.0 - iou_l) / jnp.maximum(1.0 - iou_max[None, :],
                                                1e-10)
        decay = jnp.where(tri, decay, 1.0)
        min_decay = jnp.min(decay, axis=1)
        ds = min_decay * top_sc
        keep = valid & (ds > post_th)
        return b, jnp.where(keep, ds, 0.0)

    def one_image(boxes, sc_img):
        bs, dss = jax.vmap(lambda s: one_class(boxes, s))(sc_img)  # (C,k,..)
        cls = jnp.broadcast_to(jnp.arange(C, dtype=jnp.float32)[:, None],
                               (C, k))
        flat_ds = dss.reshape(-1)
        if 0 <= background < C:
            bg_mask = (cls.reshape(-1) == background)
            flat_ds = jnp.where(bg_mask, 0.0, flat_ds)
        keep_k = C * k if keep_top_k <= 0 else min(keep_top_k, C * k)
        top_ds, fidx = jax.lax.top_k(flat_ds, keep_k)
        out = jnp.concatenate([
            cls.reshape(-1, 1)[fidx], top_ds[:, None],
            bs.reshape(-1, 4)[fidx]], axis=1)
        valid = top_ds > 0
        out = jnp.where(valid[:, None], out, 0.0)
        return out, valid.sum().astype(jnp.int64)

    out, counts = jax.vmap(one_image)(bboxes, scores)
    return {"Out": [out], "Index": [jnp.zeros_like(counts)],
            "RoisNum": [counts]}


@register_op("box_decoder_and_assign")
def _box_decoder_and_assign(ins, attrs, op):
    """ref box_decoder_and_assign_op.h: decode per-class deltas against
    shared priors (+1 widths, global 4-var), then assign each roi the box
    of its argmax non-background class score."""
    prior = _one(ins, "PriorBox").astype(jnp.float32)      # (R, 4)
    pvar = _one(ins, "PriorBoxVar").astype(jnp.float32)    # (4,)
    target = _one(ins, "TargetBox").astype(jnp.float32)    # (R, C*4)
    score = _one(ins, "BoxScore").astype(jnp.float32)      # (R, C)
    clip = float(attrs.get("box_clip", 4.135166556742356))
    R, C = score.shape
    t = target.reshape(R, C, 4)
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    dw = jnp.minimum(pvar[2] * t[:, :, 2], clip)
    dh = jnp.minimum(pvar[3] * t[:, :, 3], clip)
    cx = pvar[0] * t[:, :, 0] * pw[:, None] + pcx[:, None]
    cy = pvar[1] * t[:, :, 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - 1.0, cy + h / 2 - 1.0], axis=-1)
    decode_box = dec.reshape(R, C * 4)
    # assign: argmax over classes 1..C-1 (0 = background)
    sc = score.at[:, 0].set(-jnp.inf) if C > 1 else score
    best = jnp.argmax(sc, axis=1)
    assign = dec[jnp.arange(R), best]
    return {"DecodeBox": [decode_box], "OutputAssignBox": [assign]}


_FPN_EPS = 1e-6


@register_op("distribute_fpn_proposals")
def _distribute_fpn_proposals(ins, attrs, op):
    """ref distribute_fpn_proposals_op.h: route each roi to FPN level
    floor(refer_level + log2(sqrt(area)/refer_scale)), clipped to
    [min_level, max_level].  Dense: FpnRois (R, 4) -> per-level (R, 4)
    zero-padded + per-level counts + RestoreIndex."""
    rois = _one(ins, "FpnRois").astype(jnp.float32)
    min_l = int(attrs["min_level"])
    max_l = int(attrs["max_level"])
    refer_l = int(attrs["refer_level"])
    refer_s = int(attrs["refer_scale"])
    num_l = max_l - min_l + 1
    R = rois.shape[0]
    valid = (rois[:, 2] > rois[:, 0]) | (rois[:, 3] > rois[:, 1])
    # BBoxArea(rois, normalized=false): +1 widths
    # (distribute_fpn_proposals_op.h:32)
    area = jnp.maximum(rois[:, 2] - rois[:, 0] + 1, 0) * \
        jnp.maximum(rois[:, 3] - rois[:, 1] + 1, 0)
    scale = jnp.sqrt(area)
    lvl = jnp.floor(jnp.log2(scale / refer_s + _FPN_EPS)) + refer_l
    lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
    lvl = jnp.where(valid, lvl, -1)

    outs, counts, restore_parts = [], [], []
    offset = jnp.zeros((), jnp.int32)
    restore = jnp.full((R,), -1, jnp.int32)
    for li, level in enumerate(range(min_l, max_l + 1)):
        mask = lvl == level
        tgt = jnp.cumsum(mask) - 1
        out = jnp.zeros((R, 4), jnp.float32).at[
            jnp.where(mask, tgt, R)].set(rois, mode="drop")
        outs.append(out)
        n = mask.sum().astype(jnp.int32)
        counts.append(n.astype(jnp.int64))
        # original position i of this level's row r sits at offset+r in
        # the concatenated-by-level order; RestoreIndex maps back
        restore = restore.at[jnp.where(mask, offset + tgt, R)].set(
            jnp.arange(R, dtype=jnp.int32), mode="drop")
        offset = offset + n
    return {"MultiFpnRois": outs,
            "MultiLevelRoIsNum": [jnp.stack(counts)],
            "RestoreIndex": [restore[:, None]]}


@register_op("collect_fpn_proposals")
def _collect_fpn_proposals(ins, attrs, op):
    """ref collect_fpn_proposals_op.h: concat per-level rois+scores, keep
    the global top post_nms_topN by score.  Dense: each level zero-padded
    (R_l, 4) + per-level valid counts via MultiLevelRoIsNum."""
    rois_list = ins.get("MultiLevelRois", [])
    scores_list = ins.get("MultiLevelScores", [])
    counts = _one(ins, "MultiLevelRoIsNum")
    post_n = int(attrs.get("post_nms_topN", 1000))
    all_rois = jnp.concatenate([r.reshape(-1, 4) for r in rois_list], 0)
    all_scores = jnp.concatenate([s.reshape(-1) for s in scores_list], 0)
    if counts is not None:
        masks = []
        for i, r in enumerate(rois_list):
            n = counts[i]
            masks.append(jnp.arange(r.reshape(-1, 4).shape[0]) < n)
        m = jnp.concatenate(masks)
        all_scores = jnp.where(m, all_scores, -jnp.inf)
    k = min(post_n, all_scores.shape[0])
    top_sc, idx = jax.lax.top_k(all_scores, k)
    sel = all_rois[idx]
    valid = jnp.isfinite(top_sc)
    sel = jnp.where(valid[:, None], sel, 0.0)
    return {"FpnRois": [sel],
            "RoisNum": [valid.sum().astype(jnp.int64)]}
