"""Sharding-plan verifier: Program × ShardingPlan static checks (SC001–SC010).

The second tier of the static-analysis stack.  Tier one
(``static/analysis.py``, PV001–PV010) checks a Program in isolation; this
module checks the *pairing* of a Program with a ``parallel.ShardingPlan``
— the misconfigurations that today surface minutes into a run as an opaque
XLA trace error, a ``ValueError`` deep inside ``feed_sharding``, or (worst)
a silent wrong layout: a param the user believes is tensor-parallel that
``infer_sharding`` quietly replicated because a dim was indivisible.

Diagnostic codes (severity ``error`` aborts ``Executor.run`` under flag
``check_sharding``; ``warning`` never does):

- ``SC001`` feed batch divisibility: a concrete feed batch dim (or a
  serving bucket edge) does not divide the plan's batch-axis device
  product — ``feed_sharding`` would raise at placement time, the serving
  frontend at first submit.  An indivisible ``seq_axis`` dim is a warning
  (the plan silently skips sequence sharding there).
- ``SC002`` mesh-axis validity: a rules/annotations/batch_axes/seq_axis
  axis name that is neither in the mesh nor a canonical axis
  (dp/pp/ep/sp/tp) — almost always a typo; a difflib nearest-name
  suggestion is attached.  A *canonical* name absent from the mesh is the
  legitimate degree-1 collapse and stays silent.
- ``SC003`` state placement: an annotation whose rank does not match the
  variable, or an annotation/rule spec over an indivisible dim —
  ``infer_sharding`` silently falls back to replication (annotation: error;
  broad-regex rule: warning).  An annotation overriding a matching rule is
  a warning (precedence is defined, but usually unintended).
- ``SC004`` donation aliasing: under a donating plan, a var that is both
  ``is_data`` and persistable (the donated buffer aliases the feed), or a
  fed name that names persistable state (warning — the executor skips the
  alias at runtime, but the overlap is usually a bug).
- ``SC005`` comm_quantize applicability: unknown quantize kind (today it
  silently disables compression), fp8 without hardware dtype support,
  non-positive block size / buffer, non-float trainable params under block
  quantization; a gradient bucket smaller than one quantization block is a
  warning (scale overhead dominates).
- ``SC006`` sub-block consistency: cond branches whose *inferred* output
  shapes/dtypes disagree, while carries that are not shape-invariant
  against the body — lax.cond/lax.while_loop reject these at trace time
  with an aval error that names no source op.  (Found by the analysis
  engine; surfaced here because declared shapes often agree while inferred
  ones do not.)
- ``SC007`` serving buckets: registration-time validation of a tenant
  program against the server's bucket ladder — unsorted/non-positive
  edges, a fed name that is not a data var, a declared concrete batch dim
  exceeding the largest bucket.
- ``SC008`` ZeRO/annotation conflict: ``zero_stage > 0`` with an
  annotation/rule sharding state over a *batch* axis (dp carries replica
  semantics for gradient sync), or ``zero_stage >= 3`` with a param no dim
  of which divides the dp world (zero_spec silently replicates — warning).
- ``SC009`` predicted collective sites (warning): a matmul-family weight
  sharded on its contraction dim — GSPMD must insert an allreduce /
  all-gather there.  Legitimate for row-parallel layers; the site and its
  estimated bytes feed the communication estimate either way.
- ``SC010`` vocab-sharded embeddings (``ShardingPlan(embedding_shard=)``,
  parallel/embedding.py): a vocab dim indivisible by the shard axis
  (error — the sharded lookup raises at trace time), the shard axis doubling
  as a batch axis or a user annotation conflicting with the plan's table
  placement (errors — silent wrong layout otherwise), and a large table
  served by neither is_sparse nor a shard plan (warning — the backward
  materializes a dense vocab-sized gradient).

``estimate_comm`` additionally produces the static per-bucket allreduce
byte estimate for the data-parallel gradient sync (same math as
``compress.sync_gradients``: reverse-order leaves, ``bucket_assignment``,
``wire_bytes`` per bucket), cross-checkable against the measured
``comm.allreduce_bytes`` histogram via ``CommEstimate.measured_bytes``.

``check_with_plan`` is the Executor entry point: memoized by plan token ×
program version × feed-shape signature, so steady-state cost is zero and
the retrace/fast-path pins hold.  CLI: ``python -m tools.shardcheck``.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import errors as _errors
from ..utils import monitor as _monitor
from .analysis import Diagnostic, infer_program
from .backward import GRAD_SUFFIX
from .framework import Parameter, Program

__all__ = [
    "CommEstimate", "PlanReport", "verify_plan", "check_plan",
    "check_with_plan", "estimate_comm",
]

_m_plans_checked = _monitor.counter(
    "analysis.plans_checked",
    "Full sharding-plan verifier walks (cache misses of check_with_plan "
    "plus direct verify_plan calls).")

# ops whose second operand is contracted: op type -> (weight slot, fn that
# maps (weight rank, attrs) -> contracted dim indices of the weight)
_CONTRACTION_OPS = {
    "mul": ("Y", lambda nd, at: tuple(range(int(at.get("y_num_col_dims", 1))))),
    "matmul": ("Y", lambda nd, at: (
        (nd - 1,) if at.get("transpose_Y", at.get("trans_y", False))
        else (nd - 2,)) if nd >= 2 else (0,)),
    "matmul_v2": ("Y", lambda nd, at: (
        (nd - 1,) if at.get("transpose_Y", at.get("trans_y", False))
        else (nd - 2,)) if nd >= 2 else (0,)),
    "fc": ("W", lambda nd, at: (0,)),
}


# ---------------------------------------------------------------------------
# Result containers
# ---------------------------------------------------------------------------

@dataclass
class CommEstimate:
    """Static communication prediction for one Program × plan."""

    world: int                       # batch-axis device product (dp sync)
    payload: Optional[str]           # "int8"/"fp8" or None (full precision)
    block_size: int
    buffer_mb: float
    # [(leaf names, total elements, predicted wire bytes)] per bucket, in
    # allreduce issue order (reverse parameter-declaration order)
    buckets: List[Tuple[Tuple[str, ...], int, int]] = field(default_factory=list)
    allreduce_bytes: int = 0
    # [(op site, weight name, sharded axes, estimated bytes)] from SC009
    gather_sites: List[Tuple[str, str, Tuple[str, ...], int]] = \
        field(default_factory=list)
    gather_bytes: int = 0
    # [(op site, table name, local ids priced, estimated bytes)] — the
    # vocab-sharded embedding all_to_all exchange (parallel/embedding.py),
    # same dedup-capacity x row-bytes x quantize-ratio math the traced
    # emb.exchange_bytes histogram observes
    exchange_sites: List[Tuple[str, str, int, int]] = field(default_factory=list)
    exchange_bytes: int = 0

    @property
    def total_bytes(self) -> int:
        return self.allreduce_bytes + self.gather_bytes + self.exchange_bytes

    def measured_bytes(self, axis: Optional[str] = None) -> float:
        """Sum of the ``comm.allreduce_bytes`` histogram (recorded at trace
        time by compress._record_comm) for cross-checking the estimate.
        ``axis=None`` sums every labeled cell."""
        return measured_comm_bytes(axis)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "world": self.world,
            "payload": self.payload,
            "block_size": self.block_size,
            "buffer_mb": self.buffer_mb,
            "allreduce_bytes": self.allreduce_bytes,
            "gather_bytes": self.gather_bytes,
            "exchange_bytes": self.exchange_bytes,
            "total_bytes": self.total_bytes,
            "buckets": [{"leaves": list(names), "nelem": nelem,
                         "wire_bytes": wire}
                        for names, nelem, wire in self.buckets],
            "gather_sites": [{"site": site, "weight": w,
                              "axes": list(axes), "bytes": b}
                             for site, w, axes, b in self.gather_sites],
            "exchange_sites": [{"site": site, "table": w,
                                "n_local": n, "bytes": b}
                               for site, w, n, b in self.exchange_sites],
        }


@dataclass
class PlanReport:
    """verify_plan output: diagnostics + the communication estimate +
    the resident-memory estimate (static/memcheck.py) — one call prices
    a plan in both bytes-moved and bytes-resident."""

    diagnostics: List[Diagnostic] = field(default_factory=list)
    comm: Optional[CommEstimate] = None
    mem: Optional[Any] = None          # memcheck.MemEstimate

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    def render(self) -> str:
        lines = []
        if self.diagnostics:
            lines.append(_errors.render_diagnostics(self.diagnostics))
        else:
            lines.append("shardcheck: no findings")
        if self.comm is not None:
            c = self.comm
            lines.append(
                f"comm estimate: world={c.world} payload={c.payload or 'fp32'}"
                f" buckets={len(c.buckets)}"
                f" allreduce={c.allreduce_bytes}B gather={c.gather_bytes}B"
                f" exchange={c.exchange_bytes}B total={c.total_bytes}B")
            for names, nelem, wire in c.buckets:
                head = ", ".join(names[:3]) + (", ..." if len(names) > 3
                                               else "")
                lines.append(f"  bucket [{head}] nelem={nelem} wire={wire}B")
            for site, w, axes, b in c.gather_sites:
                lines.append(f"  gather @{site} weight={w} axes={axes} "
                             f"~{b}B")
            for site, w, n, b in c.exchange_sites:
                lines.append(f"  exchange @{site} table={w} n_local={n} "
                             f"~{b}B")
        if self.mem is not None:
            lines.append(self.mem.render())
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Individual checks (each appends Diagnostics to `out`)
# ---------------------------------------------------------------------------

def _axis_names_of(spec) -> List[str]:
    """Flatten a PartitionSpec-like tuple into its axis-name strings."""
    out = []
    for a in (spec or ()):
        if a is None:
            continue
        for x in (a if isinstance(a, (tuple, list)) else (a,)):
            if isinstance(x, str):
                out.append(x)
    return out


def _check_mesh_axes(plan, mesh, out: List[Diagnostic]):
    from ..parallel.mesh import _CANONICAL_ORDER
    from .registry import suggest_names

    referenced: List[Tuple[str, str]] = []      # (axis, where)
    for a in plan.batch_axes:
        referenced.append((a, "batch_axes"))
    if plan.seq_axis is not None:
        referenced.append((plan.seq_axis, "seq_axis"))
    if plan.annotations:
        for name, spec in plan.annotations.items():
            for a in _axis_names_of(spec):
                referenced.append((a, f"annotations[{name!r}]"))
    if plan.rules is not None:
        for pat, axes in plan.rules.rules:
            for a in _axis_names_of(axes):
                referenced.append((a, f"rules[{pat.pattern!r}]"))
    valid = set(mesh.axis_names) | set(_CANONICAL_ORDER)
    seen = set()
    for axis, where in referenced:
        if axis in valid or (axis, where) in seen:
            continue
        seen.add((axis, where))
        suggestion = suggest_names(
            axis, candidates=list(mesh.axis_names) + list(_CANONICAL_ORDER))
        out.append(Diagnostic(
            "SC002", "error",
            f"{where} references mesh axis {axis!r} which is neither in "
            f"the mesh {tuple(mesh.axis_names)} nor a canonical axis — "
            "_clean_spec would silently drop it (replication)",
            var=axis, hint=suggestion or
            f"valid axes: {sorted(valid)}"))


def _check_feeds(program, plan, mesh, feed_shapes, bucket_edges,
                 out: List[Diagnostic]):
    n = plan.batch_divisor(mesh)
    shapes = dict(feed_shapes or {})
    if not shapes:
        for v in program.list_vars():
            if v.is_data and tuple(v.shape):
                shapes[v.name] = tuple(v.shape)
    for name, shape in shapes.items():
        shape = tuple(shape)
        if not shape:
            continue
        b = shape[0]
        if n > 1 and isinstance(b, (int, np.integer)) and b > 1 and b % n:
            out.append(Diagnostic(
                "SC001", "error",
                f"feed {name!r} batch dim {int(b)} does not divide the "
                f"plan's {n} batch-axis devices "
                f"(batch_axes={plan.batch_axes}) — feed_sharding raises "
                "at placement time",
                var=name,
                hint=f"pad the batch to a multiple of {n} or shrink the "
                     "mesh"))
        if (plan.seq_axis is not None and plan.seq_axis in mesh.axis_names
                and len(shape) > 1):
            s = shape[1]
            sz = mesh.shape[plan.seq_axis]
            if isinstance(s, (int, np.integer)) and s > 1 and s % sz:
                out.append(Diagnostic(
                    "SC001", "warning",
                    f"feed {name!r} seq dim {int(s)} does not divide "
                    f"seq_axis {plan.seq_axis!r} ({sz} devices) — the "
                    "plan silently skips sequence sharding for it",
                    var=name,
                    hint=f"pad the sequence to a multiple of {sz}"))
    if bucket_edges and n > 1:
        bad = [int(e) for e in bucket_edges if int(e) > 1 and int(e) % n]
        if bad:
            out.append(Diagnostic(
                "SC001", "error",
                f"serving bucket edges {bad} do not divide the plan's {n} "
                "batch-axis devices — every padded batch hits the "
                "feed_sharding error at first submit",
                hint=f"use bucket edges that are multiples of {n}"))


def _state_vars(program) -> List[Tuple[str, Tuple[int, ...], Any, bool]]:
    """(name, concrete-shape-or-(), dtype, trainable) per persistable var."""
    out = []
    for v in program.list_vars():
        if not (v.persistable or isinstance(v, Parameter)):
            continue
        if v.name.endswith(GRAD_SUFFIX):
            continue
        shape = tuple(v.shape)
        if any(not isinstance(d, (int, np.integer)) or d < 0 for d in shape):
            shape = ()
        out.append((v.name, shape, np.dtype(v.dtype),
                    bool(getattr(v, "trainable", False))))
    return out


def _check_state_placement(program, plan, mesh, out: List[Diagnostic]):
    from ..parallel.sharding import PartitionSpec, _clean_spec, _divisible

    from .registry import suggest_names

    all_names = {v.name for v in program.list_vars()}
    for name in (plan.annotations or {}):
        if name not in all_names:
            suggestion = suggest_names(name, candidates=sorted(all_names))
            out.append(Diagnostic(
                "SC003", "warning",
                f"annotation names {name!r}, which is not a variable of "
                "the program — the placement silently never applies",
                var=name, hint=suggestion or "check the variable name"))

    batch_axes = set(plan.batch_axes)
    for name, shape, _dtype, _tr in _state_vars(program):
        ann = (plan.annotations or {}).get(name)
        rule = (plan.rules.match(name, len(shape))
                if plan.rules is not None and shape else None)
        if ann is not None and shape:
            if len(ann) > len(shape):
                out.append(Diagnostic(
                    "SC003", "error",
                    f"annotation for {name!r} has {len(ann)} entries but "
                    f"the variable is rank {len(shape)} ({shape})",
                    var=name,
                    hint="a PartitionSpec may be shorter than the rank, "
                         "never longer"))
                continue
            spec = _clean_spec(ann, mesh)
            if tuple(spec) and not _divisible(shape, spec, mesh):
                out.append(Diagnostic(
                    "SC003", "error",
                    f"annotation {tuple(ann)} for {name!r} does not divide "
                    f"its shape {shape} on mesh "
                    f"{dict(mesh.shape)} — infer_sharding silently falls "
                    "back to full replication",
                    var=name,
                    hint="resize the dim to a multiple of the axis size or "
                         "drop the annotation"))
            if rule is not None and tuple(rule) != tuple(ann):
                out.append(Diagnostic(
                    "SC003", "warning",
                    f"{name!r} matches both an annotation {tuple(ann)} and "
                    f"a rule {tuple(rule)}; the annotation wins",
                    var=name, hint="drop one of the two placements"))
        elif rule is not None and shape:
            spec = _clean_spec(rule, mesh)
            if tuple(spec) and not _divisible(shape, spec, mesh):
                out.append(Diagnostic(
                    "SC003", "warning",
                    f"rule spec {tuple(rule)} matches {name!r} but does "
                    f"not divide its shape {shape} — it silently "
                    "replicates",
                    var=name,
                    hint="tighten the rule regex or resize the dim"))
        # SC008: ZeRO vs explicit dp-axis placement
        if plan.zero_stage > 0:
            placed = ann if ann is not None else rule
            dp_used = sorted(set(_axis_names_of(placed)) & batch_axes)
            if dp_used:
                out.append(Diagnostic(
                    "SC008", "error",
                    f"zero_stage={plan.zero_stage} shards state over the "
                    f"batch axes, but {name!r} is explicitly placed on "
                    f"{dp_used} by an "
                    f"{'annotation' if ann is not None else 'rule'} — the "
                    "two placements fight over the same axis",
                    var=name,
                    hint="use a non-batch axis (e.g. 'tp') for explicit "
                         "placement, or drop zero_stage"))
            elif (plan.zero_stage >= 3 and placed is None and shape):
                n = plan.batch_divisor(mesh)
                if n > 1 and not any(
                        d % n == 0 and d >= n for d in shape):
                    out.append(Diagnostic(
                        "SC008", "warning",
                        f"zero_stage=3: no dim of {name!r} {shape} divides "
                        f"the {n}-way batch axes — zero_spec silently "
                        "keeps it fully replicated",
                        var=name,
                        hint="pad the largest dim to a multiple of "
                             f"{n} to actually shard it"))


def _check_donation(program, plan, feed_shapes, out: List[Diagnostic]):
    if not plan.donate:
        return
    fed = set(feed_shapes or ())
    for v in program.list_vars():
        persistable = v.persistable or isinstance(v, Parameter)
        if persistable and v.is_data:
            out.append(Diagnostic(
                "SC004", "error",
                f"{v.name!r} is both a data (feed) var and persistable "
                "state under a donating plan — the donated buffer would "
                "alias the caller's feed array",
                var=v.name,
                hint="split the feed var from the state var, or build the "
                     "plan with donate=False"))
        elif persistable and v.name in fed:
            out.append(Diagnostic(
                "SC004", "warning",
                f"feed {v.name!r} names persistable state under a "
                "donating plan — the executor skips the aliased donation "
                "at runtime, but feeding state is usually a bug",
                var=v.name,
                hint="initialize state through the startup program "
                     "instead of feeding it"))


def _check_comm_quantize(program, plan, mesh, out: List[Diagnostic]):
    from ..parallel.compress import (COMPRESS_KINDS, _payload_dtype,
                                     bucket_assignment)
    from .registry import suggest_names

    comm = plan.comm
    if comm is None:
        return
    kind = comm.quantize
    if kind not in ("", "none") and kind not in COMPRESS_KINDS:
        suggestion = suggest_names(
            kind, candidates=list(COMPRESS_KINDS) + ["none"])
        out.append(Diagnostic(
            "SC005", "error",
            f"comm_quantize={kind!r} is not a known kind — CommOptions "
            "silently treats it as no compression",
            hint=suggestion or f"use one of {COMPRESS_KINDS} or 'none'"))
        return
    if kind == "fp8":
        try:
            _payload_dtype("fp8")
        except NotImplementedError as e:
            out.append(Diagnostic(
                "SC005", "error",
                f"comm_quantize='fp8' is unavailable here: {e}",
                hint="use comm_quantize='int8' on this jax version"))
    if comm.block_size <= 0:
        out.append(Diagnostic(
            "SC005", "error",
            f"comm_block_size={comm.block_size} must be positive",
            hint="the block is the quantization scale granularity"))
    if comm.buffer_mb <= 0:
        out.append(Diagnostic(
            "SC005", "error",
            f"comm_buffer_mb={comm.buffer_mb} must be positive",
            hint="the buffer caps each gradient bucket"))
    if comm.payload() is None or comm.block_size <= 0 or comm.buffer_mb <= 0:
        return
    grads = _grad_leaves(program)
    for name, _nelem, dtype in grads:
        if dtype.kind != "f":
            out.append(Diagnostic(
                "SC005", "error",
                f"comm_quantize={kind!r} block-quantizes gradients, but "
                f"trainable param {name!r} is {dtype.name} — integer "
                "grads cannot take a float scale",
                var=name,
                hint="exclude the param from training or drop "
                     "comm_quantize"))
    sizes = [nelem * 4 for _n, nelem, _d in grads]
    for bucket in bucket_assignment(sizes, comm.buffer_mb):
        nelem = sum(sizes[i] for i in bucket) // 4
        if 0 < nelem < comm.block_size:
            names = [grads[i][0] for i in bucket]
            out.append(Diagnostic(
                "SC005", "warning",
                f"gradient bucket {names} has {nelem} elements — smaller "
                f"than one quantization block ({comm.block_size}); scale "
                "overhead dominates the wire savings",
                hint="raise comm_buffer_mb or lower comm_block_size"))


def _check_serving_buckets(program, feed_names, bucket_edges,
                           out: List[Diagnostic]):
    edges = [int(e) for e in (bucket_edges or ())]
    if not edges:
        return
    if sorted(edges) != edges or any(e <= 0 for e in edges) \
            or len(set(edges)) != len(edges):
        out.append(Diagnostic(
            "SC007", "error",
            f"bucket_edges {edges} must be strictly increasing positive "
            "ints",
            hint="e.g. (1, 2, 4, 8, 16, 32)"))
        return
    data_vars = {v.name: v for v in program.list_vars() if v.is_data}
    for name in (feed_names or ()):
        v = data_vars.get(name)
        if v is None:
            out.append(Diagnostic(
                "SC007", "error",
                f"tenant feed {name!r} is not a data var of the program — "
                "every submit would fail feed-name validation",
                var=name,
                hint=f"data vars: {sorted(data_vars)}"))
            continue
        shape = tuple(v.shape)
        if shape and isinstance(shape[0], (int, np.integer)) \
                and shape[0] > edges[-1]:
            out.append(Diagnostic(
                "SC007", "error",
                f"feed {name!r} declares batch dim {int(shape[0])}, larger "
                f"than the largest bucket ({edges[-1]}) — every submit "
                "would be rejected at batch time",
                var=name,
                hint="declare the batch dim -1 or extend bucket_edges"))


def _effective_spec(plan, mesh, name, shape):
    """Mirror infer_sharding's precedence (annotation > rule > ZeRO) for a
    declared shape, including the silent indivisible→replicate fallback."""
    from ..parallel.sharding import (PartitionSpec, _clean_spec, _divisible,
                                     zero_spec)

    spec = None
    if plan.annotations and plan.annotations.get(name) is not None:
        spec = _clean_spec(plan.annotations[name], mesh)
    if spec is None and plan.rules is not None:
        m = plan.rules.match(name, len(shape))
        if m is not None:
            spec = _clean_spec(m, mesh)
    if spec is not None and not _divisible(shape, spec, mesh):
        spec = None
    if spec is None or spec == PartitionSpec():
        spec = zero_spec(shape, mesh) if plan.zero_stage >= 3 \
            else PartitionSpec()
    return spec


def _check_contractions(program, plan, mesh, out: List[Diagnostic],
                        est: CommEstimate):
    """SC009: weights sharded on a contracted dim → predicted collective."""
    state = {name: (shape, dtype)
             for name, shape, dtype, _tr in _state_vars(program) if shape}
    for block in program.blocks:
        for op_idx, op in enumerate(block.ops):
            site = _CONTRACTION_OPS.get(op.type)
            if site is None:
                continue
            slot, contracted_of = site
            names = op.inputs.get(slot, ())
            if not names or names[0] not in state:
                continue
            wname = names[0]
            shape, dtype = state[wname]
            spec = _effective_spec(plan, mesh, wname, shape)
            spec_t = tuple(spec)
            contracted = contracted_of(len(shape), op.attrs)
            for dim in contracted:
                if not 0 <= dim < len(spec_t) or spec_t[dim] is None:
                    continue
                axes = tuple(a for a in (
                    spec_t[dim] if isinstance(spec_t[dim], tuple)
                    else (spec_t[dim],)) if a is not None)
                n = 1
                for a in axes:
                    n *= mesh.shape[a]
                if n <= 1:
                    continue
                nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
                coll = int(round(nbytes * (n - 1) / n))
                loc = f"{op.type}.b{block.idx}.i{op_idx}"
                est.gather_sites.append((loc, wname, axes, coll))
                est.gather_bytes += coll
                out.append(Diagnostic(
                    "SC009", "warning",
                    f"{op.type} at block {block.idx} op {op_idx} contracts "
                    f"dim {dim} of {wname!r}, which the plan shards over "
                    f"{axes} — GSPMD inserts an allreduce/all-gather "
                    f"(~{coll} wire bytes) at this site",
                    block.idx, op_idx, op.type, var=wname,
                    hint="intended for row-parallel layers; otherwise "
                         "shard the non-contracted dim"))


_LOOKUP_OPS = ("lookup_table", "lookup_table_v2", "embedding")
# below this vocab size a dense gradient is cheap enough not to nag about
_SC010_DENSE_VOCAB = 65536


def _check_embedding(program, plan, mesh, out: List[Diagnostic]):
    """SC010: vocab-sharded embedding tables (parallel/embedding.py) — an
    indivisible vocab dim raises inside shard_map at trace time, a table
    whose id batch shares the vocab axis double-shards, and a conflicting
    user annotation places the table somewhere the lookup lowering's
    exchange does not expect; an *uncovered* huge table without is_sparse
    silently pays the dense vocab-sized gradient (warning)."""
    state = {name: (shape, dtype)
             for name, shape, dtype, _tr in _state_vars(program) if shape}
    covered = getattr(plan, "embedding_shard", None) is not None
    for block in program.blocks:
        for op_idx, op in enumerate(block.ops):
            if op.type not in _LOOKUP_OPS:
                continue
            names = op.inputs.get("W", ())
            if not names or names[0] not in state:
                continue
            wname = names[0]
            shape, _dtype = state[wname]
            axis = (plan.embedding_axis_for(wname, lookup=True)
                    if covered else None)
            if axis is None:
                if (not op.attrs.get("is_sparse", False)
                        and shape[0] >= _SC010_DENSE_VOCAB):
                    out.append(Diagnostic(
                        "SC010", "warning",
                        f"{op.type} at block {block.idx} op {op_idx} reads "
                        f"table {wname!r} (vocab {shape[0]}) with neither "
                        "is_sparse nor an embedding_shard plan — the "
                        "backward materializes a dense vocab-sized gradient",
                        block.idx, op_idx, op.type, var=wname,
                        hint="set is_sparse=True or "
                             "ShardingPlan(embedding_shard=...)"))
                continue
            k = int(mesh.shape[axis]) if axis in mesh.axis_names else 1
            if k > 1 and shape[0] % k:
                out.append(Diagnostic(
                    "SC010", "error",
                    f"embedding table {wname!r} vocab {shape[0]} does not "
                    f"divide mesh axis {axis!r} size {k} — the sharded "
                    "lookup raises at trace time",
                    block.idx, op_idx, op.type, var=wname,
                    hint="pad the vocab to a multiple of the axis size"))
            if axis in plan.batch_axes:
                out.append(Diagnostic(
                    "SC010", "error",
                    f"embedding_shard axis {axis!r} for table {wname!r} is "
                    "also a plan batch axis — ids and vocab would shard "
                    "over the same devices and the exchange computes "
                    "garbage",
                    block.idx, op_idx, op.type, var=wname,
                    hint="vocab-shard over a model axis (tp), batch over "
                         "dp"))
            ann = (plan.annotations or {}).get(wname)
            if ann is not None:
                dim0 = ann[0] if len(ann) else None
                dim0_axes = tuple(
                    a for a in (dim0 if isinstance(dim0, (tuple, list))
                                else (dim0,)) if a is not None)
                if dim0_axes != (axis,):
                    out.append(Diagnostic(
                        "SC010", "error",
                        f"table {wname!r} is vocab-sharded over {axis!r} by "
                        f"embedding_shard but annotated {tuple(ann)!r} — "
                        "annotations win placement, so the lookup's "
                        f"all_to_all over {axis!r} would read a "
                        "differently-laid-out table",
                        block.idx, op_idx, op.type, var=wname,
                        hint="drop the annotation or align it to "
                             f"({axis!r}, None)"))


# ---------------------------------------------------------------------------
# Communication estimate
# ---------------------------------------------------------------------------

def _grad_leaves(program) -> List[Tuple[str, int, np.dtype]]:
    """(name, nelem, dtype) of every trainable param with a grad var, in
    allreduce issue order (reverse declaration order — backward produces
    the last layer's gradients first, matching compress._named_leaves)."""
    grad_names = {n for b in program.blocks for n in b.vars
                  if n.endswith(GRAD_SUFFIX)}
    leaves = []
    for p in program.all_parameters():
        if not p.trainable or p.name + GRAD_SUFFIX not in grad_names:
            continue
        shape = tuple(p.shape)
        if any(not isinstance(d, (int, np.integer)) or d < 0 for d in shape):
            continue
        leaves.append((p.name, int(np.prod(shape, dtype=np.int64)) if shape
                       else 1, np.dtype(p.dtype)))
    return list(reversed(leaves))


def measured_comm_bytes(axis: Optional[str] = None) -> float:
    """Cumulative sum of the ``comm.allreduce_bytes`` histogram (wire bytes
    recorded when a step is *traced*, compress._record_comm) — the shared
    snapshot/delta primitive behind ``CommEstimate.measured_bytes`` and the
    calibration ledger's per-compile comm attribution (utils/ledger.py
    snapshots it before a compile and charges the delta to that trace)."""
    hist = _monitor.histogram(
        "comm.allreduce_bytes", "wire bytes per allreduce",
        labelnames=("axis", "dtype"),
        buckets=(1 << 10, 1 << 14, 1 << 18, 1 << 22, 1 << 26, 1 << 30))
    total = 0.0
    for labels, stat in hist.samples():
        if axis is None or labels.get("axis") == axis:
            total += stat["sum"]
    return total


def _estimate_exchange(program, plan, mesh, feed_shapes,
                       est: CommEstimate) -> None:
    """Price the vocab-sharded embedding all_to_all exchange per lookup
    site with the exact math ``embedding.exchange_bytes`` observes at trace
    time (``emb.exchange_bytes`` histogram): dedup capacity x row bytes x
    quantize ratio, for the batch-local id count.  Sites whose id batch is
    unknowable statically (no feed shape and a dynamic declared shape) are
    skipped — underpricing honestly beats inventing a batch."""
    if getattr(plan, "embedding_shard", None) is None:
        return
    from ..parallel.embedding import exchange_bytes as _exchange_bytes

    shapes = dict(feed_shapes or {})
    state = {name: shape
             for name, shape, _dtype, _tr in _state_vars(program) if shape}
    dp = plan.batch_divisor(mesh)
    for block in program.blocks:
        for op_idx, op in enumerate(block.ops):
            if op.type not in _LOOKUP_OPS:
                continue
            wnames = op.inputs.get("W", ())
            inames = op.inputs.get("Ids", ())
            if not wnames or not inames or wnames[0] not in state:
                continue
            wname = wnames[0]
            wshape = state[wname]
            if len(wshape) < 2:
                continue
            axis = plan.embedding_axis_for(wname, lookup=True)
            if axis is None or axis not in mesh.axis_names:
                continue
            k = int(mesh.shape[axis])
            if k <= 1 or wshape[0] % k or axis in plan.batch_axes:
                continue               # degenerate/SC010-invalid: no exchange
            ishape = shapes.get(inames[0])
            if ishape is None:
                v = block.vars.get(inames[0])
                ishape = tuple(getattr(v, "shape", ()) or ()) if v else ()
            ishape = tuple(ishape or ())
            if not ishape or any(not isinstance(d, (int, np.integer)) or d < 0
                                 for d in ishape):
                continue
            # lower_lookup flattens ids before the exchange; the id batch is
            # dp-sharded when it divides (sharded_lookup's fallback rule)
            n_global = int(np.prod(ishape, dtype=np.int64))
            n_local = n_global // dp if dp > 1 and n_global % dp == 0 \
                else n_global
            wire = int(_exchange_bytes(
                n_local, int(wshape[1]), k,
                getattr(plan, "embedding_capacity", None),
                getattr(plan, "embedding_quantize", "") or None))
            est.exchange_sites.append(
                (f"block {block.idx} op {op_idx}", wname, n_local, wire))
            est.exchange_bytes += wire


def estimate_comm(program: Program, plan, mesh=None,
                  feed_shapes=None) -> CommEstimate:
    """Static per-bucket allreduce wire-byte estimate for the plan's
    data-parallel gradient sync — same bucketing and wire math as
    ``compress.sync_gradients`` (bucket_assignment + wire_bytes), so on the
    fleet/collbench path the estimate matches the traced
    ``comm.allreduce_bytes`` records — plus the per-site vocab-sharded
    embedding exchange bytes (mirroring the traced ``emb.exchange_bytes``)
    so recommender plans score their dominant collective honestly."""
    from ..parallel.compress import bucket_assignment, wire_bytes

    mesh = mesh or plan.resolve_mesh()
    world = plan.batch_divisor(mesh)
    comm = plan.comm
    payload = comm.payload() if comm is not None else None
    block_size = comm.block_size if comm is not None else 256
    if block_size <= 0:               # SC005 already flagged it; keep going
        block_size = 256
    buffer_mb = comm.buffer_mb if comm is not None else 25.0
    est = CommEstimate(world=world, payload=payload, block_size=block_size,
                       buffer_mb=max(buffer_mb, 1e-9))
    _estimate_exchange(program, plan, mesh, feed_shapes, est)
    leaves = _grad_leaves(program)
    if not leaves:
        return est
    sizes = [nelem * 4 for _n, nelem, _d in leaves]
    for bucket in bucket_assignment(sizes, est.buffer_mb):
        names = tuple(leaves[i][0] for i in bucket)
        nelem = sum(leaves[i][1] for i in bucket)
        wire = wire_bytes(nelem, payload, block_size, n=world)
        est.buckets.append((names, nelem, wire))
        est.allreduce_bytes += wire
    return est


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def verify_plan(program: Program, plan,
                feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
                bucket_edges: Optional[Sequence[int]] = None,
                feed_names: Optional[Sequence[str]] = None) -> PlanReport:
    """Run every SC check for `program` under `plan`; returns the full
    report (diagnostics + communication estimate).  ``feed_shapes`` narrows
    the feed assumption to concrete arrays (the Executor passes the real
    batch); ``bucket_edges``/``feed_names`` enable the serving checks."""
    _m_plans_checked.inc()
    mesh = plan.resolve_mesh()
    out: List[Diagnostic] = []
    _check_mesh_axes(plan, mesh, out)
    _check_feeds(program, plan, mesh, feed_shapes, bucket_edges, out)
    _check_state_placement(program, plan, mesh, out)
    _check_donation(program, plan, feed_shapes, out)
    _check_comm_quantize(program, plan, mesh, out)
    _check_serving_buckets(program, feed_names, bucket_edges, out)
    # SC006 rides the analysis engine's sub-block findings: declared shapes
    # often agree (the builder checked them) while inferred ones clash
    _diags, engine = infer_program(program, feed_names=feed_names or (
        None if feed_shapes is None else set(feed_shapes)))
    out.extend(engine.subblock_findings)
    est = estimate_comm(program, plan, mesh, feed_shapes=feed_shapes)
    _check_contractions(program, plan, mesh, out, est)
    _check_embedding(program, plan, mesh, out)
    # the memory dimension (static/memcheck.py): the same call that prices
    # the plan in bytes moved prices it in bytes resident.  Findings stay
    # out of this report (the Executor's check_memory hook owns MC
    # enforcement) — here the estimate is the deliverable, the HBM leg of
    # the auto-sharding scorer next to `comm`.  Deferred import: memcheck
    # builds on this module.
    mem = None
    try:
        from .memcheck import estimate_peak

        mem = estimate_peak(program, plan, feed_shapes)
    except Exception:      # pragma: no cover - defensive
        pass               # a sizing failure must never mask SC findings
    return PlanReport(diagnostics=out, comm=est, mem=mem)


def check_plan(program: Program, plan,
               feed_shapes: Optional[Dict[str, Sequence[int]]] = None,
               bucket_edges: Optional[Sequence[int]] = None,
               feed_names: Optional[Sequence[str]] = None) -> PlanReport:
    """verify_plan + raise ``ProgramVerificationError`` on any
    error-severity finding."""
    report = verify_plan(program, plan, feed_shapes, bucket_edges,
                         feed_names)
    errs = report.errors
    if errs:
        raise _errors.ProgramVerificationError(
            "sharding-plan verification failed (set "
            "PDTPU_FLAGS_check_sharding=0 to bypass):\n"
            + _errors.render_diagnostics(errs), diagnostics=errs)
    return report


_memo_lock = threading.Lock()
_MEMO: Dict[tuple, PlanReport] = {}
_MEMO_CAP = 4096


def check_with_plan(program: Program, plan,
                    feed_arrays: Optional[Dict[str, Any]] = None
                    ) -> PlanReport:
    """Executor entry point: ``check_plan`` memoized by (plan token,
    program version, feed-shape signature).  The plan token is monotonic
    per ShardingPlan instance and the version bumps on any program
    mutation, so a hit is exact; steady-state (hot-cache) steps never even
    reach here — this runs only in the trace/compile branch."""
    feed_shapes = None
    if feed_arrays is not None:
        feed_shapes = {k: tuple(int(d) for d in np.shape(v))
                       for k, v in feed_arrays.items()}
    sig = None if feed_shapes is None else tuple(sorted(feed_shapes.items()))
    key = (plan.token, program._version, sig)
    with _memo_lock:
        hit = _MEMO.get(key)
    if hit is not None:
        return hit
    report = check_plan(program, plan, feed_shapes=feed_shapes)
    with _memo_lock:
        if len(_MEMO) >= _MEMO_CAP:
            _MEMO.clear()
        _MEMO[key] = report
    return report
