"""Lowerings for the fused ops emitted by the graph-rewrite passes.

Reference parity: the `framework/ir` fusion passes materialize fused op
types (conv_bn_fuse_pass -> conv2d with folded weights, fc_fuse_pass ->
`fc`, conv_elementwise_add_act_fuse_pass -> `conv2d_fusion`).  Here the
pass manager (static/passes.py) rewrites op *patterns* into these two op
types; their lowerings fold at trace time, so XLA sees one region:

- ``fused_conv2d_bn_act``: conv2d -> batch_norm -> act collapsed into one
  op.  Inference mode has two executions of the same math: when the
  Pallas gate holds (NHWC, lane-aligned channels, TPU backend — see
  ops/pallas/conv_fused.py) the conv runs as a Pallas kernel with the
  per-channel BN transform ``a·x + b`` fused as an epilogue on its output
  tiles; otherwise BN is folded INTO THE FILTER (``w' = w * a`` per
  output channel, ``b' = conv_bias * a + b`` — the r05 weight-space fold)
  and XLA runs one unfused conv.  Training mode (is_test=False) keeps
  XLA's conv and fuses the BN-stats reduction + scale/shift + activation
  via nn.functional.norm.batch_norm_act (Pallas when gated, jnp
  otherwise), emitting MeanOut/VarianceOut running-stat updates like the
  unfused batch_norm op — this is what lets fuse_conv_bn_act fire inside
  programs with a backward_region.
- ``fused_matmul_bias_act``: mul -> elementwise_add(1-D bias) -> act (the
  `fc`/transformer-MLP pattern, gelu included) as one op.
- ``quant_conv2d`` / ``quant_mul``: the int8 inference ops minted by the
  quant_infer pass from PTQ artifacts (weight_scale attrs + fixed-scale
  activation quant ops).  Flag-on they run the ops/pallas/int8 kernels
  (int8 MXU dots, int32 accumulate, fp32 per-channel dequant epilogue);
  flag-off or unsupported they run the *simulate* fallback — quantize +
  dequantize + fp32 op — which is bitwise the pre-rewrite fake-quant
  graph, so parity tests can pin the rewrite exactly.

The float lowerings reproduce the unfused op chain's math (same primitive
sequence modulo the weight-space refactor), so golden parity holds bitwise
for ints and within float tolerance for the BN fold; the int8 kernels hold
parity to calibrated tolerance (int32 accumulation vs fp32 rounding).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn.functional.norm import batch_norm_act, bn_inference_scale_bias
from .registry import get_lowering, register_op
from .ops import _one


def _pair(v):
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1])) if len(v) >= 2 \
            else (int(v[0]), int(v[0]))
    return (int(v), int(v))

# Activations a fusion pattern may absorb: value-wise, attr-free in the
# emitted-by-layers form, with a registered X->Out lowering.
FUSABLE_ACTS = frozenset({
    "relu", "gelu", "sigmoid", "tanh", "relu6", "silu", "swish",
    "leaky_relu", "hard_swish", "softplus", "mish", "elu",
})


def _apply_act(out, act, attrs, op):
    if not act:
        return out
    return get_lowering(act)({"X": [out]}, attrs, op)["Out"][0]


def _use_pallas_conv(x, w, stride, padding, dilation, groups, act,
                     data_format) -> bool:
    """Gate for the fused conv+BN+act epilogue kernel (flag + TPU backend
    via ops.pallas.config — tests patch `config.backend_is_tpu` — plus the
    kernel's own shape gates).  String paddings (SAME/VALID) stay on XLA."""
    if not (isinstance(padding, tuple) and data_format == "NHWC"):
        return False
    from ..ops.pallas import config as _pcfg

    if not _pcfg.kernel_enabled("use_pallas_conv_fused"):
        return False
    from ..ops.pallas import conv_fused as _cf

    return _cf.supported(x, w.shape, stride, padding, dilation, groups, act,
                         data_format)


@register_op("fused_conv2d_bn_act")
def _fused_conv2d_bn_act(ins, attrs, op):
    x = _one(ins, "Input")
    w = _one(ins, "Filter")
    conv_bias = _one(ins, "Bias")
    act = attrs.get("act", "")
    data_format = attrs.get("data_format", "NCHW")
    stride = _pair(attrs.get("strides", 1))
    dilation = _pair(attrs.get("dilations", 1))
    groups = attrs.get("groups", 1)
    raw_padding = attrs.get("paddings", 0)
    padding = raw_padding if isinstance(raw_padding, str) \
        else _pair(raw_padding)

    if not attrs.get("is_test", True):
        # training mode: XLA's conv + fused BN-stats/scale-shift/act with
        # running-stat outputs (differentiable — safe under backward_region)
        out = F.conv2d(x, w, bias=conv_bias, stride=stride,
                       padding=raw_padding, dilation=dilation, groups=groups,
                       data_format=data_format)
        y, new_rm, new_rv = batch_norm_act(
            out, _one(ins, "Mean"), _one(ins, "Variance"),
            weight=_one(ins, "Scale"), bias=_one(ins, "BnBias"),
            momentum=attrs.get("momentum", 0.9),
            epsilon=attrs.get("epsilon", 1e-5), act=act,
            data_format=data_format)
        return {"Output": [y], "MeanOut": [new_rm], "VarianceOut": [new_rv]}

    a, b = bn_inference_scale_bias(
        _one(ins, "Mean"), _one(ins, "Variance"),
        _one(ins, "Scale"), _one(ins, "BnBias"),
        attrs.get("epsilon", 1e-5))
    if conv_bias is not None:
        b = b + conv_bias.astype(jnp.float32) * a

    if _use_pallas_conv(x, w, stride, padding, dilation, groups, act,
                        data_format):
        from ..ops.pallas import conv_fused as _cf

        out = _cf.conv2d_bn_act(x, w, a, b, stride=stride, padding=padding,
                                act=act)
        return {"Output": [out]}

    # weight-space fold: scale each OUTPUT channel's filter (OIHW axis 0)
    w = w * a.astype(w.dtype).reshape(-1, 1, 1, 1)
    out = F.conv2d(x, w, bias=b.astype(x.dtype),
                   stride=stride, padding=raw_padding, dilation=dilation,
                   groups=groups, data_format=data_format)
    return {"Output": [_apply_act(out, act, attrs, op)]}


def _qmax(bits: int) -> float:
    return float(2 ** (int(bits) - 1) - 1)


def _quantize_int8(x, scale, qmax):
    """Symmetric zero-point quantization matching the
    fake_quantize_dequantize_fixed_scale lowering's rounding exactly:
    ``round(clip(x/scale, -1, 1) * qmax)`` as int8."""
    return jnp.round(jnp.clip(x / scale, -1.0, 1.0) * qmax).astype(jnp.int8)


def _simulate_qdq(x, in_scale, in_bits, op):
    """The bitwise flag-off path: replay the exact fixed-scale fake-quant
    lowering the quant_infer pass removed (NOT a reimplementation — the
    STE form ``x + stop_gradient(q - x)`` must match to the last ulp)."""
    return get_lowering("fake_quantize_dequantize_fixed_scale")(
        {"X": [x]}, {"bit_length": in_bits, "scale": in_scale}, op)["Out"][0]


@register_op("quant_conv2d")
def _quant_conv2d(ins, attrs, op):
    x = _one(ins, "Input")
    w = _one(ins, "Filter")
    bias = _one(ins, "Bias")
    act = attrs.get("act", "")
    data_format = attrs.get("data_format", "NCHW")
    stride = _pair(attrs.get("strides", 1))
    dilation = _pair(attrs.get("dilations", 1))
    groups = attrs.get("groups", 1)
    raw_padding = attrs.get("paddings", 0)
    padding = raw_padding if isinstance(raw_padding, str) \
        else _pair(raw_padding)
    in_scale = float(attrs["in_scale"])
    in_bits = int(attrs.get("in_bits", 8))
    w_scale = jnp.asarray(attrs["weight_scale"], jnp.float32)   # (O,)
    w_bits = int(attrs.get("weight_bits", 8))

    use_pallas = False
    if isinstance(padding, tuple) and data_format == "NHWC" \
            and w_scale.shape[0] == w.shape[0]:
        from ..ops.pallas import config as _pcfg

        if _pcfg.kernel_enabled("use_pallas_int8"):
            from ..ops.pallas import int8 as _int8

            use_pallas = _int8.conv_supported(
                jax.ShapeDtypeStruct(x.shape, jnp.int8), w.shape, stride,
                padding, dilation, groups, act, data_format)
    if use_pallas:
        from ..ops.pallas import int8 as _int8

        qm_in, qm_w = _qmax(in_bits), _qmax(w_bits)
        x_q = _quantize_int8(x, in_scale, qm_in)
        # the weight in scope is already int8-SIMULATED (q/qmax*scale, q
        # integral — the freeze/PTQ pass wrote it), so dividing by the
        # step recovers the exact int8 grid point
        step_w = w_scale / qm_w
        w_q = jnp.round(w / step_w.reshape(-1, 1, 1, 1)).astype(jnp.int8)
        out = _int8.int8_conv2d_dequant(
            x_q, w_q, (in_scale / qm_in) * step_w, bias=bias,
            stride=stride, padding=padding, act=act, out_dtype=x.dtype)
        return {"Output": [out]}

    # simulate fallback: bitwise the pre-rewrite fake-quant graph
    xq = _simulate_qdq(x, in_scale, in_bits, op)
    out = F.conv2d(xq, w, bias=bias, stride=stride, padding=raw_padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    return {"Output": [_apply_act(out, act, attrs, op)]}


@register_op("quant_mul")
def _quant_mul(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Y")
    act = attrs.get("act", "")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    in_scale = float(attrs["in_scale"])
    in_bits = int(attrs.get("in_bits", 8))
    w_scale = jnp.asarray(attrs["weight_scale"], jnp.float32)   # (out,)
    w_bits = int(attrs.get("weight_bits", 8))
    x2_shape = (int(np.prod(xs[:xd])), int(np.prod(xs[xd:])))
    y2_shape = (int(np.prod(ys[:yd])), int(np.prod(ys[yd:])))

    use_pallas = False
    # per-channel scales only line up with the flattened output dim when
    # the weight's quant axis IS the flattened minor axis
    if w_scale.shape[0] == y2_shape[1]:
        from ..ops.pallas import config as _pcfg

        if _pcfg.kernel_enabled("use_pallas_int8"):
            from ..ops.pallas import int8 as _int8

            use_pallas = _int8.matmul_supported(
                jax.ShapeDtypeStruct(x2_shape, jnp.int8), y2_shape, act)
    if use_pallas:
        from ..ops.pallas import int8 as _int8

        qm_in, qm_w = _qmax(in_bits), _qmax(w_bits)
        x_q = _quantize_int8(x.reshape(x2_shape), in_scale, qm_in)
        step_w = w_scale / qm_w
        w_q = jnp.round(y.reshape(y2_shape) / step_w[None, :]) \
            .astype(jnp.int8)
        out2 = _int8.int8_matmul_dequant(
            x_q, w_q, (in_scale / qm_in) * step_w, act=act,
            out_dtype=x.dtype)
        return {"Out": [out2.reshape(xs[:xd] + ys[yd:])]}

    xq = _simulate_qdq(x, in_scale, in_bits, op)
    out = (xq.reshape(x2_shape) @ y.reshape(y2_shape)) \
        .reshape(xs[:xd] + ys[yd:])
    return {"Out": [_apply_act(out, act, attrs, op)]}


@register_op("fused_matmul_bias_act")
def _fused_matmul_bias_act(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Y")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    # identical math to the mul lowering (ops.py _mul)
    x2 = x.reshape(int(np.prod(xs[:xd])), int(np.prod(xs[xd:])))
    y2 = y.reshape(int(np.prod(ys[:yd])), int(np.prod(ys[yd:])))
    out = (x2 @ y2).reshape(xs[:xd] + ys[yd:])
    bias = _one(ins, "Bias")
    if bias is not None:
        out = out + bias          # 1-D bias broadcasts on the last axis
    return {"Out": [_apply_act(out, attrs.get("act", ""), attrs, op)]}
