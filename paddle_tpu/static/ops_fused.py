"""Lowerings for the fused ops emitted by the graph-rewrite passes.

Reference parity: the `framework/ir` fusion passes materialize fused op
types (conv_bn_fuse_pass -> conv2d with folded weights, fc_fuse_pass ->
`fc`, conv_elementwise_add_act_fuse_pass -> `conv2d_fusion`).  Here the
pass manager (static/passes.py) rewrites op *patterns* into these two op
types; their lowerings fold at trace time, so XLA sees one region:

- ``fused_conv2d_bn_act``: conv2d -> batch_norm(is_test) -> act collapsed
  into one conv with BN folded INTO THE FILTER (``w' = w * a`` per output
  channel, ``b' = conv_bias * a + b``) — the r05 per-activation a·x+b
  hand-fold (nn/functional/norm.py bn_inference_scale_bias) promoted to a
  weight-space fold: the scale multiplies O(C·k·k) filter values once
  instead of riding every activation.
- ``fused_matmul_bias_act``: mul -> elementwise_add(1-D bias) -> act (the
  `fc`/transformer-MLP pattern, gelu included) as one op.

Both lowerings reproduce the unfused op chain's math (same primitive
sequence modulo the weight-space refactor), so golden parity holds bitwise
for ints and within float tolerance for the BN fold.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn.functional.norm import bn_inference_scale_bias
from .registry import get_lowering, register_op
from .ops import _one

# Activations a fusion pattern may absorb: value-wise, attr-free in the
# emitted-by-layers form, with a registered X->Out lowering.
FUSABLE_ACTS = frozenset({
    "relu", "gelu", "sigmoid", "tanh", "relu6", "silu", "swish",
    "leaky_relu", "hard_swish", "softplus", "mish", "elu",
})


def _apply_act(out, act, attrs, op):
    if not act:
        return out
    return get_lowering(act)({"X": [out]}, attrs, op)["Out"][0]


@register_op("fused_conv2d_bn_act")
def _fused_conv2d_bn_act(ins, attrs, op):
    x = _one(ins, "Input")
    w = _one(ins, "Filter")
    conv_bias = _one(ins, "Bias")
    a, b = bn_inference_scale_bias(
        _one(ins, "Mean"), _one(ins, "Variance"),
        _one(ins, "Scale"), _one(ins, "BnBias"),
        attrs.get("epsilon", 1e-5))
    # weight-space fold: scale each OUTPUT channel's filter (OIHW axis 0)
    w = w * a.astype(w.dtype).reshape(-1, 1, 1, 1)
    if conv_bias is not None:
        b = b + conv_bias.astype(jnp.float32) * a
    out = F.conv2d(x, w, bias=b.astype(x.dtype),
                   stride=attrs.get("strides", 1),
                   padding=attrs.get("paddings", 0),
                   dilation=attrs.get("dilations", 1),
                   groups=attrs.get("groups", 1),
                   data_format=attrs.get("data_format", "NCHW"))
    return {"Output": [_apply_act(out, attrs.get("act", ""), attrs, op)]}


@register_op("fused_matmul_bias_act")
def _fused_matmul_bias_act(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Y")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    # identical math to the mul lowering (ops.py _mul)
    x2 = x.reshape(int(np.prod(xs[:xd])), int(np.prod(xs[xd:])))
    y2 = y.reshape(int(np.prod(ys[:yd])), int(np.prod(ys[yd:])))
    out = (x2 @ y2).reshape(xs[:xd] + ys[yd:])
    bias = _one(ins, "Bias")
    if bias is not None:
        out = out + bias          # 1-D bias broadcasts on the last axis
    return {"Out": [_apply_act(out, attrs.get("act", ""), attrs, op)]}
