"""Lowering rules for the static-graph op set.

Reference parity: the operator library (paddle/fluid/operators/, SURVEY.md
N27 — 467 registered ops); this registers the working set the fluid layers
DSL emits (conv2d, pool2d, batch_norm, mul/fc, elementwise, softmax CE,
optimizer update ops, fill/random init ops...).  Each rule lowers to
jax/nn.functional calls under the Executor's trace — XLA does the kernel
work the reference's .cu files do.

Rule signature: fn(ins: {slot: [arrays]}, attrs: dict, op) -> {slot: [arrays]}.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod, random as _random
from ..nn import functional as F
from .registry import register_op


def _one(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


# -- creation / init ---------------------------------------------------------

@register_op("fill_constant")
def _fill_constant(ins, attrs, op):
    shape = tuple(attrs["shape"])
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.full(shape, attrs.get("value", 0.0), dtype)]}


@register_op("gaussian_random")
def _gaussian_random(ins, attrs, op):
    shape = tuple(attrs["shape"])
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        _random.next_key(), shape, dtype)
    return {"Out": [out]}


@register_op("uniform_random")
def _uniform_random(ins, attrs, op):
    shape = tuple(attrs["shape"])
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    out = jax.random.uniform(_random.next_key(), shape, dtype,
                             attrs.get("min", -1.0), attrs.get("max", 1.0))
    return {"Out": [out]}


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ins, attrs, op):
    shape = tuple(attrs["shape"])
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.truncated_normal(
        _random.next_key(), -2.0, 2.0, shape, dtype)
    return {"Out": [out]}


@register_op("assign")
def _assign(ins, attrs, op):
    return {"Out": [_one(ins, "X")]}


@register_op("cast")
def _cast(ins, attrs, op):
    return {"Out": [_one(ins, "X").astype(
        _dtype_mod.convert_dtype(attrs["out_dtype"]))]}


@register_op("scale")
def _scale(ins, attrs, op):
    x = _one(ins, "X")
    s, b = attrs.get("scale", 1.0), attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


# -- math --------------------------------------------------------------------

def _bcast_axis(x, y, axis):
    """Reference elementwise broadcasting: align y's dims starting at `axis`
    (operators/elementwise/elementwise_op_function.h semantics)."""
    if axis is None or axis == -1 or x.ndim == y.ndim:
        return y
    shape = [1] * x.ndim
    for i, s in enumerate(y.shape):
        shape[axis + i] = s
    return y.reshape(shape)


def _elementwise(fn):
    def rule(ins, attrs, op):
        x, y = _one(ins, "X"), _one(ins, "Y")
        y = _bcast_axis(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}

    return rule


for _name, _fn in [("elementwise_add", jnp.add), ("elementwise_sub", jnp.subtract),
                   ("elementwise_mul", jnp.multiply),
                   ("elementwise_div", jnp.divide),
                   ("elementwise_max", jnp.maximum),
                   ("elementwise_min", jnp.minimum),
                   ("elementwise_pow", jnp.power),
                   ("elementwise_mod", jnp.mod),
                   ("elementwise_floordiv", jnp.floor_divide)]:
    register_op(_name)(_elementwise(_fn))


@register_op("mul")
def _mul(ins, attrs, op):
    """ref mul_op: flatten x to 2-D at x_num_col_dims then matmul."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    xd = attrs.get("x_num_col_dims", 1)
    yd = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape(int(np.prod(xs[:xd])), int(np.prod(xs[xd:])))
    y2 = y.reshape(int(np.prod(ys[:yd])), int(np.prod(ys[yd:])))
    out = x2 @ y2
    return {"Out": [out.reshape(xs[:xd] + ys[yd:])]}


@register_op("matmul")
def _matmul(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Y")
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": [jnp.matmul(x, y) * attrs.get("alpha", 1.0)]}


for _name, _ufn in [("relu", jax.nn.relu), ("sigmoid", jax.nn.sigmoid),
                    ("tanh", jnp.tanh), ("gelu", jax.nn.gelu),
                    ("exp", jnp.exp), ("log", jnp.log), ("sqrt", jnp.sqrt),
                    ("square", jnp.square), ("abs", jnp.abs),
                    ("floor", jnp.floor), ("ceil", jnp.ceil),
                    ("softsign", jax.nn.soft_sign)]:
    def _make_unary(fn):
        def rule(ins, attrs, op):
            return {"Out": [fn(_one(ins, "X"))]}
        return rule
    register_op(_name)(_make_unary(_ufn))


@register_op("softmax")
def _softmax(ins, attrs, op):
    return {"Out": [jax.nn.softmax(_one(ins, "X"),
                                   axis=attrs.get("axis", -1))]}


@register_op("mean")
def _mean(ins, attrs, op):
    return {"Out": [jnp.mean(_one(ins, "X"))]}


def _reduce(fn):
    def rule(ins, attrs, op):
        x = _one(ins, "X")
        dim = attrs.get("dim", None)
        if attrs.get("reduce_all", False) or dim is None:
            dim = tuple(range(x.ndim))
        elif isinstance(dim, int):
            dim = (dim,)
        return {"Out": [fn(x, axis=tuple(dim),
                           keepdims=attrs.get("keep_dim", False))]}

    return rule


for _name, _fn in [("reduce_sum", jnp.sum), ("reduce_mean", jnp.mean),
                   ("reduce_max", jnp.max), ("reduce_min", jnp.min),
                   ("reduce_prod", jnp.prod)]:
    register_op(_name)(_reduce(_fn))


@register_op("sum")
def _sum(ins, attrs, op):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("clip")
def _clip(ins, attrs, op):
    return {"Out": [jnp.clip(_one(ins, "X"), attrs.get("min"),
                             attrs.get("max"))]}


# -- shape manipulation ------------------------------------------------------

@register_op("reshape2")
def _reshape2(ins, attrs, op):
    x = _one(ins, "X")
    shape = list(attrs["shape"])
    # ref reshape semantics: 0 = copy input dim, -1 = infer
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)] \
        if any(s == 0 for s in shape) else shape
    return {"Out": [x.reshape(shape)], "XShape": [jnp.zeros((0,))]}


@register_op("transpose2")
def _transpose2(ins, attrs, op):
    return {"Out": [jnp.transpose(_one(ins, "X"), attrs["axis"])],
            "XShape": [jnp.zeros((0,))]}


@register_op("flatten2")
def _flatten2(ins, attrs, op):
    x = _one(ins, "X")
    ax = attrs.get("axis", 1)
    out = x.reshape(int(np.prod(x.shape[:ax])) if ax else 1,
                    int(np.prod(x.shape[ax:])))
    return {"Out": [out], "XShape": [jnp.zeros((0,))]}


@register_op("concat")
def _concat(ins, attrs, op):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("split")
def _split(ins, attrs, op):
    x = _one(ins, "X")
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", None)
    if num:
        outs = jnp.split(x, num, axis=axis)
    else:
        idx = np.cumsum(sections)[:-1]
        outs = jnp.split(x, idx, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def _stack(ins, attrs, op):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("squeeze2")
def _squeeze2(ins, attrs, op):
    x = _one(ins, "X")
    axes = tuple(attrs.get("axes", ()))
    return {"Out": [jnp.squeeze(x, axis=axes or None)],
            "XShape": [jnp.zeros((0,))]}


@register_op("unsqueeze2")
def _unsqueeze2(ins, attrs, op):
    x = _one(ins, "X")
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": [x], "XShape": [jnp.zeros((0,))]}


# -- nn ----------------------------------------------------------------------

@register_op("conv2d")
def _conv2d(ins, attrs, op):
    out = F.conv2d(_one(ins, "Input"), _one(ins, "Filter"),
                   bias=_one(ins, "Bias"),
                   stride=attrs.get("strides", 1),
                   padding=attrs.get("paddings", 0),
                   dilation=attrs.get("dilations", 1),
                   groups=attrs.get("groups", 1),
                   data_format=attrs.get("data_format", "NCHW"))
    return {"Output": [out]}


@register_op("pool2d")
def _pool2d(ins, attrs, op):
    x = _one(ins, "X")
    ptype = attrs.get("pooling_type", "max")
    fmt = attrs.get("data_format", "NCHW")
    if attrs.get("global_pooling", False):
        axes = (1, 2) if fmt == "NHWC" else (2, 3)
        out = (jnp.max if ptype == "max" else jnp.mean)(
            x, axis=axes, keepdims=True)
    elif attrs.get("adaptive", False):
        fn = (F.adaptive_max_pool2d if ptype == "max"
              else F.adaptive_avg_pool2d)
        out = fn(x, attrs["ksize"], data_format=fmt)
    else:
        fn = F.max_pool2d if ptype == "max" else F.avg_pool2d
        out = fn(x, attrs["ksize"], stride=attrs.get("strides", None),
                 padding=attrs.get("paddings", 0), data_format=fmt)
    return {"Out": [out]}


@register_op("batch_norm")
def _batch_norm(ins, attrs, op):
    training = not attrs.get("is_test", False)
    out, new_rm, new_rv = F.batch_norm(
        _one(ins, "X"), _one(ins, "Mean"), _one(ins, "Variance"),
        weight=_one(ins, "Scale"), bias=_one(ins, "Bias"),
        training=training, momentum=attrs.get("momentum", 0.9),
        epsilon=attrs.get("epsilon", 1e-5))
    return {"Y": [out], "MeanOut": [new_rm], "VarianceOut": [new_rv]}


@register_op("layer_norm")
def _layer_norm(ins, attrs, op):
    x = _one(ins, "X")
    ax = attrs.get("begin_norm_axis", 1)
    out = F.layer_norm(x, x.shape[ax:], weight=_one(ins, "Scale"),
                       bias=_one(ins, "Bias"),
                       epsilon=attrs.get("epsilon", 1e-5))
    return {"Y": [out]}


@register_op("dropout")
def _dropout(ins, attrs, op):
    out = F.dropout(_one(ins, "X"), p=attrs.get("dropout_prob", 0.5),
                    training=not attrs.get("is_test", False),
                    mode=attrs.get("dropout_implementation",
                                   "upscale_in_train"))
    return {"Out": [out]}


@register_op("lookup_table_v2")
def _lookup_table_v2(ins, attrs, op):
    # routes through parallel.embedding.lower_lookup: vocab-sharded
    # all_to_all exchange when the ambient plan covers W, dedup'd
    # segment-sum gradient under is_sparse, plain gather otherwise;
    # padding_idx rows are zeroed (and so get zero gradient)
    from ..parallel import embedding as _pemb
    wname = op.inputs.get("W", [""])[0]
    return {"Out": [_pemb.lower_lookup(_one(ins, "W"), _one(ins, "Ids"),
                                       attrs, wname)]}


# -- loss / metrics ----------------------------------------------------------

@register_op("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ins, attrs, op):
    logits = _one(ins, "Logits")
    label = _one(ins, "Label")
    loss = F.softmax_with_cross_entropy(
        logits, label, soft_label=attrs.get("soft_label", False),
        ignore_index=attrs.get("ignore_index", -100))
    if loss.ndim < logits.ndim:
        loss = loss[..., None]
    return {"Loss": [loss], "Softmax": [jax.nn.softmax(logits, axis=-1)]}


@register_op("cross_entropy")
def _cross_entropy(ins, attrs, op):
    x = _one(ins, "X")  # probabilities
    label = _one(ins, "Label")
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, 1e-20)), axis=-1,
                        keepdims=True)
    else:
        lab = label[..., 0] if label.ndim == x.ndim else label
        p = jnp.take_along_axis(x, lab[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(jnp.maximum(p, 1e-20))
    return {"Y": [loss]}


@register_op("accuracy")
def _accuracy(ins, attrs, op):
    pred = _one(ins, "Out")
    label = _one(ins, "Label")
    top1 = jnp.argmax(pred, axis=-1)
    lab = label[..., 0] if label.ndim == pred.ndim else label
    acc = jnp.mean((top1 == lab).astype(jnp.float32))
    n = jnp.asarray(pred.shape[0], jnp.int32)
    return {"Accuracy": [acc], "Correct": [(acc * n).astype(jnp.int32)],
            "Total": [n]}


@register_op("top_k")
def _top_k(ins, attrs, op):
    vals, idx = jax.lax.top_k(_one(ins, "X"), attrs.get("k", 1))
    return {"Out": [vals], "Indices": [idx]}


@register_op("arg_max")
def _arg_max(ins, attrs, op):
    return {"Out": [jnp.argmax(_one(ins, "X"),
                               axis=attrs.get("axis", -1)).astype(jnp.int64)]}


# -- optimizer update ops (ref operators/optimizers/, SURVEY.md N30) ---------

@register_op("sgd")
def _sgd(ins, attrs, op):
    p, g, lr = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "LearningRate")
    return {"ParamOut": [p - lr.astype(p.dtype) * g.astype(p.dtype)]}


@register_op("momentum")
def _momentum(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    v, lr = _one(ins, "Velocity"), _one(ins, "LearningRate")
    mu = attrs.get("mu", 0.9)
    lr = lr.astype(p.dtype)
    v_new = mu * v + g.astype(p.dtype)
    if attrs.get("use_nesterov", False):
        p_new = p - lr * (g.astype(p.dtype) + mu * v_new)
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op("adam")
def _adam(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m, v = _one(ins, "Moment1"), _one(ins, "Moment2")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    b1p = _one(ins, "Beta1Pow").astype(jnp.float32)
    b2p = _one(ins, "Beta2Pow").astype(jnp.float32)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    gf = g.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * gf
    v_new = b2 * v + (1 - b2) * gf * gf
    lr_t = lr * jnp.sqrt(1 - b2p * b2) / (1 - b1p * b1)
    p_new = p.astype(jnp.float32) - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return {"ParamOut": [p_new.astype(p.dtype)], "Moment1Out": [m_new],
            "Moment2Out": [v_new], "Beta1PowOut": [b1p * b1],
            "Beta2PowOut": [b2p * b2]}


# -- comparisons / logicals (ref operators/controlflow/compare_op.cc,
#    logical_op.cc) — booleans feed cond/while lowerings -----------------------
def _compare(fn):
    def rule(ins, attrs, op):
        x, y = _one(ins, "X"), _one(ins, "Y")
        return {"Out": [fn(x, y)]}
    return rule


for _name, _fn in [
    ("less_than", lambda x, y: x < y),
    ("less_equal", lambda x, y: x <= y),
    ("greater_than", lambda x, y: x > y),
    ("greater_equal", lambda x, y: x >= y),
    ("equal", lambda x, y: x == y),
    ("not_equal", lambda x, y: x != y),
    ("logical_and", jnp.logical_and),
    ("logical_or", jnp.logical_or),
    ("logical_xor", jnp.logical_xor),
]:
    register_op(_name)(_compare(_fn))


@register_op("logical_not")
def _logical_not(ins, attrs, op):
    return {"Out": [jnp.logical_not(_one(ins, "X"))]}


@register_op("increment")
def _increment(ins, attrs, op):
    # ref increment_op: in-place X += step (functional here; the DSL reuses
    # the input name so while-loop counters carry through the env)
    x = _one(ins, "X")
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


# -- long-tail elementwise / manipulation (ref operators/*.cc) ---------------
def _unary_rule(fn):
    def rule(ins, attrs, op):
        return {"Out": [fn(_one(ins, "X"))]}
    return rule


for _name, _fn in [
    ("sin", jnp.sin), ("cos", jnp.cos), ("tan", jnp.tan),
    ("asin", jnp.arcsin), ("acos", jnp.arccos), ("atan", jnp.arctan),
    ("sinh", jnp.sinh), ("cosh", jnp.cosh),
    ("rsqrt", jax.lax.rsqrt), ("reciprocal", lambda x: 1.0 / x),
    ("round", jnp.round), ("sign", jnp.sign),
    ("log2", jnp.log2), ("log10", jnp.log10), ("log1p", jnp.log1p),
    ("expm1", jnp.expm1), ("erf", jax.scipy.special.erf),
    ("softplus", jax.nn.softplus), ("silu", jax.nn.silu),
    ("swish", jax.nn.silu), ("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x))),
    ("relu6", lambda x: jnp.clip(x, 0.0, 6.0)),
    ("hard_swish", lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0),
    ("selu", jax.nn.selu),
    ("logsigmoid", jax.nn.log_sigmoid),
]:
    register_op(_name)(_unary_rule(_fn))


@register_op("leaky_relu")
def _leaky_relu(ins, attrs, op):
    a = attrs.get("alpha", 0.02)
    x = _one(ins, "X")
    return {"Out": [jnp.where(x >= 0, x, a * x)]}


@register_op("elu")
def _elu(ins, attrs, op):
    a = attrs.get("alpha", 1.0)
    x = _one(ins, "X")
    return {"Out": [jnp.where(x >= 0, x, a * (jnp.exp(x) - 1.0))]}


@register_op("hard_sigmoid")
def _hard_sigmoid(ins, attrs, op):
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": [jnp.clip(slope * _one(ins, "X") + offset, 0.0, 1.0)]}


@register_op("pow")
def _pow(ins, attrs, op):
    return {"Out": [jnp.power(_one(ins, "X"), attrs.get("factor", 1.0))]}


@register_op("log_softmax")
def _log_softmax(ins, attrs, op):
    return {"Out": [jax.nn.log_softmax(_one(ins, "X"),
                                       axis=attrs.get("axis", -1))]}


@register_op("arg_min")
def _arg_min(ins, attrs, op):
    x = _one(ins, "X")
    return {"Out": [jnp.argmin(x, axis=attrs.get("axis", -1))
                    .astype(jnp.int64)]}


@register_op("cumsum")
def _cumsum(ins, attrs, op):
    x = _one(ins, "X")
    axis = attrs.get("axis")
    if attrs.get("flatten", False) or axis is None:
        x, axis = x.reshape(-1), 0
    reverse = attrs.get("reverse", False)
    exclusive = attrs.get("exclusive", False)
    out = jnp.cumsum(x, axis=axis)
    if reverse:
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if exclusive:
        # shift by one along `axis`: drop the first (last when reverse)
        # element and pad a zero on the other side, matching cumsum_op's
        # exclusive semantics for both directions.
        pad = [(0, 0)] * out.ndim
        sl = [slice(None)] * out.ndim
        if reverse:
            pad[axis] = (0, 1)
            sl[axis] = slice(1, None)
        else:
            pad[axis] = (1, 0)
            sl[axis] = slice(0, -1)
        out = jnp.pad(out, pad)[tuple(sl)]
    return {"Out": [out]}


@register_op("gather")
def _gather(ins, attrs, op):
    x, idx = _one(ins, "X"), _one(ins, "Index")
    return {"Out": [jnp.take(x, idx.astype(jnp.int32),
                             axis=attrs.get("axis", 0))]}


@register_op("gather_nd")
def _gather_nd(ins, attrs, op):
    x, idx = _one(ins, "X"), _one(ins, "Index")
    idx = idx.astype(jnp.int32)
    return {"Out": [x[tuple(jnp.moveaxis(idx, -1, 0))]]}


@register_op("scatter")
def _scatter(ins, attrs, op):
    x, ids, upd = _one(ins, "X"), _one(ins, "Ids"), _one(ins, "Updates")
    ids = ids.astype(jnp.int32)
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(upd)]}
    return {"Out": [x.at[ids].add(upd)]}


@register_op("slice")
def _slice(ins, attrs, op):
    x = _one(ins, "Input")
    axes = attrs["axes"]
    starts, ends = attrs["starts"], attrs["ends"]
    sl = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        sl[ax] = slice(s, e)
    return {"Out": [x[tuple(sl)]]}


@register_op("expand_v2")
def _expand_v2(ins, attrs, op):
    x = _one(ins, "X")
    shape = [x.shape[i] if s == -1 else s
             for i, s in enumerate(attrs["shape"])]
    return {"Out": [jnp.broadcast_to(x, shape)]}


@register_op("tile")
def _tile(ins, attrs, op):
    return {"Out": [jnp.tile(_one(ins, "X"), attrs["repeat_times"])]}


@register_op("where")
def _where(ins, attrs, op):
    c, x, y = _one(ins, "Condition"), _one(ins, "X"), _one(ins, "Y")
    return {"Out": [jnp.where(c, x, y)]}


@register_op("one_hot_v2")
def _one_hot(ins, attrs, op):
    x = _one(ins, "X")
    return {"Out": [jax.nn.one_hot(x.astype(jnp.int32), attrs["depth"])]}


@register_op("range")
def _range(ins, attrs, op):
    s, e, st = _one(ins, "Start"), _one(ins, "End"), _one(ins, "Step")
    # static-shape contract: bounds must be compile-time constants
    return {"Out": [jnp.arange(float(s), float(e), float(st))
                    .astype(s.dtype)]}


@register_op("shape")
def _shape(ins, attrs, op):
    x = _one(ins, "Input")
    return {"Out": [jnp.asarray(x.shape, jnp.int32)]}


@register_op("fill_constant_batch_size_like")
def _fill_like(ins, attrs, op):
    ref_arr = _one(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref_arr.shape[
        attrs.get("input_dim_idx", 0)]
    return {"Out": [jnp.full(shape, attrs["value"],
                             _dtype_mod.convert_dtype(attrs.get("dtype", "float32")))]}


@register_op("fill_zeros_like")
def _fill_zeros_like(ins, attrs, op):
    return {"Out": [jnp.zeros_like(_one(ins, "X"))]}


@register_op("pad2d")
def _pad2d(ins, attrs, op):
    """ref pad2d_op: NCHW [top, bottom, left, right]; constant/reflect/edge
    modes via the eager F.pad kernel."""
    p = attrs["paddings"]
    return {"Out": [F.pad(_one(ins, "X"), [p[2], p[3], p[0], p[1]],
                          mode=attrs.get("mode", "constant"),
                          value=attrs.get("pad_value", 0.0),
                          data_format="NCHW")]}


@register_op("pad")
def _pad(ins, attrs, op):
    x = _one(ins, "X")
    p = attrs["paddings"]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pairs,
                            constant_values=attrs.get("pad_value", 0.0))]}


@register_op("maximum")
def _maximum(ins, attrs, op):
    return {"Out": [jnp.maximum(_one(ins, "X"), _one(ins, "Y"))]}


@register_op("minimum")
def _minimum(ins, attrs, op):
    return {"Out": [jnp.minimum(_one(ins, "X"), _one(ins, "Y"))]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ins, attrs, op):
    x = _one(ins, "X")
    return {"Out": [jnp.sum(jnp.square(x)).reshape(1)]}


@register_op("huber_loss")
def _huber_loss(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Y")
    d = attrs.get("delta", 1.0)
    r = jnp.abs(x - y)
    loss = jnp.where(r <= d, 0.5 * r * r, d * (r - 0.5 * d))
    return {"Out": [loss], "Residual": [x - y]}


@register_op("smooth_l1_loss")
def _smooth_l1(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Y")
    sigma2 = attrs.get("sigma", 1.0) ** 2
    d = jnp.abs(x - y)
    loss = jnp.where(d < 1.0 / sigma2, 0.5 * d * d * sigma2, d - 0.5 / sigma2)
    return {"Out": [jnp.sum(loss, axis=tuple(range(1, x.ndim)),
                            keepdims=True)], "Diff": [x - y]}


@register_op("square_error_cost")
def _square_error_cost(ins, attrs, op):
    x, y = _one(ins, "X"), _one(ins, "Label")
    return {"Out": [jnp.square(x - y)]}


@register_op("relu_grad_passthrough")  # reserved (grad ops are jax.grad'd)
def _relu_grad_passthrough(ins, attrs, op):
    return {"Out": [_one(ins, "X")]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_xent(ins, attrs, op):
    x, label = _one(ins, "X"), _one(ins, "Label")
    # ref sigmoid_cross_entropy_with_logits_op: max(x,0) - x*z + log1p(exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if attrs.get("normalize", False):
        n = jnp.maximum(jnp.sum(label != ignore), 1)
        loss = loss / n
    return {"Out": [loss]}


@register_op("log_loss")
def _log_loss(ins, attrs, op):
    p, label = _one(ins, "Predicted"), _one(ins, "Labels")
    e = attrs.get("epsilon", 1e-4)
    out = -label * jnp.log(p + e) - (1 - label) * jnp.log(1 - p + e)
    return {"Loss": [out]}


@register_op("label_smooth")
def _label_smooth(ins, attrs, op):
    x = _one(ins, "X")
    eps = attrs.get("epsilon", 0.1)
    prior = _one(ins, "PriorDist")
    k = x.shape[-1]
    smooth = prior if prior is not None else 1.0 / k
    return {"Out": [(1 - eps) * x + eps * smooth]}


@register_op("norm")
def _l2_normalize(ins, attrs, op):
    x = _one(ins, "X")
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    return {"Out": [x / jnp.maximum(n, eps)], "Norm": [n]}


@register_op("kldiv_loss")
def _kldiv_loss(ins, attrs, op):
    x, tgt = _one(ins, "X"), _one(ins, "Target")
    # ref kldiv_loss_op: x is log-prob input, target is prob
    loss = tgt * (jnp.log(jnp.maximum(tgt, 1e-20)) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss)
    elif red == "sum":
        loss = jnp.sum(loss)
    elif red == "batchmean":
        loss = jnp.sum(loss) / x.shape[0]
    return {"Loss": [loss]}


@register_op("sequence_mask")
def _sequence_mask(ins, attrs, op):
    """Padded-layout sequence_mask (ref fluid/layers/nn.py sequence_mask);
    delegates to the eager ops/sequence.py implementation."""
    from ..ops import sequence as _seq

    mask = _seq.sequence_mask(_one(ins, "X"), maxlen=int(attrs["maxlen"]),
                              dtype=attrs.get("out_dtype", "float32"))
    return {"Y": [mask]}


@register_op("sequence_last_step_padded")
def _sequence_last_step_padded(ins, attrs, op):
    """Last valid timestep of a padded (b, s, d) batch given lengths (b,);
    delegates to ops/sequence.py sequence_last_step (the reference's
    LoD-aware sequence_last_step in the padded TPU layout)."""
    from ..ops import sequence as _seq

    return {"Out": [_seq.sequence_last_step(_one(ins, "X"),
                                            _one(ins, "Lengths"))]}


@register_op("sequence_pool_padded")
def _sequence_pool_padded(ins, attrs, op):
    """Padded-layout sequence_pool (ref sequence_ops/sequence_pool_op:
    sum/average/max/min/sqrt/first/last over each sequence's valid steps)."""
    from ..ops import sequence as _seq

    pool = attrs.get("pooltype", "sum").lower()
    pool = {"average": "mean"}.get(pool, pool)  # fluid name for mean
    out = _seq.sequence_pool(_one(ins, "X"), _one(ins, "Lengths"),
                             pool_type=pool,
                             pad_value=float(attrs.get("pad_value", 0.0)))
    return {"Out": [out]}


@register_op("sequence_softmax_padded")
def _sequence_softmax_padded(ins, attrs, op):
    """Padded-layout sequence_softmax (ref sequence_softmax_op): softmax over
    each sequence's valid positions, zeros on padding."""
    from ..ops import sequence as _seq

    return {"Out": [_seq.sequence_softmax(_one(ins, "X"),
                                          _one(ins, "Lengths"))]}


@register_op("sequence_reverse_padded")
def _sequence_reverse_padded(ins, attrs, op):
    """Padded-layout sequence_reverse (ref sequence_reverse_op): reverses
    the valid prefix of each row, padding stays in place."""
    from ..ops import sequence as _seq

    return {"Y": [_seq.sequence_reverse(_one(ins, "X"),
                                        _one(ins, "Lengths"))]}


@register_op("sequence_first_step_padded")
def _sequence_first_step_padded(ins, attrs, op):
    from ..ops import sequence as _seq

    return {"Out": [_seq.sequence_first_step(_one(ins, "X"),
                                             _one(ins, "Lengths"))]}


@register_op("conv2d_transpose")
def _conv2d_transpose(ins, attrs, op):
    out = F.conv2d_transpose(_one(ins, "Input"), _one(ins, "Filter"),
                             bias=_one(ins, "Bias"),
                             stride=attrs.get("strides", 1),
                             padding=attrs.get("paddings", 0),
                             output_padding=attrs.get("output_padding", 0),
                             dilation=attrs.get("dilations", 1),
                             groups=attrs.get("groups", 1))
    return {"Output": [out]}


@register_op("group_norm")
def _group_norm(ins, attrs, op):
    out = F.group_norm(_one(ins, "X"), attrs["groups"],
                       weight=_one(ins, "Scale"), bias=_one(ins, "Bias"),
                       epsilon=attrs.get("epsilon", 1e-5))
    return {"Y": [out]}


@register_op("instance_norm")
def _instance_norm(ins, attrs, op):
    out = F.instance_norm(_one(ins, "X"), weight=_one(ins, "Scale"),
                          bias=_one(ins, "Bias"),
                          epsilon=attrs.get("epsilon", 1e-5))
    return {"Y": [out]}


@register_op("prelu")
def _prelu(ins, attrs, op):
    return {"Out": [F.prelu(_one(ins, "X"), _one(ins, "Alpha"))]}


@register_op("resize_interp")
def _resize_interp(ins, attrs, op):
    """Shared lowering for resize_bilinear / resize_nearest (ref
    interpolate_op family)."""
    out = F.interpolate(_one(ins, "X"), size=tuple(attrs["out_shape"]),
                        mode=attrs["interp_method"],
                        align_corners=attrs.get("align_corners", False))
    return {"Out": [out]}


@register_op("prior_box")
def _prior_box(ins, attrs, op):
    from ..ops import vision as V

    x = _one(ins, "Input")
    img = _one(ins, "Image")
    boxes, variances = V.prior_box(
        (x.shape[2], x.shape[3]), (img.shape[2], img.shape[3]),
        min_sizes=list(attrs["min_sizes"]),
        max_sizes=list(attrs.get("max_sizes", [])),
        aspect_ratios=list(attrs.get("aspect_ratios", [1.0])),
        variances=list(attrs.get("variances", [0.1, 0.1, 0.2, 0.2])),
        flip=attrs.get("flip", False), clip=attrs.get("clip", False),
        steps=attrs.get("steps", (0.0, 0.0)),
        offset=attrs.get("offset", 0.5))
    return {"Boxes": [boxes], "Variances": [variances]}


@register_op("box_coder")
def _box_coder(ins, attrs, op):
    from ..ops import vision as V

    out = V.box_coder(_one(ins, "PriorBox"), _one(ins, "PriorBoxVar"),
                      _one(ins, "TargetBox"), attrs["code_type"],
                      box_normalized=attrs.get("box_normalized", True),
                      axis=attrs.get("axis", 0))
    return {"OutputBox": [out]}


@register_op("roi_align")
def _roi_align(ins, attrs, op):
    """Batch-1 RoIAlign (the eager kernel's static-shape contract; the
    reference's LoD multi-image batching is descoped to per-image calls)."""
    from ..ops import vision as V

    x = _one(ins, "X")
    if x.ndim == 4:
        if x.shape[0] != 1:
            raise ValueError(
                "static roi_align lowers the batch-1 eager kernel; split "
                f"the batch into per-image calls (got N={x.shape[0]})")
        x = x[0]
    out = V.roi_align(x, _one(ins, "ROIs"),
                      output_size=(attrs["pooled_height"],
                                   attrs["pooled_width"]),
                      spatial_scale=attrs.get("spatial_scale", 1.0),
                      sampling_ratio=attrs.get("sampling_ratio", -1))
    return {"Out": [out]}


@register_op("linear_chain_crf")
def _linear_chain_crf(ins, attrs, op):
    from ..ops import crf as _crf

    nll = _crf.linear_chain_crf(_one(ins, "Emission"), _one(ins, "Label"),
                                _one(ins, "Transition"), _one(ins, "Length"))
    return {"LogLikelihood": [nll]}


@register_op("crf_decoding")
def _crf_decoding(ins, attrs, op):
    from ..ops import crf as _crf

    path = _crf.crf_decoding(_one(ins, "Emission"), _one(ins, "Transition"),
                             _one(ins, "Length"))
    return {"ViterbiPath": [path]}


def _misc_op(op_type, in_slots, out_slot="Out", attr_names=()):
    """Register a lowering that forwards to the eager ops.misc function of
    the same name (fluid layer-function parity batch)."""
    from ..ops import misc as _misc

    fn = getattr(_misc, op_type)

    @register_op(op_type)
    def _lowered(ins, attrs, op, fn=fn, in_slots=in_slots,
                 attr_names=attr_names, out_slot=out_slot):
        args = [_one(ins, slot) for slot in in_slots]
        kwargs = {name: attrs[name] for name in attr_names if name in attrs}
        return {out_slot: [fn(*args, **kwargs)]}
    return _lowered


_misc_op("pixel_shuffle", ["X"], attr_names=("upscale_factor",))
_misc_op("space_to_depth", ["X"], attr_names=("blocksize",))
_misc_op("shuffle_channel", ["X"], attr_names=("group",))
_misc_op("temporal_shift", ["X"], attr_names=("seg_num", "shift_ratio"))
_misc_op("cos_sim", ["X", "Y"])
_misc_op("lrn", ["X"], attr_names=("n", "k", "alpha", "beta"))

@register_op("multiplex")
def _multiplex(ins, attrs, op):
    from ..ops import misc as _misc

    return {"Out": [_misc.multiplex(ins["X"], _one(ins, "Ids"))]}



@register_op("rank_loss")
def _rank_loss(ins, attrs, op):
    from ..ops import misc as _misc

    return {"Out": [_misc.rank_loss(_one(ins, "Label"), _one(ins, "Left"),
                                    _one(ins, "Right"))]}


@register_op("sigmoid_focal_loss")
def _sigmoid_focal_loss(ins, attrs, op):
    from ..ops import misc as _misc

    return {"Out": [_misc.sigmoid_focal_loss(
        _one(ins, "X"), _one(ins, "Label"), _one(ins, "FgNum"),
        gamma=attrs.get("gamma", 2.0), alpha=attrs.get("alpha", 0.25))]}


@register_op("grid_sampler")
def _grid_sampler(ins, attrs, op):
    from ..ops import misc as _misc

    return {"Output": [_misc.grid_sampler(
        _one(ins, "X"), _one(ins, "Grid"),
        mode=attrs.get("mode", "bilinear"),
        padding_mode=attrs.get("padding_mode", "zeros"),
        align_corners=attrs.get("align_corners", True))]}


@register_op("affine_grid")
def _affine_grid(ins, attrs, op):
    from ..ops import misc as _misc

    return {"Output": [_misc.affine_grid(
        _one(ins, "Theta"), tuple(attrs["output_shape"]),
        align_corners=attrs.get("align_corners", True))]}


@register_op("roi_pool")
def _roi_pool(ins, attrs, op):
    from ..ops import misc as _misc

    x = _one(ins, "X")
    if x.ndim == 4:
        if x.shape[0] != 1:
            raise ValueError("static roi_pool lowers the batch-1 kernel "
                             f"(got N={x.shape[0]})")
        x = x[0]
    return {"Out": [_misc.roi_pool(
        x, _one(ins, "ROIs"),
        (attrs["pooled_height"], attrs["pooled_width"]),
        spatial_scale=attrs.get("spatial_scale", 1.0))]}


@register_op("row_conv")
def _row_conv(ins, attrs, op):
    from ..ops import misc as _misc

    lengths = ins.get("Lengths")
    return {"Out": [_misc.row_conv(_one(ins, "X"), _one(ins, "Filter"),
                                   lengths=lengths[0] if lengths else None)]}


@register_op("sequence_conv_padded")
def _sequence_conv_padded(ins, attrs, op):
    from ..ops import misc as _misc

    lengths = ins.get("Lengths")
    out = _misc.sequence_conv(
        _one(ins, "X"), _one(ins, "Filter"),
        lengths=lengths[0] if lengths else None,
        context_length=attrs["contextLength"],
        context_start=attrs.get("contextStart"))
    return {"Out": [out]}


@register_op("nce")
def _nce(ins, attrs, op):
    from ..ops import misc as _misc

    cost = _misc.nce_loss(_one(ins, "Input"), _one(ins, "Label"),
                          _one(ins, "Weight"), _one(ins, "Bias"),
                          _one(ins, "SampleIds"),
                          num_total_classes=attrs.get("num_total_classes"))
    return {"Cost": [cost]}


from . import ops_tail  # noqa: E402,F401 — long-tail lowerings (registry side effects)
from . import ops_tail2  # noqa: E402,F401 — batch-2 lowerings (registry side effects)
from . import ops_tail3  # noqa: E402,F401 — batch-3 lowerings (registry side effects)
from . import ops_tail4  # noqa: E402,F401 — batch-4 lowerings (registry side effects)
from . import ops_tail5  # noqa: E402,F401 — batch-5 lowerings (registry side effects)
from . import ops_tail6  # noqa: E402,F401 — batch-6 lowerings (registry side effects)
from . import ops_tail7  # noqa: E402,F401 — batch-7 lowerings (registry side effects)
from . import ops_fused  # noqa: E402,F401 — pass-emitted fused-op lowerings
