"""Reference binary model interop: `__model__` ProgramDesc + LoDTensor
parameter files.

Reference parity: `framework/framework.proto:212` (ProgramDesc/BlockDesc/
OpDesc/VarDesc/VarType — field numbers schema-copied below, no paddle or
protobuf import), `framework/lod_tensor.cc SerializeToStream` +
`tensor_util.cc TensorToStream` (the parameter wire format), and
`python/paddle/fluid/io.py:1164/:1374` (save/load_inference_model's
`__model__` + per-var / `__params__` layout).

This closes the round-4 VERDICT missing #1: a model saved by the
reference's `save_inference_model` loads HERE — the proto decoder maps
each OpDesc onto the registered lowerings (op names/attrs kept parity
across static/ops*.py precisely for this) through the op-version
migration path, and the LoDTensor reader ingests the parameter bytes.
The encoder side round-trips our pruned inference programs into the same
wire format, so models also port OUT to reference tooling.

Proto2 wire handling: varints are decoded with 64-bit sign semantics
(dims use -1), repeated scalars accept both packed and unpacked layouts,
and unknown fields are skipped by wire type — old/new reference minors
parse without a schema bump.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "parse_program_desc", "encode_program_desc",
    "program_from_desc", "program_to_desc",
    "read_lod_tensor", "write_lod_tensor",
    "load_reference_params", "save_reference_params",
]

# -- AttrType enum (framework.proto:25) --------------------------------------
INT, FLOAT, STRING, INTS, FLOATS, STRINGS, BOOLEAN, BOOLEANS, BLOCK, LONG, \
    BLOCKS, LONGS = range(12)

# -- VarType.Type (framework.proto:105) --------------------------------------
VARTYPE_NP = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
              5: np.float32, 6: np.float64, 20: np.uint8, 21: np.int8}
NP_VARTYPE = {np.dtype(v).name: k for k, v in VARTYPE_NP.items()}


def _vartype_np(code: int):
    if code == 4:    # FP16
        return np.float16
    if code == 22:   # BF16
        import ml_dtypes

        return ml_dtypes.bfloat16
    try:
        return VARTYPE_NP[code]
    except KeyError:
        raise ValueError(f"unsupported VarType.Type {code}") from None


def _np_vartype(dtype) -> int:
    name = np.dtype(dtype).name
    if name == "float16":
        return 4
    if name == "bfloat16":
        return 22
    try:
        return NP_VARTYPE[name]
    except KeyError:
        raise ValueError(f"no VarType.Type for dtype {name}") from None


LOD_TENSOR, FEED_MINIBATCH, FETCH_LIST = 7, 9, 10


# =========================================================================
# proto2 wire primitives
# =========================================================================

def _read_varint(b: bytes, off: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        byte = b[off]
        off += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result & 0xFFFFFFFFFFFFFFFF, off
        shift += 7


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _write_varint(v: int) -> bytes:
    v &= 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        byte = v & 0x7F
        v >>= 7
        if v:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _iter_fields(b: bytes):
    """Yield (field_number, wire_type, value) skipping nothing: value is
    int for varint/fixed, bytes for length-delimited."""
    off = 0
    n = len(b)
    while off < n:
        key, off = _read_varint(b, off)
        num, wire = key >> 3, key & 7
        if wire == 0:
            v, off = _read_varint(b, off)
        elif wire == 1:
            v = struct.unpack_from("<Q", b, off)[0]
            off += 8
        elif wire == 2:
            ln, off = _read_varint(b, off)
            v = b[off:off + ln]
            off += ln
        elif wire == 5:
            v = struct.unpack_from("<I", b, off)[0]
            off += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield num, wire, v


def _field(num: int, wire: int, payload: bytes) -> bytes:
    return _write_varint((num << 3) | wire) + payload


def _f_varint(num: int, v: int) -> bytes:
    return _field(num, 0, _write_varint(v))


def _f_bytes(num: int, v: bytes) -> bytes:
    return _field(num, 2, _write_varint(len(v)) + v)


def _f_float(num: int, v: float) -> bytes:
    return _field(num, 5, struct.pack("<f", v))


def _varints_maybe_packed(wire, v) -> List[int]:
    """A repeated varint field: one value (unpacked) or a packed blob."""
    if wire == 0:
        return [v]
    out = []
    off = 0
    while off < len(v):
        x, off = _read_varint(v, off)
        out.append(x)
    return out


def _floats_maybe_packed(wire, v) -> List[float]:
    if wire == 5:
        return [struct.unpack("<f", struct.pack("<I", v))[0]]
    return list(struct.unpack(f"<{len(v) // 4}f", v))


# =========================================================================
# message decoders (field numbers from framework.proto)
# =========================================================================

def _parse_attr(b: bytes) -> Tuple[str, int, object]:
    name, atype = "", INT
    i = f = s = blk = l = None
    ints: List[int] = []
    floats: List[float] = []
    strings: List[str] = []
    b_ = None
    bools: List[bool] = []
    blocks: List[int] = []
    longs: List[int] = []
    for num, wire, v in _iter_fields(b):
        if num == 1:
            name = v.decode()
        elif num == 2:
            atype = v
        elif num == 3:
            i = _signed(v) & 0xFFFFFFFF
            i = i - (1 << 32) if i >= (1 << 31) else i
        elif num == 4:
            f = struct.unpack("<f", struct.pack("<I", v))[0]
        elif num == 5:
            s = v.decode()
        elif num == 6:
            ints.extend(_varints_maybe_packed(wire, v))
        elif num == 7:
            floats.extend(_floats_maybe_packed(wire, v))
        elif num == 8:
            strings.append(v.decode())
        elif num == 10:
            b_ = bool(v)
        elif num == 11:
            bools.extend(bool(x) for x in _varints_maybe_packed(wire, v))
        elif num == 12:
            blk = v
        elif num == 13:
            l = _signed(v)
        elif num == 14:
            blocks.extend(_varints_maybe_packed(wire, v))
        elif num == 15:
            longs.extend(_signed(x) for x in _varints_maybe_packed(wire, v))
    value = {
        INT: i, FLOAT: f, STRING: s,
        INTS: [x - (1 << 32) if x >= (1 << 31) else x
               for x in (y & 0xFFFFFFFF for y in ints)],
        FLOATS: floats, STRINGS: strings, BOOLEAN: b_, BOOLEANS: bools,
        BLOCK: blk, LONG: l, BLOCKS: blocks, LONGS: longs,
    }[atype]
    return name, atype, value


def _parse_opvar(b: bytes) -> Tuple[str, List[str]]:
    param, args = "", []
    for num, wire, v in _iter_fields(b):
        if num == 1:
            param = v.decode()
        elif num == 2:
            args.append(v.decode())
    return param, args


def _parse_op(b: bytes) -> dict:
    op = {"type": "", "inputs": {}, "outputs": {}, "attrs": {},
          "attr_types": {}}
    for num, wire, v in _iter_fields(b):
        if num == 3:
            op["type"] = v.decode()
        elif num == 1:
            k, args = _parse_opvar(v)
            op["inputs"][k] = args
        elif num == 2:
            k, args = _parse_opvar(v)
            op["outputs"][k] = args
        elif num == 4:
            name, atype, value = _parse_attr(v)
            op["attrs"][name] = value
            op["attr_types"][name] = atype
    return op


def _parse_tensor_desc(b: bytes) -> dict:
    dtype, dims = 5, []
    for num, wire, v in _iter_fields(b):
        if num == 1:
            dtype = v
        elif num == 2:
            dims.extend(_signed(x) for x in _varints_maybe_packed(wire, v))
    return {"data_type": dtype, "dims": dims}


def _parse_vartype(b: bytes) -> dict:
    vt = {"type": LOD_TENSOR, "tensor": None, "lod_level": 0}
    for num, wire, v in _iter_fields(b):
        if num == 1:
            vt["type"] = v
        elif num == 3:  # LoDTensorDesc
            for n2, w2, v2 in _iter_fields(v):
                if n2 == 1:
                    vt["tensor"] = _parse_tensor_desc(v2)
                elif n2 == 2:
                    vt["lod_level"] = v2
        elif num == 2:  # selected_rows TensorDesc
            vt["tensor"] = _parse_tensor_desc(v)
    return vt


def _parse_var(b: bytes) -> dict:
    var = {"name": "", "type": None, "persistable": False}
    for num, wire, v in _iter_fields(b):
        if num == 1:
            var["name"] = v.decode()
        elif num == 2:
            var["type"] = _parse_vartype(v)
        elif num == 3:
            var["persistable"] = bool(v)
    return var


def _parse_block(b: bytes) -> dict:
    blk = {"idx": 0, "parent_idx": -1, "vars": [], "ops": []}
    for num, wire, v in _iter_fields(b):
        if num == 1:
            blk["idx"] = v
        elif num == 2:
            blk["parent_idx"] = _signed(v)
        elif num == 3:
            blk["vars"].append(_parse_var(v))
        elif num == 4:
            blk["ops"].append(_parse_op(v))
    return blk


def parse_program_desc(data: bytes) -> dict:
    """ProgramDesc bytes -> {'blocks': [...], 'version': int}."""
    prog = {"blocks": [], "version": 0}
    for num, wire, v in _iter_fields(data):
        if num == 1:
            prog["blocks"].append(_parse_block(v))
        elif num == 4:  # Version message
            for n2, w2, v2 in _iter_fields(v):
                if n2 == 1:
                    prog["version"] = _signed(v2)
    return prog


# =========================================================================
# message encoders (round trip; also the export path)
# =========================================================================

def _enc_attr(name: str, atype: int, value) -> bytes:
    out = _f_bytes(1, name.encode()) + _f_varint(2, atype)
    if atype == INT:
        # proto2 int32: negative values are sign-extended to 64 bits and
        # emitted as the canonical 10-byte varint (NOT truncated to the
        # 32-bit pattern, which real protobuf decoders reject/misread)
        out += _f_varint(3, int(value) & 0xFFFFFFFFFFFFFFFF)
    elif atype == FLOAT:
        out += _f_float(4, float(value))
    elif atype == STRING:
        out += _f_bytes(5, str(value).encode())
    elif atype == INTS:
        for x in value:
            out += _f_varint(6, int(x) & 0xFFFFFFFFFFFFFFFF)
    elif atype == FLOATS:
        for x in value:
            out += _f_float(7, float(x))
    elif atype == STRINGS:
        for x in value:
            out += _f_bytes(8, str(x).encode())
    elif atype == BOOLEAN:
        out += _f_varint(10, 1 if value else 0)
    elif atype == BOOLEANS:
        for x in value:
            out += _f_varint(11, 1 if x else 0)
    elif atype == BLOCK:
        out += _f_varint(12, int(value))
    elif atype == LONG:
        out += _f_varint(13, int(value))
    elif atype == BLOCKS:
        for x in value:
            out += _f_varint(14, int(x))
    elif atype == LONGS:
        for x in value:
            out += _f_varint(15, int(x))
    else:
        raise ValueError(f"bad AttrType {atype}")
    return out


def _enc_opvar(num: int, param: str, args: Sequence[str]) -> bytes:
    body = _f_bytes(1, param.encode())
    for a in args:
        body += _f_bytes(2, a.encode())
    return _f_bytes(num, body)


def _enc_op(op: dict) -> bytes:
    body = b""
    for k, args in op["inputs"].items():
        body += _enc_opvar(1, k, args)
    for k, args in op["outputs"].items():
        body += _enc_opvar(2, k, args)
    body += _f_bytes(3, op["type"].encode())
    for name, value in op["attrs"].items():
        body += _f_bytes(4, _enc_attr(name, op["attr_types"][name], value))
    return body


def _enc_tensor_desc(td: dict) -> bytes:
    body = _f_varint(1, td["data_type"])
    for d in td["dims"]:
        body += _f_varint(2, d)
    return body


def _enc_var(var: dict) -> bytes:
    vt = var["type"]
    vt_body = _f_varint(1, vt["type"])
    if vt.get("tensor") is not None:
        lod_body = _f_bytes(1, _enc_tensor_desc(vt["tensor"])) \
            + _f_varint(2, vt.get("lod_level", 0))
        vt_body += _f_bytes(3, lod_body)
    body = _f_bytes(1, var["name"].encode()) + _f_bytes(2, vt_body)
    if var.get("persistable"):
        body += _f_varint(3, 1)
    return body


def _enc_block(blk: dict) -> bytes:
    body = _f_varint(1, blk["idx"]) + _f_varint(2, blk["parent_idx"])
    for v in blk["vars"]:
        body += _f_bytes(3, _enc_var(v))
    for op in blk["ops"]:
        body += _f_bytes(4, _enc_op(op))
    return body


def encode_program_desc(prog: dict) -> bytes:
    out = b""
    for blk in prog["blocks"]:
        out += _f_bytes(1, _enc_block(blk))
    out += _f_bytes(4, _f_varint(1, prog.get("version", 0)))
    return out


# =========================================================================
# desc <-> Program
# =========================================================================

def program_from_desc(desc: dict):
    """Decoded ProgramDesc -> (Program, feed_names, fetch_names).

    The reference's feed/fetch ops (io.py prepend_feed_ops/append_fetch_ops)
    are unwound into the (program, feeds, fetches) triple our Executor
    uses; op attrs flow through the op-version migration path (saved
    reference descs are version 0 of each op)."""
    from ..core.errors import UnimplementedError
    from . import op_version as _opv
    from .framework import Program
    from .registry import registered_ops

    if len(desc["blocks"]) != 1:
        raise UnimplementedError(
            "reference __model__ with control-flow sub-blocks: the proto "
            "importer handles single-block inference programs; rebuild "
            "cond/while via static.control_flow (executor lowers those to "
            "lax.cond/while_loop — the reference block encoding carries "
            "scope semantics that do not survive the XLA lowering)")
    blk = desc["blocks"][0]
    p = Program()
    b = p.global_block()
    known = set(registered_ops())
    feeds = [op["outputs"]["Out"][0] for op in blk["ops"]
             if op["type"] == "feed"]
    fetches = [op["inputs"]["X"][0] for op in blk["ops"]
               if op["type"] == "fetch"]

    for var in blk["vars"]:
        vt = var["type"] or {}
        if vt.get("type") in (FEED_MINIBATCH, FETCH_LIST):
            continue
        td = vt.get("tensor") or {"data_type": 5, "dims": []}
        dtype = np.dtype(_vartype_np(td["data_type"])).name
        shape = tuple(td["dims"])
        if var["persistable"]:
            # reference VarDesc does not mark Parameter-ness; persistable
            # non-data vars load as parameters (io.py load matches on
            # persistables either way)
            b.create_parameter(var["name"], shape, dtype)
        else:
            b.create_var(var["name"], shape, dtype,
                         is_data=var["name"] in feeds)
    for op in blk["ops"]:
        if op["type"] in ("feed", "fetch"):
            continue
        if op["type"] not in known:
            raise UnimplementedError(
                f"__model__ uses op {op['type']!r} with no registered "
                f"lowering (see static/op_coverage.py for the descope "
                "rationale table)")
        ins, outs, attrs = _opv.apply_converters(
            op["type"], 0, dict(op["inputs"]), dict(op["outputs"]),
            dict(op["attrs"]))
        # drop empty slots (the reference serializes dispensable empties)
        ins = {k: v for k, v in ins.items() if v}
        outs = {k: v for k, v in outs.items() if v}
        b.append_op(op["type"], ins, outs, attrs)
    return p, feeds, fetches


def _attr_type_of(value) -> Tuple[int, object]:
    if isinstance(value, bool):
        return BOOLEAN, value
    if isinstance(value, (int, np.integer)):
        v = int(value)
        return (INT, v) if -(1 << 31) <= v < (1 << 31) else (LONG, v)
    if isinstance(value, (float, np.floating)):
        return FLOAT, float(value)
    if isinstance(value, str):
        return STRING, value
    if isinstance(value, np.ndarray):
        value = value.tolist()
    if isinstance(value, (list, tuple)):
        vals = list(value)
        if all(isinstance(x, bool) for x in vals) and vals:
            return BOOLEANS, vals
        if all(isinstance(x, (int, np.integer)) for x in vals):
            vals = [int(x) for x in vals]
            if all(-(1 << 31) <= x < (1 << 31) for x in vals):
                return INTS, vals
            return LONGS, vals
        if all(isinstance(x, (int, float, np.floating, np.integer))
               for x in vals):
            return FLOATS, [float(x) for x in vals]
        if all(isinstance(x, str) for x in vals):
            return STRINGS, vals
    raise ValueError(f"attr value {value!r} has no AttrType mapping")


def program_to_desc(program, feeds: Sequence[str],
                    fetches: Sequence[str]) -> dict:
    """Our (single-block) Program -> ProgramDesc dict ready for
    encode_program_desc, with reference-style feed/fetch ops."""
    from ..core.errors import UnimplementedError
    from .framework import SUB_BLOCK_ATTRS, Parameter

    # mirror of the import-side guard (program_from_desc): a silently
    # truncated export would round-trip to a program missing its cond/while
    # bodies — fail legibly instead (ADVICE round-5 finding)
    if (len(program.blocks) > 1
            or any(a in op.attrs for op in program.global_block().ops
                   for a in SUB_BLOCK_ATTRS)):
        raise UnimplementedError(
            "exporting a Program with control-flow sub-blocks: the proto "
            "exporter emits single-block inference programs only — the "
            "reference block encoding carries scope semantics that do not "
            "survive the XLA lowering, so a multi-block export would drop "
            "the cond/while bodies silently")

    blk = program.global_block()
    vars_out = [
        {"name": "feed", "persistable": True,
         "type": {"type": FEED_MINIBATCH, "tensor": None}},
        {"name": "fetch", "persistable": True,
         "type": {"type": FETCH_LIST, "tensor": None}},
    ]
    for v in blk.vars.values():
        vars_out.append({
            "name": v.name,
            "persistable": bool(v.persistable
                                or isinstance(v, Parameter)),
            "type": {"type": LOD_TENSOR, "lod_level": 0,
                     "tensor": {"data_type": _np_vartype(v.dtype),
                                "dims": [int(d) for d in v.shape]}}})
    ops_out = []
    for i, name in enumerate(feeds):
        ops_out.append({"type": "feed", "inputs": {"X": ["feed"]},
                        "outputs": {"Out": [name]},
                        "attrs": {"col": i}, "attr_types": {"col": INT}})
    for op in blk.ops:
        attrs, attr_types = {}, {}
        for k, v in op.attrs.items():
            try:
                attr_types[k], attrs[k] = _attr_type_of(v)
            except ValueError:
                continue  # lowering-internal attrs with no proto encoding
        ops_out.append({"type": op.type, "inputs": dict(op.inputs),
                        "outputs": dict(op.outputs), "attrs": attrs,
                        "attr_types": attr_types})
    for i, name in enumerate(fetches):
        ops_out.append({"type": "fetch", "inputs": {"X": [name]},
                        "outputs": {"Out": ["fetch"]},
                        "attrs": {"col": i}, "attr_types": {"col": INT}})
    return {"blocks": [{"idx": 0, "parent_idx": -1, "vars": vars_out,
                        "ops": ops_out}], "version": 0}


# =========================================================================
# LoDTensor parameter files (lod_tensor.cc SerializeToStream)
# =========================================================================

def write_lod_tensor(f, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    f.write(struct.pack("<I", 0))          # LoDTensor version
    f.write(struct.pack("<Q", 0))          # lod levels
    f.write(struct.pack("<I", 0))          # Tensor version
    desc = _enc_tensor_desc({"data_type": _np_vartype(arr.dtype),
                             "dims": list(arr.shape)})
    f.write(struct.pack("<i", len(desc)))
    f.write(desc)
    f.write(arr.tobytes())


def read_lod_tensor(f) -> np.ndarray:
    (ver,) = struct.unpack("<I", f.read(4))
    if ver != 0:
        raise ValueError(f"unsupported LoDTensor version {ver}")
    (lod_levels,) = struct.unpack("<Q", f.read(8))
    for _ in range(lod_levels):
        (nbytes,) = struct.unpack("<Q", f.read(8))
        f.read(nbytes)  # LoD offsets: meaningless under the dense layout
    (tver,) = struct.unpack("<I", f.read(4))
    if tver != 0:
        raise ValueError(f"unsupported Tensor version {tver}")
    (dlen,) = struct.unpack("<i", f.read(4))
    td = _parse_tensor_desc(f.read(dlen))
    dtype = np.dtype(_vartype_np(td["data_type"]))
    count = int(np.prod(td["dims"])) if td["dims"] else 1
    data = f.read(count * dtype.itemsize)
    return np.frombuffer(data, dtype).reshape(td["dims"]).copy()


def save_reference_params(dirname: str, values: Dict[str, np.ndarray],
                          params_filename: Optional[str] = None) -> None:
    """Per-var files (save_vars) or one combined file (save_combine —
    tensors concatenated in SORTED name order, the reference convention)."""
    import os

    if params_filename:
        with open(os.path.join(dirname, params_filename), "wb") as f:
            for name in sorted(values):
                write_lod_tensor(f, values[name])
    else:
        for name, arr in values.items():
            with open(os.path.join(dirname, name), "wb") as f:
                write_lod_tensor(f, arr)


def load_reference_params(dirname: str, names: Sequence[str],
                          params_filename: Optional[str] = None
                          ) -> Dict[str, np.ndarray]:
    import os

    out = {}
    if params_filename:
        with open(os.path.join(dirname, params_filename), "rb") as f:
            for name in sorted(names):
                out[name] = read_lod_tensor(f)
    else:
        for name in names:
            with open(os.path.join(dirname, name), "rb") as f:
                out[name] = read_lod_tensor(f)
    return out
