"""Composite network helpers (ref python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, glu) built from the layers DSL."""
from __future__ import annotations

from . import layers as L


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         act=None, param_attr=None, bias_attr=None):
    """ref nets.py simple_img_conv_pool — conv2d + pool2d."""
    conv = L.conv2d(input, num_filters, filter_size, padding=0,
                    param_attr=param_attr, bias_attr=bias_attr, act=act)
    return L.pool2d(conv, pool_size, pool_type=pool_type,
                    pool_stride=pool_stride, pool_padding=pool_padding)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act="relu",
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max"):
    """ref nets.py img_conv_group — N conv(+bn+dropout) layers then a pool
    (the VGG building block of the image_classification book model)."""
    n = len(conv_num_filter)
    def _broadcast(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n

    filters = list(conv_num_filter)
    paddings = _broadcast(conv_padding)
    sizes = _broadcast(conv_filter_size)
    with_bn = _broadcast(conv_with_batchnorm)
    drops = _broadcast(conv_batchnorm_drop_rate)

    tmp = input
    for i in range(n):
        tmp = L.conv2d(tmp, filters[i], sizes[i], padding=paddings[i],
                       act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            tmp = L.batch_norm(tmp, act=conv_act)
            if drops[i] > 0:
                tmp = L.dropout(tmp, dropout_prob=drops[i])
    return L.pool2d(tmp, pool_size, pool_type=pool_type,
                    pool_stride=pool_stride)


def glu(input, dim=-1):
    """ref nets.py glu — gated linear unit: a * sigmoid(b)."""
    a, b = L.split(input, 2, dim=dim)
    return L.elementwise_mul(a, L.sigmoid(b))


def sequence_conv_pool(input, num_filters, filter_size, sequence_length,
                       param_attr=None, act="sigmoid", pool_type="max"):
    """ref nets.py sequence_conv_pool — sequence_conv + sequence_pool (the
    text-CNN building block of the understand_sentiment book model)."""
    conv = L.sequence_conv(input, num_filters, filter_size=filter_size,
                           sequence_length=sequence_length,
                           param_attr=param_attr, act=act)
    return L.sequence_pool(conv, pool_type, sequence_length)


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """ref nets.py scaled_dot_product_attention — multi-head attention from
    DSL primitives (batch, seq, dim inputs; single-head when num_heads=1).
    Returns the context tensor (batch, seq_q, dim_v)."""
    if keys.shape[-1] % num_heads or queries.shape[-1] % num_heads \
            or values.shape[-1] % num_heads:
        raise ValueError(
            f"scaled_dot_product_attention: hidden dims "
            f"(q {queries.shape[-1]}, k {keys.shape[-1]}, "
            f"v {values.shape[-1]}) must divide num_heads={num_heads}")
    d_k = keys.shape[-1] // num_heads
    if num_heads > 1:
        def split(x):
            b, s, dim = x.shape
            r = L.reshape(x, (-1, s, num_heads, dim // num_heads))
            return L.transpose(r, [0, 2, 1, 3])

        q, k, v = split(queries), split(keys), split(values)
    else:
        q, k, v = queries, keys, values
    scores = L.matmul(q, k, transpose_y=True, alpha=1.0 / (d_k ** 0.5))
    weights = L.softmax(scores)
    if dropout_rate > 0.0:
        weights = L.dropout(weights, dropout_prob=dropout_rate)
    ctx = L.matmul(weights, v)
    if num_heads > 1:
        # use the STATIC seq/dim from the declared inputs: matmul shape
        # inference propagates -1 batch dims and reshape allows one -1 only
        seq_q = queries.shape[1]
        dim_v = values.shape[-1]
        ctx = L.reshape(L.transpose(ctx, [0, 2, 1, 3]), (-1, seq_q, dim_v))
    return ctx
