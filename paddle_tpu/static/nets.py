"""Composite network helpers (ref python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, glu) built from the layers DSL."""
from __future__ import annotations

from . import layers as L


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         act=None, param_attr=None, bias_attr=None):
    """ref nets.py simple_img_conv_pool — conv2d + pool2d."""
    conv = L.conv2d(input, num_filters, filter_size, padding=0,
                    param_attr=param_attr, bias_attr=bias_attr, act=act)
    return L.pool2d(conv, pool_size, pool_type=pool_type,
                    pool_stride=pool_stride, pool_padding=pool_padding)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act="relu",
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max"):
    """ref nets.py img_conv_group — N conv(+bn+dropout) layers then a pool
    (the VGG building block of the image_classification book model)."""
    n = len(conv_num_filter)
    def _broadcast(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n

    filters = list(conv_num_filter)
    paddings = _broadcast(conv_padding)
    sizes = _broadcast(conv_filter_size)
    with_bn = _broadcast(conv_with_batchnorm)
    drops = _broadcast(conv_batchnorm_drop_rate)

    tmp = input
    for i in range(n):
        tmp = L.conv2d(tmp, filters[i], sizes[i], padding=paddings[i],
                       act=None if with_bn[i] else conv_act)
        if with_bn[i]:
            tmp = L.batch_norm(tmp, act=conv_act)
            if drops[i] > 0:
                tmp = L.dropout(tmp, dropout_prob=drops[i])
    return L.pool2d(tmp, pool_size, pool_type=pool_type,
                    pool_stride=pool_stride)


def glu(input, dim=-1):
    """ref nets.py glu — gated linear unit: a * sigmoid(b)."""
    a, b = L.split(input, 2, dim=dim)
    return L.elementwise_mul(a, L.sigmoid(b))
