"""Static-graph model persistence.

Reference parity: python/paddle/fluid/io.py — save/load_persistables
(:598/:692) and save/load_inference_model (:1164/:1374), which serialize a
pruned ProgramDesc + parameter files.

TPU-native format: a directory with `program.json` (the symbolic program:
vars + ops + attrs — human-readable, replaces the protobuf ProgramDesc) and
`params.npz` (every persistable's value).  load_inference_model rebuilds the
Program and returns (program, feed_names, fetch_names) exactly like the
reference API.

WIRE-COMPAT DESCOPE (deliberate, recorded): this format is NOT
byte-compatible with the reference's `framework.proto:212` ProgramDesc or
`save_inference_model`'s `__model__` + per-var LoDTensor files.  Rationale:
(a) the proto encodes executor-era concepts (LoD levels, kernel hints,
op-version map) that have no meaning under the XLA lowering, so a faithful
decoder would immediately re-encode into this in-memory form anyway;
(b) no reference-built binary models exist in this environment to migrate;
(c) JSON + npz keeps the format inspectable and diffable.  A migration
would need: a protobuf schema copy of framework.proto, a desc→Program
decoder mapping each OpDesc attr onto the registered lowerings (the op
names already match), and a LoDTensor file reader (plain header + raw
bytes).  The op-name/attr parity maintained throughout static/ops.py is
what keeps that door open.
"""
from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .executor import Executor, Scope, global_scope
from .framework import Parameter, Program, Variable

__all__ = ["save_persistables", "load_persistables", "save_inference_model",
           "load_inference_model"]


def _persistable_values(program: Program, scope: Scope):
    out = {}
    for v in program.list_vars():
        if v.persistable:
            val = scope.find_var(v.name)
            if val is not None:
                out[v.name] = np.asarray(val)
    return out


def save_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None):
    """ref fluid/io.py:598 — all persistables (params + optimizer state)."""
    from .framework import default_main_program
    program = main_program or default_main_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, "params.npz"),
             **_persistable_values(program, scope))


def load_persistables(executor: Executor, dirname: str,
                      main_program: Optional[Program] = None,
                      scope: Optional[Scope] = None):
    from .framework import default_main_program
    program = main_program or default_main_program()
    scope = scope or global_scope()
    data = np.load(os.path.join(dirname, "params.npz"))
    for v in program.list_vars():
        if v.persistable and v.name in data:
            scope.set(v.name, data[v.name])


def _program_to_json(program: Program) -> dict:
    from . import op_version as _opv

    blk = program.global_block()
    used = {op.type for op in blk.ops}
    return {
        # ref op_version_registry.h: stamp versions of the op types this
        # PROGRAM uses (stamping the whole registry would make packages
        # reject on version bumps in ops they never touch)
        "op_versions": {t: v for t, v in _opv.op_version_map().items()
                        if t in used},
        "vars": [
            {"name": v.name, "shape": list(v.shape),
             "dtype": np.dtype(v.dtype).name, "persistable": v.persistable,
             "is_data": v.is_data, "parameter": isinstance(v, Parameter),
             "trainable": getattr(v, "trainable", False)}
            for v in blk.vars.values()],
        "ops": [
            {"type": op.type, "inputs": op.inputs, "outputs": op.outputs,
             "attrs": _jsonable(op.attrs)}
            for op in blk.ops],
    }


def _jsonable(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        elif isinstance(v, (tuple,)):
            v = list(v)
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out


def _program_from_json(d: dict) -> Program:
    from ..core.errors import UnimplementedError
    from . import op_version as _opv

    saved_versions = d.get("op_versions", {})  # pre-registry packages: v0
    problems = _opv.check_compatible(saved_versions)
    if problems:
        raise UnimplementedError("; ".join(problems))
    p = Program()
    b = p.global_block()
    for v in d["vars"]:
        if v["parameter"]:
            b.create_parameter(v["name"], v["shape"], v["dtype"],
                               trainable=v.get("trainable", True))
        else:
            b.create_var(v["name"], v["shape"], v["dtype"],
                         persistable=v["persistable"], is_data=v["is_data"])
    for op in d["ops"]:
        ins, outs, attrs = _opv.apply_converters(
            op["type"], int(saved_versions.get(op["type"], 0)),
            op["inputs"], op["outputs"], op["attrs"])
        b.append_op(op["type"], ins, outs, attrs)
    return p


def _prune_for_inference(program: Program, feed_names, fetch_names) -> Program:
    """Backward slice from the fetches, dropping backward/optimizer ops —
    the reference's prune + inference-transpile step (io.py:1164)."""
    blk = program.global_block()
    needed = set(fetch_names)
    kept = []
    for op in reversed(blk.ops):
        if op.type in ("backward_region", "sgd", "momentum", "adam", "feed",
                       "fetch"):
            continue
        if set(op.output_names()) & needed:
            kept.append(op)
            needed |= set(op.input_names())
    kept.reverse()
    pruned = Program()
    b = pruned.global_block()
    for name, v in blk.vars.items():
        if name in needed or name in fetch_names:
            if isinstance(v, Parameter):
                b.create_parameter(name, v.shape, v.dtype, v.trainable)
            else:
                b.create_var(name, v.shape, v.dtype, persistable=v.persistable,
                             is_data=v.is_data)
    for op in kept:
        attrs = dict(op.attrs)
        if op.type in ("dropout", "batch_norm"):
            attrs["is_test"] = True
        b.append_op(op.type, op.inputs, op.outputs, attrs)
    return pruned


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars: Sequence[Variable], executor: Executor,
                         main_program: Optional[Program] = None,
                         scope: Optional[Scope] = None,
                         cipher=None, model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None):
    """ref fluid/io.py:1164.  ``cipher`` (utils.crypto.Cipher) encrypts the
    saved parameter file like the reference's encrypted inference models
    (framework/io/crypto/): params.npz becomes params.npz.enc.

    ``model_filename`` selects the REFERENCE BINARY format: the program is
    written as a `framework.proto` ProgramDesc (conventionally
    ``model_filename="__model__"``) and parameters as LoDTensor files —
    one per var, or combined into ``params_filename`` — loadable by the
    reference's `load_inference_model` (static/proto_format.py)."""
    from .framework import default_main_program
    program = main_program or default_main_program()
    scope = scope or global_scope()
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in target_vars]
    pruned = _prune_for_inference(program, list(feeded_var_names), fetch_names)
    os.makedirs(dirname, exist_ok=True)
    if model_filename is not None:
        from . import proto_format as PF

        if cipher is not None:
            raise ValueError("cipher is a feature of the native json+npz "
                             "format; the reference wire format has no "
                             "encryption envelope")
        desc = PF.program_to_desc(pruned, list(feeded_var_names),
                                  fetch_names)
        with open(os.path.join(dirname, model_filename), "wb") as f:
            f.write(PF.encode_program_desc(desc))
        PF.save_reference_params(
            dirname, _persistable_values(pruned, scope), params_filename)
        # a stale native-format program would win load auto-detection
        for stale in ("program.json", "params.npz", "params.npz.enc"):
            sp = os.path.join(dirname, stale)
            if os.path.exists(sp):
                os.remove(sp)
        return fetch_names
    with open(os.path.join(dirname, "program.json"), "w") as f:
        json.dump({"program": _program_to_json(pruned),
                   "feeds": list(feeded_var_names),
                   "fetches": fetch_names}, f, indent=1)
    # mirror of the reference-format branch: a stale __model__ would win
    # the reference-API load spelling (model_filename="__model__")
    for stale in ("__model__", "__params__"):
        sp = os.path.join(dirname, stale)
        if os.path.exists(sp):
            os.remove(sp)
    plain = os.path.join(dirname, "params.npz")
    enc = plain + ".enc"
    if cipher is None:
        np.savez(plain, **_persistable_values(pruned, scope))
        if os.path.exists(enc):  # stale ciphertext from a prior cipher save
            os.remove(enc)
    else:
        import io as _io

        buf = _io.BytesIO()
        np.savez(buf, **_persistable_values(pruned, scope))
        cipher.encrypt_to_file(buf.getvalue(), enc)
        if os.path.exists(plain):  # stale plaintext from a prior plain save
            os.remove(plain)
    return fetch_names


def load_inference_model(dirname: str, executor: Executor,
                         scope: Optional[Scope] = None,
                         cipher=None, model_filename: Optional[str] = None,
                         params_filename: Optional[str] = None
                         ) -> Tuple[Program, List[str], List[str]]:
    """ref fluid/io.py:1374 — returns (program, feed_names, fetch_names).
    Pass the ``cipher`` used at save time to read encrypted params.

    Accepts BOTH formats: the native `program.json` + `params.npz`, and
    the reference's binary `__model__` ProgramDesc + LoDTensor parameter
    files (auto-detected; or name them via ``model_filename`` /
    ``params_filename`` exactly like the reference API) — so a model
    exported by the reference's `save_inference_model` serves here
    unchanged (static/proto_format.py)."""
    scope = scope or global_scope()
    json_path = os.path.join(dirname, "program.json")
    if model_filename is None and not os.path.exists(json_path) \
            and os.path.exists(os.path.join(dirname, "__model__")):
        model_filename = "__model__"
    if model_filename is not None:
        from .framework import Parameter
        from . import proto_format as PF

        if cipher is not None:
            raise ValueError("cipher is a feature of the native json+npz "
                             "format; the reference wire format has no "
                             "encryption envelope")
        with open(os.path.join(dirname, model_filename), "rb") as f:
            desc = PF.parse_program_desc(f.read())
        program, feeds, fetches = PF.program_from_desc(desc)
        names = [v.name for v in program.list_vars()
                 if v.persistable or isinstance(v, Parameter)]
        for name, arr in PF.load_reference_params(
                dirname, names, params_filename).items():
            scope.set(name, arr)
        return program, feeds, fetches
    with open(json_path) as f:
        d = json.load(f)
    program = _program_from_json(d["program"])
    enc = os.path.join(dirname, "params.npz.enc")
    if cipher is not None:
        import io as _io

        data = np.load(_io.BytesIO(cipher.decrypt_from_file(enc)))
    elif os.path.exists(enc):
        raise ValueError(
            f"{dirname} holds an encrypted model (params.npz.enc); pass "
            "cipher= with the key it was saved with")
    else:
        data = np.load(os.path.join(dirname, "params.npz"))
    for name in data.files:
        scope.set(name, data[name])
    return program, d["feeds"], d["fetches"]


def save(program: Program, model_prefix: str, executor: Executor = None,
         scope: Optional[Scope] = None, fetches: Sequence = ()) -> None:
    """Save a FULL program (including backward/optimizer ops) + its
    persistable state: ``<prefix>.pdmodel`` (JSON program) and
    ``<prefix>.pdparams`` (npz) (ref fluid/io.py save :1669 — program +
    state serialization; JSON replaces the pickled ProgramDesc, see the
    wire-compat descope note in this module's docstring).

    Unlike save_inference_model this does NOT prune: the saved program can
    keep TRAINING when reloaded (the reference's C++ train-from-saved-
    program demo contract, train/demo/demo_trainer.cc).
    """
    scope = scope or global_scope()
    os.makedirs(os.path.dirname(model_prefix) or ".", exist_ok=True)
    feeds = [v.name for v in program.global_block().vars.values()
             if getattr(v, "is_data", False)]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in fetches]
    with open(model_prefix + ".pdmodel", "w") as f:
        json.dump({"program": _program_to_json(program), "feeds": feeds,
                   "fetches": fetch_names}, f, indent=1)
    with open(model_prefix + ".pdparams", "wb") as f:
        np.savez(f, **_persistable_values(program, scope))


def load(model_prefix: str, executor: Executor = None,
         scope: Optional[Scope] = None
         ) -> Tuple[Program, List[str], List[str]]:
    """Load a program + state saved by ``save`` (ref fluid/io.py load
    :1730).  Returns (program, feed_names, fetch_names)."""
    scope = scope or global_scope()
    with open(model_prefix + ".pdmodel") as f:
        d = json.load(f)
    program = _program_from_json(d["program"])
    data = np.load(model_prefix + ".pdparams")
    for name in data.files:
        scope.set(name, data[name])
    return program, d["feeds"], d.get("fetches", [])
