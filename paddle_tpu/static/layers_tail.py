"""fluid.layers DSL tail: wrappers over already-registered lowerings.

Reference parity: the remainder of python/paddle/fluid/layers/ (nn.py,
tensor.py, loss.py, detection.py, sequence_lod.py) — each function appends
the same-named op (or the documented composition) exactly like the
reference's LayerHelper.append_op flow.  Ops themselves live in
static/ops*.py; this module is pure graph-building surface.
"""
from __future__ import annotations

import numpy as np

from .layers import (  # noqa: F401 — shared builders
    _append,
    _apply_act,
    _out,
    _pair,
    Variable,
)
from . import layers as _L

__all__ = []


def _export(fn):
    __all__.append(fn.__name__)
    setattr(_L, fn.__name__, fn)  # surface on static.layers like the ref
    return fn


def _xo(op_type, x, attrs=None, dtype=None, shape=None, out_slot="Out",
        in_slot="X"):
    out = _out(dtype or x.dtype, x.shape if shape is None else shape)
    _append(op_type, {in_slot: [x.name]}, {out_slot: [out.name]},
            attrs or {})
    return out


# -- logicals / reductions ---------------------------------------------------

def _logical(op_type):
    def fn(x, y=None, name=None):
        ins = {"X": [x.name]}
        if y is not None:
            ins["Y"] = [y.name]
        out = _out("bool", x.shape)
        _append(op_type, ins, {"Out": [out.name]})
        return out

    fn.__name__ = op_type
    return _export(fn)


logical_and = _logical("logical_and")
logical_or = _logical("logical_or")
logical_xor = _logical("logical_xor")
logical_not = _logical("logical_not")


def _reduce(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        if dim is None:
            shape = () if not keep_dim else (1,) * input.ndim
        else:
            dims = [dim] if isinstance(dim, int) else list(dim)
            dims = [d % input.ndim for d in dims]
            shape = tuple(
                (1 if keep_dim else None) if i in dims else s
                for i, s in enumerate(input.shape))
            shape = tuple(s for s in shape if s is not None)
        out = _out("bool" if op_type in ("reduce_all", "reduce_any")
                   else input.dtype, shape)
        _append(op_type, {"X": [input.name]}, {"Out": [out.name]},
                {"dim": dim, "keep_dim": keep_dim,
                 "reduce_all": dim is None})
        return out

    fn.__name__ = op_type
    return _export(fn)


reduce_max = _reduce("reduce_max")
reduce_min = _reduce("reduce_min")
reduce_prod = _reduce("reduce_prod")
reduce_all = _reduce("reduce_all")
reduce_any = _reduce("reduce_any")


# -- creation ----------------------------------------------------------------

@_export
def ones(shape, dtype="float32", name=None):
    return _L.fill_constant(shape, dtype, 1.0)


@_export
def zeros(shape, dtype="float32", name=None):
    return _L.fill_constant(shape, dtype, 0.0)


@_export
def ones_like(x, name=None):
    out = _out(x.dtype, x.shape)
    _append("fill_any_like", {"X": [x.name]}, {"Out": [out.name]},
            {"value": 1.0})
    return out


@_export
def zeros_like(x, name=None):
    return _xo("fill_zeros_like", x)


@_export
def eye(num_rows, num_columns=None, dtype="float32", name=None):
    n = num_columns or num_rows
    vals = np.eye(num_rows, n).reshape(-1).tolist()
    out = _out(dtype, (num_rows, n))
    _append("assign_value", {}, {"Out": [out.name]},
            {"shape": (num_rows, n), "dtype": dtype, "fp32_values": vals})
    return out


@_export
def diag(diagonal, name=None):
    n = diagonal.shape[0]
    out = _out(diagonal.dtype, (n, n))
    _append("diag", {"Diagonal": [diagonal.name]}, {"Out": [out.name]})
    return out


@_export
def create_tensor(dtype="float32", name=None, persistable=False):
    from .framework import default_main_program

    return default_main_program().current_block().create_var(
        name=name, dtype=dtype, persistable=persistable)


@_export
def create_global_var(shape, value, dtype="float32", persistable=False,
                      force_cpu=False, name=None):
    from ..nn import initializer as I
    from .layers import create_parameter

    del force_cpu
    return create_parameter(tuple(shape), dtype, name=name,
                            default_initializer=I.Constant(value),
                            trainable=False)


@_export
def gaussian_random(shape, mean=0.0, std=1.0, dtype="float32", seed=0,
                    name=None):
    out = _out(dtype, tuple(shape))
    _append("gaussian_random", {}, {"Out": [out.name]},
            {"shape": tuple(shape), "mean": mean, "std": std,
             "dtype": dtype, "seed": seed})
    return out


@_export
def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    out = _out(dtype, tuple(shape))
    _append("uniform_random", {}, {"Out": [out.name]},
            {"shape": tuple(shape), "min": min, "max": max, "dtype": dtype,
             "seed": seed})
    return out


@_export
def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return gaussian_random(shape, mean, std, dtype)


@_export
def uniform_random_batch_size_like(input, shape, min=-1.0, max=1.0,
                                   input_dim_idx=0, output_dim_idx=0,
                                   dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return uniform_random(shape, dtype, min, max)


@_export
def linspace(start, stop, num, dtype="float32", name=None):
    out = _out(dtype, (int(num),))
    ins = {}
    if isinstance(start, Variable):
        ins["Start"] = [start.name]
    if isinstance(stop, Variable):
        ins["Stop"] = [stop.name]
    attrs = {"num": int(num), "dtype": dtype}
    if not isinstance(start, Variable):
        s = _L.fill_constant((1,), dtype, float(start))
        ins["Start"] = [s.name]
    if not isinstance(stop, Variable):
        e = _L.fill_constant((1,), dtype, float(stop))
        ins["Stop"] = [e.name]
    _append("linspace", ins, {"Out": [out.name]}, attrs)
    return out


# -- manipulation ------------------------------------------------------------

@_export
def reverse(x, axis, name=None):
    return _xo("reverse", x, {"axis": [axis] if isinstance(axis, int)
                              else list(axis)})


@_export
def unbind(input, axis=0, name=None):
    ax = axis % input.ndim
    n = input.shape[ax]
    shape = tuple(s for i, s in enumerate(input.shape) if i != ax)
    outs = [_out(input.dtype, shape) for _ in range(n)]
    _append("unbind", {"X": [input.name]},
            {"Out": [o.name for o in outs]}, {"axis": axis})
    return outs


@_export
def unstack(x, axis=0, num=None, name=None):
    ax = axis % x.ndim
    n = num or x.shape[ax]
    shape = tuple(s for i, s in enumerate(x.shape) if i != ax)
    outs = [_out(x.dtype, shape) for _ in range(n)]
    _append("unstack", {"X": [x.name]}, {"Y": [o.name for o in outs]},
            {"axis": axis, "num": n})
    return outs


@_export
def strided_slice(input, axes, starts, ends, strides, name=None):
    shape = list(input.shape)
    for ax, s, e, st in zip(axes, starts, ends, strides):
        if shape[ax] >= 0:
            n = shape[ax]
            # normalize negative indices the way the slice executes
            s_ = s + n if s < 0 else s
            e_ = e + n if e < 0 else e
            if st > 0:
                shape[ax] = max(0, -(-(min(e_, n) - min(max(s_, 0), n))
                                     // st))
            else:
                shape[ax] = max(0, -(-(min(s_, n - 1) - max(e_, -1))
                                     // -st))
    out = _out(input.dtype, tuple(shape))
    _append("strided_slice", {"Input": [input.name]}, {"Out": [out.name]},
            {"axes": list(axes), "starts": list(starts),
             "ends": list(ends), "strides": list(strides)})
    return out


@_export
def crop_tensor(x, shape=None, offsets=None, name=None):
    out = _out(x.dtype, tuple(shape))
    _append("crop_tensor", {"X": [x.name]}, {"Out": [out.name]},
            {"shape": list(shape), "offsets": list(offsets or [])})
    return out


@_export
def crop(x, shape=None, offsets=None, name=None):
    return crop_tensor(x, shape, offsets, name)


@_export
def expand_as(x, target_tensor, name=None):
    out = _out(x.dtype, target_tensor.shape)
    _append("expand_as", {"X": [x.name],
                          "target_tensor": [target_tensor.name]},
            {"Out": [out.name]})
    return out


@_export
def pad_constant_like(x, y, pad_value=0.0, name=None):
    out = _out(y.dtype, x.shape)
    _append("pad_constant_like", {"X": [x.name], "Y": [y.name]},
            {"Out": [out.name]}, {"pad_value": pad_value})
    return out


@_export
def scatter_nd_add(ref, index, updates, name=None):
    out = _out(ref.dtype, ref.shape)
    _append("scatter_nd_add",
            {"X": [ref.name], "Index": [index.name],
             "Updates": [updates.name]},
            {"Out": [out.name]})
    return out


@_export
def scatter_nd(index, updates, shape, name=None):
    z = zeros(shape, updates.dtype)
    return scatter_nd_add(z, index, updates)


@_export
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _xo("shard_index", input,
               {"index_num": index_num, "nshards": nshards,
                "shard_id": shard_id, "ignore_value": ignore_value})


@_export
def gather_tree(ids, parents):
    out = _out(ids.dtype, ids.shape)
    _append("gather_tree", {"Ids": [ids.name], "Parents": [parents.name]},
            {"Out": [out.name]})
    return out


@_export
def sum(x, name=None):
    """fluid.layers.sum over Variables; attaching this to the layers
    module shadows the builtin for code inside layers.py, so non-Variable
    inputs dispatch to builtins.sum (generators/int lists keep working)."""
    import builtins

    xs = x if isinstance(x, (list, tuple)) else [x]
    if not xs or not isinstance(xs[0], Variable):
        return builtins.sum(x)
    out = _out(xs[0].dtype, xs[0].shape)
    _append("sum", {"X": [v.name for v in xs]}, {"Out": [out.name]})
    return out


@_export
def sums(input, out=None):
    res = sum(input)
    if out is not None:
        _append("assign", {"X": [res.name]}, {"Out": [out.name]})
        return out
    return res


@_export
def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    # the runtime rule reshapes back to x.shape[:xd] + y.shape[yd:]
    out = _out(x.dtype,
               tuple(x.shape[:x_num_col_dims]) + tuple(
                   y.shape[y_num_col_dims:]))
    _append("mul", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]},
            {"x_num_col_dims": x_num_col_dims,
             "y_num_col_dims": y_num_col_dims})
    return out


@_export
def rank(input):
    return _L.fill_constant((1,), "int32", input.ndim)


@_export
def size(input):
    out = _out("int64", ())
    _append("size", {"Input": [input.name]}, {"Out": [out.name]})
    return out


@_export
def clip_by_norm(x, max_norm, name=None):
    return _xo("clip_by_norm", x, {"max_norm": max_norm})


@_export
def isfinite(x, name=None):
    out = _out("bool", (1,))
    _append("isfinite", {"X": [x.name]}, {"Out": [out.name]})
    return out


@_export
def has_inf(x):
    out = _out("bool", x.shape)  # isinf_v2 is elementwise
    _append("isinf_v2", {"X": [x.name]}, {"Out": [out.name]})
    return reduce_any(out)


@_export
def has_nan(x):
    out = _out("bool", x.shape)
    _append("isnan_v2", {"X": [x.name]}, {"Out": [out.name]})
    return reduce_any(out)


# -- losses / misc -----------------------------------------------------------

@_export
def bpr_loss(input, label, name=None):
    out = _out(input.dtype, (input.shape[0], 1))
    _append("bpr_loss", {"X": [input.name], "Label": [label.name]},
            {"Out": [out.name]})
    return out


@_export
def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    from .layers import create_parameter
    from ..nn import initializer as I

    centers = create_parameter((num_classes, input.shape[-1]), input.dtype,
                               default_initializer=I.Constant(0.0),
                               trainable=False)
    rate = _L.fill_constant((1,), "float32", alpha)
    loss = _out(input.dtype, (input.shape[0], 1))
    diff = _out(input.dtype, input.shape)
    _append("center_loss",
            {"X": [input.name], "Label": [label.name],
             "Centers": [centers.name], "CenterUpdateRate": [rate.name]},
            {"Loss": [loss.name], "SampleCenterDiff": [diff.name],
             "CentersOut": [centers.name]},
            {"need_update": update_center})
    return loss


@_export
def margin_rank_loss(label, left, right, margin=0.1, name=None):
    out = _out(left.dtype, left.shape)
    _append("margin_rank_loss",
            {"Label": [label.name], "X1": [left.name], "X2": [right.name]},
            {"Out": [out.name]}, {"margin": margin})
    return out


@_export
def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    out = _out(input.dtype, (input.shape[0], 1))
    _append("teacher_student_sigmoid_loss",
            {"X": [input.name], "Label": [label.name]},
            {"Y": [out.name]},
            {"soft_max_up_bound": soft_max_up_bound,
             "soft_max_lower_bound": soft_max_lower_bound})
    return out


@_export
def cross_entropy2(input, label, ignore_index=-100):
    y = _out(input.dtype, tuple(input.shape[:-1]) + (1,))
    match = _out(input.dtype, tuple(input.shape[:-1]) + (1,))
    xshape = _out(input.dtype, input.shape)
    _append("cross_entropy2", {"X": [input.name], "Label": [label.name]},
            {"Y": [y.name], "MatchX": [match.name],
             "XShape": [xshape.name]},
            {"ignore_index": ignore_index})
    return y


@_export
def dice_loss(input, label, epsilon=1e-5):
    """ref fluid/layers/nn.py dice_loss: one_hot the int labels, dice per
    SAMPLE over dims 1.., then mean — the reference composition exactly."""
    # v1 one_hot semantics: the trailing size-1 label dim is replaced by
    # the class dim (label [N1..ND-1,1] -> [N1..ND-1,classes])
    depth = input.shape[-1]
    label_oh = _out("float32", tuple(label.shape[:-1]) + (depth,))
    _append("one_hot", {"X": [label.name]}, {"Out": [label_oh.name]},
            {"depth": depth})
    rd = list(range(1, input.ndim))
    inse = _L.reduce_sum(_L.elementwise_mul(input, label_oh), dim=rd)
    denom = _L.elementwise_add(_L.reduce_sum(input, dim=rd),
                               _L.reduce_sum(label_oh, dim=rd))
    two = _L.fill_constant((), "float32", 2.0)
    one = _L.fill_constant((), "float32", 1.0)
    eps = _L.fill_constant((), "float32", epsilon)
    score = _L.elementwise_sub(one, _L.elementwise_div(
        _L.elementwise_mul(inse, two), _L.elementwise_add(denom, eps)))
    return _L.mean(score)


@_export
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """ref fluid/layers/loss.py npair_loss (NIPS'16 N-pair): soft-label CE
    over the anchor·positiveᵀ similarity matrix, where the soft target is
    the row-normalized label-EQUALITY matrix; plus Beta*l2_reg * mean
    per-sample embedding norms — the reference composition exactly."""
    B = labels.shape[0]
    lab = _L.reshape(labels, (B, 1))
    expanded = _out(lab.dtype, (B, B))
    _append("expand_v2", {"X": [lab.name]}, {"Out": [expanded.name]},
            {"shape": (B, B)})
    eq_b = _out("bool", (B, B))
    _append("equal", {"X": [expanded.name],
                      "Y": [_L.transpose(expanded, [1, 0]).name]},
            {"Out": [eq_b.name]})
    eq = _L.cast(eq_b, "float32")
    target = _L.elementwise_div(
        eq, _L.reduce_sum(eq, dim=1, keep_dim=True))
    l2 = _L.elementwise_add(
        _L.mean(_L.reduce_sum(_L.elementwise_mul(anchor, anchor), dim=1)),
        _L.mean(_L.reduce_sum(_L.elementwise_mul(positive, positive),
                              dim=1)))
    reg = _L.fill_constant((), "float32", 0.25 * l2_reg)
    sim = _L.matmul(anchor, positive, transpose_y=True)
    ce = _L.softmax_with_cross_entropy(sim, target, soft_label=True)
    celoss = _L.mean(_L.reduce_sum(_L.elementwise_mul(target, ce), dim=0))
    return _L.elementwise_add(celoss, _L.elementwise_mul(reg, l2))


@_export
def fsp_matrix(x, y):
    out = _out(x.dtype, (x.shape[0], x.shape[1], y.shape[1]))
    _append("fsp", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]})
    return out


@_export
def iou_similarity(x, y, box_normalized=True, name=None):
    out = _out(x.dtype, (x.shape[0], y.shape[0]))
    _append("iou_similarity", {"X": [x.name], "Y": [y.name]},
            {"Out": [out.name]}, {"box_normalized": box_normalized})
    return out


@_export
def box_clip(input, im_info, name=None):
    out = _out(input.dtype, input.shape)
    _append("box_clip", {"Input": [input.name], "ImInfo": [im_info.name]},
            {"Output": [out.name]})
    return out


@_export
def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    out = _out(input.dtype, (rois.shape[0], input.shape[1], pooled_height,
                             pooled_width))
    ins = {"X": [input.name], "ROIs": [rois.name]}
    if batch_roi_nums is not None:
        ins["BatchRoINums"] = [batch_roi_nums.name]
    _append("prroi_pool", ins, {"Out": [out.name]},
            {"spatial_scale": spatial_scale, "pooled_height": pooled_height,
             "pooled_width": pooled_width})
    return out


@_export
def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    out = _out(ins.dtype, ins.shape)
    w = _out(ins.dtype, (ins.shape[0], 1))
    idx = _out("int32", (ins.shape[0], 2))
    _append("filter_by_instag",
            {"Ins": [ins.name], "Ins_tag": [ins_tag.name],
             "Filter_tag": [filter_tag.name]},
            {"Out": [out.name], "LossWeight": [w.name],
             "IndexMap": [idx.name]},
            {"is_lod": is_lod, "out_val_if_empty": out_val_if_empty})
    return out, w


@_export
def data_norm(input, name=None, epsilon=1e-4):
    from .layers import create_parameter
    from ..nn import initializer as I

    c = input.shape[-1]
    bs = create_parameter((c,), "float32", default_initializer=I.Constant(
        1e4), trainable=False, name=f"{name}.batch_size" if name else None)
    bsum = create_parameter((c,), "float32",
                            default_initializer=I.Constant(0.0),
                            trainable=False)
    bsq = create_parameter((c,), "float32",
                           default_initializer=I.Constant(1e4),
                           trainable=False)
    y = _out(input.dtype, input.shape)
    _append("data_norm",
            {"X": [input.name], "BatchSize": [bs.name],
             "BatchSum": [bsum.name], "BatchSquareSum": [bsq.name]},
            {"Y": [y.name], "BatchSizeOut": [bs.name],
             "BatchSumOut": [bsum.name], "BatchSquareSumOut": [bsq.name]},
            {"epsilon": epsilon})
    return y


@_export
def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    ks = _pair(filter_size)
    st = _pair(stride)
    pd = padding if isinstance(padding, (list, tuple)) else [padding] * 4
    n, c, h, w = input.shape
    oh = -1 if h < 0 else (h + pd[0] + pd[2] - ks[0]) // st[0] + 1
    ow = -1 if w < 0 else (w + pd[1] + pd[3] - ks[1]) // st[1] + 1
    rows = -1 if (oh < 0 or ow < 0 or n < 0) else n * oh * ow
    out = _out(input.dtype, (rows, c * ks[0] * ks[1]))
    _append("im2sequence", {"X": [input.name]}, {"Out": [out.name]},
            {"kernels": list(ks), "strides": list(st), "paddings": list(pd)})
    return out


@_export
def inplace_abn(input, act="identity", is_test=False, momentum=0.9,
                epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    from .layers import create_parameter
    from ..nn import initializer as I

    c = input.shape[1]
    scale = create_parameter((c,), input.dtype, attr=param_attr,
                             default_initializer=I.Constant(1.0))
    bias = create_parameter((c,), input.dtype, attr=bias_attr,
                            default_initializer=I.Constant(0.0))
    mean = create_parameter((c,), input.dtype,
                            default_initializer=I.Constant(0.0),
                            trainable=False)
    var = create_parameter((c,), input.dtype,
                           default_initializer=I.Constant(1.0),
                           trainable=False)
    y = _out(input.dtype, input.shape)
    _append("inplace_abn",
            {"X": [input.name], "Scale": [scale.name], "Bias": [bias.name],
             "Mean": [mean.name], "Variance": [var.name]},
            {"Y": [y.name], "MeanOut": [mean.name],
             "VarianceOut": [var.name]},
            {"activation": act, "is_test": is_test, "momentum": momentum,
             "epsilon": epsilon})
    return y


@_export
def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from .layers import create_parameter
    from ..nn import initializer as I

    u = create_parameter((weight.shape[dim],), "float32",
                         default_initializer=I.Constant(1.0),
                         trainable=False)
    out = _out(weight.dtype, weight.shape)
    _append("spectral_norm", {"Weight": [weight.name], "U": [u.name]},
            {"Out": [out.name]},
            {"dim": dim, "power_iters": power_iters, "eps": eps})
    return out


@_export
def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       seed=0):
    """ref loss.py sampled_softmax_with_cross_entropy — sample_logits +
    softmax CE over the (1+num_samples)-way sampled problem."""
    B = logits.shape[0]
    sampled = _out(logits.dtype, (B, 1 + num_samples))
    samples = _out("int32", (B, 1 + num_samples))
    slabels = _out("int32", (B,))
    _append("sample_logits",
            {"Logits": [logits.name], "Labels": [label.name]},
            {"SampledLogits": [sampled.name], "Samples": [samples.name],
             "SampledLabels": [slabels.name]},
            {"num_samples": num_samples, "seed": seed})
    zero = _L.fill_constant((B, 1), "int64", 0)  # true label is column 0
    return _L.softmax_with_cross_entropy(sampled, zero)


@_export
def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """ref nn.py add_position_encoding: alpha*x + beta*sincos — the
    position table is a build-time constant."""
    b, t, d = input.shape
    pos = np.arange(t)[:, None]
    div = np.exp(np.arange(0, d, 2) * -(np.log(10000.0) / d))
    table = np.zeros((t, d), np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div[: d // 2])
    tab = _out(input.dtype, (1, t, d))
    _append("assign_value", {}, {"Out": [tab.name]},
            {"shape": (1, t, d), "dtype": "float32",
             "fp32_values": table.reshape(-1).tolist()})
    a = _L.fill_constant((), "float32", alpha)
    bta = _L.fill_constant((), "float32", beta)
    return _L.elementwise_add(
        _L.elementwise_mul(input, a),
        _L.elementwise_mul(tab, bta))


@_export
def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 align_corners=True, name=None):
    method = resample.lower()
    if out_shape is None:
        h, w = input.shape[2], input.shape[3]
        out_shape = (int(h * scale), int(w * scale))
    return _L._resize(input, out_shape, method, align_corners)


@_export
def resize_linear(input, out_shape, align_corners=True, name=None):
    out = _out(input.dtype,
               (input.shape[0], input.shape[1], out_shape[0]))
    _append("linear_interp", {"X": [input.name]}, {"Out": [out.name]},
            {"out_w": out_shape[0], "align_corners": align_corners})
    return out


@_export
def resize_trilinear(input, out_shape, align_corners=True, name=None):
    out = _out(input.dtype,
               (input.shape[0], input.shape[1]) + tuple(out_shape))
    _append("trilinear_interp", {"X": [input.name]}, {"Out": [out.name]},
            {"out_d": out_shape[0], "out_h": out_shape[1],
             "out_w": out_shape[2], "align_corners": align_corners})
    return out


@_export
def get_tensor_from_selected_rows(x, name=None):
    return _xo("get_tensor_from_selected_rows", x)


@_export
def merge_selected_rows(x, name=None):
    return _xo("merge_selected_rows", x)


@_export
def lod_reset(x, y=None, target_lod=None):
    return _xo("lod_reset", x)


@_export
def lod_append(x, level):
    del level  # dense layout carries no LoD levels
    return _xo("lod_reset", x)


@_export
def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref py_func_op: the callable registers into the op registry keyed
    by id (static/ops_tail2.register_py_func)."""
    from . import ops_tail2

    fid = id(func)
    ops_tail2.register_py_func(fid, func)
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    _append("py_func", {"X": [v.name for v in xs]},
            {"Out": [o.name for o in outs]},
            {"forward_callable_id": fid,
             "out_shapes": [tuple(o.shape) for o in outs],
             "out_dtypes": [str(np.dtype(o.dtype)) for o in outs]})
    return out


@_export
def save(x, file_path, overwrite=True):
    _append("save", {"X": [x.name]}, {}, {"file_path": file_path,
                                          "overwrite": overwrite})


@_export
def save_combine(x, file_path, overwrite=True):
    xs = x if isinstance(x, (list, tuple)) else [x]
    _append("save_combine", {"X": [v.name for v in xs]}, {},
            {"file_path": file_path, "overwrite": overwrite})


@_export
def load_combine(out, file_path):
    outs = out if isinstance(out, (list, tuple)) else [out]
    _append("load_combine", {}, {"Out": [o.name for o in outs]},
            {"file_path": file_path})
    return out


# activation-style wrappers over batch-registered act ops
def _act_layer(op_type, **default_attrs):
    def fn(x, name=None, **kw):
        attrs = dict(default_attrs)
        attrs.update(kw)
        return _xo(op_type, x, attrs)

    fn.__name__ = op_type
    return _export(fn)


soft_relu = _act_layer("soft_relu", threshold=40.0)
brelu = _act_layer("brelu", t_min=0.0, t_max=24.0)
stanh = _act_layer("stanh", scale_a=0.67, scale_b=1.7159)


@_export
def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """ref fluid/layers/nn.py chunk_eval -> chunk_eval op.  Returns the
    reference's six outputs (precision, recall, f1, n_infer, n_label,
    n_correct)."""
    outs = {
        "Precision": _out("float32", ()),
        "Recall": _out("float32", ()),
        "F1-Score": _out("float32", ()),
        "NumInferChunks": _out("int64", ()),
        "NumLabelChunks": _out("int64", ()),
        "NumCorrectChunks": _out("int64", ()),
    }
    ins = {"Inference": [input.name], "Label": [label.name]}
    if seq_length is not None:
        ins["SeqLength"] = [seq_length.name]
    _append("chunk_eval", ins, {k: [v.name] for k, v in outs.items()},
            {"chunk_scheme": chunk_scheme,
             "num_chunk_types": num_chunk_types,
             "excluded_chunk_types": list(excluded_chunk_types or [])})
    return (outs["Precision"], outs["Recall"], outs["F1-Score"],
            outs["NumInferChunks"], outs["NumLabelChunks"],
            outs["NumCorrectChunks"])
