"""Static-graph Executor: lower a Program to one jitted XLA computation.

Reference parity: `Executor::Run` (paddle/fluid/framework/executor.cc:180):
Prepare builds the op list (:378), RunPreparedContext interprets it
sequentially per op with kernel dispatch + GC (:476); python side
fluid/executor.py:474/:915 with feed/fetch injection and a prepared-context
cache (:1272).

TPU-native design (SURVEY.md §7 step 3): the op loop becomes a *trace* — the
Executor walks the block once inside jax.jit, invoking each op's lowering
rule to build a single fused XLA program `(feeds, donated_state,
carried_state, step) -> (fetches, new_state)`, cached by (program version,
feed signature, fetch list, donation mode).  State = every persistable
variable (parameters, optimizer slots, BN statistics, LR); the "write-back"
the reference does through Scope mutation becomes the functional state
round-trip — and with the `donate_state` flag on (default), the round-trip
is a buffer donation: XLA aliases the updated state onto the input buffers
and the Python-side write-back is a pointer swap, not a copy.  The PRNG
base key derives inside the compiled step from a per-entry seed and the
scalar `step` arg, so steady-state dispatch mints no host keys.  The `backward_region` pseudo-op (see
backward.py) differentiates a replay of the forward prefix; per-op
`fold_in`-derived PRNG scopes make the replay's random draws (dropout)
bit-identical to the primal's, so AD is exact.
"""
from __future__ import annotations

import contextlib
import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..utils import ledger as _ledger
from ..utils import monitor as _monitor
from ..utils import profiler as _profiler
from ..utils import trace as _trace
from . import ops as _ops  # registers lowerings
from .backward import GRAD_SUFFIX
from .framework import Program, Variable, default_main_program
from .registry import get_lowering

__all__ = ["Scope", "global_scope", "scope_guard", "Executor"]

# Test hook: force donation even where _donation_async_safe() says the
# platform serializes it (tests/test_fastpath.py covers the donation guard
# and parity paths on the CPU-only CI this way).
_FORCE_DONATION = False
_DONATE_PLATFORM_OK: Optional[bool] = None


def _donation_async_safe() -> bool:
    """Whether buffer donation keeps dispatch asynchronous on this backend.

    XLA:CPU executes a computation with donated inputs synchronously — the
    dispatch call blocks for the whole step, even when every donated buffer
    is already materialized (measured on jaxlib CPU: donated dispatch ==
    full step time, undonated dispatch ~10us).  Donating there would
    serialize the steady-state pipeline the fast path exists to build, so
    with `donate_state` on, CPU keeps device-resident state + async
    dispatch but skips `donate_argnums`; accelerator backends (tpu, gpu,
    and tunneled PJRT plugins) alias the buffers without giving up async
    dispatch and donate for real — hence exclude-cpu, not include-known."""
    global _DONATE_PLATFORM_OK
    if _FORCE_DONATION:
        return True
    if _DONATE_PLATFORM_OK is None:
        _DONATE_PLATFORM_OK = jax.default_backend() != "cpu"
    return _DONATE_PLATFORM_OK


def _guard_stale(name: str, value):
    """Donation-safety guard: a scope entry whose buffer was donated into a
    compiled step (donate_state fast path) and consumed by XLA must fail
    legibly on read, not with XLA's 'Array has been deleted' crash.  Live
    values (the run scope's write-back) pass through untouched."""
    if isinstance(value, jax.Array) and value.is_deleted():
        from ..core.errors import StaleScopeValueError

        raise StaleScopeValueError(
            f"variable {name!r} holds a stale buffer: it was donated into a "
            "compiled Executor step (flag donate_state=1) and its device "
            "memory has been reused for the updated state.  Read the value "
            "from the scope the Executor ran on (the step's write-back "
            "replaced it there), or set PDTPU_FLAGS_donate_state=0 to "
            "restore copy semantics.")
    return value


class Scope:
    """Name -> host array store for persistables (ref framework/scope.h:46).

    Hierarchical like the reference: `new_scope()` creates a child whose
    lookups fall through to ancestors (the pattern the reference's
    per-thread/per-section scopes rely on); writes always land in the scope
    they are issued on (kid scopes never clobber the parent)."""

    def __init__(self, parent: "Optional[Scope]" = None):
        self._vars: Dict[str, Any] = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def new_scope(self) -> "Scope":
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    @property
    def parent(self) -> "Optional[Scope]":
        return self._parent

    def find_var(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return _guard_stale(name, s._vars[name])
            s = s._parent
        return None

    def local_var(self, name: str):
        """Lookup without falling through to ancestors."""
        return _guard_stale(name, self._vars.get(name))

    def var(self, name: str):
        return self._vars.setdefault(name, None)

    def set(self, name: str, value):
        self._vars[name] = value

    def keys(self):
        return self._vars.keys()

    def drop_kids(self):
        """ref Scope::DropKids."""
        self._kids.clear()

    def drop(self):
        self._vars.clear()
        self._kids.clear()


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


class scope_guard:
    """ref fluid/executor.py scope_guard."""

    def __init__(self, scope: Scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack.append(self.scope)
        return self.scope

    def __exit__(self, *exc):
        _scope_stack.pop()


def _run_op_traced(op, env, base_key, salt):
    """Execute one op's lowering under a per-op PRNG scope (deterministic
    replay for the backward region).  `salt` is unique per (block, op index)
    so sub-block randomness is trace-stable too."""
    lowering = get_lowering(op.type)
    ins = {slot: [env[n] for n in names] if names else []
           for slot, names in op.inputs.items()}
    with _random.rng_scope(jax.random.fold_in(base_key, salt)):
        outs = lowering(ins, op.attrs, op)
    for slot, names in op.outputs.items():
        vals = outs.get(slot, [])
        for name, val in zip(names, vals):
            env[name] = val


def _op_salt(block_idx: int, op_idx: int) -> int:
    return block_idx * 65536 + op_idx


def _xprof_scope_name(op_type: str, block_idx: int, op_idx: int) -> str:
    from ..utils.xprof import op_scope_name

    return op_scope_name(op_type, block_idx, op_idx)


def _trace_ops(program: Program, block_idx: int, ops, env, base_key,
               frozen=None):
    """Trace a list of ops (any block) with control-flow dispatch.

    ``frozen`` maps names to values that must stay bound to those exact
    (traced) values even when an op writes them — the backward replay
    injects differentiated intermediates this way, so ∂loss/∂v means "v as
    consumed downstream" rather than being recomputed by its producer
    (reference backward.py gradients() semantics).

    With the ``xprof_scopes`` flag on, every op (control-flow included, so
    sub-block ops nest under their parent's scope) traces inside
    ``jax.named_scope("<op_type>.b<block>.i<idx>")`` — op identity lands in
    optimized-HLO instruction metadata, survives fusion and AD, and
    utils/xprof.py joins per-instruction flops/bytes back to it.  Scopes
    are metadata-only: same HLO computation, same compile-cache key, same
    retrace behavior (pinned by tests/test_xprof.py)."""
    from ..core import flags as _flags

    scoped = bool(_flags.get_flag("xprof_scopes"))
    for idx, op in enumerate(ops):
        if op.type in ("feed", "fetch"):
            continue
        ctx = (jax.named_scope(_xprof_scope_name(op.type, block_idx, idx))
               if scoped else contextlib.nullcontext())
        with ctx:
            if op.type == "backward_region":
                _lower_backward(program, block_idx, ops, idx, env, base_key)
            elif op.type == "conditional_block":
                _lower_cond(program, op, env, base_key)
            elif op.type == "while":
                _lower_while(program, op, env, base_key)
            elif op.type == "static_rnn":
                _lower_static_rnn(program, op, env, base_key)
            else:
                salt = op.rng_salt if getattr(op, "rng_salt", None) \
                    is not None else _op_salt(block_idx, idx)
                _run_op_traced(op, env, base_key, salt)
        if frozen:
            env.update(frozen)


def _trace_block(program: Program, env: Dict[str, Any], base_key):
    """Walk block 0 building the computation into env."""
    _trace_ops(program, 0, program.global_block().ops, env, base_key)


def _arrays_only(env: Dict[str, Any]) -> Dict[str, Any]:
    """The sub-block closure snapshot passed through lax.cond/while must be a
    pytree of arrays."""
    out = {}
    for k, v in env.items():
        if hasattr(v, "dtype") or isinstance(v, (int, float, bool)):
            out[k] = jnp.asarray(v)
    return out


def _lower_cond(program, op, env, base_key):
    """conditional_block → jax.lax.cond over an env snapshot (ref
    operators/controlflow/conditional_block_op.cc — scoped sub-block run)."""
    tb = program.blocks[op.attrs["true_block"]]
    fb = program.blocks[op.attrs["false_block"]]
    pred = jnp.reshape(env[op.inputs["Cond"][0]], ()).astype(bool)
    snapshot = _arrays_only(env)

    def branch(block, out_names):
        def fn(captured):
            env2 = dict(captured)
            _trace_ops(program, block.idx, block.ops, env2, base_key)
            return tuple(env2[n] for n in out_names)
        return fn

    outs = jax.lax.cond(pred,
                        branch(tb, op.attrs["true_outs"]),
                        branch(fb, op.attrs["false_outs"]),
                        snapshot)
    for name, val in zip(op.outputs["Out"], outs):
        env[name] = val


def _lower_while(program, op, env, base_key):
    """while → jax.lax.while_loop with loop_vars as the carry (ref
    operators/controlflow/while_op.cc — here the carried Scope is explicit)."""
    cb = program.blocks[op.attrs["cond_block"]]
    bb = program.blocks[op.attrs["body_block"]]
    loop_names = op.inputs["X"]
    body_outs = op.attrs["body_outs"]
    cond_out = op.attrs["cond_out"]
    outer = _arrays_only(env)
    carry0 = tuple(jnp.asarray(env[n]) for n in loop_names)

    def with_carry(carry):
        env2 = dict(outer)
        env2.update(zip(loop_names, carry))
        return env2

    def cond_fun(carry):
        env2 = with_carry(carry)
        _trace_ops(program, cb.idx, cb.ops, env2, base_key)
        return jnp.reshape(env2[cond_out], ()).astype(bool)

    def body_fun(carry):
        env2 = with_carry(carry)
        _trace_ops(program, bb.idx, bb.ops, env2, base_key)
        return tuple(jnp.asarray(env2[n], carry[i].dtype)
                     for i, n in enumerate(body_outs))

    final = jax.lax.while_loop(cond_fun, body_fun, carry0)
    for name, val in zip(op.outputs["Out"], final):
        env[name] = val


def _lower_static_rnn(program, op, env, base_key):
    """static_rnn → jax.lax.scan over the time-major leading axis (ref
    operators/recurrent_op.cc; AD-of-scan replaces RecurrentGradOp)."""
    blk = program.blocks[op.attrs["rnn_block"]]
    step_in = op.attrs["step_in_names"]
    mem_names = op.attrs["mem_names"]
    mem_next = op.attrs["mem_next"]
    out_names = op.attrs["out_names"]
    outer = _arrays_only(env)
    seqs = tuple(jnp.asarray(env[n]) for n in op.inputs["X"])
    inits = tuple(jnp.asarray(env[n]) for n in op.inputs["Init"])

    def body(carry, xs_t):
        env2 = dict(outer)
        env2.update(zip(mem_names, carry))
        env2.update(zip(step_in, xs_t))
        _trace_ops(program, blk.idx, blk.ops, env2, base_key)
        new_carry = tuple(jnp.asarray(env2[n], carry[i].dtype)
                          for i, n in enumerate(mem_next))
        outs_t = tuple(env2[n] for n in out_names)
        return new_carry, outs_t

    _, stacked = jax.lax.scan(body, inits, seqs)
    for name, val in zip(op.outputs["Out"], stacked):
        env[name] = val


def _lower_backward(program, block_idx, ops, bw_idx, env, base_key):
    op = ops[bw_idx]
    loss_names = op.inputs["Loss"]
    param_names = op.inputs["Params"]
    grad_names = op.outputs["Grads"]
    # the replay closes over the *initial* bindings of everything except the
    # differentiated params — snapshot env entries that ops 0..bw_idx read
    init_env = dict(env)

    def replay(param_values: Dict[str, Any]):
        env2 = dict(init_env)
        env2.update(param_values)
        # freeze the differentiated names: a producer op in the replay must
        # not overwrite an injected intermediate (gradients()-wrt-
        # intermediate semantics, ref backward.py:1795)
        _trace_ops(program, block_idx, ops[:bw_idx], env2, base_key,
                   frozen=param_values)
        total = 0.0
        for ln in loss_names:
            total = total + jnp.sum(env2[ln].astype(jnp.float32))
        return total

    pv = {n: env[n] for n in param_names}
    grads = jax.grad(replay)(pv)
    for pname, gname in zip(param_names, grad_names):
        env[gname] = grads[pname]


# -- telemetry (utils/monitor.py; SURVEY §5.1) -------------------------------
# Registered at import so metricsdump lists them even before any run; every
# mutation is gated on the `metrics` flag inside the metric objects.
_m_cache_hit = _monitor.counter(
    "executor.cache_hit", "Executor.run compile-cache hits.")
_m_cache_miss = _monitor.counter(
    "executor.cache_miss", "Executor.run compile-cache misses (trace+compile).")
_m_compile_ms = _monitor.histogram(
    "executor.compile_time_ms",
    "Wall time of a cache-miss step: trace + XLA compile + first run (ms).")
_m_dispatch_ms = _monitor.histogram(
    "executor.dispatch_time_ms",
    "Host time a cache-hit (steady-state) Executor.run spends DISPATCHING "
    "the compiled step (ms).  Under async dispatch this returns before the "
    "device finishes — it measures the Python rim, not the device step; see "
    "executor.step_time_ms for the blocked wall time.")
_m_step_ms = _monitor.histogram(
    "executor.step_time_ms",
    "True steady-state step wall time (ms): dispatch plus blocking on one "
    "fetch until the device finishes.  Recorded only while the `metrics` "
    "flag is on — the block IS the cost of measuring; set "
    "PDTPU_FLAGS_metrics=0 to keep the fast path fully asynchronous.")
_m_donated_bytes = _monitor.gauge(
    "executor.donated_bytes", "Bytes of persistable state donated into the "
    "last step (device-resident, updated in place by XLA).",
    labelnames=("program",))
_m_prog_ops = _monitor.gauge(
    "executor.program_ops", "Op count of the last-compiled program "
    "(all blocks).", labelnames=("program",))
_m_state_bytes = _monitor.gauge(
    "executor.state_size_bytes", "Bytes of persistable state round-tripped "
    "through the last step.", labelnames=("program",))
_m_cost_flops = _monitor.gauge(
    "executor.cost_flops", "XLA cost_analysis() flop estimate of the "
    "last-compiled executable (absent when the backend exposes no cost "
    "model).", labelnames=("program",))
_m_cost_bytes = _monitor.gauge(
    "executor.cost_bytes_accessed", "XLA cost_analysis() bytes-accessed "
    "estimate of the last-compiled executable.", labelnames=("program",))
_m_traces = _monitor.counter(
    "executor.traces", "Program traces: how many times the Executor walked "
    "a Program's ops to (re)build a step function.  Increments at trace "
    "time only — steady-state dispatch of a compiled step never bumps it, "
    "and a warm persistent compile-cache start keeps it at 0 (the step "
    "deserializes instead of tracing).  A growing value in steady state is "
    "a retrace bug.")
# Device-memory profile of the last-compiled executable (utils/xprof.py over
# XLA memory_analysis(); the TPU-native stand-in for the reference's CUPTI
# memory counters).  Set whenever telemetry is on and the single-device AOT
# path compiled.
_m_mem_args = _monitor.gauge(
    "executor.device_mem_args_bytes", "memory_analysis() argument bytes of "
    "the last-compiled executable.", labelnames=("program",))
_m_mem_out = _monitor.gauge(
    "executor.device_mem_out_bytes", "memory_analysis() output bytes of the "
    "last-compiled executable.", labelnames=("program",))
_m_mem_temp = _monitor.gauge(
    "executor.device_mem_temp_bytes", "memory_analysis() temp (scratch) "
    "bytes of the last-compiled executable — the part of the memory "
    "footprint that is XLA's choice, not the model's.",
    labelnames=("program",))
_m_mem_code = _monitor.gauge(
    "executor.device_mem_code_bytes", "memory_analysis() generated-code "
    "bytes of the last-compiled executable.", labelnames=("program",))
_m_mem_total = _monitor.gauge(
    "executor.device_mem_total_bytes", "args + out + temp + code bytes of "
    "the last-compiled executable.", labelnames=("program",))
_m_predicted_peak = _monitor.gauge(
    "executor.predicted_peak_bytes", "memcheck's static per-device peak-HBM "
    "estimate for this program, set before the trace/compile it prices — "
    "compare against executor.device_mem_total_bytes to watch calibration "
    "in production.", labelnames=("program",))
# Collect-time census of what is actually resident: every live jax.Array in
# the process (donated state, prefetch staging, stray host copies included).
_m_mem_live_bytes = _monitor.gauge(
    "executor.device_mem_live_bytes", "Bytes of all live jax.Arrays in the "
    "process (jax.live_arrays() census, evaluated at collect time).")
_m_mem_live_count = _monitor.gauge(
    "executor.device_mem_live_arrays", "Count of live jax.Arrays in the "
    "process (jax.live_arrays() census, evaluated at collect time).")


def _census_field(field: str):
    def sample():
        from ..utils.xprof import live_array_census

        try:
            return float(live_array_census()[field])
        except Exception:
            return 0.0
    return sample


_m_mem_live_bytes.set_function(_census_field("bytes"))
_m_mem_live_count.set_function(_census_field("count"))


_prog_tokens = iter(range(1, 1 << 62))


def _program_token(program) -> int:
    """Stable per-Program cache token.  `id()` can alias after GC (round-1/2
    finding); a token stored ON the object cannot."""
    tok = getattr(program, "_exec_cache_token", None)
    if tok is None:
        tok = next(_prog_tokens)
        program._exec_cache_token = tok
    return tok


class _CacheEntry:
    """One compiled steady-state step plus everything needed to re-dispatch
    it without rebuilding signatures: the per-program key-prefix cache.  A
    steady-state `Executor.run` finds this via one dict lookup on the
    program's cache token and re-validates the feed shapes against
    ``feed_sig`` in place — no sorted-tuple signature is rebuilt, no program
    walk recomputes the persistable list."""

    __slots__ = ("key", "compiled", "version", "donate", "plan_token",
                 "fetch_names", "feed_sig", "state_names", "needs_value",
                 "op_count", "fingerprint", "kernel_fp", "disk_cache",
                 "aot", "mem")

    def __init__(self, key, version, donate, plan_token, fetch_names,
                 feed_arrays, state_names, needs_value, op_count, fingerprint,
                 kernel_fp=""):
        self.key = key
        self.compiled = None
        self.version = version
        self.donate = donate
        self.plan_token = plan_token
        self.fetch_names = list(fetch_names)
        self.feed_sig = {k: (tuple(v.shape), v.dtype)
                         for k, v in feed_arrays.items()}
        self.state_names = list(state_names)
        self.needs_value = frozenset(needs_value)
        self.op_count = op_count
        self.fingerprint = fingerprint
        self.kernel_fp = kernel_fp
        self.disk_cache = "off"  # persistent-cache provenance: hit|miss|off
        self.aot = None  # AOT executable when telemetry compiled one —
        self.mem = None  # xprof's attribution source + its memory breakdown

    def matches(self, version, fetch_names, feed_arrays, plan_token,
                donate, kernel_fp="") -> bool:
        if (self.version != version or self.donate != donate
                or self.plan_token != plan_token
                or self.kernel_fp != kernel_fp
                or self.fetch_names != fetch_names
                or len(self.feed_sig) != len(feed_arrays)):
            return False
        sig = self.feed_sig
        try:
            for k, v in feed_arrays.items():
                shape, dtype = sig[k]
                if v.shape != shape or v.dtype != dtype:
                    return False
        except KeyError:
            return False
        return True


class Executor:
    """ref fluid/executor.py:474.  `place` is accepted for API parity; XLA
    owns placement (SURVEY.md L0a TPU mapping)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Tuple, _CacheEntry] = {}
        # (program token, entry key) -> last entry; entry keys partition the
        # hot map so e.g. serving shape buckets each keep a pinned slot
        self._hot: Dict[Tuple, _CacheEntry] = {}
        self._step = 0

    # -- public API ----------------------------------------------------------
    def run(self, program=None, feed: Optional[dict] = None,
            fetch_list: Optional[Sequence] = None, scope: Optional[Scope] = None,
            return_numpy: bool = True, entry_key: Optional[str] = None):
        """Run one step of ``program``.

        ``entry_key`` names an independent steady-state entry point for the
        same program: each distinct key keeps its own hot-cache slot (and
        its own persistent-cache artifact), so a caller that legitimately
        alternates between several compiled shapes of one program — the
        serving frontend dispatching padded shape *buckets* — stays on the
        one-dict-lookup fast path for every bucket instead of thrashing the
        single per-program hot slot.  ``None`` (the default) preserves the
        historical one-hot-entry-per-program behavior.

        Steady-state fast path: with ``return_numpy=False`` the call is
        dispatch-asynchronous — it returns unmaterialized ``jax.Array``
        fetches as soon as XLA has enqueued the step, so host work (the next
        batch's collate, logging) overlaps device compute.  With the
        ``donate_state`` flag on (default), the persistable state pytree is
        donated into the compiled step: XLA updates parameters/optimizer
        slots in place and the scope write-back is a pointer swap, not a
        copy.  ``jax.Array`` feed values are passed through without a host
        round-trip (pair with ``io.DeviceFeeder`` prefetch)."""
        from .compiler import CompiledProgram

        plan = None
        if isinstance(program, CompiledProgram):
            # feed/fetch ride along so a plan="auto" resolution (the first
            # run only — the choice is memoized) prices real batch shapes
            plan = program._sharding_plan(feed=feed, fetch_list=fetch_list)
            program = program._program
        program = program or default_main_program()
        feed = feed or {}
        scope = scope or global_scope()

        fetch_names = [v.name if isinstance(v, Variable) else str(v)
                       for v in (fetch_list or [])]
        # device-resident feeds (DeviceFeeder prefetch) stay on device —
        # np.asarray on a jax.Array is a blocking D2H sync that would defeat
        # async dispatch; only host values are normalized to numpy
        feed_arrays = {k: v if isinstance(v, jax.Array) else np.asarray(v)
                       for k, v in feed.items()}

        from ..core import flags as _flags

        # donation follows the plan: the sharded fast path donates the
        # *sharded* state pytree (with_sharding's default), while the
        # data-parallel plan pins a place-once buffer-identity contract
        # (tests/test_static_dp.py) that in-place donation would break
        donate = (bool(_flags.get_flag("donate_state"))
                  and _donation_async_safe()
                  and (plan is None or plan.donate))
        plan_token = plan.token if plan is not None else None

        # hot path: one dict lookup on (program token, entry key), then an
        # in-place feed-shape check — no sorted signature tuple, no program
        # re-walk.  Distinct entry keys (shape buckets) never evict each
        # other's hot slot.
        hot_key = (getattr(program, "_exec_cache_token", None), entry_key)
        # kernel-config fingerprint (ops/pallas/config.py): kernel selection
        # happens at trace time, so a flag flip (or backend-gate change)
        # must be a clean recompile, never a stale hot-entry hit
        from ..ops.pallas import config as _pcfg

        kernel_fp = _pcfg.cache_key_part()
        entry = self._hot.get(hot_key)
        if entry is None or not entry.matches(program._version, fetch_names,
                                              feed_arrays, plan_token, donate,
                                              kernel_fp):
            entry = self._cold_lookup(program, fetch_names, feed_arrays,
                                      plan_token, donate, entry_key,
                                      kernel_fp)

        state, missing = {}, None
        for n in entry.state_names:
            v = scope.find_var(n)
            if v is None:
                if n in entry.needs_value:
                    missing = (missing or [])
                    missing.append(n)
            else:
                state[n] = v
        if missing:
            from ..core.errors import PreconditionNotMetError

            raise PreconditionNotMetError(
                f"persistable variables {missing} have no value in scope — "
                "run the startup program first (exe.run(startup_program))")

        # partition the state for donation: only buffers LOCAL to the run
        # scope are donated (fall-through reads must never clobber a parent
        # scope — ref framework/scope.h semantics), and a buffer aliased by
        # a feed or by a second state name is carried by copy so XLA never
        # sees the same donated buffer twice
        if donate:
            d_state: Dict[str, Any] = {}
            p_state: Dict[str, Any] = {}
            seen = {id(v) for v in feed_arrays.values()
                    if isinstance(v, jax.Array)}
            for n, v in state.items():
                if (isinstance(v, jax.Array) and id(v) not in seen
                        and scope.local_var(n) is v):
                    seen.add(id(v))
                    d_state[n] = v
                else:
                    p_state[n] = v
        else:
            d_state, p_state = {}, state

        token = entry.key[0]
        step_arg = np.int32(self._step)
        cache_miss = entry.compiled is None
        t_compile0 = time.perf_counter()
        if cache_miss:
            _m_cache_miss.inc()
            # calibration ledger: traced comm bytes accumulate in a
            # process-wide histogram, so the delta across this compile is
            # what *this* trace moved (utils/ledger.py joins it against
            # shardcheck's estimate); mem_report joins the memcheck leg
            ledger_pre = _ledger.pre_compile()
            mem_report = None
            with _trace.span("executor::trace_compile",
                             program=entry.fingerprint,
                             ops=entry.op_count) as sp:
                if _flags.get_flag("check_program"):
                    # pre-trace static analysis (SURVEY §7: fail fast and
                    # legibly before jit) — memoized by program version ×
                    # feed/fetch signature, so neither steady-state steps
                    # nor a second cold entry for the same program re-walk
                    from .analysis import check_program_cached \
                        as _check_program

                    _check_program(program, feed_names=set(feed_arrays),
                                   fetch_names=fetch_names)
                if plan is not None and _flags.get_flag("check_sharding"):
                    # tier-two: Program × ShardingPlan checks (SC001–SC009)
                    # — memoized by plan token × program version × feed
                    # shapes, zero steady-state cost
                    from .shardcheck import check_with_plan as _check_plan

                    _check_plan(program, plan, feed_arrays)
                if _flags.get_flag("check_memory"):
                    # tier-three: static peak-HBM pricing (MC001-MC007) —
                    # a predicted OOM aborts here, before the trace XLA
                    # would spend minutes on; advisory findings are
                    # flight-recorded, never raised.  Memoized like
                    # check_with_plan: zero steady-state cost
                    from .memcheck import check_memory_cached as _check_mem

                    mem_report = _check_mem(program, plan, feed_arrays,
                                            fetch_names)
                    if mem_report.mem is not None and _monitor.enabled():
                        _m_predicted_peak.set(
                            mem_report.mem.peak_bytes, program=str(token))
                    for d in mem_report.diagnostics:
                        _trace.flight_recorder().record(
                            "memcheck_violation", code=d.code,
                            severity=d.severity, var=d.var or "",
                            message=d.message)
                # verified graph-rewrite pipeline (static/passes.py):
                # compile-path only — hot-path steps never re-enter this
                # branch, and a verification failure rolls back to the
                # caller's program, so the step always compiles
                exec_program, passes_fp = program, ""
                _opt = _flags.get_flag("opt_passes")
                if _opt:
                    from . import passes as _passes

                    exec_program, passes_fp = _passes.optimize_for_executor(
                        program, _opt, feed_names=set(feed_arrays),
                        fetch_names=fetch_names, plan=plan,
                        feed_arrays=feed_arrays)
                    sp.set_attr("opt_passes", passes_fp or "rollback")
                seed = exec_program.random_seed or _random_seed()
                # persistent AOT cache (static/compile_cache.py): key the
                # artifact by program content × mesh/plan × versions; a hit
                # deserializes the compiled step instead of tracing it
                from . import compile_cache as _ccache

                disk = _ccache.active_cache()
                disk_key = None
                if disk is not None:
                    disk_key = _ccache.build_cache_key(
                        exec_program, seed, fetch_names, feed_arrays,
                        d_state, p_state, donate,
                        plan.fingerprint() if plan is not None else None,
                        entry=entry_key or "", passes=passes_fp,
                        kernel=entry.kernel_fp)
                (entry.compiled, entry.disk_cache, cost,
                 entry.aot) = self._build(
                    exec_program, fetch_names, entry.state_names, seed,
                    plan=plan, feed_arrays=feed_arrays, donate=donate,
                    example=(feed_arrays, d_state, p_state, step_arg),
                    disk=disk, disk_key=disk_key)
                sp.set_attr("compile_cache", entry.disk_cache)
                if entry.disk_cache == "hit":
                    _ccache._m_cc_hit.inc()
                elif entry.disk_cache == "miss":
                    _ccache._m_cc_miss.inc()
                if cost:
                    # XLA cost_analysis() of the compiled artifact:
                    # flops/bytes land on the compile span and as gauges —
                    # on persistent-cache hits too (the cost model is
                    # re-derived from the deserialized executable)
                    flops = cost.get("flops")
                    nbytes = cost.get("bytes accessed")
                    if flops is not None:
                        sp.set_attr("flops", float(flops))
                        _m_cost_flops.set(float(flops), program=str(token))
                    if nbytes is not None:
                        sp.set_attr("bytes_accessed", float(nbytes))
                        _m_cost_bytes.set(float(nbytes), program=str(token))
                if entry.aot is not None:
                    from ..utils import xprof as _xprof

                    entry.mem = _xprof.memory_stats(entry.aot)
                    if entry.mem and plan is not None:
                        # sharded build: when memory_analysis() priced a
                        # per-partition SPMD module, report the
                        # addressable-shard sum (this process's slice of
                        # the mesh) so memory_stats()/gauges cover meshes.
                        # Some backends (XLA:CPU) compile the module at
                        # global shapes instead — detected by comparing
                        # the reported args leg against the example's
                        # known global bytes; those are left unscaled.
                        mesh_l = plan.resolve_mesh()
                        try:
                            pi = jax.process_index()
                            n_local = sum(
                                1 for d in mesh_l.devices.flat
                                if d.process_index == pi) or 1
                        except Exception:
                            n_local = int(mesh_l.devices.size)
                        global_args = sum(
                            int(np.asarray(v).nbytes)
                            for part in (feed_arrays, d_state, p_state)
                            for v in (part or {}).values())
                        per_partition = (
                            entry.mem["args_bytes"] < 0.75 * global_args)
                        if n_local > 1 and per_partition:
                            entry.mem = {k: int(v) * n_local
                                         for k, v in entry.mem.items()}
                    if entry.mem:
                        prog = str(token)
                        _m_mem_args.set(entry.mem["args_bytes"], program=prog)
                        _m_mem_out.set(entry.mem["out_bytes"], program=prog)
                        _m_mem_temp.set(entry.mem["temp_bytes"], program=prog)
                        _m_mem_code.set(entry.mem["code_bytes"], program=prog)
                        _m_mem_total.set(entry.mem["total_bytes"],
                                         program=prog)
            if _monitor.enabled():
                _m_prog_ops.set(entry.op_count, program=str(token))
            # measured-vs-predicted compile record: joins estimate_comm /
            # estimate_peak / roofline against entry.mem and the traced
            # comm delta.  Guarded inside — an estimator bug degrades to
            # an unpriced record, never a failed run
            _ledger.observe_compile(entry=entry, program=program, plan=plan,
                                    feed_arrays=feed_arrays,
                                    fetch_names=fetch_names,
                                    mem_report=mem_report, pre=ledger_pre)
        else:
            _m_cache_hit.inc()

        if _monitor.enabled():
            _m_state_bytes.set(
                sum(getattr(v, "nbytes", 0) or 0 for v in state.values()),
                program=str(token))
            _m_donated_bytes.set(
                sum(getattr(v, "nbytes", 0) or 0 for v in d_state.values()),
                program=str(token))
        self._step += 1
        t_run0 = time.perf_counter()
        with _trace.span("executor::run", program=entry.fingerprint,
                         cache="miss" if cache_miss else "hit"):
            fetches, new_state = entry.compiled(feed_arrays, d_state,
                                                p_state, step_arg)
        now = time.perf_counter()
        # a miss's timing spans trace+compile+first run (XLA compiles on the
        # first jitted call); steady-state hits time only the dispatch —
        # under async dispatch the device may still be computing when
        # compiled() returns, so this is the Python-rim cost, not step time
        if cache_miss:
            from . import compile_cache as _ccache

            cold_ms = (now - t_compile0) * 1000.0
            _m_compile_ms.observe(cold_ms)
            # cold-start cost labeled by executable provenance: a warm
            # persistent cache (hit) should sit well below a real compile
            _ccache._m_cold_ms.observe(cold_ms, cache=entry.disk_cache)
        else:
            _m_dispatch_ms.observe((now - t_run0) * 1000.0)
        _trace.flight_recorder().record(
            "executor_run", name=entry.fingerprint,
            cache="miss" if cache_miss else "hit", ops=entry.op_count,
            dur_ms=round((now - t_run0) * 1000.0, 3))
        # pointer-swap write-back: under donation the arrays are already
        # device-resident and the old buffers were consumed in place
        for n, v in new_state.items():
            scope.set(n, v)
        if not cache_miss and _monitor.enabled():
            # true step time needs one device sync; only pay it while the
            # metrics flag is on (PDTPU_FLAGS_metrics=0 keeps full async)
            sync = fetches[0] if fetches else \
                next(iter(new_state.values()), None)
            if isinstance(sync, jax.Array):
                sync.block_until_ready()
                step_ms = (time.perf_counter() - t_run0) * 1000.0
                _m_step_ms.observe(step_ms)
                # same measured value feeds the calibration ledger's
                # steady-state window (a list append; the window closes
                # into a record every ledger_window steps)
                _ledger.observe_step(entry.fingerprint, step_ms)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def _cold_lookup(self, program, fetch_names, feed_arrays, plan_token,
                     donate, entry_key=None, kernel_fp="") -> _CacheEntry:
        """Full cache-key build (sorted feed signature + program walk); the
        resulting entry is pinned on the hot map (keyed by program token ×
        entry key) so steady-state calls skip this entirely."""
        token = _program_token(program)
        key = (token, entry_key, program._version, tuple(fetch_names),
               tuple(sorted((k, tuple(v.shape), str(v.dtype))
                            for k, v in feed_arrays.items())),
               plan_token, donate, kernel_fp)
        entry = self._cache.get(key)
        if entry is None:
            state_names = self._state_names(program, global_scope())
            needs = [n for n in state_names if self._needs_value(program, n)]
            entry = _CacheEntry(
                key, program._version, donate, plan_token, fetch_names,
                feed_arrays, state_names, needs,
                op_count=sum(len(b.ops) for b in program.blocks),
                # cache token + program version identify the exact compiled
                # artifact on spans/flight events
                fingerprint=f"{token}v{program._version}",
                kernel_fp=kernel_fp)
            self._cache[key] = entry
        self._hot[(token, entry_key)] = entry
        return entry

    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread: int = 0, debug: bool = False,
                           fetch_list=None, fetch_info=None,
                           print_period: int = 100,
                           prefetch_to_device=False):
        """ref fluid/executor.py:1597 train_from_dataset →
        TrainerFactory/MultiTrainer/DeviceWorker (trainer.h:41,
        device_worker.h:215 HogwildWorker threads pulling from the DataFeed
        channel).

        TPU-native collapse: the C++ DataFeed (native/src/datafeed.cc)
        already parses/shuffles/batches on background threads, and a single
        XLA device consumes steps in order — so the N-worker Hogwild loop
        becomes sequential jitted steps over the feed stream (`thread` is
        accepted for parity; parallel parsing is configured on the dataset
        via set_thread).

        ``prefetch_to_device=True`` (or a device) stages batch N+1 on the
        device from a background thread while batch N computes — the
        TPU-native replacement for the reference's DataFeed channel into
        per-thread DeviceWorkers (see io/prefetch.py)."""
        if dataset is None:
            raise ValueError("train_from_dataset requires a dataset")
        del thread  # parity knob; parse parallelism lives on the dataset
        fetch_list = list(fetch_list or [])
        names = [v.name if isinstance(v, Variable) else str(v)
                 for v in fetch_list]
        labels = list(fetch_info or names)
        stream = dataset
        if prefetch_to_device:
            from ..io.prefetch import DeviceFeeder

            stream = DeviceFeeder(
                dataset,
                device=None if prefetch_to_device is True
                else prefetch_to_device)
        step = 0
        last = None
        for batch in stream:
            last = self.run(program, feed=batch, fetch_list=fetch_list,
                            scope=scope)
            step += 1
            if debug and fetch_list and step % print_period == 0:
                msg = ", ".join(f"{l}={np.asarray(v).ravel()[:1][0]:.6g}"
                                for l, v in zip(labels, last))
                print(f"[train_from_dataset] step {step}: {msg}")
        return last

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread: int = 0, debug: bool = False,
                           fetch_list=None, fetch_info=None,
                           print_period: int = 100,
                           prefetch_to_device=False):
        """ref fluid/executor.py:1476 — same loop; the program is expected
        to be an inference/test clone (no optimizer ops)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period, prefetch_to_device)

    # -- internals -----------------------------------------------------------
    def _state_names(self, program: Program, scope: Scope) -> List[str]:
        names = []
        for v in program.list_vars():
            if v.persistable:
                names.append(v.name)
        return names

    def _needs_value(self, program: Program, name: str) -> bool:
        """A persistable var needs a prior value unless some op in this
        program writes it before any read (init ops in startup programs)."""
        return self._first_access(program, program.global_block(), name) == "read"

    def _first_access(self, program: Program, block, name: str):
        """First access to `name` in execution order: 'read', 'write', or None.

        Walks cond/while/rnn sub-blocks at the point of their control-flow
        op.  Sub-block READS count — branch/body traces close over a
        snapshot of the enclosing env (`_lower_cond`/`_lower_while`), so an
        unset persistable read there fails just like a block-0 read.
        Sub-block WRITES do not — they mutate the branch-local env copy and
        escape only through the control-flow op's declared outputs, which
        the parent-level ``output_names()`` check already covers."""
        for op in block.ops:
            if name in op.input_names():
                return "read"
            for _a, sub_idx in op.sub_block_indices():
                sub = self._first_access(
                    program, program.blocks[sub_idx], name)
                if sub == "read":
                    return "read"
                # sub == 'write': local to that branch trace; a
                # write-then-read inside the sub-block was already
                # resolved locally (the recursion returned at the
                # write), so keep scanning the parent.
            if name in op.output_names():
                return "write"
        return None

    def _build(self, program: Program, fetch_names, state_names, seed,
               plan=None, feed_arrays=None, example=None, donate=False,
               disk=None, disk_key=None):
        """Trace the program into `(feeds, donated, carried, step) ->
        (fetches, new_state)`.  The PRNG base key is derived INSIDE the
        compiled function — `fold_in(PRNGKey(seed), step)` with `step`
        passed as a scalar arg — so steady-state calls never mint a host
        PRNGKey (a small jit dispatch of its own) and never retrace on the
        step counter.  `seed` is captured per compile-cache entry.

        Returns ``(compiled, disk_cache_status, xla_cost, aot)``: status is
        ``"hit"`` (step deserialized from ``compile_cache_dir`` — no trace,
        no lowering), ``"miss"`` (traced, exported, stored), or ``"off"``
        (persistent cache disabled or export unavailable); ``aot`` is the
        AOT-compiled executable when telemetry built one (the xprof
        attribution source), else None."""
        state_constraints: Dict[str, Any] = {}

        def raw(feeds, donated, carried, step):
            _m_traces.inc()  # host side effect: fires at trace time only
            env: Dict[str, Any] = {}
            env.update({k: jnp.asarray(v) for k, v in carried.items()})
            env.update({k: jnp.asarray(v) for k, v in donated.items()})
            env.update({k: jnp.asarray(v) for k, v in feeds.items()})
            base_key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            # plan comm options (quantized/hierarchical gradient sync) are
            # ambient only while the body traces: axis-bound collective
            # lowerings consult parallel.compress.current_comm()
            comm_ctx = plan.comm_scope() if plan is not None \
                else contextlib.nullcontext()
            # likewise the plan's embedding-shard config: lookup_table
            # lowerings consult parallel.embedding.current_embedding() to
            # route covered tables through the all_to_all exchange
            emb_ctx = plan.embedding_scope(program) if plan is not None \
                else contextlib.nullcontext()
            with comm_ctx, emb_ctx:
                _trace_block(program, env, base_key)
            fetches = [env[n] for n in fetch_names]
            new_state = {}
            for n in state_names:
                if n in env:
                    v = env[n]
                    sh = state_constraints.get(n)
                    if sh is not None:
                        # pin the updated state to the plan's layout so
                        # steady-state write-backs come home already sharded
                        # and the placement rim passes them through
                        v = jax.lax.with_sharding_constraint(v, sh)
                    new_state[n] = v
            return fetches, new_state

        if plan is None:
            return self._build_single(raw, example, donate, disk, disk_key)
        # resolve which state leaves are embedding tables BEFORE placement:
        # state_shardings must see the bound names to vocab-shard them
        plan.bind_embedding_tables(program)
        from .memcheck import _optimizer_slots
        return self._build_sharded(raw, plan, example, donate,
                                   state_constraints, disk, disk_key,
                                   optimizer_slots=frozenset(
                                       _optimizer_slots(program)))

    @staticmethod
    def _load_or_export(raw, example, donate, disk, disk_key):
        """Resolve the core compiled step through the persistent cache.

        Hit: deserialize the ``jax.export`` artifact and jit its ``call``
        (donation re-applied via ``donate_argnums``) — the program is never
        traced and XLA never lowers it.  Miss: export once (the only trace
        of ``raw``), store atomically, and RUN the exported module too, so
        cold and warm processes execute the byte-identical artifact.  Any
        export-layer failure degrades to plain jit — the cache can only
        cost time, never a step."""
        donate_args = (1,) if donate else ()
        if disk is not None and disk_key is not None and example is not None:
            from jax import export as _export

            payload = disk.load(disk_key)
            if payload is not None:
                try:
                    exp = _export.deserialize(payload)
                    return (jax.jit(exp.call, donate_argnums=donate_args),
                            "hit")
                except Exception as e:
                    _trace.flight_recorder().record(
                        "compile_cache_deserialize_failed",
                        key=disk_key[:16], error=repr(e))
            try:
                exp = _export.export(jax.jit(raw))(*example)
                disk.store(disk_key, exp.serialize())
                return (jax.jit(exp.call, donate_argnums=donate_args),
                        "miss")
            except Exception as e:
                _trace.flight_recorder().record(
                    "compile_cache_export_failed", key=disk_key[:16],
                    error=repr(e))
        return jax.jit(raw, donate_argnums=donate_args), "off"

    # named-scope metadata in optimized HLO: op_name="...<type>.b<k>.i<j>..."
    _SCOPED_META_RE = re.compile(r'op_name="[^"]*\.b\d+\.i\d+')

    @staticmethod
    def _refresh_stale_metadata(core, example, aot, status):
        """Guard against jax's compilation caches serving an executable
        compiled before xprof scopes existed: the persistent cache key
        strips HLO metadata (cache_key.py runs strip-debuginfo), so a warm
        cache returns the old artifact and every op attributes to
        <unattributed> — and once loaded, the in-memory compilation memo
        pins it for the process, so no cache-config toggle can dislodge it.
        When scopes are on but none survived into the optimized HLO,
        recompile once with an explicit (default-valued, semantically
        no-op) compiler option: compile options ride both the in-memory
        memo key and the persistent key, so the scoped module resolves to
        its own entry — a real compile the first time, a cache hit in later
        processes.  Compile-cache *hits* are exempt: the deserialized
        artifact is authoritative and a recompile could not change its
        metadata."""
        from ..core import flags as _flags

        if (status == "hit" or not _flags.get_flag("xprof_scopes")
                or Executor._SCOPED_META_RE.search(aot.as_text())):
            return aot
        try:
            fresh = core.lower(*example).compile(
                compiler_options={"xla_embed_ir_in_executable": False})
        except Exception:
            return aot  # a backend rejecting the option keeps the original
        return (fresh if Executor._SCOPED_META_RE.search(fresh.as_text())
                else aot)

    @staticmethod
    def _build_single(raw, example, donate, disk=None, disk_key=None):
        """jit the traced step (donating the `donated` state subtree when the
        donate_state fast path is on); when telemetry is on, AOT-compile
        against the example args so the compiled artifact's
        `cost_analysis()` (flops / bytes accessed — XLA's replacement for
        the reference's per-op cost model), `memory_analysis()`, and the
        optimized HLO text (xprof attribution) are observable.  This runs
        on every persistent-cache status: a cache *hit*'s jitted
        ``exp.call`` would compile at first dispatch anyway, so AOT-
        compiling it up front re-derives the cost model at no extra
        compile — and never re-traces the program (``executor.traces``
        stays 0 on a warm start; the historical bug was cost gauges set
        only on the status-"off" path).  The AOT executable is pinned to
        the example's arg structure; a later call with a different state
        pytree (a program that grows persistables) falls back to the
        jitted path, which retraces as usual."""
        core, status = Executor._load_or_export(raw, example, donate, disk,
                                                disk_key)
        if example is None or not _monitor.enabled():
            return core, status, None, None
        try:
            aot = core.lower(*example).compile()
            aot = Executor._refresh_stale_metadata(core, example, aot, status)
        except Exception:
            return core, status, None, None
        cost = None
        try:
            ca = aot.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                cost = ca
        except Exception:
            pass

        def call(feeds, donated, carried, step):
            try:
                return aot(feeds, donated, carried, step)
            except Exception:
                # structure mismatches raise host-side before execution, so
                # the donated buffers are still live for the jitted retry
                return core(feeds, donated, carried, step)

        return call, status, cost, aot

    @staticmethod
    def _build_sharded(raw, plan, example, donate, state_constraints,
                       disk=None, disk_key=None, optimizer_slots=None):
        """Sharded build: the SAME traced computation with feeds and
        persistable state placed by the ShardingPlan's NamedShardings.
        GSPMD partitions the compute and inserts the collectives the
        reference's MultiDevSSAGraphBuilder spelled out per gradient
        (ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:464).

        The updated state is pinned to its input layout inside the traced
        step (``state_constraints`` feeds the `with_sharding_constraint` in
        ``raw``), so steady-state write-backs land already sharded and the
        placement rim below passes them through by identity: per-shard
        device residency across steps, donation of the sharded pytree
        included when the plan allows it (``with_sharding``; the
        data-parallel plan forbids it — the place-once contract in
        tests/test_static_dp.py pins buffer identity)."""
        mesh = plan.resolve_mesh()
        feeds0, d0, p0, step0 = example
        feed_sh = {k: plan.feed_sharding(k, v, mesh)
                   for k, v in feeds0.items()}
        state_all = dict(p0)
        state_all.update(d0)
        state_sh = plan.state_shardings(state_all, mesh,
                                        optimizer_slots=optimizer_slots)
        state_constraints.update(state_sh)

        def place(v, sh):
            # place-once: an array already laid out per the plan passes
            # through by identity (no device_put, no copy — what the DP
            # buffer-identity test and the donation path both rely on);
            # host values and stale layouts are placed
            if isinstance(v, jax.Array):
                try:
                    if v.sharding.is_equivalent_to(sh, v.ndim):
                        return v
                except Exception:
                    pass
            return jax.device_put(v, sh)

        def place_all(feeds, donated, carried):
            return ({k: place(v, feed_sh[k]) for k, v in feeds.items()},
                    {n: place(v, state_sh[n]) for n, v in donated.items()},
                    {n: place(v, state_sh[n]) for n, v in carried.items()})

        placed_example = None
        if disk is not None or _monitor.enabled():
            placed_example = (*place_all(feeds0, d0, p0), step0)
        core, status = Executor._load_or_export(raw, placed_example, donate,
                                                disk, disk_key)

        def call(feeds, donated, carried, step):
            pf, pd, pc = place_all(feeds, donated, carried)
            return core(pf, pd, pc, step)

        if placed_example is None or not _monitor.enabled():
            return call, status, None, None
        # AOT-compile the placed example so the sharded path reports
        # cost_analysis()/memory_analysis() like the single-device one —
        # the compiled module is the per-partition SPMD program, so its
        # memory numbers are per-device shards (memory_stats() scales them
        # to the addressable-shard sum).  Dispatch stays on the jitted
        # `core`: the AOT handle is observability-only here, the
        # per-shard attribution story remains a roadmap item.
        try:
            aot = core.lower(*placed_example).compile()
        except Exception:
            return call, status, None, None
        cost = None
        try:
            ca = aot.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if isinstance(ca, dict):
                cost = ca
        except Exception:
            pass
        return call, status, cost, aot

    # -- observability (utils/xprof.py) --------------------------------------
    def memory_stats(self) -> Dict[str, int]:
        """Aggregate device-memory breakdown (memory_analysis()) over this
        Executor's hot compiled entries: args/out/temp/code/total bytes plus
        the contributing entry count.  Zeroes when nothing compiled with
        telemetry on — the serving TenantManager sums this across live
        tenants for its peak-temp gauges."""
        agg = {"args_bytes": 0, "out_bytes": 0, "temp_bytes": 0,
               "code_bytes": 0, "alias_bytes": 0, "total_bytes": 0,
               "programs": 0}
        seen = set()
        for entry in list(self._hot.values()):
            if id(entry) in seen or not entry.mem:
                continue
            seen.add(id(entry))
            agg["programs"] += 1
            for k, v in entry.mem.items():
                agg[k] = agg.get(k, 0) + int(v)
        return agg

    def xprof_report(self, program=None, entry_key: Optional[str] = None,
                     measured_ms: Optional[float] = None,
                     top: Optional[int] = None) -> Dict[str, Any]:
        """The xprof attribution/roofline report for a compiled entry (see
        utils/xprof.py): per-source-op regions with flops, bytes,
        compute/memory bound class, modeled time and MFU, anchored by the
        measured ``executor.step_time_ms`` median unless ``measured_ms``
        overrides it.  ``program=None`` with a single hot entry profiles
        that entry."""
        import math as _math

        from ..utils import xprof as _xprof

        entry = None
        if program is None:
            live = [e for e in self._hot.values() if e.aot is not None]
            entry = live[0] if len(live) == 1 else None
            if entry is None and len(live) > 1:
                raise ValueError(
                    "xprof_report(program=None) is ambiguous: "
                    f"{len(live)} profiled entries are live — pass the "
                    "program (and entry_key for shape buckets)")
        else:
            tok = getattr(program, "_exec_cache_token", None)
            entry = self._hot.get((tok, entry_key))
        if entry is None or entry.aot is None:
            raise RuntimeError(
                "no profiled executable for this program: xprof needs the "
                "`metrics` flag on at compile time, at least one "
                "Executor.run, and the single-device path (sharded entries "
                "are not yet attributable)")
        if measured_ms is None:
            p50 = _m_step_ms.percentile(50)
            if not _math.isnan(p50):
                measured_ms = p50
        report = _xprof.profile_aot(entry.aot, measured_ms=measured_ms,
                                    top=top)
        # publish to the telemetry plane: a live scrape of /xprof returns
        # the last report without re-profiling
        from ..utils import telemetry as _telemetry

        _telemetry.publish_snapshot("xprof", report)
        return report

    def close(self):
        self._cache.clear()
        self._hot.clear()


def _random_seed() -> int:
    # derive from the process-wide RNG stream so `paddle_tpu.seed` governs
    # static-graph randomness too
    key, counter = _random.get_rng_state()
    data = np.asarray(jax.random.key_data(key)).ravel()
    return (int(data[-1]) + int(counter)) & 0x7FFFFFFF
