"""Static-op long tail, batch 2: collectives, RNN monoliths, fusion ops,
LoD-array/control ops, PS data-plane ops, and host-IO ops.

Reference parity targets: operators/collective/ (c_allreduce_sum & co),
lstm_op.cc / gru_op.cc / lstmp_op.cc / cudnn_lstm_op.cu, operators/fused/
(fusion_lstm, fusion_gru, fusion_repeated_fc_relu, fusion_squared_mat_sub,
fusion_seqpool_concat, fusion_seqconv_eltadd_relu, fused_embedding_fc_lstm),
tensor-array ops (tensor_array_read_write_op.cc, array_to_lod_tensor_op.cc,
shrink_rnn_memory_op.cc), merge/split_lod_tensor_op.cc, PS data-plane ops
(distributed_lookup_table_op.cc, operators/pscore pull/push_sparse),
save/load/print ops (save_op.cc, load_op.cc, print_op.cc, py_func_op.cc),
and the int8 quantize/dequantize pair (operators/mkldnn quantize_op.cc).

TPU-native design notes:
- collectives lower to jax.lax collectives when tracing inside a mapped
  context (the GSPMD/shard_map path the Executor's with_data_parallel
  uses) and degrade to identities on one device — the reference's NCCL
  rings are ICI here, and stream-sync ops are structurally unnecessary
  under XLA's dataflow ordering (documented per-op).
- RNN monolith ops run the recurrence as ONE lax.scan over time — the
  reference's hand-written CPU/GPU kernels collapse into a compiled loop
  whose per-step matmul hits the MXU.
- host-IO ops (save/print/push_sparse) use jax's ordered io_callback so
  side effects survive jit; load materializes at trace time (shapes must
  be static anyway).  NOTE: callbacks need PJRT host send/recv, which
  real TPU/CPU runtimes have but the axon remote-TPU tunnel of this dev
  environment does not ("axon_pjrt does not support host send/recv
  callbacks") — the callback-backed ops are therefore CPU/real-TPU only
  here, verified on the CPU backend in tests/test_ops_tail2.py.
- tensor arrays: the executor's var env can hold a python LIST of arrays
  (static length under trace); read/write need a trace-time-constant
  index — dynamic-index array reads belong to the StaticRNN collapse
  (SURVEY §1 L4 mapping), and the rule says so when violated.
"""
from __future__ import annotations



import jax
import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from .registry import register_op


def _one(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


# =========================================================================
# collective ops (ref operators/collective/c_*.cc)
# =========================================================================

def _data_axis():
    from ..parallel import collective as _coll

    return _coll.bound_data_axis()


def _c_allreduce(reduce_fn, summing=False):
    def rule(ins, attrs, op):
        x = _one(ins, "X")
        axis = _data_axis()
        if axis is None:
            return {"Out": [x]}
        if summing:
            # sum allreduce honors ambient comm options (ShardingPlan /
            # comm_scope: quantized payload, hierarchical schedule) or an
            # explicit `compress` op attr; other reductions stay exact
            from ..parallel import compress as _compress

            kind = attrs.get("compress") or None
            opts = _compress.current_comm()
            if kind is None and opts is not None:
                kind = opts.payload()
            if kind:
                return {"Out": [_compress.optimized_all_reduce(
                    x, axis, compress=kind,
                    block_size=opts.block_size if opts else 256,
                    hierarchy=opts.hierarchy if opts else "auto")]}
        return {"Out": [reduce_fn(x, axis)]}

    return rule


register_op("c_allreduce_sum")(_c_allreduce(jax.lax.psum, summing=True))
register_op("c_allreduce_max")(_c_allreduce(jax.lax.pmax))
register_op("c_allreduce_min")(_c_allreduce(jax.lax.pmin))
register_op("c_allreduce_prod")(_c_allreduce(
    # NOT exp(psum(log)): negatives must keep their sign
    lambda x, ax: jnp.prod(jax.lax.all_gather(x, ax), axis=0)))


@register_op("c_allgather")
def _c_allgather(ins, attrs, op):
    x = _one(ins, "X")
    axis = _data_axis()
    if axis is None:
        return {"Out": [x]}
    g = jax.lax.all_gather(x, axis)          # (n, ...) leading device dim
    return {"Out": [g.reshape((-1,) + x.shape[1:])]}


@register_op("c_reducescatter")
def _c_reducescatter(ins, attrs, op):
    x = _one(ins, "X")
    axis = _data_axis()
    if axis is None:
        return {"Out": [x]}
    return {"Out": [jax.lax.psum_scatter(x, axis, scatter_dimension=0,
                                         tiled=True)]}


@register_op("c_broadcast")
def _c_broadcast(ins, attrs, op):
    x = _one(ins, "X")
    axis = _data_axis()
    if axis is None:
        return {"Out": [x]}
    # broadcast from root: take root's value on every member
    src = attrs.get("root", 0)
    idx = jax.lax.axis_index(axis)
    return {"Out": [jax.lax.psum(
        jnp.where(idx == src, x, jnp.zeros_like(x)), axis)]}


def _comm_noop_rule(why):
    def rule(ins, attrs, op):
        # identity pass-through; the reference op exists to manage NCCL
        # communicators/streams, which XLA's dataflow ordering + the mesh
        # runtime own here (SURVEY N21/N22 mapping): {why}
        del attrs, op
        xs = ins.get("X", [])
        return {"Out": list(xs)} if xs else {}

    rule.__doc__ = why
    return rule


for _name, _why in [
        ("c_comm_init", "communicator creation = jax mesh/distributed init"),
        ("c_comm_init_all", "same; all-rank init is the mesh constructor"),
        ("c_gen_nccl_id", "no NCCL id exchange: ICI topology is static"),
        ("c_sync_calc_stream", "XLA orders compute by dataflow, no streams"),
        ("c_sync_comm_stream", "collectives are dataflow-ordered too"),
        ("gen_nccl_id", "legacy alias of c_gen_nccl_id")]:
    register_op(_name)(_comm_noop_rule(_why))


@register_op("sync_batch_norm")
def _sync_batch_norm(ins, attrs, op):
    """ref sync_batch_norm_op.cu: BN statistics averaged across the data
    axis; degrades to plain BN on one device."""
    x = _one(ins, "X")
    axis = _data_axis()
    training = not attrs.get("is_test", False)
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    if axis is None or not training:
        out, new_rm, new_rv = F.batch_norm(
            x, _one(ins, "Mean"), _one(ins, "Variance"),
            weight=_one(ins, "Scale"), bias=_one(ins, "Bias"),
            training=training, momentum=momentum, epsilon=eps)
        return {"Y": [out], "MeanOut": [new_rm], "VarianceOut": [new_rv]}
    red = (0,) + tuple(range(2, x.ndim))
    shape = [1, -1] + [1] * (x.ndim - 2)
    mean = jax.lax.pmean(jnp.mean(x, axis=red), axis)
    mean_sq = jax.lax.pmean(jnp.mean(jnp.square(x), axis=red), axis)
    var = mean_sq - jnp.square(mean)
    out = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
    scale, bias = _one(ins, "Scale"), _one(ins, "Bias")
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    rm, rv = _one(ins, "Mean"), _one(ins, "Variance")
    return {"Y": [out],
            "MeanOut": [momentum * rm + (1 - momentum) * mean],
            "VarianceOut": [momentum * rv + (1 - momentum) * var]}


# =========================================================================
# RNN monolith ops (ref lstm_op.cc, gru_op.cc, lstmp_op.cc, cudnn_lstm,
# fused/fusion_lstm.cc, fusion_gru.cc, fused_embedding_fc_lstm_op.cc)
# — dense (B, T, ...) layout, ONE lax.scan over time
# =========================================================================

def _sig(v):
    return jax.nn.sigmoid(v)


def _lstm_scan(gates_x, w_h, bias, h0, c0, mask=None, proj=None):
    """gates_x: (B, T, 4H) pre-projected inputs; returns (h_seq, c_seq)."""
    B, T, H4 = gates_x.shape
    H = H4 // 4

    def step(carry, t_in):
        h, c = carry
        xt, mt = t_in
        g = xt + h @ w_h + (bias if bias is not None else 0.0)
        i, f, gg, o = jnp.split(g, 4, axis=-1)
        c_new = _sig(f) * c + _sig(i) * jnp.tanh(gg)
        h_new = _sig(o) * jnp.tanh(c_new)
        if proj is not None:
            h_new = h_new @ proj
        if mt is not None:
            h_new = h_new * mt + h * (1 - mt)
            c_new = c_new * mt + c * (1 - mt)
        return (h_new, c_new), (h_new, c_new)

    xs = jnp.swapaxes(gates_x, 0, 1)  # (T, B, 4H)
    ms = (jnp.swapaxes(mask, 0, 1)[..., None]
          if mask is not None else jnp.ones((T, 1, 1), gates_x.dtype))
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), (xs, ms))
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


@register_op("lstm")
def _lstm_op(ins, attrs, op):
    """ref lstm_op.cc (padded layout): Input (B,T,4H) pre-gates, Weight
    (H,4H), Bias (4H) [+ optional (B,T) Mask] -> Hidden/Cell (B,T,H)."""
    x = _one(ins, "Input")
    w = _one(ins, "Weight")
    b = _one(ins, "Bias")
    mask = _one(ins, "Mask")
    B, T, H4 = x.shape
    H = H4 // 4
    h0 = _one(ins, "H0")
    c0 = _one(ins, "C0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), x.dtype)
    hs, cs = _lstm_scan(x, w, b, h0, c0, mask)
    return {"Hidden": [hs], "Cell": [cs]}


@register_op("lstmp")
def _lstmp_op(ins, attrs, op):
    """ref lstmp_op.cc: LSTM with a recurrent projection — the projected
    state (B,T,P) is the recurrent input and the output."""
    x = _one(ins, "Input")          # (B, T, 4H)
    w = _one(ins, "Weight")         # (P, 4H)
    proj = _one(ins, "ProjWeight")  # (H, P)
    b = _one(ins, "Bias")
    mask = _one(ins, "Mask")
    B, T, H4 = x.shape
    H = H4 // 4
    P = proj.shape[1]
    h0 = jnp.zeros((B, P), x.dtype)
    c0 = jnp.zeros((B, H), x.dtype)
    hs, cs = _lstm_scan(x, w, b, h0, c0, mask, proj=proj)
    return {"Projection": [hs], "Cell": [cs]}


@register_op("cudnn_lstm")
def _cudnn_lstm_op(ins, attrs, op):
    """ref cudnn_lstm_op.cu: time-major (T,B,I) input with packed weights;
    single layer, unidirectional subset (the multi-layer/bidir config is a
    stack of this rule).  W packs [Wx (I,4H); Wh (H,4H); b (4H)]."""
    x = _one(ins, "Input")   # (T, B, I)
    w = _one(ins, "W")
    hidden_size = attrs["hidden_size"]
    T, B, inp = x.shape
    H = hidden_size
    wx = w[:inp * 4 * H].reshape(inp, 4 * H)
    wh = w[inp * 4 * H:(inp + H) * 4 * H].reshape(H, 4 * H)
    b = w[(inp + H) * 4 * H:(inp + H) * 4 * H + 4 * H]
    gates = jnp.einsum("tbi,ih->tbh", x, wx)
    hs, cs = _lstm_scan(jnp.swapaxes(gates, 0, 1), wh, b,
                        jnp.zeros((B, H), x.dtype),
                        jnp.zeros((B, H), x.dtype))
    return {"Out": [jnp.swapaxes(hs, 0, 1)],
            "LastH": [hs[:, -1]], "LastC": [cs[:, -1]]}


def _gru_scan(gates_x, w_h, h0, mask=None):
    """gates_x (B,T,3H) pre-projected; w_h (H,3H): [:, :2H] update/reset,
    [:, 2H:] candidate (ref gru_unit_op.h layout)."""
    B, T, H3 = gates_x.shape
    H = H3 // 3

    def step(h, t_in):
        xt, mt = t_in
        uh = h @ w_h[:, :2 * H]
        r = _sig(xt[:, :H] + uh[:, :H])
        z = _sig(xt[:, H:2 * H] + uh[:, H:])
        c = jnp.tanh(xt[:, 2 * H:] + (r * h) @ w_h[:, 2 * H:])
        h_new = z * h + (1 - z) * c
        if mt is not None:
            h_new = h_new * mt + h * (1 - mt)
        return h_new, h_new

    xs = jnp.swapaxes(gates_x, 0, 1)
    ms = (jnp.swapaxes(mask, 0, 1)[..., None]
          if mask is not None else jnp.ones((T, 1, 1), gates_x.dtype))
    _, hs = jax.lax.scan(step, h0, (xs, ms))
    return jnp.swapaxes(hs, 0, 1)


@register_op("gru")
def _gru_op(ins, attrs, op):
    """ref gru_op.cc (padded): Input (B,T,3H), Weight (H,3H), Bias (3H)."""
    x = _one(ins, "Input")
    w = _one(ins, "Weight")
    b = _one(ins, "Bias")
    mask = _one(ins, "Mask")
    if b is not None:
        x = x + b
    B, T, H3 = x.shape
    H = H3 // 3
    h0 = _one(ins, "H0")
    if h0 is None:
        h0 = jnp.zeros((B, H), x.dtype)
    hs = _gru_scan(x, w, h0, mask)
    return {"Hidden": [hs]}


@register_op("fusion_lstm")
def _fusion_lstm_op(ins, attrs, op):
    """ref fused/fusion_lstm_op.cc: X (B,T,M) @ WeightX (M,4H) + lstm —
    the input projection and recurrence in one op."""
    x = _one(ins, "X")
    wx = _one(ins, "WeightX")
    wh = _one(ins, "WeightH")
    b = _one(ins, "Bias")
    mask = _one(ins, "Mask")
    B, T, _ = x.shape
    H = wh.shape[0]
    gates = jnp.einsum("btm,mh->bth", x, wx)
    hs, cs = _lstm_scan(gates, wh, b, jnp.zeros((B, H), x.dtype),
                        jnp.zeros((B, H), x.dtype), mask)
    return {"Hidden": [hs], "Cell": [cs]}


@register_op("fusion_gru")
def _fusion_gru_op(ins, attrs, op):
    """ref fused/fusion_gru_op.cc: X @ WeightX then the GRU recurrence."""
    x = _one(ins, "X")
    wx = _one(ins, "WeightX")
    wh = _one(ins, "WeightH")
    b = _one(ins, "Bias")
    mask = _one(ins, "Mask")
    B, T, _ = x.shape
    H = wh.shape[0]
    gates = jnp.einsum("btm,mh->bth", x, wx)
    if b is not None:
        gates = gates + b
    hs = _gru_scan(gates, wh, jnp.zeros((B, H), x.dtype), mask)
    return {"Hidden": [hs]}


@register_op("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm_op(ins, attrs, op):
    """ref fused_embedding_fc_lstm_op.cc: ids -> embedding (the fc is
    folded into the embedding table) -> lstm."""
    ids = _one(ins, "Ids")          # (B, T) int
    emb = _one(ins, "Embeddings")   # (V, 4H) pre-projected rows
    wh = _one(ins, "WeightH")
    b = _one(ins, "Bias")
    gates = jnp.take(emb, ids.astype(jnp.int32), axis=0)  # (B,T,4H)
    B = gates.shape[0]
    H = wh.shape[0]
    hs, cs = _lstm_scan(gates, wh, b, jnp.zeros((B, H), gates.dtype),
                        jnp.zeros((B, H), gates.dtype))
    return {"Hidden": [hs], "Cell": [cs]}


# =========================================================================
# fusion ops (ref operators/fused/)
# =========================================================================

@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ins, attrs, op):
    """ref fusion_repeated_fc_relu_op.cc: x -> [fc -> relu]*N."""
    x = _one(ins, "X")
    for w, b in zip(ins["W"], ins["Bias"]):
        x = jax.nn.relu(x @ w + b)
    return {"Out": [x]}


@register_op("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ins, attrs, op):
    """ref fusion_squared_mat_sub_op.cc: scalar * ((x@y)^2 - x^2@y^2)."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    s = attrs.get("scalar", 1.0)
    xy = x @ y
    return {"Out": [s * (xy * xy - (x * x) @ (y * y))]}


@register_op("fusion_seqpool_concat")
def _fusion_seqpool_concat(ins, attrs, op):
    """ref fusion_seqpool_concat_op.cc: per-input sequence_pool (padded
    (B,T,D) + shared Length) then feature concat."""
    from ..ops import sequence as S

    length = _one(ins, "Length")
    ptype = attrs.get("pooltype", "SUM").lower()
    pooled = [S.sequence_pool(x, length, pool_type=ptype)
              for x in ins["X"]]
    return {"Out": [jnp.concatenate(pooled, axis=-1)]}


@register_op("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ins, attrs, op):
    """ref fusion_seqconv_eltadd_relu_op.cc: sequence_conv + bias + relu
    over the padded layout."""
    from ..ops import misc as M

    out = M.sequence_conv(_one(ins, "X"), _one(ins, "Filter"),
                          lengths=_one(ins, "Length"),
                          context_length=attrs["contextLength"],
                          context_start=attrs.get("contextStart"))
    return {"Out": [jax.nn.relu(out + _one(ins, "Bias"))]}


@register_op("fsp")
def _fsp(ins, attrs, op):
    """ref fsp_op.cc (knowledge distillation): normalized gram matrix
    between two feature maps, (B, C1, C2)."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    B, C1 = x.shape[0], x.shape[1]
    C2 = y.shape[1]
    hw = x.shape[2] * x.shape[3]
    g = jnp.einsum("bchw,bdhw->bcd", x, y) / hw
    return {"Out": [g.reshape(B, C1, C2)]}


@register_op("inplace_abn")
def _inplace_abn(ins, attrs, op):
    """ref inplace_abn_op.cc: batch_norm + activation (the in-place memory
    trick is XLA's buffer assignment problem, not ours)."""
    training = not attrs.get("is_test", False)
    out, new_rm, new_rv = F.batch_norm(
        _one(ins, "X"), _one(ins, "Mean"), _one(ins, "Variance"),
        weight=_one(ins, "Scale"), bias=_one(ins, "Bias"),
        training=training, momentum=attrs.get("momentum", 0.9),
        epsilon=attrs.get("epsilon", 1e-5))
    act = attrs.get("activation", "identity")
    if act == "leaky_relu":
        out = jax.nn.leaky_relu(out, attrs.get("alpha", 0.01))
    elif act == "elu":
        out = jax.nn.elu(out, attrs.get("alpha", 1.0))
    elif act != "identity":
        out = getattr(jax.nn, act)(out)
    return {"Y": [out], "MeanOut": [new_rm], "VarianceOut": [new_rv]}


# =========================================================================
# pooling tails: max_pool3d_with_index, unpool
# =========================================================================

@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ins, attrs, op):
    x = _one(ins, "X")
    ks = tuple(attrs["ksize"])
    st = tuple(attrs.get("strides", ks))
    N, C, D, H, W = x.shape
    kd, kh, kw = ks
    sd, sh, sw = st
    od, oh, ow = (D - kd) // sd + 1, (H - kh) // sh + 1, (W - kw) // sw + 1
    # patch-extract view then argmax per window (flat index in the volume)
    patches = jnp.stack([
        x[:, :, i * sd:i * sd + kd, j * sh:j * sh + kh, k * sw:k * sw + kw]
        .reshape(N, C, -1)
        for i in range(od) for j in range(oh) for k in range(ow)], axis=2)
    out = patches.max(axis=-1).reshape(N, C, od, oh, ow)
    arg = patches.argmax(axis=-1).reshape(N, C, od, oh, ow)
    # convert window-local argmax to the global flat D*H*W index
    li = jnp.arange(od)[:, None, None] * sd
    lj = jnp.arange(oh)[None, :, None] * sh
    lk = jnp.arange(ow)[None, None, :] * sw
    wd = arg // (kh * kw)
    wh_ = (arg // kw) % kh
    wk = arg % kw
    gidx = ((li + wd) * H + (lj + wh_)) * W + (lk + wk)
    return {"Out": [out], "Mask": [gidx.astype(jnp.int32)]}


@register_op("unpool")
def _unpool(ins, attrs, op):
    """ref unpool_op.cc: scatter pooled values back to the argmax
    positions recorded by max_pool2d_with_index."""
    x = _one(ins, "X")          # (N, C, oh, ow)
    idx = _one(ins, "Indices")  # flat H*W indices
    H, W = attrs["unpool_size"] if "unpool_size" in attrs else (
        attrs["output_size"][0], attrs["output_size"][1])
    N, C = x.shape[0], x.shape[1]
    flat = jnp.zeros((N, C, H * W), x.dtype)
    out = flat.at[
        jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None],
        idx.reshape(N, C, -1)].add(x.reshape(N, C, -1))
    return {"Out": [out.reshape(N, C, H, W)]}


# =========================================================================
# tensor-array / LoD control ops (ref tensor_array_read_write_op.cc,
# array_to_lod_tensor_op.cc, shrink_rnn_memory_op.cc,
# merge/split_lod_tensor_op.cc)
# =========================================================================

def _static_index(i, what, op=None, attrs=None):
    """Tensor-array indices must be program-level constants.  Under the
    whole-program jit even a fill_constant value arrives as a tracer, so
    the rule constant-propagates from the producing op in the block (or
    an explicit ``index`` attr); a data-dependent index is structurally
    impossible (dynamic-length arrays cannot exist under jit —
    recurrences belong to StaticRNN/lax.scan, SURVEY §1 L4)."""
    if attrs is not None and "index" in attrs:
        return int(attrs["index"])
    if not isinstance(i, jax.core.Tracer):
        return int(np.asarray(i).reshape(-1)[0])
    if op is not None:
        iname = op.inputs.get("I", [None])[0]
        for prior in op.block.ops:
            if iname in prior.output_names():
                if prior.type == "fill_constant":
                    return int(prior.attrs.get("value", 0))
                break
    raise ValueError(
        f"{what} needs a program-constant index (fill_constant or the "
        "'index' attr): dynamic-length tensor arrays cannot exist under "
        "whole-program jit — recurrences belong to StaticRNN/lax.scan "
        "(SURVEY §1 L4)")


@register_op("write_to_array")
def _write_to_array(ins, attrs, op):
    i = _static_index(_one(ins, "I"), "write_to_array", op, attrs)
    arr = list(ins.get("Array", [None])[0] or []) \
        if ins.get("Array") else []
    while len(arr) <= i:
        arr.append(None)
    arr[i] = _one(ins, "X")
    return {"Out": [arr]}


@register_op("read_from_array")
def _read_from_array(ins, attrs, op):
    i = _static_index(_one(ins, "I"), "read_from_array", op, attrs)
    arr = _one(ins, "X")
    return {"Out": [arr[i]]}


@register_op("array_to_lod_tensor")
def _array_to_lod_tensor(ins, attrs, op):
    """Stack the time-step list back into a padded (T, ...) tensor (dense
    analogue of the LoD re-assembly)."""
    arr = _one(ins, "X")
    return {"Out": [jnp.stack(list(arr), axis=0)]}


@register_op("lod_tensor_to_array")
def _lod_tensor_to_array(ins, attrs, op):
    x = _one(ins, "X")
    return {"Out": [[x[t] for t in range(x.shape[0])]]}


@register_op("shrink_rnn_memory")
def _shrink_rnn_memory(ins, attrs, op):
    """ref shrink_rnn_memory_op.cc: in the dense layout every sequence is
    padded to the same length, so the memory never shrinks — identity,
    with masking handled by the recurrence itself."""
    return {"Out": [_one(ins, "X")]}


@register_op("merge_lod_tensor")
def _merge_lod_tensor(ins, attrs, op):
    """ref merge_lod_tensor_op.cc (IfElse runtime): rows from InTrue where
    Mask else InFalse."""
    mask = _one(ins, "Mask").reshape(-1).astype(bool)
    t, f = _one(ins, "InTrue"), _one(ins, "InFalse")
    shape = (-1,) + (1,) * (t.ndim - 1)
    return {"Out": [jnp.where(mask.reshape(shape), t, f)]}


@register_op("split_lod_tensor")
def _split_lod_tensor(ins, attrs, op):
    """ref split_lod_tensor_op.cc: dense analogue — both branches get the
    full batch with non-selected rows zeroed (static shapes; the IfElse
    merge re-selects by the same mask)."""
    x = _one(ins, "X")
    mask = _one(ins, "Mask").reshape(-1).astype(bool)
    shape = (-1,) + (1,) * (x.ndim - 1)
    m = mask.reshape(shape)
    return {"OutTrue": [jnp.where(m, x, 0)],
            "OutFalse": [jnp.where(m, 0, x)]}


# =========================================================================
# PS data-plane ops (ref distributed_lookup_table_op.cc, pscore
# pull_sparse/push_sparse) — host SparseTable reached via io_callback
# =========================================================================

_PS_TABLES = {}


def register_ps_table(name: str, table) -> None:
    """Bind a SparseTable/RemoteSparseTable for the PS data-plane ops."""
    _PS_TABLES[name] = table


def _table(attrs):
    name = attrs.get("table_name", attrs.get("table_id", "default"))
    try:
        return _PS_TABLES[str(name)]
    except KeyError:
        raise ValueError(
            f"PS table {name!r} not registered; call "
            "static.ops_tail2.register_ps_table(name, table) first"
        ) from None


def _pull_rule(ins, attrs, op):
    """Embedding rows fetched from the host/remote table mid-program:
    jax.pure_callback crosses from the jitted program to the PS client
    (the reference's RPC pull)."""
    ids = _one(ins, "Ids")
    table = _table(attrs)
    dim = int(table.dim)

    def host_pull(ids_np):
        return table.pull(np.asarray(ids_np).reshape(-1)).astype(np.float32)

    flat = ids.reshape(-1)
    rows = jax.pure_callback(
        host_pull,
        jax.ShapeDtypeStruct((flat.shape[0], dim), jnp.float32), flat)
    return {"Outputs" if "Outputs" in op.outputs else "Out":
            [rows.reshape(ids.shape + (dim,))]}


def _push_rule(ins, attrs, op):
    from jax.experimental import io_callback

    ids = _one(ins, "Ids")
    grads = _one(ins, "Grads" if ins.get("Grads") else "X")
    table = _table(attrs)
    lr = attrs.get("lr", 0.1)

    def host_push(ids_np, g_np):
        table.push(np.asarray(ids_np).reshape(-1),
                   np.asarray(g_np, np.float32), float(lr))
        return np.zeros((), np.int32)

    tok = io_callback(host_push, jax.ShapeDtypeStruct((), jnp.int32),
                      ids.reshape(-1),
                      grads.reshape(-1, grads.shape[-1]), ordered=True)
    return {"Out": [tok]} if "Out" in op.outputs else {}


for _name in ("distributed_lookup_table", "pull_sparse", "pull_sparse_v2"):
    register_op(_name)(_pull_rule)
for _name in ("push_sparse", "push_sparse_v2"):
    register_op(_name)(_push_rule)


@register_op("c_embedding")
def _c_embedding(ins, attrs, op):
    """ref collective c_embedding_op.cc: W is one vocab *partition* whose
    global offset is ``start_index``; out-of-partition ids yield zero rows
    and the caller allreduces partial results across the model group (the
    manual Megatron-style layout; the automatic path is
    ShardingPlan(embedding_shard=...) over the whole table)."""
    ids = _one(ins, "Ids")
    w = _one(ins, "W")
    start = int(attrs.get("start_index", 0))
    rows_per = int(w.shape[0])
    flat = ids.reshape(-1).astype(jnp.int32)
    local = flat - start
    mine = (local >= 0) & (local < rows_per)
    rows = jnp.take(w, jnp.clip(local, 0, rows_per - 1), axis=0)
    rows = jnp.where(mine[:, None], rows, jnp.zeros((), w.dtype))
    return {"Out": [rows.reshape(tuple(ids.shape) + (int(w.shape[-1]),))]}


@register_op("merge_ids")
def _merge_ids(ins, attrs, op):
    """ref merge_ids_op.cc: reassemble rows pulled per-shard back into the
    original id order."""
    # dense re-scope pairing split_ids: every shard carries the FULL
    # position-aligned vector with -1 where it does not own the slot, and
    # rows computed for the slots it owns; merging is a mask-select per
    # position (no scatter, no dynamic shapes)
    out = jnp.zeros_like(ins["X"][0])
    for ids_s, rows_s in zip(ins["Ids"], ins["X"]):
        mask = (ids_s.reshape(-1) >= 0)
        out = jnp.where(mask.reshape((-1,) + (1,) * (out.ndim - 1)),
                        rows_s, out)
    return {"Out": [out]}


@register_op("split_ids")
def _split_ids(ins, attrs, op):
    """ref split_ids_op.cc: route ids to N shards by id % N.  Static
    shapes: each shard gets the full-length vector with non-owned slots
    filled by -1 (the dense analogue of the reference's variable-length
    splits)."""
    ids = _one(ins, "Ids").reshape(-1)
    n = len(op.outputs["Out"])
    outs = [jnp.where(ids % n == s, ids, -1) for s in range(n)]
    return {"Out": outs}


@register_op("split_selected_rows")
def _split_selected_rows(ins, attrs, op):
    """Dense SelectedRows split: rows routed by height_sections."""
    x = _one(ins, "X")
    sections = attrs["height_sections"]
    outs, start = [], 0
    for h in sections:
        outs.append(x[start:start + h])
        start += h
    return {"Out": outs}


@register_op("split_byref")
def _split_byref(ins, attrs, op):
    x = _one(ins, "X")
    n = len(op.outputs["Out"])
    return {"Out": list(jnp.split(x, n, axis=0))}


@register_op("lookup_sparse_table_merge")
def _lookup_sparse_table_merge(ins, attrs, op):
    """ref lookup_sparse_table_merge_op.cc: union of id sets (dense:
    concat + unique via sort, padded with -1)."""
    ids = jnp.concatenate([x.reshape(-1) for x in ins["X"]])
    s = jnp.sort(ids)
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    return {"Out": [jnp.where(first, s, -1)]}


# =========================================================================
# host-IO ops (ref save_op.cc, load_op.cc, save_combine_op.cc,
# load_combine_op.cc, print_op.cc, py_func_op.cc)
# =========================================================================

@register_op("save")
def _save_op(ins, attrs, op):
    from jax.experimental import io_callback

    path = attrs["file_path"]
    x = _one(ins, "X")

    def host_save(arr):
        import os as _os

        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:  # exact path: np.save(str) appends .npy
            np.save(f, np.asarray(arr))
        return np.zeros((), np.int32)

    io_callback(host_save, jax.ShapeDtypeStruct((), jnp.int32), x,
                ordered=True)
    return {}


@register_op("save_combine")
def _save_combine_op(ins, attrs, op):
    from jax.experimental import io_callback

    path = attrs["file_path"]
    names = [str(n) for n in op.inputs["X"]]

    def host_save(*arrs):
        import os as _os

        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:  # exact path: np.savez(str) appends .npz
            np.savez(f, **{n: np.asarray(a) for n, a in zip(names, arrs)})
        return np.zeros((), np.int32)

    io_callback(host_save, jax.ShapeDtypeStruct((), jnp.int32),
                *ins["X"], ordered=True)
    return {}


@register_op("load")
def _load_op(ins, attrs, op):
    # shapes must be static under jit, so the file materializes at TRACE
    # time as a constant (the executor re-traces when the program changes)
    return {"Out": [jnp.asarray(np.load(attrs["file_path"]))]}


@register_op("load_combine")
def _load_combine_op(ins, attrs, op):
    data = np.load(attrs["file_path"])
    names = [str(n) for n in op.outputs["Out"]]
    return {"Out": [jnp.asarray(data[n]) for n in names]}


@register_op("print")
def _print_op(ins, attrs, op):
    from jax.experimental import io_callback

    x = _one(ins, "In")
    msg = attrs.get("message", "")

    def host_print(arr):
        print(f"{msg}{np.asarray(arr)}")
        return np.zeros((), np.int32)

    io_callback(host_print, jax.ShapeDtypeStruct((), jnp.int32), x,
                ordered=True)
    return {"Out": [x]}


_PY_FUNCS = {}


def register_py_func(fid: int, fn) -> None:
    """ref py_func_op.cc's python-callable registry."""
    _PY_FUNCS[int(fid)] = fn


@register_op("py_func")
def _py_func_op(ins, attrs, op):
    fn = _PY_FUNCS[int(attrs["forward_callable_id"])]
    out_shapes = attrs["out_shapes"]
    out_dtypes = attrs.get("out_dtypes", ["float32"] * len(out_shapes))
    def call_fn(*a):
        r = fn(*a)
        if not isinstance(r, (tuple, list)):
            r = (r,)
        return tuple(np.asarray(v) for v in r)

    results = jax.pure_callback(
        call_fn,
        tuple(jax.ShapeDtypeStruct(tuple(sh), np.dtype(d))
              for sh, d in zip(out_shapes, out_dtypes)),
        *ins.get("X", []))
    return {"Out": list(results)}


# =========================================================================
# int8 quantize/dequantize pair (ref mkldnn quantize_op.cc — the int8
# deployment data path; requantize rescales between int8 domains)
# =========================================================================

@register_op("quantize")
def _quantize_op(ins, attrs, op):
    x = _one(ins, "Input")
    scale = attrs.get("Scale", attrs.get("scale", 1.0))
    return {"Output": [jnp.clip(jnp.round(x * scale), -128, 127)
                       .astype(jnp.int8)]}


@register_op("dequantize")
def _dequantize_op(ins, attrs, op):
    x = _one(ins, "Input")
    scale = attrs.get("Scale", attrs.get("scale", 1.0))
    return {"Output": [x.astype(jnp.float32) / scale]}


@register_op("requantize")
def _requantize_op(ins, attrs, op):
    x = _one(ins, "Input")
    s_in = attrs.get("Scale_in", 1.0)
    s_out = attrs.get("Scale_out", 1.0)
    return {"Output": [jnp.clip(
        jnp.round(x.astype(jnp.float32) / s_in * s_out), -128, 127)
        .astype(jnp.int8)]}


@register_op("cross_entropy2")
def _cross_entropy2(ins, attrs, op):
    """ref cross_entropy_op2.cc: hard-label CE over PROBABILITIES with the
    intermediate XShape/MatchX the paired grad kernel wants."""
    x = _one(ins, "X")
    label = _one(ins, "Label").reshape(x.shape[:-1]).astype(jnp.int32)
    ignore = attrs.get("ignore_index", -100)
    match = jnp.take_along_axis(x, label[..., None], axis=-1)
    loss = -jnp.log(jnp.clip(match, 1e-12, None))
    loss = jnp.where(label[..., None] == ignore, 0.0, loss)
    return {"Y": [loss], "MatchX": [match], "XShape": [x]}


@register_op("sample_logits")
def _sample_logits(ins, attrs, op):
    """ref sample_logits_op.cc (sampled softmax): gather the true-label
    logit plus ``num_samples`` uniformly sampled negatives, with the
    log-probability correction."""
    from ..core import random as _random

    logits = _one(ins, "Logits")   # (B, C)
    labels = _one(ins, "Labels").reshape(-1).astype(jnp.int32)
    n = attrs["num_samples"]
    B, C = logits.shape
    samples = jax.random.randint(_random.next_key(), (B, n), 0, C)
    idx = jnp.concatenate([labels[:, None], samples], axis=1)  # (B, 1+n)
    sampled = jnp.take_along_axis(logits, idx, axis=1)
    # Q correction: uniform proposal q = n / C (ref subtracts log q)
    logq = jnp.log(jnp.asarray(n / C, jnp.float32))
    out = sampled - logq
    out = out.at[:, 0].set(sampled[:, 0])  # true label: no correction
    return {"SampledLogits": [out], "Samples": [idx],
            "SampledLabels": [jnp.zeros((B,), jnp.int32)]}
