"""Static-op long tail: lowering rules beyond the core working set.

Reference parity: the remainder of paddle/fluid/operators/ (SURVEY.md N27 —
467 registered ops): CTC (warpctc_op.cc), 3D conv/pool families
(conv_op.cc, pool_op.cc), the detection suite (operators/detection/), the
interpolate family (interpolate_v2_op.cc), the optimizer ops
(operators/optimizers/), beam search (beam_search_op.cc,
beam_search_decode_op.cc, gather_tree_op.cc), the fake-quantization ops
(fake_quantize_op.cc — consumed by the slim QAT pass), and the linalg /
manipulation / loss tail.  Each rule lowers to jax under the Executor's
trace; most delegate to the eager op library (paddle_tpu/ops/), which keeps
one numeric implementation per op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod
from ..nn import functional as F
from .registry import register_op


def _one(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


def _xo(fn, in_slot="X", out_slot="Out"):
    """X -> Out delegation rule."""

    def rule(ins, attrs, op):
        return {out_slot: [fn(_one(ins, in_slot))]}

    return rule


def _xyo(fn, a="X", b="Y", out="Out"):
    def rule(ins, attrs, op):
        return {out: [fn(_one(ins, a), _one(ins, b))]}

    return rule


# =========================================================================
# CTC + sequence distance (ref warpctc_op.cc, edit_distance_op.cc,
# ctc_align_op.cu)
# =========================================================================

@register_op("warpctc")
def _warpctc(ins, attrs, op):
    """Padded-mode warpctc: Logits (T,B,C), Label (B,L) + lengths."""
    logits = _one(ins, "Logits")
    label = _one(ins, "Label")
    llen = _one(ins, "LogitsLength")
    lablen = _one(ins, "LabelLength")
    loss = F.ctc_loss(logits, label, llen, lablen,
                      blank=attrs.get("blank", 0), reduction="none",
                      norm_by_times=attrs.get("norm_by_times", False))
    return {"Loss": [loss[:, None]]}


@register_op("edit_distance")
def _edit_distance(ins, attrs, op):
    from ..ops import ctc as C

    d, n = C.edit_distance(_one(ins, "Hyps"), _one(ins, "Refs"),
                           _one(ins, "HypsLength"), _one(ins, "RefsLength"),
                           normalized=attrs.get("normalized", True))
    return {"Out": [d], "SequenceNum": [n]}


@register_op("ctc_align")
def _ctc_align(ins, attrs, op):
    from ..ops import ctc as C

    out, lens = C.ctc_greedy_decoder(
        _one(ins, "Input"), attrs.get("blank", 0),
        _one(ins, "InputLength"),
        padding_value=attrs.get("padding_value", 0))
    return {"Output": [out], "OutputLength": [lens]}


# =========================================================================
# conv/pool 3D + depthwise + unfold + pad3d (ref conv_op.cc pool_op.cc
# conv_transpose_op.cc unfold_op.cc pad3d_op.cc)
# =========================================================================

def _conv_nd(ins, attrs, op, fn, transpose=False):
    kwargs = dict(stride=tuple(attrs.get("strides", (1,))),
                  padding=tuple(attrs.get("paddings", (0,))),
                  dilation=tuple(attrs.get("dilations", (1,))),
                  groups=attrs.get("groups", 1))
    if transpose:
        kwargs["output_padding"] = tuple(
            attrs.get("output_padding", (0,)) or (0,))
    out = fn(_one(ins, "Input"), _one(ins, "Filter"), **kwargs)
    b = _one(ins, "Bias")
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * (out.ndim - 2))
    return {"Output": [out]}


@register_op("conv3d")
def _conv3d(ins, attrs, op):
    return _conv_nd(ins, attrs, op, F.conv3d)


@register_op("conv3d_transpose")
def _conv3d_transpose(ins, attrs, op):
    return _conv_nd(ins, attrs, op, F.conv3d_transpose, transpose=True)


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ins, attrs, op):
    x = _one(ins, "Input")
    a = dict(attrs)
    a["groups"] = a.get("groups", 0) or x.shape[1]
    return _conv_nd(ins, a, op, F.conv2d)


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ins, attrs, op):
    x = _one(ins, "Input")
    a = dict(attrs)
    a["groups"] = a.get("groups", 0) or x.shape[1]
    return _conv_nd(ins, a, op, F.conv2d_transpose, transpose=True)


@register_op("pool3d")
def _pool3d(ins, attrs, op):
    x = _one(ins, "X")
    ksize = tuple(attrs["ksize"])
    if attrs.get("global_pooling", False):
        ksize = x.shape[2:]
    kwargs = dict(stride=tuple(attrs.get("strides", ksize)),
                  padding=tuple(attrs.get("paddings", (0, 0, 0))))
    if attrs.get("pooling_type", "max") == "max":
        out = F.max_pool3d(x, ksize, **kwargs)
    else:
        out = F.avg_pool3d(x, ksize, exclusive=attrs.get("exclusive", True),
                           **kwargs)
    return {"Out": [out]}


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ins, attrs, op):
    from ..ops import misc as M

    out, idx = M.max_pool2d_with_index(
        _one(ins, "X"), tuple(attrs["ksize"]),
        tuple(attrs.get("strides", attrs["ksize"])),
        tuple(attrs.get("paddings", (0, 0))))
    return {"Out": [out], "Mask": [idx]}


@register_op("unfold")
def _unfold(ins, attrs, op):
    """im2col (ref unfold_op.cc): (N,C,H,W) -> (N, C*kh*kw, L)."""
    x = _one(ins, "X")
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", (1, 1))
    p = list(attrs.get("paddings", (0, 0, 0, 0)))
    if len(p) == 2:  # symmetric (ph, pw)
        pads = [(p[0], p[0]), (p[1], p[1])]
    else:  # reference order: (up, left, down, right)
        pads = [(p[0], p[2]), (p[1], p[3])]
    dh, dw = attrs.get("dilations", (1, 1))
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), pads,
        rhs_dilation=(dh, dw), dimension_numbers=("NCHW", "OIHW", "NCHW"))
    return {"Y": [patches.reshape(n, c * kh * kw, -1)]}


@register_op("im2sequence")
def _im2sequence(ins, attrs, op):
    """ref im2sequence_op.cc: patches flattened to (N*L, C*kh*kw) rows."""
    x = _one(ins, "X")
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", (1, 1))
    p = attrs.get("paddings", (0, 0, 0, 0))
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(p[0], p[2]), (p[1], p[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # (N, C*kh*kw, Ho, Wo) -> (N*Ho*Wo, C*kh*kw)
    return {"Out": [jnp.moveaxis(patches, 1, -1).reshape(
        -1, c * kh * kw)]}


@register_op("pad3d")
def _pad3d(ins, attrs, op):
    x = _one(ins, "X")
    p = list(attrs["paddings"])  # (l, r, t, b, front, back) for NCDHW
    mode = attrs.get("mode", "constant")
    value = attrs.get("value", 0.0)
    cfg = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    if mode == "constant":
        out = jnp.pad(x, cfg, constant_values=value)
    else:
        jmode = {"reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        out = jnp.pad(x, cfg, mode=jmode)
    return {"Out": [out]}


@register_op("spectral_norm")
def _spectral_norm(ins, attrs, op):
    from ..ops import misc as M

    out, _ = M.spectral_norm(_one(ins, "Weight"), _one(ins, "U"),
                             power_iters=attrs.get("power_iters", 1),
                             epsilon=attrs.get("eps", 1e-12),
                             dim=attrs.get("dim", 0))
    return {"Out": [out]}


@register_op("affine_channel")
def _affine_channel(ins, attrs, op):
    x = _one(ins, "X")
    scale = _one(ins, "Scale").reshape(1, -1, *([1] * (x.ndim - 2)))
    bias = _one(ins, "Bias").reshape(1, -1, *([1] * (x.ndim - 2)))
    return {"Out": [x * scale + bias]}


@register_op("conv_shift")
def _conv_shift(ins, attrs, op):
    """ref conv_shift_op.cc: circular correlation of X (B,M) with Y (B,N)."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    m, n = x.shape[1], y.shape[1]
    half = (n - 1) // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    return {"Out": [jnp.einsum("bmn,bn->bm", x[:, idx], y)]}


# =========================================================================
# interpolate family (ref interpolate_op.cc / interpolate_v2_op.cc)
# =========================================================================

def _interp(mode):
    def rule(ins, attrs, op):
        x = _one(ins, "X")
        size = _one(ins, "OutSize")
        if size is not None:
            size = tuple(int(v) for v in np.asarray(size))  # proglint: host-sync-ok — static-shape contract: OutSize must be compile-time constant
        elif attrs.get("out_shape"):
            size = tuple(attrs["out_shape"])
        elif mode == "trilinear":
            size = (attrs["out_d"], attrs["out_h"], attrs["out_w"])
        elif mode == "linear":
            size = (attrs["out_w"],)
        else:
            size = (attrs["out_h"], attrs["out_w"])
        if mode == "linear":  # NCW via the bilinear kernel on (N,C,1,W)
            out = F.interpolate(x[:, :, None, :], size=(1,) + size,
                                mode="bilinear",
                                align_corners=attrs.get("align_corners",
                                                        True))[:, :, 0]
        else:
            out = F.interpolate(x, size=size, mode=mode,
                                align_corners=attrs.get("align_corners",
                                                        True))
        return {"Out": [out]}

    return rule


for _name, _mode in [
        ("bilinear_interp", "bilinear"), ("bilinear_interp_v2", "bilinear"),
        ("nearest_interp", "nearest"), ("nearest_interp_v2", "nearest"),
        ("bicubic_interp", "bicubic"), ("bicubic_interp_v2", "bicubic"),
        ("trilinear_interp", "trilinear"),
        ("trilinear_interp_v2", "trilinear"),
        ("linear_interp", "linear"), ("linear_interp_v2", "linear")]:
    register_op(_name)(_interp(_mode))


# =========================================================================
# detection suite (ref operators/detection/)
# =========================================================================

@register_op("yolo_box")
def _yolo_box(ins, attrs, op):
    from ..ops import vision as V

    boxes, scores = V.yolo_box(
        _one(ins, "X"), _one(ins, "ImgSize"), attrs["anchors"],
        attrs["class_num"], attrs.get("conf_thresh", 0.01),
        attrs.get("downsample_ratio", 32),
        clip_bbox=attrs.get("clip_bbox", True),
        scale_x_y=attrs.get("scale_x_y", 1.0))
    return {"Boxes": [boxes], "Scores": [scores]}


@register_op("yolov3_loss")
def _yolov3_loss(ins, attrs, op):
    from ..ops import vision as V

    loss = V.yolo_loss(
        _one(ins, "X"), _one(ins, "GTBox"), _one(ins, "GTLabel"),
        attrs["anchors"], attrs["anchor_mask"], attrs["class_num"],
        attrs.get("ignore_thresh", 0.7), attrs.get("downsample_ratio", 32),
        gt_score=_one(ins, "GTScore"),
        use_label_smooth=attrs.get("use_label_smooth", True),
        scale_x_y=attrs.get("scale_x_y", 1.0))
    return {"Loss": [loss]}


@register_op("multiclass_nms")
def _multiclass_nms(ins, attrs, op):
    from ..ops import vision as V

    bboxes = _one(ins, "BBoxes")   # (N, M, 4)
    scores = _one(ins, "Scores")   # (N, C, M)
    keep_top_k = attrs.get("keep_top_k", -1)
    if keep_top_k <= 0:
        keep_top_k = scores.shape[1] * scores.shape[2]
    nms_top_k = attrs.get("nms_top_k", -1)
    if nms_top_k <= 0:
        nms_top_k = scores.shape[2]

    def one_image(b, s):
        return V.multiclass_nms(
            b, s,
            score_threshold=attrs.get("score_threshold", 0.05),
            nms_top_k=nms_top_k, keep_top_k=keep_top_k,
            nms_threshold=attrs.get("nms_threshold", 0.3),
            normalized=attrs.get("normalized", True),
            background_label=attrs.get("background_label", 0))

    dets, num = jax.vmap(one_image)(bboxes, scores)  # (N, keep, 6), (N,)
    return {"Out": [dets], "NmsRoisNum": [num]}


@register_op("density_prior_box")
def _density_prior_box(ins, attrs, op):
    from ..ops import vision as V

    x, img = _one(ins, "Input"), _one(ins, "Image")
    boxes, var = V.density_prior_box(
        (x.shape[2], x.shape[3]), (img.shape[2], img.shape[3]),
        attrs["densities"], attrs["fixed_sizes"],
        attrs.get("fixed_ratios", (1.0,)), clip=attrs.get("clip", False),
        steps=(attrs.get("step_w", 0.0), attrs.get("step_h", 0.0)),
        offset=attrs.get("offset", 0.5),
        variances=attrs.get("variances", (0.1, 0.1, 0.2, 0.2)),
        flatten_to_2d=attrs.get("flatten_to_2d", False))
    return {"Boxes": [boxes], "Variances": [var]}


def _deform_conv_rule(with_mask):
    def rule(ins, attrs, op):
        from ..ops import vision as V

        out = V.deformable_conv(
            _one(ins, "Input"), _one(ins, "Offset"), _one(ins, "Filter"),
            mask=_one(ins, "Mask") if with_mask else None,
            stride=tuple(attrs.get("strides", (1, 1))),
            padding=tuple(attrs.get("paddings", (0, 0))),
            dilation=tuple(attrs.get("dilations", (1, 1))),
            groups=attrs.get("groups", 1),
            deformable_groups=attrs.get("deformable_groups", 1))
        return {"Output": [out]}

    return rule


register_op("deformable_conv")(_deform_conv_rule(True))
register_op("deformable_conv_v1")(_deform_conv_rule(False))


@register_op("psroi_pool")
def _psroi_pool(ins, attrs, op):
    from ..ops import vision as V

    out = V.psroi_pool(
        _one(ins, "X"), _one(ins, "ROIs"), _one(ins, "RoisBatchId"),
        attrs["output_channels"], attrs["pooled_height"],
        attrs["pooled_width"], attrs.get("spatial_scale", 1.0))
    return {"Out": [out]}


@register_op("iou_similarity")
def _iou_similarity(ins, attrs, op):
    from ..ops import vision as V

    return {"Out": [V.iou_similarity(
        _one(ins, "X"), _one(ins, "Y"),
        box_normalized=attrs.get("box_normalized", True))]}


@register_op("box_clip")
def _box_clip(ins, attrs, op):
    from ..ops import vision as V

    return {"Output": [V.box_clip(_one(ins, "Input"), _one(ins, "ImInfo"))]}


@register_op("anchor_generator")
def _anchor_generator(ins, attrs, op):
    from ..ops import vision as V

    x = _one(ins, "Input")
    anchors, var = V.anchor_generator(
        (x.shape[2], x.shape[3]), attrs["anchor_sizes"],
        attrs["aspect_ratios"], attrs.get("stride", (16.0, 16.0)),
        variances=attrs.get("variances", (0.1, 0.1, 0.2, 0.2)),
        offset=attrs.get("offset", 0.5))
    return {"Anchors": [anchors], "Variances": [var]}


# =========================================================================
# optimizer ops (ref operators/optimizers/*.h) — slot contract mirrors the
# reference: Param/Grad/moments in, ParamOut/moment outs back
# =========================================================================

@register_op("adamax")
def _adamax_op(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m, u = _one(ins, "Moment"), _one(ins, "InfNorm")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    b1p = _one(ins, "Beta1Pow").astype(jnp.float32)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    g32 = g.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * g32
    u_new = jnp.maximum(b2 * u, jnp.abs(g32) + eps)
    p_new = p.astype(jnp.float32) - lr / (1 - b1p) * m_new / u_new
    return {"ParamOut": [p_new.astype(p.dtype)], "MomentOut": [m_new],
            "InfNormOut": [u_new]}


@register_op("adamw")
def _adamw_op(ins, attrs, op):
    from .ops import _adam  # reuse the adam rule

    coeff = attrs.get("coeff", 0.01)
    out = _adam(ins, attrs, op)
    p = _one(ins, "Param")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    p_new = out["ParamOut"][0].astype(jnp.float32) - lr * coeff * p.astype(
        jnp.float32)
    out["ParamOut"] = [p_new.astype(p.dtype)]
    return out


@register_op("adagrad")
def _adagrad_op(ins, attrs, op):
    p, g, acc = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    eps = attrs.get("epsilon", 1e-6)
    g32 = g.astype(jnp.float32)
    acc_new = acc + g32 * g32
    p_new = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc_new) + eps)
    return {"ParamOut": [p_new.astype(p.dtype)], "MomentOut": [acc_new]}


@register_op("decayed_adagrad")
def _decayed_adagrad_op(ins, attrs, op):
    p, g, acc = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Moment")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g32 = g.astype(jnp.float32)
    acc_new = decay * acc + (1 - decay) * g32 * g32
    p_new = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc_new) + eps)
    return {"ParamOut": [p_new.astype(p.dtype)], "MomentOut": [acc_new]}


@register_op("adadelta")
def _adadelta_op(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    avg_sq_g = _one(ins, "AvgSquaredGrad")
    avg_sq_u = _one(ins, "AvgSquaredUpdate")
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g32 = g.astype(jnp.float32)
    avg_sq_g_new = rho * avg_sq_g + (1 - rho) * g32 * g32
    upd = jnp.sqrt(avg_sq_u + eps) / jnp.sqrt(avg_sq_g_new + eps) * g32
    avg_sq_u_new = rho * avg_sq_u + (1 - rho) * upd * upd
    p_new = p.astype(jnp.float32) - upd
    return {"ParamOut": [p_new.astype(p.dtype)],
            "AvgSquaredGradOut": [avg_sq_g_new],
            "AvgSquaredUpdateOut": [avg_sq_u_new]}


@register_op("rmsprop")
def _rmsprop_op(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    ms, mg = _one(ins, "MeanSquare"), _one(ins, "MeanGrad")
    mom = _one(ins, "Moment")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-10)
    momentum = attrs.get("momentum", 0.0)
    g32 = g.astype(jnp.float32)
    ms_new = rho * ms + (1 - rho) * g32 * g32
    if attrs.get("centered", False):
        mg_new = rho * mg + (1 - rho) * g32
        denom = jnp.sqrt(ms_new - mg_new * mg_new + eps)
    else:
        mg_new = mg
        denom = jnp.sqrt(ms_new + eps)
    mom_new = momentum * mom + lr * g32 / denom
    p_new = p.astype(jnp.float32) - mom_new
    return {"ParamOut": [p_new.astype(p.dtype)], "MeanSquareOut": [ms_new],
            "MeanGradOut": [mg_new], "MomentOut": [mom_new]}


@register_op("ftrl")
def _ftrl_op(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    sq, lin = _one(ins, "SquaredAccumulator"), _one(ins, "LinearAccumulator")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    sq_new = sq + g32 * g32
    pow_old = sq ** (-lr_power)
    pow_new = sq_new ** (-lr_power)
    sigma = (pow_new - jnp.where(sq > 0, pow_old, 0.0)) / lr
    lin_new = lin + g32 - sigma * p32
    quad = pow_new / lr + 2 * l2
    pre = jnp.clip(lin_new, -l1, l1) - lin_new
    p_new = jnp.where(jnp.abs(lin_new) > l1, pre / quad, jnp.zeros_like(p32))
    return {"ParamOut": [p_new.astype(p.dtype)],
            "SquaredAccumOut": [sq_new], "LinearAccumOut": [lin_new]}


@register_op("lamb")
def _lamb_op(ins, attrs, op):
    p, g = _one(ins, "Param"), _one(ins, "Grad")
    m, v = _one(ins, "Moment1"), _one(ins, "Moment2")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    b1p = _one(ins, "Beta1Pow").astype(jnp.float32)
    b2p = _one(ins, "Beta2Pow").astype(jnp.float32)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    g32 = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    m_new = b1 * m + (1 - b1) * g32
    v_new = b2 * v + (1 - b2) * g32 * g32
    mhat = m_new / (1 - b1p * b1)
    vhat = v_new / (1 - b2p * b2)
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
    p_norm = jnp.linalg.norm(p32)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_new = p32 - lr * trust * r
    return {"ParamOut": [p_new.astype(p.dtype)], "Moment1Out": [m_new],
            "Moment2Out": [v_new], "Beta1PowOut": [b1p * b1],
            "Beta2PowOut": [b2p * b2]}


@register_op("lars_momentum")
def _lars_momentum_op(ins, attrs, op):
    p, g, vel = _one(ins, "Param"), _one(ins, "Grad"), _one(ins, "Velocity")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 1e-9)
    g32, p32 = g.astype(jnp.float32), p.astype(jnp.float32)
    p_norm = jnp.linalg.norm(p32)
    g_norm = jnp.linalg.norm(g32)
    local_lr = jnp.where((p_norm > 0) & (g_norm > 0),
                         coeff * p_norm / (g_norm + wd * p_norm + eps), 1.0)
    v_new = mu * vel + lr * local_lr * (g32 + wd * p32)
    p_new = p32 - v_new
    return {"ParamOut": [p_new.astype(p.dtype)], "VelocityOut": [v_new]}


@register_op("dpsgd")
def _dpsgd_op(ins, attrs, op):
    from ..core import random as _random

    p, g = _one(ins, "Param"), _one(ins, "Grad")
    lr = _one(ins, "LearningRate").astype(jnp.float32)
    clip = attrs.get("clip", 10.0)
    sigma = attrs.get("sigma", 1.0)
    g32 = g.astype(jnp.float32)
    g_norm = jnp.linalg.norm(g32)
    g_clip = g32 / jnp.maximum(1.0, g_norm / clip)
    noise = sigma * clip * jax.random.normal(_random.next_key(), g32.shape,
                                             jnp.float32)
    p_new = p.astype(jnp.float32) - lr * (g_clip + noise)
    return {"ParamOut": [p_new.astype(p.dtype)]}


# =========================================================================
# beam search (ref beam_search_op.cc, beam_search_decode_op.cc,
# gather_tree_op.cc) — dense (batch, beam) layout
# =========================================================================

@register_op("beam_search")
def _beam_search(ins, attrs, op):
    """One dense beam step: scores (B, beam, V) cumulative log-probs ->
    top-beam (ids, parents, scores)."""
    scores = _one(ins, "Scores")
    beam = attrs["beam_size"]
    B, K, V = scores.shape
    flat = scores.reshape(B, K * V)
    top, idx = jax.lax.top_k(flat, beam)
    return {"SelectedIds": [(idx % V).astype(jnp.int32)],
            "ParentIdx": [(idx // V).astype(jnp.int32)],
            "SelectedScores": [top]}


@register_op("gather_tree")
def _gather_tree(ins, attrs, op):
    from ..nn.decode import gather_tree as gt

    return {"Out": [gt(_one(ins, "Ids"), _one(ins, "Parents"))]}


@register_op("beam_search_decode")
def _beam_search_decode(ins, attrs, op):
    """Backtrack full beams (ref beam_search_decode_op.cc), dense layout:
    Ids/ParentIdx (T, B, beam) -> time-major token paths + final scores."""
    from ..nn.decode import gather_tree as gt

    ids = _one(ins, "Ids")
    parents = _one(ins, "ParentIdx")
    scores = _one(ins, "Scores")
    return {"SentenceIds": [gt(ids, parents)],
            "SentenceScores": [scores[-1] if scores is not None
                               else jnp.zeros(ids.shape[1:], jnp.float32)]}


# =========================================================================
# fake quantization (ref fake_quantize_op.cc) — STE rounding; consumed by
# slim's static QAT pass
# =========================================================================

def _qmax(bits):
    return float(2 ** (bits - 1) - 1)


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ins, attrs, op):
    x = _one(ins, "X")
    qm = _qmax(attrs.get("bit_length", 8))
    scale = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    q = jnp.round(x / scale * qm)
    return {"Out": [q], "OutScale": [scale.reshape(1)]}


@register_op("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(ins, attrs, op):
    from ..slim.quant import fake_quant_dequant_abs_max

    y, scale = fake_quant_dequant_abs_max(
        _one(ins, "X"), attrs.get("bit_length", 8))
    return {"Out": [y], "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_dequantize_abs_max")
def _fake_cw_qdq_abs_max(ins, attrs, op):
    from ..slim.quant import fake_channel_wise_quant_dequant_abs_max

    y, scale = fake_channel_wise_quant_dequant_abs_max(
        _one(ins, "X"), attrs.get("bit_length", 8),
        quant_axis=attrs.get("quant_axis", 0))
    return {"Out": [y], "OutScale": [scale]}


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def _fake_qdq_moving_avg(ins, attrs, op):
    x = _one(ins, "X")
    state = _one(ins, "InScale")
    rate = attrs.get("moving_rate", 0.9)
    qm = _qmax(attrs.get("bit_length", 8))
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = jnp.where(state.reshape(()) > 0,
                      rate * state.reshape(()) + (1 - rate) * cur, cur)
    q = jnp.round(jnp.clip(x / scale, -1.0, 1.0) * qm) / qm * scale
    # straight-through estimator: identity gradient
    y = x + jax.lax.stop_gradient(q - x)
    return {"Out": [y], "OutScale": [scale.reshape(1)]}


@register_op("moving_average_abs_max_scale")
def _moving_avg_scale(ins, attrs, op):
    x = _one(ins, "X")
    state = _one(ins, "InScale")
    rate = attrs.get("moving_rate", 0.9)
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = jnp.where(state.reshape(()) > 0,
                      rate * state.reshape(()) + (1 - rate) * cur, cur)
    return {"Out": [x], "OutScale": [scale.reshape(1)]}


# =========================================================================
# linalg / manipulation / loss long tail — delegation to the eager library
# (ref operators/<name>_op.cc for each)
# =========================================================================

def _register_delegates():
    from .. import ops as T

    register_op("matmul_v2")(
        lambda ins, attrs, op: {"Out": [T.matmul(
            _one(ins, "X"), _one(ins, "Y"),
            transpose_x=attrs.get("trans_x", False),
            transpose_y=attrs.get("trans_y", False))]})
    register_op("bmm")(_xyo(T.bmm))
    register_op("dot")(_xyo(T.dot))
    register_op("cross")(
        lambda ins, attrs, op: {"Out": [T.cross(
            _one(ins, "X"), _one(ins, "Y"),
            axis=attrs.get("dim", attrs.get("axis", -1)))]})
    register_op("inverse")(_xo(T.inverse, "Input", "Output"))
    register_op("cholesky")(
        lambda ins, attrs, op: {"Out": [T.cholesky(
            _one(ins, "X"), upper=attrs.get("upper", False))]})
    register_op("kron")(_xyo(T.kron))
    register_op("addmm")(
        lambda ins, attrs, op: {"Out": [T.addmm(
            _one(ins, "Input"), _one(ins, "X"), _one(ins, "Y"),
            beta=attrs.get("Beta", 1.0), alpha=attrs.get("Alpha", 1.0))]})
    register_op("trace")(
        lambda ins, attrs, op: {"Out": [T.trace(
            _one(ins, "Input"), offset=attrs.get("offset", 0),
            axis1=attrs.get("axis1", 0), axis2=attrs.get("axis2", 1))]})
    register_op("dist")(
        lambda ins, attrs, op: {"Out": [T.dist(
            _one(ins, "X"), _one(ins, "Y"), p=attrs.get("p", 2.0))]})
    register_op("p_norm")(
        lambda ins, attrs, op: {"Out": [T.p_norm(
            _one(ins, "X"), p=attrs.get("porder", 2.0),
            axis=attrs.get("axis", -1),
            keepdim=attrs.get("keepdim", False))]})
    register_op("frobenius_norm")(
        lambda ins, attrs, op: {"Out": [T.frobenius_norm(
            _one(ins, "X"), axis=tuple(attrs["dim"]) if attrs.get("dim")
            else None, keepdim=attrs.get("keep_dim", False))]})
    register_op("logsumexp")(
        lambda ins, attrs, op: {"Out": [T.logsumexp(
            _one(ins, "X"), axis=tuple(attrs["axis"]) if attrs.get("axis")
            else None, keepdim=attrs.get("keepdim", False))]})
    register_op("l1_norm")(
        lambda ins, attrs, op: {"Out": [T.l1_norm(_one(ins, "X"))]})
    register_op("squared_l2_distance")(
        lambda ins, attrs, op: (lambda d: {
            "Out": [jnp.sum(d * d, axis=tuple(range(1, d.ndim)),
                            keepdims=True)],
            "sub_result": [d]})(_one(ins, "X") - _one(ins, "Y")))
    register_op("clip_by_norm")(
        lambda ins, attrs, op: (lambda x, mn: {
            "Out": [x * jnp.minimum(1.0, mn / jnp.maximum(
                jnp.linalg.norm(x), 1e-12))]})(
                _one(ins, "X"), attrs["max_norm"]))

    # manipulation
    register_op("flip")(
        lambda ins, attrs, op: {"Out": [T.flip(
            _one(ins, "X"), attrs["axis"])]})
    register_op("roll")(
        lambda ins, attrs, op: {"Out": [T.roll(
            _one(ins, "X"), attrs["shifts"],
            attrs.get("axis", attrs.get("dims", None)))]})
    register_op("tril_triu")(
        lambda ins, attrs, op: {"Out": [
            (T.tril if attrs.get("lower", True) else T.triu)(
                _one(ins, "X"), attrs.get("diagonal", 0))]})
    register_op("index_select")(
        lambda ins, attrs, op: {"Out": [T.index_select(
            _one(ins, "X"), _one(ins, "Index"),
            axis=attrs.get("dim", 0))]})
    register_op("index_sample")(_xyo(T.index_sample, "X", "Index"))
    register_op("masked_select")(
        lambda ins, attrs, op: {"Y": [T.masked_select(
            _one(ins, "X"), _one(ins, "Mask"))]})
    register_op("meshgrid")(
        lambda ins, attrs, op: {"Out": list(T.meshgrid(*ins["X"]))})
    register_op("unbind")(
        lambda ins, attrs, op: {"Out": list(T.unbind(
            _one(ins, "X"), attrs.get("axis", 0)))})
    register_op("unstack")(
        lambda ins, attrs, op: {"Y": list(T.unstack(
            _one(ins, "X"), attrs.get("axis", 0)))})
    register_op("strided_slice")(
        lambda ins, attrs, op: {"Out": [T.strided_slice(
            _one(ins, "Input"), attrs["axes"], attrs["starts"],
            attrs["ends"], attrs.get("strides",
                                     [1] * len(attrs["axes"])))]})
    register_op("crop")(
        lambda ins, attrs, op: {"Out": [T.crop(
            _one(ins, "X"), shape=attrs.get("shape"),
            offsets=attrs.get("offsets"))]})
    register_op("crop_tensor")(
        lambda ins, attrs, op: {"Out": [T.crop(
            _one(ins, "X"), shape=attrs.get("shape"),
            offsets=attrs.get("offsets"))]})
    register_op("expand")(
        lambda ins, attrs, op: {"Out": [jnp.tile(
            _one(ins, "X"), attrs["expand_times"])]})
    register_op("expand_as")(
        lambda ins, attrs, op: {"Out": [jnp.broadcast_to(
            _one(ins, "X"), ins["target_tensor"][0].shape)]})
    register_op("expand_as_v2")(
        lambda ins, attrs, op: {"Out": [jnp.broadcast_to(
            _one(ins, "X"), tuple(attrs["target_shape"])
            if attrs.get("target_shape") else ins["Y"][0].shape)]})
    register_op("flatten")(
        lambda ins, attrs, op: (lambda x, ax: {"Out": [x.reshape(
            int(np.prod(x.shape[:ax])) if ax else 1, -1)]})(
            _one(ins, "X"), attrs.get("axis", 1)))
    register_op("squeeze")(
        lambda ins, attrs, op: {"Out": [T.squeeze(
            _one(ins, "X"), tuple(attrs.get("axes", ())) or None)]})
    register_op("unsqueeze")(
        lambda ins, attrs, op: {"Out": [T.unsqueeze(
            _one(ins, "X"), list(attrs["axes"]))]})
    register_op("reverse")(
        lambda ins, attrs, op: {"Out": [T.flip(
            _one(ins, "X"), attrs["axis"])]})
    register_op("pad_constant_like")(
        lambda ins, attrs, op: {"Out": [T.pad_constant_like(
            _one(ins, "X"), _one(ins, "Y"),
            attrs.get("pad_value", 0.0))]})
    register_op("scatter_nd_add")(
        lambda ins, attrs, op: {"Out": [T.scatter_nd_add(
            _one(ins, "X"), _one(ins, "Index"), _one(ins, "Updates"))]})
    register_op("shard_index")(
        lambda ins, attrs, op: (lambda x, sz, ni: {"Out": [jnp.where(
            x // sz == ni, x % sz, attrs.get("ignore_value", -1))]})(
            _one(ins, "X"),
            # ref shard_index_op.h: shard_size = ceil(index_num / nshards)
            -(-attrs["index_num"] // attrs["nshards"]),
            attrs["shard_id"]))
    register_op("top_k_v2")(
        lambda ins, attrs, op: (lambda v, i: {"Out": [v], "Indices": [i]})(
            *T.topk(_one(ins, "X"), attrs.get("k", 1),
                    axis=attrs.get("axis", -1),
                    largest=attrs.get("largest", True))))
    register_op("argsort")(
        lambda ins, attrs, op: (lambda x, ax, desc: {
            "Out": [jnp.flip(jnp.sort(x, axis=ax), axis=ax) if desc
                    else jnp.sort(x, axis=ax)],
            "Indices": [jnp.flip(jnp.argsort(x, axis=ax), axis=ax) if desc
                        else jnp.argsort(x, axis=ax)]})(
            _one(ins, "X"), attrs.get("axis", -1),
            attrs.get("descending", False)))
    def _lookup_table_v1(ins, attrs, op):
        # v1 ids carry a trailing length-1 dim; otherwise identical to
        # lookup_table_v2 — same routing (sharded exchange / is_sparse
        # segment-sum gradient / plain gather) and padding_idx zeroing
        from ..parallel import embedding as _pemb
        return {"Out": [_pemb.lower_lookup(
            ins["W"][0], _one(ins, "Ids").squeeze(-1), attrs,
            op.inputs.get("W", [""])[0])]}
    register_op("lookup_table")(_lookup_table_v1)
    register_op("size")(
        lambda ins, attrs, op: {"Out": [jnp.asarray(
            int(np.prod(_one(ins, "Input").shape)), jnp.int64)]})
    register_op("isfinite_v2")(_xo(jnp.isfinite))
    register_op("isinf_v2")(_xo(jnp.isinf))
    register_op("isnan_v2")(_xo(jnp.isnan))
    register_op("isfinite")(
        lambda ins, attrs, op: {"Out": [jnp.all(jnp.isfinite(
            _one(ins, "X")))[None]]})
    register_op("linspace")(_linspace)
    register_op("one_hot")(
        lambda ins, attrs, op: {"Out": [jax.nn.one_hot(
            _one(ins, "X").squeeze(-1), attrs["depth"],
            dtype=jnp.float32)]})
    register_op("assign_value")(
        lambda ins, attrs, op: {"Out": [jnp.asarray(
            attrs.get("fp32_values") or attrs.get("int32_values"),
            _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
        ).reshape(tuple(attrs["shape"]))]})
    def _partial_slice(xs, s, ln):
        # ref partial_sum_op.cc / partial_concat_op.cc: length=-1 means
        # "to the end of the row"
        end = xs[0].shape[1] if ln in (-1, None) else s + ln
        return [x[:, s:end] for x in xs]

    register_op("partial_sum")(
        lambda ins, attrs, op: {"Out": [sum(_partial_slice(
            ins["X"], attrs.get("start_index", 0),
            attrs.get("length", -1)))]})
    register_op("partial_concat")(
        lambda ins, attrs, op: {"Out": [jnp.concatenate(_partial_slice(
            ins["X"], attrs.get("start_index", 0),
            attrs.get("length", -1)), axis=1)]})
    register_op("batch_fc")(
        lambda ins, attrs, op: {"Out": [jnp.einsum(
            "bsi,bio->bso", _one(ins, "Input"), _one(ins, "W"))
            + _one(ins, "Bias")]})
    register_op("shuffle_batch")(_shuffle_batch)
    register_op("lod_reset")(
        lambda ins, attrs, op: {"Out": [_one(ins, "X")]})
    register_op("minus")(_xyo(T.minus))
    register_op("cvm")(
        lambda ins, attrs, op: {"Y": [T.cvm(
            _one(ins, "X"), use_cvm=attrs.get("use_cvm", True))]})
    register_op("data_norm")(
        lambda ins, attrs, op: (lambda r: {
            "Y": [r[0]], "BatchSizeOut": [r[1]], "BatchSumOut": [r[2]],
            "BatchSquareSumOut": [r[3]]})(
            T.data_norm(_one(ins, "X"), _one(ins, "BatchSize"),
                        _one(ins, "BatchSum"), _one(ins, "BatchSquareSum"),
                        epsilon=attrs.get("epsilon", 1e-4))))
    register_op("get_tensor_from_selected_rows")(
        lambda ins, attrs, op: {"Out": [_one(ins, "X")]})
    register_op("merge_selected_rows")(
        lambda ins, attrs, op: {"Out": [_one(ins, "X")]})
    register_op("coalesce_tensor")(_coalesce_tensor)

    # losses
    register_op("bce_loss")(
        lambda ins, attrs, op: {"Out": [F.binary_cross_entropy(
            _one(ins, "X"), _one(ins, "Label"), reduction="none")]})
    register_op("nll_loss")(
        lambda ins, attrs, op: {"Out": [F.nll_loss(
            _one(ins, "X"), _one(ins, "Label"),
            weight=_one(ins, "Weight"),
            ignore_index=attrs.get("ignore_index", -100),
            reduction=attrs.get("reduction", "mean"))],
            "Total_weight": [jnp.asarray(
                _one(ins, "X").shape[0], jnp.float32)]})
    register_op("hinge_loss")(
        lambda ins, attrs, op: {"Loss": [F.hinge_loss(
            _one(ins, "Logits"), _one(ins, "Labels"))]})
    register_op("margin_rank_loss")(
        lambda ins, attrs, op: {"Out": [F.margin_ranking_loss(
            _one(ins, "X1"), _one(ins, "X2"), _one(ins, "Label"),
            margin=attrs.get("margin", 0.0), reduction="none")]})
    register_op("bpr_loss")(_bpr_loss)
    register_op("center_loss")(_center_loss)
    register_op("cos_sim_v2")(
        lambda ins, attrs, op: {"Out": [T.cos_sim(
            _one(ins, "X"), _one(ins, "Y"))]})


def _linspace(ins, attrs, op):
    """ref linspace_op.cc.  Num fixes the OUTPUT SHAPE, so under the
    whole-program jit it must be static: attr ``num`` or a literal feed
    (a traced Num tensor cannot size an XLA buffer)."""
    num = attrs.get("num")
    if num is None:
        num_in = _one(ins, "Num")
        if isinstance(num_in, jax.core.Tracer):
            raise ValueError(
                "linspace: Num must be a static attr (or compile-time "
                "constant) — it determines the output shape under jit")
        num = int(np.asarray(num_in))  # proglint: host-sync-ok — static-shape contract enforced by the ValueError above
    return {"Out": [jnp.linspace(
        _one(ins, "Start").reshape(()), _one(ins, "Stop").reshape(()),
        int(num),
        dtype=_dtype_mod.convert_dtype(attrs.get("dtype", "float32")))]}


def _shuffle_batch(ins, attrs, op):
    from ..core import random as _random

    x = _one(ins, "X")
    perm = jax.random.permutation(_random.next_key(), x.shape[0])
    return {"Out": [x[perm]], "ShuffleIdx": [perm.astype(jnp.int64)]}


def _coalesce_tensor(ins, attrs, op):
    """ref coalesce_tensor_op.cc: fuse a var list into one flat buffer.
    XLA owns memory, so the fused buffer is a concatenation and the outputs
    alias slices of it."""
    xs = ins["Input"]
    flat = jnp.concatenate([x.reshape(-1) for x in xs])
    outs, offset = [], 0
    for x in xs:
        n = int(np.prod(x.shape))
        outs.append(flat[offset:offset + n].reshape(x.shape))
        offset += n
    return {"Output": outs, "FusedOutput": [flat]}


def _bpr_loss(ins, attrs, op):
    """ref bpr_loss_op.cc: pairwise ranking -mean(log(sigmoid(pos - negs)))."""
    x = _one(ins, "X")          # (B, C) scores
    label = _one(ins, "Label")  # (B, 1) positive class
    B, C = x.shape
    pos = jnp.take_along_axis(x, label.reshape(B, 1).astype(jnp.int32),
                              axis=1)
    diff = pos - x
    mask = jnp.ones((B, C)).at[jnp.arange(B),
                               label.reshape(B).astype(jnp.int32)].set(0.0)
    loss = -jnp.sum(jax.nn.log_sigmoid(diff) * mask, axis=1,
                    keepdims=True) / jnp.maximum(C - 1, 1)
    return {"Out": [loss]}


def _center_loss(ins, attrs, op):
    """ref center_loss_op.cc: 0.5*||x - center_label||²; centers update via
    the CenterUpdateRate when update_center is set."""
    x = _one(ins, "X")
    label = _one(ins, "Label").reshape(-1).astype(jnp.int32)
    centers = _one(ins, "Centers")
    rate = _one(ins, "CenterUpdateRate")
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    if attrs.get("need_update", True) and rate is not None:
        # the dense center-table update IS the op's semantics (ref
        # center_loss_op.cc)  # proglint: dense-intermediate-ok
        counts = jnp.zeros(centers.shape[0]).at[label].add(1.0)
        # proglint: dense-intermediate-ok
        delta = jnp.zeros_like(centers).at[label].add(diff)
        centers_new = centers + rate.reshape(()) * delta / (
            counts[:, None] + 1.0)
    else:
        centers_new = centers
    return {"Loss": [loss], "SampleCenterDiff": [diff],
            "CentersOut": [centers_new]}


_register_delegates()


# =========================================================================
# activation tail (ref operators/activation_op.cc registrations that the
# bulk batches in ops.py did not cover)
# =========================================================================

def _act(fn):
    def rule(ins, attrs, op):
        return {"Out": [fn(_one(ins, "X"), attrs)]}

    return rule


register_op("maxout")(
    lambda ins, attrs, op: (lambda x, g: {"Out": [jnp.max(
        x.reshape(x.shape[0], x.shape[1] // g, g, *x.shape[2:]),
        axis=2)]})(_one(ins, "X"), attrs["groups"]))
register_op("soft_relu")(_act(
    lambda x, a: jnp.log1p(jnp.exp(jnp.clip(x, -a.get("threshold", 40.0),
                                            a.get("threshold", 40.0))))))
register_op("brelu")(_act(
    lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0))))
register_op("stanh")(_act(
    lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
        a.get("scale_a", 0.67) * x)))
register_op("thresholded_relu")(_act(
    lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0)))
register_op("hard_shrink")(_act(
    lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0)))
register_op("softshrink")(_act(
    lambda x, a: (lambda lam: jnp.where(x > lam, x - lam,
                                        jnp.where(x < -lam, x + lam, 0.0)))(
        a.get("lambda", 0.5))))
register_op("tanh_shrink")(_act(lambda x, a: x - jnp.tanh(x)))
register_op("hard_tanh")(_act(
    lambda x, a: jnp.clip(x, a.get("t_min", -1.0), a.get("t_max", 1.0))))


# =========================================================================
# metrics (ref operators/metrics/): mean_iou, auc
# =========================================================================

@register_op("mean_iou")
def _mean_iou(ins, attrs, op):
    """ref mean_iou_op.h: mean of per-class intersection/union."""
    pred = _one(ins, "Predictions").reshape(-1).astype(jnp.int32)
    label = _one(ins, "Labels").reshape(-1).astype(jnp.int32)
    n = attrs["num_classes"]
    inter = jnp.zeros((n,), jnp.float32).at[
        jnp.where(pred == label, pred, n)].add(1.0, mode="drop")
    pred_cnt = jnp.zeros((n,), jnp.float32).at[pred].add(1.0, mode="drop")
    label_cnt = jnp.zeros((n,), jnp.float32).at[label].add(1.0, mode="drop")
    union = pred_cnt + label_cnt - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)
    valid = (union > 0).astype(jnp.float32)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1.0)
    return {"OutMeanIou": [mean], "OutWrong": [(pred_cnt - inter)],
            "OutCorrect": [inter]}


@register_op("auc")
def _auc(ins, attrs, op):
    """ref auc_op.h: batch AUC from the positive-class score histogram."""
    probs = _one(ins, "Predict")[:, 1]
    label = _one(ins, "Label").reshape(-1).astype(jnp.float32)
    bins = attrs.get("num_thresholds", 4095) + 1
    idx = jnp.clip((probs * (bins - 1)).astype(jnp.int32), 0, bins - 1)
    pos = jnp.zeros((bins,), jnp.float32).at[idx].add(label)
    neg = jnp.zeros((bins,), jnp.float32).at[idx].add(1.0 - label)
    # accumulate from the high-score end: at threshold bin b, tp = pos above
    tp = jnp.cumsum(pos[::-1])[::-1]
    fp = jnp.cumsum(neg[::-1])[::-1]
    tot_pos, tot_neg = tp[0], fp[0]
    # trapezoid over thresholds
    auc = jnp.sum((fp[:-1] - fp[1:]) * (tp[:-1] + tp[1:]) / 2.0)
    auc = jnp.where((tot_pos > 0) & (tot_neg > 0),
                    auc / jnp.maximum(tot_pos * tot_neg, 1.0), 0.0)
    return {"AUC": [auc], "StatPosOut": [pos], "StatNegOut": [neg]}


# =========================================================================
# padded sequence statics + RNN units + remaining quant/creation ops
# =========================================================================

@register_op("sequence_pad")
def _sequence_pad(ins, attrs, op):
    from ..ops import sequence as S

    out, lens = S.sequence_pad(_one(ins, "X"), _one(ins, "SegmentIds"),
                               attrs["batch"], attrs["maxlen"],
                               pad_value=attrs.get("pad_value", 0.0))
    return {"Out": [out], "Length": [lens]}


@register_op("sequence_unpad")
def _sequence_unpad(ins, attrs, op):
    from ..ops import sequence as S

    vals, seg, mask = S.sequence_unpad(_one(ins, "X"), _one(ins, "Length"))
    return {"Out": [vals], "SegmentIds": [seg], "Mask": [mask]}


@register_op("sequence_expand_padded")
def _sequence_expand_padded(ins, attrs, op):
    from ..ops import sequence as S

    return {"Out": [S.sequence_expand(_one(ins, "X"), _one(ins, "Length"),
                                      _one(ins, "RefLength"),
                                      attrs["maxlen"])]}


@register_op("sequence_slice_padded")
def _sequence_slice_padded(ins, attrs, op):
    from ..ops import sequence as S

    y, lens = S.sequence_slice(_one(ins, "X"), _one(ins, "Length"),
                               _one(ins, "Offset"), _one(ins, "SliceLength"))
    return {"Out": [y], "OutLength": [lens]}


@register_op("sequence_concat_padded")
def _sequence_concat_padded(ins, attrs, op):
    """Concatenate two padded sequence batches along time (ref
    sequence_concat_op.cc at LoD level 0), left-packing valid steps."""
    x, y = ins["X"]
    lx, ly = ins["Length"]
    B, Tx = x.shape[0], x.shape[1]
    Ty = y.shape[1]
    T = Tx + Ty
    t_idx = jnp.arange(T)[None, :]
    out_len = lx + ly
    from_x = t_idx < lx[:, None]
    xi = jnp.clip(t_idx, 0, Tx - 1)
    yi = jnp.clip(t_idx - lx[:, None], 0, Ty - 1)
    gx = jnp.take_along_axis(
        x, xi.reshape(B, T, *([1] * (x.ndim - 2))), axis=1)
    gy = jnp.take_along_axis(
        y, yi.reshape(B, T, *([1] * (y.ndim - 2))), axis=1)
    valid = t_idx < out_len[:, None]
    out = jnp.where(
        jnp.expand_dims(from_x, tuple(range(2, x.ndim))), gx, gy)
    out = jnp.where(jnp.expand_dims(valid, tuple(range(2, x.ndim))), out,
                    0.0)
    return {"Out": [out], "OutLength": [out_len]}


@register_op("gru_unit")
def _gru_unit(ins, attrs, op):
    """ref gru_unit_op.h: one GRU step from pre-projected input gates."""
    gates_x = _one(ins, "Input")       # (B, 3D) x-projection
    h_prev = _one(ins, "HiddenPrev")   # (B, D)
    w = _one(ins, "Weight")            # (D, 3D): [:, :2D] gates, [:, 2D:] cand
    b = _one(ins, "Bias")
    D = h_prev.shape[1]
    g = gates_x + (b if b is not None else 0.0)
    uh = h_prev @ w[:, :2 * D]
    r = jax.nn.sigmoid(g[:, :D] + uh[:, :D])
    z = jax.nn.sigmoid(g[:, D:2 * D] + uh[:, D:])
    c = jnp.tanh(g[:, 2 * D:] + (r * h_prev) @ w[:, 2 * D:])
    h = z * h_prev + (1 - z) * c
    return {"Hidden": [h], "ResetHiddenPrev": [r * h_prev], "Gate": [g]}


@register_op("lstm_unit")
def _lstm_unit(ins, attrs, op):
    """ref lstm_unit_op.h: one LSTM step from the fused gate
    pre-activations."""
    gates = _one(ins, "X")      # (B, 4D): i, f, c~, o  (ref ifco order)
    c_prev = _one(ins, "C_prev")
    D = c_prev.shape[1]
    fb = attrs.get("forget_bias", 0.0)
    i = jax.nn.sigmoid(gates[:, :D])
    f = jax.nn.sigmoid(gates[:, D:2 * D] + fb)
    g = jnp.tanh(gates[:, 2 * D:3 * D])
    o = jax.nn.sigmoid(gates[:, 3 * D:])
    c = f * c_prev + i * g
    return {"C": [c], "H": [o * jnp.tanh(c)]}


@register_op("fake_quantize_range_abs_max")
def _fake_quantize_range_abs_max(ins, attrs, op):
    x = _one(ins, "X")
    in_scale = _one(ins, "InScale")
    qm = _qmax(attrs.get("bit_length", 8))
    cur = jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)
    scale = jnp.maximum(in_scale.reshape(()), cur)
    return {"Out": [jnp.round(jnp.clip(x / scale, -1, 1) * qm)],
            "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_abs_max")
def _fake_cw_quantize_abs_max(ins, attrs, op):
    x = _one(ins, "X")
    axis = attrs.get("quant_axis", 0)
    qm = _qmax(attrs.get("bit_length", 8))
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red), 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    return {"Out": [jnp.round(x / scale.reshape(shape) * qm)],
            "OutScale": [scale]}


@register_op("fill_any_like")
def _fill_any_like(ins, attrs, op):
    x = _one(ins, "X")
    dtype = attrs.get("dtype", -1)
    dt = x.dtype if dtype in (-1, None) else _dtype_mod.convert_dtype(dtype)
    return {"Out": [jnp.full(x.shape, attrs.get("value", 0.0), dt)]}


@register_op("is_empty")
def _is_empty(ins, attrs, op):
    x = _one(ins, "X")
    return {"Out": [jnp.asarray(int(np.prod(x.shape)) == 0)]}


@register_op("smooth_l1")
def _smooth_l1(ins, attrs, op):
    """ref smooth_l1_loss_op.cc (sigma-weighted variant)."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    sigma = attrs.get("sigma", 1.0)
    inw = _one(ins, "InsideWeight")
    outw = _one(ins, "OutsideWeight")
    d = (x - y) * (inw if inw is not None else 1.0)
    s2 = sigma * sigma
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    if outw is not None:
        loss = loss * outw
    return {"Out": [jnp.sum(loss, axis=tuple(range(1, loss.ndim)),
                            keepdims=True)], "Diff": [d]}


@register_op("teacher_student_sigmoid_loss")
def _teacher_student_sigmoid_loss(ins, attrs, op):
    """ref teacher_student_sigmoid_loss_op.cc (distillation CTR loss)."""
    x = _one(ins, "X").reshape(-1)
    label = _one(ins, "Label").reshape(-1)
    soft_max_up = attrs.get("soft_max_up_bound", 15.0)
    soft_max_lo = attrs.get("soft_max_lower_bound", -15.0)
    z = jnp.clip(x, soft_max_lo, soft_max_up)
    # teacher label in (0,1) blends the hard CE with a soft sigmoid CE
    ce = jnp.maximum(z, 0.0) - z * (label > 0.5) + jnp.log1p(
        jnp.exp(-jnp.abs(z)))
    soft = jnp.maximum(z, 0.0) - z * label + jnp.log1p(jnp.exp(-jnp.abs(z)))
    loss = jnp.where((label > 0.0) & (label < 1.0), ce + soft, ce)
    return {"Y": [loss[:, None]]}


@register_op("reduce_all")
def _reduce_all(ins, attrs, op):
    x = _one(ins, "X")
    dim = attrs.get("dim")
    axis = tuple(range(x.ndim)) if attrs.get("reduce_all") or dim is None \
        else ((dim,) if isinstance(dim, int) else tuple(dim))
    return {"Out": [jnp.all(x, axis=axis,
                            keepdims=attrs.get("keep_dim", False))]}


@register_op("reduce_any")
def _reduce_any(ins, attrs, op):
    x = _one(ins, "X")
    dim = attrs.get("dim")
    axis = tuple(range(x.ndim)) if attrs.get("reduce_all") or dim is None \
        else ((dim,) if isinstance(dim, int) else tuple(dim))
    return {"Out": [jnp.any(x, axis=axis,
                            keepdims=attrs.get("keep_dim", False))]}


@register_op("diag")
def _diag(ins, attrs, op):
    """ref diag_op.cc: vector -> diagonal matrix."""
    return {"Out": [jnp.diagflat(_one(ins, "Diagonal"))]}


@register_op("fake_quantize_dequantize_fixed_scale")
def _fake_qdq_fixed_scale(ins, attrs, op):
    """Frozen-scale quant-dequant (emitted by QuantizationFreezePass / PTQ;
    the reference encodes the same thing as quantize+dequantize pairs with
    scale attributes after its freeze pass)."""
    x = _one(ins, "X")
    qm = _qmax(attrs.get("bit_length", 8))
    scale = attrs["scale"]
    q = jnp.round(jnp.clip(x / scale, -1.0, 1.0) * qm) / qm * scale
    return {"Out": [x + jax.lax.stop_gradient(q - x)]}
