"""Static-graph control flow: cond / while_loop + compare/logical DSL.

Reference parity: fluid/layers/control_flow.py (`cond`, `while_loop`,
`While`, `increment`, `less_than` ...) lowering to
operators/controlflow/conditional_block_op.cc and while_op.cc, which run
sub-blocks through a scoped Executor with mutable Scopes.

TPU-native design (SURVEY.md §7 "hard parts"): sub-blocks are real
`Block`s in the Program (built by running the user callbacks under
`Program._create_block`), and the Executor lowers the ops to
`jax.lax.cond` / `jax.lax.while_loop` — the reference's mutable-Scope
semantics become a functional environment snapshot: sub-block ops may read
any outer variable (closure capture), and the loop state is exactly the
`loop_vars` carry.  Consequences of the XLA model (documented contract):
  * both cond branches must produce matching shapes/dtypes,
  * while-loop carries are shape-invariant,
  * loop trip counts are data-dependent at *runtime* but the body is traced
    once (no Python side effects per iteration),
  * sub-block randomness is traced once: a dropout/random op inside a
    ``while_loop`` body draws from the same per-op PRNG key every iteration
    (the same mask repeats) — thread a counter through ``loop_vars`` and
    fold it in manually if per-iteration randomness is required,
  * ``append_backward`` rejects programs containing a ``while`` op:
    jax.lax.while_loop is not reverse-mode differentiable (see
    backward._reject_while_ops).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from .framework import Program, Variable, default_main_program
from .layers import _append, _main_block, _out, fill_constant

__all__ = [
    "cond", "while_loop", "StaticRNN", "increment", "less_than", "less_equal",
    "greater_than", "greater_equal", "equal", "not_equal", "logical_and",
    "logical_or", "logical_xor", "logical_not",
]


# -- compare / logical DSL (ref layers/control_flow.py less_than :1262 etc.) --
def _sym_broadcast(a, b):
    """np.broadcast_shapes that tolerates -1 (unknown) dims."""
    out = []
    for da, db in zip((1,) * (len(b) - len(a)) + tuple(a),
                      (1,) * (len(a) - len(b)) + tuple(b)):
        if da == 1:
            out.append(db)
        elif db == 1 or da == db:
            out.append(da)
        elif -1 in (da, db):
            out.append(-1)
        else:
            raise ValueError(f"cannot broadcast {a} with {b}")
    return tuple(out)


def _cmp(op_type, x: Variable, y: Variable) -> Variable:
    out = _out("bool", _sym_broadcast(x.shape, y.shape))
    _append(op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]})
    return out


def less_than(x, y):
    return _cmp("less_than", x, y)


def less_equal(x, y):
    return _cmp("less_equal", x, y)


def greater_than(x, y):
    return _cmp("greater_than", x, y)


def greater_equal(x, y):
    return _cmp("greater_equal", x, y)


def equal(x, y):
    return _cmp("equal", x, y)


def not_equal(x, y):
    return _cmp("not_equal", x, y)


def logical_and(x, y):
    return _cmp("logical_and", x, y)


def logical_or(x, y):
    return _cmp("logical_or", x, y)


def logical_xor(x, y):
    return _cmp("logical_xor", x, y)


def logical_not(x):
    out = _out("bool", x.shape)
    _append("logical_not", {"X": [x.name]}, {"Out": [out.name]})
    return out


def increment(x: Variable, value: float = 1.0, in_place: bool = True) -> Variable:
    """ref layers/control_flow.py increment :1203 — writes back to the same
    variable name so while-loop counters advance through the env."""
    out_name = x.name if in_place else None
    if in_place:
        _append("increment", {"X": [x.name]}, {"Out": [x.name]},
                {"step": float(value)})
        return x
    out = _out(x.dtype, x.shape)
    _append("increment", {"X": [x.name]}, {"Out": [out.name]},
            {"step": float(value)})
    return out


# -- structure helpers --------------------------------------------------------
def _flatten_vars(out) -> List[Variable]:
    if out is None:
        return []
    if isinstance(out, Variable):
        return [out]
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_flatten_vars(o))
        return res
    raise TypeError(f"control-flow branch returned non-Variable {type(out)}")


def _pack_like(template, flat: List[Variable]):
    """Rebuild the user's structure from a flat var list."""
    if template is None:
        return None
    if isinstance(template, Variable):
        return flat.pop(0)
    if isinstance(template, tuple):
        return tuple(_pack_like(t, flat) for t in template)
    if isinstance(template, list):
        return [_pack_like(t, flat) for t in template]
    raise TypeError(type(template))


# -- cond ---------------------------------------------------------------------
def cond(pred: Variable, true_fn: Callable, false_fn: Callable,
         name: Optional[str] = None):
    """ref layers/control_flow.py cond :2313 → conditional_block_op.cc.

    Both branches build real sub-blocks; the Executor lowers to
    jax.lax.cond over a snapshot of the enclosing environment.
    """
    prog = pred.block.program
    parent = prog.current_block()

    tb = prog._create_block()
    t_out = true_fn()
    prog._rollback()
    fb = prog._create_block()
    f_out = false_fn()
    prog._rollback()

    t_list = _flatten_vars(t_out)
    f_list = _flatten_vars(f_out)
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches returned {len(t_list)} vs {len(f_list)} outputs; "
            "they must match (lax.cond requires identical output structure)")
    for tv, fv in zip(t_list, f_list):
        if tv.shape != fv.shape or tv.dtype != fv.dtype:
            raise ValueError(
                f"cond branch outputs mismatch: {tv.name}{tv.shape}:"
                f"{tv.dtype} vs {fv.name}{fv.shape}:{fv.dtype}")

    outs = [parent.create_var(shape=v.shape, dtype=v.dtype) for v in t_list]
    parent.append_op(
        "conditional_block",
        inputs={"Cond": [pred.name]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"true_block": tb.idx, "false_block": fb.idx,
               "true_outs": [v.name for v in t_list],
               "false_outs": [v.name for v in f_list]})
    flat = list(outs)
    return _pack_like(t_out, flat)


# -- while_loop ---------------------------------------------------------------
def while_loop(cond_fn: Callable, body_fn: Callable,
               loop_vars: Sequence[Variable], is_test: bool = False,
               name: Optional[str] = None):
    """ref layers/control_flow.py while_loop :1085 → while_op.cc.

    `loop_vars` is the carried state (shape-invariant).  `body_fn` must
    return the next carry with matching structure; the Executor lowers to
    jax.lax.while_loop.
    """
    loop_vars = list(loop_vars)
    if not loop_vars:
        raise ValueError("while_loop requires at least one loop variable")
    prog = loop_vars[0].block.program
    parent = prog.current_block()

    cb = prog._create_block()
    c_out = cond_fn(*loop_vars)
    prog._rollback()
    if not isinstance(c_out, Variable):
        raise TypeError("while_loop cond_fn must return a boolean Variable")

    bb = prog._create_block()
    b_out = body_fn(*loop_vars)
    prog._rollback()
    if isinstance(b_out, Variable):
        b_out = [b_out]
    b_list = _flatten_vars(list(b_out))
    if len(b_list) != len(loop_vars):
        raise ValueError(
            f"while_loop body returned {len(b_list)} vars for "
            f"{len(loop_vars)} loop_vars")
    for lv, bv in zip(loop_vars, b_list):
        if lv.shape != bv.shape or lv.dtype != bv.dtype:
            raise ValueError(
                f"loop var {lv.name}{lv.shape}:{lv.dtype} vs body output "
                f"{bv.name}{bv.shape}:{bv.dtype} — carries must be "
                "shape-invariant (XLA while_loop)")

    outs = [parent.create_var(shape=v.shape, dtype=v.dtype)
            for v in loop_vars]
    parent.append_op(
        "while",
        inputs={"X": [v.name for v in loop_vars]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"cond_block": cb.idx, "body_block": bb.idx,
               "cond_out": c_out.name,
               "body_outs": [v.name for v in b_list]})
    return outs


class StaticRNN:
    """Static (fixed-length) recurrence (ref layers/control_flow.py
    StaticRNN → recurrent_op.cc).

    TPU-native: the step block lowers to ``lax.scan`` over the TIME-MAJOR
    leading axis of every step input — and scan is reverse-mode
    differentiable, so seq2seq models TRAIN through this construct (the
    reference's RecurrentGradOp machinery collapses into AD-of-scan).

    Usage (reference API shape)::

        rnn = StaticRNN()
        with rnn.step():
            w = rnn.step_input(x_tmajor)        # [T, B, D] -> per-step [B, D]
            prev = rnn.memory(init=h0)          # carried state
            h = layers.fc(concat([w, prev], 1), H, act='tanh')
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        outs = rnn()                            # [T, B, H]
    """

    def __init__(self, name: Optional[str] = None):
        self._prog: Optional[Program] = None
        self._block = None
        self._seq_pairs = []     # (outer_var, step_var)
        self._mem_pairs = []     # (step_mem_var, init_outer_var)
        self._mem_next = {}      # step_mem_name -> step_next_name
        self._outputs = []       # step vars
        self._built = False

    class _StepGuard:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            from .framework import default_main_program

            rnn = self.rnn
            rnn._prog = default_main_program()
            rnn._block = rnn._prog._create_block()
            return rnn

        def __exit__(self, *exc):
            self.rnn._prog._rollback()
            return False

    def step(self):
        return StaticRNN._StepGuard(self)

    def step_input(self, x: Variable) -> Variable:
        if x.ndim < 1:
            raise ValueError("step_input needs a [T, ...] sequence variable")
        v = self._block.create_var(shape=tuple(x.shape[1:]), dtype=x.dtype)
        self._seq_pairs.append((x, v))
        return v

    def memory(self, init: Optional[Variable] = None) -> Variable:
        if init is None:
            raise ValueError(
                "memory requires init= (batch_ref/shape form of the "
                "reference is not supported; pass an initialized tensor)")
        m = self._block.create_var(shape=init.shape, dtype=init.dtype)
        self._mem_pairs.append((m, init))
        return m

    def update_memory(self, mem: Variable, new: Variable) -> None:
        if mem.shape != new.shape or mem.dtype != new.dtype:
            raise ValueError(
                f"update_memory: carry must be shape-invariant, got "
                f"{mem.shape}:{mem.dtype} vs {new.shape}:{new.dtype}")
        self._mem_next[mem.name] = new.name

    def step_output(self, o: Variable) -> None:
        self._outputs.append(o)

    output = step_output

    def __call__(self):
        if self._built:
            raise RuntimeError("StaticRNN() already materialized")
        if not self._seq_pairs:
            raise ValueError("StaticRNN needs at least one step_input")
        missing = [m.name for m, _ in self._mem_pairs
                   if m.name not in self._mem_next]
        if missing:
            raise ValueError(f"memories {missing} never update_memory'd")
        self._built = True
        parent = self._prog.current_block()
        T = self._seq_pairs[0][0].shape[0]
        outs = [parent.create_var(shape=(T,) + tuple(v.shape),
                                  dtype=v.dtype) for v in self._outputs]
        parent.append_op(
            "static_rnn",
            inputs={"X": [x.name for x, _ in self._seq_pairs],
                    "Init": [i.name for _, i in self._mem_pairs]},
            outputs={"Out": [o.name for o in outs]},
            attrs={"rnn_block": self._block.idx,
                   "step_in_names": [v.name for _, v in self._seq_pairs],
                   "mem_names": [m.name for m, _ in self._mem_pairs],
                   "mem_next": [self._mem_next[m.name]
                                for m, _ in self._mem_pairs],
                   "out_names": [v.name for v in self._outputs]})
        return outs if len(outs) > 1 else outs[0]


class While:
    """Legacy block-style While (ref layers/control_flow.py While :1005):

        i = fill_constant(shape=[1], dtype='int64', value=0)
        cond = less_than(i, limit)
        w = While(cond)
        with w.block():
            ...body ops...
            increment(i)
            # body must recompute the condition in-place:
            less_than(i, limit, out=cond)   # here: assign via cond.update()

    The TPU lowering requires the carried state to be explicit, which the
    legacy mutable-Scope API hides; prefer ``while_loop``.  This shim
    supports the common counter pattern by tracking variables written
    in-place inside the block.
    """

    def __init__(self, cond_var: Variable):
        raise NotImplementedError(
            "the legacy While block API relies on mutable-Scope semantics "
            "that do not map to XLA; use paddle_tpu.static.while_loop("
            "cond_fn, body_fn, loop_vars) instead (same expressive power, "
            "explicit carried state)")
