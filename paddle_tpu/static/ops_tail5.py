"""Static-op long tail, batch 5: v1 aliases + the remaining numeric tail
from the registry audit (tests/test_registry_exhaustive.py enforces that
everything NOT here or in earlier batches has a recorded rationale in
static/op_coverage.py).

Reference parity targets: reshape_op.cc / transpose_op.cc v1 forms,
allclose_op.cc, bernoulli (distribution ops), eye_op.cc, fill_op.cc,
diag_v2/diag_embed, histogram_op.cc, randint/randperm, sampling_id_op.h,
seed_op.cc, modified_huber_loss_op.h, add_position_encoding_op.h,
amp/check_finite_and_unscale + update_loss_scaling (+ the v1
amp_check_finite_and_scale), fake_init, bilinear_tensor_product_op.h,
*_batch_size_like random ops, flatten_contiguous_range (flatten_op.cc),
the dequantize family (fake_dequantize_op.cc, dequantize_abs_max_op.cc,
dequantize_log_op.cc), fake_quantize_moving_average_abs_max
(fake_quantize_op.cc), average_accumulates_op.h (ModelAverage),
precision_recall_op.h, spp_op.h, polygon_box_transform_op.cc,
random_crop_op.h, hsigmoid (hierarchical_sigmoid_op.h +
math/matrix_bit_code.h), and the SSD training-assignment trio
bipartite_match_op.cc / target_assign_op.h / mine_hard_examples_op.cc.

TPU-native notes:
- Dynamic-size outputs keep the padded + valid-count contract of batch 4
  (mine_hard_examples' NegIndices is (B, P) padded with -1).
- bipartite_match's greedy global-argmax loop runs as a lax.fori_loop
  over ROWS (#gt, small) with a full (rows, cols) mask update per step —
  the data-dependent `while (row_pool)` of the reference is a fixed
  row-count loop here because each iteration always matches exactly one
  remaining row (or none when no positive dist remains).
- hierarchical_sigmoid implements the default complete-binary-tree code
  (ref math/matrix_bit_code.h SimpleCode) vectorized over a static
  max-code-length; the custom-tree (PathTable/PathCode) inputs are
  accepted and used when present.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dtype_mod
from ..core import random as _random
from .registry import get_lowering, register_op


def _one(ins, slot):
    vs = ins.get(slot, [])
    return vs[0] if vs else None


# =========================================================================
# v1 aliases: the v2 rule already implements the math; extra output slots
# (XShape) are bound only when declared
# =========================================================================

for _v1, _v2 in [("reshape", "reshape2"), ("transpose", "transpose2"),
                 ("sequence_softmax", "sequence_softmax_padded"),
                 ("multiclass_nms2", "multiclass_nms"),
                 ("merge_lod_tensor_infer", "merge_lod_tensor")]:
    register_op(_v1)(get_lowering(_v2))


@register_op("allreduce")
def _allreduce(ins, attrs, op):
    """ref collective/allreduce_op.h: red_type 0..3 = sum/prod/max/min."""
    red = {0: "c_allreduce_sum", 1: "c_allreduce_prod",
           2: "c_allreduce_max", 3: "c_allreduce_min"}[
        int(attrs.get("reduce_type", 0))]
    return get_lowering(red)(ins, attrs, op)


register_op("broadcast")(lambda ins, attrs, op:
                         get_lowering("c_broadcast")(ins, attrs, op))


# =========================================================================
# easy numeric tail
# =========================================================================

@register_op("allclose")
def _allclose(ins, attrs, op):
    x, y = _one(ins, "Input"), _one(ins, "Other")
    rtol = float(attrs.get("rtol", 1e-5))
    atol = float(attrs.get("atol", 1e-8))
    close = jnp.abs(x - y) <= atol + rtol * jnp.abs(y)
    if attrs.get("equal_nan", False):
        close = close | (jnp.isnan(x) & jnp.isnan(y))
    return {"Out": [jnp.all(close)]}


@register_op("bernoulli")
def _bernoulli(ins, attrs, op):
    x = _one(ins, "X")
    u = jax.random.uniform(_random.next_key(), x.shape)
    return {"Out": [(u < x).astype(x.dtype)]}


@register_op("eye")
def _eye(ins, attrs, op):
    rows = int(attrs["num_rows"])
    cols = int(attrs.get("num_columns", -1))
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.eye(rows, cols if cols > 0 else rows, dtype=dtype)]}


@register_op("fill")
def _fill(ins, attrs, op):
    """ref fill_op.cc: tensor from an attr value list + shape."""
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    vals = jnp.asarray(np.asarray(attrs["value"], np.float64), dtype)
    return {"Out": [vals.reshape(tuple(attrs["shape"]))]}


@register_op("fill_zeros_like2")
def _fill_zeros_like2(ins, attrs, op):
    return {"Out": [jnp.zeros_like(_one(ins, "X"))]}


@register_op("diag_v2")
def _diag_v2(ins, attrs, op):
    x = _one(ins, "X")
    offset = int(attrs.get("offset", 0))
    if x.ndim == 1:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n),
                        jnp.asarray(attrs.get("padding_value", 0), x.dtype))
        i = jnp.arange(x.shape[0])
        r, c = (i, i + offset) if offset >= 0 else (i - offset, i)
        return {"Out": [base.at[r, c].set(x)]}
    return {"Out": [jnp.diagonal(x, offset)]}


@register_op("diag_embed")
def _diag_embed(ins, attrs, op):
    x = _one(ins, "X")
    offset = int(attrs.get("offset", 0))
    dim1 = int(attrs.get("dim1", -2))
    dim2 = int(attrs.get("dim2", -1))
    n = x.shape[-1] + abs(offset)
    i = jnp.arange(x.shape[-1])
    r, c = (i, i + offset) if offset >= 0 else (i - offset, i)
    # the (n, n) buffer IS the output  # proglint: dense-intermediate-ok
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype).at[..., r, c].set(x)
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return {"Out": [out]}


@register_op("histogram")
def _histogram(ins, attrs, op):
    x = _one(ins, "X").ravel().astype(jnp.float32)
    bins = int(attrs.get("bins", 100))
    lo = float(attrs.get("min", 0))
    hi = float(attrs.get("max", 0))
    if lo == hi == 0:
        lo_t, hi_t = jnp.min(x), jnp.max(x)
        hi_t = jnp.where(hi_t == lo_t, lo_t + 1, hi_t)
    else:
        lo_t, hi_t = jnp.asarray(lo, x.dtype), jnp.asarray(hi, x.dtype)
    idx = jnp.clip(((x - lo_t) / (hi_t - lo_t) * bins).astype(jnp.int32),
                   0, bins - 1)
    inside = (x >= lo_t) & (x <= hi_t)
    counts = jnp.zeros((bins,), jnp.int64).at[
        jnp.where(inside, idx, bins)].add(1, mode="drop")
    return {"Out": [counts]}


@register_op("randint")
def _randint(ins, attrs, op):
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "int64"))
    return {"Out": [jax.random.randint(
        _random.next_key(), tuple(attrs["shape"]),
        int(attrs.get("low", 0)), int(attrs.get("high", 100))).astype(dtype)]}


@register_op("randperm")
def _randperm(ins, attrs, op):
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "int64"))
    return {"Out": [jax.random.permutation(
        _random.next_key(), int(attrs["n"])).astype(dtype)]}


@register_op("sampling_id")
def _sampling_id(ins, attrs, op):
    """ref sampling_id_op.h: per row, inverse-CDF sample over the prob
    vector (uniform draw in [min, max))."""
    x = _one(ins, "X")
    u = jax.random.uniform(_random.next_key(), (x.shape[0], 1), x.dtype,
                           float(attrs.get("min", 0.0)),
                           float(attrs.get("max", 1.0)))
    cdf = jnp.cumsum(x, axis=1)
    idx = jnp.sum(cdf < u, axis=1)  # first j with cdf >= u
    return {"Out": [jnp.minimum(idx, x.shape[1] - 1).astype(jnp.int64)]}


@register_op("seed")
def _seed(ins, attrs, op):
    """ref seed_op.cc: emit the dropout seed scalar (attr seed, or a
    fresh random one when 0)."""
    s = int(attrs.get("seed", 0))
    if s != 0:
        return {"Out": [jnp.asarray([s], jnp.int32)]}
    return {"Out": [jax.random.randint(
        _random.next_key(), (1,), 1, 2 ** 31 - 1).astype(jnp.int32)]}


@register_op("modified_huber_loss")
def _modified_huber_loss(ins, attrs, op):
    """ref modified_huber_loss_op.h: z = x*(2y-1); loss = -4z (z<-1),
    (1-z)^2 (z<1), 0 otherwise."""
    x, y = _one(ins, "X"), _one(ins, "Y")
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, (1.0 - z) ** 2, 0.0))
    return {"IntermediateVal": [z], "Out": [loss]}


@register_op("add_position_encoding")
def _add_position_encoding(ins, attrs, op):
    """ref add_position_encoding_op.h: out = alpha*x + beta*PE with the
    half-sin/half-cos layout (first half sin, second half cos, shared
    frequency index k/(half-1))."""
    x = _one(ins, "X")
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    B, T, D = x.shape
    half = D // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    denom = (jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32)
                       / max(half - 1, 1)) if half > 1
             else jnp.full((1,), 10000.0))
    val = pos / denom[None, :]
    pe = jnp.concatenate([jnp.sin(val), jnp.cos(val)], axis=1)
    if 2 * half < D:  # odd enc size: last channel has no PE pair
        pe = jnp.pad(pe, ((0, 0), (0, 1)))
    return {"Out": [alpha * x + beta * pe[None].astype(x.dtype)]}


@register_op("amp_check_finite_and_scale")
def _amp_check_finite_and_scale(ins, attrs, op):
    """ref amp/check_finite_and_scale (v1 name): Out_i = X_i * Scale;
    FoundInfinite = any nonfinite across all inputs."""
    xs = ins.get("X", [])
    scale = jnp.reshape(_one(ins, "Scale"), ())
    found = jnp.zeros((), bool)
    outs = []
    for x in xs:
        found = found | ~jnp.all(jnp.isfinite(x))
        outs.append(x * scale.astype(x.dtype))
    return {"Out": outs, "FoundInfinite": [found.reshape(1)]}


@register_op("fake_init")
def _fake_init(ins, attrs, op):
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jnp.zeros(tuple(attrs["shape"]), dtype)]}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ins, attrs, op):
    """ref bilinear_tensor_product_op.h: out[b,k] = x[b] W[k] y[b]^T."""
    x, y, w = _one(ins, "X"), _one(ins, "Y"), _one(ins, "Weight")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    b = _one(ins, "Bias")
    if b is not None:
        out = out + b.reshape(1, -1)
    return {"Out": [out]}


def _batch_size_like_shape(ins, attrs):
    ref_shape = _one(ins, "Input").shape
    shape = list(attrs["shape"])
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ref_shape[in_idx]
    return tuple(shape)


@register_op("gaussian_random_batch_size_like")
def _gaussian_random_bsl(ins, attrs, op):
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        _random.next_key(), _batch_size_like_shape(ins, attrs), dtype)
    return {"Out": [out]}


@register_op("uniform_random_batch_size_like")
def _uniform_random_bsl(ins, attrs, op):
    dtype = _dtype_mod.convert_dtype(attrs.get("dtype", "float32"))
    return {"Out": [jax.random.uniform(
        _random.next_key(), _batch_size_like_shape(ins, attrs), dtype,
        attrs.get("min", -1.0), attrs.get("max", 1.0))]}


@register_op("flatten_contiguous_range")
def _flatten_contiguous_range(ins, attrs, op):
    x = _one(ins, "X")
    start = int(attrs.get("start_axis", 1)) % x.ndim
    stop = int(attrs.get("stop_axis", -1)) % x.ndim
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    out = x.reshape(shape)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("sequence_expand_as")
def _sequence_expand_as(ins, attrs, op):
    """ref sequence_expand_as_op.cc, dense re-scope: X row b repeats
    across timesteps < Length[b] of the (B, T, ...) output (Y provides
    the target T and lengths)."""
    x = _one(ins, "X")
    y = _one(ins, "Y")
    lengths = _one(ins, "Length")
    T = y.shape[1]
    out = jnp.repeat(x[:, None], T, axis=1)
    if lengths is not None:
        mask = jnp.arange(T)[None, :] < lengths.astype(jnp.int32)[:, None]
        out = jnp.where(mask.reshape(mask.shape + (1,) * (out.ndim - 2)),
                        out, jnp.zeros_like(out))
    return {"Out": [out]}


# =========================================================================
# dequantize family (slim/int8 deploy path)
# =========================================================================

@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ins, attrs, op):
    """ref fake_dequantize_op.cc: Out = X * Scale / max_range."""
    x = _one(ins, "X").astype(jnp.float32)
    scale = jnp.reshape(_one(ins, "Scale"), ()).astype(jnp.float32)
    return {"Out": [x * scale / float(attrs["max_range"])]}


register_op("dequantize_abs_max")(_fake_dequantize_max_abs)


@register_op("fake_channel_wise_dequantize_max_abs")
def _fake_cw_dequantize_max_abs(ins, attrs, op):
    """ref fake_dequantize_op.cc channel-wise form: one scale per output
    channel (axis quant_axis), optional second scale for activations."""
    x = _one(ins, "X").astype(jnp.float32)
    scales = ins.get("Scales", [])
    qaxis = int(attrs.get("quant_axis", 0))
    bits = attrs.get("quant_bits", [8])
    s0 = scales[0].astype(jnp.float32)
    shape = [1] * x.ndim
    shape[qaxis] = -1
    out = x * s0.reshape(shape) / (2 ** (int(bits[0]) - 1) - 1)
    if len(scales) > 1 and scales[1] is not None:
        out = out * jnp.reshape(scales[1], ()).astype(jnp.float32) \
            / (2 ** (int(bits[1]) - 1) - 1)
    return {"Out": [out]}


@register_op("dequantize_log")
def _dequantize_log(ins, attrs, op):
    """ref dequantize_log_op.cc: int8 codes index a 128-entry dict;
    negative codes mirror with a sign flip."""
    x = _one(ins, "X").astype(jnp.int32)
    table = _one(ins, "Dict").astype(jnp.float32)
    neg = x < 0
    out = jnp.where(neg, -table[(x + 128) % 128], table[x % 128])
    return {"Out": [out]}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_moving_avg_abs_max(ins, attrs, op):
    """ref fake_quantize_op.cc FakeQuantizeMovingAverageAbsMax: EMA of
    |x|_max drives the quantization scale; round(x/scale*bin_cnt)."""
    x = _one(ins, "X")
    in_scale = jnp.reshape(_one(ins, "InScale"), ())
    rate = float(attrs.get("moving_rate", 0.9))
    bits = int(attrs.get("bit_length", 8))
    bin_cnt = 2 ** (bits - 1) - 1
    cur = jnp.max(jnp.abs(x)).astype(in_scale.dtype)
    state = _one(ins, "InState")
    accum = _one(ins, "InAccum")
    if attrs.get("is_test", False):
        scale = in_scale
        new_state, new_accum = state, accum
    else:
        new_state = (rate * jnp.reshape(state, ()) + 1
                     if state is not None else jnp.asarray(1.0))
        new_accum = (rate * jnp.reshape(accum, ()) + cur
                     if accum is not None else cur)
        scale = new_accum / new_state
    inv = bin_cnt / jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) * inv), -bin_cnt, bin_cnt)
    out = {"Out": [(q / inv).astype(x.dtype)],
           "OutScale": [scale.reshape(1)]}
    if state is not None:
        out["OutState"] = [jnp.reshape(new_state, state.shape)]
    if accum is not None:
        out["OutAccum"] = [jnp.reshape(new_accum, accum.shape)]
    return out


# =========================================================================
# ModelAverage support + metric ops
# =========================================================================

@register_op("average_accumulates")
def _average_accumulates(ins, attrs, op):
    """ref average_accumulates_op.h: three-tier sum accumulation with
    precision-preserving rollover every 16384 updates and window restart
    when the average window outgrows num_updates*average_window.  The
    data-dependent branches become jnp.where over the traced counters."""
    kmax = 16384.0
    p = _one(ins, "param")
    s1 = _one(ins, "in_sum_1")
    s2 = _one(ins, "in_sum_2")
    s3 = _one(ins, "in_sum_3")
    nu = jnp.reshape(_one(ins, "in_num_updates"), ()).astype(jnp.int64) + 1
    na = jnp.reshape(_one(ins, "in_num_accumulates"),
                     ()).astype(jnp.int64) + 1
    ona = jnp.reshape(_one(ins, "in_old_num_accumulates"),
                      ()).astype(jnp.int64)
    avg_win = float(attrs.get("average_window", 0.0))
    max_win = int(attrs.get("max_average_window", 2 ** 62))
    min_win = int(attrs.get("min_average_window", 10000))

    o1, o2, o3 = s1 + p, s2, s3
    roll = (nu % int(kmax)) == 0
    o2 = jnp.where(roll, o2 + o1, o2)
    o1 = jnp.where(roll, jnp.zeros_like(o1), o1)
    restart = (na >= min_win) & (
        na >= jnp.minimum(jnp.asarray(max_win, jnp.float64),
                          nu.astype(jnp.float64) * avg_win).astype(jnp.int64))
    o3 = jnp.where(restart, o1 + o2, o3)
    o1 = jnp.where(restart, jnp.zeros_like(o1), o1)
    o2 = jnp.where(restart, jnp.zeros_like(o2), o2)
    ona = jnp.where(restart, na, ona)
    na = jnp.where(restart, jnp.zeros_like(na), na)
    dt = _one(ins, "in_num_updates").dtype
    return {"out_sum_1": [o1], "out_sum_2": [o2], "out_sum_3": [o3],
            "out_num_updates": [nu.astype(dt).reshape(1)],
            "out_num_accumulates": [na.astype(dt).reshape(1)],
            "out_old_num_accumulates": [ona.astype(dt).reshape(1)]}


@register_op("precision_recall")
def _precision_recall(ins, attrs, op):
    """ref precision_recall_op.h: per-class TP/FP/TN/FN stats from
    argmax predictions vs labels (+ optional per-sample weights), macro-
    and micro-averaged precision/recall/F1, with running accumulation."""
    cls = int(attrs["class_number"])
    idx = _one(ins, "Indices").reshape(-1).astype(jnp.int32)
    labels = _one(ins, "Labels").reshape(-1).astype(jnp.int32)
    w = _one(ins, "Weights")
    w = (w.reshape(-1).astype(jnp.float32) if w is not None
         else jnp.ones_like(idx, jnp.float32))
    onehot_p = jax.nn.one_hot(idx, cls, dtype=jnp.float32)
    onehot_l = jax.nn.one_hot(labels, cls, dtype=jnp.float32)
    tp = jnp.einsum("nc,nc,n->c", onehot_p, onehot_l, w)
    fp = jnp.einsum("nc,n->c", onehot_p, w) - tp
    fn = jnp.einsum("nc,n->c", onehot_l, w) - tp
    tn = jnp.sum(w) - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)  # (C, 4)
    acc = _one(ins, "StatesInfo")
    accum_states = (batch_states + acc.astype(jnp.float32)
                    if acc is not None else batch_states)

    def metrics(states):
        tp_, fp_, tn_, fn_ = (states[:, 0], states[:, 1],
                              states[:, 2], states[:, 3])
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12),
                       0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mp = jnp.where(stp + sfp > 0, stp / (stp + sfp + 1e-12), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / (stp + sfn + 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / (mp + mr + 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    return {"BatchMetrics": [metrics(batch_states)],
            "AccumMetrics": [metrics(accum_states)],
            "AccumStatesInfo": [accum_states]}


# =========================================================================
# vision tail
# =========================================================================

@register_op("spp")
def _spp(ins, attrs, op):
    """ref spp_op.h: pyramid of 2^p x 2^p poolings, each flattened and
    concatenated along the feature dim (ceil kernel + centering pad)."""
    from ..nn.functional import pooling as P

    x = _one(ins, "X")
    height = int(attrs["pyramid_height"])
    ptype = attrs.get("pooling_type", "max")
    N, C, H, W = x.shape
    outs = []
    for p in range(height):
        bins = 2 ** p
        kh, kw = -(-H // bins), -(-W // bins)
        ph, pw = (kh * bins - H + 1) // 2, (kw * bins - W + 1) // 2
        if ptype == "max":
            lvl = P.max_pool2d(x, (kh, kw), (kh, kw), (ph, pw))
        else:
            lvl = P.avg_pool2d(x, (kh, kw), (kh, kw), (ph, pw),
                               exclusive=False)
        outs.append(lvl.reshape(N, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("polygon_box_transform")
def _polygon_box_transform(ins, attrs, op):
    """ref detection/polygon_box_transform_op.cc: even geo channels are
    x-offsets (out = 4*w_idx - in), odd are y-offsets (out = 4*h_idx -
    in)."""
    x = _one(ins, "Input")
    N, G, H, W = x.shape
    wi = jnp.arange(W, dtype=x.dtype).reshape(1, 1, 1, W)
    hi = jnp.arange(H, dtype=x.dtype).reshape(1, 1, H, 1)
    even = (jnp.arange(G) % 2 == 0).reshape(1, G, 1, 1)
    return {"Output": [jnp.where(even, 4.0 * wi - x, 4.0 * hi - x)]}


@register_op("random_crop")
def _random_crop(ins, attrs, op):
    """ref random_crop_op.h: crop the trailing dims to attr shape at a
    random offset (batch dims keep their extent)."""
    x = _one(ins, "X")
    shape = tuple(attrs["shape"])
    nbatch = x.ndim - len(shape)
    key = _random.next_key()
    starts = []
    for i, s in enumerate(shape):
        key, sub = jax.random.split(key)
        hi = x.shape[nbatch + i] - s
        starts.append(jax.random.randint(sub, (), 0, hi + 1)
                      if hi > 0 else jnp.zeros((), jnp.int32))
    start_idx = [jnp.zeros((), jnp.int32)] * nbatch \
        + [s.astype(jnp.int32) for s in starts]
    out = jax.lax.dynamic_slice(x, start_idx, x.shape[:nbatch] + shape)
    # SeedOut is a threading artifact of the reference's per-op RNG; the
    # rng_scope key stream owns randomness here (int32: x64 is off)
    return {"Out": [out], "SeedOut": [jnp.zeros((1,), jnp.int32)]}


# =========================================================================
# hierarchical sigmoid (ref hierarchical_sigmoid_op.h +
# math/matrix_bit_code.h SimpleCode)
# =========================================================================

@register_op("hierarchical_sigmoid")
def _hierarchical_sigmoid(ins, attrs, op):
    x = _one(ins, "X")                        # (B, D)
    w = _one(ins, "W")                        # (C-1, D)
    label = _one(ins, "Label").reshape(-1)    # (B,)
    bias = _one(ins, "Bias")                  # (C-1,) or (C-1, 1)
    path = _one(ins, "PathTable")
    code = _one(ins, "PathCode")
    B = x.shape[0]
    if path is not None and code is not None:
        # custom tree: per-sample node ids (-1 pad) + bits
        node = path.astype(jnp.int32)
        bits = code.astype(jnp.float32)
        valid = node >= 0
        node = jnp.maximum(node, 0)
    else:
        C = int(attrs["num_classes"])
        # SimpleCode (ref matrix_bit_code.h:106): c = label + C; for bit
        # position j (leaf->root), weight index = (c >> (j+1)) - 1 (the
        # prefix) and the branch bit = (c >> j) & 1 (the suffix); the
        # path ends when the prefix hits the root (index < 0).
        L = max((2 * C - 1).bit_length() - 1, 1)
        c = label.astype(jnp.int32) + C
        j = jnp.arange(L)[None, :]
        node = (c[:, None] >> (j + 1)) - 1
        bits = ((c[:, None] >> j) & 1).astype(jnp.float32)
        valid = node >= 0
        node = jnp.where(valid, node, 0)
    pre = jnp.einsum("bd,bld->bl", x, w[node])          # (B, L)
    if bias is not None:
        pre = pre + bias.reshape(-1)[node]
    # sum over path of softplus(pre) - bit*pre  (sigmoid cross-entropy
    # with bit targets, the matrix_bit_code sum)
    lossb = jax.nn.softplus(pre) - bits * pre
    loss = jnp.sum(jnp.where(valid, lossb, 0.0), axis=1, keepdims=True)
    return {"Out": [loss], "PreOut": [pre]}


# =========================================================================
# SSD training-assignment trio
# =========================================================================

@register_op("bipartite_match")
def _bipartite_match(ins, attrs, op):
    """ref detection/bipartite_match_op.cc: greedy global-argmax matching
    of rows (gt) to cols (priors) by descending DistMat, then optional
    per_prediction argmax completion above overlap_threshold.

    Dense layout: DistMat (B, R, C) (the reference's LoD batch of (R, C)
    mats); outputs ColToRowMatchIndices / ColToRowMatchDist (B, C)."""
    dist = _one(ins, "DistMat")
    if dist.ndim == 2:
        dist = dist[None]
    B, R, C = dist.shape
    mtype = attrs.get("match_type", "bipartite")
    thresh = float(attrs.get("dist_threshold", 0.5))

    def one(dmat):
        def body(_, carry):
            md, mi, used_r = carry  # (C,), (C,), (R,)
            # mask already-matched rows and cols
            col_free = mi < 0
            m = dmat * used_r[:, None] * col_free[None, :]
            flat = jnp.argmax(m)
            r, c = flat // C, flat % C
            ok = m[r, c] > 0
            mi = jnp.where(ok, mi.at[c].set(r.astype(jnp.int32)), mi)
            md = jnp.where(ok, md.at[c].set(dmat[r, c]), md)
            used_r = jnp.where(ok, used_r.at[r].set(0.0), used_r)
            return md, mi, used_r

        init = (jnp.zeros((C,), dist.dtype), jnp.full((C,), -1, jnp.int32),
                jnp.ones((R,), dist.dtype))
        md, mi, _ = jax.lax.fori_loop(0, R, body, init)
        if mtype == "per_prediction":
            best_r = jnp.argmax(dmat, axis=0).astype(jnp.int32)
            best_d = jnp.max(dmat, axis=0)
            take = (mi < 0) & (best_d >= thresh)
            mi = jnp.where(take, best_r, mi)
            md = jnp.where(take, best_d, md)
        return mi, md

    mi, md = jax.vmap(one)(dist)
    return {"ColToRowMatchIndices": [mi], "ColToRowMatchDis": [md],
            "ColToRowMatchDist": [md]}


@register_op("target_assign")
def _target_assign(ins, attrs, op):
    """ref detection/target_assign_op.h, dense layout: X (B, P, K)
    per-image candidate rows, MatchIndices (B, M) -> Out (B, M, K) +
    OutWeight (B, M, 1); optional NegIndices (B, M) (-1 padded) overrides
    matched-away entries with mismatch_value/weight 1."""
    x = _one(ins, "X")
    if x.ndim == 2:
        x = x[:, :, None]
    match = _one(ins, "MatchIndices").astype(jnp.int32)
    mismatch = float(attrs.get("mismatch_value", 0))
    B, M = match.shape
    K = x.shape[2]
    b_idx = jnp.arange(B)[:, None]
    gathered = x[b_idx, jnp.maximum(match, 0)]           # (B, M, K)
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, x.dtype))
    wt = matched.astype(jnp.float32)
    neg = _one(ins, "NegIndices")
    if neg is not None:
        neg = neg.astype(jnp.int32)
        negmask = jnp.zeros((B, M), bool).at[
            jnp.arange(B)[:, None],
            jnp.where(neg >= 0, neg, M)].set(True, mode="drop")
        out = jnp.where(negmask[..., None],
                        jnp.asarray(mismatch, x.dtype), out)
        wt = jnp.where(negmask[..., None], 1.0, wt)
    return {"Out": [out], "OutWeight": [wt]}


@register_op("mine_hard_examples")
def _mine_hard_examples(ins, attrs, op):
    """ref detection/mine_hard_examples_op.cc.  max_negative (default):
    candidates are unmatched priors, ranked by ClsLoss desc, keep
    min(num_pos*neg_pos_ratio, #candidates); hard_example: candidates
    have MatchDist < neg_dist_threshold, loss = cls+loc, keep sample_size
    and un-match positives that don't survive.  NegIndices is (B, P)
    ascending, -1 padded (the reference's ragged LoD output)."""
    cls_loss = _one(ins, "ClsLoss")
    loc_loss = _one(ins, "LocLoss")
    match = _one(ins, "MatchIndices").astype(jnp.int32)
    match_dist = _one(ins, "MatchDist")
    ratio = float(attrs.get("neg_pos_ratio", 1.0))
    thresh = float(attrs.get("neg_dist_threshold", 0.5))
    sample_size = int(attrs.get("sample_size", 0))
    mining = attrs.get("mining_type", "max_negative")
    B, P = match.shape

    if mining == "hard_example":
        eligible = match_dist < thresh
        loss = cls_loss + (loc_loss if loc_loss is not None else 0.0)
        neg_sel = jnp.minimum(sample_size, eligible.sum(axis=1))
    else:
        eligible = match < 0
        loss = cls_loss
        num_pos = (match >= 0).sum(axis=1)
        neg_sel = jnp.minimum((num_pos * ratio).astype(jnp.int32),
                              eligible.sum(axis=1).astype(jnp.int32))

    masked = jnp.where(eligible, loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)
    rank = jnp.argsort(order, axis=1)                   # rank of each prior
    selected = eligible & (rank < neg_sel[:, None])

    upd = match
    if mining == "hard_example":
        upd = jnp.where((match > -1) & ~selected, -1, match)
        neg_mask = (match < 0) & selected
    else:
        neg_mask = selected
    # ascending compaction of selected indices, -1 pad
    tgt = jnp.cumsum(neg_mask, axis=1) - 1
    neg_idx = jnp.full((B, P), -1, jnp.int32).at[
        jnp.arange(B)[:, None],
        jnp.where(neg_mask, tgt, P)].set(
        jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32), (B, P)),
        mode="drop")
    return {"NegIndices": [neg_idx], "UpdatedMatchIndices": [upd]}


@register_op("fc")
def _fc_op(ins, attrs, op):
    """ref fc_op.h: the fused inference-pass mul+bias(+relu) op —
    flatten leading in_num_col_dims dims, x @ W + b, optional relu."""
    x = _one(ins, "Input")
    w = _one(ins, "W")
    b = _one(ins, "Bias")
    ncol = int(attrs.get("in_num_col_dims", 1))
    lead = x.shape[:ncol]
    out = x.reshape((int(np.prod(lead)) if lead else 1, -1)) @ w
    if b is not None:
        out = out + b.reshape(1, -1)
    if attrs.get("activation_type", "") == "relu":
        out = jax.nn.relu(out)
    return {"Out": [out.reshape(lead + (w.shape[1],))]}


@register_op("assert")
def _assert_op(ins, attrs, op):
    """ref controlflow/assert_op.cc: abort the run when Cond is false,
    printing the attached data vars.  Host-side check via ordered
    io_callback (same contract as the print op — CPU/real-TPU runtimes;
    the axon dev tunnel lacks host callbacks, noted in the module
    docstring of ops_tail2)."""
    from jax.experimental import io_callback

    cond = _one(ins, "Cond")
    data = ins.get("Data", [])
    summarize = int(attrs.get("summarize", -1))

    def host_check(c, *arrs):
        # ALL elements must hold (assert_op.cc checks the full tensor)
        if not bool(np.asarray(c).all()):
            shown = [np.asarray(a).ravel()[:summarize if summarize > 0
                                           else None] for a in arrs]
            raise AssertionError(
                f"assert_op failed; data: {shown}")
        return np.zeros((), np.int32)

    io_callback(host_check, jax.ShapeDtypeStruct((), jnp.int32),
                cond, *data, ordered=True)
    return {}
