"""append_backward for static programs.

Reference parity: python/paddle/fluid/backward.py:1215 `append_backward`,
which walks the block emitting one grad-op per forward op via each op's
GradOpMaker (:862 `_append_backward_ops_`).

TPU-native design: no per-op grad kernels exist — the whole forward region is
differentiated at lowering time with `jax.grad` (the Executor replays the
op list as a pure function of the parameters and lets AD produce the
cotangents; XLA CSEs the replayed forward against the primal one).  The
program therefore records a single `backward_region` op carrying loss +
parameter names, plus `<param>@GRAD` variables that downstream optimizer ops
consume exactly like the reference's grad vars.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from .framework import Parameter, Program, Variable, default_main_program

GRAD_SUFFIX = "@GRAD"


def append_backward(loss: Variable, parameter_list: Optional[List] = None,
                    no_grad_set=None, program: Optional[Program] = None
                    ) -> List[Tuple[Parameter, Variable]]:
    """Returns [(param, grad_var)] like the reference (backward.py:1215)."""
    program = program or default_main_program()
    block = program.global_block()
    if parameter_list:
        params = [block.var(p) if isinstance(p, str) else p
                  for p in parameter_list]
    else:
        params = [p for p in program.all_parameters() if p.trainable]
    no_grad = {v if isinstance(v, str) else v.name for v in (no_grad_set or ())}
    params = [p for p in params if p.name not in no_grad]

    grad_vars = []
    for p in params:
        g = block.create_var(name=p.name + GRAD_SUFFIX, shape=p.shape,
                             dtype=p.dtype, stop_gradient=True)
        grad_vars.append(g)
    block.append_op(
        "backward_region",
        inputs={"Loss": [loss.name], "Params": [p.name for p in params]},
        outputs={"Grads": [g.name for g in grad_vars]},
        attrs={})
    return list(zip(params, grad_vars))


def gradients(targets, inputs, program: Optional[Program] = None):
    """ref backward.py:1795 `gradients` — grads of targets wrt inputs."""
    program = program or default_main_program()
    block = program.global_block()
    tgt = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    grad_vars = []
    for v in ins:
        g = block.create_var(name=v.name + GRAD_SUFFIX, shape=v.shape,
                             dtype=v.dtype, stop_gradient=True)
        grad_vars.append(g)
    block.append_op(
        "backward_region",
        inputs={"Loss": [t.name for t in tgt], "Params": [v.name for v in ins]},
        outputs={"Grads": [g.name for g in grad_vars]},
        attrs={"wrt_any": True})
    return grad_vars
